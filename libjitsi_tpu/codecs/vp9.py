"""VP9 RTP payload descriptor handling (draft-ietf-payload-vp9) — vectorized.

Rebuilds the role of the reference's VP9 depacketizer
(`org.jitsi.impl.neomedia.codec.video.vp9.DePacketizer` [M per SURVEY
§2.5 — era-dependent]) the same way `codecs/vp8.py` rebuilds the VP8 one:
batched parse of the payload descriptor over a PacketBatch — I/P/L/F/B/E/
V/Z flags, 7/15-bit PictureID, layer indices (TID/U/SID/D + TL0PICIDX in
non-flexible mode), flexible-mode P_DIFFs, and the scalability structure
(SS) size — plus keyframe detection (P=0, B=1, SID=0).  The VP9 bitstream
itself stays on libvpx (host, verification only).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header

_MAX_PDIFF = 3      # flexible mode allows at most 3 reference diffs
_MAX_NG = 8         # picture-group entries we account for in SS sizing


@dataclasses.dataclass
class Vp9Descriptors:
    """Parsed per-row VP9 payload descriptor fields (-1 where absent)."""

    desc_len: np.ndarray        # descriptor size in bytes
    inter_predicted: np.ndarray  # P bit
    flexible: np.ndarray        # F bit
    begin_frame: np.ndarray     # B bit
    end_frame: np.ndarray       # E bit
    not_reference: np.ndarray   # Z bit
    payload_start: np.ndarray   # first VP9 payload byte (abs column)
    payload_end: np.ndarray     # one past the last payload byte (no padding)
    picture_id: np.ndarray      # 7/15-bit, -1 if no I
    tid: np.ndarray             # temporal layer id, -1 if no L
    sid: np.ndarray             # spatial layer id, -1 if no L
    switching_up: np.ndarray    # U bit (-1 if no L)
    inter_layer_dep: np.ndarray  # D bit (-1 if no L)
    tl0picidx: np.ndarray       # -1 unless L and non-flexible
    num_pdiff: np.ndarray       # flexible-mode reference count
    has_ss: np.ndarray          # V bit
    is_keyframe: np.ndarray     # P=0, B=1 and (no L or SID=0)
    valid: np.ndarray


def parse_descriptors(batch: PacketBatch, hdr=None) -> Vp9Descriptors:
    """Vectorized draft-ietf-payload-vp9 §4.2 parse over RTP payloads.

    Pass `hdr` (a prior `rtp_header.parse(batch)`) to avoid re-parsing
    on hot paths that already hold one (the SVC forwarder)."""
    if hdr is None:
        hdr = rtp_header.parse(batch)
    d = batch.data
    n, cap = d.shape
    ln = np.asarray(batch.length, dtype=np.int64)
    off = hdr.payload_off.astype(np.int64)

    def byte_at(pos):
        return rtp_header.byte_at(d, pos)

    b0 = byte_at(off)
    i_bit = (b0 >> 7) & 1
    p_bit = (b0 >> 6) & 1
    l_bit = (b0 >> 5) & 1
    f_bit = (b0 >> 4) & 1
    b_bit = (b0 >> 3) & 1
    e_bit = (b0 >> 2) & 1
    v_bit = (b0 >> 1) & 1
    z_bit = b0 & 1
    cur = off + 1

    # PictureID: 7-bit, or 15-bit when the M bit of the first byte is set
    pid0 = byte_at(cur)
    m_bit = (pid0 >> 7) & 1
    pic7 = pid0 & 0x7F
    pic15 = ((pid0 & 0x7F) << 8) | byte_at(cur + 1)
    picture_id = np.where(i_bit == 1,
                          np.where(m_bit == 1, pic15, pic7), -1)
    cur = cur + i_bit * (1 + m_bit)

    # Layer indices: TID(3) U(1) SID(3) D(1); + TL0PICIDX in non-flexible
    lb = np.where(l_bit == 1, byte_at(cur), 0)
    tid = np.where(l_bit == 1, (lb >> 5) & 0x7, -1)
    switching_up = np.where(l_bit == 1, (lb >> 4) & 1, -1)
    sid = np.where(l_bit == 1, (lb >> 1) & 0x7, -1)
    inter_layer_dep = np.where(l_bit == 1, lb & 1, -1)
    cur = cur + l_bit
    nonflex_tl0 = l_bit * (1 - f_bit)
    tl0picidx = np.where(nonflex_tl0 == 1, byte_at(cur), -1)
    cur = cur + nonflex_tl0

    # Flexible mode P_DIFFs: while the N bit continues, up to 3
    num_pdiff = np.zeros(n, dtype=np.int64)
    take = (f_bit == 1) & (p_bit == 1)
    for _ in range(_MAX_PDIFF):
        pb = byte_at(cur)
        num_pdiff = num_pdiff + take.astype(np.int64)
        cur = cur + take.astype(np.int64)
        take = take & ((pb & 1) == 1)

    # Scalability structure (V): N_S(3) Y(1) G(1); sizes counted so
    # desc_len is right — the SS content itself is keyframe-rate metadata
    ssb = np.where(v_bit == 1, byte_at(cur), 0)
    n_s = ((ssb >> 5) & 0x7) + 1
    y_bit = (ssb >> 4) & 1
    g_bit = (ssb >> 3) & 1
    cur = cur + v_bit
    cur = cur + v_bit * y_bit * n_s * 4          # WIDTH/HEIGHT pairs
    ng = np.where((v_bit == 1) & (g_bit == 1), byte_at(cur), 0)
    cur = cur + v_bit * g_bit
    # each picture-group entry: TID|U|R byte + R × P_DIFF
    remaining = np.minimum(ng, _MAX_NG)
    for _ in range(_MAX_NG):
        has = remaining > 0
        gb = np.where(has, byte_at(cur), 0)
        r = (gb >> 2) & 0x3
        cur = cur + has.astype(np.int64) * (1 + r)
        remaining = remaining - has.astype(np.int64)

    desc_len = cur - off
    payload_end = ln - hdr.pad_len                 # padding is not payload
    # rows with more SS picture-group entries than we size are NOT parsed
    # with a guessed desc_len — they are rejected, not silently corrupted
    valid = (hdr.valid & (payload_end > off + desc_len)
             & (ng <= _MAX_NG))
    is_keyframe = ((p_bit == 0) & (b_bit == 1)
                   & ((l_bit == 0) | (sid == 0)) & valid)
    return Vp9Descriptors(
        desc_len=desc_len.astype(np.int32),
        payload_start=(off + desc_len).astype(np.int32),
        payload_end=payload_end.astype(np.int32),
        inter_predicted=p_bit.astype(bool),
        flexible=f_bit.astype(bool),
        begin_frame=b_bit.astype(bool),
        end_frame=e_bit.astype(bool),
        not_reference=z_bit.astype(bool),
        picture_id=picture_id,
        tid=tid, sid=sid,
        switching_up=switching_up,
        inter_layer_dep=inter_layer_dep,
        tl0picidx=tl0picidx,
        num_pdiff=num_pdiff,
        has_ss=(v_bit == 1),
        is_keyframe=np.asarray(is_keyframe, dtype=bool),
        valid=np.asarray(valid, dtype=bool),
    )


def build_descriptor(
    begin: bool, end: bool = False, picture_id: int = -1,
    tid: int = -1, sid: int = 0, tl0picidx: int = -1,
    inter_predicted: bool = True, flexible: bool = False,
    pdiffs: Optional[List[int]] = None,
    ss_sizes: Optional[List[tuple]] = None,
) -> bytes:
    """Build a VP9 payload descriptor (test/packetizer helper)."""
    i = picture_id >= 0
    l = tid >= 0
    pdiffs = pdiffs or []
    f = flexible
    if f and inter_predicted and not pdiffs:
        # F=1,P=1 implies at least one P_DIFF on the wire; emitting none
        # would make every parser (ours included) eat a payload byte
        raise ValueError("flexible inter-predicted descriptor needs >=1 "
                         "pdiff (or inter_predicted=False)")
    v = ss_sizes is not None
    b0 = ((i << 7) | (int(inter_predicted) << 6) | (l << 5) | (f << 4)
          | (int(begin) << 3) | (int(end) << 2) | (v << 1))
    out = bytes([b0])
    if i:
        if picture_id > 0x7F:
            out += bytes([0x80 | (picture_id >> 8), picture_id & 0xFF])
        else:
            out += bytes([picture_id & 0x7F])
    if l:
        out += bytes([((tid & 7) << 5) | ((sid & 7) << 1)])
        if not f:
            out += bytes([tl0picidx & 0xFF if tl0picidx >= 0 else 0])
    if f and inter_predicted:
        for k, pd in enumerate(pdiffs):
            n_bit = 1 if k + 1 < len(pdiffs) else 0
            out += bytes([((pd & 0x7F) << 1) | n_bit])
    if v:
        n_s = len(ss_sizes)
        out += bytes([((n_s - 1) << 5) | (1 << 4)])   # Y=1, G=0
        for w, h in ss_sizes:
            out += w.to_bytes(2, "big") + h.to_bytes(2, "big")
    return out


class Vp9FrameAssembler:
    """Groups packets of one VP9 spatial/temporal stream into frames by
    (picture_id, sid), tracking begin/end markers — the depacketizer's
    frame-boundary logic, host-side (per-frame rate is low)."""

    def __init__(self):
        self._partial = {}

    def push(self, desc: Vp9Descriptors, batch: PacketBatch,
             row: int) -> Optional[bytes]:
        """Feed one row; returns the assembled frame payload when its
        end-marker packet arrives (packets assumed seq-ordered, as after
        the jitter buffer)."""
        if not desc.valid[row]:
            return None
        key = (int(desc.picture_id[row]), int(desc.sid[row]))
        payload = bytes(batch.data[
            row, int(desc.payload_start[row]):int(desc.payload_end[row])])
        if desc.begin_frame[row]:
            # a new frame on this spatial layer obsoletes any partial
            # frame whose end packet was lost — evict, don't leak
            sid = key[1]
            for stale in [k for k in self._partial
                          if k[1] == sid and k != key]:
                del self._partial[stale]
            self._partial[key] = [payload]
        elif key in self._partial:
            self._partial[key].append(payload)
        else:
            return None                      # mid-frame without a start
        if desc.end_frame[row]:
            return b"".join(self._partial.pop(key))
        return None
