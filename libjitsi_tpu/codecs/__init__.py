from libjitsi_tpu.codecs.opus import OpusDecoder, OpusEncoder, opus_available  # noqa: F401
