from libjitsi_tpu.codecs.opus import OpusDecoder, OpusEncoder, opus_available  # noqa: F401
from libjitsi_tpu.codecs.gsm import GsmCodec, gsm_available  # noqa: F401
from libjitsi_tpu.codecs.speex import (SpeexDecoder, SpeexEncoder,  # noqa: F401
                                       speex_available)
from libjitsi_tpu.codecs.vpx import (VpxDecoder, VpxEncoder,  # noqa: F401
                                     vpx_available)
