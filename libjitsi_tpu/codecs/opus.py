"""Opus encode/decode via ctypes on the system libopus.

Rebuilds the JNI surface of the reference's
`org.jitsi.impl.neomedia.codec.audio.opus.Opus` (+ `src/native/opus`):
encoder create/encode with bitrate / complexity / inband-FEC / DTX
knobs, decoder with packet-loss concealment and FEC decode.  Opus is a
host-side codec (audio encode/decode has no TPU analog worth building);
the decoded PCM feeds the device mixer.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

import numpy as np

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("opus") or "libopus.so.0"
    _lib = ctypes.CDLL(name)
    _lib.opus_encoder_create.restype = ctypes.c_void_p
    _lib.opus_encoder_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int)]
    _lib.opus_encode.restype = ctypes.c_int
    _lib.opus_encode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int16), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int]
    _lib.opus_encoder_ctl.restype = ctypes.c_int
    _lib.opus_decoder_create.restype = ctypes.c_void_p
    _lib.opus_decoder_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    _lib.opus_decode.restype = ctypes.c_int
    _lib.opus_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int16), ctypes.c_int, ctypes.c_int]
    return _lib


def opus_available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


APPLICATION_VOIP = 2048
APPLICATION_AUDIO = 2049
# opus_defines.h ctl request codes
_SET_BITRATE = 4002
_SET_COMPLEXITY = 4010
_SET_INBAND_FEC = 4012
_SET_PACKET_LOSS_PERC = 4014
_SET_DTX = 4016


class OpusEncoder:
    """Reference: Opus.encoder_create/encode + JavaEncoder knobs."""

    def __init__(self, sample_rate: int = 48000, channels: int = 1,
                 application: int = APPLICATION_VOIP):
        lib = _load()
        err = ctypes.c_int()
        self._channels = channels
        self._enc = lib.opus_encoder_create(sample_rate, channels,
                                            application, ctypes.byref(err))
        if err.value != 0:
            raise RuntimeError(f"opus_encoder_create failed: {err.value}")

    def _ctl(self, request: int, value: int) -> None:
        _load().opus_encoder_ctl(ctypes.c_void_p(self._enc),
                                 ctypes.c_int(request), ctypes.c_int(value))

    def set_bitrate(self, bps: int) -> None:
        self._ctl(_SET_BITRATE, bps)

    def set_complexity(self, c: int) -> None:
        self._ctl(_SET_COMPLEXITY, c)

    def set_inband_fec(self, on: bool) -> None:
        self._ctl(_SET_INBAND_FEC, int(on))

    def set_packet_loss_perc(self, pct: int) -> None:
        self._ctl(_SET_PACKET_LOSS_PERC, pct)

    def set_dtx(self, on: bool) -> None:
        self._ctl(_SET_DTX, int(on))

    def encode(self, pcm: np.ndarray) -> bytes:
        """pcm: int16 [frame * channels] (20 ms = 960/ch @48k)."""
        pcm = np.ascontiguousarray(pcm, dtype=np.int16)
        out = ctypes.create_string_buffer(4000)
        n = _load().opus_encode(
            ctypes.c_void_p(self._enc),
            pcm.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
            len(pcm) // self._channels, out, len(out))
        if n < 0:
            raise RuntimeError(f"opus_encode error {n}")
        return out.raw[:n]


class OpusDecoder:
    """Reference: Opus.decoder_create/decode (+ PLC via data=None)."""

    def __init__(self, sample_rate: int = 48000, channels: int = 1):
        lib = _load()
        err = ctypes.c_int()
        self._channels = channels
        self._rate = sample_rate
        self._dec = lib.opus_decoder_create(sample_rate, channels,
                                            ctypes.byref(err))
        if err.value != 0:
            raise RuntimeError(f"opus_decoder_create failed: {err.value}")

    def decode(self, packet: Optional[bytes], frame_size: int = 960,
               decode_fec: bool = False) -> np.ndarray:
        """packet=None triggers packet-loss concealment."""
        out = np.empty(frame_size * self._channels, dtype=np.int16)
        n = _load().opus_decode(
            ctypes.c_void_p(self._dec),
            packet if packet is not None else None,
            len(packet) if packet is not None else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
            frame_size, int(decode_fec))
        if n < 0:
            raise RuntimeError(f"opus_decode error {n}")
        return out[: n * self._channels]
