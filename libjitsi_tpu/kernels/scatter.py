"""Per-row byte scatter/gather shared by the SRTP and GCM kernels.

`scatter_bytes` writes a small per-row byte vector at a per-row column
offset using UNROLLED broadcast compare+selects.  The obvious
`take_along_axis(src, col - pos)` form is a per-element dynamic gather
over the full [B, W] plane — fetch-verified at ~135 ms per scatter at
65536x192 on a v5e, 3x the cost of the AES keystream it decorates —
while n broadcast compares are plain vector ops.  `gather_span` keeps
`take_along_axis` because its gather plane is only [B, n] (n <= 20).
"""

from __future__ import annotations

import jax.numpy as jnp


def scatter_bytes(data, pos, src, n: int):
    """Write src[:, :n] ([B, >=n] uint8) into data [B, W] at per-row
    byte offset pos [B]; positions beyond W fall off the end (no-op),
    matching the masked-gather form this replaces."""
    col = jnp.arange(data.shape[1], dtype=jnp.int32)[None, :]
    pos = pos[:, None]
    out = data
    for k in range(n):
        out = jnp.where(col == pos + k, src[:, k][:, None], out)
    return out


def gather_span(data, pos, n: int):
    """Read n bytes at per-row byte offset `pos` -> [B, n] (clamped)."""
    idx = pos[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, data.shape[1] - 1)
    return jnp.take_along_axis(data, idx, axis=1)
