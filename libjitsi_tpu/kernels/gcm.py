"""Batched AES-GCM AEAD (SP 800-38D) for SRTP/SRTCP (RFC 7714).

Layout convention matches the SRTP packet: ``data[:aad_len]`` is the
AAD (the RTP/RTCP header) and ``data[aad_len:length]`` the plaintext /
ciphertext — encryption happens in place, the 16-byte tag is appended.
CTR rides the existing AES kernel (J0 = IV||0x00000001; within one
packet the 32-bit counter cannot wrap, so the full-128-bit increment is
equivalent); the tag rides the GHASH MXU matmul kernel with the
per-row index arithmetic building each row's ``AAD||0* || C||0* ||
len(A)||len(C)`` block stream without host round trips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from libjitsi_tpu.kernels.scatter import gather_span as _gather_span
from libjitsi_tpu.kernels.scatter import scatter_bytes
from libjitsi_tpu.kernels.aes import (aes_encrypt, ctr_crypt_offset,
                                      ctr_crypt_uniform)
from libjitsi_tpu.kernels.ghash import ghash

TAG_LEN = 16


def _ceil16(x):
    return (x + 15) & ~15


def _ghash_width(capacity: int) -> int:
    """Tight bound on the GHASH input row: padded-AAD + padded-CT +
    length block.  ceil16(a) + ceil16(c) <= ceil16(a + c) + 16 for any
    split, and a + c <= capacity, so ceil16(cap) + 16 covers the data
    and +16 the length block.  (The old 2*cap+16 bound nearly doubled
    the Horner matmul rounds every GCM path pays.)"""
    return _ceil16(capacity) + 32


def _length_block(cols, ap, cp, abits, cbits):
    """be64(aad_bits) || be64(ct_bits) bytes at columns [ap+cp, ap+cp+16).

    Shared by both GHASH-input builders — the two paths MUST stay
    bit-identical or the uniform fast path's tags stop verifying against
    the general path's.  Bit counts fit in 32 bits (capacity << 2^29),
    so bytes 0..3 of each u64 are zero and the math stays in int32.
    """
    p = cols - (ap + cp)
    shift_a = jnp.clip(8 * (7 - p), 0, 24)
    shift_c = jnp.clip(8 * (15 - p), 0, 24)
    byte = jnp.where(
        (p >= 4) & (p < 8), (abits >> shift_a) & 0xFF,
        jnp.where((p >= 12) & (p < 16), (cbits >> shift_c) & 0xFF, 0)
    ).astype(jnp.uint8)
    return byte, p


def _build_ghash_input(data, aad_len, ct_len, width: int):
    """[B, W] packet bytes -> [B, width] GHASH block stream + counts.

    Row layout: AAD (0-padded to 16) || ciphertext (0-padded) ||
    be64(aad_bits) || be64(ct_bits).
    """
    bsz, cap = data.shape
    a = aad_len.astype(jnp.int32)
    c = ct_len.astype(jnp.int32)
    ap = (a + 15) & ~15
    cp = (c + 15) & ~15
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]

    in_aad = cols < a[:, None]
    k = cols - ap[:, None]
    in_ct = (k >= 0) & (k < c[:, None])
    src = jnp.where(in_aad, cols, jnp.where(in_ct, a[:, None] + k, 0))
    gathered = jnp.take_along_axis(
        data, jnp.clip(src, 0, cap - 1), axis=1)

    len_byte, p = _length_block(cols, ap[:, None], cp[:, None],
                                (a * 8)[:, None], (c * 8)[:, None])

    out = jnp.where(in_aad | in_ct, gathered, 0).astype(jnp.uint8)
    out = jnp.where((p >= 0) & (p < 16), len_byte, out)
    nblocks = (ap + cp + 16) // 16
    return out, nblocks


def _build_ghash_input_uniform(data, aad: int, ct_len, width: int):
    """Uniform-AAD twin of `_build_ghash_input`: with every row's AAD the
    same static size (SRTP: the 12-byte RTP header / 8-byte RTCP prefix),
    the AAD->padded-AAD and ciphertext shifts are static pad/slice ops —
    no [B, width] gather (the gather dominates the general path's cost on
    TPU, like the CTR alignment gather did)."""
    bsz, cap = data.shape
    c = ct_len.astype(jnp.int32)
    ap = _ceil16(aad)
    cp = (c + 15) & ~15
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]

    # AAD bytes land at columns [0, aad); ct bytes at [ap, ap + c)
    aad_part = jnp.pad(data[:, :aad], ((0, 0), (0, width - aad)))
    ct_src = jnp.pad(data[:, aad:], ((0, 0), (0, max(0, width - (cap - aad)))))
    ct_part = jnp.pad(ct_src, ((0, 0), (ap, 0)))[:, :width]
    k = cols - ap
    in_aad = cols < aad
    in_ct = (k >= 0) & (k < c[:, None])
    out = jnp.where(in_aad, aad_part,
                    jnp.where(in_ct, ct_part, 0)).astype(jnp.uint8)

    len_byte, p = _length_block(cols, ap, cp[:, None],
                                jnp.full_like(c, aad * 8)[:, None],
                                (c * 8)[:, None])
    out = jnp.where((p >= 0) & (p < 16), len_byte, out)
    nblocks = (ap + cp + 16) // 16
    return out, nblocks


def _j0(iv12):
    """[B, 12] -> [B, 16] J0 = IV || 0x00000001."""
    b = iv12.shape[0]
    tail = jnp.tile(jnp.array([0, 0, 0, 1], dtype=jnp.uint8), (b, 1))
    return jnp.concatenate([iv12.astype(jnp.uint8), tail], axis=1)


def _inc32(block):
    """Increment the last 32 bits (big-endian) of [B, 16] blocks."""
    hi = block[:, :12]
    lo = block[:, 12:].astype(jnp.uint32)
    val = (lo[:, 0] << 24) | (lo[:, 1] << 16) | (lo[:, 2] << 8) | lo[:, 3]
    val = val + 1  # uint32 wraps naturally
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    lo2 = ((val[:, None] >> shifts[None, :]) & 0xFF).astype(jnp.uint8)
    return jnp.concatenate([hi, lo2], axis=1)


def _scatter_tag(data, pos, tag):
    # gather-free (kernels/scatter.py has the perf story)
    return scatter_bytes(data, pos, tag, TAG_LEN)


def _tag(round_keys, gmat, data, aad_len, ct_len, j0, width: int,
         aad_const=None):
    if aad_const is not None:
        gin, nblk = _build_ghash_input_uniform(data, aad_const, ct_len,
                                               width)
    else:
        gin, nblk = _build_ghash_input(data, aad_len, ct_len, width)
    s = ghash(gmat, gin, nblk, width // 16)
    ek_j0 = aes_encrypt(round_keys, j0)
    return jnp.bitwise_xor(s, ek_j0)


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_protect(data, length, aad_len, round_keys, gmat, iv12,
                aad_const=None):
    """Batched seal: encrypt data[aad:length] in place, append 16B tag.

    data [B, W] uint8; length/aad_len [B] int32; round_keys [B, R, 16];
    gmat [B, 128, 128] int8 (per-stream GHASH matrix); iv12 [B, 12].
    Returns (data', length + 16).
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    aad_len = jnp.asarray(aad_len, dtype=jnp.int32)
    j0 = _j0(jnp.asarray(iv12))
    ctr0 = _inc32(j0)
    ct_len = length - aad_len
    if aad_const is not None:
        enc = ctr_crypt_uniform(round_keys, ctr0, data, aad_const, ct_len)
    else:
        enc = ctr_crypt_offset(round_keys, ctr0, data, aad_len, ct_len)
    width = _ghash_width(data.shape[1])
    tag = _tag(round_keys, gmat, enc, aad_len, ct_len, j0, width,
               aad_const)
    out = _scatter_tag(enc, length, tag)
    return out, length + TAG_LEN


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_unprotect(data, length, aad_len, round_keys, gmat, iv12,
                  aad_const=None):
    """Batched open: verify tag, decrypt in place.

    Returns (data', length - 16, auth_ok).  Decrypt always runs
    (branch-free); callers mask failed rows.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    aad_len = jnp.asarray(aad_len, dtype=jnp.int32)
    mlen = length - TAG_LEN
    ct_len = mlen - aad_len
    j0 = _j0(jnp.asarray(iv12))
    width = _ghash_width(data.shape[1])
    want = _tag(round_keys, gmat, data, aad_len, ct_len, j0, width,
                aad_const)
    stored = _gather_span(data, mlen, TAG_LEN)
    auth_ok = jnp.all(stored == want, axis=1)
    ctr0 = _inc32(j0)
    if aad_const is not None:
        dec = ctr_crypt_uniform(round_keys, ctr0, data, aad_const, ct_len)
    else:
        dec = ctr_crypt_offset(round_keys, ctr0, data, aad_len, ct_len)
    return dec, mlen, auth_ok


def _grouped_tag(round_keys, gmat_g, enc, aad_len, ct_len, j0,
                 grid_rows, inv_pos, width: int, aad_const):
    """Per-stream-grouped tag for a mixed-stream batch.

    The per-row `_tag` gathers a 16 KiB GHASH matrix per packet — at
    batch 65536 that is 1 GiB of HBM traffic for key material, which
    capped the GCM launch size (BENCH_r02).  Here the host pre-groups
    rows by stream into a [G, P] grid (`grid_rows`: row index or -1
    padding) so each stream's matrix is read ONCE and applied to all its
    rows as one MXU matmul per Horner step (`ghash_grouped`), then the
    digests scatter back to batch order via `inv_pos`.
    """
    from libjitsi_tpu.kernels.ghash import ghash_grouped

    if aad_const is not None:
        gin, nblk = _build_ghash_input_uniform(enc, aad_const, ct_len,
                                               width)
    else:
        gin, nblk = _build_ghash_input(enc, aad_len, ct_len, width)
    g, p = grid_rows.shape
    safe = jnp.clip(grid_rows.reshape(-1), 0, enc.shape[0] - 1)
    gin_g = gin[safe].reshape(g, p, width)
    nblk_g = jnp.where(grid_rows >= 0, nblk[safe].reshape(g, p), 0)
    s = ghash_grouped(gmat_g, gin_g, nblk_g, width // 16)
    s_rows = s.reshape(g * p, 16)[inv_pos]
    ek_j0 = aes_encrypt(round_keys, j0)
    return jnp.bitwise_xor(s_rows, ek_j0)


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_protect_grouped(data, length, aad_len, round_keys, gmat_g, iv12,
                        grid_rows, inv_pos, aad_const=None):
    """`gcm_protect` with stream-grouped GHASH: round_keys [B, R, 16]
    stay per-row (cheap), gmat_g [G, 128, 128] is per GROUP."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    aad_len = jnp.asarray(aad_len, dtype=jnp.int32)
    j0 = _j0(jnp.asarray(iv12))
    ctr0 = _inc32(j0)
    ct_len = length - aad_len
    if aad_const is not None:
        enc = ctr_crypt_uniform(round_keys, ctr0, data, aad_const, ct_len)
    else:
        enc = ctr_crypt_offset(round_keys, ctr0, data, aad_len, ct_len)
    width = _ghash_width(data.shape[1])
    tag = _grouped_tag(round_keys, gmat_g, enc, aad_len, ct_len, j0,
                       grid_rows, inv_pos, width, aad_const)
    out = _scatter_tag(enc, length, tag)
    return out, length + TAG_LEN


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_unprotect_grouped(data, length, aad_len, round_keys, gmat_g,
                          iv12, grid_rows, inv_pos, aad_const=None):
    """`gcm_unprotect` with stream-grouped GHASH."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    aad_len = jnp.asarray(aad_len, dtype=jnp.int32)
    mlen = length - TAG_LEN
    ct_len = mlen - aad_len
    j0 = _j0(jnp.asarray(iv12))
    width = _ghash_width(data.shape[1])
    want = _grouped_tag(round_keys, gmat_g, data, aad_len, ct_len, j0,
                        grid_rows, inv_pos, width, aad_const)
    stored = _gather_span(data, mlen, TAG_LEN)
    auth_ok = jnp.all(stored == want, axis=1)
    ctr0 = _inc32(j0)
    if aad_const is not None:
        dec = ctr_crypt_uniform(round_keys, ctr0, data, aad_const, ct_len)
    else:
        dec = ctr_crypt_offset(round_keys, ctr0, data, aad_len, ct_len)
    return dec, mlen, auth_ok


# --- keystream-cache fast path ---------------------------------------------
#
# SRTP-GCM's per-packet AES work is fully determined by (session key,
# ssrc, packet index): the CTR keystream and the E(K, J0) tag mask can
# be computed before the packet exists.  The cached kernels below take
# that material pre-gathered per row (transform/srtp/keystream.py owns
# the window bookkeeping) and run only the irreducibly online half —
# the payload XOR and the ciphertext-dependent GHASH.  No round keys
# cross the jit boundary at all on this path.

def _cached_width(cap: int, aad_const: int, ks_bytes: int) -> int:
    """GHASH width for the cached path: the cache's hit test guarantees
    ct_len <= ks_bytes, so the Horner round count is bounded by the
    keystream window's byte depth, not the packet buffer's padded
    capacity — at the default 256-byte window that is ~18 rounds
    instead of ~96 for a 1504-byte buffer."""
    return min(_ghash_width(cap), _ceil16(aad_const) + _ceil16(ks_bytes) + 16)


def _xor_cached(data, ks, offset: int, ct_len):
    """XOR a cached keystream row into [offset, offset+ct_len) with the
    same static pad-shift as `_xor_window_uniform`.  `ks` is [B, KS]
    with KS possibly smaller than the packet width — the cache's hit
    test guarantees ct_len <= KS per row, so the right zero-pad is
    never reached by an inside column."""
    width = data.shape[1]
    ks = jnp.asarray(ks, dtype=jnp.uint8)
    pad = max(0, width - offset - ks.shape[1])
    ks_aligned = jnp.pad(ks, ((0, 0), (offset, pad)))[:, :width]
    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    ln = jnp.asarray(ct_len, dtype=jnp.int32)[:, None]
    inside = (col >= offset) & (col < offset + ln)
    return jnp.where(inside, data ^ ks_aligned, data)


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_protect_cached(data, length, ks, ek_j0, gmat, aad_const: int):
    """`gcm_protect` with the AES plane precomputed: ks [B, KS] uint8 is
    the CTR keystream starting at inc32(J0); ek_j0 [B, 16] the cached
    E(K, J0) tag masks; gmat [B, 128, 128] per-row GHASH matrices.
    Only the uniform-AAD shape exists — the cache serves all-or-nothing
    batches whose headers agree on one payload offset.  Bit-exact with
    `gcm_protect` by construction: the GHASH-input builder and tag
    scatter are the same code, and CTR keystream ⊕ data is the same
    bytes regardless of when the keystream was generated."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    ct_len = length - aad_const
    enc = _xor_cached(data, ks, aad_const, ct_len)
    width = _cached_width(data.shape[1], aad_const, ks.shape[1])
    gin, nblk = _build_ghash_input_uniform(enc, aad_const, ct_len, width)
    s = ghash(gmat, gin, nblk, width // 16)
    tag = jnp.bitwise_xor(s, jnp.asarray(ek_j0, dtype=jnp.uint8))
    out = _scatter_tag(enc, length, tag)
    return out, length + TAG_LEN


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_unprotect_cached(data, length, ks, ek_j0, gmat, aad_const: int):
    """`gcm_unprotect` on cached keystream/tag-mask rows.  Returns
    (data', length - 16, auth_ok); decrypt always runs (branch-free)."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    mlen = length - TAG_LEN
    ct_len = mlen - aad_const
    width = _cached_width(data.shape[1], aad_const, ks.shape[1])
    gin, nblk = _build_ghash_input_uniform(data, aad_const, ct_len, width)
    s = ghash(gmat, gin, nblk, width // 16)
    want = jnp.bitwise_xor(s, jnp.asarray(ek_j0, dtype=jnp.uint8))
    stored = _gather_span(data, mlen, TAG_LEN)
    auth_ok = jnp.all(stored == want, axis=1)
    dec = _xor_cached(data, ks, aad_const, ct_len)
    return dec, mlen, auth_ok


def _cached_grouped_digest(gmat_g, enc, ct_len, grid_rows, inv_pos,
                           width: int, aad_const: int, packed: bool):
    """Grouped-GHASH digest for the cached path (same grid/inverse
    plumbing as `_grouped_tag`, minus the AES tag-mask encrypt).
    `packed` selects the AND/popcount GF(2) matvec over the int8 MXU
    matmul — both are registered as providers and the registry's
    benchmark-and-pick keeps the faster one per backend."""
    from libjitsi_tpu.kernels.ghash import (ghash_grouped,
                                            ghash_grouped_packed)

    gin, nblk = _build_ghash_input_uniform(enc, aad_const, ct_len, width)
    g, p = grid_rows.shape
    safe = jnp.clip(grid_rows.reshape(-1), 0, enc.shape[0] - 1)
    gin_g = gin[safe].reshape(g, p, width)
    nblk_g = jnp.where(grid_rows >= 0, nblk[safe].reshape(g, p), 0)
    fn = ghash_grouped_packed if packed else ghash_grouped
    s = fn(gmat_g, gin_g, nblk_g, width // 16)
    return s.reshape(g * p, 16)[inv_pos]


@functools.partial(jax.jit, static_argnames=("aad_const", "packed"))
def gcm_protect_cached_grouped(data, length, ks, ek_j0, gmat_g,
                               grid_rows, inv_pos, aad_const: int,
                               packed: bool = False):
    """`gcm_protect_cached` with stream-grouped GHASH (gmat_g is per
    GROUP, read once per stream instead of once per row)."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    ct_len = length - aad_const
    enc = _xor_cached(data, ks, aad_const, ct_len)
    width = _cached_width(data.shape[1], aad_const, ks.shape[1])
    s_rows = _cached_grouped_digest(gmat_g, enc, ct_len, grid_rows,
                                    inv_pos, width, aad_const, packed)
    tag = jnp.bitwise_xor(s_rows, jnp.asarray(ek_j0, dtype=jnp.uint8))
    out = _scatter_tag(enc, length, tag)
    return out, length + TAG_LEN


@functools.partial(jax.jit, static_argnames=("aad_const", "packed"))
def gcm_unprotect_cached_grouped(data, length, ks, ek_j0, gmat_g,
                                 grid_rows, inv_pos, aad_const: int,
                                 packed: bool = False):
    """`gcm_unprotect_cached` with stream-grouped GHASH."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    mlen = length - TAG_LEN
    ct_len = mlen - aad_const
    width = _cached_width(data.shape[1], aad_const, ks.shape[1])
    s_rows = _cached_grouped_digest(gmat_g, data, ct_len, grid_rows,
                                    inv_pos, width, aad_const, packed)
    want = jnp.bitwise_xor(s_rows, jnp.asarray(ek_j0, dtype=jnp.uint8))
    stored = _gather_span(data, mlen, TAG_LEN)
    auth_ok = jnp.all(stored == want, axis=1)
    dec = _xor_cached(data, ks, aad_const, ct_len)
    return dec, mlen, auth_ok


@functools.partial(jax.jit, static_argnames=("aad_const",))
def gcm_protect_fanout(data, length, round_keys, gmat, iv12,
                       aad_const: int = 12):
    """Full-mesh SFU seal: P packets x G receiver legs in one launch.

    data [P, W] uint8 — the SAME decrypted packets go to every leg;
    length [P] int32; round_keys [G, R, 16]; gmat [G, 128, 128] int8
    (one GHASH matrix per LEG, read once per leg via `ghash_grouped`
    instead of once per output row); iv12 [G, P, 12] (leg salt x sender
    ssrc/index).  Returns (out [G, P, W], out_len [P] + 16).
    """
    from libjitsi_tpu.kernels.ghash import ghash_grouped

    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    g = round_keys.shape[0]
    p, w = data.shape
    rows = g * p
    data_gp = jnp.broadcast_to(data[None], (g, p, w)).reshape(rows, w)
    rk_rows = jnp.repeat(jnp.asarray(round_keys), p, axis=0)
    j0 = _j0(jnp.asarray(iv12).reshape(rows, 12))
    ctr0 = _inc32(j0)
    length_r = jnp.tile(length, g)
    ct_len = length_r - aad_const
    enc = ctr_crypt_uniform(rk_rows, ctr0, data_gp, aad_const, ct_len)
    width = _ghash_width(w)
    gin, nblk = _build_ghash_input_uniform(enc, aad_const, ct_len, width)
    s = ghash_grouped(jnp.asarray(gmat), gin.reshape(g, p, width),
                      nblk.reshape(g, p), width // 16)
    ek_j0 = aes_encrypt(rk_rows, j0)
    tag = jnp.bitwise_xor(s.reshape(rows, 16), ek_j0)
    out = _scatter_tag(enc, length_r, tag)
    return out.reshape(g, p, w), length + TAG_LEN


def srtp_gcm_iv(salt12: np.ndarray, ssrc: np.ndarray,
                index: np.ndarray) -> np.ndarray:
    """RFC 7714 §8.1 SRTP IV: (00 00 || SSRC || ROC || SEQ) XOR salt.

    Host-side, broadcast-capable: `salt12` [..., 12] uint8 is copied and
    XORed with `ssrc` (4 bytes at offsets 2..5) and the 48-bit `index`
    (offsets 6..11).  Single IV-construction source for the stream table
    and the SFU translator — nonce layout must never diverge.
    """
    iv = np.array(salt12[..., :12], dtype=np.uint8, copy=True)
    ssrc = np.asarray(ssrc, dtype=np.int64)
    index = np.asarray(index, dtype=np.int64)
    for k in range(4):
        iv[..., 2 + k] ^= ((ssrc >> (8 * (3 - k))) & 0xFF).astype(np.uint8)
    for k in range(6):
        iv[..., 6 + k] ^= ((index >> (8 * (5 - k))) & 0xFF).astype(np.uint8)
    return iv
