"""Pallas TPU kernels for the conference hot ops.

BASELINE.json's north star names Pallas for the per-packet/PCM hot math
("...AudioMixer's N-participant PCM sum become Pallas kernels...").  This
module provides the Pallas implementations; `kernels.registry` pairs each
with its XLA twin and — like the reference's
`org.jitsi.impl.neomedia.transform.srtp.crypto.Aes`, which benchmarks
SunJCE/BouncyCastle/OpenSSL at startup and keeps the fastest — selects
per op by measurement, not by assumption.  (Measured on v5e via the axon
tunnel, XLA's fusion currently wins the mixer by ~2x; the registry keeps
whichever wins on the deployment's hardware.)

Kernel design notes
- One fused VMEM pass per conference frame: the [N, F] PCM block is read
  once; total-sum, mix-minus, clipping and the RFC 6465 level reduction
  all happen before anything returns to HBM.  The XLA path materializes
  the same math as two programs (mix and levels) when called separately.
- No gathers: Mosaic on this toolchain rejects table gathers (the AES
  S-box experiment fails to lower), so only gather-free ops live here.
- Outputs are int32 (int16/uint8 tiles need (16,128)/(32,128) sublane
  alignment; the cheap narrowing cast happens outside the kernel).
- Everything is interpret-mode testable on CPU (tests force
  `interpret=True`), matching the survey's test strategy (§5: "interpret
  -mode Pallas runs in CI").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I16_MIN = -32768
I16_MAX = 32767


def _mix_kernel(pcm_ref, active_ref, out_ref, lvl_ref):
    """Fused mix-minus + RFC 6465 levels over one [N, F] frame block."""
    pcm = pcm_ref[:].astype(jnp.int32)
    active = active_ref[:].astype(jnp.int32)  # [N, 1] 0/1
    contrib = pcm * active
    total = jnp.sum(contrib, axis=0, keepdims=True)       # [1, F]
    out_ref[:] = jnp.clip(total - contrib, I16_MIN, I16_MAX)
    x = pcm.astype(jnp.float32) * (1.0 / 32768.0)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)          # [N, 1]
    db = 10.0 * jnp.log10(jnp.maximum(ms, 1e-12))
    lvl = jnp.clip(jnp.round(-db), 0, 127).astype(jnp.int32)
    silent = jnp.logical_or(ms <= 1e-12, active == 0)
    lvl_ref[:] = jnp.where(silent, jnp.int32(127), lvl)


@functools.partial(jax.jit, static_argnames=("interpret",))
def mix_minus_pallas(pcm, active, interpret: bool = False):
    """Pallas twin of `conference.mixer.mix_minus`.

    pcm int16 [N, F], active bool [N] -> (out int16 [N, F], levels uint8
    [N]).  Bit-identical to the XLA path (same clipping, same dBov
    rounding, inactive/silent rows report 127).
    """
    n, f = pcm.shape
    act = jnp.asarray(active, dtype=jnp.int32).reshape(n, 1)
    out, lvl = pl.pallas_call(
        _mix_kernel,
        out_shape=(jax.ShapeDtypeStruct((n, f), jnp.int32),
                   jax.ShapeDtypeStruct((n, 1), jnp.int32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(jnp.asarray(pcm), act)
    return out.astype(jnp.int16), lvl.reshape(n).astype(jnp.uint8)
