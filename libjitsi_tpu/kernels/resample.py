"""Polyphase audio resampler as an XLA convolution.

Rebuilds the role of the reference's Speex resampler (`src/native/speex`,
used to normalize all conference inputs to one rate before mixing —
SURVEY §2.5 "the resampler matters for the mixer").  A windowed-sinc FIR
evaluated polyphase: for conversion L/M, output phase p uses filter bank
row p; the whole batch of streams resamples in one `conv_general_dilated`
(MXU-friendly: [B, 1, T] x [phases, taps]).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _design(l: int, m: int, taps_per_phase: int = 16,
            cutoff_scale: float = 0.9) -> np.ndarray:
    """[L, taps] polyphase bank of a Kaiser-windowed sinc low-pass."""
    ntaps = taps_per_phase * l
    cutoff = cutoff_scale * 0.5 / max(l, m)  # in units of upsampled rate
    n = np.arange(ntaps) - (ntaps - 1) / 2.0
    h = 2 * cutoff * np.sinc(2 * cutoff * n)
    h *= np.kaiser(ntaps, beta=8.0)
    h *= l  # gain compensation for zero-stuffing
    # phase p takes taps h[p], h[p+L], ...
    bank = np.zeros((l, taps_per_phase), dtype=np.float32)
    for p in range(l):
        bank[p] = h[p::l][:taps_per_phase]
    return bank


@functools.partial(jax.jit, static_argnames=("l", "m", "taps_per_phase"))
def _resample_jit(pcm, l: int, m: int, taps_per_phase: int):
    b, t = pcm.shape
    bank = jnp.asarray(_design(l, m, taps_per_phase))
    out_len = (t * l) // m
    # output sample j sits at upsampled position j*M = phase + L*shift
    j = jnp.arange(out_len)
    pos = j * m
    phase = (pos % l).astype(jnp.int32)
    base = (pos // l).astype(jnp.int32)
    # gather input windows [B, out_len, taps]
    k = jnp.arange(taps_per_phase, dtype=jnp.int32)
    idx = base[None, :, None] - k[None, None, :] + (taps_per_phase // 2)
    idx = jnp.clip(idx, 0, t - 1)
    x = pcm.astype(jnp.float32)[:, None, :]
    win = jnp.take_along_axis(jnp.broadcast_to(x, (b, out_len, t)), idx,
                              axis=2)
    coef = bank[phase]  # [out_len, taps]
    y = jnp.einsum("bot,ot->bo", win, coef)
    return jnp.clip(jnp.round(y), -32768, 32767).astype(jnp.int16)


def resample(pcm, rate_in: int, rate_out: int,
             taps_per_phase: int = 16):
    """int16 [B, T] at rate_in -> int16 [B, T*L//M] at rate_out.

    L/M reduced from the rate ratio; supports the conference-relevant
    conversions (8k/16k/24k/44.1k <-> 48k).
    """
    if rate_in == rate_out:
        return jnp.asarray(pcm, dtype=jnp.int16)
    g = math.gcd(rate_in, rate_out)
    l, m = rate_out // g, rate_in // g
    if l > 480:
        raise ValueError(f"unreasonable ratio {rate_out}/{rate_in}")
    return _resample_jit(jnp.asarray(pcm), l, m, taps_per_phase)


def resample_to_frame(pcm, rate_in: int, rate_out: int,
                      frame: int) -> "np.ndarray":
    """`resample` pinned to an exact output frame width.

    The conference paths (mixer deposit up-conversion and egress
    down-conversion) both need rows of exactly the target clock's frame
    size; L/M rounding can leave the resampler a sample short/long, so
    trim or zero-pad to `frame`.  Shared so the two paths can never
    drift apart.
    """
    import numpy as np

    out = np.asarray(resample(pcm, rate_in, rate_out), dtype=np.int16)
    if out.shape[1] != frame:
        out = (out[:, :frame] if out.shape[1] > frame
               else np.pad(out, ((0, 0), (0, frame - out.shape[1]))))
    return out
