"""Batched AES-128/256 and AES-CTR as pure-JAX vectorized kernels.

This is the cipher half of the SRTP hot path.  The reference selects among
AES providers at startup (`org.jitsi.impl.neomedia.transform.srtp.crypto.Aes`
benchmarks SunJCE / BouncyCastle / OpenSSL-JNI and picks the fastest) and
runs AES-CM per packet.  Here the per-packet loop inverts into one batched
computation: `[B, 16]` counter blocks -> `[B, 16]` keystream blocks, uint8
vector math + one 256-entry S-box gather per round, with the batch axis
(packets x blocks) supplying the parallelism the MXU/VPU wants.

Design notes
- Key expansion is host-side NumPy (cold path, per-stream, tiny); the device
  consumes a dense `[B, rounds+1, 16]` round-key tensor gathered per packet
  row by stream id — this is how per-stream SRTP session keys batch.
- The round loop is unrolled at trace time (constant 10/14 trip count).
- S-box lookups are `jnp.take` gathers on a 256-byte constant; correctness
  first.  A bitsliced boolean-circuit S-box (gather-free) is the planned
  optimization — swap inside `_sub_bytes` without touching callers.
- State layout is the FIPS-197 flat byte order (index = row + 4*col), so
  blocks go in/out with no repacking.
- The S-box and round constants are *generated* from GF(2^8) arithmetic at
  import, not transcribed, eliminating table-typo risk.

KATs: FIPS-197 App. C, NIST SP 800-38A F.5 (CTR), plus differential tests
against the OpenSSL-backed `cryptography` package (tests/test_aes.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# GF(2^8) tables (host, generated once)
# ---------------------------------------------------------------------------

def _make_sbox() -> np.ndarray:
    # log/antilog over GF(2^8) with generator 0x03
    exp = np.zeros(256, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        # x *= 3  (== xtime(x) ^ x)
        x = (((x << 1) ^ (0x1B if x & 0x80 else 0)) & 0xFF) ^ x
    sbox = np.zeros(256, dtype=np.uint8)
    for a in range(256):
        inv = 0 if a == 0 else exp[(255 - log[a]) % 255]
        s = inv
        for _ in range(4):
            inv = ((inv << 1) | (inv >> 7)) & 0xFF
            s ^= inv
        sbox[a] = s ^ 0x63
    return sbox


_SBOX = _make_sbox()

# ShiftRows as a static permutation of the flat (row + 4*col) state:
# out[r + 4c] = in[r + 4*((c + r) % 4)]
_SHIFT_IDX = np.array(
    [r + 4 * ((c + r) % 4) for c in range(4) for r in range(4)], dtype=np.int32
)


# ---------------------------------------------------------------------------
# Key expansion (host)
# ---------------------------------------------------------------------------

def expand_key(key) -> np.ndarray:
    """FIPS-197 key schedule.  key: 16 or 32 bytes -> [rounds+1, 16] uint8.

    Host-side, per stream (cold path).  Reference analog: the cipher init in
    SRTPCipherCTR / the JCE key schedule.
    """
    key = np.frombuffer(bytes(key), dtype=np.uint8) if isinstance(key, (bytes, bytearray)) else np.asarray(key, dtype=np.uint8)
    if len(key) not in (16, 32):
        raise ValueError("AES key must be 16 or 32 bytes")
    nk = len(key) // 4
    nr = nk + 6
    w = np.zeros((4 * (nr + 1), 4), dtype=np.uint8)
    w[:nk] = key.reshape(nk, 4)
    rcon = np.uint8(1)
    for i in range(nk, 4 * (nr + 1)):
        t = w[i - 1].copy()
        if i % nk == 0:
            t = np.roll(t, -1)
            t = _SBOX[t]
            t[0] ^= rcon
            rcon = np.uint8(((int(rcon) << 1) ^ (0x11B if rcon & 0x80 else 0)) & 0xFF)
        elif nk == 8 and i % nk == 4:
            t = _SBOX[t]
        w[i] = w[i - nk] ^ t
    # word c of round r -> flat bytes [4c .. 4c+3] == (row + 4*col) layout
    return w.reshape(nr + 1, 16)


def expand_keys_batch(keys: np.ndarray) -> np.ndarray:
    """[S, 16|32] uint8 -> [S, rounds+1, 16] uint8 round-key tensor.

    Vectorized across streams: the FIPS-197 schedule is sequential in the
    word index (44/60 steps) but embarrassingly parallel across keys, so
    each step is one [S, 4] vector op.  10k-stream installs take
    milliseconds instead of the per-key loop's seconds.
    """
    keys = np.atleast_2d(np.asarray(keys, dtype=np.uint8))
    s, kl = keys.shape
    if kl not in (16, 32):
        raise ValueError("AES keys must be 16 or 32 bytes")
    nk = kl // 4
    nr = nk + 6
    w = np.zeros((s, 4 * (nr + 1), 4), dtype=np.uint8)
    w[:, :nk] = keys.reshape(s, nk, 4)
    rcon = 1
    for i in range(nk, 4 * (nr + 1)):
        t = w[:, i - 1].copy()
        if i % nk == 0:
            t = np.roll(t, -1, axis=1)
            t = _SBOX[t]
            t[:, 0] ^= np.uint8(rcon)
            rcon = ((rcon << 1) ^ (0x11B if rcon & 0x80 else 0)) & 0xFF
        elif nk == 8 and i % nk == 4:
            t = _SBOX[t]
        w[:, i] = w[:, i - nk] ^ t
    return w.reshape(s, nr + 1, 16)


# ---------------------------------------------------------------------------
# Host cipher (NumPy mirror of the device core — cold paths only)
# ---------------------------------------------------------------------------

def _xtime_np(x):
    return ((x << 1) ^ (np.uint8(0x1B) * (x >> 7))).astype(np.uint8)


def aes_encrypt_np(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """Host-side batched AES block encrypt (NumPy; mirrors `aes_encrypt`).

    Used by the cold paths that must not touch the device: RFC 3711 key
    derivation at stream setup, KATs, and the CPU fallback backend (the
    reference keeps a pure-Java AES fallback beside the OpenSSL JNI path in
    `.srtp.crypto.Aes`).  round_keys: [R, 16] or [B, R, 16]; blocks: [B, 16].
    """
    rk = np.asarray(round_keys, dtype=np.uint8)
    if rk.ndim == 2:
        rk = np.broadcast_to(rk, (blocks.shape[0],) + rk.shape)
    st = np.asarray(blocks, dtype=np.uint8) ^ rk[:, 0, :]
    nr = rk.shape[1] - 1
    for r in range(1, nr):
        st = _SBOX[st][:, _SHIFT_IDX]
        s = st.reshape(-1, 4, 4)
        x = _xtime_np(s)
        r0 = x[..., 0] ^ x[..., 1] ^ s[..., 1] ^ s[..., 2] ^ s[..., 3]
        r1 = s[..., 0] ^ x[..., 1] ^ x[..., 2] ^ s[..., 2] ^ s[..., 3]
        r2 = s[..., 0] ^ s[..., 1] ^ x[..., 2] ^ x[..., 3] ^ s[..., 3]
        r3 = x[..., 0] ^ s[..., 0] ^ s[..., 1] ^ s[..., 2] ^ x[..., 3]
        st = np.stack([r0, r1, r2, r3], axis=-1).reshape(st.shape) ^ rk[:, r, :]
    return (_SBOX[st][:, _SHIFT_IDX] ^ rk[:, nr, :]).astype(np.uint8)


def ctr_keystream_np(round_keys: np.ndarray, iv16: np.ndarray, nbytes: int) -> np.ndarray:
    """Host AES-CTR keystream from one IV block: [R,16] keys, [16] iv -> [nbytes]."""
    nblocks = (nbytes + 15) // 16
    iv = np.asarray(iv16, dtype=np.uint8)
    ctrs = np.zeros((nblocks, 16), dtype=np.uint8)
    val = int.from_bytes(bytes(iv), "big")
    for j in range(nblocks):
        ctrs[j] = np.frombuffer(
            ((val + j) % (1 << 128)).to_bytes(16, "big"), dtype=np.uint8
        )
    return aes_encrypt_np(np.asarray(round_keys), ctrs).reshape(-1)[:nbytes]


# ---------------------------------------------------------------------------
# Device cipher core
# ---------------------------------------------------------------------------

def _sub_bytes(st):
    return jnp.take(jnp.asarray(_SBOX), st, axis=0)


def _shift_rows(st):
    return st[..., jnp.asarray(_SHIFT_IDX)]


def _xtime(x):
    # uint8 lanes: (x<<1) wraps mod 256; conditional 0x1B reduction
    return (x << 1) ^ (jnp.uint8(0x1B) * (x >> 7))


def _mix_columns(st):
    # st: [..., 16] flat (row + 4*col) -> view as [..., 4 cols, 4 rows]
    s = st.reshape(st.shape[:-1] + (4, 4))
    s0, s1, s2, s3 = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    x0, x1, x2, x3 = _xtime(s0), _xtime(s1), _xtime(s2), _xtime(s3)
    r0 = x0 ^ (x1 ^ s1) ^ s2 ^ s3
    r1 = s0 ^ x1 ^ (x2 ^ s2) ^ s3
    r2 = s0 ^ s1 ^ x2 ^ (x3 ^ s3)
    r3 = (x0 ^ s0) ^ s1 ^ s2 ^ x3
    return jnp.stack([r0, r1, r2, r3], axis=-1).reshape(st.shape)


def aes_encrypt_table(round_keys, blocks):
    """Batched AES block encrypt (table/S-box-gather core).

    round_keys: [..., R, 16] uint8 (R = 11 for AES-128, 15 for AES-256);
    blocks: [..., 16] uint8.  -> [..., 16] uint8.  Round count is taken
    from the static shape, so this traces once per key size.
    """
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    st = jnp.asarray(blocks, dtype=jnp.uint8) ^ rk[..., 0, :]
    nr = rk.shape[-2] - 1
    for r in range(1, nr):
        st = _mix_columns(_shift_rows(_sub_bytes(st))) ^ rk[..., r, :]
    return _shift_rows(_sub_bytes(st)) ^ rk[..., nr, :]


# Selectable encrypt core (the reference's `.srtp.crypto.Aes`
# benchmark-and-pick idea at the kernel level): "table" (S-box gather)
# or a "bitsliced" variant (gather-free Boolean circuits,
# kernels/aes_bitsliced.py).  Selection order in get_core():
#   1. LIBJITSI_TPU_AES_CORE / set_core() — explicit pin, wins always;
#   2. the measured record (AES_CORES.json via
#      kernels/registry.py:measured_aes_core): per-backend chained
#      above-floor numbers from the bench_aes_cores protocol, picked
#      by blocks/s among status=="ok" cores only — below_floor and
#      budget-skipped entries are refusals, never evidence;
#   3. heuristic fallback when no record covers the backend: table on
#      CPU (XLA:CPU's gather is cheap), composite-field tower bitslice
#      on accelerators (per-byte S-box gathers are the vector unit's
#      worst case, pure lane-parallel bit math its best).
# The choice is read at TRACE time, so switch before the first jit of
# the consuming kernels (set_core clears jax caches so later compiles
# re-pick).
import os as _os

_CORES = ("table", "bitsliced", "bitsliced_tower", "bitsliced32")
_CORE_NAME = _os.environ.get("LIBJITSI_TPU_AES_CORE")  # None = by backend
if _CORE_NAME not in (None,) + _CORES:
    raise ValueError(
        f"LIBJITSI_TPU_AES_CORE={_CORE_NAME!r}: must be one of {_CORES} "
        "(a typo would otherwise silently run the default)")


def set_core(name: str) -> None:
    global _CORE_NAME
    if name not in _CORES:
        raise ValueError(f"aes core must be one of {_CORES}")
    if name != _CORE_NAME:
        _CORE_NAME = name
        jax.clear_caches()


def get_core() -> str:
    global _CORE_NAME
    if _CORE_NAME is None:
        # resolved lazily so importing this module never forces a
        # backend init (conftest flips platforms before first use).
        # Measured pick first: AES_CORES.json holds per-backend
        # chained above-floor blocks/s (the only timing protocol that
        # survived round 5 — single-launch spans sit inside the
        # scalar-fetch floor's jitter and emit junk, see BASELINE.md),
        # and measured_aes_core returns the fastest status=="ok" core
        # for this backend or None when none exists.  Heuristic
        # fallback mirrors what the measurements have shown so far:
        # table on CPU (chained: gathers are cheap there), the
        # composite-field tower bitslice elsewhere (fetch-verified
        # fastest credible core on v5e; bitsliced32 has no above-floor
        # TPU number, so it can only win via a future measured record).
        from libjitsi_tpu.kernels import registry as _registry

        measured = _registry.measured_aes_core()
        if measured is not None:
            _CORE_NAME = measured
        else:
            _CORE_NAME = ("table" if jax.default_backend() == "cpu"
                          else "bitsliced_tower")
    return _CORE_NAME


def aes_encrypt(round_keys, blocks):
    """Batched AES block encrypt via the selected core ([..., R, 16]
    keys, [..., 16] blocks; see `set_core`)."""
    core = get_core()
    if core == "bitsliced":
        from libjitsi_tpu.kernels.aes_bitsliced import \
            aes_encrypt_bitsliced_nd

        return aes_encrypt_bitsliced_nd(round_keys, blocks)
    if core == "bitsliced_tower":
        from libjitsi_tpu.kernels.aes_bitsliced import \
            aes_encrypt_bitsliced_tower_nd

        return aes_encrypt_bitsliced_tower_nd(round_keys, blocks)
    if core == "bitsliced32":
        from libjitsi_tpu.kernels.aes_bitsliced import \
            aes_encrypt_bitsliced32_nd

        return aes_encrypt_bitsliced32_nd(round_keys, blocks)
    return aes_encrypt_table(round_keys, blocks)


def _iv_to_limbs(iv):
    """[B, 16] uint8 -> [B, 4] uint32 big-endian limbs."""
    w = iv.astype(jnp.uint32).reshape(iv.shape[0], 4, 4)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


def _limbs_to_bytes(limbs):
    """[..., 4] uint32 -> [..., 16] uint8 big-endian."""
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    b = (limbs[..., :, None] >> shifts) & jnp.uint32(0xFF)
    return b.astype(jnp.uint8).reshape(limbs.shape[:-1] + (16,))


def _counter_blocks(iv, nblocks):
    """[B, 16] iv -> [B, nblocks, 16] counter blocks (128-bit BE increment)."""
    limbs = _iv_to_limbs(iv)  # [B, 4]
    j = jnp.arange(nblocks, dtype=jnp.uint32)  # [n]
    l3 = limbs[:, None, 3] + j[None, :]
    carry = (l3 < j[None, :]).astype(jnp.uint32)
    l2 = limbs[:, None, 2] + carry
    carry = (l2 < carry).astype(jnp.uint32)
    l1 = limbs[:, None, 1] + carry
    carry = (l1 < carry).astype(jnp.uint32)
    l0 = limbs[:, None, 0] + carry
    return _limbs_to_bytes(jnp.stack([l0, l1, l2, l3], axis=-1))


@functools.partial(jax.jit, static_argnames=("nblocks",))
def ctr_keystream(round_keys, iv, nblocks: int):
    """AES-CTR keystream:  [B, R, 16] keys + [B, 16] iv -> [B, nblocks*16] uint8.

    The counter is the full 128-bit big-endian block (NIST SP 800-38A
    increment); SRTP's 16-bit block counter (RFC 3711 §4.1.1) is the special
    case where the IV's low 16 bits start at zero.
    """
    bsz = iv.shape[0]
    ctr = _counter_blocks(jnp.asarray(iv, dtype=jnp.uint8), nblocks)  # [B, n, 16]
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)[:, None, :, :]  # [B, 1, R, 16]
    ks = aes_encrypt(jnp.broadcast_to(rk, (bsz, nblocks) + rk.shape[2:]), ctr)
    return ks.reshape(bsz, nblocks * 16)


@functools.partial(jax.jit, static_argnames=("nblocks",))
def f8_keystream(round_keys, f8_round_keys, iv, nblocks: int):
    """AES-F8 keystream (RFC 3711 §4.1.2): the reference's SRTPCipherF8.

    IV' = E(k_e XOR m, IV) is one batched block encrypt; the keystream
    S(j) = E(k_e, IV' XOR j XOR S(j-1)) has a sequential dependence over
    a packet's blocks (unlike CTR), so blocks run under `lax.scan` while
    the batch axis stays fully parallel — ≤ ~12 scan steps for audio
    MTUs.  `j` is the block counter as a 128-bit big-endian integer.

    round_keys/f8_round_keys: [B, R, 16] (schedules of k_e and k_e XOR m);
    iv: [B, 16].  -> [B, nblocks*16] uint8.
    """
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    ivp = aes_encrypt(jnp.asarray(f8_round_keys, dtype=jnp.uint8),
                      jnp.asarray(iv, dtype=jnp.uint8))  # IV'

    def body(s_prev, j):
        blk = ivp ^ s_prev
        # XOR the 128-bit BE block counter.  j is uint32, so only the low
        # 4 counter bytes (12..15) can be nonzero — shifting uint32 by
        # >=32 would be undefined, so touch only those bytes.
        jb = (j >> (jnp.arange(4, dtype=jnp.uint32)[::-1] * 8)).astype(
            jnp.uint8)
        blk = blk.at[:, 12:].set(blk[:, 12:] ^ jb[None, :])
        s = aes_encrypt(rk, blk)
        return s, s

    _, ks = jax.lax.scan(body, jnp.zeros_like(ivp),
                         jnp.arange(nblocks, dtype=jnp.uint32))
    # ks: [nblocks, B, 16] -> [B, nblocks*16]
    return ks.transpose(1, 0, 2).reshape(ivp.shape[0], nblocks * 16)


def f8_m(session_key: bytes, session_salt: bytes) -> bytes:
    """RFC 3711 §4.1.2.2: m = k_s || 0x55.. padded to the key length."""
    return session_salt + b"\x55" * (len(session_key) - len(session_salt))


def f8_keystream_np(session_key: bytes, session_salt: bytes, iv16: bytes,
                    nbytes: int) -> bytes:
    """Independent scalar F8 oracle (OpenSSL AES via `cryptography`).

    Deliberately shares no code with the batched path — the differential
    test compares two implementations written from the RFC separately.
    """
    from cryptography.hazmat.primitives.ciphers import (
        Cipher as _C, algorithms as _a, modes as _m)

    def ecb(key: bytes, block: bytes) -> bytes:
        enc = _C(_a.AES(key), _m.ECB()).encryptor()
        return enc.update(block) + enc.finalize()

    m = f8_m(session_key, session_salt)
    kxm = bytes(a ^ b for a, b in zip(session_key, m))
    ivp = ecb(kxm, bytes(iv16))
    out = b""
    s = b"\x00" * 16
    j = 0
    while len(out) < nbytes:
        blk = bytes(a ^ b for a, b in zip(ivp, s))
        blk = bytes(a ^ b for a, b in zip(blk, j.to_bytes(16, "big")))
        s = ecb(session_key, blk)
        out += s
        j += 1
    return out[:nbytes]


def _xor_window_uniform(data, ks, offset: int, length):
    """XOR keystream `ks` into each row's [offset, offset+length) span
    with a static pad-shift (no per-row gather)."""
    width = data.shape[1]
    ks_aligned = jnp.pad(ks, ((0, 0), (offset, 0)))[:, :width]
    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    ln = jnp.asarray(length, dtype=jnp.int32)[:, None]
    inside = (col >= offset) & (col < offset + ln)
    return jnp.where(inside, data ^ ks_aligned, data)


@functools.partial(jax.jit, static_argnames=("offset",))
def f8_crypt_uniform(round_keys, f8_round_keys, iv, data, offset: int,
                     length):
    """F8-encrypt/decrypt each row's payload window (uniform offset)."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    width = data.shape[1]
    nblocks = max(0, (width - offset + 15) // 16)
    if nblocks == 0:
        return data
    ks = f8_keystream(round_keys, f8_round_keys, iv, nblocks)
    return _xor_window_uniform(data, ks, offset, length)


@functools.partial(jax.jit, static_argnames=("offset",))
def ctr_crypt_uniform(round_keys, iv, data, offset: int, length):
    """Uniform-offset fast path of `ctr_crypt_offset`.

    When every row's payload begins at the same byte offset (the common
    case: fixed 12-byte RTP headers, or SRTCP's constant 8), the keystream
    alignment is a static left-pad — the per-row `take_along_axis` gather
    in the general path is by far its dominant cost on TPU (measured ~5x
    the AES itself), so the host picks this variant whenever the batch is
    offset-uniform.  Encrypt == decrypt (CTR).  -> [B, W] uint8.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    bsz, width = data.shape
    nblocks = max(0, (width - offset + 15) // 16)
    if nblocks == 0:            # offset beyond the buffer: nothing to crypt
        return data
    ks = ctr_keystream(round_keys, iv, nblocks)  # [B, nblocks*16]
    return _xor_window_uniform(data, ks, offset, length)


@jax.jit
def ctr_crypt_offset(round_keys, iv, data, offset, length):
    """XOR an AES-CTR keystream into each row's [offset, offset+length) span.

    data: [B, W] uint8; offset/length: [B] int32 — per-row payload windows
    (RTP payload begins at a per-packet header length).  Keystream byte k of
    the stream is applied at column offset+k, i.e. column j uses keystream
    byte (j - offset); bytes outside the window pass through unchanged.
    Encrypt == decrypt (CTR).  -> [B, W] uint8.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    bsz, width = data.shape
    nblocks = (width + 15) // 16
    ks = ctr_keystream(round_keys, iv, nblocks)  # [B, nblocks*16]
    return _xor_window_offset(data, ks, offset, length)


def _xor_window_offset(data, ks, offset, length):
    """XOR keystream into per-row windows (per-row gather alignment)."""
    width = data.shape[1]
    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    off = jnp.asarray(offset, dtype=jnp.int32)[:, None]
    ln = jnp.asarray(length, dtype=jnp.int32)[:, None]
    rel = jnp.clip(col - off, 0, ks.shape[1] - 1)
    ks_aligned = jnp.take_along_axis(ks, rel, axis=1)
    inside = (col >= off) & (col < off + ln)
    return jnp.where(inside, data ^ ks_aligned, data)


@jax.jit
def f8_crypt_offset(round_keys, f8_round_keys, iv, data, offset, length):
    """F8-encrypt/decrypt per-row payload windows (general offsets)."""
    data = jnp.asarray(data, dtype=jnp.uint8)
    nblocks = (data.shape[1] + 15) // 16
    ks = f8_keystream(round_keys, f8_round_keys, iv, nblocks)
    return _xor_window_offset(data, ks, offset, length)
