"""GHASH (GCM's GF(2^128) universal hash) as batched MXU bit-matrix math.

No CLMUL instruction exists on TPU; the usual software fallbacks are
bit-serial loops or 4-bit Shoup tables (gather-heavy).  The TPU-native
observation: multiplication by the *fixed* hash key H is GF(2)-linear,
so the whole Horner step ``Y <- (Y xor X) * H`` is one 128x128 Boolean
matrix applied to a 128-bit vector — i.e. an int8 matmul (mod 2) that
maps straight onto the MXU, batched over packets.  The matrix M_H
(including polynomial reduction) is precomputed on host per session key
(H = AES_K(0^128)), exactly the kind of per-stream constant the SRTP
tables already gather per row.

Bit order follows NIST SP 800-38D: bit 0 = MSB of byte 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_R = 0xE1 << 120  # reduction polynomial bits (11100001 || 0^120)


def gf_mult(x: int, y: int) -> int:
    """SP 800-38D §6.3 multiplication on 128-bit ints (b0 = MSB)."""
    z = 0
    v = y
    for i in range(128):
        if (x >> (127 - i)) & 1:
            z ^= v
        lsb = v & 1
        v >>= 1
        if lsb:
            v ^= _R
    return z


def ghash_matrix(h_block: bytes) -> np.ndarray:
    """[128, 128] uint8 matrix M with (M @ bits(X)) % 2 == bits(X * H).

    h_block: the 16-byte hash subkey H = AES_K(0^128).
    """
    h = int.from_bytes(h_block, "big")
    m = np.zeros((128, 128), dtype=np.uint8)
    for j in range(128):
        col = gf_mult(1 << (127 - j), h)
        for i in range(128):
            m[i, j] = (col >> (127 - i)) & 1
    return m


def ghash_matrix_batch(h_blocks: np.ndarray) -> np.ndarray:
    """Vectorized `ghash_matrix`: [S, 16] uint8 H blocks -> [S, 128, 128].

    Column j of M_H is H * x^j in GF(2^128); successive columns follow by
    one right-shift + conditional reduction, so the whole matrix builds in
    128 vector steps across all S streams (vs the scalar version's
    128x128 Python loop per stream — the GCM install-plane bottleneck).
    """
    hb = np.atleast_2d(np.asarray(h_blocks, dtype=np.uint8))
    s = hb.shape[0]
    # [S, 128] bit vectors, bit 0 = MSB of byte 0 (SP 800-38D order)
    col = np.unpackbits(hb, axis=1)
    rbits = np.unpackbits(
        np.frombuffer(_R.to_bytes(16, "big"), dtype=np.uint8))
    m = np.zeros((s, 128, 128), dtype=np.uint8)
    for j in range(128):
        m[:, :, j] = col
        lsb = col[:, 127:128]                  # coefficient of x^127
        col = np.concatenate(
            [np.zeros((s, 1), dtype=np.uint8), col[:, :-1]], axis=1)
        col = col ^ (lsb * rbits[None, :])
    return m


def ghash_ref(h_block: bytes, data: bytes) -> bytes:
    """Host reference GHASH over a whole (block-aligned) byte string."""
    if len(data) % 16:
        raise ValueError("ghash input must be block-aligned")
    h = int.from_bytes(h_block, "big")
    y = 0
    for i in range(0, len(data), 16):
        y = gf_mult(y ^ int.from_bytes(data[i:i + 16], "big"), h)
    return y.to_bytes(16, "big")


# ------------------------------------------------------------------ device

def _bytes_to_bits(blk):
    """uint8 [B, 16] -> int8 bits [B, 128] (bit 0 = MSB of byte 0)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (blk[:, :, None] >> shifts[None, None, :]) & 1
    return bits.reshape(blk.shape[0], 128).astype(jnp.int8)


def _bits_to_bytes(bits):
    w = (jnp.uint8(1) << jnp.arange(7, -1, -1, dtype=jnp.uint8))
    b = bits.reshape(bits.shape[0], 16, 8).astype(jnp.uint8) * w[None, None, :]
    return jnp.sum(b, axis=2).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("nblk_max",))
def ghash(matrices, data, nblocks, nblk_max: int):
    """Batched GHASH.

    matrices: int8 [B, 128, 128] per-row M_H (gathered per stream);
    data: uint8 [B, nblk_max*16] block-aligned, zero-padded;
    nblocks: int32 [B] actual block count per row.
    Returns uint8 [B, 16] digests.

    The Horner loop is sequential in blocks (data dependence) but each
    step is one batched MXU matmul over the whole packet batch; rows
    shorter than the running block index take identity steps.
    """
    b = data.shape[0]
    y = jnp.zeros((b, 128), dtype=jnp.int8)

    def body(i, y):
        blk = jax.lax.dynamic_slice_in_dim(data, i * 16, 16, axis=1)
        x = _bytes_to_bits(blk)
        t = jnp.bitwise_xor(y, x)
        prod = jnp.einsum("bij,bj->bi", matrices, t,
                          preferred_element_type=jnp.int32)
        y2 = (prod & 1).astype(jnp.int8)
        active = (i < nblocks)[:, None]
        return jnp.where(active, y2, y)

    y = jax.lax.fori_loop(0, nblk_max, body, y)
    return _bits_to_bytes(y)


def ghash_grouped(matrices, data, nblocks, nblk_max: int):
    """Grouped GHASH: G legs x P rows sharing one M_H per leg.

    matrices: int8 [G, 128, 128]; data: uint8 [G, P, nblk_max*16];
    nblocks: int32 [G, P].  Returns uint8 [G, P, 16].

    The per-row form (`ghash`) gathers a 16 KiB matrix PER ROW — for an
    SFU fan-out of P packets x G receivers that is P x G x 16 KiB of HBM
    traffic for key material alone, and it capped the GCM launch size.
    Here each leg's matrix is read once and applied to all its rows as
    one [128,128] x [128, P] MXU matmul per Horner step.
    """
    g, p, _ = data.shape
    y = jnp.zeros((g, p, 128), dtype=jnp.int8)

    def body(i, y):
        blk = jax.lax.dynamic_slice_in_dim(data, i * 16, 16, axis=2)
        x = _bytes_to_bits(blk.reshape(g * p, 16)).reshape(g, p, 128)
        t = jnp.bitwise_xor(y, x)
        prod = jnp.einsum("gij,gpj->gpi", matrices, t,
                          preferred_element_type=jnp.int32)
        y2 = (prod & 1).astype(jnp.int8)
        active = (i < nblocks)[..., None]
        return jnp.where(active, y2, y)

    y = jax.lax.fori_loop(0, nblk_max, body, y)
    return _bits_to_bytes(y.reshape(g * p, 128)).reshape(g, p, 16)


# ------------------------------------------------- packed (VPU) variant

def _pack_bits(bits):
    """0/1 int [..., 128] -> uint32 words [..., 4]; bit j lands at bit
    (31 - j%32) of word j//32, matching `_bytes_to_words` below so the
    AND/popcount parity below is order-consistent."""
    w = bits.astype(jnp.uint32).reshape(*bits.shape[:-1], 4, 32)
    shifts = jnp.arange(31, -1, -1, dtype=jnp.uint32)
    return jnp.sum(w << shifts, axis=-1, dtype=jnp.uint32)


def _bytes_to_words(blk):
    """uint8 [..., 16] -> uint32 [..., 4] big-endian words (MSB of byte
    4k at bit 31 of word k — the same 128-bit order `_bytes_to_bits`
    flattens to)."""
    b = blk.astype(jnp.uint32).reshape(*blk.shape[:-1], 4, 4)
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint32)


def _words_to_bytes(wds):
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    b = (wds[..., :, None] >> shifts) & 0xFF
    return b.reshape(*wds.shape[:-1], 16).astype(jnp.uint8)


def ghash_grouped_packed(matrices, data, nblocks, nblk_max: int):
    """`ghash_grouped` with the GF(2) matvec as packed-word AND +
    popcount parity instead of an int8 matmul.

    Same signature, bit-identical digests.  The einsum form burns one
    MXU MAC per matrix BIT — ideal where the MXU is otherwise idle,
    32x pure waste on backends whose vector unit has native
    population_count (XLA:CPU).  Here each Horner step ANDs the 128
    packed matrix rows [G, 128, 4]x[G, P, 4] and reduces with
    popcount, so the work per step is 128 uint32 lanes instead of
    128x128 int8 MACs.  Neither form is hardcoded anywhere: both are
    registered as providers on the GCM ops and the kernel registry's
    benchmark-and-pick keeps whichever measures faster per backend.
    """
    g, p, _ = data.shape
    mp = _pack_bits(matrices)                       # [G, 128, 4]
    y = jnp.zeros((g, p, 4), dtype=jnp.uint32)

    def body(i, y):
        blk = jax.lax.dynamic_slice_in_dim(data, i * 16, 16, axis=2)
        t = jnp.bitwise_xor(y, _bytes_to_words(blk))
        hits = jax.lax.population_count(
            mp[:, None, :, :] & t[:, :, None, :])   # [G, P, 128, 4]
        bits = jnp.sum(hits, axis=-1, dtype=jnp.uint32) & 1
        y2 = _pack_bits(bits)
        active = (i < nblocks)[..., None]
        return jnp.where(active, y2, y)

    y = jax.lax.fori_loop(0, nblk_max, body, y)
    return _words_to_bytes(y)
