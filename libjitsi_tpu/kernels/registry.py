"""Kernel provider registry: benchmark the candidates, keep the fastest.

Rebuilds the reference's provider-selection pattern
(`org.jitsi.impl.neomedia.transform.srtp.crypto.Aes` micro-benchmarks the
SunJCE / BouncyCastle / OpenSSL-JNI AES providers at startup and installs
the winner) for TPU kernel backends: each op registers one or more
providers ("xla" fused jnp, "pallas" VMEM kernel, ...), and the first hot
call times each on the real shapes and pins the winner for that shape
signature.

The choice is per (op, shape-signature) because the winner genuinely
flips with shape (XLA's fusion wins small fused elementwise programs;
Pallas wins when staying resident in VMEM avoids HBM round trips).
`force(op, provider)` — or the config key `kernels.provider.<op>` once
`libjitsi_tpu.init()` has run — overrides the measurement for tests and
deployments that want determinism.

Benchmarking compiles and times every provider, so it must stay off the
media path: latency-sensitive callers (the mixer tick) call `warmup()`
with their real shapes at setup time, exactly when the reference runs
its startup crypto benchmark.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax


class _Op:
    def __init__(self, name: str):
        self.name = name
        self.providers: Dict[str, Callable] = {}
        self.forced: Optional[str] = None
        self.choice: Dict[Tuple, str] = {}      # shape signature -> provider
        self.timings: Dict[Tuple, Dict[str, float]] = {}
        self.errors: Dict[Tuple, Dict[str, str]] = {}


_OPS: Dict[str, _Op] = {}
_BENCH_ITERS = 5


def register(op: str, provider: str, fn: Callable) -> None:
    _OPS.setdefault(op, _Op(op)).providers[provider] = fn


def force(op: str, provider: Optional[str]) -> None:
    """Pin a provider (None returns to measured selection)."""
    o = _OPS[op]
    if provider is not None and provider not in o.providers:
        raise KeyError(f"{op}: unknown provider {provider!r} "
                       f"(have {sorted(o.providers)})")
    o.forced = provider
    o.choice.clear()


def providers(op: str) -> List[str]:
    return sorted(_OPS[op].providers)


def report() -> Dict[str, Dict[str, Any]]:
    """Selection state for observability/debugging."""
    return {
        name: {
            "providers": sorted(o.providers),
            "forced": o.forced,
            "choices": {str(k): v for k, v in o.choice.items()},
            "timings_ms": {
                str(k): {p: round(t * 1e3, 4) for p, t in d.items()}
                for k, d in o.timings.items()},
            "errors": {str(k): dict(d) for k, d in o.errors.items()},
        }
        for name, o in _OPS.items()
    }


def _signature(args) -> Tuple:
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        sig.append((tuple(shape), str(dtype)) if shape is not None else a)
    return tuple(sig)


def _force(out) -> None:
    """Fetch-verified completion: on the axon tunnel
    `jax.block_until_ready` returns before fresh launches execute
    (round-5 finding, BASELINE.md), so provider timing must fetch
    bytes.  One leaf suffices — competing providers return identical
    shapes, so the (equal) transfer cost cancels in the comparison;
    mesh `_LazyArray` leaves materialize through the same call."""
    import numpy as _np

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "__array__"):
            _np.asarray(leaf)
            return
    jax.block_until_ready(out)


def _time_once(fn: Callable, args) -> Tuple[float, Any]:
    out = fn(*args)
    _force(out)                         # compile + warm
    t0 = time.perf_counter()
    for _ in range(_BENCH_ITERS):
        out = fn(*args)
    # one fetch at the end: the device queue executes in order, so the
    # last result's bytes prove all iterations completed — 5 executions
    # amortize the single forced transfer
    _force(out)
    return (time.perf_counter() - t0) / _BENCH_ITERS, out


def _forced_provider(o: _Op) -> Optional[str]:
    if o.forced is not None:
        return o.forced
    # config override (reference: named tunables via ConfigurationService)
    try:
        import libjitsi_tpu
        if libjitsi_tpu._started:
            prov = libjitsi_tpu.configuration_service().get_string(
                f"kernels.provider.{o.name}")
            if prov in o.providers:
                return prov
    except Exception:
        pass
    return None


def _select(o: _Op, sig: Tuple, args) -> Tuple[str, Any]:
    """Benchmark every provider on these args; pin and return the winner
    (and its result).  Failures are recorded, not silently swallowed —
    report() exposes why a provider was excluded."""
    timings: Dict[str, float] = {}
    results: Dict[str, Any] = {}
    for name, fn in o.providers.items():
        try:
            timings[name], results[name] = _time_once(fn, args)
        except Exception as e:          # provider can't handle this shape
            o.errors.setdefault(sig, {})[name] = repr(e)
    if not timings:
        raise RuntimeError(
            f"{o.name}: no provider succeeded for {sig}: "
            f"{o.errors.get(sig)}")
    chosen = min(timings, key=timings.get)
    o.choice[sig] = chosen
    o.timings[sig] = timings
    return chosen, results[chosen]


def warmup(op: str, *args) -> str:
    """Compile + benchmark all providers for these argument shapes, off
    the hot path (the reference benches its crypto providers at startup;
    latency-sensitive callers do this at setup time).  Returns the
    pinned provider name."""
    o = _OPS[op]
    forced = _forced_provider(o)
    if forced is not None:
        _force(o.providers[forced](*args))
        return forced
    sig = _signature(args)
    chosen = o.choice.get(sig)
    if chosen is None:
        chosen, _ = _select(o, sig, args)
    return chosen


def call(op: str, *args):
    """Dispatch to the selected provider, measuring on first sight of a
    shape signature (use `warmup()` beforehand to keep the measurement
    off latency-sensitive paths)."""
    o = _OPS[op]
    forced = _forced_provider(o)
    if forced is not None:
        return o.providers[forced](*args)
    if len(o.providers) == 1:
        return next(iter(o.providers.values()))(*args)
    sig = _signature(args)
    chosen = o.choice.get(sig)
    if chosen is None:
        _, result = _select(o, sig, args)
        return result
    return o.providers[chosen](*args)


# ------------------------------------------------- measured AES core
#
# The per-shape provider race above picks between whole-kernel
# implementations; the AES *core* (table / bitsliced variants inside
# kernels/aes.py) is chosen once per backend instead, because the core
# is read at trace time and switching it invalidates every compiled
# crypto kernel.  The measurement is the chained above-floor protocol
# from scripts/bench_aes_cores.py (k data-dependent encrypts inside one
# jitted program, k doubled until the net span clears the scalar-fetch
# floor's jitter — single-launch timings on the tunnel are junk, see
# BASELINE.md round 5).  Results are cached to a `_meta`-stamped
# AES_CORES.json at the repo root so startup reads a record instead of
# re-paying the ~minutes-long sweep; set LIBJITSI_TPU_AES_MEASURE to a
# per-core second budget to (re)measure the current backend and update
# the record.

AES_FLOOR_MULT = 10.0       # net span must exceed this x floor jitter
AES_SAMPLES = 5

_AES_CORE_CACHE: Dict[str, Optional[str]] = {}


def aes_record_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "AES_CORES.json")


def aes_floor_stats() -> Tuple[float, float]:
    """Median + spread (max-min) of the 4-byte verification fetch on a
    trivial program — the spread is the jitter bar every measurement
    must clear."""
    import jax.numpy as jnp
    import numpy as np

    g = jax.jit(lambda x: jnp.sum(x))
    x = jnp.arange(8, dtype=jnp.uint32)
    np.asarray(g(x))                        # compile + prime
    samples = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(g(x))
        samples.append(time.perf_counter() - t0)
    arr = np.asarray(samples)
    return float(np.median(arr)), float(arr.max() - arr.min())


def aes_chained(fn: Callable, rks, k: int) -> Callable:
    """jit( blocks -> checksum(fn applied k times, chained) ).

    The loop-carried value is the block batch itself: round i's output
    is round i+1's input, so dead-code elimination cannot drop work and
    the program's span scales with k."""
    import jax.numpy as jnp
    from jax import lax

    def body(_i, blk):
        return fn(rks, blk)

    def prog(blk):
        out = lax.fori_loop(0, k, body, blk)
        return jnp.sum(out.astype(jnp.uint32))

    return jax.jit(prog)


def measure_aes_core(fn: Callable, rks, blocks, floor: float,
                     jitter: float, deadline: float) -> Dict[str, Any]:
    """Blocks/s for one core, or a refusal record.  Doubles the chain
    length until the net span clears the jitter bar; a core that cannot
    reach the bar inside the budget reports "below_floor"/"skipped:
    budget", never a number."""
    import numpy as np

    b = blocks.shape[0]
    k = 4
    while True:
        if time.monotonic() > deadline:
            return {"status": "skipped: budget", "chain_k": k}
        try:
            g = aes_chained(fn, rks, k)
            np.asarray(g(blocks))           # compile + prime
            spans = []
            for _ in range(AES_SAMPLES):
                t0 = time.perf_counter()
                np.asarray(g(blocks))
                spans.append(time.perf_counter() - t0)
                if time.monotonic() > deadline:
                    break
        except Exception as e:              # lowering refusal, recorded
            return {"status": f"error: {type(e).__name__}"}
        net = float(np.median(spans)) - floor
        if net >= AES_FLOOR_MULT * jitter:
            return {
                "status": "ok",
                "blocks_per_sec": round(b * k / net, 1),
                "chain_k": k,
                "net_span_ms": round(net * 1e3, 3),
                "floor_jitter_ms": round(jitter * 1e3, 3),
            }
        if k >= 1 << 16:
            # even 65k chained rounds sit inside the floor jitter:
            # the honest answer is a bound, not a rate
            return {"status": "below_floor", "chain_k": k,
                    "net_span_ms": round(net * 1e3, 3)}
        k *= 2


def measure_aes_cores(batch: int = 4096,
                      budget: float = 60.0) -> Dict[str, Any]:
    """Run the chained sweep over every AES core on the current backend
    and return one backend record (the value stored under
    `backends.<name>` in AES_CORES.json)."""
    import jax.numpy as jnp
    import numpy as np

    from libjitsi_tpu.kernels.aes import (aes_encrypt_table,
                                          expand_keys_batch)
    from libjitsi_tpu.kernels.aes_bitsliced import (
        aes_encrypt_bitsliced, aes_encrypt_bitsliced32,
        aes_encrypt_bitsliced_tower, aes_encrypt_pallas_bitsliced)

    rng = np.random.default_rng(21)
    rks = jnp.asarray(expand_keys_batch(
        rng.integers(0, 256, (batch, 16), dtype=np.uint8)))
    blocks = jnp.asarray(
        rng.integers(0, 256, (batch, 16), dtype=np.uint8))

    floor, jitter = aes_floor_stats()
    rec = {
        "batch": batch,
        "fetch_floor_ms": round(floor * 1e3, 3),
        "floor_jitter_ms": round(jitter * 1e3, 3),
        "method": ("k chained (data-dependent) encrypts per program; "
                   f"k doubled until net span >= {AES_FLOOR_MULT}x "
                   "floor jitter"),
        "cores": {},
    }
    for name, fn in (("xla_table", aes_encrypt_table),
                     ("xla_bitsliced", aes_encrypt_bitsliced),
                     ("xla_bitsliced_tower", aes_encrypt_bitsliced_tower),
                     ("xla_bitsliced32", aes_encrypt_bitsliced32),
                     ("pallas_bitsliced", aes_encrypt_pallas_bitsliced)):
        deadline = time.monotonic() + budget
        rec["cores"][name] = measure_aes_core(
            fn, rks, blocks, floor, jitter, deadline)
    return rec


def write_aes_record(batch: int = 4096, budget: float = 60.0,
                     path: Optional[str] = None) -> Dict[str, Any]:
    """Measure the current backend and merge it into AES_CORES.json
    (other backends' entries are preserved; `_meta` is re-stamped)."""
    import datetime
    import subprocess

    path = path or aes_record_path()
    doc: Dict[str, Any] = {}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except Exception:
            doc = {}
    backend = jax.default_backend()
    doc.setdefault("backends", {})[backend] = measure_aes_cores(
        batch=batch, budget=budget)
    try:
        git = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(path)).stdout.strip() or "unknown"
    except Exception:
        git = "unknown"
    doc["_meta"] = {
        "written": datetime.datetime.now().isoformat(timespec="seconds"),
        "git": git,
        "note": ("measured AES-core record consumed by "
                 "kernels/aes.py:get_core(); regenerate with "
                 "scripts/bench_aes_cores.py --write-record or "
                 "LIBJITSI_TPU_AES_MEASURE=<budget-seconds>"),
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    _AES_CORE_CACHE.pop(backend, None)
    return doc["backends"][backend]


def measured_aes_core(backend: Optional[str] = None) -> Optional[str]:
    """The fastest *measured* AES core for `backend` (default: the
    current one), or None when no credible number exists — the caller
    (kernels/aes.py:get_core) falls back to its heuristic default then.

    Only `status == "ok"` entries count (below_floor / budget-skipped /
    errored cores are refusals, not slow results), and only the xla_*
    core names map onto aes.py's `_CORES` (the pallas entry is a
    whole-kernel provider raced by the registry above, not a core
    get_core can select)."""
    backend = backend or jax.default_backend()
    if backend in _AES_CORE_CACHE:
        return _AES_CORE_CACHE[backend]

    path = aes_record_path()
    budget = os.environ.get("LIBJITSI_TPU_AES_MEASURE")
    have = False
    if os.path.exists(path):
        try:
            with open(path) as fh:
                have = backend in json.load(fh).get("backends", {})
        except Exception:
            have = False
    if budget and not have and backend == jax.default_backend():
        try:
            write_aes_record(budget=max(float(budget), 1.0))
        except Exception:
            pass

    choice: Optional[str] = None
    try:
        with open(path) as fh:
            cores = (json.load(fh).get("backends", {})
                     .get(backend, {}).get("cores", {}))
        from libjitsi_tpu.kernels.aes import _CORES
        best = -1.0
        for name, rec in cores.items():
            if not name.startswith("xla_") or rec.get("status") != "ok":
                continue
            core = name[len("xla_"):]
            if core in _CORES and rec["blocks_per_sec"] > best:
                best, choice = rec["blocks_per_sec"], core
    except Exception:
        choice = None
    _AES_CORE_CACHE[backend] = choice
    return choice
