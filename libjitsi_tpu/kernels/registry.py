"""Kernel provider registry: benchmark the candidates, keep the fastest.

Rebuilds the reference's provider-selection pattern
(`org.jitsi.impl.neomedia.transform.srtp.crypto.Aes` micro-benchmarks the
SunJCE / BouncyCastle / OpenSSL-JNI AES providers at startup and installs
the winner) for TPU kernel backends: each op registers one or more
providers ("xla" fused jnp, "pallas" VMEM kernel, ...), and the first hot
call times each on the real shapes and pins the winner for that shape
signature.

The choice is per (op, shape-signature) because the winner genuinely
flips with shape (XLA's fusion wins small fused elementwise programs;
Pallas wins when staying resident in VMEM avoids HBM round trips).
`force(op, provider)` — or the config key `kernels.provider.<op>` once
`libjitsi_tpu.init()` has run — overrides the measurement for tests and
deployments that want determinism.

Benchmarking compiles and times every provider, so it must stay off the
media path: latency-sensitive callers (the mixer tick) call `warmup()`
with their real shapes at setup time, exactly when the reference runs
its startup crypto benchmark.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax


class _Op:
    def __init__(self, name: str):
        self.name = name
        self.providers: Dict[str, Callable] = {}
        self.forced: Optional[str] = None
        self.choice: Dict[Tuple, str] = {}      # shape signature -> provider
        self.timings: Dict[Tuple, Dict[str, float]] = {}
        self.errors: Dict[Tuple, Dict[str, str]] = {}


_OPS: Dict[str, _Op] = {}
_BENCH_ITERS = 5


def register(op: str, provider: str, fn: Callable) -> None:
    _OPS.setdefault(op, _Op(op)).providers[provider] = fn


def force(op: str, provider: Optional[str]) -> None:
    """Pin a provider (None returns to measured selection)."""
    o = _OPS[op]
    if provider is not None and provider not in o.providers:
        raise KeyError(f"{op}: unknown provider {provider!r} "
                       f"(have {sorted(o.providers)})")
    o.forced = provider
    o.choice.clear()


def providers(op: str) -> List[str]:
    return sorted(_OPS[op].providers)


def report() -> Dict[str, Dict[str, Any]]:
    """Selection state for observability/debugging."""
    return {
        name: {
            "providers": sorted(o.providers),
            "forced": o.forced,
            "choices": {str(k): v for k, v in o.choice.items()},
            "timings_ms": {
                str(k): {p: round(t * 1e3, 4) for p, t in d.items()}
                for k, d in o.timings.items()},
            "errors": {str(k): dict(d) for k, d in o.errors.items()},
        }
        for name, o in _OPS.items()
    }


def _signature(args) -> Tuple:
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        sig.append((tuple(shape), str(dtype)) if shape is not None else a)
    return tuple(sig)


def _force(out) -> None:
    """Fetch-verified completion: on the axon tunnel
    `jax.block_until_ready` returns before fresh launches execute
    (round-5 finding, BASELINE.md), so provider timing must fetch
    bytes.  One leaf suffices — competing providers return identical
    shapes, so the (equal) transfer cost cancels in the comparison;
    mesh `_LazyArray` leaves materialize through the same call."""
    import numpy as _np

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "__array__"):
            _np.asarray(leaf)
            return
    jax.block_until_ready(out)


def _time_once(fn: Callable, args) -> Tuple[float, Any]:
    out = fn(*args)
    _force(out)                         # compile + warm
    t0 = time.perf_counter()
    for _ in range(_BENCH_ITERS):
        out = fn(*args)
    # one fetch at the end: the device queue executes in order, so the
    # last result's bytes prove all iterations completed — 5 executions
    # amortize the single forced transfer
    _force(out)
    return (time.perf_counter() - t0) / _BENCH_ITERS, out


def _forced_provider(o: _Op) -> Optional[str]:
    if o.forced is not None:
        return o.forced
    # config override (reference: named tunables via ConfigurationService)
    try:
        import libjitsi_tpu
        if libjitsi_tpu._started:
            prov = libjitsi_tpu.configuration_service().get_string(
                f"kernels.provider.{o.name}")
            if prov in o.providers:
                return prov
    except Exception:
        pass
    return None


def _select(o: _Op, sig: Tuple, args) -> Tuple[str, Any]:
    """Benchmark every provider on these args; pin and return the winner
    (and its result).  Failures are recorded, not silently swallowed —
    report() exposes why a provider was excluded."""
    timings: Dict[str, float] = {}
    results: Dict[str, Any] = {}
    for name, fn in o.providers.items():
        try:
            timings[name], results[name] = _time_once(fn, args)
        except Exception as e:          # provider can't handle this shape
            o.errors.setdefault(sig, {})[name] = repr(e)
    if not timings:
        raise RuntimeError(
            f"{o.name}: no provider succeeded for {sig}: "
            f"{o.errors.get(sig)}")
    chosen = min(timings, key=timings.get)
    o.choice[sig] = chosen
    o.timings[sig] = timings
    return chosen, results[chosen]


def warmup(op: str, *args) -> str:
    """Compile + benchmark all providers for these argument shapes, off
    the hot path (the reference benches its crypto providers at startup;
    latency-sensitive callers do this at setup time).  Returns the
    pinned provider name."""
    o = _OPS[op]
    forced = _forced_provider(o)
    if forced is not None:
        _force(o.providers[forced](*args))
        return forced
    sig = _signature(args)
    chosen = o.choice.get(sig)
    if chosen is None:
        chosen, _ = _select(o, sig, args)
    return chosen


def call(op: str, *args):
    """Dispatch to the selected provider, measuring on first sight of a
    shape signature (use `warmup()` beforehand to keep the measurement
    off latency-sensitive paths)."""
    o = _OPS[op]
    forced = _forced_provider(o)
    if forced is not None:
        return o.providers[forced](*args)
    if len(o.providers) == 1:
        return next(iter(o.providers.values()))(*args)
    sig = _signature(args)
    chosen = o.choice.get(sig)
    if chosen is None:
        _, result = _select(o, sig, args)
        return result
    return o.providers[chosen](*args)
