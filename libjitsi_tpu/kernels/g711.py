"""G.711 a-law / µ-law as batched LUT kernels.

Rebuilds `org.jitsi.impl.neomedia.codec.audio.{alaw,ulaw}.*` as the
trivial-but-illustrative TPU codec: encode/decode are 256-entry lookups
(decode) and magnitude/segment arithmetic (encode), fully vectorized —
[B, frame] int16 <-> uint8 in one fused program.  Tables are generated
from the G.711 spec at import, not transcribed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _ulaw_decode_table() -> np.ndarray:
    out = np.zeros(256, dtype=np.int16)
    for u in range(256):
        v = ~u & 0xFF
        sign = v & 0x80
        exp = (v >> 4) & 0x07
        mant = v & 0x0F
        x = ((mant << 3) + 0x84) << exp
        x -= 0x84
        out[u] = -x if sign else x
    return out


def _alaw_decode_table() -> np.ndarray:
    out = np.zeros(256, dtype=np.int16)
    for a in range(256):
        v = a ^ 0x55
        sign = v & 0x80
        exp = (v >> 4) & 0x07
        mant = v & 0x0F
        if exp == 0:
            x = (mant << 4) + 8
        else:
            x = ((mant << 4) + 0x108) << (exp - 1)
        # A-law sign bit (after the 0x55 toggle) set == positive
        out[a] = x if sign else -x
    return out


_ULAW_DEC = _ulaw_decode_table()
_ALAW_DEC = _alaw_decode_table()


@jax.jit
def ulaw_decode(data):
    """uint8 [...] -> int16 [...]."""
    return jnp.take(jnp.asarray(_ULAW_DEC), data.astype(jnp.int32), axis=0)


@jax.jit
def alaw_decode(data):
    return jnp.take(jnp.asarray(_ALAW_DEC), data.astype(jnp.int32), axis=0)


@jax.jit
def ulaw_encode(pcm):
    """int16 [...] -> uint8 [...] (G.711 µ-law, bias 0x84)."""
    x = pcm.astype(jnp.int32)
    sign = jnp.where(x < 0, 0x80, 0)
    mag = jnp.minimum(jnp.abs(x), 32635) + 0x84
    # exponent = position of the highest set bit above bit 7
    exp = jnp.clip(
        jnp.floor(jnp.log2(mag.astype(jnp.float32))).astype(jnp.int32) - 7,
        0, 7)
    mant = (mag >> (exp + 3)) & 0x0F
    return (~(sign | (exp << 4) | mant) & 0xFF).astype(jnp.uint8)


@jax.jit
def alaw_encode(pcm):
    """int16 [...] -> uint8 [...] (G.711 A-law, 0x55 toggle)."""
    x = pcm.astype(jnp.int32)
    sign = jnp.where(x >= 0, 0x80, 0)
    mag = jnp.minimum(jnp.abs(x), 32767) >> 3  # 13-bit magnitude
    exp = jnp.clip(
        jnp.floor(jnp.log2(jnp.maximum(mag, 1).astype(jnp.float32)))
        .astype(jnp.int32) - 4, 0, 7)
    mant = jnp.where(exp == 0, (mag >> 1) & 0x0F, (mag >> exp) & 0x0F)
    return ((sign | (exp << 4) | mant) ^ 0x55).astype(jnp.uint8)
