"""Batched SHA-1 / HMAC-SHA1 as pure-JAX vectorized kernels.

This is the auth half of the SRTP hot path: the reference computes
HMAC-SHA1-80/32 per packet in `org.jitsi.impl.neomedia.transform.srtp`
(`HMACSHA1` / OpenSSL JNI under `.srtp.crypto`).  On TPU the per-packet
loop inverts into one batched computation: `[B, L]` message bytes ->
`[B, 20]` digests, entirely uint32 VPU bitwise math with no data-dependent
control flow (variable message lengths are handled by masking), so XLA can
fuse and tile it.

Design notes
- The block loop is a `lax.fori_loop` over the *maximum* block count for the
  buffer width; rows with fewer blocks mask their state updates.  This keeps
  shapes static under jit at any batch size.
- The 80-round compression is unrolled at trace time (pure Python loop) —
  constant trip count, XLA sees straight-line code.
- HMAC precomputes the ipad/opad midstates per key (host side, tiny) so the
  device path is exactly two SHA-1 tails; per-packet keys are row-gathered
  midstates, which is how per-stream SRTP auth keys batch across streams.
- Messages up to 2^29-1 bytes (bit length fits in 32 bits) — plenty for MTU
  sized packets; asserted at trace time.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_H0 = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0],
               dtype=np.uint32)
_K = np.array([0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6], dtype=np.uint32)

BLOCK = 64  # bytes
DIGEST = 20  # bytes


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _compress_block(h, w16):
    """One SHA-1 compression: h [..., 5] uint32, w16 [..., 16] uint32."""
    w = [w16[..., t] for t in range(16)]
    for t in range(16, 80):
        w.append(_rotl(w[t - 3] ^ w[t - 8] ^ w[t - 14] ^ w[t - 16], 1))
    a, b, c, d, e = (h[..., i] for i in range(5))
    for t in range(80):
        if t < 20:
            f = (b & c) | (~b & d)
            k = _K[0]
        elif t < 40:
            f = b ^ c ^ d
            k = _K[1]
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
            k = _K[2]
        else:
            f = b ^ c ^ d
            k = _K[3]
        tmp = _rotl(a, 5) + f + e + jnp.uint32(k) + w[t]
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return jnp.stack(
        [h[..., 0] + a, h[..., 1] + b, h[..., 2] + c, h[..., 3] + d, h[..., 4] + e],
        axis=-1,
    )


def _pad_and_blockify(data, lengths, bit_offset):
    """Build padded message blocks: [B, nblk, 16] uint32 + per-row block counts.

    `bit_offset` is added to the encoded bit length (512 for HMAC tails whose
    key block was already compressed into the midstate).
    """
    bsz, width = data.shape
    max_total = ((width + 9 + BLOCK - 1) // BLOCK) * BLOCK
    nblk_max = max_total // BLOCK
    assert width < (1 << 29), "message too long for 32-bit bit-length encoding"

    lengths = lengths.astype(jnp.int32)
    nblocks = (lengths + 9 + BLOCK - 1) // BLOCK  # per-row used blocks
    total = nblocks * BLOCK

    idx = jnp.arange(max_total, dtype=jnp.int32)[None, :]
    ln = lengths[:, None]
    buf = jnp.zeros((bsz, max_total), dtype=jnp.uint8)
    buf = buf.at[:, :width].set(data)
    # zero everything at/after length, then place 0x80 terminator
    buf = jnp.where(idx < ln, buf, jnp.uint8(0))
    buf = jnp.where(idx == ln, jnp.uint8(0x80), buf)
    # 64-bit big-endian bit length in the last 8 bytes of the last used block;
    # high word is always 0 (width < 2^29).
    bitlen = (lengths * 8 + bit_offset).astype(jnp.uint32)[:, None]
    tpos = total[:, None] - 8 + jnp.arange(8, dtype=jnp.int32)[None, :]  # [B, 8]
    shift = (jnp.uint32(7) - jnp.arange(8, dtype=jnp.uint32)[None, :]) * 8
    lenbytes = jnp.where(
        shift >= 32, jnp.uint32(0), (bitlen >> jnp.minimum(shift, 31)) & 0xFF
    ).astype(jnp.uint8)
    buf = buf.at[jnp.arange(bsz)[:, None], tpos].set(lenbytes)

    words = buf.reshape(bsz, nblk_max, 16, 4).astype(jnp.uint32)
    w16 = (
        (words[..., 0] << 24) | (words[..., 1] << 16) | (words[..., 2] << 8)
        | words[..., 3]
    )
    return w16, nblocks, nblk_max


def _sha1_core(w16, nblocks, nblk_max, h0):
    """Run masked compression over blocks. h0: [B, 5] or [5]."""
    bsz = w16.shape[0]
    h = jnp.broadcast_to(h0, (bsz, 5)).astype(jnp.uint32)

    def body(i, h):
        hn = _compress_block(h, w16[:, i, :])
        active = (i < nblocks)[:, None]
        return jnp.where(active, hn, h)

    return jax.lax.fori_loop(0, nblk_max, body, h)


def _digest_bytes(h):
    """[B, 5] uint32 -> [B, 20] uint8 big-endian."""
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    return ((h[:, :, None] >> shifts[None, None, :]) & 0xFF).astype(jnp.uint8).reshape(
        h.shape[0], DIGEST
    )


@functools.partial(jax.jit, static_argnames=())
def sha1(data, lengths):
    """Batched SHA-1.  data: [B, L] uint8; lengths: [B] int. -> [B, 20] uint8."""
    w16, nblocks, nblk_max = _pad_and_blockify(
        jnp.asarray(data, dtype=jnp.uint8), jnp.asarray(lengths), 0
    )
    h = _sha1_core(w16, nblocks, nblk_max, jnp.asarray(_H0))
    return _digest_bytes(h)


# ---------------------------------------------------------------------------
# HMAC-SHA1
# ---------------------------------------------------------------------------

def hmac_precompute(key: bytes) -> np.ndarray:
    """Host-side: compress ipad/opad blocks once per key.

    Returns a [2, 5] uint32 midstate array (row 0 = inner, row 1 = outer).
    Per-stream keys stack into [S, 2, 5]; the device path gathers rows by
    stream id.  (Reference analog: per-`SRTPCryptoContext` derived auth key.)
    """
    if len(key) > BLOCK:
        import hashlib

        key = hashlib.sha1(key).digest()
    k = np.zeros(BLOCK, dtype=np.uint8)
    k[: len(key)] = np.frombuffer(key, dtype=np.uint8)
    states = []
    for pad in (0x36, 0x5C):
        blk = (k ^ pad).astype(np.uint32).reshape(16, 4)
        w16 = (blk[:, 0] << 24) | (blk[:, 1] << 16) | (blk[:, 2] << 8) | blk[:, 3]
        # pure-host compress: a device call here costs one accelerator
        # round trip PER KEY (x2 pads) — at 10k streams that is 20k RTTs
        # of setup (hashlib can't help: it never exposes midstates)
        states.append(_compress_block_np(_H0, w16))
    return np.stack(states).astype(np.uint32)


def hmac_precompute_batch(keys: np.ndarray) -> np.ndarray:
    """Vectorized `hmac_precompute`: [S, kl<=64] uint8 -> [S, 2, 5] uint32.

    The install plane's form (bulk conference joins, 10k-stream
    bootstrap): both pad blocks of every key compress in one vectorized
    pass instead of a per-key Python loop.
    """
    keys = np.atleast_2d(np.asarray(keys, dtype=np.uint8))
    s, kl = keys.shape
    if kl > BLOCK:
        raise ValueError("batched HMAC keys must be <= one block (64B)")
    k = np.zeros((s, BLOCK), dtype=np.uint8)
    k[:, :kl] = keys
    out = np.zeros((s, 2, 5), dtype=np.uint32)
    for row, pad in enumerate((0x36, 0x5C)):
        blk = (k ^ pad).astype(np.uint32).reshape(s, 16, 4)
        w16 = ((blk[..., 0] << 24) | (blk[..., 1] << 16)
               | (blk[..., 2] << 8) | blk[..., 3])
        out[:, row] = _compress_blocks_np(_H0, w16)
    return out


def _compress_blocks_np(h: np.ndarray, w16: np.ndarray) -> np.ndarray:
    """SHA-1 compression on host, vectorized over lanes (cold path only).

    h: [5] or [S, 5] uint32 initial state; w16: [S, 16] uint32 words.
    """
    mask = np.uint64(0xFFFFFFFF)
    w16 = np.atleast_2d(w16)
    s = w16.shape[0]
    h = np.broadcast_to(np.asarray(h, dtype=np.uint32), (s, 5))

    def rotl(x, n):
        return ((x << np.uint64(n)) | (x >> np.uint64(32 - n))) & mask

    w = [w16[:, t].astype(np.uint64) for t in range(16)]
    for t in range(16, 80):
        w.append(rotl(w[t - 3] ^ w[t - 8] ^ w[t - 14] ^ w[t - 16], 1))
    a, b, c, d, e = (h[:, i].astype(np.uint64) for i in range(5))
    K = (np.uint64(0x5A827999), np.uint64(0x6ED9EBA1),
         np.uint64(0x8F1BBCDC), np.uint64(0xCA62C1D6))
    for t in range(80):
        if t < 20:
            f = (b & c) | (~b & d & mask)
        elif t < 40 or t >= 60:
            f = b ^ c ^ d
        else:
            f = (b & c) | (b & d) | (c & d)
        tmp = (rotl(a, 5) + f + e + K[t // 20] + w[t]) & mask
        a, b, c, d, e = tmp, a, rotl(b, 30), c, d
    out = np.stack([a, b, c, d, e], axis=1)
    return ((out + h.astype(np.uint64)) & mask).astype(np.uint32)


def _compress_block_np(h: np.ndarray, w16: np.ndarray) -> np.ndarray:
    """One SHA-1 compression on host (scalar shim over the batch form)."""
    return _compress_blocks_np(h, np.asarray(w16)[None])[0]


@jax.jit
def hmac_sha1(midstates, data, lengths):
    """Batched HMAC-SHA1 with precomputed key midstates.

    midstates: [B, 2, 5] uint32 (per-row key, from `hmac_precompute`);
    data: [B, L] uint8; lengths: [B].  -> [B, 20] uint8 tags.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    midstates = jnp.asarray(midstates, dtype=jnp.uint32)
    # inner: continue from ipad midstate; bit length offset = 512 (key block)
    w16, nblocks, nblk_max = _pad_and_blockify(data, jnp.asarray(lengths), 512)
    inner = _digest_bytes(_sha1_core(w16, nblocks, nblk_max, midstates[:, 0, :]))
    # outer: 20-byte inner digest as message
    lens20 = jnp.full((data.shape[0],), DIGEST, dtype=jnp.int32)
    w16o, nbo, nbmo = _pad_and_blockify(inner, lens20, 512)
    return _digest_bytes(_sha1_core(w16o, nbo, nbmo, midstates[:, 1, :]))
