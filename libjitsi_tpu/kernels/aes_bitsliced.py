"""Bitsliced, gather-free AES — the SURVEY §7 "hard parts" candidate.

The production AES path (`kernels.aes.aes_encrypt`) uses a 256-entry
S-box `jnp.take`, which XLA lowers well but Mosaic (Pallas TPU) refuses
to lower at all.  This module builds AES-128/256 encryption as a pure
Boolean circuit — XOR/AND/slice/concat only, no gathers — so the same
body runs as an XLA program *and* as a Pallas kernel, and the provider
registry (`kernels.registry`, the reference's `.srtp.crypto.Aes`
benchmark-and-pick pattern) can measure all three and keep the winner.

Circuit construction is derived, not transcribed: the S-box is computed
as ``affine(x^254)`` over GF(2^8), with the squaring/power linear maps
and the polynomial-reduction matrix generated from field arithmetic at
import time and the complete 256-entry truth table asserted against an
independently generated S-box.  Inversion uses the addition chain
x -> x^2 -> x^3 -> x^12 -> x^15 -> x^240 -> x^252 -> x^254
(4 variable GF multiplications; squarings are linear).

State layout: 8 bit-planes, each ``[B, 4, 4]`` (byte i = row + 4*col),
LSB-first bit order.  ShiftRows is slice+concat per row; MixColumns is
xtime/XOR over row variables — nothing here indexes by data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ------------------------------------------------------------ host derivation

_POLY = 0x11B


def _gf_mul(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= _POLY
        b >>= 1
    return r


def _gf_pow(a: int, n: int) -> int:
    r = 1
    while n:
        if n & 1:
            r = _gf_mul(r, a)
        a = _gf_mul(a, a)
        n >>= 1
    return r


def _linear_matrix(fn) -> np.ndarray:
    """8x8 GF(2) matrix of a linear byte map, via basis probing
    (bit i = (byte >> i) & 1)."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        y = fn(1 << j)
        for i in range(8):
            m[i, j] = (y >> i) & 1
    return m


_M_SQ = _linear_matrix(lambda x: _gf_pow(x, 2))
_M_P4 = _linear_matrix(lambda x: _gf_pow(x, 4))
_M_P16 = _linear_matrix(lambda x: _gf_pow(x, 16))
# AES S-box affine layer: s = A*x + 0x63 (applied AFTER inversion)
_M_AFF = _linear_matrix(
    lambda x: (x ^ ((x << 1) | (x >> 7)) ^ ((x << 2) | (x >> 6))
               ^ ((x << 3) | (x >> 5)) ^ ((x << 4) | (x >> 4))) & 0xFF)
_AFF_C = 0x63
# x^k mod poly for the 15 product coefficients of an 8x8-bit multiply
_REDC = [_gf_pow(2, k) for k in range(15)]


# ----------------------------------------------------------- circuit builders

def _linear(bits, mat: np.ndarray, const: int = 0, ones=1):
    """`ones` is the all-true word for the plane element type: 1 for
    one-bit-per-uint8 planes, 0xFFFFFFFF for the packed-word provider
    (every bit of an int32 element is a different block)."""
    out = []
    for i in range(8):
        acc = None
        for j in range(8):
            if mat[i, j]:
                acc = bits[j] if acc is None else acc ^ bits[j]
        if acc is None:
            acc = bits[0] ^ bits[0]
        if (const >> i) & 1:
            acc = acc ^ ones
        out.append(acc)
    return out


def _gf_mult_bits(a, b):
    """Bitsliced GF(2^8) multiply of two byte variables."""
    c = []
    for k in range(15):
        acc = None
        for i in range(max(0, k - 7), min(8, k + 1)):
            t = a[i] & b[k - i]
            acc = t if acc is None else acc ^ t
        c.append(acc)
    out = []
    for i in range(8):
        acc = None
        for k in range(15):
            if (_REDC[k] >> i) & 1:
                acc = c[k] if acc is None else acc ^ c[k]
        out.append(acc)
    return out


def _sbox_bits(x, ones=1):
    """S(x) = affine(x^254): 4 GF multiplies + linear maps, no tables."""
    a2 = _linear(x, _M_SQ)
    a3 = _gf_mult_bits(a2, x)
    a12 = _linear(a3, _M_P4)
    a15 = _gf_mult_bits(a12, a3)
    a240 = _linear(a15, _M_P16)
    a252 = _gf_mult_bits(a240, a12)
    a254 = _gf_mult_bits(a252, a2)
    return _linear(a254, _M_AFF, _AFF_C, ones)


# ------------------------------------------- tower-field S-box circuit
#
# Round-5: the addition-chain inversion above costs 4 GF(2^8)
# bitsliced multiplies (~860 gate-ops per byte).  The classic
# composite-field decomposition GF(2^8) ~ GF((2^4)^2) does the same
# inversion with 5 GF(2^4) multiplies (~250 gate-ops): map through a
# basis change, invert (a y + b) as (a D^-1) y + ((a+b) D^-1) with
# D = lambda a^2 + ab + b^2, and map back into the affine.  The tower
# parameters and both basis-change matrices are DERIVED at import (a
# search for an irreducible y^2+y+lambda and a tower root of the AES
# polynomial), and the whole circuit is asserted against the 256-entry
# S-box table below — same no-transcription doctrine as the rest of
# this module.

def _derive_tower():
    g4mul = [[_gf_mul_16(a, b) for b in range(16)] for a in range(16)]

    def t_mul(u, v, lam):
        a, b = u
        c, d = v
        ac = g4mul[a][c]
        return (g4mul[a][d] ^ g4mul[b][c] ^ ac,
                g4mul[b][d] ^ g4mul[ac][lam])

    def t_pow(u, n, lam):
        r = (0, 1)
        for _ in range(n):
            r = t_mul(r, u, lam)
        return r

    def is_root(g, lam):
        acc = t_pow(g, 8, lam)
        for n in (4, 3, 1):
            p = t_pow(g, n, lam)
            acc = (acc[0] ^ p[0], acc[1] ^ p[1])
        return (acc[0], acc[1] ^ 1) == (0, 0)

    for lam in range(1, 16):
        if any(g4mul[t][t] ^ t ^ lam == 0 for t in range(16)):
            continue           # y^2+y+lam reducible over GF(16)
        for hi in range(16):
            for lo in range(16):
                if (hi, lo) != (0, 0) and is_root((hi, lo), lam):
                    gamma = (hi, lo)
                    m = np.zeros((8, 8), dtype=np.uint8)
                    for i in range(8):
                        a, b = t_pow(gamma, i, lam)
                        c = (a << 4) | b
                        for j in range(8):
                            m[j, i] = (c >> j) & 1
                    return lam, m, _gf2_inv_mat(m)
    raise AssertionError("no tower isomorphism found")


def _gf_mul_16(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x10:
            a ^= 0b10011        # GF(2^4) poly x^4 + x + 1
        b >>= 1
    return r


def _gf2_inv_mat(mx: np.ndarray) -> np.ndarray:
    n = mx.shape[0]
    a = np.concatenate([mx.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = next(r for r in range(col, n) if a[r, col])
        a[[col, piv]] = a[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
    return a[:, n:]


_TOWER_LAM, _M_TOWER, _M_TOWER_INV = _derive_tower()


def _mul4_bits(a, b):
    """Bitsliced GF(2^4) multiply (poly x^4+x+1): 16 ANDs + XOR tree."""
    c = []
    for k in range(7):
        acc = None
        for i in range(max(0, k - 3), min(4, k + 1)):
            t = a[i] & b[k - i]
            acc = t if acc is None else acc ^ t
        c.append(acc)
    return [c[0] ^ c[4], c[1] ^ c[4] ^ c[5], c[2] ^ c[5] ^ c[6],
            c[3] ^ c[6]]


def _sq4_bits(a):
    """x^2 over GF(2^4) (linear)."""
    return [a[0] ^ a[2], a[2], a[1] ^ a[3], a[3]]


def _mul_lam_bits(a):
    """Multiply by lambda over GF(2^4) (linear; derived per _TOWER_LAM
    at import via the generic matrix probe)."""
    return _linear4(a, _M_LAM)


def _linear4(bits, mat):
    out = []
    for i in range(4):
        acc = None
        for j in range(4):
            if mat[i, j]:
                acc = bits[j] if acc is None else acc ^ bits[j]
        out.append(acc if acc is not None else bits[0] ^ bits[0])
    return out


def _lam_matrix() -> np.ndarray:
    m = np.zeros((4, 4), dtype=np.uint8)
    for j in range(4):
        v = _gf_mul_16(1 << j, _TOWER_LAM)
        for i in range(4):
            m[i, j] = (v >> i) & 1
    return m


_M_LAM = _lam_matrix()


def _inv4_bits(a):
    """GF(2^4) inverse = x^14 = x^8 * x^4 * x^2 (0 -> 0)."""
    t2 = _sq4_bits(a)
    t4 = _sq4_bits(t2)
    t8 = _sq4_bits(t4)
    return _mul4_bits(_mul4_bits(t8, t4), t2)


def _sbox_bits_tower(x, ones=1):
    """S(x) = affine(x^-1) with the inversion in GF((2^4)^2)."""
    x4 = lambda u, v: [p ^ q for p, q in zip(u, v)]  # noqa: E731
    t = _linear(x, _M_TOWER)
    b, a = t[:4], t[4:]                     # byte = (a << 4) | b
    delta = x4(x4(_mul_lam_bits(_sq4_bits(a)), _mul4_bits(a, b)),
               _sq4_bits(b))
    di = _inv4_bits(delta)
    hi = _mul4_bits(a, di)
    lo = _mul4_bits(x4(a, b), di)
    inv = _linear(lo + hi, _M_TOWER_INV)
    return _linear(inv, _M_AFF, _AFF_C, ones)


def _self_check() -> None:
    """Assert the derived circuits reproduce the full S-box table."""
    from libjitsi_tpu.kernels.aes import _SBOX

    xs = np.arange(256, dtype=np.uint8)
    bits = [((xs >> p) & 1).astype(np.uint8) for p in range(8)]
    for impl in (_sbox_bits, _sbox_bits_tower):
        out = impl(bits)
        got = np.zeros(256, dtype=np.uint16)
        for p in range(8):
            got |= out[p].astype(np.uint16) << p
        if not np.array_equal(got.astype(np.uint8), _SBOX):
            raise AssertionError(
                f"bitsliced S-box circuit {impl.__name__} != table")


_self_check()


def _vxor(a, b):
    return [x ^ y for x, y in zip(a, b)]


def _xtime_bits(v):
    """GF doubling: out = v << 1 reduced by 0x11B (LSB-first planes)."""
    return [v[7], v[0] ^ v[7], v[1], v[2] ^ v[7], v[3] ^ v[7],
            v[4], v[5], v[6]]


def _shift_rows_bits(bits, cat):
    out = []
    for p in bits:
        rows = []
        for r in range(4):
            row = p[:, r:r + 1, :]
            rows.append(cat([row[..., r:], row[..., :r]], -1)
                        if r else row)
        out.append(cat(rows, 1))
    return out


def _mix_columns_bits(bits, stack):
    rows = [[p[:, r, :] for p in bits] for r in range(4)]
    new_rows = []
    for r in range(4):
        a, b = rows[r], rows[(r + 1) % 4]
        c, d = rows[(r + 2) % 4], rows[(r + 3) % 4]
        new_rows.append(_vxor(_vxor(_xtime_bits(a), _vxor(_xtime_bits(b),
                                                          b)),
                              _vxor(c, d)))
    return [stack([new_rows[r][p] for r in range(4)], 1)
            for p in range(8)]


def _rounds(bits, rk_bits, nr: int, cat, stack, ones=1,
            sbox=None):
    """The shared round schedule over bit-plane state (`sbox` picks
    the inversion circuit: addition-chain `_sbox_bits` or the
    composite-field `_sbox_bits_tower`)."""
    sbox = sbox or _sbox_bits
    bits = _vxor(bits, rk_bits[0])
    for r in range(1, nr):
        bits = sbox(bits, ones)
        bits = _shift_rows_bits(bits, cat)
        bits = _mix_columns_bits(bits, stack)
        bits = _vxor(bits, rk_bits[r])
    bits = sbox(bits, ones)
    bits = _shift_rows_bits(bits, cat)
    return _vxor(bits, rk_bits[nr])


# --------------------------------------------------------------- XLA provider

def _to_planes(blocks):
    """[B, 16] uint8 -> 8 planes [B, 4, 4] (byte i = row + 4*col)."""
    x = blocks.reshape(-1, 4, 4).transpose(0, 2, 1)   # [B, r, c]
    return [((x >> p) & 1).astype(jnp.uint8) for p in range(8)]


def _from_planes(bits):
    acc = bits[0]
    for p in range(1, 8):
        acc = acc | (bits[p] << p)
    return acc.transpose(0, 2, 1).reshape(-1, 16).astype(jnp.uint8)


def _make_plane_provider(sbox):
    """Build the (jitted flat fn, leading-dim-agnostic wrapper) pair
    for one S-box circuit — the plane setup and the `_nd` reshape
    contract ([..., R, 16] broadcast keys from the CTR/GCM call sites)
    exist ONCE, shared by the addition-chain and tower providers."""

    @jax.jit
    def flat(round_keys, blocks):
        rk = jnp.asarray(round_keys, dtype=jnp.uint8)
        nr = rk.shape[-2] - 1
        bits = _to_planes(jnp.asarray(blocks, dtype=jnp.uint8))
        rk_bits = [_to_planes(rk[:, r, :]) for r in range(nr + 1)]
        out = _rounds(bits, rk_bits, nr, jnp.concatenate, jnp.stack,
                      sbox=sbox)
        return _from_planes(out)

    def nd(round_keys, blocks):
        rk = jnp.asarray(round_keys, dtype=jnp.uint8)
        blk = jnp.asarray(blocks, dtype=jnp.uint8)
        lead = blk.shape[:-1]
        out = flat(rk.reshape((-1,) + rk.shape[-2:]),
                   blk.reshape(-1, 16))
        return out.reshape(lead + (16,))

    return flat, nd


# Drop-in twins of `kernels.aes.aes_encrypt_table`, gather-free:
# round_keys [B, R, 16] uint8; blocks [B, 16] uint8 -> [B, 16].  The
# `_nd` forms take leading-dim-agnostic ([..., R, 16]) arguments.
# `tower` uses the composite-field S-box (5 GF(2^4) multiplies instead
# of 4 GF(2^8) ones; fetch-verified ~1.6x on v5e).
aes_encrypt_bitsliced, aes_encrypt_bitsliced_nd = \
    _make_plane_provider(_sbox_bits)
aes_encrypt_bitsliced_tower, aes_encrypt_bitsliced_tower_nd = \
    _make_plane_provider(_sbox_bits_tower)


# ----------------------------------------------- packed-word XLA provider
#
# Round-5: the provider above stores ONE bit per uint8 element; this
# one packs 32 BLOCKS per uint32 word (plane p, word (g, byte): bit k
# = bit p of byte of block 32g + k), so every XOR/AND in the identical
# circuit processes 32 blocks at once.  Per-block keys pack the same
# way, which keeps the per-packet-key SRTP contract (each lane bit
# carries its own block's key bit).  Fetch-verified on the v5e the two
# providers measured at PARITY (~10-12M blocks/s net — XLA:TPU handles
# the u8 planes better than the classic bitslice intuition predicts),
# so this stays a selectable provider for the registry/`set_core`
# rather than the default; other TPU generations may rank differently.

def _to_packed_planes(blocks):
    """[B, 16] uint8 (B % 32 == 0) -> 8 planes [B/32, 4, 4] uint32."""
    x = blocks.reshape(-1, 32, 16).astype(jnp.uint32)
    sh = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    planes = []
    for p in range(8):
        w = jnp.sum(((x >> p) & 1) << sh, axis=1, dtype=jnp.uint32)
        planes.append(w.reshape(-1, 4, 4).transpose(0, 2, 1))
    return planes


def _from_packed_planes(bits):
    """8 planes [G, 4, 4] uint32 -> [G*32, 16] uint8."""
    sh = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    acc = None
    for p in range(8):
        w = bits[p].transpose(0, 2, 1).reshape(-1, 1, 16)   # [G, 1, 16]
        bit = (w >> sh) & 1                                 # [G, 32, 16]
        acc = (bit << p) if acc is None else acc | (bit << p)
    return acc.astype(jnp.uint8).reshape(-1, 16)


@jax.jit
def aes_encrypt_bitsliced32(round_keys, blocks):
    """Packed-word twin of `aes_encrypt_bitsliced` (32 blocks/word).

    round_keys [B, R, 16] uint8; blocks [B, 16] uint8 -> [B, 16].
    Pads B up to a multiple of 32 internally (zero blocks/keys) and
    slices the pad back off.
    """
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    n = blk.shape[0]
    pad = (-n) % 32
    if pad:
        blk = jnp.concatenate(
            [blk, jnp.zeros((pad, 16), jnp.uint8)], axis=0)
        rk = jnp.concatenate(
            [rk, jnp.zeros((pad,) + rk.shape[1:], jnp.uint8)], axis=0)
    nr = rk.shape[-2] - 1
    ones = jnp.uint32(0xFFFFFFFF)
    bits = _to_packed_planes(blk)
    rk_bits = [_to_packed_planes(rk[:, r, :]) for r in range(nr + 1)]
    out = _rounds(bits, rk_bits, nr, jnp.concatenate, jnp.stack,
                  ones=ones)
    return _from_packed_planes(out)[:n]


def aes_encrypt_bitsliced32_nd(round_keys, blocks):
    """Leading-dim-agnostic wrapper (see aes_encrypt_bitsliced_nd)."""
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    lead = blk.shape[:-1]
    out = aes_encrypt_bitsliced32(rk.reshape((-1,) + rk.shape[-2:]),
                                  blk.reshape(-1, 16))
    return out.reshape(lead + (16,))


# ------------------------------------------------------------ Pallas provider
#
# Round-2 postmortem (BENCH_r02 "error: MosaicError"): the first Pallas
# twin ran `reshape(-1, 4, 4).transpose(0, 2, 1)` on uint8 INSIDE the
# kernel — minor-dim relayout + 8-bit shifts, exactly what Mosaic
# declines to lower.  This version is lane-native instead: the batch
# rides the 128-wide lane axis, each bit plane is a [4, 4, 128] int32
# tile (row, col, lane), bit extraction/packing happens OUTSIDE the
# kernel as plain XLA, and the kernel body is nothing but elementwise
# XOR/AND plus static sublane slice+concat (ShiftRows) and stacks
# (MixColumns) — no transpose, no gather, no sub-32-bit arithmetic.

_LANES = 128


def _shift_rows_tile(bits):
    """[4, 4, L] planes: row r rolls left by r columns (axis 1)."""
    out = []
    for p in bits:
        rows = []
        for r in range(4):
            row = p[r]                       # [4 cols, L]
            if r:
                row = jnp.concatenate([row[r:], row[:r]], axis=0)
            rows.append(row)
        out.append(jnp.stack(rows, axis=0))
    return out


def _mix_columns_tile(bits):
    rows = [[p[r] for p in bits] for r in range(4)]   # [4 cols, L] each
    new_rows = []
    for r in range(4):
        a, b = rows[r], rows[(r + 1) % 4]
        c, d = rows[(r + 2) % 4], rows[(r + 3) % 4]
        new_rows.append(_vxor(_vxor(_xtime_bits(a),
                                    _vxor(_xtime_bits(b), b)),
                              _vxor(c, d)))
    return [jnp.stack([new_rows[r][p] for r in range(4)], axis=0)
            for p in range(8)]


def _pallas_kernel(bits_ref, rk_ref, out_ref, *, nr: int):
    """Bit-plane tile in VMEM: bits [8, 4, 4, L], rk [(nr+1)*8, 4, 4, L]."""
    bits = [bits_ref[p] for p in range(8)]
    rk_bits = [[rk_ref[r * 8 + p] for p in range(8)]
               for r in range(nr + 1)]
    bits = _vxor(bits, rk_bits[0])
    for r in range(1, nr):
        bits = _sbox_bits(bits)
        bits = _shift_rows_tile(bits)
        bits = _mix_columns_tile(bits)
        bits = _vxor(bits, rk_bits[r])
    bits = _sbox_bits(bits)
    bits = _shift_rows_tile(bits)
    bits = _vxor(bits, rk_bits[nr])
    for p in range(8):
        out_ref[p] = bits[p]


def _to_lane_planes(x16):
    """[B, 16] uint8 -> [8, 4, 4, B] int32 bit planes (row, col, lane).

    byte i = row + 4*col, same state layout as the XLA provider."""
    y = x16.reshape(-1, 4, 4).transpose(2, 1, 0)      # [row, col, B]
    return jnp.stack([((y >> p) & 1).astype(jnp.int32)
                      for p in range(8)], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def aes_encrypt_pallas_bitsliced(round_keys, blocks,
                                 interpret: bool = False):
    """Pallas twin of `aes_encrypt_bitsliced` (lane-native layout)."""
    from jax.experimental import pallas as pl

    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    nr = rk.shape[-2] - 1
    b = blocks.shape[0]
    pad = (-b) % _LANES
    if pad:
        blocks = jnp.pad(blocks, ((0, pad), (0, 0)))
        rk = jnp.pad(rk, ((0, pad), (0, 0), (0, 0)))
    bp = b + pad
    bits = _to_lane_planes(blocks)                    # [8, 4, 4, BP]
    rkb = _to_lane_planes(
        rk.transpose(1, 0, 2).reshape(-1, 16)
    ).reshape(8, 4, 4, nr + 1, bp)
    # [(nr+1)*8, 4, 4, BP]: round-major so the kernel indexes r*8+p
    rkb = rkb.transpose(3, 0, 1, 2, 4).reshape((nr + 1) * 8, 4, 4, bp)
    out = pl.pallas_call(
        functools.partial(_pallas_kernel, nr=nr),
        grid=(bp // _LANES,),
        in_specs=[
            pl.BlockSpec((8, 4, 4, _LANES), lambda i: (0, 0, 0, i)),
            pl.BlockSpec(((nr + 1) * 8, 4, 4, _LANES),
                         lambda i: (0, 0, 0, i)),
        ],
        out_specs=pl.BlockSpec((8, 4, 4, _LANES),
                               lambda i: (0, 0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 4, 4, bp), jnp.int32),
        interpret=interpret,
    )(bits, rkb)
    acc = out[0]
    for p in range(1, 8):
        acc = acc | (out[p] << p)
    res = acc.astype(jnp.uint8).transpose(2, 1, 0).reshape(-1, 16)
    return res[:b] if pad else res


# ------------------------------------------------------------------ registry

def register_providers() -> None:
    from libjitsi_tpu.kernels import aes as aes_mod
    from libjitsi_tpu.kernels import registry

    registry.register("aes_encrypt", "xla_table", aes_mod.aes_encrypt)
    registry.register("aes_encrypt", "xla_bitsliced",
                      aes_encrypt_bitsliced)
    registry.register("aes_encrypt", "xla_bitsliced_tower",
                      aes_encrypt_bitsliced_tower)
    registry.register("aes_encrypt", "xla_bitsliced32",
                      aes_encrypt_bitsliced32)
    registry.register("aes_encrypt", "pallas_bitsliced",
                      aes_encrypt_pallas_bitsliced)


register_providers()
