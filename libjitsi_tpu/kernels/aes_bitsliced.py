"""Bitsliced, gather-free AES — the SURVEY §7 "hard parts" candidate.

The production AES path (`kernels.aes.aes_encrypt`) uses a 256-entry
S-box `jnp.take`, which XLA lowers well but Mosaic (Pallas TPU) refuses
to lower at all.  This module builds AES-128/256 encryption as a pure
Boolean circuit — XOR/AND/slice/concat only, no gathers — so the same
body runs as an XLA program *and* as a Pallas kernel, and the provider
registry (`kernels.registry`, the reference's `.srtp.crypto.Aes`
benchmark-and-pick pattern) can measure all three and keep the winner.

Circuit construction is derived, not transcribed: the S-box is computed
as ``affine(x^254)`` over GF(2^8), with the squaring/power linear maps
and the polynomial-reduction matrix generated from field arithmetic at
import time and the complete 256-entry truth table asserted against an
independently generated S-box.  Inversion uses the addition chain
x -> x^2 -> x^3 -> x^12 -> x^15 -> x^240 -> x^252 -> x^254
(4 variable GF multiplications; squarings are linear).

State layout: 8 bit-planes, each ``[B, 4, 4]`` (byte i = row + 4*col),
LSB-first bit order.  ShiftRows is slice+concat per row; MixColumns is
xtime/XOR over row variables — nothing here indexes by data.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ------------------------------------------------------------ host derivation

_POLY = 0x11B


def _gf_mul(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= _POLY
        b >>= 1
    return r


def _gf_pow(a: int, n: int) -> int:
    r = 1
    while n:
        if n & 1:
            r = _gf_mul(r, a)
        a = _gf_mul(a, a)
        n >>= 1
    return r


def _linear_matrix(fn) -> np.ndarray:
    """8x8 GF(2) matrix of a linear byte map, via basis probing
    (bit i = (byte >> i) & 1)."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        y = fn(1 << j)
        for i in range(8):
            m[i, j] = (y >> i) & 1
    return m


_M_SQ = _linear_matrix(lambda x: _gf_pow(x, 2))
_M_P4 = _linear_matrix(lambda x: _gf_pow(x, 4))
_M_P16 = _linear_matrix(lambda x: _gf_pow(x, 16))
# AES S-box affine layer: s = A*x + 0x63 (applied AFTER inversion)
_M_AFF = _linear_matrix(
    lambda x: (x ^ ((x << 1) | (x >> 7)) ^ ((x << 2) | (x >> 6))
               ^ ((x << 3) | (x >> 5)) ^ ((x << 4) | (x >> 4))) & 0xFF)
_AFF_C = 0x63
# x^k mod poly for the 15 product coefficients of an 8x8-bit multiply
_REDC = [_gf_pow(2, k) for k in range(15)]


# ----------------------------------------------------------- circuit builders

def _linear(bits, mat: np.ndarray, const: int = 0, ones=1):
    """`ones` is the all-true word for the plane element type: 1 for
    one-bit-per-uint8 planes, 0xFFFFFFFF for the packed-word provider
    (every bit of an int32 element is a different block)."""
    out = []
    for i in range(8):
        acc = None
        for j in range(8):
            if mat[i, j]:
                acc = bits[j] if acc is None else acc ^ bits[j]
        if acc is None:
            acc = bits[0] ^ bits[0]
        if (const >> i) & 1:
            acc = acc ^ ones
        out.append(acc)
    return out


def _gf_mult_bits(a, b):
    """Bitsliced GF(2^8) multiply of two byte variables."""
    c = []
    for k in range(15):
        acc = None
        for i in range(max(0, k - 7), min(8, k + 1)):
            t = a[i] & b[k - i]
            acc = t if acc is None else acc ^ t
        c.append(acc)
    out = []
    for i in range(8):
        acc = None
        for k in range(15):
            if (_REDC[k] >> i) & 1:
                acc = c[k] if acc is None else acc ^ c[k]
        out.append(acc)
    return out


def _sbox_bits(x, ones=1):
    """S(x) = affine(x^254): 4 GF multiplies + linear maps, no tables."""
    a2 = _linear(x, _M_SQ)
    a3 = _gf_mult_bits(a2, x)
    a12 = _linear(a3, _M_P4)
    a15 = _gf_mult_bits(a12, a3)
    a240 = _linear(a15, _M_P16)
    a252 = _gf_mult_bits(a240, a12)
    a254 = _gf_mult_bits(a252, a2)
    return _linear(a254, _M_AFF, _AFF_C, ones)


def _self_check() -> None:
    """Assert the derived circuit reproduces the full S-box table."""
    xs = np.arange(256, dtype=np.uint8)
    bits = [((xs >> p) & 1).astype(np.uint8) for p in range(8)]
    out = _sbox_bits(bits)
    got = np.zeros(256, dtype=np.uint16)
    for p in range(8):
        got |= out[p].astype(np.uint16) << p
    from libjitsi_tpu.kernels.aes import _SBOX

    if not np.array_equal(got.astype(np.uint8), _SBOX):
        raise AssertionError("bitsliced S-box circuit != S-box table")


_self_check()


def _vxor(a, b):
    return [x ^ y for x, y in zip(a, b)]


def _xtime_bits(v):
    """GF doubling: out = v << 1 reduced by 0x11B (LSB-first planes)."""
    return [v[7], v[0] ^ v[7], v[1], v[2] ^ v[7], v[3] ^ v[7],
            v[4], v[5], v[6]]


def _shift_rows_bits(bits, cat):
    out = []
    for p in bits:
        rows = []
        for r in range(4):
            row = p[:, r:r + 1, :]
            rows.append(cat([row[..., r:], row[..., :r]], -1)
                        if r else row)
        out.append(cat(rows, 1))
    return out


def _mix_columns_bits(bits, stack):
    rows = [[p[:, r, :] for p in bits] for r in range(4)]
    new_rows = []
    for r in range(4):
        a, b = rows[r], rows[(r + 1) % 4]
        c, d = rows[(r + 2) % 4], rows[(r + 3) % 4]
        new_rows.append(_vxor(_vxor(_xtime_bits(a), _vxor(_xtime_bits(b),
                                                          b)),
                              _vxor(c, d)))
    return [stack([new_rows[r][p] for r in range(4)], 1)
            for p in range(8)]


def _rounds(bits, rk_bits, nr: int, cat, stack, ones=1):
    """The shared round schedule over bit-plane state."""
    bits = _vxor(bits, rk_bits[0])
    for r in range(1, nr):
        bits = _sbox_bits(bits, ones)
        bits = _shift_rows_bits(bits, cat)
        bits = _mix_columns_bits(bits, stack)
        bits = _vxor(bits, rk_bits[r])
    bits = _sbox_bits(bits, ones)
    bits = _shift_rows_bits(bits, cat)
    return _vxor(bits, rk_bits[nr])


# --------------------------------------------------------------- XLA provider

def _to_planes(blocks):
    """[B, 16] uint8 -> 8 planes [B, 4, 4] (byte i = row + 4*col)."""
    x = blocks.reshape(-1, 4, 4).transpose(0, 2, 1)   # [B, r, c]
    return [((x >> p) & 1).astype(jnp.uint8) for p in range(8)]


def _from_planes(bits):
    acc = bits[0]
    for p in range(1, 8):
        acc = acc | (bits[p] << p)
    return acc.transpose(0, 2, 1).reshape(-1, 16).astype(jnp.uint8)


@jax.jit
def aes_encrypt_bitsliced(round_keys, blocks):
    """Drop-in twin of `kernels.aes.aes_encrypt_table`, gather-free.

    round_keys [B, R, 16] uint8; blocks [B, 16] uint8 -> [B, 16].
    """
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    nr = rk.shape[-2] - 1
    bits = _to_planes(jnp.asarray(blocks, dtype=jnp.uint8))
    rk_bits = [_to_planes(rk[:, r, :]) for r in range(nr + 1)]
    out = _rounds(bits, rk_bits, nr, jnp.concatenate, jnp.stack)
    return _from_planes(out)


def aes_encrypt_bitsliced_nd(round_keys, blocks):
    """Leading-dim-agnostic wrapper matching `aes_encrypt`'s contract
    ([..., R, 16] keys, [..., 16] blocks) — the CTR/GCM paths call with
    broadcast key tensors, which flatten away under jit."""
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    lead = blk.shape[:-1]
    out = aes_encrypt_bitsliced(rk.reshape((-1,) + rk.shape[-2:]),
                                blk.reshape(-1, 16))
    return out.reshape(lead + (16,))


# ----------------------------------------------- packed-word XLA provider
#
# Round-5: the provider above stores ONE bit per uint8 element; this
# one packs 32 BLOCKS per uint32 word (plane p, word (g, byte): bit k
# = bit p of byte of block 32g + k), so every XOR/AND in the identical
# circuit processes 32 blocks at once.  Per-block keys pack the same
# way, which keeps the per-packet-key SRTP contract (each lane bit
# carries its own block's key bit).  Fetch-verified on the v5e the two
# providers measured at PARITY (~10-12M blocks/s net — XLA:TPU handles
# the u8 planes better than the classic bitslice intuition predicts),
# so this stays a selectable provider for the registry/`set_core`
# rather than the default; other TPU generations may rank differently.

def _to_packed_planes(blocks):
    """[B, 16] uint8 (B % 32 == 0) -> 8 planes [B/32, 4, 4] uint32."""
    x = blocks.reshape(-1, 32, 16).astype(jnp.uint32)
    sh = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    planes = []
    for p in range(8):
        w = jnp.sum(((x >> p) & 1) << sh, axis=1, dtype=jnp.uint32)
        planes.append(w.reshape(-1, 4, 4).transpose(0, 2, 1))
    return planes


def _from_packed_planes(bits):
    """8 planes [G, 4, 4] uint32 -> [G*32, 16] uint8."""
    sh = jnp.arange(32, dtype=jnp.uint32)[None, :, None]
    acc = None
    for p in range(8):
        w = bits[p].transpose(0, 2, 1).reshape(-1, 1, 16)   # [G, 1, 16]
        bit = (w >> sh) & 1                                 # [G, 32, 16]
        acc = (bit << p) if acc is None else acc | (bit << p)
    return acc.astype(jnp.uint8).reshape(-1, 16)


@jax.jit
def aes_encrypt_bitsliced32(round_keys, blocks):
    """Packed-word twin of `aes_encrypt_bitsliced` (32 blocks/word).

    round_keys [B, R, 16] uint8; blocks [B, 16] uint8 -> [B, 16].
    Pads B up to a multiple of 32 internally (zero blocks/keys) and
    slices the pad back off.
    """
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    n = blk.shape[0]
    pad = (-n) % 32
    if pad:
        blk = jnp.concatenate(
            [blk, jnp.zeros((pad, 16), jnp.uint8)], axis=0)
        rk = jnp.concatenate(
            [rk, jnp.zeros((pad,) + rk.shape[1:], jnp.uint8)], axis=0)
    nr = rk.shape[-2] - 1
    ones = jnp.uint32(0xFFFFFFFF)
    bits = _to_packed_planes(blk)
    rk_bits = [_to_packed_planes(rk[:, r, :]) for r in range(nr + 1)]
    out = _rounds(bits, rk_bits, nr, jnp.concatenate, jnp.stack,
                  ones=ones)
    return _from_packed_planes(out)[:n]


def aes_encrypt_bitsliced32_nd(round_keys, blocks):
    """Leading-dim-agnostic wrapper (see aes_encrypt_bitsliced_nd)."""
    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blk = jnp.asarray(blocks, dtype=jnp.uint8)
    lead = blk.shape[:-1]
    out = aes_encrypt_bitsliced32(rk.reshape((-1,) + rk.shape[-2:]),
                                  blk.reshape(-1, 16))
    return out.reshape(lead + (16,))


# ------------------------------------------------------------ Pallas provider
#
# Round-2 postmortem (BENCH_r02 "error: MosaicError"): the first Pallas
# twin ran `reshape(-1, 4, 4).transpose(0, 2, 1)` on uint8 INSIDE the
# kernel — minor-dim relayout + 8-bit shifts, exactly what Mosaic
# declines to lower.  This version is lane-native instead: the batch
# rides the 128-wide lane axis, each bit plane is a [4, 4, 128] int32
# tile (row, col, lane), bit extraction/packing happens OUTSIDE the
# kernel as plain XLA, and the kernel body is nothing but elementwise
# XOR/AND plus static sublane slice+concat (ShiftRows) and stacks
# (MixColumns) — no transpose, no gather, no sub-32-bit arithmetic.

_LANES = 128


def _shift_rows_tile(bits):
    """[4, 4, L] planes: row r rolls left by r columns (axis 1)."""
    out = []
    for p in bits:
        rows = []
        for r in range(4):
            row = p[r]                       # [4 cols, L]
            if r:
                row = jnp.concatenate([row[r:], row[:r]], axis=0)
            rows.append(row)
        out.append(jnp.stack(rows, axis=0))
    return out


def _mix_columns_tile(bits):
    rows = [[p[r] for p in bits] for r in range(4)]   # [4 cols, L] each
    new_rows = []
    for r in range(4):
        a, b = rows[r], rows[(r + 1) % 4]
        c, d = rows[(r + 2) % 4], rows[(r + 3) % 4]
        new_rows.append(_vxor(_vxor(_xtime_bits(a),
                                    _vxor(_xtime_bits(b), b)),
                              _vxor(c, d)))
    return [jnp.stack([new_rows[r][p] for r in range(4)], axis=0)
            for p in range(8)]


def _pallas_kernel(bits_ref, rk_ref, out_ref, *, nr: int):
    """Bit-plane tile in VMEM: bits [8, 4, 4, L], rk [(nr+1)*8, 4, 4, L]."""
    bits = [bits_ref[p] for p in range(8)]
    rk_bits = [[rk_ref[r * 8 + p] for p in range(8)]
               for r in range(nr + 1)]
    bits = _vxor(bits, rk_bits[0])
    for r in range(1, nr):
        bits = _sbox_bits(bits)
        bits = _shift_rows_tile(bits)
        bits = _mix_columns_tile(bits)
        bits = _vxor(bits, rk_bits[r])
    bits = _sbox_bits(bits)
    bits = _shift_rows_tile(bits)
    bits = _vxor(bits, rk_bits[nr])
    for p in range(8):
        out_ref[p] = bits[p]


def _to_lane_planes(x16):
    """[B, 16] uint8 -> [8, 4, 4, B] int32 bit planes (row, col, lane).

    byte i = row + 4*col, same state layout as the XLA provider."""
    y = x16.reshape(-1, 4, 4).transpose(2, 1, 0)      # [row, col, B]
    return jnp.stack([((y >> p) & 1).astype(jnp.int32)
                      for p in range(8)], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def aes_encrypt_pallas_bitsliced(round_keys, blocks,
                                 interpret: bool = False):
    """Pallas twin of `aes_encrypt_bitsliced` (lane-native layout)."""
    from jax.experimental import pallas as pl

    rk = jnp.asarray(round_keys, dtype=jnp.uint8)
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    nr = rk.shape[-2] - 1
    b = blocks.shape[0]
    pad = (-b) % _LANES
    if pad:
        blocks = jnp.pad(blocks, ((0, pad), (0, 0)))
        rk = jnp.pad(rk, ((0, pad), (0, 0), (0, 0)))
    bp = b + pad
    bits = _to_lane_planes(blocks)                    # [8, 4, 4, BP]
    rkb = _to_lane_planes(
        rk.transpose(1, 0, 2).reshape(-1, 16)
    ).reshape(8, 4, 4, nr + 1, bp)
    # [(nr+1)*8, 4, 4, BP]: round-major so the kernel indexes r*8+p
    rkb = rkb.transpose(3, 0, 1, 2, 4).reshape((nr + 1) * 8, 4, 4, bp)
    out = pl.pallas_call(
        functools.partial(_pallas_kernel, nr=nr),
        grid=(bp // _LANES,),
        in_specs=[
            pl.BlockSpec((8, 4, 4, _LANES), lambda i: (0, 0, 0, i)),
            pl.BlockSpec(((nr + 1) * 8, 4, 4, _LANES),
                         lambda i: (0, 0, 0, i)),
        ],
        out_specs=pl.BlockSpec((8, 4, 4, _LANES),
                               lambda i: (0, 0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 4, 4, bp), jnp.int32),
        interpret=interpret,
    )(bits, rkb)
    acc = out[0]
    for p in range(1, 8):
        acc = acc | (out[p] << p)
    res = acc.astype(jnp.uint8).transpose(2, 1, 0).reshape(-1, 16)
    return res[:b] if pad else res


# ------------------------------------------------------------------ registry

def register_providers() -> None:
    from libjitsi_tpu.kernels import aes as aes_mod
    from libjitsi_tpu.kernels import registry

    registry.register("aes_encrypt", "xla_table", aes_mod.aes_encrypt)
    registry.register("aes_encrypt", "xla_bitsliced",
                      aes_encrypt_bitsliced)
    registry.register("aes_encrypt", "pallas_bitsliced",
                      aes_encrypt_pallas_bitsliced)


register_providers()
