"""Receive-side bandwidth estimator (reference:
`...remotebitrateestimator.RemoteBitrateEstimatorAbsSendTime`): packets
stamped with abs-send-time feed InterArrival -> Kalman OveruseEstimator
-> OveruseDetector -> AIMD; the result goes out as REMB.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from libjitsi_tpu.bwe.aimd import AimdRateControl
from libjitsi_tpu.bwe.inter_arrival import InterArrival
from libjitsi_tpu.bwe.overuse import OveruseDetector, OveruseEstimator
from libjitsi_tpu.bwe.rate_stats import RateStatistics


def abs_send_time_to_ms(ast24: int) -> float:
    """24-bit 6.18 fixed-point seconds -> ms (wraps every 64 s)."""
    return (ast24 / float(1 << 18)) * 1000.0


class RemoteBitrateEstimator:
    """One estimator per transport (all SSRCs share the bottleneck)."""

    def __init__(self, min_bitrate_bps: float = 30_000,
                 start_bitrate_bps: float = 300_000):
        self._inter = InterArrival()
        self._est = OveruseEstimator()
        self._det = OveruseDetector()
        self._aimd = AimdRateControl(min_bitrate_bps, start_bitrate_bps)
        self._incoming = RateStatistics(window_ms=1000)
        self._last_send_ms: Optional[float] = None
        self._send_unwrapped = 0.0

    def _unwrap_send_ms(self, send_ms: float) -> float:
        """abs-send-time wraps every 64 s; unwrap against the last value."""
        if self._last_send_ms is None:
            self._last_send_ms = send_ms
            self._send_unwrapped = send_ms
            return self._send_unwrapped
        d = send_ms - self._last_send_ms
        if d < -32000:       # wrapped forward
            d += 64000
        elif d > 32000:      # out-of-order across the wrap
            d -= 64000
        self._last_send_ms = send_ms
        self._send_unwrapped += d
        return self._send_unwrapped

    def incoming_packet(self, arrival_ms: float, ast24: int, size: int
                        ) -> None:
        """Feed one media packet (arrival host time, abs-send-time stamp)."""
        self._incoming.update(size, int(arrival_ms))
        send_ms = self._unwrap_send_ms(abs_send_time_to_ms(ast24))
        deltas = self._inter.add(send_ms, arrival_ms, size)
        if deltas is None:
            return
        send_delta, arrival_delta, size_delta = deltas
        self._est.update(arrival_delta, send_delta, size_delta,
                         self._det.state)
        self._det.detect(self._est.offset, send_delta,
                         self._est.num_deltas, arrival_ms)

    def incoming_batch(self, arrival_ms, ast24, sizes) -> None:
        for a, s, z in zip(np.asarray(arrival_ms), np.asarray(ast24),
                           np.asarray(sizes)):
            self.incoming_packet(float(a), int(s), int(z))

    def update_estimate(self, now_ms: float) -> float:
        """Periodic tick -> current REMB bitrate (bps)."""
        return self._aimd.update(self._det.state,
                                 self._incoming.rate(int(now_ms)), now_ms)

    def set_rtt(self, rtt_ms: float) -> None:
        self._aimd.set_rtt(rtt_ms)

    @property
    def state(self) -> str:
        return self._det.state
