"""Over-use estimation + detection (reference:
`...remotebitrateestimator.{OveruseEstimator,OveruseDetector}` — the
WebRTC Kalman filter over the one-way-delay gradient and the adaptive
threshold detector)."""

from __future__ import annotations

import math


NORMAL, OVERUSING, UNDERUSING = "normal", "overusing", "underusing"


class OveruseEstimator:
    """Kalman filter on [offset_ms, slope]; tracks the queuing-delay
    gradient m(t) from per-group (send_delta, arrival_delta)."""

    def __init__(self):
        self.offset = 0.0            # estimated delay gradient (ms)
        self._slope = 8.0 / 512.0
        self._e = [[100.0, 0.0], [0.0, 1e-1]]
        self._process_noise = [1e-13, 1e-3]
        self._avg_noise = 0.0
        self._var_noise = 50.0
        self.num_deltas = 0

    def update(self, t_delta_ms: float, ts_delta_ms: float,
               size_delta: int, state: str) -> None:
        min_frame_period = ts_delta_ms
        self.num_deltas = min(self.num_deltas + 1, 60)
        t_ts_delta = t_delta_ms - ts_delta_ms
        fs_delta = float(size_delta)

        # propagate covariance
        e = self._e
        e[0][0] += self._process_noise[0]
        e[1][1] += self._process_noise[1]
        if state == OVERUSING and self.offset < 0 or \
           state == UNDERUSING and self.offset > 0:
            e[1][1] += 10 * self._process_noise[1]

        h = [fs_delta, 1.0]
        eh = [e[0][0] * h[0] + e[0][1] * h[1],
              e[1][0] * h[0] + e[1][1] * h[1]]
        residual = t_ts_delta - self._slope * h[0] - self.offset

        max_residual = 3.0 * math.sqrt(self._var_noise)
        in_stable = abs(residual) < max_residual
        self._update_noise(min_frame_period,
                           residual if in_stable else
                           math.copysign(max_residual, residual), state)

        denom = self._var_noise + (h[0] * eh[0] + h[1] * eh[1])
        k = [eh[0] / denom, eh[1] / denom]
        ikh = [[1.0 - k[0] * h[0], -k[0] * h[1]],
               [-k[1] * h[0], 1.0 - k[1] * h[1]]]
        e00, e01 = e[0]
        e10, e11 = e[1]
        e[0][0] = e00 * ikh[0][0] + e10 * ikh[0][1]
        e[0][1] = e01 * ikh[0][0] + e11 * ikh[0][1]
        e[1][0] = e00 * ikh[1][0] + e10 * ikh[1][1]
        e[1][1] = e01 * ikh[1][0] + e11 * ikh[1][1]

        self._slope += k[0] * residual
        self.offset += k[1] * residual

    def _update_noise(self, ts_delta: float, residual: float,
                      state: str) -> None:
        if state != NORMAL:
            return
        alpha = 0.01 ** (ts_delta / 30.0) if ts_delta > 0 else 0.0
        alpha = min(max(alpha, 0.0), 1.0)
        self._avg_noise = alpha * self._avg_noise + (1 - alpha) * residual
        self._var_noise = alpha * self._var_noise + (1 - alpha) * (
            residual - self._avg_noise) ** 2
        self._var_noise = max(self._var_noise, 1.0)


class OveruseDetector:
    """Adaptive-threshold comparison of the estimator's offset
    (WebRTC's 'adaptive threshold' kup/kdown gains)."""

    def __init__(self, overuse_time_th_ms: float = 10.0):
        self.threshold = 12.5
        self._last_update_ms: float = -1.0
        self._time_over_using = -1.0
        self._overuse_counter = 0
        self.state = NORMAL
        self._overuse_time_th = overuse_time_th_ms

    def detect(self, offset: float, ts_delta_ms: float, num_deltas: int,
               now_ms: float) -> str:
        if num_deltas < 2:
            return NORMAL
        t = min(num_deltas, 60) * offset
        if t > self.threshold:
            if self._time_over_using == -1:
                self._time_over_using = ts_delta_ms / 2
            else:
                self._time_over_using += ts_delta_ms
            self._overuse_counter += 1
            if self._time_over_using > self._overuse_time_th and \
               self._overuse_counter > 1:
                self.state = OVERUSING
        elif t < -self.threshold:
            self._time_over_using = -1
            self._overuse_counter = 0
            self.state = UNDERUSING
        else:
            self._time_over_using = -1
            self._overuse_counter = 0
            self.state = NORMAL
        self._adapt(t, now_ms)
        return self.state

    def _adapt(self, t: float, now_ms: float) -> None:
        if self._last_update_ms < 0:
            self._last_update_ms = now_ms
        if abs(t) > self.threshold + 15.0:
            self._last_update_ms = now_ms
            return
        # kDown (fast decay toward |t| when below), kUp (slow growth above)
        k = 0.039 if abs(t) < self.threshold else 0.0087
        dt = min(max(now_ms - self._last_update_ms, 0.0), 100.0)
        self.threshold += k * (abs(t) - self.threshold) * dt
        self.threshold = min(max(self.threshold, 6.0), 600.0)
        self._last_update_ms = now_ms
