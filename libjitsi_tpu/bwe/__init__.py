from libjitsi_tpu.bwe.rate_stats import RateStatistics  # noqa: F401
from libjitsi_tpu.bwe.remote_estimator import RemoteBitrateEstimator  # noqa: F401
from libjitsi_tpu.bwe.send_side import SendSideBandwidthEstimation  # noqa: F401
from libjitsi_tpu.bwe.batched import BatchedRemoteBitrateEstimator  # noqa: F401
