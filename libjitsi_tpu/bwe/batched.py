"""Batched receive-side bandwidth estimation: T transports as arrays.

The scalar classes (`InterArrival`, `OveruseEstimator`, `OveruseDetector`,
`AimdRateControl`, `RateStatistics` — ports of the reference's
`...remotebitrateestimator.*`, themselves WebRTC GCC ports) are one
Python state machine per transport, driven per packet.  A bridge with
thousands of transports pays a Python-loop toll per packet; this bank
keeps every transport's state in `[T]` NumPy arrays and applies the same
update laws vectorized — the dense-state doctrine of the rest of the
framework (SURVEY §2.3's re-design note).

Equivalence: updates use the identical formulas in the identical order,
so results match the scalar classes to float rounding; the differential
test tests/test_dense_receive.py::test_batched_bwe_matches_scalar pins
it.  In-batch multi-packet
transports decompose into waves by per-transport rank, preserving
per-packet sequencing.

States are int codes here (vector-friendly): signal 0/1/2 =
normal/overusing/underusing; rate state 0/1/2 = hold/increase/decrease;
region 0/1 = multiplicative/additive.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from libjitsi_tpu.core.rtp_math import segment_ranks
from libjitsi_tpu.utils.checkpoint import ArraySnapshotMixin

SIG_NORMAL, SIG_OVERUSING, SIG_UNDERUSING = 0, 1, 2
ST_HOLD, ST_INCREASE, ST_DECREASE = 0, 1, 2
RG_MULTIPLICATIVE, RG_ADDITIVE = 0, 1

_BURST_SPAN_MS = 5.0
_BETA = 0.85


class BatchedRemoteBitrateEstimator(ArraySnapshotMixin):
    """T independent GCC estimators in dense arrays."""

    def __init__(self, capacity: int, min_bitrate_bps: float = 30_000,
                 start_bitrate_bps: float = 300_000,
                 max_bitrate_bps: float = 30e6,
                 window_ms: int = 1000):
        t = capacity
        self.capacity = t
        # ---- abs-send-time unwrap
        self._last_send = np.zeros(t, dtype=np.float64)
        self._send_unwrapped = np.zeros(t, dtype=np.float64)
        self._has_send = np.zeros(t, dtype=bool)
        # ---- InterArrival groups
        self._g_has = np.zeros(t, dtype=bool)
        self._g_first_send = np.zeros(t, dtype=np.float64)
        self._g_send = np.zeros(t, dtype=np.float64)
        self._g_arrival = np.zeros(t, dtype=np.float64)
        self._g_size = np.zeros(t, dtype=np.int64)
        self._p_has = np.zeros(t, dtype=bool)
        self._p_send = np.zeros(t, dtype=np.float64)
        self._p_arrival = np.zeros(t, dtype=np.float64)
        self._p_size = np.zeros(t, dtype=np.int64)
        # ---- Kalman (OveruseEstimator)
        self.offset = np.zeros(t, dtype=np.float64)
        self._slope = np.full(t, 8.0 / 512.0, dtype=np.float64)
        self._e00 = np.full(t, 100.0, dtype=np.float64)
        self._e01 = np.zeros(t, dtype=np.float64)
        self._e10 = np.zeros(t, dtype=np.float64)
        self._e11 = np.full(t, 1e-1, dtype=np.float64)
        self._avg_noise = np.zeros(t, dtype=np.float64)
        self._var_noise = np.full(t, 50.0, dtype=np.float64)
        self.num_deltas = np.zeros(t, dtype=np.int64)
        # ---- detector
        self.threshold = np.full(t, 12.5, dtype=np.float64)
        self._last_update_ms = np.full(t, -1.0, dtype=np.float64)
        self._time_over_using = np.full(t, -1.0, dtype=np.float64)
        self._overuse_counter = np.zeros(t, dtype=np.int64)
        self.signal = np.zeros(t, dtype=np.int8)
        self._overuse_time_th = 10.0
        # ---- AIMD
        self.min_bitrate = float(min_bitrate_bps)
        self.max_bitrate = float(max_bitrate_bps)
        self.start_bitrate = float(start_bitrate_bps)
        self.bitrate = np.full(t, float(start_bitrate_bps),
                               dtype=np.float64)
        self.rate_state = np.zeros(t, dtype=np.int8)
        self.region = np.zeros(t, dtype=np.int8)
        self.rtt_ms = np.full(t, 200.0, dtype=np.float64)
        self._avg_max_kbps = np.full(t, -1.0, dtype=np.float64)
        self._var_max_kbps = np.full(t, 0.4, dtype=np.float64)
        self._last_change_ms = np.full(t, -1.0, dtype=np.float64)
        # ---- incoming rate window (erase-on-advance, running totals —
        # the scalar RateStatistics' incremental design, vectorized;
        # no full-window scan on the tick path)
        self.window_ms = window_ms
        self._buckets = np.zeros((t, window_ms), dtype=np.int64)
        self._win_total = np.zeros(t, dtype=np.int64)
        self._oldest_ms = np.full(t, -1, dtype=np.int64)

    def set_rtt(self, tids, rtt_ms) -> None:
        self.rtt_ms[np.asarray(tids, dtype=np.int64)] = rtt_ms

    def reset_rows(self, tids,
                   start_bitrate_bps: Optional[float] = None) -> None:
        """Return rows to their fresh state — a departing transport's
        Kalman/AIMD state must not leak into the next occupant of a
        recycled row."""
        if start_bitrate_bps is None:
            start_bitrate_bps = self.start_bitrate
        t = np.asarray(tids, dtype=np.int64)
        self._last_send[t] = 0.0
        self._send_unwrapped[t] = 0.0
        self._has_send[t] = False
        self._g_has[t] = False
        self._p_has[t] = False
        self.offset[t] = 0.0
        self._slope[t] = 8.0 / 512.0
        self._e00[t] = 100.0
        self._e01[t] = 0.0
        self._e10[t] = 0.0
        self._e11[t] = 1e-1
        self._avg_noise[t] = 0.0
        self._var_noise[t] = 50.0
        self.num_deltas[t] = 0
        self.threshold[t] = 12.5
        self._last_update_ms[t] = -1.0
        self._time_over_using[t] = -1.0
        self._overuse_counter[t] = 0
        self.signal[t] = SIG_NORMAL
        self.bitrate[t] = float(start_bitrate_bps)
        self.rate_state[t] = ST_HOLD
        self.region[t] = RG_MULTIPLICATIVE
        self.rtt_ms[t] = 200.0
        self._avg_max_kbps[t] = -1.0
        self._var_max_kbps[t] = 0.4
        self._last_change_ms[t] = -1.0
        self._buckets[t] = 0
        self._win_total[t] = 0
        self._oldest_ms[t] = -1

    # ------------------------------------------------------------- feeding
    def incoming_batch(self, tids, arrival_ms, ast24, sizes) -> None:
        """Feed a packet batch: tids [B] transport rows, arrival_ms [B]
        host arrival, ast24 [B] 24-bit abs-send-time, sizes [B] bytes.

        Fast path: a tick's batch carries many packets per transport,
        but the GCC arrival filter only *updates* on burst-group
        closures (5 ms send-time spans) — so within-group packets fold
        in one vectorized pass and the Python loop runs per group
        closure (1-2 per transport per tick), not per packet.  A batch
        whose arrivals span >= the rate window could alias its own
        bucket writes; that pathological shape routes through the exact
        per-packet wave path instead.
        """
        tids = np.asarray(tids, dtype=np.int64)
        b = len(tids)
        if b == 0:
            return
        arrival_ms = np.asarray(arrival_ms, dtype=np.float64)
        send_ms = (np.asarray(ast24, dtype=np.float64)
                   / float(1 << 18)) * 1000.0
        sizes = np.asarray(sizes, dtype=np.int64)
        if (b > 1 and float(arrival_ms.max()) - float(arrival_ms.min())
                >= self.window_ms - 1):
            self._incoming_waves(tids, arrival_ms, send_ms, sizes)
            return

        order = np.argsort(tids, kind="stable")
        t_s = tids[order]
        a_s = arrival_ms[order]
        s_s = send_ms[order]
        z_s = sizes[order]
        first = np.ones(b, dtype=bool)
        first[1:] = t_s[1:] != t_s[:-1]
        seg_start = np.nonzero(first)[0]
        seg_end = np.append(seg_start[1:], b)
        ut = t_s[seg_start]
        seg_id = np.repeat(np.arange(len(ut)), seg_end - seg_start)

        self._rate_update_batch(ut, seg_id, seg_start, seg_end, a_s, z_s)
        u = self._unwrap_batch(ut, seg_id, seg_start, seg_end, s_s)
        self._group_rounds(ut, seg_start, seg_end, u, a_s, z_s)

    def _incoming_waves(self, tids, arrival_ms, send_ms, sizes) -> None:
        """Exact per-packet order via rank waves (slow fallback)."""
        ranks = segment_ranks(tids)
        for r in range(int(ranks.max(initial=0)) + 1):
            rows = np.nonzero(ranks == r)[0]
            if len(rows) == 0:
                break
            self._packet_wave(tids[rows], arrival_ms[rows],
                              send_ms[rows], sizes[rows])

    def _rate_update_batch(self, ut, seg_id, seg_start, seg_end,
                           a_s, z_s) -> None:
        """Whole-batch form of per-packet `_rate_update`, bit-exact for
        batches spanning < window_ms (guarded by the caller).

        Per packet the scalar does: erase to now-W+1, init oldest on
        first sight, fold late packets into the oldest live bucket, add
        bytes.  With the span bound, the only in-batch interaction is a
        later packet's erase zeroing an earlier packet's bucket — which
        is exactly the set of packets whose effective time falls before
        the *final* window edge, so those are masked out instead of
        written and erased.
        """
        w = self.window_ms
        a_i = a_s.astype(np.int64)
        lo = int(a_i.min())
        # segmented running max of arrivals via a seg-keyed cummax (the
        # key makes later segments always dominate earlier ones)
        span1 = int(a_i.max()) - lo + 1
        enc = seg_id * np.int64(span1) + (a_i - lo)
        pref = (np.maximum.accumulate(enc)
                - seg_id * np.int64(span1)) + lo
        oldest_before = self._oldest_ms[ut]
        oldest_start = np.where(oldest_before >= 0, oldest_before,
                                a_i[seg_start])
        oldest_i = np.maximum(oldest_start[seg_id], pref - w + 1)
        now_eff = np.maximum(a_i, oldest_i)
        final_oldest = oldest_i[seg_end - 1]
        # pre-batch buckets: erase up to the final edge, then pin oldest
        # to the per-packet-equivalent end state (covers fresh rows the
        # erase can't see)
        self._erase_old(ut, pref[seg_end - 1])
        self._oldest_ms[ut] = final_oldest
        survive = now_eff >= final_oldest[seg_id]
        flat = ut[seg_id] * np.int64(w) + now_eff % w
        np.add.at(self._buckets.reshape(-1), flat[survive], z_s[survive])
        tot = np.bincount(seg_id[survive],
                          weights=z_s[survive].astype(np.float64),
                          minlength=len(ut))
        self._win_total[ut] += tot.astype(np.int64)

    def _unwrap_batch(self, ut, seg_id, seg_start, seg_end, s_s
                      ) -> np.ndarray:
        """Per-packet 64 s abs-send-time unwrap as a segmented prefix
        sum of wrapped deltas; returns unwrapped send [B]."""
        b = len(s_s)
        prev = np.empty(b, dtype=np.float64)
        prev[1:] = s_s[:-1]
        prev[seg_start] = self._last_send[ut]
        d = s_s - prev
        d = np.where(d < -32000, d + 64000,
                     np.where(d > 32000, d - 64000, d))
        fresh = ~self._has_send[ut]
        start = np.where(fresh, s_s[seg_start],
                         self._send_unwrapped[ut] + d[seg_start])
        d[seg_start] = 0.0
        c = np.cumsum(d)
        u = start[seg_id] + (c - c[seg_start][seg_id])
        self._send_unwrapped[ut] = u[seg_end - 1]
        self._last_send[ut] = s_s[seg_end - 1]
        self._has_send[ut] = True
        return u

    def _group_rounds(self, ut, seg_start, seg_end, u, a_s, z_s
                      ) -> None:
        """InterArrival group bookkeeping, one Python round per group
        *closure* instead of per packet: each round folds every
        transport's maximal run of in-group/out-of-order packets in one
        vector pass, then performs the (Kalman + detector) closure for
        transports whose next packet opens a new group."""
        big = np.int64(1) << 60
        h = seg_start.copy()
        act = np.nonzero(h < seg_end)[0]
        while len(act):
            t_a = ut[act]
            nog = ~self._g_has[t_a]
            if nog.any():
                rows = h[act[nog]]
                tn = t_a[nog]
                self._g_has[tn] = True
                self._g_first_send[tn] = u[rows]
                self._g_send[tn] = u[rows]
                self._g_arrival[tn] = a_s[rows]
                self._g_size[tn] = z_s[rows]
                h[act[nog]] += 1
                act = act[h[act] < seg_end[act]]
                if len(act) == 0:
                    break
                t_a = ut[act]
            lens = seg_end[act] - h[act]
            offs = np.zeros(len(act), dtype=np.int64)
            np.cumsum(lens[:-1], out=offs[1:])
            ar = (np.arange(int(lens.sum()), dtype=np.int64)
                  - np.repeat(offs, lens))
            idx = np.repeat(h[act], lens) + ar
            sid = np.repeat(np.arange(len(act)), lens)
            su = u[idx]
            gf = self._g_first_send[t_a][sid]
            ooo = su < gf                      # out-of-order: ignored
            close = ~ooo & (su - gf > _BURST_SPAN_MS)
            firstclose = np.minimum.reduceat(
                np.where(close, ar, big), offs)
            consumed = ar < firstclose[sid]
            ing = consumed & ~ooo
            if ing.any():
                gmax = np.maximum.reduceat(
                    np.where(ing, su, -np.inf), offs)
                lpos = np.maximum.reduceat(
                    np.where(ing, ar, np.int64(-1)), offs)
                zsum = np.add.reduceat(np.where(ing, z_s[idx], 0), offs)
                hasin = lpos >= 0
                tf = t_a[hasin]
                self._g_send[tf] = np.maximum(self._g_send[tf],
                                              gmax[hasin])
                self._g_arrival[tf] = a_s[h[act[hasin]] + lpos[hasin]]
                self._g_size[tf] += zsum[hasin]
            closing = firstclose < lens
            newh = h[act] + np.minimum(firstclose, lens)
            if closing.any():
                ci = act[closing]
                rows = h[ci] + firstclose[closing]
                tc = ut[ci]
                sg, ag, zg = u[rows], a_s[rows], z_s[rows]
                have_prev = self._p_has[tc]
                send_delta = self._g_send[tc] - self._p_send[tc]
                arr_delta = self._g_arrival[tc] - self._p_arrival[tc]
                size_delta = self._g_size[tc] - self._p_size[tc]
                fm = have_prev & (send_delta >= 0)
                self._p_has[tc] = True
                self._p_send[tc] = self._g_send[tc]
                self._p_arrival[tc] = self._g_arrival[tc]
                self._p_size[tc] = self._g_size[tc]
                self._g_first_send[tc] = sg
                self._g_send[tc] = sg
                self._g_arrival[tc] = ag
                self._g_size[tc] = zg
                if fm.any():
                    filt = tc[fm]
                    self._kalman_update(filt, arr_delta[fm],
                                        send_delta[fm],
                                        size_delta[fm].astype(
                                            np.float64))
                    self._detect(filt, send_delta[fm], ag[fm])
                newh[closing] += 1
            h[act] = newh
            act = np.nonzero(h < seg_end)[0]

    def _packet_wave(self, t, arrival, send, size) -> None:
        """One packet per transport."""
        self._rate_update(t, size, arrival.astype(np.int64))

        # unwrap 64 s abs-send-time circle against the last value
        fresh = ~self._has_send[t]
        d = send - self._last_send[t]
        d = np.where(d < -32000, d + 64000,
                     np.where(d > 32000, d - 64000, d))
        unwrapped = np.where(fresh, send, self._send_unwrapped[t] + d)
        self._send_unwrapped[t] = unwrapped
        self._last_send[t] = send
        self._has_send[t] = True
        send = unwrapped

        # ---- InterArrival group bookkeeping
        no_group = ~self._g_has[t]
        n = t[no_group]
        self._g_has[n] = True
        self._g_first_send[n] = send[no_group]
        self._g_send[n] = send[no_group]
        self._g_arrival[n] = arrival[no_group]
        self._g_size[n] = size[no_group]

        g = ~no_group
        tg, sg, ag, zg = t[g], send[g], arrival[g], size[g]
        ooo = sg < self._g_first_send[tg]            # out-of-order: ignore
        in_group = ~ooo & (sg - self._g_first_send[tg] <= _BURST_SPAN_MS)
        ti = tg[in_group]
        self._g_send[ti] = np.maximum(self._g_send[ti], sg[in_group])
        self._g_arrival[ti] = ag[in_group]
        self._g_size[ti] += zg[in_group]

        closes = ~ooo & ~in_group
        tc = tg[closes]
        if len(tc):
            have_prev = self._p_has[tc]
            send_delta = self._g_send[tc] - self._p_send[tc]
            arr_delta = self._g_arrival[tc] - self._p_arrival[tc]
            size_delta = self._g_size[tc] - self._p_size[tc]
            filt = tc[have_prev & (send_delta >= 0)]
            fm = have_prev & (send_delta >= 0)
            # previous <- current, current <- new packet
            self._p_has[tc] = True
            self._p_send[tc] = self._g_send[tc]
            self._p_arrival[tc] = self._g_arrival[tc]
            self._p_size[tc] = self._g_size[tc]
            self._g_first_send[tc] = sg[closes]
            self._g_send[tc] = sg[closes]
            self._g_arrival[tc] = ag[closes]
            self._g_size[tc] = zg[closes]
            if len(filt):
                self._kalman_update(filt, arr_delta[fm], send_delta[fm],
                                    size_delta[fm].astype(np.float64))
                self._detect(filt, send_delta[fm], ag[closes][fm])

    # --------------------------------------------------------------- kalman
    def _kalman_update(self, t, t_delta, ts_delta, fs_delta) -> None:
        """OveruseEstimator.update, vectorized over the closing rows."""
        self.num_deltas[t] = np.minimum(self.num_deltas[t] + 1, 60)
        t_ts_delta = t_delta - ts_delta

        e00, e01 = self._e00[t], self._e01[t]
        e10, e11 = self._e10[t], self._e11[t]
        e00 = e00 + 1e-13
        e11 = e11 + 1e-3
        sig = self.signal[t]
        off = self.offset[t]
        unstable = ((sig == SIG_OVERUSING) & (off < 0)) | \
                   ((sig == SIG_UNDERUSING) & (off > 0))
        e11 = e11 + np.where(unstable, 10 * 1e-3, 0.0)

        h0, h1 = fs_delta, 1.0
        eh0 = e00 * h0 + e01 * h1
        eh1 = e10 * h0 + e11 * h1
        residual = t_ts_delta - self._slope[t] * h0 - off

        max_residual = 3.0 * np.sqrt(self._var_noise[t])
        in_stable = np.abs(residual) < max_residual
        shaped = np.where(in_stable, residual,
                          np.copysign(max_residual, residual))
        self._update_noise(t, ts_delta, shaped)

        denom = self._var_noise[t] + (h0 * eh0 + h1 * eh1)
        k0, k1 = eh0 / denom, eh1 / denom
        ikh00 = 1.0 - k0 * h0
        ikh01 = -k0 * h1
        ikh10 = -k1 * h0
        ikh11 = 1.0 - k1 * h1
        n00 = e00 * ikh00 + e10 * ikh01
        n01 = e01 * ikh00 + e11 * ikh01
        n10 = e00 * ikh10 + e10 * ikh11
        n11 = e01 * ikh10 + e11 * ikh11
        self._e00[t], self._e01[t] = n00, n01
        self._e10[t], self._e11[t] = n10, n11
        self._slope[t] += k0 * residual
        self.offset[t] = off + k1 * residual

    def _update_noise(self, t, ts_delta, residual) -> None:
        norm = self.signal[t] == SIG_NORMAL
        alpha = np.where(ts_delta > 0,
                         np.power(0.01, np.maximum(ts_delta, 0) / 30.0),
                         0.0)
        alpha = np.clip(alpha, 0.0, 1.0)
        avg = alpha * self._avg_noise[t] + (1 - alpha) * residual
        var = alpha * self._var_noise[t] + (1 - alpha) * (
            residual - avg) ** 2
        var = np.maximum(var, 1.0)
        self._avg_noise[t] = np.where(norm, avg, self._avg_noise[t])
        self._var_noise[t] = np.where(norm, var, self._var_noise[t])

    # -------------------------------------------------------------- detect
    def _detect(self, t, ts_delta, now_ms) -> None:
        nd = self.num_deltas[t]
        enough = nd >= 2
        tt = np.minimum(nd, 60) * self.offset[t]
        over = tt > self.threshold[t]
        under = tt < -self.threshold[t]

        tou = self._time_over_using[t]
        tou = np.where(over, np.where(tou == -1, ts_delta / 2,
                                      tou + ts_delta), -1.0)
        cnt = np.where(over, self._overuse_counter[t] + 1, 0)
        trip = over & (tou > self._overuse_time_th) & (cnt > 1)
        sig = self.signal[t]
        new_sig = np.where(trip, SIG_OVERUSING,
                           np.where(under, SIG_UNDERUSING,
                                    np.where(over, sig, SIG_NORMAL)))
        self._time_over_using[t] = np.where(enough, tou,
                                            self._time_over_using[t])
        self._overuse_counter[t] = np.where(enough, cnt,
                                            self._overuse_counter[t])
        self.signal[t] = np.where(enough, new_sig, sig).astype(np.int8)

        # adaptive threshold
        lu_orig = self._last_update_ms[t]
        lu = np.where(lu_orig < 0, now_ms, lu_orig)
        far = np.abs(tt) > self.threshold[t] + 15.0
        k = np.where(np.abs(tt) < self.threshold[t], 0.039, 0.0087)
        dt = np.clip(now_ms - lu, 0.0, 100.0)
        new_th = self.threshold[t] + k * (np.abs(tt)
                                          - self.threshold[t]) * dt
        new_th = np.clip(new_th, 6.0, 600.0)
        self.threshold[t] = np.where(enough & ~far, new_th,
                                     self.threshold[t])
        self._last_update_ms[t] = np.where(enough, now_ms, lu_orig)

    # ------------------------------------------------------------ rate win
    def _erase_old(self, t, now_ms) -> None:
        """Advance each transport's window edge to now-window+1,
        subtracting the outgoing buckets (vectorized form of the scalar
        _erase_old; the partial-erase loop is bounded by the largest
        advance, typically the tick interval in ms)."""
        seen = self._oldest_ms[t] >= 0
        new_oldest = np.asarray(now_ms) - self.window_ms + 1
        adv = np.where(seen,
                       np.clip(new_oldest - self._oldest_ms[t], 0, None),
                       0)
        full = adv >= self.window_ms
        ft = t[full]
        if len(ft):
            self._buckets[ft] = 0
            self._win_total[ft] = 0
        part = np.nonzero(~full & (adv > 0))[0]
        if len(part):
            # ragged zeroing: all outgoing buckets of all rows at once
            # (advance < window, so each row's range hits distinct slots)
            tp = t[part]
            start = self._oldest_ms[tp]
            n = np.asarray(adv[part], dtype=np.int64)
            offs = np.zeros(len(part), dtype=np.int64)
            np.cumsum(n[:-1], out=offs[1:])
            ar = (np.arange(int(n.sum()), dtype=np.int64)
                  - np.repeat(offs, n))
            flat = (np.repeat(tp, n) * np.int64(self.window_ms)
                    + (np.repeat(start, n) + ar) % self.window_ms)
            bf = self._buckets.reshape(-1)
            gone = bf[flat]
            self._win_total[tp] -= np.add.reduceat(gone, offs)
            bf[flat] = 0
        upd = adv > 0
        self._oldest_ms[t] = np.where(
            upd, np.broadcast_to(new_oldest, adv.shape),
            self._oldest_ms[t])

    def _rate_update(self, t, nbytes, now_ms) -> None:
        self._erase_old(t, now_ms)
        first = self._oldest_ms[t] < 0
        self._oldest_ms[t] = np.where(first, now_ms, self._oldest_ms[t])
        # late packet: fold into the oldest live bucket (scalar rule)
        now_eff = np.maximum(now_ms, self._oldest_ms[t])
        idx = now_eff % self.window_ms
        self._buckets[t, idx] += nbytes
        self._win_total[t] += nbytes

    def incoming_rate(self, now_ms: int) -> np.ndarray:
        """Windowed receive rate, bits/sec, all T transports (O(T) via
        the running totals; the erase keeps them window-exact)."""
        now_ms = int(now_ms)
        self._erase_old(np.arange(self.capacity), now_ms)
        seen = self._oldest_ms >= 0
        active = np.where(seen,
                          np.clip(now_ms - self._oldest_ms + 1, 1,
                                  self.window_ms),
                          1)
        return self._win_total * 8000.0 / active

    # ---------------------------------------------------------------- aimd
    def update_estimate(self, now_ms: float) -> np.ndarray:
        """Periodic GCC tick for every transport -> REMB bitrates [T]."""
        sig = self.signal
        st = self.rate_state.copy()
        st = np.where((sig == SIG_NORMAL) & (st == ST_HOLD),
                      ST_INCREASE, st)
        st = np.where(sig == SIG_OVERUSING, ST_DECREASE, st)
        st = np.where(sig == SIG_UNDERUSING, ST_HOLD, st)

        lc = self._last_change_ms
        lc = np.where(lc < 0, now_ms, lc)
        dt = now_ms - lc
        self._last_change_ms[:] = now_ms

        incoming = self.incoming_rate(int(now_ms))
        rate = self.bitrate.copy()

        inc = st == ST_INCREASE
        mul = inc & (self.region == RG_MULTIPLICATIVE)
        factor = np.minimum(np.power(1.08, np.minimum(dt / 1000.0, 1.0)),
                            1.5)
        rate = np.where(mul, rate * factor, rate)
        add = inc & (self.region == RG_ADDITIVE)
        response_ms = 100.0 + self.rtt_ms
        alpha = 0.5 * np.minimum(dt / response_ms, 1.0)
        rate = np.where(add, rate + np.maximum(1000.0, alpha * 8 * 1200),
                        rate)

        dec = st == ST_DECREASE
        rate = np.where(dec, _BETA * incoming, rate)
        # max-estimate EWMA on decrease
        sample = incoming / 1000.0
        d = self._avg_max_kbps < 0
        avg = np.where(d, sample,
                       0.95 * self._avg_max_kbps + 0.05 * sample)
        norm = np.maximum(avg, 1.0)
        dev = (sample - avg) ** 2 / norm
        var = np.clip(0.95 * self._var_max_kbps + 0.05 * dev, 0.4, 2.5)
        self._avg_max_kbps = np.where(dec, avg, self._avg_max_kbps)
        self._var_max_kbps = np.where(dec, var, self._var_max_kbps)
        self.region = np.where(dec, RG_ADDITIVE, self.region
                               ).astype(np.int8)
        st = np.where(dec, ST_HOLD, st)

        # back to multiplicative far above the max estimate
        has_max = self._avg_max_kbps >= 0
        sigma = np.sqrt(np.maximum(self._var_max_kbps
                                   * self._avg_max_kbps, 0.0))
        above = has_max & (rate / 1000.0
                           > self._avg_max_kbps + 3 * sigma)
        self.region = np.where(above, RG_MULTIPLICATIVE, self.region
                               ).astype(np.int8)
        self._avg_max_kbps = np.where(above, -1.0, self._avg_max_kbps)

        self.bitrate = np.clip(rate, self.min_bitrate, self.max_bitrate)
        self.rate_state = st.astype(np.int8)
        return self.bitrate

    # --------------------------------------------------------- checkpoint
    # (snapshot()/restore() from ArraySnapshotMixin; SURVEY §5: a
    # restarted worker must not re-probe bandwidth from the start
    # bitrate and overload already-congested links)
    _SNAP_FIELDS = (
        "_last_send", "_send_unwrapped", "_has_send", "_g_has",
        "_g_first_send", "_g_send", "_g_arrival", "_g_size", "_p_has",
        "_p_send", "_p_arrival", "_p_size", "offset", "_slope", "_e00",
        "_e01", "_e10", "_e11", "_avg_noise", "_var_noise", "num_deltas",
        "threshold", "_last_update_ms", "_time_over_using",
        "_overuse_counter", "signal", "bitrate", "rate_state", "region",
        "rtt_ms", "_avg_max_kbps", "_var_max_kbps", "_last_change_ms",
        "_buckets", "_win_total", "_oldest_ms")

    def _snap_scalars(self) -> dict:
        return {"window_ms": self.window_ms,
                "min_bitrate": self.min_bitrate,
                "max_bitrate": self.max_bitrate,
                "start_bitrate": self.start_bitrate}

    @classmethod
    def _restore_kwargs(cls, snap: dict) -> dict:
        return {"capacity": len(snap["offset"]),
                "min_bitrate_bps": snap["min_bitrate"],
                "max_bitrate_bps": snap["max_bitrate"],
                "start_bitrate_bps": snap.get("start_bitrate", 300_000),
                "window_ms": snap["window_ms"]}
