"""Send-side bandwidth estimation (reference:
`org.jitsi.impl.neomedia.rtp.sendsidebandwidthestimation.
{SendSideBandwidthEstimation,BandwidthEstimatorImpl}` — WebRTC's
loss-based controller):

- RTCP RR fraction-lost drives loss-based up/down moves;
- a delay-based estimate (from TCC feedback run through the same GCC
  filters as the receive side) caps the result;
- REMB from the remote receiver caps it too.
"""

from __future__ import annotations

from typing import Optional

from libjitsi_tpu.bwe.remote_estimator import RemoteBitrateEstimator
from libjitsi_tpu.rtp.rtcp import TccFeedback


class SendSideBandwidthEstimation:
    LOW_LOSS = 0.02
    HIGH_LOSS = 0.10

    def __init__(self, min_bitrate_bps: float = 30_000,
                 start_bitrate_bps: float = 300_000,
                 max_bitrate_bps: float = 30e6):
        self.min_bitrate = min_bitrate_bps
        self.max_bitrate = max_bitrate_bps
        self.bitrate = start_bitrate_bps
        self.remb_cap: Optional[float] = None
        self._last_decrease_ms = -1e18
        self._last_loss_ms = -1e18
        # smoothed reported loss: the BWE loss signal consumed by the
        # adaptive FEC sender (sfu/recovery.py) — same RR stream that
        # drives the loss-based rate moves below
        self.loss_estimate = 0.0
        self.last_fraction_lost = 0
        # delay-based estimator over TCC feedback (send times are ours,
        # arrival deltas are the remote's)
        self._delay = RemoteBitrateEstimator(min_bitrate_bps,
                                             start_bitrate_bps)
        self.delay_cap: Optional[float] = None

    # ------------------------------------------------------------- inputs
    def on_receiver_report(self, fraction_lost_255: int, now_ms: float
                           ) -> float:
        """Loss-based update from an RTCP RR (reference:
        SendSideBandwidthEstimation.updateReceiverBlock)."""
        loss = fraction_lost_255 / 255.0
        self.last_fraction_lost = int(fraction_lost_255) & 0xFF
        self.loss_estimate += 0.3 * (loss - self.loss_estimate)
        if loss < self.LOW_LOSS:
            # 8% per second, compounded by elapsed time
            dt = min(max(now_ms - self._last_loss_ms, 0.0), 1000.0) \
                if self._last_loss_ms > -1e17 else 1000.0
            self.bitrate *= 1.08 ** (dt / 1000.0)
            self.bitrate += 1000.0
        elif loss > self.HIGH_LOSS:
            if now_ms - self._last_decrease_ms > 300:
                self.bitrate *= (1 - 0.5 * loss)
                self._last_decrease_ms = now_ms
        self._last_loss_ms = now_ms
        return self._clamp()

    def on_remb(self, bitrate_bps: float) -> float:
        self.remb_cap = bitrate_bps
        return self._clamp()

    def on_tcc_feedback(self, fb: TccFeedback, send_times_ms, now_ms: float
                        ) -> float:
        """Delay-based update from transport-wide-cc feedback.

        send_times_ms: our recorded send time (ms) per seq in the
        feedback range (NaN/None where unknown) — from
        TransportCCEngine.lookup_send_time.
        """
        base_ms = fb.reference_time * 64.0
        for i, rec in enumerate(fb.received):
            if not rec:
                continue
            st = send_times_ms[i]
            if st is None:
                continue
            arrival = base_ms + fb.arrival_250us[i] * 0.25
            # reuse the GCC filter chain with real send times: feed the
            # 6.18 fixed-point encoding it expects
            ast24 = int((st / 1000.0) * (1 << 18)) & 0xFFFFFF
            self._delay.incoming_packet(arrival, ast24, 1200)
        self.delay_cap = self._delay.update_estimate(now_ms)
        return self._clamp()

    # ------------------------------------------------------------- output
    def _clamp(self) -> float:
        b = self.bitrate
        if self.remb_cap is not None:
            b = min(b, self.remb_cap)
        if self.delay_cap is not None:
            b = min(b, self.delay_cap)
        b = min(max(b, self.min_bitrate), self.max_bitrate)
        # floor the INTERNAL state too: sustained loss must not drive it
        # toward zero, or recovery would compound up from ~nothing
        self.bitrate = min(max(self.bitrate, self.min_bitrate),
                           self.max_bitrate)
        return b

    @property
    def estimate_bps(self) -> float:
        return self._clamp()
