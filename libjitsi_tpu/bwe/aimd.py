"""AIMD rate control (reference:
`...remotebitrateestimator.AimdRateControl` — WebRTC GCC's
increase/hold/decrease state machine)."""

from __future__ import annotations

from libjitsi_tpu.bwe.overuse import NORMAL, OVERUSING, UNDERUSING

HOLD, INCREASE, DECREASE = "hold", "increase", "decrease"
MULTIPLICATIVE, ADDITIVE = "multiplicative", "additive"

BETA = 0.85
DEFAULT_RTT_MS = 200.0


class AimdRateControl:
    def __init__(self, min_bitrate_bps: float = 30_000,
                 start_bitrate_bps: float = 300_000,
                 max_bitrate_bps: float = 30e6):
        self.min_bitrate = min_bitrate_bps
        self.max_bitrate = max_bitrate_bps
        self.bitrate = start_bitrate_bps
        self.state = HOLD
        self.region = MULTIPLICATIVE
        self.rtt_ms = DEFAULT_RTT_MS
        self._avg_max_bitrate_kbps = -1.0
        self._var_max_bitrate_kbps = 0.4
        self._last_change_ms = -1.0
        self._inited = False

    def set_rtt(self, rtt_ms: float) -> None:
        self.rtt_ms = rtt_ms

    def update(self, signal: str, incoming_bitrate_bps: float,
               now_ms: float) -> float:
        """One GCC tick: map the detector signal to the rate state
        machine and move the target bitrate."""
        # state transitions (reference: AimdRateControl.changeState)
        if signal == NORMAL:
            if self.state == HOLD:
                self.state = INCREASE
        elif signal == OVERUSING:
            self.state = DECREASE
        elif signal == UNDERUSING:
            self.state = HOLD

        if self._last_change_ms < 0:
            self._last_change_ms = now_ms
        dt = now_ms - self._last_change_ms
        self._last_change_ms = now_ms

        if self.state == INCREASE:
            if self.region == MULTIPLICATIVE:
                factor = min(1.08 ** min(dt / 1000.0, 1.0), 1.5)
                self.bitrate *= factor
            else:
                # additive: ~ one packet per response time
                response_ms = 100.0 + self.rtt_ms
                alpha = 0.5 * min(dt / response_ms, 1.0)
                packet_bits = 8 * 1200
                self.bitrate += max(1000.0, alpha * packet_bits)
            self._inited = True
        elif self.state == DECREASE:
            self.bitrate = BETA * incoming_bitrate_bps
            self._update_max_estimate(incoming_bitrate_bps / 1000.0)
            # near the observed max: switch to cautious additive increase
            self.region = ADDITIVE
            self.state = HOLD
        # hold: no change

        # switch back to multiplicative when far below the max estimate
        if self._avg_max_bitrate_kbps >= 0:
            sigma = (self._var_max_bitrate_kbps *
                     self._avg_max_bitrate_kbps) ** 0.5
            if self.bitrate / 1000.0 > self._avg_max_bitrate_kbps + 3 * sigma:
                self.region = MULTIPLICATIVE
                self._avg_max_bitrate_kbps = -1.0

        self.bitrate = min(max(self.bitrate, self.min_bitrate),
                           self.max_bitrate)
        return self.bitrate

    def _update_max_estimate(self, sample_kbps: float) -> None:
        alpha = 0.05
        if self._avg_max_bitrate_kbps < 0:
            self._avg_max_bitrate_kbps = sample_kbps
        else:
            self._avg_max_bitrate_kbps = (
                (1 - alpha) * self._avg_max_bitrate_kbps +
                alpha * sample_kbps)
        norm = max(self._avg_max_bitrate_kbps, 1.0)
        dev = (sample_kbps - self._avg_max_bitrate_kbps) ** 2 / norm
        self._var_max_bitrate_kbps = min(max(
            (1 - alpha) * self._var_max_bitrate_kbps + alpha * dev,
            0.4), 2.5)
