"""Inter-arrival burst grouping (reference:
`...remotebitrateestimator.InterArrival`, WebRTC GCC §5.2).

Packets whose send times fall in the same 5 ms window form one group;
the filterable signal is the per-group (send delta, arrival delta, size
delta) triple.  Out-of-order send times reset nothing — they are simply
ignored, as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

BURST_DELTA_THRESHOLD_MS = 5


@dataclasses.dataclass
class _Group:
    first_send_ms: float = 0.0
    send_ms: float = 0.0       # max send time in group
    arrival_ms: float = 0.0    # last arrival
    size: int = 0
    complete: bool = False


class InterArrival:
    def __init__(self, group_span_ms: float = BURST_DELTA_THRESHOLD_MS):
        self.span = group_span_ms
        self._cur: Optional[_Group] = None
        self._prev: Optional[_Group] = None

    def add(self, send_ms: float, arrival_ms: float, size: int
            ) -> Optional[Tuple[float, float, int]]:
        """Feed one packet; returns (send_delta_ms, arrival_delta_ms,
        size_delta) when a group completes, else None."""
        if self._cur is None:
            self._cur = _Group(send_ms, send_ms, arrival_ms, size)
            return None
        if send_ms < self._cur.first_send_ms:
            return None  # out-of-order send time: ignore
        if send_ms - self._cur.first_send_ms <= self.span:
            self._cur.send_ms = max(self._cur.send_ms, send_ms)
            self._cur.arrival_ms = arrival_ms
            self._cur.size += size
            return None
        # group completed
        out = None
        if self._prev is not None:
            send_delta = self._cur.send_ms - self._prev.send_ms
            arrival_delta = self._cur.arrival_ms - self._prev.arrival_ms
            size_delta = self._cur.size - self._prev.size
            if send_delta >= 0:
                out = (send_delta, arrival_delta, size_delta)
        self._prev = self._cur
        self._cur = _Group(send_ms, send_ms, arrival_ms, size)
        return out
