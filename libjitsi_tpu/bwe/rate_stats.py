"""Sliding-window rate counter (reference:
`org.jitsi.impl.neomedia.rtp.remotebitrateestimator.RateStatistics`, a
port of webrtc/modules/remote_bitrate_estimator's rate_statistics).
"""

from __future__ import annotations

import numpy as np


class RateStatistics:
    """Bytes-per-window -> bits/sec over a ms-bucketed circular window."""

    def __init__(self, window_ms: int = 1000, scale: float = 8000.0):
        self.window_ms = window_ms
        self.scale = scale  # converts bytes/window to bits/sec
        self._buckets = np.zeros(window_ms, dtype=np.int64)
        self._total = 0
        self._oldest_ms = -1

    def update(self, nbytes: int, now_ms: int) -> None:
        if self._oldest_ms < 0:
            self._oldest_ms = now_ms
        self._erase_old(now_ms)
        if now_ms < self._oldest_ms:       # very late packet: fold into oldest
            now_ms = self._oldest_ms
        self._buckets[now_ms % self.window_ms] += nbytes
        self._total += nbytes

    def rate(self, now_ms: int) -> float:
        """Current rate in bits/sec."""
        self._erase_old(now_ms)
        active = min(max(now_ms - self._oldest_ms + 1, 1), self.window_ms) \
            if self._oldest_ms >= 0 else 1
        return self._total * self.scale / active

    def _erase_old(self, now_ms: int) -> None:
        if self._oldest_ms < 0:
            return
        new_oldest = now_ms - self.window_ms + 1
        if new_oldest <= self._oldest_ms:
            return
        if new_oldest - self._oldest_ms >= self.window_ms:
            self._buckets[:] = 0
            self._total = 0
        else:
            for t in range(self._oldest_ms, new_oldest):
                b = t % self.window_ms
                self._total -= self._buckets[b]
                self._buckets[b] = 0
        self._oldest_ms = new_oldest
