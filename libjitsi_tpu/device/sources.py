"""Synthetic capture sources — the server-side replacement for hardware.

The reference's L3 device layer (SURVEY §2.5) discovers microphones and
cameras through PortAudio/WASAPI/CoreAudio/V4L2 JNI backends; a server-side
TPU framework has none of those, so the survey's stated obligation is
"synthetic sources/sinks (file, PRNG, socket)".  The reference itself ships
the same idea as its CI/offline fixtures (SURVEY §4):

- `...jmfext.media.protocol.audiosilence.DataSource` — a silent capture
  device used when no hardware exists -> `SilenceSource`.
- `...jmfext.media.protocol.rtpdumpfile.DataSource` — plays recorded
  rtpdump traces as a fake capture device -> `RtpdumpCaptureDevice`.
- `...jmfext.media.protocol.ivffile.DataSource` — plays IVF (VP8) files as
  a fake camera -> `IvfReader` (+ `IvfWriter` to author fixtures).

Audio sources produce mono int16 PCM via ``read(n) -> np.ndarray [n]`` and
never block or run dry (silence-pad / loop), matching the reference's
capture `PushBufferStream.read(Buffer)` contract where a stalled device
pads silence rather than stalling the Processor graph.
"""

from __future__ import annotations

import struct
import wave
from typing import Iterator, List, Optional, Tuple

import numpy as np


class AudioSource:
    """Base: mono int16 PCM pull source."""

    sample_rate: int = 48000
    channels: int = 1

    def read(self, n: int) -> np.ndarray:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class SilenceSource(AudioSource):
    """All-zero PCM (reference: the `audiosilence` capture device)."""

    def __init__(self, sample_rate: int = 48000):
        self.sample_rate = sample_rate

    def read(self, n: int) -> np.ndarray:
        return np.zeros(n, dtype=np.int16)


class ToneSource(AudioSource):
    """Continuous-phase sine generator (test signal / notification tone).

    Stands in for the reference's `audionotifier` sound playback as a
    signal generator; also the standard SNR fixture for codec tests.
    """

    def __init__(self, freq_hz: float = 440.0, amplitude: float = 0.5,
                 sample_rate: int = 48000):
        self.freq_hz = float(freq_hz)
        self.amplitude = float(amplitude)
        self.sample_rate = sample_rate
        self._phase = 0.0

    def read(self, n: int) -> np.ndarray:
        w = 2.0 * np.pi * self.freq_hz / self.sample_rate
        t = self._phase + w * np.arange(n)
        self._phase = float((self._phase + w * n) % (2.0 * np.pi))
        return np.round(self.amplitude * 32767.0 * np.sin(t)).astype(np.int16)


class NoiseSource(AudioSource):
    """Seeded PRNG PCM (the survey's "PRNG source"); deterministic."""

    def __init__(self, seed: int = 0, amplitude: float = 0.25,
                 sample_rate: int = 48000):
        self._rng = np.random.default_rng(seed)
        self.amplitude = float(amplitude)
        self.sample_rate = sample_rate

    def read(self, n: int) -> np.ndarray:
        span = int(self.amplitude * 32767)
        return self._rng.integers(-span, span + 1, n).astype(np.int16)


class PcmFileSource(AudioSource):
    """Raw s16le or WAV file as a capture device; loops or silence-pads.

    The file analog of the reference's rtpdumpfile fixture for plain PCM:
    feed recorded audio through the pipeline without hardware.
    """

    def __init__(self, path: str, sample_rate: int = 48000,
                 loop: bool = False):
        self.loop = loop
        if path.endswith(".wav"):
            with wave.open(path, "rb") as w:
                if w.getsampwidth() != 2:
                    raise ValueError("only 16-bit WAV supported")
                self.sample_rate = w.getframerate()
                raw = w.readframes(w.getnframes())
                pcm = np.frombuffer(raw, dtype="<i2")
                if w.getnchannels() > 1:  # downmix to mono
                    pcm = pcm.reshape(-1, w.getnchannels()).mean(
                        axis=1).astype(np.int16)
        else:
            self.sample_rate = sample_rate
            pcm = np.fromfile(path, dtype="<i2")
        self._pcm = np.ascontiguousarray(pcm, dtype=np.int16)
        self._pos = 0

    def read(self, n: int) -> np.ndarray:
        out = np.zeros(n, dtype=np.int16)
        got = 0
        while got < n:
            avail = len(self._pcm) - self._pos
            if avail <= 0:
                if not self.loop or len(self._pcm) == 0:
                    break  # silence-pad the tail
                self._pos = 0
                continue
            take = min(n - got, avail)
            out[got:got + take] = self._pcm[self._pos:self._pos + take]
            self._pos += take
            got += take
        return out


class MixerCaptureSource(AudioSource):
    """A participant's mix-minus output as a capture source.

    Reference: `AudioMixerMediaDevice` presents the conference mix as a
    JMF capture device so a MediaStream can use the mix as its input;
    here the device/system.py AudioMixerMediaDevice deposits each tick's
    per-participant output and this source replays row `sid`.
    """

    def __init__(self, device, sid: int, sample_rate: int = 48000):
        self._device = device
        self.sid = sid
        self.sample_rate = sample_rate
        self._buf = np.zeros(0, dtype=np.int16)

    def read(self, n: int) -> np.ndarray:
        while len(self._buf) < n:
            frame = self._device.pull_frame(self.sid)
            if frame is None:
                break
            self._buf = np.concatenate([self._buf, frame])
        out = np.zeros(n, dtype=np.int16)
        take = min(n, len(self._buf))
        out[:take] = self._buf[:take]
        self._buf = self._buf[take:]
        return out


# ------------------------------------------------------------ rtpdump ----


class RtpdumpCaptureDevice:
    """Paced replay of an rtpdump trace as a packet capture device.

    Reference: `...jmfext.media.protocol.rtpdumpfile.DataSource` — the
    standard way to exercise the RTP pipeline offline.  `due(now_ms)`
    returns every packet whose record offset has elapsed — now_ms is
    **milliseconds since the start of the trace**, not wall clock; a
    host loop ticks it on its own relative clock.  `loop=True` rewinds
    with a timestamp shift the way the reference's RtpdumpFileReader
    restarts; `max_packets` bounds one call so a huge now_ms jump on a
    looping trace cannot materialize unbounded packets.
    """

    def __init__(self, path: str, loop: bool = False,
                 max_packets: int = 1000):
        from libjitsi_tpu.io.pcap import RtpdumpReader

        self._path = path
        self.loop = loop
        self.max_packets = max_packets
        self._reader = RtpdumpReader(path)
        self._it: Iterator[Tuple[int, bytes]] = iter(self._reader)
        self._pending: Optional[Tuple[int, bytes]] = None
        self._epoch_ms = 0  # added to record offsets after each rewind
        self._last_off = 0

    def _next_record(self) -> Optional[Tuple[int, bytes]]:
        from libjitsi_tpu.io.pcap import RtpdumpReader

        rec = next(self._it, None)
        if rec is None and self.loop:
            self._reader.close()
            self._epoch_ms += self._last_off
            self._reader = RtpdumpReader(self._path)
            self._it = iter(self._reader)
            rec = next(self._it, None)
        if rec is None:
            return None
        self._last_off = rec[0]
        return rec[0] + self._epoch_ms, rec[1]

    def due(self, now_ms: int) -> List[bytes]:
        out: List[bytes] = []
        while len(out) < self.max_packets:
            rec = self._pending or self._next_record()
            self._pending = None
            if rec is None:
                return out
            off, pkt = rec
            if off > now_ms:
                self._pending = rec
                return out
            out.append(pkt)
        return out

    def close(self) -> None:
        self._reader.close()


# ---------------------------------------------------------------- IVF ----

_IVF_HDR = struct.Struct("<4sHH4sHHIII4x")   # DKIF header, 32 bytes
_IVF_FRAME = struct.Struct("<IQ")            # size, pts


class IvfWriter:
    """Author IVF (VP8/VP9) fixture files (reference: ivffile devices)."""

    def __init__(self, path: str, width: int, height: int,
                 fourcc: bytes = b"VP80", timebase: Tuple[int, int] = (1, 30)):
        self._f = open(path, "wb")
        self._count = 0
        self._head = (width, height, fourcc, timebase)
        self._write_header()

    def _write_header(self) -> None:
        w, h, fourcc, (num, den) = self._head
        self._f.seek(0)
        self._f.write(_IVF_HDR.pack(b"DKIF", 0, 32, fourcc, w, h, den, num,
                                    self._count))

    def write(self, frame: bytes, pts: int) -> None:
        self._f.seek(0, 2)
        self._f.write(_IVF_FRAME.pack(len(frame), pts))
        self._f.write(frame)
        self._count += 1

    def close(self) -> None:
        self._write_header()  # patch the frame count
        self._f.close()


class IvfReader:
    """Iterate (pts, frame_bytes) from an IVF file; a fake camera.

    Reference: `...jmfext.media.protocol.ivffile.DataSource` plays IVF
    VP8 streams as a capture device for video-pipeline tests.
    """

    def __init__(self, path: str):
        self._f = open(path, "rb")
        head = self._f.read(32)
        if len(head) < 32 or head[:4] != b"DKIF":
            raise ValueError("not an IVF file")
        (_, _, hdr_len, self.fourcc, self.width, self.height, self.tb_den,
         self.tb_num, self.frame_count) = _IVF_HDR.unpack(head)
        self._f.seek(hdr_len)

    def __iter__(self) -> Iterator[Tuple[int, bytes]]:
        while True:
            h = self._f.read(12)
            if len(h) < 12:
                return
            size, pts = _IVF_FRAME.unpack(h)
            payload = self._f.read(size)
            if len(payload) < size:
                return  # truncated final frame: don't hand fragments on
            yield pts, payload

    def close(self) -> None:
        self._f.close()
