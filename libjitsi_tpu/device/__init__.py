"""Synthetic device framework (SURVEY §2.5 L3 obligation).

Reference: `org.jitsi.impl.neomedia.device.*` + the offline fixture
protocols (`audiosilence`, `rtpdumpfile`, `ivffile`).  See system.py.
"""

from libjitsi_tpu.device.sinks import (AudioSink, NullSink, PcmFileSink,
                                       WavFileSink)
from libjitsi_tpu.device.sources import (AudioSource, IvfReader, IvfWriter,
                                         MixerCaptureSource, NoiseSource,
                                         PcmFileSource, RtpdumpCaptureDevice,
                                         SilenceSource, ToneSource)
from libjitsi_tpu.device.system import (AudioMixerMediaDevice, AudioSystem,
                                        DataFlow, DeviceSystem, MediaDevice)

__all__ = [
    "AudioSource", "SilenceSource", "ToneSource", "NoiseSource",
    "PcmFileSource", "MixerCaptureSource", "RtpdumpCaptureDevice",
    "IvfReader", "IvfWriter",
    "AudioSink", "NullSink", "PcmFileSink", "WavFileSink",
    "DataFlow", "MediaDevice", "AudioSystem", "DeviceSystem",
    "AudioMixerMediaDevice",
]
