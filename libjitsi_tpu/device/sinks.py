"""Playback sinks — the server-side replacement for renderers.

The reference renders audio through PortAudio/WASAPI/CoreAudio
`Renderer` plugins (SURVEY §2.5); on a server the "speaker" is a file,
a socket, or nothing.  Sinks accept mono int16 PCM via ``write(pcm)``.
"""

from __future__ import annotations

import wave
from typing import Optional

import numpy as np


class AudioSink:
    sample_rate: int = 48000

    def write(self, pcm: np.ndarray) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSink(AudioSink):
    """Discard (the reference's null renderer when no playback device)."""

    def __init__(self, sample_rate: int = 48000):
        self.sample_rate = sample_rate
        self.samples_written = 0

    def write(self, pcm: np.ndarray) -> None:
        self.samples_written += len(pcm)


class PcmFileSink(AudioSink):
    """Raw s16le file sink."""

    def __init__(self, path: str, sample_rate: int = 48000):
        self.sample_rate = sample_rate
        self._f = open(path, "wb")

    def write(self, pcm: np.ndarray) -> None:
        self._f.write(np.asarray(pcm, dtype="<i2").tobytes())

    def close(self) -> None:
        self._f.close()


class WavFileSink(AudioSink):
    """WAV file sink (16-bit mono) for human-auditable test output."""

    def __init__(self, path: str, sample_rate: int = 48000):
        self.sample_rate = sample_rate
        self._w: Optional[wave.Wave_write] = wave.open(path, "wb")
        self._w.setnchannels(1)
        self._w.setsampwidth(2)
        self._w.setframerate(sample_rate)

    def write(self, pcm: np.ndarray) -> None:
        self._w.writeframes(np.asarray(pcm, dtype="<i2").tobytes())

    def close(self) -> None:
        if self._w is not None:
            self._w.close()
            self._w = None
