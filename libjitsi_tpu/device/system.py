"""Device framework — discovery, selection, hotplug for synthetic devices.

Rebuilds the shape of the reference's L3 device layer
(`org.jitsi.impl.neomedia.device.{DeviceSystem,AudioSystem,
MediaDeviceImpl,DeviceConfiguration}`, SURVEY §2.5) for a server: devices
are synthetic (silence/tone/noise/file/rtpdump/ivf — see sources.py), but
the framework semantics match the reference:

- `DeviceSystem.initialize_device_systems()` scans/registers systems and
  can re-initialize (the reference's hotplug path re-runs `initialize()`
  and fires property-change events; SURVEY §5 "failure detection" row).
- `AudioSystem` tracks a device list per role (CAPTURE / PLAYBACK /
  NOTIFY — the reference AudioSystem's three `DataFlow`s) with the
  selected device persisted through the ConfigurationService the way
  `DeviceConfiguration` persists `net.java.sip.communicator.*` keys.
- `MediaDevice` is the factory handle streams consume
  (`org.jitsi.service.neomedia.device.MediaDevice`): direction +
  media_type + `create_source()/create_sink()`.
- `AudioMixerMediaDevice` presents the conference mix as a capture
  device (`org.jitsi.impl.neomedia.device.AudioMixerMediaDevice`).
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from libjitsi_tpu.core.config import ConfigurationService
from libjitsi_tpu.device import sinks as _sinks
from libjitsi_tpu.device import sources as _sources


class DataFlow(enum.Enum):
    """Reference: AudioSystem.DataFlow — the three audio roles."""

    CAPTURE = "capture"
    PLAYBACK = "playback"
    NOTIFY = "notify"


class MediaDevice:
    """A named device handle: factory for sources (capture) / sinks
    (playback).  Reference: MediaDeviceImpl wrapping a JMF CaptureDeviceInfo.
    """

    def __init__(self, name: str, media_type: str = "audio",
                 direction: str = "sendrecv",
                 source_factory: Optional[Callable[[], object]] = None,
                 sink_factory: Optional[Callable[[], object]] = None):
        self.name = name
        self.media_type = media_type
        self.direction = direction
        self._source_factory = source_factory
        self._sink_factory = sink_factory

    def create_source(self):
        if self._source_factory is None:
            raise ValueError(f"device {self.name!r} is not a capture device")
        return self._source_factory()

    def create_sink(self):
        if self._sink_factory is None:
            raise ValueError(f"device {self.name!r} is not a playback device")
        return self._sink_factory()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"MediaDevice({self.name!r}, {self.media_type}, {self.direction})"


class AudioSystem:
    """Synthetic audio system: device lists per role + persisted selection.

    Reference: `org.jitsi.impl.neomedia.device.AudioSystem` (one per
    backend — portaudio, wasapi, ...); ours is the single "synthetic"
    backend.  Selection is stored under
    ``libjitsi_tpu.devices.audio.<role>`` mirroring DeviceConfiguration's
    property persistence, so a restart restores the same device.
    """

    CONFIG_PREFIX = "libjitsi_tpu.devices.audio"

    def __init__(self, config: ConfigurationService):
        self.config = config
        self._devices: Dict[DataFlow, List[MediaDevice]] = {
            f: [] for f in DataFlow}
        # app-registered devices survive re-initialization: unlike real
        # hardware they cannot be re-discovered by a scan, so a hotplug
        # rescan must not silently drop them (and their selection)
        self._app_devices: List[Tuple[MediaDevice, DataFlow]] = []
        self._listeners: List[Callable[[str], None]] = []
        self._in_builtin_scan = False
        self.initialize()

    # -- discovery ----------------------------------------------------

    def initialize(self) -> None:
        """(Re-)scan devices; reference AudioSystem.initialize() — the
        hotplug path calls this again and listeners hear about it."""
        for f in DataFlow:
            self._devices[f] = []
        self._register_builtins()
        for dev, flow in self._app_devices:
            self._devices[flow].append(dev)
        self._fire("initialized")

    def _register_builtins(self) -> None:
        self._in_builtin_scan = True
        try:
            self._do_register_builtins()
        finally:
            self._in_builtin_scan = False

    def _do_register_builtins(self) -> None:
        self.add_device(MediaDevice(
            "silence", "audio", "sendonly",
            source_factory=_sources.SilenceSource), DataFlow.CAPTURE)
        self.add_device(MediaDevice(
            "tone:440", "audio", "sendonly",
            source_factory=lambda: _sources.ToneSource(440.0)),
            DataFlow.CAPTURE)
        self.add_device(MediaDevice(
            "noise", "audio", "sendonly",
            source_factory=lambda: _sources.NoiseSource(0)),
            DataFlow.CAPTURE)
        null = MediaDevice("null", "audio", "recvonly",
                           sink_factory=_sinks.NullSink)
        self.add_device(null, DataFlow.PLAYBACK)
        self.add_device(null, DataFlow.NOTIFY)

    def add_device(self, device: MediaDevice, flow: DataFlow) -> None:
        """Register a device (tests/apps add file/rtpdump devices); the
        reference's CaptureDeviceListManager.add analog."""
        self._devices[flow].append(device)
        if not self._in_builtin_scan:
            self._app_devices.append((device, flow))
        self._fire(f"added:{flow.value}:{device.name}")

    def remove_device(self, name: str, flow: DataFlow) -> None:
        """Unplug (reference: hotplug removal events)."""
        self._devices[flow] = [d for d in self._devices[flow]
                               if d.name != name]
        self._app_devices = [(d, f) for d, f in self._app_devices
                             if not (f == flow and d.name == name)]
        if self.config.get_string(f"{self.CONFIG_PREFIX}.{flow.value}") \
                == name:
            self.config.remove(f"{self.CONFIG_PREFIX}.{flow.value}")
        self._fire(f"removed:{flow.value}:{name}")

    def devices(self, flow: DataFlow) -> List[MediaDevice]:
        return list(self._devices[flow])

    # -- selection ----------------------------------------------------

    def set_selected_device(self, flow: DataFlow, name: str) -> None:
        if not any(d.name == name for d in self._devices[flow]):
            raise KeyError(f"no {flow.value} device named {name!r}")
        self.config.set(f"{self.CONFIG_PREFIX}.{flow.value}", name)
        self._fire(f"selected:{flow.value}:{name}")

    def selected_device(self, flow: DataFlow) -> Optional[MediaDevice]:
        """Configured device, else the first registered (the reference
        falls back to the backend's default device)."""
        want = self.config.get_string(f"{self.CONFIG_PREFIX}.{flow.value}")
        devs = self._devices[flow]
        for d in devs:
            if d.name == want:
                return d
        return devs[0] if devs else None

    # -- events -------------------------------------------------------

    def add_listener(self, cb: Callable[[str], None]) -> None:
        self._listeners.append(cb)

    def _fire(self, event: str) -> None:
        for cb in list(self._listeners):
            cb(event)


class DeviceSystem:
    """Top-level registry of per-media-type systems.

    Reference: `DeviceSystem.initializeDeviceSystems(MediaType)` called
    from MediaServiceImpl's ctor (SURVEY §3.1).  Video capture is file-
    based only (IVF via sources.IvfReader); there is no camera system.
    """

    def __init__(self, config: ConfigurationService):
        self.config = config
        self.audio = AudioSystem(config)

    def reinitialize(self) -> None:
        """Hotplug analog: rescan all systems."""
        self.audio.initialize()


class AudioMixerMediaDevice:
    """The conference mix exposed as a capture device.

    Reference: `org.jitsi.impl.neomedia.device.AudioMixerMediaDevice` —
    a MediaStream whose device is the mixer captures the mix-minus of
    everyone else.  Tick flow here: deposit each participant's decoded
    frame (`push`), run `tick()` once per frame period, then each
    participant's `MixerCaptureSource` (from `capture_for`) pulls its own
    mix-minus row.
    """

    # bound on queued un-pulled frames per participant: an abandoned
    # consumer must not leak a frame per tick forever (50 Hz * days)
    MAX_QUEUED_FRAMES = 50

    def __init__(self, mixer):
        self.mixer = mixer
        self._out: Dict[int, List[np.ndarray]] = {}

    def add_participant(self, sid: int) -> None:
        self.mixer.add_participant(sid)
        self._out.setdefault(sid, [])

    def remove_participant(self, sid: int) -> None:
        self.mixer.remove_participant(sid)
        self._out.pop(sid, None)

    def push(self, sid: int, pcm: np.ndarray) -> None:
        self.mixer.push(sid, pcm)

    def tick(self):
        """One frame period: mix and queue per-participant output.
        Returns (out [N, F] int16, levels uint8 [N]) for observability."""
        out, levels = self.mixer.mix()
        for sid, q in self._out.items():
            # copy: a row view would pin the whole [capacity, F] tick
            # array alive for as long as it sits in the queue
            q.append(out[sid].copy())
            if len(q) > self.MAX_QUEUED_FRAMES:
                del q[0]          # drop oldest: late consumer hears "now"
        return out, levels

    def pull_frame(self, sid: int) -> Optional[np.ndarray]:
        q = self._out.get(sid)
        return q.pop(0) if q else None

    def capture_for(self, sid: int) -> _sources.MixerCaptureSource:
        if sid not in self._out:
            self.add_participant(sid)
        return _sources.MixerCaptureSource(self, sid)
