"""ctypes binding for the C++ OpenSSL differential oracle.

SURVEY §2.6-1 names a native OpenSSL fallback beside the device crypto;
`crypto_oracle.cpp` is that twin — the same libcrypto.so.3 the
`cryptography` package wraps, reached through a C++ shim instead of a
Python binding.  tests/test_native_oracle.py differential-checks the
TPU kernels against it (test_srtp.py covers the Python-binding oracle),
pinning the kernels to OpenSSL itself.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    so = os.path.join(_NATIVE_DIR, "libcrypto_oracle.so")
    if not os.path.exists(so):
        r = subprocess.run(
            ["sh", os.path.join(_NATIVE_DIR, "build.sh"), "oracle"],
            capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"crypto oracle build failed:\n{r.stderr}")
    lib = ctypes.CDLL(so)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.oracle_aes_ctr.restype = ctypes.c_int
    lib.oracle_aes_ctr.argtypes = [u8p, ctypes.c_int, u8p, u8p,
                                   ctypes.c_int, u8p]
    lib.oracle_hmac_sha1.restype = ctypes.c_int
    lib.oracle_hmac_sha1.argtypes = [u8p, ctypes.c_int, u8p,
                                     ctypes.c_int, u8p]
    lib.oracle_gcm_seal.restype = ctypes.c_int
    lib.oracle_gcm_seal.argtypes = [u8p, u8p, u8p, ctypes.c_int, u8p,
                                    ctypes.c_int, u8p, u8p]
    _lib = lib
    return lib


def _buf(data: bytes):
    return (ctypes.c_uint8 * max(1, len(data))).from_buffer_copy(
        data or b"\x00")


def aes_ctr(key: bytes, iv16: bytes, data: bytes) -> bytes:
    lib = _load()
    out = (ctypes.c_uint8 * max(1, len(data)))()
    rc = lib.oracle_aes_ctr(_buf(key), len(key), _buf(iv16), _buf(data),
                            len(data), out)
    if rc != 0:
        raise RuntimeError(f"oracle_aes_ctr rc={rc}")
    return bytes(out[:len(data)])


def hmac_sha1(key: bytes, msg: bytes) -> bytes:
    lib = _load()
    out = (ctypes.c_uint8 * 20)()
    rc = lib.oracle_hmac_sha1(_buf(key), len(key), _buf(msg), len(msg),
                              out)
    if rc != 0:
        raise RuntimeError(f"oracle_hmac_sha1 rc={rc}")
    return bytes(out)


def gcm_seal(key16: bytes, iv12: bytes, aad: bytes,
             plaintext: bytes) -> tuple:
    """Returns (ciphertext, tag16)."""
    lib = _load()
    ct = (ctypes.c_uint8 * max(1, len(plaintext)))()
    tag = (ctypes.c_uint8 * 16)()
    rc = lib.oracle_gcm_seal(_buf(key16), _buf(iv12), _buf(aad),
                             len(aad), _buf(plaintext), len(plaintext),
                             ct, tag)
    if rc != 0:
        raise RuntimeError(f"oracle_gcm_seal rc={rc}")
    return bytes(ct[:len(plaintext)]), bytes(tag)
