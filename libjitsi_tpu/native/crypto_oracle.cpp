// C++ OpenSSL differential oracle (SURVEY §2.6-1's "C++ OpenSSL
// fallback" row): one-shot AES-CTR / HMAC-SHA1 / AES-GCM primitives
// backed by the SAME libcrypto the reference's JNI provider wraps,
// exposed as a C ABI for ctypes.  This is the native twin of the
// Python `cryptography`-package oracle the tests already use — both
// call into libcrypto.so.3, so agreement between the TPU kernels and
// BOTH oracles pins the kernels to OpenSSL itself, not to a Python
// binding's interpretation of it.
//
// The image ships libcrypto.so.3 but no OpenSSL headers; the EVP/HMAC
// entry points below are OpenSSL 3.x's stable public C ABI, declared
// here verbatim from the documented signatures.

#include <cstdint>
#include <cstring>

extern "C" {
// ---- libcrypto 3.x public ABI (subset) ----
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;

EVP_CIPHER_CTX *EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX *);
int EVP_EncryptInit_ex(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                       const unsigned char *key, const unsigned char *iv);
int EVP_EncryptUpdate(EVP_CIPHER_CTX *, unsigned char *out, int *outl,
                      const unsigned char *in, int inl);
int EVP_EncryptFinal_ex(EVP_CIPHER_CTX *, unsigned char *out, int *outl);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX *, int type, int arg, void *ptr);
const EVP_CIPHER *EVP_aes_128_ctr(void);
const EVP_CIPHER *EVP_aes_256_ctr(void);
const EVP_CIPHER *EVP_aes_128_gcm(void);
const EVP_MD *EVP_sha1(void);
unsigned char *HMAC(const EVP_MD *, const void *key, int key_len,
                    const unsigned char *data, size_t data_len,
                    unsigned char *md, unsigned int *md_len);

#define EVP_CTRL_AEAD_GET_TAG 0x10

// ------------------------------------------------------------- oracle

// AES-CTR keystream-encrypt `n` bytes (128- or 256-bit key by keylen).
// Returns 0 on success.
int oracle_aes_ctr(const uint8_t *key, int keylen, const uint8_t iv[16],
                   const uint8_t *in, int n, uint8_t *out) {
    const EVP_CIPHER *c =
        keylen == 16 ? EVP_aes_128_ctr()
                     : (keylen == 32 ? EVP_aes_256_ctr() : nullptr);
    if (!c) return -1;
    EVP_CIPHER_CTX *ctx = EVP_CIPHER_CTX_new();
    if (!ctx) return -2;
    int rc = -3, outl = 0, fin = 0;
    if (EVP_EncryptInit_ex(ctx, c, nullptr, key, iv) == 1 &&
        EVP_EncryptUpdate(ctx, out, &outl, in, n) == 1 &&
        EVP_EncryptFinal_ex(ctx, out + outl, &fin) == 1 &&
        outl + fin == n)
        rc = 0;
    EVP_CIPHER_CTX_free(ctx);
    return rc;
}

// HMAC-SHA1 of `n` bytes; writes 20 bytes.  Returns 0 on success.
int oracle_hmac_sha1(const uint8_t *key, int keylen, const uint8_t *msg,
                     int n, uint8_t out[20]) {
    unsigned int len = 0;
    if (!HMAC(EVP_sha1(), key, keylen, msg, (size_t)n, out, &len))
        return -1;
    return len == 20 ? 0 : -2;
}

// AES-128-GCM seal: ct[n] + tag[16].  Returns 0 on success.
int oracle_gcm_seal(const uint8_t *key, const uint8_t iv[12],
                    const uint8_t *aad, int aadlen, const uint8_t *pt,
                    int n, uint8_t *ct, uint8_t tag[16]) {
    EVP_CIPHER_CTX *ctx = EVP_CIPHER_CTX_new();
    if (!ctx) return -2;
    int rc = -3, outl = 0, fin = 0, aadl = 0;
    if (EVP_EncryptInit_ex(ctx, EVP_aes_128_gcm(), nullptr, key, iv) == 1 &&
        (aadlen == 0 ||
         EVP_EncryptUpdate(ctx, nullptr, &aadl, aad, aadlen) == 1) &&
        EVP_EncryptUpdate(ctx, ct, &outl, pt, n) == 1 &&
        EVP_EncryptFinal_ex(ctx, ct + outl, &fin) == 1 &&
        outl + fin == n &&
        EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_AEAD_GET_TAG, 16, tag) == 1)
        rc = 0;
    EVP_CIPHER_CTX_free(ctx);
    return rc;
}

}  // extern "C"
