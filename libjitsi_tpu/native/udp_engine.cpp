// Batched UDP I/O engine for the host data plane.
//
// The reference's packet I/O is java.net sockets with one thread per
// connector stream (org.jitsi.impl.neomedia.RTPConnectorUDPImpl et al.);
// at 10k streams that design melts.  This engine is the TPU-native
// replacement (SURVEY §2.6 item 12): recvmmsg/sendmmsg syscall batching,
// SO_REUSEPORT fan-in, and a receive buffer whose memory layout IS the
// framework's PacketBatch struct-of-arrays ([max_pkts, capacity] uint8
// matrix + int32 length vector) so datagrams land ready for the device
// with zero repacking.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

extern "C" {

// Create a bound UDP socket.  reuseport != 0 enables SO_REUSEPORT so N
// engine instances can share one port (kernel-level stream sharding).
// Returns fd >= 0 or -errno.
int udp_create(const char *bind_ip, uint16_t port, int reuseport,
               int rcvbuf_bytes) {
  int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (reuseport) setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  if (rcvbuf_bytes > 0)
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf_bytes, sizeof(rcvbuf_bytes));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr = bind_ip ? inet_addr(bind_ip) : INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return fd;
}

int udp_close(int fd) { return close(fd); }

// Enable kernel receive timestamps (SO_TIMESTAMPNS).  The BWE
// inter-arrival filters (GCC) react to sub-millisecond queueing-delay
// gradients; userspace arrival times include scheduler jitter that the
// kernel stamp (taken at skb receive) does not.  Returns 0 or -errno.
int udp_enable_timestamps(int fd) {
  int one = 1;
  if (setsockopt(fd, SOL_SOCKET, SO_TIMESTAMPNS, &one, sizeof(one)) < 0)
    return -errno;
  return 0;
}

// Get the locally bound port (for port-0 ephemeral binds in tests).
int udp_local_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &len) < 0)
    return -errno;
  return ntohs(addr.sin_port);
}

// (see udp_recv_batch_ts below; this entry point keeps the original
// ABI and simply skips the timestamp plumbing)
int udp_recv_batch_ts(int fd, uint8_t *buf, int capacity, int max_pkts,
                      int32_t *lengths, uint32_t *src_ip,
                      uint16_t *src_port, int64_t *arrival_ns,
                      int timeout_ms);

// Batched receive via recvmmsg into the caller's [max_pkts, capacity]
// row-major buffer; writes per-packet lengths, source ip4 (host order)
// and ports.  Waits up to timeout_ms for the FIRST packet, then drains
// whatever is immediately available (the batching-window pattern: the
// caller controls latency by the timeout, throughput by max_pkts).
// Returns number of packets, 0 on timeout, -errno on error.
int udp_recv_batch(int fd, uint8_t *buf, int capacity, int max_pkts,
                   int32_t *lengths, uint32_t *src_ip, uint16_t *src_port,
                   int timeout_ms) {
  return udp_recv_batch_ts(fd, buf, capacity, max_pkts, lengths, src_ip,
                           src_port, nullptr, timeout_ms);
}

// Timestamped batched receive: like udp_recv_batch, and when
// arrival_ns != nullptr also writes per-packet kernel arrival times
// (CLOCK_REALTIME nanoseconds).  Packets without a kernel stamp
// (SO_TIMESTAMPNS not enabled / not delivered) fall back to a
// syscall-time clock_gettime taken once per batch.
//
// After the first recvmmsg a busy-poll drain pass keeps calling
// recvmmsg(MSG_DONTWAIT) into the remaining rows while datagrams are
// still queued, so a burst that straddles the first syscall fills the
// batch instead of spilling into the next tick.  The drain is bounded
// by max_pkts — it never spins on an idle socket.
int udp_recv_batch_ts(int fd, uint8_t *buf, int capacity, int max_pkts,
                      int32_t *lengths, uint32_t *src_ip,
                      uint16_t *src_port, int64_t *arrival_ns,
                      int timeout_ms) {
  if (timeout_ms > 0) {
    pollfd p{fd, POLLIN, 0};
    int pr = poll(&p, 1, timeout_ms);
    if (pr < 0) return -errno;
    if (pr == 0) return 0;
  }
  // hoisted per-call scratch: the tick loop calls this at high rate and
  // the header/iov arrays are identical shape every time
  thread_local std::vector<mmsghdr> hdrs;
  thread_local std::vector<iovec> iovs;
  thread_local std::vector<sockaddr_in> addrs;
  thread_local std::vector<uint8_t> ctrl;
  if (static_cast<int>(hdrs.size()) < max_pkts) {
    hdrs.resize(max_pkts);
    iovs.resize(max_pkts);
    addrs.resize(max_pkts);
  }
  constexpr size_t kCtrl = 64;  // room for one timestampns cmsg
  if (arrival_ns &&
      ctrl.size() < static_cast<size_t>(max_pkts) * kCtrl)
    ctrl.resize(static_cast<size_t>(max_pkts) * kCtrl);
  for (int i = 0; i < max_pkts; i++) {
    iovs[i].iov_base = buf + static_cast<size_t>(i) * capacity;
    iovs[i].iov_len = capacity;
    std::memset(&hdrs[i], 0, sizeof(mmsghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &addrs[i];
    hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    if (arrival_ns) {
      hdrs[i].msg_hdr.msg_control =
          ctrl.data() + static_cast<size_t>(i) * kCtrl;
      hdrs[i].msg_hdr.msg_controllen = kCtrl;
    }
  }
  int total = 0;
  while (total < max_pkts) {
    int want = max_pkts - total;
    int n = recvmmsg(fd, hdrs.data() + total, want, MSG_DONTWAIT, nullptr);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (total > 0) break;  // deliver what we have; error next call
      return -errno;
    }
    if (n == 0) break;
    // a short return means the queue emptied mid-call — but datagrams
    // may have landed during the copy, so go around again and let
    // EAGAIN (not the short count) terminate the drain
    total += n;
  }
  if (total == 0) return 0;
  int64_t fallback = 0;
  if (arrival_ns) {
    timespec now{};
    clock_gettime(CLOCK_REALTIME, &now);
    fallback = static_cast<int64_t>(now.tv_sec) * 1000000000LL + now.tv_nsec;
  }
  for (int i = 0; i < total; i++) {
    lengths[i] = static_cast<int32_t>(hdrs[i].msg_len);
    src_ip[i] = ntohl(addrs[i].sin_addr.s_addr);
    src_port[i] = ntohs(addrs[i].sin_port);
    if (!arrival_ns) continue;
    arrival_ns[i] = fallback;
    for (cmsghdr *c = CMSG_FIRSTHDR(&hdrs[i].msg_hdr); c;
         c = CMSG_NXTHDR(&hdrs[i].msg_hdr, c)) {
      if (c->cmsg_level == SOL_SOCKET && c->cmsg_type == SO_TIMESTAMPNS) {
        timespec ts{};
        std::memcpy(&ts, CMSG_DATA(c), sizeof(ts));
        arrival_ns[i] =
            static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
        break;
      }
    }
  }
  return total;
}

// Row-indexed gather send via sendmmsg.  Rows are selected by idx[]
// into the caller's full [*, capacity] row-major matrix, so the host
// never materializes a contiguous copy of the egress subset: the iovec
// gather IS the row selection, and the whole multi-destination burst
// is one syscall (per-msg msg_name carries each row's destination).
// lengths/dst_ip/dst_port are length-n arrays in idx order; idx may be
// nullptr for the identity (rows 0..n-1).  dst_ip is host-order ip4.
// Returns packets sent or -errno.
int udp_send_batch_idx(int fd, const uint8_t *buf, int capacity,
                       const int32_t *lengths, const uint32_t *dst_ip,
                       const uint16_t *dst_port, const int32_t *idx,
                       int n) {
  thread_local std::vector<mmsghdr> hdrs;
  thread_local std::vector<iovec> iovs;
  thread_local std::vector<sockaddr_in> addrs;
  if (static_cast<int>(hdrs.size()) < n) {
    hdrs.resize(n);
    iovs.resize(n);
    addrs.resize(n);
  }
  for (int i = 0; i < n; i++) {
    int row = idx ? idx[i] : i;
    iovs[i].iov_base = const_cast<uint8_t *>(buf) +
                       static_cast<size_t>(row) * capacity;
    iovs[i].iov_len = lengths[i];
    addrs[i] = sockaddr_in{};
    addrs[i].sin_family = AF_INET;
    addrs[i].sin_port = htons(dst_port[i]);
    addrs[i].sin_addr.s_addr = htonl(dst_ip[i]);
    std::memset(&hdrs[i], 0, sizeof(mmsghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &addrs[i];
    hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int sent = 0;
  while (sent < n) {
    int r = sendmmsg(fd, hdrs.data() + sent, n - sent, 0);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      return -errno;
    }
    sent += r;
  }
  return sent;
}

// Batched send via sendmmsg from the same row-major layout.
// dst_ip is host-order ip4.  Returns packets sent or -errno.
int udp_send_batch(int fd, const uint8_t *buf, int capacity,
                   const int32_t *lengths, const uint32_t *dst_ip,
                   const uint16_t *dst_port, int n) {
  return udp_send_batch_idx(fd, buf, capacity, lengths, dst_ip, dst_port,
                            nullptr, n);
}

}  // extern "C"

// ===========================================================================
// io_uring engine (generation 2 host I/O).
//
// Same socket, same pinned-arena memory contract as the recvmmsg engine
// above, but ingest is ring-driven: every row of the CURRENT recv arena
// gets a single-shot RECVMSG SQE whose iovec points at that row, the
// whole arena is armed with ONE io_uring_enter, and steady-state drains
// reap completions from the shared-memory CQ without entering the
// kernel at all.  One syscall then covers an entire arena fill-cycle
// (rows packets) instead of one per recvmmsg window.
//
// Deliberate non-use of multishot RECVMSG: multishot completions carry
// an io_uring_recvmsg_out header + name/control blob IN the data
// buffer, in completion order from a provided-buffer pool — both break
// the arena contract (payload bytes at row offset 0, rows contiguous
// in arrival order) that makes the recv arena a zero-copy PacketBatch.
// Re-armed single-shot RECVMSG keeps the exact memory layout and still
// amortizes the enter down to ~1/rows per packet, which is what the
// syscall telemetry (udp_uring_stat) lets callers verify.
//
// Delivery is CONTIGUOUS-PREFIX: completions can land out of row order
// (rarely, under load), so a drain hands back only the completed prefix
// [delivered, first-hole) and later calls pick up the rest.  Egress
// multiplexes SENDMSG SQEs on the same CQ, tagged in user_data.
//
// Built only when the kernel UAPI header is present; otherwise every
// entry point is an ENOSYS stub so one .so serves both worlds and the
// Python probe (udp_uring_supported) picks the engine at runtime.

#if defined(__linux__) && defined(HAVE_IO_URING)

#include <linux/io_uring.h>
#include <linux/time_types.h>
#include <new>
#include <sys/mman.h>
#include <sys/syscall.h>

// cancel-any postdates some UAPI headers (kernel 5.19); the running
// kernel decides support at runtime, the constant is ABI-stable
#ifndef IORING_ASYNC_CANCEL_ANY
#define IORING_ASYNC_CANCEL_ANY (1U << 2)
#endif

namespace {

int sys_uring_setup(unsigned entries, io_uring_params *p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sys_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                    unsigned flags, const void *arg, size_t argsz) {
  return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                  min_complete, flags, arg, argsz));
}

constexpr uint64_t kSendTag = 1ULL << 62;  // user_data: send vs recv row

struct UringEngine {
  int sock_fd = -1;
  int ring_fd = -1;
  unsigned features = 0;
  bool sqpoll = false;
  bool want_ts = false;
  // mmapped ring state
  void *sq_ptr = nullptr, *cq_ptr = nullptr;
  size_t sq_len = 0, cq_len = 0, sqe_len = 0;
  io_uring_sqe *sqes = nullptr;
  unsigned *sq_head = nullptr, *sq_tail = nullptr, *sq_mask = nullptr;
  unsigned *sq_flags = nullptr, *sq_array = nullptr;
  unsigned *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  io_uring_cqe *cqes = nullptr;
  unsigned sq_entries = 0, cq_entries = 0;
  unsigned sq_pending = 0;  // SQEs staged since the last submit
  // current arena (one fill-cycle): metadata written straight into the
  // caller's arena-backed arrays at absolute row positions
  uint8_t *buf = nullptr;
  int rows = 0, capacity = 0;
  int32_t *out_len = nullptr;
  uint32_t *out_ip = nullptr;
  uint16_t *out_port = nullptr;
  int64_t *out_ts = nullptr;
  int posted = 0;     // rows with an SQE armed (staged or submitted)
  int delivered = 0;  // contiguous prefix handed back to the caller
  int inflight = 0;   // armed, not yet completed
  std::vector<uint8_t> completed;    // per-row completion flag
  std::vector<msghdr> mh;            // per-row op resources: must stay
  std::vector<iovec> iov;            // alive until the CQE arrives
  std::vector<sockaddr_in> addr;
  std::vector<uint8_t> ctrl;
  long enters = 0;      // io_uring_enter syscalls (the honest count)
  long reaps = 0;       // completions consumed ring-side
  long recv_errors = 0; // failed recv completions (row re-armed)
};

constexpr size_t kUringCtrl = 64;  // room for one timestampns cmsg

unsigned npow2(unsigned v) {
  unsigned p = 1;
  while (p < v) p <<= 1;
  return p;
}

int64_t cmsg_stamp(msghdr *m, int64_t fallback) {
  for (cmsghdr *c = CMSG_FIRSTHDR(m); c; c = CMSG_NXTHDR(m, c)) {
    if (c->cmsg_level == SOL_SOCKET && c->cmsg_type == SO_TIMESTAMPNS) {
      timespec ts{};
      std::memcpy(&ts, CMSG_DATA(c), sizeof(ts));
      return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
    }
  }
  return fallback;
}

// stage one SQE (caller guarantees SQ room); submission happens later
io_uring_sqe *stage_sqe(UringEngine *u) {
  unsigned tail = *u->sq_tail + u->sq_pending;
  io_uring_sqe *sqe = &u->sqes[tail & *u->sq_mask];
  std::memset(sqe, 0, sizeof(*sqe));
  u->sq_array[tail & *u->sq_mask] = tail & *u->sq_mask;
  u->sq_pending++;
  return sqe;
}

unsigned sq_room(UringEngine *u) {
  unsigned head = __atomic_load_n(u->sq_head, __ATOMIC_ACQUIRE);
  unsigned used = (*u->sq_tail + u->sq_pending) - head;
  return u->sq_entries - used;
}

// publish staged SQEs and optionally wait for >=1 completion.  The
// only place the engine enters the kernel.
int uring_submit(UringEngine *u, bool wait, int timeout_ms) {
  unsigned to_submit = u->sq_pending;
  if (to_submit) {
    __atomic_store_n(u->sq_tail, *u->sq_tail + to_submit,
                     __ATOMIC_RELEASE);
    u->sq_pending = 0;
  }
  unsigned flags = 0;
  unsigned min_complete = 0;
  io_uring_getevents_arg arg{};
  __kernel_timespec kts{};
  const void *argp = nullptr;
  size_t argsz = 0;
  if (wait) {
    flags |= IORING_ENTER_GETEVENTS;
    min_complete = 1;
    if (timeout_ms >= 0 && (u->features & IORING_FEAT_EXT_ARG)) {
      kts.tv_sec = timeout_ms / 1000;
      kts.tv_nsec = static_cast<long long>(timeout_ms % 1000) * 1000000;
      arg.ts = reinterpret_cast<uint64_t>(&kts);
      argp = &arg;
      argsz = sizeof(arg);
      flags |= IORING_ENTER_EXT_ARG;
    }
  }
  if (u->sqpoll) {
    unsigned sf = __atomic_load_n(u->sq_flags, __ATOMIC_ACQUIRE);
    if (!wait && !(sf & IORING_SQ_NEED_WAKEUP)) return 0;  // no syscall
    if (sf & IORING_SQ_NEED_WAKEUP) flags |= IORING_ENTER_SQ_WAKEUP;
    to_submit = 0;  // the poller thread consumes the SQ itself
  } else if (!to_submit && !wait) {
    return 0;
  }
  u->enters++;
  int r = sys_uring_enter(u->ring_fd, to_submit, min_complete, flags,
                          argp, argsz);
  if (r < 0 && errno != ETIME && errno != EINTR && errno != EBUSY)
    return -errno;
  return 0;
}

// Arm RECVMSG SQEs for every not-yet-posted row, as ONE IOSQE_IO_LINK
// chain: the kernel starts recv i+1 only after recv i completes.  The
// chain (a) preserves arrival order across rows — the arena stays a
// time-ordered batch exactly like the recvmmsg engine's, so the accept
// set can be bit-identical across engine modes, and (b) keeps a single
// poll waiter on the socket instead of rows-many (independent armed
// recvs race their poll retries, scrambling packet->row assignment and
// thundering-herd-waking every waiter per datagram).  A queued burst
// still cascades down the chain entirely in-kernel, zero syscalls.
//
// Guarded on inflight == 0: rows only (re-)arm when no prior SQE is
// outstanding, so a failed chain (one error cancels the remaining
// links) is re-armed as one fresh chain AFTER all its -ECANCELED
// completions drain — a row is never double-armed.
void arm_rows(UringEngine *u) {
  if (u->inflight > 0 || u->posted >= u->rows) return;
  io_uring_sqe *last = nullptr;
  while (u->posted < u->rows && sq_room(u) > 0) {
    int row = u->posted;
    u->iov[row].iov_base = u->buf + static_cast<size_t>(row) * u->capacity;
    u->iov[row].iov_len = u->capacity;
    std::memset(&u->mh[row], 0, sizeof(msghdr));
    u->mh[row].msg_iov = &u->iov[row];
    u->mh[row].msg_iovlen = 1;
    u->mh[row].msg_name = &u->addr[row];
    u->mh[row].msg_namelen = sizeof(sockaddr_in);
    if (u->want_ts) {
      u->mh[row].msg_control = u->ctrl.data() + row * kUringCtrl;
      u->mh[row].msg_controllen = kUringCtrl;
    }
    io_uring_sqe *sqe = stage_sqe(u);
    sqe->opcode = IORING_OP_RECVMSG;
    sqe->flags = IOSQE_IO_LINK;
    sqe->fd = u->sock_fd;
    sqe->addr = reinterpret_cast<uint64_t>(&u->mh[row]);
    sqe->user_data = static_cast<uint64_t>(row);
    u->posted++;
    u->inflight++;
    last = sqe;
  }
  if (last) last->flags &= ~IOSQE_IO_LINK;  // terminate the chain
}

// drain the CQ ring-side (no syscall).  Recv completions mark their
// row done and stash metadata into the arena arrays; failed recvs
// (e.g. ECONNREFUSED surfacing a prior send's ICMP error) re-arm the
// row.  Send completions (kSendTag) bump *send_done.  Returns number
// of completions consumed.
int reap(UringEngine *u, int *send_done, int *send_errs) {
  int n = 0;
  int64_t fallback = 0;
  unsigned head = *u->cq_head;
  for (;;) {
    unsigned tail = __atomic_load_n(u->cq_tail, __ATOMIC_ACQUIRE);
    if (head == tail) break;
    io_uring_cqe *cqe = &u->cqes[head & *u->cq_mask];
    uint64_t ud = cqe->user_data;
    int res = cqe->res;
    head++;
    n++;
    if (ud & kSendTag) {
      if (send_done) (*send_done)++;
      if (res < 0 && send_errs) (*send_errs)++;
    } else {
      int row = static_cast<int>(ud);
      u->inflight--;
      if (res < 0) {
        // chain-head error (e.g. ECONNREFUSED surfacing a prior
        // send's ICMP error) or the -ECANCELED tail the failed link
        // cascaded: roll `posted` back to the first affected row.
        // arm_rows re-arms the contiguous suffix as one fresh chain
        // once every outstanding completion has drained (inflight 0),
        // so ordering and the never-double-armed invariant both hold.
        if (res != -ECANCELED) u->recv_errors++;
        if (row < u->posted) u->posted = row;
        continue;
      }
      u->completed[row] = 1;
      u->out_len[row] = res;  // truncated to capacity, recvmmsg-style
      u->out_ip[row] = ntohl(u->addr[row].sin_addr.s_addr);
      u->out_port[row] = ntohs(u->addr[row].sin_port);
      if (u->out_ts) {
        if (fallback == 0) {
          timespec now{};
          clock_gettime(CLOCK_REALTIME, &now);
          fallback = static_cast<int64_t>(now.tv_sec) * 1000000000LL +
                     now.tv_nsec;
        }
        u->out_ts[row] = cmsg_stamp(&u->mh[row], fallback);
      }
    }
  }
  if (n) {
    __atomic_store_n(u->cq_head, head, __ATOMIC_RELEASE);
    u->reaps += n;
  }
  return n;
}

}  // namespace

extern "C" {

#define URING_ARENA_EXHAUSTED (-9999)

// Runtime probe: can this kernel set up an io_uring at all?  Cached.
int udp_uring_supported(void) {
  static int cached = -1;
  if (cached >= 0) return cached;
  io_uring_params p{};
  int fd = sys_uring_setup(4, &p);
  if (fd >= 0) {
    close(fd);
    cached = 1;
  } else {
    cached = 0;
  }
  return cached;
}

// Create a ring bound to an existing UDP socket (from udp_create).
// `entries` sizes the arena (rows) the ring must cover; the CQ is
// sized for a full arena of recv completions plus an egress burst.
// Returns an opaque handle or nullptr.
void *udp_uring_create(int sock_fd, int entries, int sqpoll, int want_ts) {
  UringEngine *u = new (std::nothrow) UringEngine();
  if (!u) return nullptr;
  unsigned sq = npow2(static_cast<unsigned>(entries < 8 ? 8 : entries));
  if (sq > 4096) sq = 4096;
  io_uring_params p{};
  p.flags = IORING_SETUP_CQSIZE;
  p.cq_entries = sq * 2;
  if (sqpoll) {
    p.flags |= IORING_SETUP_SQPOLL;
    p.sq_thread_idle = 100;
  }
  int rfd = sys_uring_setup(sq, &p);
  if (rfd < 0 && sqpoll) {
    // SQPOLL can need privileges older kernels reserve; fall back to
    // the enter-per-submit mode rather than failing the engine
    p.flags = IORING_SETUP_CQSIZE;
    sqpoll = 0;
    rfd = sys_uring_setup(sq, &p);
  }
  if (rfd < 0) {
    delete u;
    return nullptr;
  }
  u->sock_fd = sock_fd;
  u->ring_fd = rfd;
  u->features = p.features;
  u->sqpoll = sqpoll != 0;
  u->want_ts = want_ts != 0;
  u->sq_entries = p.sq_entries;
  u->cq_entries = p.cq_entries;
  u->sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  u->cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
  if (p.features & IORING_FEAT_SINGLE_MMAP) {
    size_t len = u->sq_len > u->cq_len ? u->sq_len : u->cq_len;
    u->sq_ptr = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, rfd, IORING_OFF_SQ_RING);
    u->cq_ptr = u->sq_ptr;
    u->sq_len = u->cq_len = len;
  } else {
    u->sq_ptr = mmap(nullptr, u->sq_len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, rfd, IORING_OFF_SQ_RING);
    u->cq_ptr = mmap(nullptr, u->cq_len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, rfd, IORING_OFF_CQ_RING);
  }
  u->sqe_len = p.sq_entries * sizeof(io_uring_sqe);
  u->sqes = static_cast<io_uring_sqe *>(
      mmap(nullptr, u->sqe_len, PROT_READ | PROT_WRITE,
           MAP_SHARED | MAP_POPULATE, rfd, IORING_OFF_SQES));
  if (u->sq_ptr == MAP_FAILED || u->cq_ptr == MAP_FAILED ||
      u->sqes == MAP_FAILED) {
    close(rfd);
    delete u;
    return nullptr;
  }
  auto *sqb = static_cast<uint8_t *>(u->sq_ptr);
  u->sq_head = reinterpret_cast<unsigned *>(sqb + p.sq_off.head);
  u->sq_tail = reinterpret_cast<unsigned *>(sqb + p.sq_off.tail);
  u->sq_mask = reinterpret_cast<unsigned *>(sqb + p.sq_off.ring_mask);
  u->sq_flags = reinterpret_cast<unsigned *>(sqb + p.sq_off.flags);
  u->sq_array = reinterpret_cast<unsigned *>(sqb + p.sq_off.array);
  auto *cqb = static_cast<uint8_t *>(u->cq_ptr);
  u->cq_head = reinterpret_cast<unsigned *>(cqb + p.cq_off.head);
  u->cq_tail = reinterpret_cast<unsigned *>(cqb + p.cq_off.tail);
  u->cq_mask = reinterpret_cast<unsigned *>(cqb + p.cq_off.ring_mask);
  u->cqes = reinterpret_cast<io_uring_cqe *>(cqb + p.cq_off.cqes);
  return u;
}

// Hand the ring a fresh arena to fill (one fill-cycle = rows packets).
// Per-row metadata is written straight into the arena-backed arrays at
// absolute row positions as completions arrive.  Fails with -EBUSY
// while recvs from the previous arena are still in flight — callers
// switch arenas only at exhaustion, where inflight is naturally 0, so
// the kernel NEVER holds a reference into a handed-back arena.
int udp_uring_arm(void *h, uint8_t *buf, int rows, int capacity,
                  int32_t *lengths, uint32_t *src_ip, uint16_t *src_port,
                  int64_t *arrival_ns) {
  auto *u = static_cast<UringEngine *>(h);
  if (!u || rows <= 0) return -EINVAL;
  reap(u, nullptr, nullptr);
  if (u->inflight > 0) return -EBUSY;
  if (static_cast<unsigned>(rows) > u->sq_entries) rows = u->sq_entries;
  u->buf = buf;
  u->rows = rows;
  u->capacity = capacity;
  u->out_len = lengths;
  u->out_ip = src_ip;
  u->out_port = src_port;
  u->out_ts = arrival_ns;
  u->posted = 0;
  u->delivered = 0;
  u->completed.assign(rows, 0);
  if (static_cast<int>(u->mh.size()) < rows) {
    u->mh.resize(rows);
    u->iov.resize(rows);
    u->addr.resize(rows);
  }
  if (u->want_ts && u->ctrl.size() < rows * kUringCtrl)
    u->ctrl.resize(rows * kUringCtrl);
  arm_rows(u);
  return uring_submit(u, false, 0);  // one enter arms the whole arena
}

// Deliver up to max_pkts completed packets as a CONTIGUOUS row run.
// Writes the first delivered row to *start_row; returns the count
// (0 on timeout), URING_ARENA_EXHAUSTED when every row of the current
// arena has been delivered (caller arms the next arena), or -errno.
// Steady state (completions already waiting) never enters the kernel.
int udp_uring_recv(void *h, int max_pkts, int timeout_ms,
                   int32_t *start_row) {
  auto *u = static_cast<UringEngine *>(h);
  if (!u || !u->buf) return -EINVAL;
  if (u->delivered >= u->rows) return URING_ARENA_EXHAUSTED;
  reap(u, nullptr, nullptr);
  arm_rows(u);
  if (u->sq_pending) uring_submit(u, false, 0);
  if (!u->completed[u->delivered] && timeout_ms > 0) {
    int r = uring_submit(u, true, timeout_ms);
    if (r < 0) return r;
    reap(u, nullptr, nullptr);
  }
  int lo = u->delivered;
  int hi = lo;
  int cap = lo + (max_pkts < u->rows - lo ? max_pkts : u->rows - lo);
  while (hi < cap && u->completed[hi]) hi++;
  if (hi == lo) return 0;
  u->delivered = hi;
  *start_row = lo;
  return hi - lo;
}

// Row-indexed gather send, ring edition: one SENDMSG SQE per packet
// submitted in SQ-sized chunks, waiting each chunk's completions so
// the per-op msghdr slots can be reused.  Same contract as
// udp_send_batch_idx.  Returns packets sent or -errno.
int udp_uring_send_idx(void *h, const uint8_t *buf, int capacity,
                       const int32_t *lengths, const uint32_t *dst_ip,
                       const uint16_t *dst_port, const int32_t *idx,
                       int n) {
  auto *u = static_cast<UringEngine *>(h);
  if (!u) return -EINVAL;
  thread_local std::vector<msghdr> smh;
  thread_local std::vector<iovec> siov;
  thread_local std::vector<sockaddr_in> saddr;
  int done = 0;
  int errs = 0;
  int sent_at = 0;
  while (sent_at < n) {
    reap(u, &done, &errs);
    unsigned room = sq_room(u);
    if (room == 0) {
      int r = uring_submit(u, true, -1);
      if (r < 0) return r;
      continue;
    }
    int chunk = n - sent_at < static_cast<int>(room)
                    ? n - sent_at
                    : static_cast<int>(room);
    if (static_cast<int>(smh.size()) < chunk) {
      smh.resize(chunk);
      siov.resize(chunk);
      saddr.resize(chunk);
    }
    for (int i = 0; i < chunk; i++) {
      int k = sent_at + i;
      int row = idx ? idx[k] : k;
      siov[i].iov_base = const_cast<uint8_t *>(buf) +
                         static_cast<size_t>(row) * capacity;
      siov[i].iov_len = lengths[k];
      saddr[i] = sockaddr_in{};
      saddr[i].sin_family = AF_INET;
      saddr[i].sin_port = htons(dst_port[k]);
      saddr[i].sin_addr.s_addr = htonl(dst_ip[k]);
      std::memset(&smh[i], 0, sizeof(msghdr));
      smh[i].msg_iov = &siov[i];
      smh[i].msg_iovlen = 1;
      smh[i].msg_name = &saddr[i];
      smh[i].msg_namelen = sizeof(sockaddr_in);
      io_uring_sqe *sqe = stage_sqe(u);
      sqe->opcode = IORING_OP_SENDMSG;
      sqe->fd = u->sock_fd;
      sqe->addr = reinterpret_cast<uint64_t>(&smh[i]);
      sqe->user_data = kSendTag | static_cast<uint64_t>(k);
    }
    int target = done + chunk;
    int r = uring_submit(u, false, 0);
    if (r < 0) return r;
    // the chunk's msghdr slots are reused next iteration: wait for
    // every completion of THIS chunk before building the next
    while (done < target) {
      reap(u, &done, &errs);
      if (done >= target) break;
      r = uring_submit(u, true, -1);
      if (r < 0) return r;
    }
    sent_at += chunk;
  }
  return n - errs;
}

// Telemetry: 0 = io_uring_enter syscalls, 1 = completions reaped
// ring-side, 2 = SQPOLL active, 3 = failed recv completions re-armed.
long udp_uring_stat(void *h, int which) {
  auto *u = static_cast<UringEngine *>(h);
  if (!u) return -EINVAL;
  switch (which) {
    case 0: return u->enters;
    case 1: return u->reaps;
    case 2: return u->sqpoll ? 1 : 0;
    case 3: return u->recv_errors;
  }
  return -EINVAL;
}

// Tear down the ring.  Armed recvs hold kernel references into the
// per-row msghdr slots (and the caller's arena), so they are cancelled
// (IORING_OP_ASYNC_CANCEL, cancel-any) and their completions drained
// BEFORE anything is freed — closing the ring fd alone defers the
// kernel-side cancellation and would race the frees.  If the drain
// cannot converge the engine struct is deliberately leaked rather than
// handing the kernel dangling memory.  Does NOT close sock_fd.
void udp_uring_destroy(void *h) {
  auto *u = static_cast<UringEngine *>(h);
  if (!u) return;
  if (u->inflight > 0 && u->ring_fd >= 0) {
    io_uring_sqe *sqe = stage_sqe(u);
    sqe->opcode = IORING_OP_ASYNC_CANCEL;
    sqe->cancel_flags = IORING_ASYNC_CANCEL_ANY;
    sqe->user_data = kSendTag | 1;
    uring_submit(u, false, 0);
    for (int i = 0; i < 64 && u->inflight > 0; i++) {
      reap(u, nullptr, nullptr);
      if (u->inflight > 0 && uring_submit(u, true, 50) < 0) break;
    }
    reap(u, nullptr, nullptr);
    if (u->inflight > 0) {
      close(u->ring_fd);  // leak u: kernel may still reference mh[]
      return;
    }
  }
  if (u->sqes && u->sqes != MAP_FAILED) munmap(u->sqes, u->sqe_len);
  if (u->cq_ptr && u->cq_ptr != u->sq_ptr && u->cq_ptr != MAP_FAILED)
    munmap(u->cq_ptr, u->cq_len);
  if (u->sq_ptr && u->sq_ptr != MAP_FAILED) munmap(u->sq_ptr, u->sq_len);
  if (u->ring_fd >= 0) close(u->ring_fd);
  delete u;
}

}  // extern "C"

#else  // !HAVE_IO_URING ------------------------------------------------

// ENOSYS stubs: the one .so serves kernels/toolchains without io_uring;
// the Python probe sees udp_uring_supported() == 0 and stays on the
// recvmmsg engine with a bit-identical accept set.
extern "C" {

int udp_uring_supported(void) { return 0; }

void *udp_uring_create(int, int, int, int) { return nullptr; }

int udp_uring_arm(void *, uint8_t *, int, int, int32_t *, uint32_t *,
                  uint16_t *, int64_t *) {
  return -ENOSYS;
}

int udp_uring_recv(void *, int, int, int32_t *) { return -ENOSYS; }

int udp_uring_send_idx(void *, const uint8_t *, int, const int32_t *,
                       const uint32_t *, const uint16_t *, const int32_t *,
                       int) {
  return -ENOSYS;
}

long udp_uring_stat(void *, int) { return -ENOSYS; }

void udp_uring_destroy(void *) {}

}  // extern "C"

#endif  // HAVE_IO_URING
