// Batched UDP I/O engine for the host data plane.
//
// The reference's packet I/O is java.net sockets with one thread per
// connector stream (org.jitsi.impl.neomedia.RTPConnectorUDPImpl et al.);
// at 10k streams that design melts.  This engine is the TPU-native
// replacement (SURVEY §2.6 item 12): recvmmsg/sendmmsg syscall batching,
// SO_REUSEPORT fan-in, and a receive buffer whose memory layout IS the
// framework's PacketBatch struct-of-arrays ([max_pkts, capacity] uint8
// matrix + int32 length vector) so datagrams land ready for the device
// with zero repacking.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

extern "C" {

// Create a bound UDP socket.  reuseport != 0 enables SO_REUSEPORT so N
// engine instances can share one port (kernel-level stream sharding).
// Returns fd >= 0 or -errno.
int udp_create(const char *bind_ip, uint16_t port, int reuseport,
               int rcvbuf_bytes) {
  int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (reuseport) setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  if (rcvbuf_bytes > 0)
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf_bytes, sizeof(rcvbuf_bytes));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr = bind_ip ? inet_addr(bind_ip) : INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return fd;
}

int udp_close(int fd) { return close(fd); }

// Enable kernel receive timestamps (SO_TIMESTAMPNS).  The BWE
// inter-arrival filters (GCC) react to sub-millisecond queueing-delay
// gradients; userspace arrival times include scheduler jitter that the
// kernel stamp (taken at skb receive) does not.  Returns 0 or -errno.
int udp_enable_timestamps(int fd) {
  int one = 1;
  if (setsockopt(fd, SOL_SOCKET, SO_TIMESTAMPNS, &one, sizeof(one)) < 0)
    return -errno;
  return 0;
}

// Get the locally bound port (for port-0 ephemeral binds in tests).
int udp_local_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr *>(&addr), &len) < 0)
    return -errno;
  return ntohs(addr.sin_port);
}

// (see udp_recv_batch_ts below; this entry point keeps the original
// ABI and simply skips the timestamp plumbing)
int udp_recv_batch_ts(int fd, uint8_t *buf, int capacity, int max_pkts,
                      int32_t *lengths, uint32_t *src_ip,
                      uint16_t *src_port, int64_t *arrival_ns,
                      int timeout_ms);

// Batched receive via recvmmsg into the caller's [max_pkts, capacity]
// row-major buffer; writes per-packet lengths, source ip4 (host order)
// and ports.  Waits up to timeout_ms for the FIRST packet, then drains
// whatever is immediately available (the batching-window pattern: the
// caller controls latency by the timeout, throughput by max_pkts).
// Returns number of packets, 0 on timeout, -errno on error.
int udp_recv_batch(int fd, uint8_t *buf, int capacity, int max_pkts,
                   int32_t *lengths, uint32_t *src_ip, uint16_t *src_port,
                   int timeout_ms) {
  return udp_recv_batch_ts(fd, buf, capacity, max_pkts, lengths, src_ip,
                           src_port, nullptr, timeout_ms);
}

// Timestamped batched receive: like udp_recv_batch, and when
// arrival_ns != nullptr also writes per-packet kernel arrival times
// (CLOCK_REALTIME nanoseconds).  Packets without a kernel stamp
// (SO_TIMESTAMPNS not enabled / not delivered) fall back to a
// syscall-time clock_gettime taken once per batch.
//
// After the first recvmmsg a busy-poll drain pass keeps calling
// recvmmsg(MSG_DONTWAIT) into the remaining rows while datagrams are
// still queued, so a burst that straddles the first syscall fills the
// batch instead of spilling into the next tick.  The drain is bounded
// by max_pkts — it never spins on an idle socket.
int udp_recv_batch_ts(int fd, uint8_t *buf, int capacity, int max_pkts,
                      int32_t *lengths, uint32_t *src_ip,
                      uint16_t *src_port, int64_t *arrival_ns,
                      int timeout_ms) {
  if (timeout_ms > 0) {
    pollfd p{fd, POLLIN, 0};
    int pr = poll(&p, 1, timeout_ms);
    if (pr < 0) return -errno;
    if (pr == 0) return 0;
  }
  // hoisted per-call scratch: the tick loop calls this at high rate and
  // the header/iov arrays are identical shape every time
  thread_local std::vector<mmsghdr> hdrs;
  thread_local std::vector<iovec> iovs;
  thread_local std::vector<sockaddr_in> addrs;
  thread_local std::vector<uint8_t> ctrl;
  if (static_cast<int>(hdrs.size()) < max_pkts) {
    hdrs.resize(max_pkts);
    iovs.resize(max_pkts);
    addrs.resize(max_pkts);
  }
  constexpr size_t kCtrl = 64;  // room for one timestampns cmsg
  if (arrival_ns &&
      ctrl.size() < static_cast<size_t>(max_pkts) * kCtrl)
    ctrl.resize(static_cast<size_t>(max_pkts) * kCtrl);
  for (int i = 0; i < max_pkts; i++) {
    iovs[i].iov_base = buf + static_cast<size_t>(i) * capacity;
    iovs[i].iov_len = capacity;
    std::memset(&hdrs[i], 0, sizeof(mmsghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &addrs[i];
    hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    if (arrival_ns) {
      hdrs[i].msg_hdr.msg_control =
          ctrl.data() + static_cast<size_t>(i) * kCtrl;
      hdrs[i].msg_hdr.msg_controllen = kCtrl;
    }
  }
  int total = 0;
  while (total < max_pkts) {
    int want = max_pkts - total;
    int n = recvmmsg(fd, hdrs.data() + total, want, MSG_DONTWAIT, nullptr);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (total > 0) break;  // deliver what we have; error next call
      return -errno;
    }
    if (n == 0) break;
    // a short return means the queue emptied mid-call — but datagrams
    // may have landed during the copy, so go around again and let
    // EAGAIN (not the short count) terminate the drain
    total += n;
  }
  if (total == 0) return 0;
  int64_t fallback = 0;
  if (arrival_ns) {
    timespec now{};
    clock_gettime(CLOCK_REALTIME, &now);
    fallback = static_cast<int64_t>(now.tv_sec) * 1000000000LL + now.tv_nsec;
  }
  for (int i = 0; i < total; i++) {
    lengths[i] = static_cast<int32_t>(hdrs[i].msg_len);
    src_ip[i] = ntohl(addrs[i].sin_addr.s_addr);
    src_port[i] = ntohs(addrs[i].sin_port);
    if (!arrival_ns) continue;
    arrival_ns[i] = fallback;
    for (cmsghdr *c = CMSG_FIRSTHDR(&hdrs[i].msg_hdr); c;
         c = CMSG_NXTHDR(&hdrs[i].msg_hdr, c)) {
      if (c->cmsg_level == SOL_SOCKET && c->cmsg_type == SO_TIMESTAMPNS) {
        timespec ts{};
        std::memcpy(&ts, CMSG_DATA(c), sizeof(ts));
        arrival_ns[i] =
            static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
        break;
      }
    }
  }
  return total;
}

// Row-indexed gather send via sendmmsg.  Rows are selected by idx[]
// into the caller's full [*, capacity] row-major matrix, so the host
// never materializes a contiguous copy of the egress subset: the iovec
// gather IS the row selection, and the whole multi-destination burst
// is one syscall (per-msg msg_name carries each row's destination).
// lengths/dst_ip/dst_port are length-n arrays in idx order; idx may be
// nullptr for the identity (rows 0..n-1).  dst_ip is host-order ip4.
// Returns packets sent or -errno.
int udp_send_batch_idx(int fd, const uint8_t *buf, int capacity,
                       const int32_t *lengths, const uint32_t *dst_ip,
                       const uint16_t *dst_port, const int32_t *idx,
                       int n) {
  thread_local std::vector<mmsghdr> hdrs;
  thread_local std::vector<iovec> iovs;
  thread_local std::vector<sockaddr_in> addrs;
  if (static_cast<int>(hdrs.size()) < n) {
    hdrs.resize(n);
    iovs.resize(n);
    addrs.resize(n);
  }
  for (int i = 0; i < n; i++) {
    int row = idx ? idx[i] : i;
    iovs[i].iov_base = const_cast<uint8_t *>(buf) +
                       static_cast<size_t>(row) * capacity;
    iovs[i].iov_len = lengths[i];
    addrs[i] = sockaddr_in{};
    addrs[i].sin_family = AF_INET;
    addrs[i].sin_port = htons(dst_port[i]);
    addrs[i].sin_addr.s_addr = htonl(dst_ip[i]);
    std::memset(&hdrs[i], 0, sizeof(mmsghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
    hdrs[i].msg_hdr.msg_name = &addrs[i];
    hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int sent = 0;
  while (sent < n) {
    int r = sendmmsg(fd, hdrs.data() + sent, n - sent, 0);
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      return -errno;
    }
    sent += r;
  }
  return sent;
}

// Batched send via sendmmsg from the same row-major layout.
// dst_ip is host-order ip4.  Returns packets sent or -errno.
int udp_send_batch(int fd, const uint8_t *buf, int capacity,
                   const int32_t *lengths, const uint32_t *dst_ip,
                   const uint16_t *dst_port, int n) {
  return udp_send_batch_idx(fd, buf, capacity, lengths, dst_ip, dst_port,
                            nullptr, n);
}

}  // extern "C"
