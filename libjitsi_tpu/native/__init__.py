"""Native C++ components (batched UDP engine); sources + built .so.

Without this file setuptools' packages.find skips the directory and
wheels ship without the engine sources/binary (io/udp.py loads
libudp_engine.so from here via ctypes).
"""
