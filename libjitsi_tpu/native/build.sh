#!/bin/sh
# Build the native UDP engine (C ABI shared lib consumed via ctypes).
set -e
cd "$(dirname "$0")"
g++ -O2 -Wall -shared -fPIC -o libudp_engine.so udp_engine.cpp
echo "built $(pwd)/libudp_engine.so"
