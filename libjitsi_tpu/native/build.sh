#!/bin/sh
# Build the native UDP engine (C ABI shared lib consumed via ctypes).
#
#   ./build.sh          optimized build -> libudp_engine.so
#   ./build.sh tsan     ThreadSanitizer build -> libudp_engine_tsan.so
#                       (SURVEY section 5 race detection: the reference
#                       ships no sanitizer builds; ours gates the C++
#                       I/O engine)
#   ./build.sh asan     AddressSanitizer build -> libudp_engine_asan.so
#
# Select a sanitized library at runtime with
#   LIBJITSI_TPU_UDP_ENGINE=/path/to/libudp_engine_tsan.so
# dlopen of a sanitized lib needs its runtime preloaded into the
# (uninstrumented) Python interpreter:
#   LD_PRELOAD=/lib/x86_64-linux-gnu/libtsan.so.2   (tsan build)
#   LD_PRELOAD=$(g++ -print-file-name=libasan.so)   (asan build;
#     add ASAN_OPTIONS=detect_leaks=0 — CPython itself trips LSan)
set -e
cd "$(dirname "$0")"
case "${1:-}" in
  tsan)
    g++ -O1 -g -Wall -fsanitize=thread -shared -fPIC \
        -o libudp_engine_tsan.so udp_engine.cpp
    echo "built $(pwd)/libudp_engine_tsan.so" ;;
  asan)
    g++ -O1 -g -Wall -fsanitize=address -shared -fPIC \
        -o libudp_engine_asan.so udp_engine.cpp
    echo "built $(pwd)/libudp_engine_asan.so" ;;
  *)
    g++ -O2 -Wall -shared -fPIC -o libudp_engine.so udp_engine.cpp
    echo "built $(pwd)/libudp_engine.so" ;;
esac
