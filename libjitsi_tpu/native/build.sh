#!/bin/sh
# Build the native UDP engine (C ABI shared lib consumed via ctypes).
#
#   ./build.sh          optimized build -> libudp_engine.so
#   ./build.sh tsan     ThreadSanitizer build -> libudp_engine_tsan.so
#                       (SURVEY section 5 race detection: the reference
#                       ships no sanitizer builds; ours gates the C++
#                       I/O engine)
#   ./build.sh asan     AddressSanitizer build -> libudp_engine_asan.so
#
# Select a sanitized library at runtime with
#   LIBJITSI_TPU_UDP_ENGINE=/path/to/libudp_engine_tsan.so
# dlopen of a sanitized lib needs its runtime preloaded into the
# (uninstrumented) Python interpreter:
#   LD_PRELOAD=/lib/x86_64-linux-gnu/libtsan.so.2   (tsan build)
#   LD_PRELOAD=$(g++ -print-file-name=libasan.so)   (asan build;
#     add ASAN_OPTIONS=detect_leaks=0 — CPython itself trips LSan)
set -e
cd "$(dirname "$0")"

# io_uring detection: prefer the kernel UAPI header (liburing is NOT
# required — the engine speaks raw io_uring_setup/enter syscalls).
# Without the header, the same .so still builds with every udp_uring_*
# entry point stubbed to ENOSYS; the Python probe then keeps the
# recvmmsg engine with a bit-identical accept set.
URING_FLAGS=""
if [ -e /usr/include/linux/io_uring.h ] || \
   [ -e /usr/include/liburing.h ]; then
  URING_FLAGS="-DHAVE_IO_URING"
fi

# C++ OpenSSL differential oracle (no dev headers in the image: the
# .cpp declares the stable EVP ABI; link the versioned lib directly)
build_oracle() {
  g++ -O2 -Wall -shared -fPIC -o libcrypto_oracle.so \
      crypto_oracle.cpp /usr/lib/x86_64-linux-gnu/libcrypto.so.3
}

case "${1:-}" in
  tsan)
    g++ -O1 -g -Wall $URING_FLAGS -fsanitize=thread -shared -fPIC \
        -o libudp_engine_tsan.so udp_engine.cpp
    echo "built $(pwd)/libudp_engine_tsan.so" ;;
  asan)
    g++ -O1 -g -Wall $URING_FLAGS -fsanitize=address -shared -fPIC \
        -o libudp_engine_asan.so udp_engine.cpp
    echo "built $(pwd)/libudp_engine_asan.so" ;;
  oracle)
    build_oracle
    echo "built $(pwd)/libcrypto_oracle.so" ;;
  *)
    g++ -O2 -Wall $URING_FLAGS -shared -fPIC \
        -o libudp_engine.so udp_engine.cpp
    # oracle is best-effort here: a box without libcrypto.so.3 still
    # gets the UDP engine (tests needing the oracle build it
    # explicitly via `build.sh oracle` and fail loudly there)
    if build_oracle 2>/dev/null; then
      echo "built $(pwd)/libudp_engine.so + libcrypto_oracle.so"
    else
      echo "built $(pwd)/libudp_engine.so (no libcrypto.so.3: oracle skipped)"
    fi ;;
esac
