"""drift: metrics/checkpoint coverage drift.

Two halves, one rule name:

**Metrics drift** (global, cross-file): a class that exports SOME of
its counters through ``MetricsRegistry`` but silently grew another
counter nobody registered is invisible in production — the exact
failure the recovery-ladder counters guard against.  We collect every
attribute name mentioned in ``register_counters(obj, [...])`` lists
and every ``lambda: obj.attr`` body inside ``register_scalar`` calls;
then for each class whose counters are *partially* covered we flag the
uncovered counter attributes.  Vice versa, a registered attribute that
no class ever defines is a typo that renders as a permanent ``0``
metric — also flagged.  Classes with NO registered counters are out of
scope (internal helpers have no exporter contract).  The same pass
covers ``Histogram``s: a class that constructs one and feeds it with
``observe``/``observe_array`` must hand it to the registry somewhere
(``register_histogram`` or the ``registry.histogram`` factory), else
the distribution is recorded but unscrapeable.  Two more cross-file
facts ride the same index: an ``SloSpec`` whose ``metric`` /
``bad_metric`` / ``total_metric`` names a family no registration ever
defines burns against a permanently-absent signal (the engine reads
``None`` forever and the SLO can never fire), and a histogram created
with ``exemplars=True`` whose ``observe``/``observe_same`` calls never
pass ``exemplar=`` ships empty exemplar slots in every OpenMetrics
scrape — both are silent-at-runtime wiring bugs, which is exactly what
a static gate is for.  ``HistogramVec`` families (one label axis, e.g.
the hop-labeled ``packet_journey_seconds``) get the same treatment:
``registry.histogram_vec(...)`` registers the family name, a chained
``vec.labels(x).observe(..., exemplar=...)`` feeds the vec's exemplar
slots, and a child bound via ``h = vec.labels(x)`` aliases its
observes back to the parent vec.

**Admission-reason drift** (global, cross-file): every refusal the
admission plane can hand a caller is TYPED — the string lives in the
``ADMIT_REASONS`` tuple in ``service/lifecycle.py``, and metrics
(``lifecycle_admit_rejected{reason=...}``), flight events, retry-after
hints and the soak gates' ``refused ⊆ ADMIT_REASONS`` assertions all
key off it.  A refusal site that returns a literal NOT in the tuple
(``return False, "mystery"``) ships an untyped reason: the smoke gates
fail it as an unknown key and dashboards can't label it.  We collect
the tuple literal plus every string a function named ``*admit*`` /
``*admission*`` refuses with (both the ``(False, "reason")`` pair and
the bare ``return "reason"`` form; ``"ok"`` is the accept token, not a
reason) and flag undeclared literals.  The same pass pins the
``capacity_forecast`` reason to its observability contract: a tree
that declares it must also register the ``capacity_*`` families
(headroom / bottleneck / confidence / forecast-refusals), else the
forecast refuses joins with no scrapeable explanation.

**Perf-baseline drift** (global, disk-backed): ``PERF_BASELINE.json``
keys must match the ``SCENARIOS`` ids in ``scripts/perf_gate.py`` both
ways — a stale key gates nothing, and a scenario without a baseline
entry can regress forever without failing the gate.  The gate script
is AST-parsed, never imported (lint stays hermetic).

**Snapshot drift** (per-file): subclasses of ``ArraySnapshotMixin``
must list every mutable array field in ``_SNAP_FIELDS`` (or carry it
via the scalar hooks) — a field missing from the snapshot restores
stale zeros after a crash-recover, the bug class
``test_checkpoint_roundtrip`` hunts one class at a time.  We flag
array-valued ``self.X = np.zeros/...`` fields of mixin subclasses
missing from both ``_SNAP_FIELDS`` and the scalar-hook sources, and
``_SNAP_FIELDS`` entries with no matching array assignment.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from libjitsi_tpu.analysis.core import (FileContext, Finding,
                                        call_func_name, node_name)

RULE = "drift"

COUNTER_NAME_RE = re.compile(
    r"(_count|_counts|_frames|_errors|_dropped|_drops|_sent|_served|"
    r"_miss|_misses|_recovered|_rejects|_rejected|_fail|_fails|"
    r"_abandoned|_suppressed|_late|_switches|_restarts|_evicted|"
    r"_expired|_total|_syscalls|_reaps)$"
    r"|^(dropped|lost|forwarded|switches|recovered)")

ARRAY_CTORS = {"zeros", "full", "empty", "ones", "array", "tile",
               "arange", "copy"}


# ------------------------------------------------------------ snapshot half

def check_snapshot_drift(ctx: FileContext) -> List[Finding]:
    findings: List[Optional[Finding]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {node_name(b) for b in node.bases}
        if "ArraySnapshotMixin" not in bases:
            continue
        findings.extend(_check_snapshot_class(ctx, node))
    return [f for f in findings if f is not None]


def _check_snapshot_class(ctx: FileContext, cls: ast.ClassDef
                          ) -> List[Optional[Finding]]:
    snap_fields: Set[str] = set()
    snap_fields_node: Optional[ast.AST] = None
    scalar_hook_names: Set[str] = set()
    array_fields: Dict[str, ast.AST] = {}

    for item in cls.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_SNAP_FIELDS":
                    snap_fields_node = item
                    for n in ast.walk(item.value):
                        if isinstance(n, ast.Constant) and \
                                isinstance(n.value, str):
                            snap_fields.add(n.value)
        elif isinstance(item, ast.FunctionDef):
            if item.name in ("_snap_scalars", "_restore_kwargs",
                             "snapshot", "restore"):
                for n in ast.walk(item):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        scalar_hook_names.add(n.value)
                    name = node_name(n)
                    if name:
                        scalar_hook_names.add(name)
            if item.name == "__init__":
                for n in ast.walk(item):
                    if isinstance(n, ast.Assign) and \
                            _is_array_ctor(n.value):
                        for tgt in n.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self":
                                array_fields[tgt.attr] = n

    out: List[Optional[Finding]] = []
    for field, node in sorted(array_fields.items()):
        if field not in snap_fields and field not in scalar_hook_names:
            out.append(ctx.finding(
                RULE, node,
                f"array field `{field}` of ArraySnapshotMixin subclass "
                f"`{cls.name}` is missing from _SNAP_FIELDS (restores "
                "as stale zeros after crash-recover)"))
    for field in sorted(snap_fields):
        if field not in array_fields:
            out.append(ctx.finding(
                RULE, snap_fields_node or cls,
                f"_SNAP_FIELDS entry `{field}` of `{cls.name}` has no "
                "matching array assignment in __init__ (snapshot() "
                "will AttributeError or copy a non-array)"))
    return out


def _is_array_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ARRAY_CTORS and \
                node_name(fn.value) in ("np", "numpy", "jnp"):
            return True
        # x.copy() / np.asarray(...).astype(...)
        if isinstance(fn, ast.Attribute) and fn.attr in ("copy", "astype"):
            return _is_array_ctor(fn.value) or True
    return False


# ------------------------------------------------------------- metrics half

#: SloSpec kwargs that reference metric-family names
SLO_REF_KWARGS = ("metric", "bad_metric", "total_metric")


def file_facts(ctx: FileContext) -> dict:
    """Everything the global metrics-drift pass needs from one file,
    gathered in ONE tree walk and JSON-serializable (the whole-tree
    checker used to re-walk every AST eight times per run — the
    dominant cost of a warm lint; facts make it a set intersection)."""
    reg_attrs: Set[str] = set()
    hist_reg: Set[str] = set()
    metric_exact: Set[str] = set()
    metric_suffixes: Set[str] = set()
    slo_refs: List[List] = []
    ex_hists: List[List] = []
    ex_observed: Set[str] = set()
    labels_alias: List[List] = []
    attr_names: Set[str] = set()
    reg_counter_names: List[List] = []
    admit_decl: List[List] = []
    admit_refusals: List[List] = []

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "ADMIT_REASONS"
                for t in node.targets) and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            admit_decl.append(
                [sorted(e.value for e in node.value.elts
                        if isinstance(e, ast.Constant) and
                        isinstance(e.value, str)),
                 node.lineno, node.col_offset])
        if isinstance(node, ast.FunctionDef) and \
                ("admit" in node.name or "admission" in node.name):
            for n in ast.walk(node):
                if not isinstance(n, ast.Return) or n.value is None:
                    continue
                lit = None
                if isinstance(n.value, ast.Tuple) and \
                        len(n.value.elts) == 2:
                    ok, reason = n.value.elts
                    if isinstance(ok, ast.Constant) and \
                            ok.value is False and \
                            isinstance(reason, ast.Constant) and \
                            isinstance(reason.value, str):
                        lit = reason.value
                elif isinstance(n.value, ast.Constant) and \
                        isinstance(n.value.value, str):
                    lit = n.value.value
                if lit is not None and lit != "ok":
                    admit_refusals.append(
                        [lit, node.name, n.lineno, n.col_offset])
        if isinstance(node, ast.Attribute):
            attr_names.add(node.attr)
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, (ast.Attribute, ast.Name)):
            # plain alias (`vec = self._journey_vec`): exemplar feeds
            # through the local name credit the attribute it came from
            src = node_name(node.value)
            for tgt in node.targets:
                nm = node_name(tgt)
                if nm and src and nm != src:
                    labels_alias.append([nm, src])
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            vname = call_func_name(node.value)
            if vname in ("histogram", "histogram_vec"):
                for tgt in node.targets:
                    nm = node_name(tgt)
                    if nm:
                        hist_reg.add(nm)
            if vname in ("histogram", "Histogram", "histogram_vec",
                         "HistogramVec") and any(
                    kw.arg == "exemplars" and
                    isinstance(kw.value, ast.Constant) and
                    kw.value.value is True
                    for kw in node.value.keywords):
                for tgt in node.targets:
                    nm = node_name(tgt)
                    if nm:
                        ex_hists.append([nm, node.lineno,
                                         node.col_offset])
            if vname == "labels" and \
                    isinstance(node.value.func, ast.Attribute):
                # h = vec.labels("local"): observes through `h` feed
                # the PARENT vec's exemplar slots
                parent = node_name(node.value.func.value)
                for tgt in node.targets:
                    nm = node_name(tgt)
                    if nm and parent:
                        labels_alias.append([nm, parent])
        if not isinstance(node, ast.Call):
            continue
        fname = call_func_name(node)
        if fname == "register_counters" and len(node.args) >= 2:
            for n in ast.walk(node.args[1]):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, str) and " " not in n.value:
                    # pairs are (attr, help): help texts contain
                    # spaces, attribute names never do
                    reg_attrs.add(n.value)
                    metric_suffixes.add(n.value)
                    reg_counter_names.append(
                        [n.value, n.lineno, n.col_offset])
        elif fname in ("register_scalar", "register_array"):
            # the reading closure names the attribute: lambda: self.x
            for n in ast.walk(node):
                if isinstance(n, ast.Lambda):
                    for leaf in ast.walk(n.body):
                        if isinstance(leaf, ast.Attribute):
                            reg_attrs.add(leaf.attr)
                elif isinstance(n, ast.Attribute):
                    reg_attrs.add(n.attr)
        elif fname == "register_histogram":
            for n in ast.walk(node):
                if isinstance(n, ast.Attribute):
                    hist_reg.add(n.attr)
        elif fname in ("observe", "observe_same", "observe_array") and \
                isinstance(node.func, ast.Attribute) and \
                any(kw.arg == "exemplar" for kw in node.keywords):
            base = node.func.value
            # vec.labels("hop").observe(..., exemplar=...): the chain
            # feeds the vec itself, so credit the vec's name
            if isinstance(base, ast.Call) and \
                    isinstance(base.func, ast.Attribute) and \
                    base.func.attr == "labels":
                base = base.func.value
            nm = node_name(base)
            if nm:
                ex_observed.add(nm)
        elif fname == "SloSpec":
            slo_name = ""
            if node.args and isinstance(node.args[0], ast.Constant):
                slo_name = str(node.args[0].value)
            for kw in node.keywords:
                if kw.arg == "name" and \
                        isinstance(kw.value, ast.Constant):
                    slo_name = str(kw.value.value)
            for kw in node.keywords:
                if kw.arg in SLO_REF_KWARGS and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str) and \
                        kw.value.value:
                    slo_refs.append([slo_name, kw.value.value,
                                     kw.value.lineno,
                                     kw.value.col_offset])
        if fname in ("register_scalar", "register_array",
                     "register_multi", "register_histogram",
                     "histogram", "histogram_vec") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                metric_exact.add(arg.value)
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                tail = arg.values[-1]
                if isinstance(tail, ast.Constant) and \
                        isinstance(tail.value, str):
                    metric_suffixes.add(tail.value.lstrip("_"))

    class_counters: List[List] = []
    class_hists: List[List] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        zeroed: Set[str] = set()
        bumped: Set[str] = set()
        created: Set[str] = set()
        observed: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Assign):
                if isinstance(n.value, ast.Constant) and \
                        n.value.value == 0 and \
                        not isinstance(n.value.value, bool):
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            zeroed.add(tgt.attr)
                elif isinstance(n.value, ast.Call) and \
                        call_func_name(n.value) == "Histogram":
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            created.add(tgt.attr)
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.op, ast.Add) and \
                    isinstance(n.target, ast.Attribute) and \
                    isinstance(n.target.value, ast.Name) and \
                    n.target.value.id == "self":
                bumped.add(n.target.attr)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("observe", "observe_array") and \
                    isinstance(n.func.value, ast.Attribute) and \
                    isinstance(n.func.value.value, ast.Name) and \
                    n.func.value.value.id == "self":
                observed.add(n.func.value.attr)
        counters = sorted(a for a in zeroed & bumped
                          if COUNTER_NAME_RE.search(a))
        if counters:
            class_counters.append([node.name, node.lineno,
                                   node.col_offset, counters])
        if created:
            class_hists.append([node.name, node.lineno,
                                node.col_offset, sorted(created),
                                sorted(observed)])

    return {
        "abspath": os.path.abspath(ctx.path),
        "reg_attrs": sorted(reg_attrs),
        "hist_reg": sorted(hist_reg),
        "class_counters": class_counters,
        "class_hists": class_hists,
        "metric_exact": sorted(metric_exact),
        "metric_suffixes": sorted(metric_suffixes),
        "slo_refs": slo_refs,
        "ex_hists": ex_hists,
        "ex_observed": sorted(ex_observed),
        "labels_alias": labels_alias,
        "attr_names": sorted(attr_names),
        "reg_counter_names": reg_counter_names,
        "admit_decl": admit_decl,
        "admit_refusals": admit_refusals,
    }


# -------------------------------------------------------- perf-baseline half

def check_perf_baseline(baseline_keys: Set[str],
                        scenario_ids: Set[str]) -> List[str]:
    """Pure comparison: messages for baseline keys matching no perf-gate
    scenario (stale — the gate never reads them) and scenarios with no
    baseline entry (ungated — a regression there never fails)."""
    msgs: List[str] = []
    for key in sorted(baseline_keys - scenario_ids):
        msgs.append(
            f"PERF_BASELINE.json key `{key}` matches no perf_gate "
            "scenario id — stale entry, the gate never compares it")
    for sid in sorted(scenario_ids - baseline_keys):
        msgs.append(
            f"perf_gate scenario `{sid}` has no PERF_BASELINE.json "
            "entry — ungated, a regression there never fails "
            "(run scripts/perf_gate.py --write-baseline)")
    return msgs


def check_baseline_meta(meta: dict) -> List[str]:
    """Pure check of the baseline's ``_meta`` block: the `git` stamp
    must be an abbreviated-or-full lowercase hex commit hash.  A
    baseline stamped "unknown" (or hand-edited prose) can't be traced
    to the commit whose numbers it froze — `--write-baseline` stamps
    HEAD automatically, so anything else means the file was edited by
    hand or written outside a checkout."""
    git = (meta or {}).get("git", "")
    if not re.fullmatch(r"[0-9a-f]{7,40}", str(git)):
        return [
            f"PERF_BASELINE.json _meta.git `{git}` is not a commit "
            "hash — the baseline cannot be traced to the revision it "
            "measured (re-run scripts/perf_gate.py --write-baseline "
            "from a checkout)"]
    # `tree` records working-tree cleanliness at stamp time.  A stamp
    # taken on a dirty tree points `git` at a commit that is NOT the
    # code that produced the numbers (how PR 11's gate run left
    # _meta.git one commit behind the baseline it wrote) —
    # --write-baseline refuses dirty trees now, so any other value
    # means the stamp predates the rule or was hand-edited.
    tree = (meta or {}).get("tree")
    if tree is not None and tree != "clean":
        return [
            f"PERF_BASELINE.json _meta.tree `{tree}` — the baseline "
            "was stamped on a dirty working tree, so _meta.git does "
            "not identify the measured code (commit first, then "
            "re-run scripts/perf_gate.py --write-baseline)"]
    return []


def _perf_gate_scenario_ids(script_path: str) -> Optional[Set[str]]:
    """String keys of the module-level ``SCENARIOS = {...}`` literal in
    scripts/perf_gate.py (AST only, never imported: the gate pulls in
    jax at import time and lint must stay hermetic)."""
    try:
        with open(script_path) as fh:
            tree = ast.parse(fh.read(), filename=script_path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Dict) and any(
                    isinstance(t, ast.Name) and t.id == "SCENARIOS"
                    for t in node.targets):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str)}
    return None


def check_baseline_justifications(entries: Dict[str, str]) -> List[str]:
    """Messages for lint-baseline entries with no one-line `why` —
    the grandfathering contract is that every surviving entry is
    justified in the file, not silently parked."""
    return [
        f"baseline entry `{key}` has no justification — add a "
        "one-line `why` to libjitsi_tpu/analysis/baseline.json or "
        "fix and prune the entry"
        for key, why in sorted(entries.items()) if not why.strip()]


def _perf_baseline_findings(abspaths: List[str]) -> List[Finding]:
    """Disk wiring: lint only indexes .py files under the linted tree,
    so the baseline json and the scripts/ gate are read from disk,
    located by walking up from any indexed file."""
    root = None
    for p in abspaths:
        d = os.path.dirname(p)
        for _ in range(6):
            if os.path.exists(os.path.join(d, "PERF_BASELINE.json")):
                root = d
                break
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        if root:
            break
    if root is None:
        return []
    try:
        with open(os.path.join(root, "PERF_BASELINE.json")) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return [Finding(rule=RULE, path="PERF_BASELINE.json", line=1,
                        col=0, message="PERF_BASELINE.json is not "
                        "valid JSON — the perf gate cannot load it",
                        snippet="PERF_BASELINE.json", symbol="")]
    msgs = check_baseline_meta(doc.get("_meta", {}))
    scenario_ids = _perf_gate_scenario_ids(
        os.path.join(root, "scripts", "perf_gate.py"))
    if scenario_ids is not None:
        baseline_keys = {k for k in doc if not k.startswith("_")}
        msgs.extend(check_perf_baseline(baseline_keys, scenario_ids))
    return [Finding(rule=RULE, path="PERF_BASELINE.json", line=1,
                    col=0, message=msg, snippet=msg, symbol="")
            for msg in msgs]


class _CtxFinder:
    """FileFacts-shaped `.finding()` over a raw FileContext — keeps
    the direct `{relpath: FileContext}` calling convention of the
    fixture tests working against the facts-based global pass."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx

    def finding(self, rule: str, line: int, col: int, message: str,
                trace=None) -> Optional[Finding]:
        shim = ast.Pass()
        shim.lineno, shim.col_offset = line, col
        return self.ctx.finding(rule, shim, message)


def _facts_view(index) -> List[Tuple[str, dict, object]]:
    """[(relpath, drift facts, finder)] from either a legacy
    {relpath: FileContext} dict or an index of facts objects."""
    out = []
    for rel, v in sorted(index.items()):
        if isinstance(v, FileContext):
            out.append((rel, file_facts(v), _CtxFinder(v)))
        else:
            out.append((rel, v.data["drift"], v))
    return out


def check_metrics_drift(index) -> List[Finding]:
    views = _facts_view(index)
    registered: Set[str] = set()
    hist_registered: Set[str] = set()
    metric_exact: Set[str] = set()
    metric_suffixes: Set[str] = set()
    exemplar_fed: Set[str] = set()
    all_attr_names: Set[str] = set()
    alias_parents: Dict[str, Set[str]] = {}
    declared_reasons: Set[str] = set()
    for _rel, d, _f in views:
        registered |= set(d["reg_attrs"])
        hist_registered |= set(d["hist_reg"])
        metric_exact |= set(d["metric_exact"])
        metric_suffixes |= set(d["metric_suffixes"])
        exemplar_fed |= set(d["ex_observed"])
        all_attr_names |= set(d["attr_names"])
        for child, parent in d.get("labels_alias", ()):
            alias_parents.setdefault(child, set()).add(parent)
        for names, _l, _c in d.get("admit_decl", ()):
            declared_reasons |= set(names)
    # a fed vec child (or local alias) feeds its parent's exemplar
    # slots too — fixpoint over the alias edges
    changed = True
    while changed:
        changed = False
        for child in sorted(set(alias_parents) & exemplar_fed):
            if not alias_parents[child] <= exemplar_fed:
                exemplar_fed |= alias_parents[child]
                changed = True

    def _family_known(ref: str) -> bool:
        if ref in metric_exact:
            return True
        return any(ref == s or ref.endswith("_" + s)
                   for s in metric_suffixes)

    findings: List[Optional[Finding]] = []
    for _rel, d, finder in views:
        for cls_name, line, col, counters in d["class_counters"]:
            covered = set(counters) & registered
            missing = set(counters) - registered
            if covered and missing:
                for attr in sorted(missing):
                    findings.append(finder.finding(
                        RULE, line, col,
                        f"counter `{cls_name}.{attr}` is incremented "
                        "but never registered with MetricsRegistry "
                        "while sibling counters "
                        f"({', '.join(sorted(covered)[:3])}) are — "
                        "invisible in production"))

        # histogram half: a Histogram constructed and fed but never
        # handed to the registry is recorded but unscrapeable
        for cls_name, line, col, created, observed in d["class_hists"]:
            for attr in sorted((set(created) & set(observed))
                               - hist_registered):
                findings.append(finder.finding(
                    RULE, line, col,
                    f"histogram `{cls_name}.{attr}` is observed but "
                    "never registered with MetricsRegistry (use "
                    "register_histogram or the registry.histogram "
                    "factory) — invisible in production"))

        # SLO half: a spec naming a family no registration defines
        # burns against a permanently-missing signal
        for slo_name, ref, line, col in d["slo_refs"]:
            if not _family_known(ref):
                findings.append(finder.finding(
                    RULE, line, col,
                    f"SloSpec `{slo_name}` references metric `{ref}` "
                    "that no MetricsRegistry registration defines — "
                    "the burn-rate engine reads an absent family "
                    "forever and this SLO can never fire"))

        # exemplar half: an exemplars=True histogram nobody ever feeds
        # ships empty exemplar slots in every OpenMetrics scrape
        for attr, line, col in d["ex_hists"]:
            if attr not in exemplar_fed:
                findings.append(finder.finding(
                    RULE, line, col,
                    f"histogram `{attr}` is created with "
                    "exemplars=True but no observe call ever passes "
                    "exemplar= — its exemplar slots stay empty in "
                    "every OpenMetrics scrape"))

        # admission-reason half: a refusal literal outside the typed
        # ADMIT_REASONS tuple is an untyped reason — the
        # admit_rejected{reason=...} label set, the flight recorder
        # and the soak gates' `refused <= ADMIT_REASONS` subset
        # assertions all miss it.  Only active once some file in the
        # tree declares the tuple (fixture trees without an admission
        # plane are out of scope).
        if declared_reasons:
            for lit, fn, line, col in d.get("admit_refusals", ()):
                if lit not in declared_reasons:
                    findings.append(finder.finding(
                        RULE, line, col,
                        f"`{fn}` refuses admission with reason "
                        f"`{lit}` that ADMIT_REASONS never declares "
                        "— untyped refusal: the admit_rejected "
                        "metric grows an unknown label and the "
                        "churn/global-day gates fail their subset "
                        "check (declare it in service/lifecycle.py)"))

        # capacity contract: declaring the forecast refusal without
        # registering the capacity_* families leaves the forecast
        # refusing joins with no scrapeable explanation
        for names, line, col in d.get("admit_decl", ()):
            if "capacity_forecast" not in names:
                continue
            for fam_name in ("capacity_headroom_users",
                             "capacity_bottleneck",
                             "capacity_estimate_confidence",
                             "capacity_forecast_refusals"):
                if not _family_known(fam_name):
                    findings.append(finder.finding(
                        RULE, line, col,
                        "ADMIT_REASONS declares `capacity_forecast` "
                        f"but no registration defines the `{fam_name}` "
                        "family — the forecast would refuse joins "
                        "with no scrapeable headroom explanation "
                        "(register the CapacityModel gauges or drop "
                        "the reason)"))

        # vice versa: registered attribute names that exist nowhere
        for name, line, col in d["reg_counter_names"]:
            if name not in all_attr_names:
                findings.append(finder.finding(
                    RULE, line, col,
                    f"register_counters names `{name}` but no "
                    "class defines that attribute (typo -> "
                    "AttributeError at scrape time)"))

    # perf-baseline half: PERF_BASELINE.json vs perf_gate SCENARIOS —
    # a stale baseline key silently gates nothing; a scenario with no
    # baseline entry silently never gates
    findings.extend(_perf_baseline_findings(
        [d["abspath"] for _r, d, _f in views]))
    return [f for f in findings if f is not None]
