"""drift: metrics/checkpoint coverage drift.

Two halves, one rule name:

**Metrics drift** (global, cross-file): a class that exports SOME of
its counters through ``MetricsRegistry`` but silently grew another
counter nobody registered is invisible in production — the exact
failure the recovery-ladder counters guard against.  We collect every
attribute name mentioned in ``register_counters(obj, [...])`` lists
and every ``lambda: obj.attr`` body inside ``register_scalar`` calls;
then for each class whose counters are *partially* covered we flag the
uncovered counter attributes.  Vice versa, a registered attribute that
no class ever defines is a typo that renders as a permanent ``0``
metric — also flagged.  Classes with NO registered counters are out of
scope (internal helpers have no exporter contract).  The same pass
covers ``Histogram``s: a class that constructs one and feeds it with
``observe``/``observe_array`` must hand it to the registry somewhere
(``register_histogram`` or the ``registry.histogram`` factory), else
the distribution is recorded but unscrapeable.  Two more cross-file
facts ride the same index: an ``SloSpec`` whose ``metric`` /
``bad_metric`` / ``total_metric`` names a family no registration ever
defines burns against a permanently-absent signal (the engine reads
``None`` forever and the SLO can never fire), and a histogram created
with ``exemplars=True`` whose ``observe``/``observe_same`` calls never
pass ``exemplar=`` ships empty exemplar slots in every OpenMetrics
scrape — both are silent-at-runtime wiring bugs, which is exactly what
a static gate is for.

**Perf-baseline drift** (global, disk-backed): ``PERF_BASELINE.json``
keys must match the ``SCENARIOS`` ids in ``scripts/perf_gate.py`` both
ways — a stale key gates nothing, and a scenario without a baseline
entry can regress forever without failing the gate.  The gate script
is AST-parsed, never imported (lint stays hermetic).

**Snapshot drift** (per-file): subclasses of ``ArraySnapshotMixin``
must list every mutable array field in ``_SNAP_FIELDS`` (or carry it
via the scalar hooks) — a field missing from the snapshot restores
stale zeros after a crash-recover, the bug class
``test_checkpoint_roundtrip`` hunts one class at a time.  We flag
array-valued ``self.X = np.zeros/...`` fields of mixin subclasses
missing from both ``_SNAP_FIELDS`` and the scalar-hook sources, and
``_SNAP_FIELDS`` entries with no matching array assignment.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from libjitsi_tpu.analysis.core import (FileContext, Finding,
                                        call_func_name, node_name)

RULE = "drift"

COUNTER_NAME_RE = re.compile(
    r"(_count|_counts|_frames|_errors|_dropped|_drops|_sent|_served|"
    r"_miss|_misses|_recovered|_rejects|_rejected|_fail|_fails|"
    r"_abandoned|_suppressed|_late|_switches|_restarts|_evicted|"
    r"_expired|_total|_syscalls|_reaps)$"
    r"|^(dropped|lost|forwarded|switches|recovered)")

ARRAY_CTORS = {"zeros", "full", "empty", "ones", "array", "tile",
               "arange", "copy"}


# ------------------------------------------------------------ snapshot half

def check_snapshot_drift(ctx: FileContext) -> List[Finding]:
    findings: List[Optional[Finding]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {node_name(b) for b in node.bases}
        if "ArraySnapshotMixin" not in bases:
            continue
        findings.extend(_check_snapshot_class(ctx, node))
    return [f for f in findings if f is not None]


def _check_snapshot_class(ctx: FileContext, cls: ast.ClassDef
                          ) -> List[Optional[Finding]]:
    snap_fields: Set[str] = set()
    snap_fields_node: Optional[ast.AST] = None
    scalar_hook_names: Set[str] = set()
    array_fields: Dict[str, ast.AST] = {}

    for item in cls.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_SNAP_FIELDS":
                    snap_fields_node = item
                    for n in ast.walk(item.value):
                        if isinstance(n, ast.Constant) and \
                                isinstance(n.value, str):
                            snap_fields.add(n.value)
        elif isinstance(item, ast.FunctionDef):
            if item.name in ("_snap_scalars", "_restore_kwargs",
                             "snapshot", "restore"):
                for n in ast.walk(item):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        scalar_hook_names.add(n.value)
                    name = node_name(n)
                    if name:
                        scalar_hook_names.add(name)
            if item.name == "__init__":
                for n in ast.walk(item):
                    if isinstance(n, ast.Assign) and \
                            _is_array_ctor(n.value):
                        for tgt in n.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self":
                                array_fields[tgt.attr] = n

    out: List[Optional[Finding]] = []
    for field, node in sorted(array_fields.items()):
        if field not in snap_fields and field not in scalar_hook_names:
            out.append(ctx.finding(
                RULE, node,
                f"array field `{field}` of ArraySnapshotMixin subclass "
                f"`{cls.name}` is missing from _SNAP_FIELDS (restores "
                "as stale zeros after crash-recover)"))
    for field in sorted(snap_fields):
        if field not in array_fields:
            out.append(ctx.finding(
                RULE, snap_fields_node or cls,
                f"_SNAP_FIELDS entry `{field}` of `{cls.name}` has no "
                "matching array assignment in __init__ (snapshot() "
                "will AttributeError or copy a non-array)"))
    return out


def _is_array_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ARRAY_CTORS and \
                node_name(fn.value) in ("np", "numpy", "jnp"):
            return True
        # x.copy() / np.asarray(...).astype(...)
        if isinstance(fn, ast.Attribute) and fn.attr in ("copy", "astype"):
            return _is_array_ctor(fn.value) or True
    return False


# ------------------------------------------------------------- metrics half

def _registered_attrs(ctx: FileContext) -> Set[str]:
    """Attribute names exported through MetricsRegistry in this file."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = call_func_name(node)
        if fname == "register_counters" and len(node.args) >= 2:
            for n in ast.walk(node.args[1]):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    # pairs are (attr, help): help texts contain spaces,
                    # attribute names never do
                    if " " not in n.value:
                        out.add(n.value)
        elif fname in ("register_scalar", "register_array"):
            # the reading closure names the attribute: lambda: self.x
            for n in ast.walk(node):
                if isinstance(n, ast.Lambda):
                    for leaf in ast.walk(n.body):
                        if isinstance(leaf, ast.Attribute):
                            out.add(leaf.attr)
                elif isinstance(n, ast.Attribute):
                    out.add(n.attr)
    return out


def _registered_hist_attrs(ctx: FileContext) -> Set[str]:
    """Histogram attribute names that reach the exporter in this file:
    mentioned inside a ``register_histogram(...)`` call, or assigned
    from the ``registry.histogram(...)`` factory (which registers on
    creation, so the factory form has no drift window)."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                call_func_name(node) == "register_histogram":
            for n in ast.walk(node):
                if isinstance(n, ast.Attribute):
                    out.add(n.attr)
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                call_func_name(node.value) == "histogram":
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    out.add(tgt.attr)
                elif isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _class_histograms(ctx: FileContext
                      ) -> List[Tuple[str, ast.AST, Set[str], Set[str]]]:
    """(class, node, ctor-assigned hist attrs, observed hist attrs) for
    every class that constructs a bare ``Histogram(...)``.  Anchoring on
    the constructor assignment keeps `.observe` calls on non-histogram
    objects (Watchdog.observe, LossTracker.observe) out of scope."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        created: Set[str] = set()
        observed: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Call) and \
                    call_func_name(n.value) == "Histogram":
                for tgt in n.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        created.add(tgt.attr)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("observe", "observe_array") and \
                    isinstance(n.func.value, ast.Attribute) and \
                    isinstance(n.func.value.value, ast.Name) and \
                    n.func.value.value.id == "self":
                observed.add(n.func.value.attr)
        if created:
            out.append((node.name, node, created, observed))
    return out


def _class_counters(ctx: FileContext) -> List[Tuple[str, str, ast.AST,
                                                    Set[str]]]:
    """(class, file, node, counter-attrs) for every class that both
    initializes integer counters and increments them."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        zeroed: Dict[str, ast.AST] = {}
        bumped: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Constant) and \
                    n.value.value == 0 and \
                    not isinstance(n.value.value, bool):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        zeroed[tgt.attr] = n
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.op, ast.Add) and \
                    isinstance(n.target, ast.Attribute) and \
                    isinstance(n.target.value, ast.Name) and \
                    n.target.value.id == "self":
                bumped.add(n.target.attr)
        counters = {a for a in zeroed if a in bumped
                    and COUNTER_NAME_RE.search(a)}
        if counters:
            out.append((node.name, ctx.relpath, node, counters))
    return out


#: SloSpec kwargs that reference metric-family names
SLO_REF_KWARGS = ("metric", "bad_metric", "total_metric")


def _registered_metric_names(ctx: FileContext
                             ) -> Tuple[Set[str], Set[str]]:
    """(exact family names, name suffixes) this file hands to the
    registry.  Exact names come from constant first args
    (register_scalar/array/multi/histogram + the ``registry.histogram``
    factory); suffixes come from ``register_counters`` attribute lists
    (full name = ``{prefix}_{attr}`` with a call-site prefix) and from
    f-string names whose constant tail survives prefix
    parameterization (``f"{prefix}_fec_k"`` -> ``fec_k``)."""
    exact: Set[str] = set()
    suffixes: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = call_func_name(node)
        if fname in ("register_scalar", "register_array",
                     "register_multi", "register_histogram",
                     "histogram") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                exact.add(arg.value)
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                tail = arg.values[-1]
                if isinstance(tail, ast.Constant) and \
                        isinstance(tail.value, str):
                    suffixes.add(tail.value.lstrip("_"))
        elif fname == "register_counters" and len(node.args) >= 2:
            for n in ast.walk(node.args[1]):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, str) and " " not in n.value:
                    suffixes.add(n.value)
    return exact, suffixes


def _slo_metric_refs(ctx: FileContext
                     ) -> List[Tuple[str, str, ast.AST]]:
    """(slo name, referenced family name, node) for every constant
    metric kwarg of an ``SloSpec(...)`` construction."""
    out: List[Tuple[str, str, ast.AST]] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and
                call_func_name(node) == "SloSpec"):
            continue
        slo_name = ""
        if node.args and isinstance(node.args[0], ast.Constant):
            slo_name = str(node.args[0].value)
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                slo_name = str(kw.value.value)
        for kw in node.keywords:
            if kw.arg in SLO_REF_KWARGS and \
                    isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str) and kw.value.value:
                out.append((slo_name, kw.value.value, kw.value))
    return out


def _exemplar_hists(ctx: FileContext) -> List[Tuple[str, ast.AST]]:
    """(attr/name, node) assigned from a histogram constructor called
    with a literal ``exemplars=True``."""
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call) and
                call_func_name(node.value) in ("histogram",
                                               "Histogram")):
            continue
        if not any(kw.arg == "exemplars" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is True
                   for kw in node.value.keywords):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute):
                out.append((tgt.attr, node))
            elif isinstance(tgt, ast.Name):
                out.append((tgt.id, node))
    return out


def _exemplar_observed(ctx: FileContext) -> Set[str]:
    """attr/local names whose observe/observe_same/observe_array call
    passes an ``exemplar=`` keyword."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr in ("observe", "observe_same",
                                   "observe_array")):
            continue
        if not any(kw.arg == "exemplar" for kw in node.keywords):
            continue
        holder = node.func.value
        if isinstance(holder, ast.Attribute):
            out.add(holder.attr)
        elif isinstance(holder, ast.Name):
            out.add(holder.id)
    return out


# -------------------------------------------------------- perf-baseline half

def check_perf_baseline(baseline_keys: Set[str],
                        scenario_ids: Set[str]) -> List[str]:
    """Pure comparison: messages for baseline keys matching no perf-gate
    scenario (stale — the gate never reads them) and scenarios with no
    baseline entry (ungated — a regression there never fails)."""
    msgs: List[str] = []
    for key in sorted(baseline_keys - scenario_ids):
        msgs.append(
            f"PERF_BASELINE.json key `{key}` matches no perf_gate "
            "scenario id — stale entry, the gate never compares it")
    for sid in sorted(scenario_ids - baseline_keys):
        msgs.append(
            f"perf_gate scenario `{sid}` has no PERF_BASELINE.json "
            "entry — ungated, a regression there never fails "
            "(run scripts/perf_gate.py --write-baseline)")
    return msgs


def check_baseline_meta(meta: dict) -> List[str]:
    """Pure check of the baseline's ``_meta`` block: the `git` stamp
    must be an abbreviated-or-full lowercase hex commit hash.  A
    baseline stamped "unknown" (or hand-edited prose) can't be traced
    to the commit whose numbers it froze — `--write-baseline` stamps
    HEAD automatically, so anything else means the file was edited by
    hand or written outside a checkout."""
    git = (meta or {}).get("git", "")
    if not re.fullmatch(r"[0-9a-f]{7,40}", str(git)):
        return [
            f"PERF_BASELINE.json _meta.git `{git}` is not a commit "
            "hash — the baseline cannot be traced to the revision it "
            "measured (re-run scripts/perf_gate.py --write-baseline "
            "from a checkout)"]
    # `tree` records working-tree cleanliness at stamp time.  A stamp
    # taken on a dirty tree points `git` at a commit that is NOT the
    # code that produced the numbers (how PR 11's gate run left
    # _meta.git one commit behind the baseline it wrote) —
    # --write-baseline refuses dirty trees now, so any other value
    # means the stamp predates the rule or was hand-edited.
    tree = (meta or {}).get("tree")
    if tree is not None and tree != "clean":
        return [
            f"PERF_BASELINE.json _meta.tree `{tree}` — the baseline "
            "was stamped on a dirty working tree, so _meta.git does "
            "not identify the measured code (commit first, then "
            "re-run scripts/perf_gate.py --write-baseline)"]
    return []


def _perf_gate_scenario_ids(script_path: str) -> Optional[Set[str]]:
    """String keys of the module-level ``SCENARIOS = {...}`` literal in
    scripts/perf_gate.py (AST only, never imported: the gate pulls in
    jax at import time and lint must stay hermetic)."""
    try:
        with open(script_path) as fh:
            tree = ast.parse(fh.read(), filename=script_path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Dict) and any(
                    isinstance(t, ast.Name) and t.id == "SCENARIOS"
                    for t in node.targets):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str)}
    return None


def _perf_baseline_findings(index: Dict[str, FileContext]
                            ) -> List[Finding]:
    """Disk wiring: lint only indexes .py files under the linted tree,
    so the baseline json and the scripts/ gate are read from disk,
    located by walking up from any indexed file."""
    root = None
    for ctx in index.values():
        d = os.path.dirname(os.path.abspath(ctx.path))
        for _ in range(6):
            if os.path.exists(os.path.join(d, "PERF_BASELINE.json")):
                root = d
                break
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        if root:
            break
    if root is None:
        return []
    try:
        with open(os.path.join(root, "PERF_BASELINE.json")) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return [Finding(rule=RULE, path="PERF_BASELINE.json", line=1,
                        col=0, message="PERF_BASELINE.json is not "
                        "valid JSON — the perf gate cannot load it",
                        snippet="PERF_BASELINE.json", symbol="")]
    msgs = check_baseline_meta(doc.get("_meta", {}))
    scenario_ids = _perf_gate_scenario_ids(
        os.path.join(root, "scripts", "perf_gate.py"))
    if scenario_ids is not None:
        baseline_keys = {k for k in doc if not k.startswith("_")}
        msgs.extend(check_perf_baseline(baseline_keys, scenario_ids))
    return [Finding(rule=RULE, path="PERF_BASELINE.json", line=1,
                    col=0, message=msg, snippet=msg, symbol="")
            for msg in msgs]


def check_metrics_drift(index: Dict[str, FileContext]) -> List[Finding]:
    registered: Set[str] = set()
    for ctx in index.values():
        registered |= _registered_attrs(ctx)

    findings: List[Optional[Finding]] = []
    all_counter_attrs: Set[str] = set()
    all_attr_names: Set[str] = set()
    for ctx in index.values():
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Attribute):
                all_attr_names.add(n.attr)
        for cls_name, _rel, node, counters in _class_counters(ctx):
            all_counter_attrs |= counters
            covered = counters & registered
            missing = counters - registered
            if covered and missing:
                for attr in sorted(missing):
                    findings.append(ctx.finding(
                        RULE, node,
                        f"counter `{cls_name}.{attr}` is incremented "
                        "but never registered with MetricsRegistry "
                        "while sibling counters "
                        f"({', '.join(sorted(covered)[:3])}) are — "
                        "invisible in production"))

    # histogram half: a Histogram constructed and fed but never handed
    # to the registry records distributions nobody can scrape
    hist_registered: Set[str] = set()
    for ctx in index.values():
        hist_registered |= _registered_hist_attrs(ctx)
    for ctx in index.values():
        for cls_name, node, created, observed in _class_histograms(ctx):
            for attr in sorted((created & observed) - hist_registered):
                findings.append(ctx.finding(
                    RULE, node,
                    f"histogram `{cls_name}.{attr}` is observed but "
                    "never registered with MetricsRegistry (use "
                    "register_histogram or the registry.histogram "
                    "factory) — invisible in production"))

    # SLO half: a spec naming a family no registration defines burns
    # against a permanently-missing signal
    metric_exact: Set[str] = set()
    metric_suffixes: Set[str] = set()
    for ctx in index.values():
        exact, sufs = _registered_metric_names(ctx)
        metric_exact |= exact
        metric_suffixes |= sufs

    def _family_known(ref: str) -> bool:
        if ref in metric_exact:
            return True
        return any(ref == s or ref.endswith("_" + s)
                   for s in metric_suffixes)

    for ctx in index.values():
        for slo_name, ref, node in _slo_metric_refs(ctx):
            if not _family_known(ref):
                findings.append(ctx.finding(
                    RULE, node,
                    f"SloSpec `{slo_name}` references metric `{ref}` "
                    "that no MetricsRegistry registration defines — "
                    "the burn-rate engine reads an absent family "
                    "forever and this SLO can never fire"))

    # exemplar half: an exemplars=True histogram nobody ever feeds an
    # exemplar ships empty exemplar slots in every OpenMetrics scrape
    exemplar_fed: Set[str] = set()
    for ctx in index.values():
        exemplar_fed |= _exemplar_observed(ctx)
    for ctx in index.values():
        for attr, node in _exemplar_hists(ctx):
            if attr not in exemplar_fed:
                findings.append(ctx.finding(
                    RULE, node,
                    f"histogram `{attr}` is created with "
                    "exemplars=True but no observe call ever passes "
                    "exemplar= — its exemplar slots stay empty in "
                    "every OpenMetrics scrape"))

    # perf-baseline half: PERF_BASELINE.json vs perf_gate SCENARIOS —
    # a stale baseline key silently gates nothing; a scenario with no
    # baseline entry silently never gates
    findings.extend(_perf_baseline_findings(index))

    # vice versa: registered attribute names that exist nowhere
    for ctx in index.values():
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    call_func_name(node) == "register_counters" and
                    len(node.args) >= 2):
                continue
            for n in ast.walk(node.args[1]):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, str) and " " not in n.value \
                        and n.value not in all_attr_names:
                    findings.append(ctx.finding(
                        RULE, n,
                        f"register_counters names `{n.value}` but no "
                        "class defines that attribute (typo -> "
                        "AttributeError at scrape time)"))
    return [f for f in findings if f is not None]
