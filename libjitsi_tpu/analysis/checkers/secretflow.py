"""secret-flow: interprocedural key-material leak detection.

The intra-file ``secret-taint`` rule guards *timing* (branches and
table lookups inside the crypto kernels).  This rule guards
*exposure*: key material must never reach an observability or
serialization surface, no matter how many helper calls it crosses.

Sources (seeded by ``summaries`` during fact extraction):
- reads of secret-named values (``is_secret_name``) inside the
  key-material modules listed in ``SOURCE_SCOPES`` — DTLS exported
  keys in the lifecycle/handshake plane, KDF outputs and keystream
  slot tables under ``transform/srtp/``, trunk keys in
  ``mesh/cascade.py``, raw key schedules in ``kernels/``;
- return values of the exporter functions in
  ``summaries.SOURCE_FUNCS`` (``srtp_keys``,
  ``export_keying_material``, ``derive_session_keys*``) anywhere in
  the tree.

Sinks: structured-log calls, ``FlightRecorder.record`` payloads,
``MetricsRegistry`` label values (``set_stream_name``), ``/debug/*``
endpoint JSON in ``service/obs_server.py``, plaintext checkpoint
serialization (``pickle.dump``), and exception payloads.

Structure-only access stays legal exactly as in the intra-file rule:
``len(key)``, ``key.shape``, ``key is None`` and boolean verdicts
carry no taint.  Each finding anchors at the SINK line and carries the
full source -> hops -> sink trace; suppression pragmas work at either
end of the flow (sink side or source side).

Real findings here are fixed, never baselined — this is the rule the
ROADMAP's E2EE item names as its prerequisite ("inner keys never
reach SFU-side code"): inner-key sources will ride the same engine.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from libjitsi_tpu.analysis import summaries as _summaries
from libjitsi_tpu.analysis.core import Finding

RULE = "secret-flow"

#: package-relative prefixes whose secret-NAMED values are taint
#: sources (the modules that hold real key material)
SOURCE_SCOPES = ("kernels/", "transform/srtp/", "control/dtls.py",
                 "control/zrtp.py", "service/lifecycle.py",
                 "service/sfu_bridge.py", "mesh/cascade.py")


def in_source_scope(relpath: str) -> bool:
    p = relpath.replace("\\", "/").split("libjitsi_tpu/")[-1]
    return any(p.startswith(pre) for pre in SOURCE_SCOPES)


def _source_hop(engine, ground) -> Optional[dict]:
    """Trace hop describing where a ground source atom was read."""
    kind, fid, which = ground
    fn = engine.fns.get(fid)
    if fn is None:
        return None
    rel, _, qual = fid.partition("::")
    if kind == "SRC":
        src = fn["sources"][int(which)]
        return {"path": rel, "line": src["l"], "symbol": qual,
                "note": f"secret-named value `{src['n']}`"}
    if kind == "SRCCALL":
        cs = fn["calls"][int(which)]
        return {"path": rel, "line": cs["l"], "symbol": qual,
                "note": f"key material from {cs['n']}(...)"}
    return None


def check_secret_flow(index) -> List[Finding]:
    """`index` is a TreeIndex (facts + call graph)."""
    engine = _summaries.TaintEngine(index.graph)
    sinks = engine.solve_sinks()

    out: List[Finding] = []
    seen = set()
    for fid, per_atom in sinks.items():
        for ground, entries in per_atom.items():
            if ground[0] not in ("SRC", "SRCCALL"):
                continue
            src_hop = _source_hop(engine, ground)
            if src_hop is None:
                continue
            for e in entries:
                sink_hop = e["path"][-1]
                key = (ground, e["kind"], sink_hop["path"],
                       sink_hop["line"])
                if key in seen:
                    continue
                seen.add(key)
                trace = [src_hop] + e["path"]
                sink_facts = index.facts.get(sink_hop["path"])
                src_facts = index.facts.get(src_hop["path"])
                # pragma scope: either end of the flow may waive it
                if src_facts is not None and src_facts.suppressed(
                        RULE, src_hop["line"]):
                    continue
                if sink_facts is None:
                    continue
                f = sink_facts.finding(
                    RULE, sink_hop["line"], 0,
                    f"key material ({src_hop['note']} in "
                    f"{src_hop['path']}:{src_hop['line']}) reaches "
                    f"{e['kind']} sink after "
                    f"{len(e['path']) - 1} call hop(s) — secrets "
                    "must never reach logs, flight payloads, metrics "
                    "labels, debug endpoints, checkpoints, or "
                    "exception text",
                    trace=trace)
                if f is not None:
                    out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.message))
    return out
