"""plane-affinity: static proof of the tick/off-tick plane split.

PR 16 split the bridge into two execution planes: the MediaLoop tick
(per-packet datapath, hard deadline) and the off-tick lifecycle window
(DTLS handshakes, OpenSSL, keystream refill, commits).  The runtime
invariant is ``handshake_tick_thread_feeds == 0``; this rule is its
static twin — call-graph reachability from the declared plane roots.

Roots are declared two ways: the built-in tables below (the known
entry points), and ``# jitlint: plane=tick|off_tick|dual`` annotations
on ``def`` lines.  Traversal from the tick root flags:

- any off-tick plane ENTRY point it can reach (``poll``, ``drain``,
  ``process``, ``fill`` — tick code scheduling lifecycle work inline);
- any handshake/OpenSSL-class function (``feed``, ``do_handshake``,
  direct ``_lib``/OpenSSL FFI work) not declared as a plane boundary;
- keystream ``fill`` work (serving cached slots on tick is the design;
  FILLING them is off-tick only);
- blocking calls (``time.sleep``, ``pickle.dump/load``) anywhere in
  tick-reachable code.

``plane=dual`` marks a function that legitimately runs on its
caller's plane — the legacy inline-DTLS path (`_process_one` in
non-deferred standalone-bridge mode) — traversal cuts there without
flagging; the deferred flag plus the runtime counter keep the managed
path honest, and the annotation makes the exception reviewable.

Second rule, any plane: a raw SRTP table ``add_stream``/``add_streams``
key install reachable outside the staged commit barrier
(``stage_endpoints`` / ``stage_dtls_keys`` / ``commit_endpoints`` /
the sanctioned legacy ``_install_dtls``) bypasses the epoch the
barrier exists to provide — keys must land through staging.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from libjitsi_tpu.analysis.core import Finding

RULE = "plane-affinity"

#: (relpath suffix, qualname) built-in plane roots
TICK_ROOTS = (("io/loop.py", "MediaLoop.tick"),)
OFF_TICK_ROOTS = (
    ("service/lifecycle.py", "StreamLifecycleManager.run_between_ticks"),
    ("service/lifecycle.py", "StreamLifecycleManager.poll"),
    ("service/lifecycle.py", "HandshakeQueue.drain"),
    ("control/dtls.py", "DtlsAssociationTable.process"),
    ("transform/srtp/keystream.py", "KeystreamCache.fill"),
)

#: handshake/OpenSSL-class work: these must never be tick-reachable
HANDSHAKE_FUNCS = {"feed", "do_handshake", "handshake"}
HANDSHAKE_SCOPE = "control/"

#: dotted call targets that block the caller's thread
BLOCKING_CALLS = {"time.sleep", "pickle.dump", "pickle.dumps",
                  "pickle.load", "pickle.loads"}

#: the sanctioned key-install surfaces (staged commit barrier + the
#: documented legacy inline twin)
BARRIER_FUNCS = {"stage_endpoints", "stage_dtls_keys",
                 "commit_endpoints", "_install_dtls"}

INSTALL_CALLS = {"add_stream", "add_streams"}

#: receiver spelling fragments that make an install call an SRTP
#: table install (vs ReceiveBank bookkeeping etc.)
_TABLE_TOKENS = ("table", "_rx", "_tx", "rx_", "tx_")


def _pkg_rel(relpath: str) -> str:
    return relpath.replace("\\", "/").split("libjitsi_tpu/")[-1]


def _fn_work_class(graph, fid: str, fn: dict) -> Optional[str]:
    """Work category of `fid` that must never run on the tick plane,
    or None for ordinary datapath code."""
    rel, _, qual = fid.partition("::")
    p = _pkg_rel(rel)
    name = fn["name"]
    if p.startswith(HANDSHAKE_SCOPE) and name in HANDSHAKE_FUNCS:
        return "handshake/OpenSSL work"
    if name == "fill" and p.startswith("transform/srtp/"):
        return "keystream fill work"
    for cs in fn.get("calls", ()):
        dotted = graph.dotted(rel, cs)
        recv = cs.get("r") or ""
        if dotted.startswith("_openssl.") or "._lib." in f".{recv}." \
                or recv.endswith("._lib") or recv == "_lib":
            return "direct OpenSSL FFI work"
        # a control/ function driving `ep.feed(...)`-style handshake
        # dispatch is handshake work even when the receiver's class
        # cannot be resolved (association tables hold mixed endpoints)
        if p.startswith(HANDSHAKE_SCOPE) and recv \
                and cs["n"] in HANDSHAKE_FUNCS:
            return "handshake/OpenSSL work"
    return None


def _roots(graph, table, plane: str) -> Dict[str, str]:
    """{fid: plane} for built-in roots present in the tree plus any
    annotated functions of that plane."""
    out: Dict[str, str] = {}
    for suffix, qual in table:
        fid = graph.find(suffix, qual)
        if fid is not None:
            out[fid] = plane
    for rel, f in graph.facts.items():
        for qual, fn in f["functions"].items():
            if fn.get("plane") == plane:
                out[f"{rel}::{qual}"] = plane
    return out


def _trace(parents: Dict[str, Tuple[Optional[str], int]], fid: str,
           graph, extra_line: Optional[int] = None) -> List[dict]:
    """Root -> ... -> fid hop list from BFS parent pointers."""
    hops = []
    cur: Optional[str] = fid
    line = extra_line
    while cur is not None:
        rel, _, qual = cur.partition("::")
        fn = graph.function(cur)
        hops.append({"path": rel,
                     "line": line if line is not None
                     else (fn or {}).get("line", 1),
                     "symbol": qual, "note": ""})
        cur, line = parents.get(cur, (None, None))
    hops.reverse()
    hops[0]["note"] = "plane root"
    return hops


def check_plane_affinity(index) -> List[Finding]:
    graph = index.graph
    tick_roots = _roots(graph, TICK_ROOTS, "tick")
    off_roots = _roots(graph, OFF_TICK_ROOTS, "off_tick")

    def finding(rel: str, line: int, message: str,
                trace: Optional[List[dict]] = None
                ) -> Optional[Finding]:
        facts = index.facts.get(rel)
        if facts is None:
            return None
        return facts.finding(RULE, line, 0, message, trace=trace)

    out: List[Finding] = []

    # ---- rule 1: BFS from the tick root; flag off-tick entries and
    # work-class functions, cut at declared plane boundaries
    visited: Set[str] = set()
    parents: Dict[str, Tuple[Optional[str], int]] = {}
    work = [fid for fid in tick_roots]
    flagged: Set[Tuple[str, str]] = set()
    while work:
        fid = work.pop(0)
        if fid in visited:
            continue
        visited.add(fid)
        fn = graph.function(fid)
        if fn is None:
            continue
        rel, _, qual = fid.partition("::")
        for i, cs in enumerate(fn.get("calls", ())):
            dotted = graph.dotted(rel, cs)
            if dotted in BLOCKING_CALLS:
                tr = _trace(parents, fid, graph)
                tr.append({"path": rel, "line": cs["l"],
                           "symbol": qual,
                           "note": f"blocking call {dotted}(...)"})
                f = finding(
                    rel, cs["l"],
                    f"blocking call `{dotted}` is reachable from the "
                    f"tick root {'/'.join(q for _, q in TICK_ROOTS)} — "
                    "the tick thread must never block (move it to the "
                    "off-tick lifecycle window)", trace=tr)
                if f is not None and ("blk", f"{rel}:{cs['l']}") \
                        not in flagged:
                    flagged.add(("blk", f"{rel}:{cs['l']}"))
                    out.append(f)
            callee = graph.resolve(rel, qual, cs)
            if callee is None or callee in visited:
                continue
            cfn = graph.function(callee)
            if cfn is None:
                continue
            plane = cfn.get("plane")
            is_off_root = callee in off_roots
            if plane == "dual":
                continue  # declared boundary: cut, no flag
            if plane == "off_tick" or is_off_root:
                crel, _, cqual = callee.partition("::")
                tr = _trace(parents, fid, graph)
                tr.append({"path": crel, "line": cfn["line"],
                           "symbol": cqual,
                           "note": "off-tick plane entry"})
                f = finding(
                    rel, cs["l"],
                    f"off-tick plane entry `{cqual}` is reachable "
                    "from the tick root — lifecycle/handshake/fill "
                    "work belongs in run_between_ticks, not the "
                    "packet tick", trace=tr)
                if f is not None and ("off", callee) not in flagged:
                    flagged.add(("off", callee))
                    out.append(f)
                continue  # do not traverse into the other plane
            wc = _fn_work_class(graph, callee, cfn)
            if wc is not None:
                crel, _, cqual = callee.partition("::")
                tr = _trace(parents, fid, graph)
                tr.append({"path": crel, "line": cfn["line"],
                           "symbol": cqual, "note": wc})
                f = finding(
                    rel, cs["l"],
                    f"`{cqual}` ({wc}) is reachable from the tick "
                    "root — the static twin of "
                    "handshake_tick_thread_feeds == 0 (defer to the "
                    "handshake queue / off-tick window)", trace=tr)
                if f is not None and ("work", callee) not in flagged:
                    flagged.add(("work", callee))
                    out.append(f)
                continue
            parents[callee] = (fid, cs["l"])
            work.append(callee)

    # ---- rule 2: raw SRTP key installs outside the commit barrier,
    # reachable from ANY plane root without traversing a barrier fn
    reach: Set[str] = set()
    parents2: Dict[str, Tuple[Optional[str], int]] = {}
    work = list(tick_roots) + list(off_roots)
    while work:
        fid = work.pop(0)
        if fid in reach:
            continue
        reach.add(fid)
        fn = graph.function(fid)
        if fn is None:
            continue
        rel, _, qual = fid.partition("::")
        for cs in fn.get("calls", ()):
            callee = graph.resolve(rel, qual, cs)
            if callee is None or callee in reach:
                continue
            cfn = graph.function(callee)
            if cfn is None or cfn["name"] in BARRIER_FUNCS:
                continue  # the barrier is the sanctioned surface
            parents2[callee] = (fid, cs["l"])
            work.append(callee)

    for fid in sorted(reach):
        fn = graph.function(fid)
        if fn is None or fn["name"] in BARRIER_FUNCS:
            continue
        rel, _, qual = fid.partition("::")
        for cs in fn.get("calls", ()):
            recv = (cs.get("r") or "").lower()
            if cs["n"] not in INSTALL_CALLS:
                continue
            if not any(tok in recv for tok in _TABLE_TOKENS):
                continue
            # warmup installs land dummy keys in throwaway scratch
            # tables to pre-compile kernels — not live key state
            if "scratch" in recv or fn["name"].startswith("warmup"):
                continue
            tr = _trace(parents2, fid, graph, extra_line=cs["l"])
            tr[-1]["note"] = f"raw {recv}.{cs['n']}(...) key install"
            f = finding(
                rel, cs["l"],
                f"SRTP key install `{recv}.{cs['n']}` is reachable "
                "from a plane root without passing the staged commit "
                "barrier (stage_endpoints/stage_dtls_keys/"
                "commit_endpoints) — keys must land through staging",
                trace=tr)
            if f is not None:
                out.append(f)

    out.sort(key=lambda f: (f.path, f.line, f.message))
    return out
