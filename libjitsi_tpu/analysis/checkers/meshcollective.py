"""mesh-collective: cross-chip collectives outside sanctioned sites.

PR 10's conference-affinity layout makes "zero cross-chip collectives
on the steady-state tick" an architectural invariant, not a habit: a
conference never straddles chips, so the mix-minus is a shard-local
``segment_sum`` and the only collectives left in ``mesh/`` are the
explicit giant-conference escape hatches enumerated in
``libjitsi_tpu/mesh/placement.py``'s ``SANCTIONED_COLLECTIVE_SITES``.
This rule is what keeps the invariant true under maintenance: any
``psum`` / ``all_gather`` / ``ppermute`` (or kin) appearing in a
``mesh/`` module outside a sanctioned (file, function) pair fails the
lint gate — the perf claim "aggregate scaling is exact because shards
share nothing" (``mesh_agg_pps_ratio``) is only as strong as this
check.

Global checker (not per-file): the sanctioned list is parsed from
``placement.py``'s AST inside the same index, so placement stays the
single source of truth and lint never imports jax.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from libjitsi_tpu.analysis.core import FileContext, Finding, node_name

RULE = "mesh-collective"

#: cross-device communication primitives (jax.lax and shard_map-body
#: spellings); anything here outside a sanctioned site is a finding
COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
               "all_to_all", "ppermute", "pshuffle", "psum_scatter"}

_PLACEMENT_SUFFIX = "mesh/placement.py"


def _in_mesh_module(relpath: str) -> bool:
    return "/mesh/" in relpath or relpath.startswith("mesh/")


def _parse_sanctioned(ctx: FileContext) -> Optional[List[List[str]]]:
    """(path, function) pairs from placement.py's module-level
    ``SANCTIONED_COLLECTIVE_SITES`` tuple literal (AST only)."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.AnnAssign) and
                isinstance(node.target, ast.Name) and
                node.target.id == "SANCTIONED_COLLECTIVE_SITES"):
            if not (isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and
                    t.id == "SANCTIONED_COLLECTIVE_SITES"
                    for t in node.targets)):
                continue
        value = getattr(node, "value", None)
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        sites = []
        for elt in value.elts:
            if isinstance(elt, (ast.Tuple, ast.List)) and \
                    len(elt.elts) == 2 and all(
                        isinstance(e, ast.Constant) and
                        isinstance(e.value, str) for e in elt.elts):
                sites.append([elt.elts[0].value, elt.elts[1].value])
        return sites
    return None


def file_facts(ctx: FileContext) -> dict:
    """Per-file mesh facts (JSON-able): collective call sites with
    their enclosing function names, plus the sanction list when this
    is placement.py itself."""
    facts: dict = {}
    if ctx.relpath.endswith(_PLACEMENT_SUFFIX):
        facts["sanctioned"] = _parse_sanctioned(ctx)
    if _in_mesh_module(ctx.relpath):
        sites = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            coll = _collective_name(node)
            if coll is not None:
                sites.append([coll, node.lineno, node.col_offset,
                              sorted(_enclosing_functions(node))])
        if sites:
            facts["collectives"] = sites
    return facts


def _collective_name(call: ast.Call) -> Optional[str]:
    """Collective id when `call` is one, else None: matches both the
    attribute spelling (`jax.lax.psum`, `lax.psum`) and a bare
    imported name (`psum(...)`)."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in COLLECTIVES:
        return func.attr
    name = node_name(func)
    if name in COLLECTIVES:
        return name
    return None


def _enclosing_functions(node: ast.AST) -> Set[str]:
    names = set()
    cur = getattr(node, "_jl_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(cur.name)
        cur = getattr(cur, "_jl_parent", None)
    return names


def _views(index) -> List[Tuple[str, dict, object]]:
    from libjitsi_tpu.analysis.checkers.drift import _CtxFinder
    out = []
    for rel, v in sorted(index.items()):
        if isinstance(v, FileContext):
            out.append((rel, file_facts(v), _CtxFinder(v)))
        else:
            out.append((rel, v.data["mesh"], v))
    return out


def check_mesh_collectives(index) -> List[Finding]:
    views = _views(index)
    sanctioned: Set[Tuple[str, str]] = set()
    for rel, facts, _f in views:
        if rel.endswith(_PLACEMENT_SUFFIX):
            sanctioned = {(p, fn)
                          for p, fn in facts.get("sanctioned") or ()}
    out: List[Optional[Finding]] = []
    for relpath, facts, finder in views:
        site_funcs = {fn for path, fn in sanctioned
                      if relpath.endswith(path)}
        for coll, line, col, enclosing in facts.get("collectives", ()):
            if relpath.endswith(_PLACEMENT_SUFFIX):
                # placement module itself defines the sanction list;
                # a collective THERE would be the steady-state tick
                # regressing — never sanctioned
                pass
            elif set(enclosing) & site_funcs:
                continue
            out.append(finder.finding(
                RULE, line, col,
                f"cross-chip collective `{coll}` outside the "
                "sanctioned escape hatches "
                "(mesh/placement.py SANCTIONED_COLLECTIVE_SITES): "
                "the steady-state tick must stay shard-local — place "
                "whole conferences (ConferencePlacer) instead of "
                "participant-sharding, or sanction the site "
                "explicitly"))
    return [f for f in out if f is not None]
