"""hotpath-purity: host syncs and tracer-dependent Python control flow
inside jitted functions.

One `.item()` (or `int()` on a traced array) inside a `@jax.jit` body
re-introduces a ~100 ms device->host sync per batch — the exact
regression class the scalar-fetch-floor work in bench.py measures.  A
Python `if`/`while` on a tracer either crashes at trace time (caught by
tests only if that branch is exercised) or, worse, silently bakes one
side into the compiled program.  `np.asarray` on a traced value forces
materialization.  Data-dependent-shape ops (`nonzero`/`unique` without
`size=`) retrace or fail on TPU.

Jit scopes found:
- decorators: ``@jax.jit``, ``@jit``, ``@functools.partial(jax.jit,
  static_argnames=(...))``, ``@partial(jit, ...)``
- call-wrapped local functions: ``jax.jit(fn)`` / ``jax.jit(
  jax.shard_map(fn, ...))`` where ``fn`` is a def in the same module.

`static_argnames` parameters are exempt from taint (they are Python
values at trace time); `x is None` tests are pytree-structure checks
and legal.  `lax.cond`/`jnp.where`/`lax.select` are calls, not Python
branches, and never fire.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from libjitsi_tpu.analysis.core import (FileContext, Finding, call_func_name,
                                        is_none_check, names_in, node_name,
                                        propagate_taint, tainted_leaves)

RULE = "hotpath-purity"

#: methods that synchronously pull device data to the host
SYNC_METHODS = {"item", "tolist", "to_py", "block_until_ready"}
#: shape-unstable calls that retrace or fail under jit without size=
SHAPE_UNSTABLE = {"nonzero", "unique", "flatnonzero", "argwhere", "where"}
HOST_CASTS = {"int", "float", "bool", "complex"}
HOST_ARRAY = {"asarray", "array"}   # flagged when the module is numpy's


def _decorator_jit_info(dec: ast.AST) -> Optional[Set[str]]:
    """Returns static_argnames when `dec` marks a jit function, else None."""
    name = node_name(dec) if not isinstance(dec, ast.Call) else None
    if name in {"jit"}:
        return set()
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return set()
    if isinstance(dec, ast.Call):
        fn = call_func_name(dec)
        if fn == "jit":
            return _static_argnames(dec)
        if fn == "partial" and dec.args:
            inner = node_name(dec.args[0])
            if inner == "jit":
                return _static_argnames(dec)
    return None


def _static_argnames(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
    return out


def _call_wrapped_jit_names(tree: ast.AST) -> Set[str]:
    """Function names passed (possibly nested) into a jax.jit(...) call."""
    wrapped: Set[str] = set()

    def collect(node: ast.AST) -> None:
        if isinstance(node, ast.Name):
            wrapped.add(node.id)
        elif isinstance(node, ast.Call):
            for a in node.args:
                collect(a)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_func_name(node) == "jit":
            for a in node.args:
                collect(a)
    return wrapped


def _function_params(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    return params


def check_hotpath_purity(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    wrapped = _call_wrapped_jit_names(ctx.tree)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        static: Optional[Set[str]] = None
        for dec in node.decorator_list:
            info = _decorator_jit_info(dec)
            if info is not None:
                static = info
                break
        if static is None and node.name in wrapped:
            static = set()
        if static is None:
            continue
        findings.extend(_check_jit_body(ctx, node, static))
    return [f for f in findings if f is not None]


def _check_jit_body(ctx: FileContext, fn: ast.FunctionDef,
                    static: Set[str]) -> List[Optional[Finding]]:
    tainted = set(_function_params(fn)) - static - {"self", "cls"}
    tainted = propagate_taint(fn.body, tainted)
    out: List[Optional[Finding]] = []

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            fname = call_func_name(node)
            # host syncs: x.item(), x.tolist(), ...
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_METHODS \
                    and names_in(node.func.value) & tainted:
                out.append(ctx.finding(
                    RULE, node,
                    f"`.{node.func.attr}()` on a traced value inside "
                    f"jitted `{fn.name}` forces a device->host sync"))
            # int()/float()/bool() on traced values
            elif fname in HOST_CASTS and node.args and \
                    tainted_leaves(node.args[0], tainted):
                out.append(ctx.finding(
                    RULE, node,
                    f"`{fname}()` on a traced value inside jitted "
                    f"`{fn.name}` forces a device->host sync (use "
                    "lax/jnp ops or hoist to the caller)"))
            # np.asarray / np.array on traced values
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in HOST_ARRAY \
                    and node_name(node.func.value) in ("np", "numpy") \
                    and node.args and tainted_leaves(node.args[0], tainted):
                out.append(ctx.finding(
                    RULE, node,
                    f"`np.{node.func.attr}` on a traced value inside "
                    f"jitted `{fn.name}` materializes on the host; use "
                    "jnp"))
            # shape-unstable ops without a static size
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SHAPE_UNSTABLE \
                    and node_name(node.func.value) in ("jnp", "np", "numpy",
                                                       "lax", "jax"):
                kwargs = {kw.arg for kw in node.keywords}
                # one-arg jnp.where is shape-unstable; 3-arg is select
                if node.func.attr == "where" and len(node.args) != 1:
                    continue
                if "size" not in kwargs and \
                        names_in(node) & tainted:
                    out.append(ctx.finding(
                        RULE, node,
                        f"`{node.func.attr}` without `size=` inside "
                        f"jitted `{fn.name}` has a data-dependent "
                        "output shape (retrace storm / trace error)"))
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
            if is_none_check(test):
                continue
            leaves = tainted_leaves(test, tainted)
            if leaves:
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression"}[type(node)]
                name = node_name(leaves[0])
                out.append(ctx.finding(
                    RULE, node,
                    f"Python `{kind}` on tracer-derived `{name}` inside "
                    f"jitted `{fn.name}` (use lax.cond/jnp.where; "
                    "Python control flow bakes one branch into the "
                    "trace)"))
        elif isinstance(node, ast.Assert):
            if tainted_leaves(node.test, tainted):
                out.append(ctx.finding(
                    RULE, node,
                    f"`assert` on a traced value inside jitted "
                    f"`{fn.name}` (trace-time no-op or host sync; use "
                    "checkify or move to the caller)"))
    return out
