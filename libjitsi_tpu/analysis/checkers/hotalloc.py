"""hotpath-alloc: per-tick bulk allocations in the host I/O modules.

The zero-copy arena work (io/udp.py) exists because `buf[:n].copy()` on
every recv window was the single largest host cost in the phase ledger:
a fresh O(batch x capacity) allocation + memcpy per tick, then another
on egress (`np.ascontiguousarray`) to re-materialize rows the arena
already held contiguously.  This rule keeps those from creeping back.

Scope: functions in the ``libjitsi_tpu/io/`` modules — the per-tick
hot path — excluding dunders (constructors allocate by design) and the
teardown/observability surface.  Flagged forms:

- ``x.copy()`` method calls (ndarray copy),
- ``np.copy(x)`` / ``numpy.copy(x)``,
- ``np.ascontiguousarray(x)`` / ``numpy.ascontiguousarray(x)``.

Deliberate copies — the legacy copy-semantics recv API, per-row
metadata staging for the C ABI — carry ``# jitlint:
disable=hotpath-alloc`` pragmas stating why the allocation stays.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from libjitsi_tpu.analysis.core import (FileContext, Finding,
                                        call_func_name, node_name)

RULE = "hotpath-alloc"

#: path fragments marking host-I/O tick modules
HOT_DIR_FRAGMENT = "/io/"

#: function names exempt from the rule even inside hot modules: one-time
#: setup/teardown and metrics render paths, not per-tick work
COLD_FUNCS = {"close", "register_metrics"}

ALLOC_FUNCS = {"copy", "ascontiguousarray"}


def _in_hot_module(ctx: FileContext) -> bool:
    path = ctx.relpath
    return HOT_DIR_FRAGMENT in path or path.startswith("io/")


def _enclosing_function(node: ast.AST) -> Optional[str]:
    cur = getattr(node, "_jl_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = getattr(cur, "_jl_parent", None)
    return None


def check_hotpath_alloc(ctx: FileContext) -> List[Finding]:
    if not _in_hot_module(ctx):
        return []
    out: List[Optional[Finding]] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        msg = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "copy" and not node.args:
            owner = node_name(node.func.value)
            if owner in ("np", "numpy"):
                continue                    # np.copy handled below
            msg = "`.copy()` in a host-I/O tick path allocates per " \
                  "tick; return an arena view (recv_batch_view) or " \
                  "gather-send (send_rows) instead"
        elif call_func_name(node) in ALLOC_FUNCS \
                and isinstance(node.func, ast.Attribute) \
                and node_name(node.func.value) in ("np", "numpy"):
            msg = (f"`np.{node.func.attr}` in a host-I/O tick path "
                   "re-materializes a contiguous copy per tick; keep "
                   "rows contiguous at the source or use the native "
                   "gather path")
        if msg is None:
            continue
        fn = _enclosing_function(node)
        if fn is None:                       # module level: import-time
            continue
        if fn in COLD_FUNCS or (fn.startswith("__")
                                and fn.endswith("__")):
            continue
        out.append(ctx.finding(RULE, node, msg))
    return [f for f in out if f is not None]
