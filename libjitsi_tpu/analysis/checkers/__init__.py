"""Checker registry.  Per-file checkers run in the parallel driver;
global checkers run once over the whole parsed index (cross-file
facts: metric registrations vs counter definitions)."""

from libjitsi_tpu.analysis.checkers.drift import (check_snapshot_drift,
                                                  check_metrics_drift)
from libjitsi_tpu.analysis.checkers.hotalloc import check_hotpath_alloc
from libjitsi_tpu.analysis.checkers.hotpath import check_hotpath_purity
from libjitsi_tpu.analysis.checkers.meshcollective import (
    check_mesh_collectives)
from libjitsi_tpu.analysis.checkers.rtpmod16 import check_rtp_mod16
from libjitsi_tpu.analysis.checkers.secrets import check_secret_taint

#: checker(ctx) -> [Finding]
PER_FILE_CHECKERS = (
    check_hotpath_purity,
    check_hotpath_alloc,
    check_secret_taint,
    check_rtp_mod16,
    check_snapshot_drift,
)

#: checker({relpath: ctx}) -> [Finding]
GLOBAL_CHECKERS = (
    check_metrics_drift,
    check_mesh_collectives,
)

RULES = ("hotpath-purity", "hotpath-alloc", "secret-taint", "rtp-mod16",
         "drift", "mesh-collective")
