"""Checker registry.  Per-file checkers run in the parallel driver
(and their findings are cached per file); global checkers run once
over the whole-tree facts index (cross-file facts: metric
registrations vs counter definitions); graph checkers run over the
TreeIndex's module-resolved call graph (interprocedural taint and
plane reachability)."""

from libjitsi_tpu.analysis.checkers.drift import (check_snapshot_drift,
                                                  check_metrics_drift)
from libjitsi_tpu.analysis.checkers.hotalloc import check_hotpath_alloc
from libjitsi_tpu.analysis.checkers.hotpath import check_hotpath_purity
from libjitsi_tpu.analysis.checkers.meshcollective import (
    check_mesh_collectives)
from libjitsi_tpu.analysis.checkers.planeaffinity import (
    check_plane_affinity)
from libjitsi_tpu.analysis.checkers.rtpmod16 import check_rtp_mod16
from libjitsi_tpu.analysis.checkers.secretflow import check_secret_flow
from libjitsi_tpu.analysis.checkers.secrets import check_secret_taint

#: checker(ctx) -> [Finding]
PER_FILE_CHECKERS = (
    check_hotpath_purity,
    check_hotpath_alloc,
    check_secret_taint,
    check_rtp_mod16,
    check_snapshot_drift,
)

#: checker({relpath: facts-or-ctx}) -> [Finding]
GLOBAL_CHECKERS = (
    check_metrics_drift,
    check_mesh_collectives,
)

#: checker(TreeIndex) -> [Finding] — need the resolved call graph
GRAPH_CHECKERS = (
    check_secret_flow,
    check_plane_affinity,
)

RULES = ("hotpath-purity", "hotpath-alloc", "secret-taint", "rtp-mod16",
         "drift", "mesh-collective", "secret-flow", "plane-affinity")
