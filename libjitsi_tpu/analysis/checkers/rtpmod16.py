"""rtp-mod16: raw arithmetic/comparison on 16-bit RTP sequence numbers
outside ``core/rtp_math.py``.

PR 2's seq-wrap fixes (jitter buffer bulk gap-skip, PacketCache
``lookup_nack`` rotation, ``rtcp.build_nack`` PID/BLP packing) all came
from the same bug class: ``a - b`` or ``a < b`` on values that live on
the mod-2^16 circle.  The discipline that prevents it is
``core/rtp_math.py`` (`seq_delta`/`is_newer_seq`/`as_seq`) or explicit
masking at the use site.  This checker flags, on any name that looks
seq/roc-like:

- ``+``/``-``/``*`` whose result is not masked (``& 0xFFFF``/``% ...``)
  in the same expression and not already inside an rtp_math helper call;
- ``<``/``<=``/``>``/``>=`` against anything but an integer literal
  (literal compares are sentinel/bounds checks — ``seq >= 0``);
- ``min()``/``max()`` over seq values (wrap-unsafe ordering);
- slices and ``range()``/``arange()`` spans with seq bounds
  (wrap-unsafe seq-range walks).

Names with an ``ext``/``unwrapped``/``index`` token are 64-bit extended
counters (`SeqNumUnwrapper` output, RFC 3711 packet indices) where raw
arithmetic is the POINT — they are exempt, and renaming a variable to
say what it is (`..._ext`) is the documented fix for counters that
never touch the wire.  Equality compares are wrap-safe and exempt.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from libjitsi_tpu.analysis.core import (FileContext, Finding, call_func_name,
                                        int_const, node_name)

RULE = "rtp-mod16"

SEQ_TOKENS = {"seq", "seqs", "seqno", "seqnum", "roc", "rollover"}
#: tokens marking a 64-bit extended/unwrapped counter — raw math is fine
EXT_TOKENS = {"ext", "extended", "unwrapped", "uts", "index", "indices",
              "idx"}
#: tokens marking a value that is ABOUT seqs but not on the mod-2^16
#: circle: container/window sizes, signed deltas, masks
META_TOKENS = {"window", "cap", "limit", "budget", "map", "mask", "mod",
               "delta", "deltas", "width", "depth", "count", "gap",
               "gaps", "span"}
#: rtp_math helpers (and wrap-aware wrappers) whose argument expressions
#: are safe: they mask/fold internally
SAFE_CALLS = {"seq_delta", "is_newer_seq", "is_older_seq", "as_seq",
              "as_ts", "estimate_packet_index", "chain_packet_indices",
              "update_index_state", "unwrap", "segment_ranks"}
WRAP_SAFE_BINOPS = (ast.BitAnd, ast.BitOr, ast.BitXor, ast.Mod,
                    ast.RShift, ast.LShift, ast.FloorDiv)


def is_seq_name(name: Optional[str]) -> bool:
    if not name:
        return False
    tokens = set(re.split(r"[_\d]+", name.lower())) - {""}
    if tokens & (EXT_TOKENS | META_TOKENS):
        return False
    if tokens & SEQ_TOKENS:
        return True
    # twseq/wireseq-style compounds
    return any(t.endswith("seq") for t in tokens)


def _seq_operand(node: ast.AST) -> Optional[str]:
    """Seq-ish identifier at the top of an operand expression (through
    unary ops, int() casts and plain subscripts like seqs[i])."""
    if isinstance(node, ast.UnaryOp):
        return _seq_operand(node.operand)
    if isinstance(node, ast.Call) and call_func_name(node) == "int" \
            and node.args:
        return _seq_operand(node.args[0])
    if isinstance(node, ast.Subscript):
        return _seq_operand(node.value)
    name = node_name(node)
    return name if is_seq_name(name) else None


def _masked_or_safe(node: ast.AST) -> bool:
    """True when an ancestor within the same expression masks the value
    (``& 0xFFFF``, ``% MOD``, shifts) or hands it to an rtp_math
    helper."""
    cur = node
    parent = getattr(cur, "_jl_parent", None)
    while parent is not None:
        if isinstance(parent, ast.BinOp) and \
                isinstance(parent.op, WRAP_SAFE_BINOPS):
            return True
        if isinstance(parent, ast.Call):
            fname = call_func_name(parent)
            if fname in SAFE_CALLS:
                return True
        if isinstance(parent, ast.stmt):
            return False
        cur, parent = parent, getattr(parent, "_jl_parent", None)
    return False


def _in_safe_call(node: ast.AST) -> bool:
    parent = getattr(node, "_jl_parent", None)
    while parent is not None and not isinstance(parent, ast.stmt):
        if isinstance(parent, ast.Call) and \
                call_func_name(parent) in SAFE_CALLS:
            return True
        parent = getattr(parent, "_jl_parent", None)
    return False


def check_rtp_mod16(ctx: FileContext) -> List[Finding]:
    if ctx.relpath.endswith("core/rtp_math.py"):
        return []
    findings: List[Optional[Finding]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
            name = _seq_operand(node.left) or _seq_operand(node.right)
            if name and not _masked_or_safe(node):
                op = {ast.Add: "+", ast.Sub: "-",
                      ast.Mult: "*"}[type(node.op)]
                findings.append(ctx.finding(
                    RULE, node,
                    f"raw `{op}` on seq-like `{name}` without a wrap "
                    "mask (use core.rtp_math.seq_delta/as_seq or mask "
                    "with & 0xFFFF in the same expression)"))
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
            name = node_name(node.target)
            if is_seq_name(name) and _seq_operand(node.target):
                findings.append(ctx.finding(
                    RULE, node,
                    f"unmasked in-place arithmetic on seq-like "
                    f"`{name}` (wraps past 2^16; use "
                    "`x = (x + n) & 0xFFFF` or rename to `..._ext` if "
                    "it is a 64-bit extended counter)"))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
            left, right = node.left, node.comparators[0]
            name = _seq_operand(left) or _seq_operand(right)
            if name and not _in_safe_call(node) \
                    and int_const(left) is None \
                    and int_const(right) is None \
                    and not _masked_expr(left) and not _masked_expr(right):
                findings.append(ctx.finding(
                    RULE, node,
                    f"raw ordering compare on seq-like `{name}` "
                    "(misorders across the 2^16 wrap; use "
                    "core.rtp_math.is_newer_seq/seq_delta)"))
        elif isinstance(node, ast.Call):
            fname = call_func_name(node)
            if fname in ("min", "max") and len(node.args) >= 2:
                for a in node.args:
                    name = _seq_operand(a)
                    if name:
                        findings.append(ctx.finding(
                            RULE, node,
                            f"`{fname}()` over seq-like `{name}` is "
                            "wrap-unsafe ordering (compare via "
                            "seq_delta on an anchor instead)"))
                        break
            elif fname in ("range", "arange") and len(node.args) >= 2:
                for a in node.args[:2]:
                    name = _seq_operand(a)
                    if name and not _masked_or_safe(node):
                        findings.append(ctx.finding(
                            RULE, node,
                            f"seq-range walk `{fname}({name}, ...)` is "
                            "wrap-unsafe (iterate a seq_delta-derived "
                            "count and mask each step)"))
                        break
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Slice):
            sl = node.slice
            for bound in (sl.lower, sl.upper):
                if bound is None:
                    continue
                name = _seq_operand(bound)
                if name and not _masked_expr(bound):
                    findings.append(ctx.finding(
                        RULE, node,
                        f"slicing by seq-like `{name}` is wrap-unsafe "
                        "(a wrapped range selects the complement; "
                        "derive lengths via seq_delta)"))
                    break
    return [f for f in findings if f is not None]


def _masked_expr(node: ast.AST) -> bool:
    """The operand expression itself already folds into wire space."""
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, WRAP_SAFE_BINOPS):
        return True
    if isinstance(node, ast.Call) and call_func_name(node) in SAFE_CALLS:
        return True
    return False
