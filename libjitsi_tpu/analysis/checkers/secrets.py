"""secret-taint: constant-time discipline in ``kernels/`` and
``transform/srtp/``.

The bitsliced-AES work (`kernels/aes_bitsliced.py`) exists because
secret-indexed table lookups and secret-dependent branches leak timing.
This checker taints names that look like key material (key, keystream,
salt, round keys, auth tags, HMAC midstates, digests) plus anything
assigned from them, then flags:

- Python ``if``/``while``/ternary/``assert`` whose condition reads a
  tainted value (secret-dependent branch; early returns ride on this);
- subscripts whose INDEX is tainted (``SBOX[key_byte]`` — the classic
  cache-timing leak; slicing a secret value itself is fine);
- ``==``/``!=`` on tainted values used as a branch condition
  (short-circuiting byte compare of auth tags).

Structure checks stay legal: ``len(key) == 16``, ``key.shape``,
``key is None``.  Vectorized verdicts (``ok = tags == expected`` used
in ``np.where``) do not branch and do not fire.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from libjitsi_tpu.analysis.core import (FileContext, Finding, is_none_check,
                                        node_name, propagate_taint,
                                        tainted_leaves)

RULE = "secret-taint"

#: package-relative path prefixes under constant-time discipline
SCOPE_PREFIXES = ("kernels/", "transform/srtp/")

SECRET_TOKENS = {"key", "keys", "keystream", "secret", "salt", "rk",
                 "mid", "tag", "tags", "digest", "mac", "hmac", "auth",
                 "priv", "dhpart", "srtp_key", "ikm", "okm", "keymat"}
#: metadata suffix tokens that make a name *about* a secret, not secret
EXEMPT_TOKENS = {"len", "size", "lens", "sizes", "idx", "index", "off",
                 "offset", "offsets", "count", "name", "names", "id",
                 "kind", "width", "cap", "shape", "fmt", "label"}


def is_secret_name(name: Optional[str]) -> bool:
    if not name:
        return False
    tokens = set(re.split(r"[_\d]+", name.lower())) - {""}
    return bool(tokens & SECRET_TOKENS) and not tokens & EXEMPT_TOKENS


def _scope_ok(relpath: str) -> bool:
    # package-root-relative ("libjitsi_tpu/kernels/..." or "kernels/...")
    p = relpath.split("libjitsi_tpu/")[-1]
    return any(p.startswith(pre) for pre in SCOPE_PREFIXES)


def check_secret_taint(ctx: FileContext) -> List[Finding]:
    if not _scope_ok(ctx.relpath):
        return []
    findings: List[Optional[Finding]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_check_fn(ctx, node))
    return [f for f in findings if f is not None]


def _check_fn(ctx: FileContext, fn: ast.FunctionDef
              ) -> List[Optional[Finding]]:
    args = fn.args
    params = [p.arg for p in args.posonlyargs + args.args + args.kwonlyargs]
    tainted = {p for p in params if is_secret_name(p)}
    # names born secret inside the body (key = derive(...), etc.);
    # method attributes (`d.keys()`) are call targets, not values
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            parent = getattr(node, "_jl_parent", None)
            if isinstance(parent, ast.Call) and parent.func is node:
                continue
        name = node_name(node)
        if is_secret_name(name):
            tainted.add(name)
    tainted = propagate_taint(fn.body, tainted)
    out: List[Optional[Finding]] = []

    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
            if is_none_check(test):
                continue
            leaves = tainted_leaves(test, tainted)
            if leaves:
                name = node_name(leaves[0])
                out.append(ctx.finding(
                    RULE, node,
                    f"secret-dependent branch on `{name}` in "
                    f"`{fn.name}` (timing leak; compute both sides and "
                    "select, or hoist the secret out of control flow)"))
        elif isinstance(node, ast.Assert):
            if tainted_leaves(node.test, tainted):
                out.append(ctx.finding(
                    RULE, node,
                    f"assert on secret data in `{fn.name}` (timing "
                    "leak + aborts differ by secret value)"))
        elif isinstance(node, ast.Subscript):
            idx = node.slice
            leaves = tainted_leaves(idx, tainted)
            if leaves:
                name = node_name(leaves[0])
                out.append(ctx.finding(
                    RULE, node,
                    f"secret-indexed lookup via `{name}` in "
                    f"`{fn.name}` (data-cache timing leak; bitslice or "
                    "mask the whole table)"))
    return out
