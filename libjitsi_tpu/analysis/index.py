"""Whole-tree facts index with a content-keyed disk cache.

Parsing + per-file checking is the expensive half of a lint run, and
it is perfectly file-local — so every file's derived *facts* (symbol
tables, imports, taint summaries, drift/mesh facts, pragma tables,
per-file findings) are JSON-serializable and cached to disk beside
``baseline.json``, keyed by the sha1 of the file's source.  A warm run
re-reads sources only to hash them, reconstructs everything else from
the cache, and the global/interprocedural checkers run over facts —
never over ASTs — so they are cache-warm too.

The cache self-invalidates on analysis changes: its ``version`` field
is a hash over the ``analysis/`` package's own sources, so editing any
checker throws the whole cache away (facts shapes may have changed).
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from libjitsi_tpu.analysis import callgraph as cg
from libjitsi_tpu.analysis.checkers import drift as drift_mod
from libjitsi_tpu.analysis.checkers import meshcollective as mesh_mod
from libjitsi_tpu.analysis.core import FileContext, Finding, TraceHop

DEFAULT_CACHE = os.path.join(os.path.dirname(__file__),
                             ".jitlint_index.json")

_version_cache: Optional[str] = None


def analysis_version() -> str:
    """Hash of the analysis package's own sources — the cache format
    version.  Any checker edit invalidates every cached fact."""
    global _version_cache
    if _version_cache is None:
        h = hashlib.sha1()
        base = os.path.dirname(os.path.abspath(__file__))
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    with open(os.path.join(dirpath, fn), "rb") as fh:
                        h.update(fh.read())
        _version_cache = h.hexdigest()[:12]
    return _version_cache


class FileFacts:
    """JSON facts for one file + the FileContext-shaped helpers the
    fact-consuming checkers need (suppression, symbols, findings)."""

    def __init__(self, data: dict):
        self.data = data
        self.relpath: str = data["relpath"]

    # --------------------------------------------------- construction

    @classmethod
    def from_ctx(cls, ctx: FileContext, sha: str) -> "FileFacts":
        from libjitsi_tpu.analysis import summaries
        from libjitsi_tpu.analysis.checkers import secretflow
        functions, classes = cg.extract_defs(ctx)
        summaries.extract_summaries(
            ctx, functions,
            seed_secrets=secretflow.in_source_scope(ctx.relpath))
        module = cg.module_name(ctx.relpath)
        data = {
            "relpath": ctx.relpath,
            "abspath": os.path.abspath(ctx.path),
            "module": module,
            "sha": sha,
            "lines": ctx.lines,
            "pragma_lines": {str(k): sorted(v)
                             for k, v in ctx.line_pragmas.items()},
            "pragma_file": sorted(ctx.file_pragmas),
            "scopes": [list(s) for s in ctx._scopes],
            "imports": cg.extract_imports(ctx.tree, module),
            "functions": functions,
            "classes": classes,
            "drift": drift_mod.file_facts(ctx),
            "mesh": mesh_mod.file_facts(ctx),
        }
        return cls(data)

    # ------------------------------------------------ context helpers

    def suppressed(self, rule: str, line: int) -> bool:
        if {"all", rule} & set(self.data["pragma_file"]):
            return True
        probes = [line, line - 1]
        for start, end, _qual, def_line in self.data["scopes"]:
            if start <= line <= end:
                probes.append(def_line)
        pragmas = self.data["pragma_lines"]
        for probe in probes:
            rules = pragmas.get(str(probe))
            if rules and {"all", rule} & set(rules):
                return True
        return False

    def symbol_at(self, line: int) -> str:
        best, best_span = "", None
        for start, end, qual, _ in self.data["scopes"]:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def finding(self, rule: str, line: int, col: int, message: str,
                trace: Optional[List[TraceHop]] = None
                ) -> Optional[Finding]:
        if self.suppressed(rule, line):
            return None
        lines = self.data["lines"]
        snippet = (lines[line - 1].strip()
                   if 0 < line <= len(lines) else "")
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=snippet,
                       symbol=self.symbol_at(line), trace=trace)


class TreeIndex:
    """All facts + per-file findings for one lint run."""

    def __init__(self) -> None:
        self.facts: Dict[str, FileFacts] = {}
        self.findings: List[Finding] = []
        self.errors: List[str] = []
        self.cache_hits = 0
        self.cache_misses = 0
        self._graph: Optional[cg.CallGraph] = None

    @property
    def graph(self) -> cg.CallGraph:
        if self._graph is None:
            self._graph = cg.CallGraph(
                {rel: f.data for rel, f in self.facts.items()})
        return self._graph

    def reverse_deps(self, rels: Iterable[str]) -> Set[str]:
        """`rels` plus every file importing one of them, transitively
        (module-level imports only) — the re-lint closure of a change."""
        mod_of = {f.data["module"]: rel
                  for rel, f in self.facts.items()}
        importers: Dict[str, Set[str]] = {}
        for rel, f in self.facts.items():
            for target in f.data["imports"].values():
                for probe in (target, target.rpartition(".")[0]):
                    dep = mod_of.get(probe)
                    if dep is not None:
                        importers.setdefault(dep, set()).add(rel)
        out: Set[str] = set()
        work = [r for r in rels if r in self.facts]
        while work:
            r = work.pop()
            if r in out:
                continue
            out.add(r)
            work.extend(importers.get(r, ()))
        return out


def _finding_from_dict(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   snippet=d["snippet"], symbol=d["symbol"],
                   trace=d.get("trace"))


def load_cache(path: str = DEFAULT_CACHE) -> Dict[str, dict]:
    """{relpath: {"sha", "facts", "findings"}} or {} when absent,
    unreadable, or written by a different analysis version."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    if doc.get("version") != analysis_version():
        return {}
    files = doc.get("files")
    return files if isinstance(files, dict) else {}


def save_cache(index: TreeIndex, per_file: Dict[str, List[Finding]],
               path: str = DEFAULT_CACHE,
               prior: Optional[Dict[str, dict]] = None) -> None:
    """Merge-write: a partial-scope run (one file, --changed) must not
    evict the rest of the tree's entries."""
    files = dict(prior or {})
    for rel, facts in index.facts.items():
        files[rel] = {
            "sha": facts.data["sha"],
            "facts": facts.data,
            "findings": [f.to_dict() for f in per_file.get(rel, [])],
        }
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": analysis_version(), "files": files},
                      fh, separators=(",", ":"))
        os.replace(tmp, path)
    except OSError:
        pass  # cache is an optimization; a read-only checkout still lints


def build_index(files: Sequence[Tuple[str, str]],
                checkers: Sequence,
                jobs: Optional[int] = None,
                cache: Optional[Dict[str, dict]] = None,
                trusted: Optional[Set[str]] = None
                ) -> Tuple[TreeIndex, Dict[str, List[Finding]]]:
    """Parse/check every file not served by `cache`.  `trusted`
    relpaths skip even the source read + sha check (--changed mode:
    git already said they are unchanged).  Returns the index plus the
    per-file findings map (for cache writing)."""
    cache = cache or {}
    trusted = trusted or set()
    index = TreeIndex()
    per_file: Dict[str, List[Finding]] = {}

    def process(pair: Tuple[str, str]):
        path, rel = pair
        rel = rel.replace("\\", "/")
        entry = cache.get(rel)
        if entry is not None and rel in trusted:
            return rel, "hit", FileFacts(entry["facts"]), \
                [_finding_from_dict(d) for d in entry["findings"]], None
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            return rel, "err", None, [], f"{rel}: {exc}"
        sha = hashlib.sha1(source.encode()).hexdigest()
        if entry is not None and entry.get("sha") == sha:
            return rel, "hit", FileFacts(entry["facts"]), \
                [_finding_from_dict(d) for d in entry["findings"]], None
        try:
            ctx = FileContext(path, rel, source)
        except SyntaxError as exc:
            return rel, "err", None, [], f"{rel}: {exc}"
        findings: List[Finding] = []
        for checker in checkers:
            findings.extend(checker(ctx))
        return rel, "miss", FileFacts.from_ctx(ctx, sha), findings, None

    workers = jobs or min(32, (os.cpu_count() or 4))
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
        for rel, kind, facts, findings, err in ex.map(process, files):
            if kind == "err":
                index.errors.append(err)
                continue
            if kind == "hit":
                index.cache_hits += 1
            else:
                index.cache_misses += 1
            index.facts[rel] = facts
            per_file[rel] = findings
            index.findings.extend(findings)
    return index, per_file
