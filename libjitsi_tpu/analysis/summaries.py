"""Function taint summaries + the interprocedural fixpoint engine.

Per function we extract a JSON-able *summary*: every call site (with
per-argument **atom** sets), the atoms its return value may carry,
``self.attr`` writes, ``raise`` payloads, and the secret *sources*
read in its body.  Atoms are strings:

    ``P:name``   the function's own parameter `name`
    ``A:Cls.x``  attribute ``self.x`` of class Cls (flow-insensitive)
    ``C:7``      the return value of call site #7 in this function
    ``S:2``      source #2 — a read of a secret-named value in a
                 key-material module (see ``secretflow.SOURCE_SCOPES``)

``TaintEngine`` resolves every call site through the module call
graph, then runs two monotone fixpoints over *ground* atoms
(params + sources): which ground atoms each function's RETURN may
carry, and which sinks each ground atom transitively REACHES —
recording one source-to-sink hop path per (atom, sink) so a finding
prints the whole flow without re-running.

Sanitizers match the intra-file rule: shape/len/dtype reads,
``is``-comparisons and boolean verdicts carry no atoms.  Unresolved
calls get **no summary** — taint neither enters nor escapes a callee
the graph cannot name — but their *value* conservatively carries its
receiver's and arguments' atoms (``key.hex()`` stays hot; a helper
with six implementations contributes no phantom flows).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from libjitsi_tpu.analysis.callgraph import CallGraph
from libjitsi_tpu.analysis.core import (NEVER_TAINT, SHAPE_ATTRS,
                                        SHAPE_CALLS, node_name)
from libjitsi_tpu.analysis.checkers.secrets import is_secret_name

#: functions whose RETURN VALUE is key material wherever they appear
SOURCE_FUNCS = {"srtp_keys", "export_keying_material",
                "derive_session_keys", "derive_session_keys_batch"}

#: tuple elements of a source call's return that are NOT key material
#: (srtp_keys -> (profile, tk, tsalt, rk, rsalt): the negotiated
#: profile enum is public signaling state)
SOURCE_ELEM_EXEMPT = {"srtp_keys": {0}}

#: declassification boundary: the protect/unprotect AEAD surface.
#: Outputs of these calls are wire ciphertext, app plaintext, or auth
#: verdicts — DERIVED from key material but not key material, so taint
#: stops at the transform.  Matched on the call's terminal name.
_DECLASSIFY_TOKENS = ("protect",)

#: logger method names (the repo idiom is `_log = get_logger(...)`)
LOG_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
               "critical", "log"}

#: dotted call targets that serialize state to disk in plaintext
CHECKPOINT_SINKS = {"pickle.dump", "pickle.dumps", "np.save", "np.savez"}


def _dotted_text(node: ast.AST) -> Optional[str]:
    """"self.rx_table" for an Attribute/Name chain, None for computed
    receivers (calls, subscripts)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted_text(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _classify_sink(relpath: str, recv: Optional[str], name: str,
                   dotted: str) -> Optional[str]:
    """Sink kind of a call site, or None.  `dotted` is the
    import-resolved target ("pickle.dump"); `recv` the literal
    receiver spelling ("self.flight")."""
    low = (recv or "").lower()
    if name in ("record", "record_headers") and \
            ("flight" in low or "recorder" in low):
        return "flight-payload"
    if name in LOG_METHODS and "log" in low.rsplit(".", 1)[-1]:
        return "log"
    if name == "set_stream_name":
        return "metrics-label"
    if dotted in CHECKPOINT_SINKS:
        return "checkpoint-plaintext"
    if relpath.endswith("service/obs_server.py") and \
            dotted in ("json.dumps", "json.dump"):
        return "debug-endpoint"
    return None


class _FnExtractor:
    """One function body -> summary dict (see module docstring)."""

    def __init__(self, fn: ast.AST, cls: Optional[str],
                 relpath: str, seed_secrets: bool):
        self.fn = fn
        self.cls = cls
        self.relpath = relpath
        self.seed = seed_secrets
        a = fn.args
        self.params = {p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs}
        # locally-assigned names: secret-NAME seeding is skipped for
        # these (their taint is whatever dataflow says — `key =
        # self._conf_key(...)` is a dict key, not key material); reads
        # of params and free names still seed on name alone
        self.assigned: Set[str] = set()
        self.env: Dict[str, Set[str]] = {}
        self.sources: List[dict] = []
        self._src_ids: Dict[str, int] = {}
        # call sites in deterministic walk order; nested defs belong
        # to their own summaries, so stop at inner function boundaries
        self.calls: List[ast.Call] = []
        self.call_id: Dict[int, int] = {}
        for node in self._walk(fn):
            if isinstance(node, ast.Call):
                self.call_id[id(node)] = len(self.calls)
                self.calls.append(node)
            tgts: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                tgts = list(node.targets)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr, ast.For)):
                tgts = [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                tgts = [node.optional_vars]
            for t in tgts:
                self.assigned |= self._bound_names(t)

    @staticmethod
    def _bound_names(tgt: ast.AST) -> Set[str]:
        """Names REBOUND by an assignment target (plain/tuple/starred
        only — `x[i] = v` mutates x, it does not rebind it)."""
        if isinstance(tgt, ast.Name):
            return {tgt.id}
        if isinstance(tgt, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for el in tgt.elts:
                out |= _FnExtractor._bound_names(el)
            return out
        if isinstance(tgt, ast.Starred):
            return _FnExtractor._bound_names(tgt.value)
        return set()

    def _walk(self, root: ast.AST):
        stack = list(ast.iter_child_nodes(root))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    # ------------------------------------------------------------ atoms

    def _src(self, name: str, line: int) -> str:
        if name not in self._src_ids:
            self._src_ids[name] = len(self.sources)
            self.sources.append({"n": name, "l": line})
        return f"S:{self._src_ids[name]}"

    def atoms(self, node: Optional[ast.AST]) -> Set[str]:
        if node is None:
            return set()
        if isinstance(node, ast.Name):
            if node.id in NEVER_TAINT:
                return set()
            out = set(self.env.get(node.id, ()))
            if node.id in self.params:
                out.add(f"P:{node.id}")
            if self.seed and is_secret_name(node.id) and \
                    (node.id in self.params
                     or node.id not in self.assigned):
                out.add(self._src(node.id, node.lineno))
            return out
        if isinstance(node, ast.Attribute):
            if node.attr in SHAPE_ATTRS:
                return set()
            base = self.atoms(node.value)
            if isinstance(node.value, ast.Name) and \
                    node.value.id in ("self", "cls"):
                base = set()
                if self.cls:
                    base.add(f"A:{self.cls}.{node.attr}")
            if self.seed and is_secret_name(node.attr):
                base.add(self._src(node.attr, node.lineno))
            return base
        if isinstance(node, ast.Call):
            fname = node_name(node.func)
            if isinstance(node.func, ast.Name) and \
                    node.func.id in SHAPE_CALLS:
                return set()
            if fname in SHAPE_CALLS:
                return set()
            if fname and any(tok in fname for tok in _DECLASSIFY_TOKENS):
                return set()
            i = self.call_id.get(id(node))
            return {f"C:{i}"} if i is not None else set()
        if isinstance(node, (ast.Compare, ast.Constant, ast.Lambda)):
            return set()
        if isinstance(node, ast.Subscript):
            return self.atoms(node.value) | self.atoms(node.slice)
        out: Set[str] = set()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.keyword):
                out |= self.atoms(child.value)
            elif isinstance(child, ast.comprehension):
                out |= self.atoms(child.iter)
            elif isinstance(child, ast.expr):
                out |= self.atoms(child)
        return out

    # ----------------------------------------------------- environment

    def _targets(self, tgt: ast.AST) -> Tuple[Set[str], List[str]]:
        """(local names, self-attrs) receiving a value."""
        names: Set[str] = set()
        attrs: List[str] = []
        if isinstance(tgt, ast.Name):
            names.add(tgt.id)
        elif isinstance(tgt, ast.Attribute):
            if isinstance(tgt.value, ast.Name) and \
                    tgt.value.id in ("self", "cls"):
                attrs.append(tgt.attr)
        elif isinstance(tgt, ast.Subscript):
            n, a = self._targets(tgt.value)
            names |= n
            attrs += a
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                n, a = self._targets(el)
                names |= n
                attrs += a
        elif isinstance(tgt, ast.Starred):
            n, a = self._targets(tgt.value)
            names |= n
            attrs += a
        return names - NEVER_TAINT, attrs

    def _env_pass(self) -> bool:
        changed = False

        def assign(tgt: ast.AST, atoms: Set[str], line: int) -> None:
            nonlocal changed
            names, attrs = self._targets(tgt)
            for nm in names:
                cur = self.env.setdefault(nm, set())
                if not atoms <= cur:
                    cur |= atoms
                    changed = True
            for at in attrs:
                self.writes.append((at, atoms, line))

        self.writes: List[Tuple[str, Set[str], int]] = \
            getattr(self, "writes", [])
        self.writes.clear()
        def assign_unpack(tgt: ast.AST, value: ast.AST,
                          atoms: Set[str], line: int) -> bool:
            """Element-exempt tuple unpack of a source call:
            `profile, tk, ... = ep.srtp_keys()` must not taint the
            public elements.  Returns True when handled."""
            if not (isinstance(value, ast.Call)
                    and isinstance(tgt, ast.Tuple)
                    and not any(isinstance(e, ast.Starred)
                                for e in tgt.elts)):
                return False
            exempt = SOURCE_ELEM_EXEMPT.get(node_name(value.func))
            if not exempt:
                return False
            for k, el in enumerate(tgt.elts):
                assign(el, set() if k in exempt else atoms, line)
            return True

        for node in self._walk(self.fn):
            if isinstance(node, ast.Assign):
                atoms = self.atoms(node.value)
                for tgt in node.targets:
                    if not assign_unpack(tgt, node.value, atoms,
                                         node.lineno):
                        assign(tgt, atoms, node.lineno)
            elif isinstance(node, ast.AnnAssign) and node.value:
                assign(node.target, self.atoms(node.value), node.lineno)
            elif isinstance(node, ast.AugAssign):
                assign(node.target, self.atoms(node.value), node.lineno)
            elif isinstance(node, ast.For):
                assign(node.target, self.atoms(node.iter), node.lineno)
            elif isinstance(node, ast.NamedExpr):
                assign(node.target, self.atoms(node.value), node.lineno)
            elif isinstance(node, ast.withitem) and \
                    node.optional_vars is not None:
                assign(node.optional_vars, self.atoms(node.context_expr),
                       getattr(node.context_expr, "lineno", 1))
        return changed

    # ----------------------------------------------------------- drive

    def run(self) -> dict:
        for _ in range(4):
            if not self._env_pass():
                break

        calls_out: List[dict] = []
        for i, call in enumerate(self.calls):
            func = call.func
            name = node_name(func) or "<computed>"
            recv = None
            if isinstance(func, ast.Attribute):
                recv = _dotted_text(func.value) or "<expr>"
            cs: dict = {"n": name, "r": recv, "l": call.lineno}
            args = [sorted(self.atoms(a)) for a in call.args]
            kwargs = {kw.arg or "**": sorted(self.atoms(kw.value))
                      for kw in call.keywords}
            if any(args) or any(kwargs.values()):
                cs["a"] = args
                cs["kw"] = {k: v for k, v in kwargs.items() if v}
            if isinstance(func, ast.Attribute):
                rv = sorted(self.atoms(func.value))
                if rv:
                    cs["rv"] = rv
            if name in SOURCE_FUNCS:
                cs["sc"] = True
            calls_out.append(cs)

        ret: Set[str] = set()
        raises: List[dict] = []
        for node in self._walk(self.fn):
            if isinstance(node, ast.Return) and node.value is not None:
                ret |= self.atoms(node.value)
            elif isinstance(node, ast.Raise) and node.exc is not None:
                at = sorted(self.atoms(node.exc))
                if at:
                    raises.append({"l": node.lineno, "at": at})
            elif isinstance(node, ast.Yield) and node.value is not None:
                ret |= self.atoms(node.value)

        return {
            "calls": calls_out,
            "ret": sorted(ret),
            "raises": raises,
            "writes": [[a, sorted(at), ln]
                       for a, at, ln in self.writes if at],
            "sources": self.sources,
        }


def extract_summaries(ctx, functions: Dict[str, dict],
                      seed_secrets: bool) -> None:
    """Fill each entry of `functions` (from callgraph.extract_defs)
    with its taint summary, matching defs to AST nodes by qualname."""
    nodes: Dict[str, ast.AST] = {}

    def collect(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                nodes[qual] = child
                collect(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                collect(child, f"{child.name}.")
            else:
                collect(child, prefix)

    collect(ctx.tree, "")
    for qual, info in functions.items():
        fn = nodes.get(qual)
        if fn is None:
            info.update(_FnExtractor(
                ast.parse("def _stub(): pass").body[0], None,
                ctx.relpath, False).run())
            continue
        info.update(_FnExtractor(
            fn, info["cls"], ctx.relpath, seed_secrets).run())


# ====================================================== fixpoint engine

#: ground atoms are tuples: ("P", fid, param) | ("SRC", fid, i) |
#: ("SRCCALL", fid, call_i)
Ground = Tuple[str, str, str]

MAX_PATH = 16
MAX_ENTRIES = 3


class TaintEngine:
    """Two whole-tree fixpoints over ground atoms + path recording."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.fns: Dict[str, dict] = {}
        self.edges: Dict[str, List[Optional[str]]] = {}
        for rel, f in graph.facts.items():
            for qual, fn in f["functions"].items():
                fid = f"{rel}::{qual}"
                self.fns[fid] = fn
                self.edges[fid] = [
                    graph.resolve(rel, qual, cs)
                    for cs in fn.get("calls", ())]
        self.ret_g: Dict[str, Set[Ground]] = {f: set() for f in self.fns}
        self.call_g: Dict[Tuple[str, int], Set[Ground]] = {}
        self.attr_g: Dict[str, Set[Ground]] = {}
        self._solve_values()

    # ------------------------------------------------- value fixpoint

    def _expand(self, fid: str, atoms: Sequence[str]) -> Set[Ground]:
        out: Set[Ground] = set()
        rel = fid.partition("::")[0]
        for a in atoms:
            kind, _, rest = a.partition(":")
            if kind == "P":
                out.add(("P", fid, rest))
            elif kind == "S":
                out.add(("SRC", fid, rest))
            elif kind == "A":
                out |= self.attr_g.get(f"{rel}::{rest}", set())
            elif kind == "C":
                out |= self.call_g.get((fid, int(rest)), set())
        return out

    def _solve_values(self) -> None:
        for _ in range(30):
            changed = False
            for fid, fn in self.fns.items():
                callees = self.edges[fid]
                for i, cs in enumerate(fn.get("calls", ())):
                    new = set()
                    if cs.get("sc"):
                        new.add(("SRCCALL", fid, str(i)))
                    g = callees[i]
                    if g is not None and g in self.fns:
                        for ga in self.ret_g[g]:
                            if ga[0] == "P" and ga[1] == g:
                                new |= self._expand(
                                    fid, self._args_for(cs, g, ga[2]))
                            else:
                                new.add(ga)
                    else:
                        passthru = list(cs.get("rv", ()))
                        for arg in cs.get("a", ()):
                            passthru += arg
                        for v in cs.get("kw", {}).values():
                            passthru += v
                        new |= self._expand(fid, passthru)
                    cur = self.call_g.setdefault((fid, i), set())
                    if not new <= cur:
                        cur |= new
                        changed = True
                rg = self._expand(fid, fn.get("ret", ()))
                if not rg <= self.ret_g[fid]:
                    self.ret_g[fid] |= rg
                    changed = True
                rel = fid.partition("::")[0]
                for attr, atoms, _ln in fn.get("writes", ()):
                    cls = fn.get("cls")
                    if not cls:
                        continue
                    key = f"{rel}::{cls}.{attr}"
                    ag = self._expand(fid, atoms)
                    cur = self.attr_g.setdefault(key, set())
                    if not ag <= cur:
                        cur |= ag
                        changed = True
            if not changed:
                break

    def _args_for(self, cs: dict, callee_fid: str,
                  param: str) -> List[str]:
        """Atoms the caller passes for `param` of the callee at `cs`
        (positional by index — shifted past `self` for methods — plus
        the matching keyword)."""
        callee = self.fns[callee_fid]
        params = list(callee.get("params", ()))
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        out: List[str] = []
        args = cs.get("a", ())
        if param in params:
            idx = params.index(param)
            if idx < len(args):
                out += args[idx]
        out += cs.get("kw", {}).get(param, ())
        return out

    # -------------------------------------------------- sink fixpoint

    def solve_sinks(self) -> Dict[str, Dict[Ground, List[dict]]]:
        """{fid: {ground atom: [sink entries]}} where an entry is
        {"kind", "path": [hop...]} — hop dicts per core.TraceHop."""
        sinks: Dict[str, Dict[Ground, List[dict]]] = \
            {f: {} for f in self.fns}

        def hop(fid: str, line: int, note: str) -> dict:
            rel, _, qual = fid.partition("::")
            return {"path": rel, "line": line, "symbol": qual,
                    "note": note}

        def add(fid: str, g: Ground, kind: str,
                path: List[dict]) -> bool:
            if len(path) > MAX_PATH:
                return False
            entries = sinks[fid].setdefault(g, [])
            sig = (kind, path[0]["path"], path[0]["line"],
                   path[-1]["path"], path[-1]["line"])
            for e in entries:
                p = e["path"]
                if (e["kind"], p[0]["path"], p[0]["line"],
                        p[-1]["path"], p[-1]["line"]) == sig:
                    return False
            if len([e for e in entries if e["kind"] == kind]) \
                    >= MAX_ENTRIES:
                return False
            entries.append({"kind": kind, "path": path})
            return True

        # direct sinks
        for fid, fn in self.fns.items():
            rel = fid.partition("::")[0]
            for i, cs in enumerate(fn.get("calls", ())):
                kind = _classify_sink(
                    rel, cs.get("r"), cs["n"],
                    self.graph.dotted(rel, cs))
                if kind is None:
                    continue
                atoms: List[str] = []
                for arg in cs.get("a", ()):
                    atoms += arg
                for v in cs.get("kw", {}).values():
                    atoms += v
                note = (f"{cs.get('r') + '.' if cs.get('r') else ''}"
                        f"{cs['n']}(...) [{kind}]")
                for g in self._expand(fid, atoms):
                    add(fid, g, kind, [hop(fid, cs["l"], note)])
            for rz in fn.get("raises", ()):
                for g in self._expand(fid, rz["at"]):
                    add(fid, g, "exception",
                        [hop(fid, rz["l"], "raise with secret payload")])

        # propagate through resolved calls: callee param reaches sink
        # => caller's matching argument reaches it one hop further out
        for _ in range(30):
            changed = False
            for fid, fn in self.fns.items():
                callees = self.edges[fid]
                for i, cs in enumerate(fn.get("calls", ())):
                    g = callees[i]
                    if g is None or g not in self.fns:
                        continue
                    for atom, entries in list(sinks[g].items()):
                        if atom[0] != "P" or atom[1] != g:
                            continue
                        arg_atoms = self._args_for(cs, g, atom[2])
                        if not arg_atoms:
                            continue
                        grounds = self._expand(fid, arg_atoms)
                        note = (f"passed to {g.partition('::')[2]}"
                                f"({atom[2]})")
                        for ga in grounds:
                            for e in entries:
                                if add(fid, ga, e["kind"],
                                       [hop(fid, cs["l"], note)]
                                       + e["path"]):
                                    changed = True
            if not changed:
                break
        return sinks
