"""jitlint — repo-native static analysis for the failure classes this
codebase has actually hit (see ISSUE 3 / README "Static analysis").

Four rules, one shared AST visitor core, a per-file parallel driver,
`# jitlint: disable=<rule>` pragmas and a committed baseline for
grandfathered findings:

- ``hotpath-purity``  — host syncs / tracer-dependent Python control
  flow / shape-unstable ops inside ``@jax.jit`` functions.
- ``secret-taint``    — secret-dependent branches and Python-level
  table indexing in ``kernels/`` and ``transform/srtp/``.
- ``rtp-mod16``       — raw arithmetic/comparison on 16-bit RTP
  seq/roc values outside ``core/rtp_math.py`` helpers.
- ``drift``           — counters incremented but never registered with
  ``MetricsRegistry`` (and dangling registrations), and
  ``ArraySnapshotMixin`` array state missing from ``_SNAP_FIELDS``.
"""

from libjitsi_tpu.analysis.core import Finding, FileContext  # noqa: F401
from libjitsi_tpu.analysis.driver import run_lint            # noqa: F401
