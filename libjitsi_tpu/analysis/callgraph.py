"""Module-resolved call graph over the whole linted tree.

The graph is built from per-file *facts* (see ``index.py``) — plain
JSON-able dicts, so a warm run reconstructs the graph from the disk
cache without re-parsing a single file.  Functions are identified by
``fid`` strings ``"<relpath>::<qualname>"``; call sites carry a
receiver spelling and a short name, and ``CallGraph.resolve`` maps
them to a callee fid with four deliberately conservative rules:

1. bare name      -> nested def in the caller, else a top-level
                     function/class of the same module, else an
                     imported function/class (``from m import f``);
2. ``self.m()``   -> method ``m`` on the caller's class or its bases
                     (bases resolved by name, same module first);
3. ``alias.f()``  -> top-level ``f`` of the module ``alias`` imports;
4. anything else  -> *unique* method name across every class in the
                     tree, else **unresolved** (dynamic dispatch with
                     several candidates gets no edge and no summary —
                     a missed edge is a missed finding, never a false
                     one).

Plane annotations: ``# jitlint: plane=tick|off_tick|dual`` on (or one
line above) a ``def`` line declares which execution plane the function
is an entry point for.  ``dual`` marks a function that legitimately
runs on its caller's plane (the legacy inline-DTLS path) — the
plane-affinity checker cuts traversal there without flagging.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from libjitsi_tpu.analysis.core import node_name

PLANE_RE = re.compile(r"#\s*jitlint:\s*plane=([a-z_]+)")

PLANES = ("tick", "off_tick", "dual")


def module_name(relpath: str) -> str:
    """"libjitsi_tpu/io/loop.py" -> "libjitsi_tpu.io.loop"."""
    p = relpath.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def extract_imports(tree: ast.AST, module: str) -> Dict[str, str]:
    """{local name: dotted target}.  ``import a.b as c`` -> {c: a.b};
    ``import a.b`` -> {a: a}; ``from .x import f as g`` -> {g:
    pkg.x.f} with relative levels resolved against `module`."""
    out: Dict[str, str] = {}
    pkg_parts = module.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    out[alias.name.split(".")[0]] = alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - node.level]
            else:
                base = []
            mod = ".".join(base + ([node.module] if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                out[local] = f"{mod}.{alias.name}" if mod else alias.name
    return out


def _plane_of(lines: List[str], def_line: int) -> Optional[str]:
    """Plane annotation on the def line or the line above it."""
    for probe in (def_line, def_line - 1):
        if 0 < probe <= len(lines):
            m = PLANE_RE.search(lines[probe - 1])
            if m and m.group(1) in PLANES:
                return m.group(1)
    return None


def extract_defs(ctx) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """(functions, classes) symbol tables for one FileContext.

    functions: {qual: {"name", "cls", "params", "line", "end_line",
                       "plane", "nested"}}
    classes:   {name: {"bases": [...], "methods": [...], "line"}}
    """
    functions: Dict[str, dict] = {}
    classes: Dict[str, dict] = {}

    def visit(node: ast.AST, prefix: str, cls: Optional[str],
              depth: int) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                a = child.args
                params = [p.arg for p in
                          a.posonlyargs + a.args + a.kwonlyargs]
                functions[qual] = {
                    "name": child.name, "cls": cls, "params": params,
                    "line": child.lineno,
                    "end_line": child.end_lineno or child.lineno,
                    "plane": _plane_of(ctx.lines, child.lineno),
                    "nested": depth > (1 if cls else 0),
                }
                visit(child, qual + ".", cls, depth + 1)
            elif isinstance(child, ast.ClassDef):
                classes[child.name] = {
                    "bases": [b for b in
                              (node_name(x) for x in child.bases) if b],
                    "methods": [n.name for n in child.body
                                if isinstance(n, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef))],
                    "line": child.lineno,
                }
                visit(child, f"{child.name}.", child.name, depth + 1)
            else:
                visit(child, prefix, cls, depth)

    visit(ctx.tree, "", None, 0)
    return functions, classes


class CallGraph:
    """Whole-tree resolution index over per-file facts dicts (the
    ``data`` attribute of ``index.FileFacts``)."""

    def __init__(self, facts: Dict[str, dict]):
        self.facts = facts
        #: dotted module -> relpath
        self.modules: Dict[str, str] = {}
        #: method name -> [(relpath, qual)] across every class
        self._methods: Dict[str, List[Tuple[str, str]]] = {}
        #: class name -> [(relpath, class dict)]
        self._classes: Dict[str, List[Tuple[str, dict]]] = {}
        for rel, f in facts.items():
            self.modules[f["module"]] = rel
            for cname, c in f["classes"].items():
                self._classes.setdefault(cname, []).append((rel, c))
            for qual, fn in f["functions"].items():
                if fn["cls"] and qual == f'{fn["cls"]}.{fn["name"]}':
                    self._methods.setdefault(fn["name"], []).append(
                        (rel, qual))

    # ------------------------------------------------------------ lookup

    def function(self, fid: str) -> Optional[dict]:
        rel, _, qual = fid.partition("::")
        f = self.facts.get(rel)
        return f["functions"].get(qual) if f else None

    def find(self, path_suffix: str, qual: str) -> Optional[str]:
        """fid of `qual` in the file whose relpath ends with
        `path_suffix`, or None."""
        for rel, f in self.facts.items():
            if rel.endswith(path_suffix) and qual in f["functions"]:
                return f"{rel}::{qual}"
        return None

    def dotted(self, rel: str, cs: dict) -> str:
        """Best-effort dotted target of a call site: the receiver's
        first segment mapped through the file's imports —
        ``time.sleep``, ``pickle.dump`` — used for the blocking-call
        and stdlib-sink tables."""
        imports = self.facts[rel]["imports"]
        recv = cs.get("r")
        if recv is None:
            return imports.get(cs["n"], cs["n"])
        parts = recv.split(".")
        parts[0] = imports.get(parts[0], parts[0])
        return ".".join(parts + [cs["n"]])

    # ---------------------------------------------------------- resolve

    def _module_func(self, rel: str, name: str) -> Optional[str]:
        f = self.facts[rel]
        fn = f["functions"].get(name)
        if fn is not None and fn["cls"] is None and not fn["nested"]:
            return f"{rel}::{name}"
        if name in f["classes"]:
            init = f"{name}.__init__"
            if init in f["functions"]:
                return f"{rel}::{init}"
        return None

    def _import_target(self, rel: str, name: str) -> Optional[str]:
        dotted = self.facts[rel]["imports"].get(name)
        if not dotted or "." not in dotted:
            return None
        mod, _, leaf = dotted.rpartition(".")
        target_rel = self.modules.get(mod)
        if target_rel is None:
            return None
        return self._module_func(target_rel, leaf)

    def _class_method(self, rel: str, cname: str, name: str,
                      seen: Optional[Set[str]] = None) -> Optional[str]:
        seen = seen or set()
        if cname in seen:
            return None
        seen.add(cname)
        for crel, c in self._candidates(rel, cname):
            if name in c["methods"]:
                return f"{crel}::{cname}.{name}"
            for base in c["bases"]:
                hit = self._class_method(crel, base, name, seen)
                if hit:
                    return hit
        return None

    def _candidates(self, rel: str, cname: str
                    ) -> List[Tuple[str, dict]]:
        cands = self._classes.get(cname, [])
        same = [(r, c) for r, c in cands if r == rel]
        return same or cands

    def resolve(self, rel: str, caller_qual: str, cs: dict
                ) -> Optional[str]:
        """fid of the callee, or None (unresolved / ambiguous)."""
        name = cs["n"]
        recv = cs.get("r")
        f = self.facts[rel]
        if recv is None:
            nested = f"{caller_qual}.{name}"
            if nested in f["functions"]:
                return f"{rel}::{nested}"
            hit = self._module_func(rel, name)
            if hit:
                return hit
            return self._import_target(rel, name)
        if recv in ("self", "cls"):
            caller = f["functions"].get(caller_qual)
            if caller and caller["cls"]:
                hit = self._class_method(rel, caller["cls"], name)
                if hit:
                    return hit
            # fall through: `self.x(...)` where x is a stored callback
            # resolves like any dynamic receiver (unique-name rule)
        elif "." not in recv:
            dotted = f["imports"].get(recv)
            if dotted:
                target_rel = self.modules.get(dotted)
                if target_rel is not None:
                    return self._module_func(target_rel, name)
        # dynamic dispatch: resolve only a tree-unique method name
        cands = self._methods.get(name, [])
        if len(cands) == 1:
            crel, qual = cands[0]
            return f"{crel}::{qual}"
        return None
