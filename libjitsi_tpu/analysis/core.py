"""Shared visitor core for jitlint checkers.

Everything a checker needs from one file lives in a `FileContext`:
parsed AST (with parent links), raw lines, pragma tables, and the
`finding()` constructor that fills in location/snippet/symbol.  The
taint helpers (`assigned_names`, `names_in`, `under_shape_access`) are
the common dataflow vocabulary of the hotpath and secret checkers —
both run the same one-pass forward propagation over statement lists.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: one hop of an interprocedural taint/reachability path:
#: {"path": relpath, "line": int, "symbol": str, "note": str}
TraceHop = Dict[str, object]

PRAGMA_RE = re.compile(r"#\s*jitlint:\s*disable=([a-z0-9_,\-]+|all)")
PRAGMA_FILE_RE = re.compile(r"#\s*jitlint:\s*disable-file=([a-z0-9_,\-]+|all)")

#: attribute/function accesses through which a traced or secret value
#: yields only STATIC (shape/dtype) information — never data
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "nbytes",
               "batch_size"}
SHAPE_CALLS = {"len", "isinstance", "hasattr", "getattr", "type", "id",
               "range", "bool"}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str              # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str           # stripped source of the flagged line
    symbol: str            # enclosing qualname, "" at module level
    occurrence: int = 0    # nth identical finding in this symbol
    #: interprocedural source->hops->sink path (secret-flow /
    #: plane-affinity); not part of content_key — the same logical
    #: finding keeps its baseline key when an intermediate hop moves
    trace: Optional[List[TraceHop]] = None

    @property
    def content_key(self) -> str:
        """Line-number-independent identity used by the baseline: the
        same logical finding keeps its key across unrelated edits that
        shift line numbers."""
        h = hashlib.sha1(
            " ".join(self.snippet.split()).encode()).hexdigest()[:12]
        return (f"{self.rule}:{self.path}:{self.symbol}:"
                f"{h}:{self.occurrence}")

    def to_dict(self) -> dict:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "col": self.col, "message": self.message,
               "snippet": self.snippet, "symbol": self.symbol,
               "key": self.content_key}
        if self.trace:
            out["trace"] = self.trace
        return out

    def render(self) -> str:
        base = (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}\n    {self.snippet}")
        if self.trace:
            hops = "\n".join(
                f"    {'source' if i == 0 else '  hop' if i < len(self.trace) - 1 else ' sink'}"
                f" {h['path']}:{h['line']} ({h['symbol'] or '<module>'})"
                f" {h.get('note', '')}".rstrip()
                for i, h in enumerate(self.trace))
            base += "\n" + hops
        return base


def _parse_pragmas(lines: List[str]) -> Tuple[Dict[int, Set[str]], Set[str]]:
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = PRAGMA_FILE_RE.search(text)
        if m:
            whole_file |= set(m.group(1).split(","))
            continue
        m = PRAGMA_RE.search(text)
        if m:
            per_line[i] = set(m.group(1).split(","))
    return per_line, whole_file


class FileContext:
    """One parsed source file plus the lookup tables checkers share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._jl_parent = parent  # type: ignore[attr-defined]
        self.line_pragmas, self.file_pragmas = _parse_pragmas(self.lines)
        # enclosing def/class intervals for scope-level pragmas and
        # finding symbols: (start, end, qualname, def_line)
        self._scopes: List[Tuple[int, int, str, int]] = []
        self._collect_scopes(self.tree, prefix="")

    def _collect_scopes(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}{child.name}"
                self._scopes.append(
                    (child.lineno, child.end_lineno or child.lineno,
                     qual, child.lineno))
                self._collect_scopes(child, prefix=qual + ".")
            else:
                self._collect_scopes(child, prefix=prefix)

    def symbol_at(self, line: int) -> str:
        best = ""
        best_span = None
        for start, end, qual, _ in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def suppressed(self, rule: str, line: int) -> bool:
        if {"all", rule} & self.file_pragmas:
            return True
        for probe in self._pragma_lines(line):
            rules = self.line_pragmas.get(probe)
            if rules and {"all", rule} & rules:
                return True
        return False

    def _pragma_lines(self, line: int) -> Iterable[int]:
        """Lines whose pragma governs `line`: the line itself, the line
        above it, and every enclosing def/class header line."""
        yield line
        yield line - 1
        for start, end, _, def_line in self._scopes:
            if start <= line <= end:
                yield def_line

    def finding(self, rule: str, node: ast.AST, message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.suppressed(rule, line):
            return None
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=snippet,
                       symbol=self.symbol_at(line))


# --------------------------------------------------------- taint helpers

def node_name(node: ast.AST) -> Optional[str]:
    """The identifier a Name/Attribute leaf refers to (`self.x` -> "x")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def names_in(node: ast.AST) -> Set[str]:
    """All Name ids and Attribute attrs mentioned under `node`."""
    out: Set[str] = set()
    for n in ast.walk(node):
        name = node_name(n)
        if name is not None:
            out.add(name)
    return out


def under_shape_access(leaf: ast.AST) -> bool:
    """True when `leaf` only contributes static information: it is read
    through .shape/.dtype/len()/… — the accesses jit and constant-time
    code may branch on freely."""
    node = leaf
    parent = getattr(node, "_jl_parent", None)
    while parent is not None:
        if isinstance(parent, ast.Attribute) and parent.attr in SHAPE_ATTRS:
            return True
        if isinstance(parent, ast.Call):
            fn = parent.func
            if isinstance(fn, ast.Name) and fn.id in SHAPE_CALLS \
                    and node in parent.args:
                return True
            # x.dtype == ..., jnp.shape(x): treated by the Attribute arm
        if isinstance(parent, (ast.stmt,)):
            return False
        node, parent = parent, getattr(parent, "_jl_parent", None)
    return False


def is_none_check(test: ast.AST) -> bool:
    """`x is None` / `x is not None` — pytree-structure checks, legal in
    jit code and secret-independent."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], (ast.Is, ast.IsNot)):
        return True
    if isinstance(test, ast.BoolOp):
        return all(is_none_check(v) for v in test.values)
    return False


def tainted_leaves(node: ast.AST, tainted: Set[str]) -> List[ast.AST]:
    """Name/Attribute leaves under `node` whose identifier is tainted
    and which are NOT read through a shape-only access."""
    hits: List[ast.AST] = []
    for n in ast.walk(node):
        name = node_name(n)
        if name in tainted and not under_shape_access(n):
            hits.append(n)
    return hits


#: names that must never carry taint — receivers, builtins, and module
#: aliases; tainting `self` or `int` poisons every later expression
NEVER_TAINT = {"self", "cls", "int", "float", "bool", "len", "bytes",
               "bytearray", "range", "enumerate", "zip", "min", "max",
               "sum", "abs", "np", "numpy", "jnp", "jax", "lax", "os",
               "functools", "struct", "isinstance", "type", "print"}


def _target_value_names(tgt: ast.AST) -> Set[str]:
    """Names that RECEIVE a value in an assignment target.  A subscript
    index or attribute chain does not receive the value — walking the
    whole target (the naive approach) taints loop indices and `self`
    and poisons everything downstream."""
    if isinstance(tgt, ast.Name):
        return {tgt.id}
    if isinstance(tgt, ast.Attribute):
        return {tgt.attr}
    if isinstance(tgt, ast.Subscript):
        return _target_value_names(tgt.value)
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in tgt.elts:
            out |= _target_value_names(el)
        return out
    if isinstance(tgt, ast.Starred):
        return _target_value_names(tgt.value)
    return set()


def propagate_taint(body: List[ast.stmt], tainted: Set[str]) -> Set[str]:
    """One forward pass: any assignment whose RHS *reads data from* a
    tainted name (not just its shape/dtype) taints the value-receiving
    names of its targets.  Conservative and loop-free on purpose —
    checkers re-run it per function, and a single pass matches how
    straight-line kernel code is written."""
    tainted = set(tainted) - NEVER_TAINT

    def rhs_tainted(value: ast.AST) -> bool:
        return bool(tainted_leaves(value, tainted))

    def add(names: Set[str]) -> None:
        tainted.update(names - NEVER_TAINT)

    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign) and rhs_tainted(node.value):
                for tgt in node.targets:
                    add(_target_value_names(tgt))
            elif isinstance(node, ast.AugAssign) and \
                    rhs_tainted(node.value):
                add(_target_value_names(node.target))
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None and rhs_tainted(node.value):
                add(_target_value_names(node.target))
            elif isinstance(node, ast.For) and rhs_tainted(node.iter):
                add(_target_value_names(node.target))
    return tainted


def call_func_name(call: ast.Call) -> Optional[str]:
    """Last path component of a call target: `a.b.f(x)` -> "f"."""
    return node_name(call.func)


def int_const(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = int_const(node.operand)
        return -v if v is not None else None
    return None
