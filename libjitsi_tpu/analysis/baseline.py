"""Committed baseline for grandfathered findings.

Every entry carries a one-line justification — the gate enforces zero
NEW findings, while documented pre-existing ones (e.g. the table AES
core's by-design gathers) stay visible in the file instead of silently
pragma'd away.  Keys are content-based (`Finding.content_key`), so
unrelated edits that shift line numbers do not invalidate the baseline;
editing the flagged line itself DOES (the finding re-fires and must be
re-justified or fixed — that is the point).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from libjitsi_tpu.analysis.core import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, str]:
    """{content_key: justification}; missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data["entries"] if isinstance(data, dict) else data
    return {e["key"]: e.get("why", "") for e in entries}


def save_baseline(findings: List[Finding], path: str = DEFAULT_BASELINE,
                  why: str = "grandfathered at baseline creation") -> None:
    entries = [{"key": f.content_key, "why": why,
                "rule": f.rule, "path": f.path, "line": f.line,
                "snippet": f.snippet}
               for f in sorted(findings,
                               key=lambda f: (f.path, f.line, f.rule))]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=1, sort_keys=False)
        fh.write("\n")


def split_by_baseline(findings: List[Finding],
                      baseline: Dict[str, str]
                      ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, grandfathered, stale_keys).  Stale keys are baseline
    entries whose finding no longer fires — kept visible so the
    baseline shrinks as code heals instead of accreting forever."""
    fired = {f.content_key for f in findings}
    new = [f for f in findings if f.content_key not in baseline]
    old = [f for f in findings if f.content_key in baseline]
    stale = sorted(k for k in baseline if k not in fired)
    return new, old, stale
