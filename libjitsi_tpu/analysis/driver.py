"""Per-file parallel lint driver + human/JSON rendering.

`run_lint(paths)` discovers ``.py`` files, parses and runs the per-file
checkers across a thread pool (one task per file — parse plus four
visitors is microseconds per file, the pool exists so a cold cache of
~200 files clears the tier-1 <10 s gate with headroom to grow), then
runs the cross-file checkers on the assembled index, assigns
occurrence indices, and applies the committed baseline.

Exit-code contract (scripts/lint.py): 0 clean, 1 findings, 2 internal
error — an unparseable file is an internal error, not a finding, so a
syntax-broken tree fails loudly rather than linting clean.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from libjitsi_tpu.analysis import baseline as baseline_mod
from libjitsi_tpu.analysis.checkers import (GLOBAL_CHECKERS,
                                            PER_FILE_CHECKERS)
from libjitsi_tpu.analysis.core import FileContext, Finding

SKIP_DIRS = {"__pycache__", ".git", "build", "dist"}


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # new (unbaselined) findings
    grandfathered: List[Finding]
    stale_baseline: List[str]
    files_checked: int
    errors: List[str]                # internal errors (parse failures)

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 1 if self.findings else 0

    def to_json(self) -> str:
        return json.dumps({
            "files_checked": self.files_checked,
            "findings": [f.to_dict() for f in self.findings],
            "grandfathered": [f.to_dict() for f in self.grandfathered],
            "stale_baseline": self.stale_baseline,
            "errors": self.errors,
            "exit_code": self.exit_code,
        }, indent=1)

    def render_human(self) -> str:
        out: List[str] = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line)):
            out.append(f.render())
        if self.stale_baseline:
            out.append(f"note: {len(self.stale_baseline)} stale baseline "
                       "entr(y/ies) no longer fire — prune with "
                       "`scripts/lint.py --prune-baseline`:")
            out.extend(f"  {k}" for k in self.stale_baseline)
        for e in self.errors:
            out.append(f"internal error: {e}")
        out.append(
            f"jitlint: {len(self.findings)} new finding(s), "
            f"{len(self.grandfathered)} baselined, "
            f"{self.files_checked} files checked")
        return "\n".join(out)


def discover_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """[(abspath, relpath)] for every .py under `paths` (files pass
    through directly)."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append((p, os.path.basename(p)))
            continue
        root_parent = os.path.dirname(p)
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    out.append((full, os.path.relpath(full, root_parent)))
    return out


def _lint_one(path: str, relpath: str
              ) -> Tuple[Optional[FileContext], List[Finding],
                         Optional[str]]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        ctx = FileContext(path, relpath, source)
    except (SyntaxError, UnicodeDecodeError, OSError) as exc:
        return None, [], f"{relpath}: {exc}"
    findings: List[Finding] = []
    for checker in PER_FILE_CHECKERS:
        findings.extend(checker(ctx))
    return ctx, findings, None


def _assign_occurrences(findings: List[Finding]) -> None:
    """Identical (rule, path, symbol, snippet) findings get stable
    ordinal suffixes in line order so each can be baselined
    independently."""
    groups = defaultdict(list)
    for f in findings:
        f.occurrence = 0
        groups[f.content_key].append(f)
    for group in groups.values():
        for i, f in enumerate(sorted(group, key=lambda f: (f.line, f.col))):
            f.occurrence = i


def run_lint(paths: Sequence[str],
             baseline_path: Optional[str] = None,
             jobs: Optional[int] = None) -> LintResult:
    files = discover_files(paths)
    index: Dict[str, FileContext] = {}
    findings: List[Finding] = []
    errors: List[str] = []

    workers = jobs or min(32, (os.cpu_count() or 4))
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
        for ctx, file_findings, err in ex.map(
                lambda pr: _lint_one(*pr), files):
            if err is not None:
                errors.append(err)
                continue
            assert ctx is not None
            index[ctx.relpath] = ctx
            findings.extend(file_findings)

    for checker in GLOBAL_CHECKERS:
        findings.extend(checker(index))

    _assign_occurrences(findings)
    base = baseline_mod.load_baseline(
        baseline_path or baseline_mod.DEFAULT_BASELINE)
    new, old, stale = baseline_mod.split_by_baseline(findings, base)
    return LintResult(findings=new, grandfathered=old,
                      stale_baseline=stale, files_checked=len(index),
                      errors=errors)
