"""Whole-tree lint driver + human/JSON rendering.

`run_lint(paths)` discovers ``.py`` files and builds the facts index
(``index.build_index``): files whose content sha matches the disk
cache skip parsing and per-file checking entirely; the rest are
parsed, checked, and fact-extracted across a thread pool.  The global
checkers (drift, mesh) and the interprocedural graph checkers
(secret-flow, plane-affinity) then run over facts — never over ASTs —
so a warm run's cost is hashing sources plus pure set/graph work.

``changed_only`` (scripts/lint.py --changed) narrows the re-check set
further: git names the changed files, the cached import graph gives
their reverse-dependency closure, and every file outside that closure
is trusted from the cache without even re-reading its source.

Exit-code contract (scripts/lint.py): 0 clean, 1 findings, 2 internal
error — an unparseable file is an internal error, not a finding, so a
syntax-broken tree fails loudly rather than linting clean.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Set, Tuple

from libjitsi_tpu.analysis import baseline as baseline_mod
from libjitsi_tpu.analysis import index as index_mod
from libjitsi_tpu.analysis.checkers import (GLOBAL_CHECKERS,
                                            GRAPH_CHECKERS,
                                            PER_FILE_CHECKERS)
from libjitsi_tpu.analysis.checkers import drift as drift_mod
from libjitsi_tpu.analysis.core import FileContext, Finding

SKIP_DIRS = {"__pycache__", ".git", "build", "dist"}


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # new (unbaselined) findings
    grandfathered: List[Finding]
    stale_baseline: List[str]
    files_checked: int
    errors: List[str]                # internal errors (parse failures)
    cache_hits: int = 0
    cache_misses: int = 0
    wall_s: float = 0.0

    @property
    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 1 if self.findings else 0

    @property
    def cache_stats(self) -> str:
        return (f"index cache {self.cache_hits} hit / "
                f"{self.cache_misses} miss")

    def to_json(self) -> str:
        return json.dumps({
            "files_checked": self.files_checked,
            "findings": [f.to_dict() for f in self.findings],
            "grandfathered": [f.to_dict() for f in self.grandfathered],
            "stale_baseline": self.stale_baseline,
            "errors": self.errors,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "wall_s": round(self.wall_s, 3),
            "exit_code": self.exit_code,
        }, indent=1)

    def render_human(self) -> str:
        out: List[str] = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line)):
            out.append(f.render())
        if self.stale_baseline:
            out.append(f"note: {len(self.stale_baseline)} stale baseline "
                       "entr(y/ies) no longer fire — prune with "
                       "`scripts/lint.py --prune-baseline`:")
            out.extend(f"  {k}" for k in self.stale_baseline)
        for e in self.errors:
            out.append(f"internal error: {e}")
        out.append(
            f"jitlint: {len(self.findings)} new finding(s), "
            f"{len(self.grandfathered)} baselined, "
            f"{self.files_checked} files checked")
        return "\n".join(out)


def discover_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """[(abspath, relpath)] for every .py under `paths` (files pass
    through directly)."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append((p, os.path.basename(p)))
            continue
        root_parent = os.path.dirname(p)
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    out.append((full, os.path.relpath(full, root_parent)))
    return out


def _assign_occurrences(findings: List[Finding]) -> None:
    """Identical (rule, path, symbol, snippet) findings get stable
    ordinal suffixes in line order so each can be baselined
    independently."""
    groups = defaultdict(list)
    for f in findings:
        f.occurrence = 0
        groups[f.content_key].append(f)
    for group in groups.values():
        for i, f in enumerate(sorted(group, key=lambda f: (f.line, f.col))):
            f.occurrence = i


def _git_changed_files() -> Optional[Set[str]]:
    """Absolute paths of files git reports modified/added/untracked
    vs HEAD, or None when git is unavailable (fall back to a full
    sha-checked run)."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=10)
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=10)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=10)
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
        names = (diff.stdout.splitlines()
                 + untracked.stdout.splitlines())
        return {os.path.abspath(os.path.join(root, n))
                for n in names if n.strip()}
    except (OSError, subprocess.SubprocessError):
        return None


def _trusted_set(files: Sequence[Tuple[str, str]],
                 cache: Dict[str, dict]) -> Set[str]:
    """--changed mode: relpaths that may be served from the cache
    without re-reading — everything OUTSIDE the changed set's
    reverse-dependency closure (computed over cached import facts)."""
    changed_abs = _git_changed_files()
    if changed_abs is None:
        return set()
    rel_of = {os.path.abspath(p): rel.replace("\\", "/")
              for p, rel in files}
    changed_rels = {rel_of[p] for p in changed_abs if p in rel_of}
    # reverse-dep closure over the cached import graph
    tindex = index_mod.TreeIndex()
    for rel, entry in cache.items():
        tindex.facts[rel] = index_mod.FileFacts(entry["facts"])
    closure = tindex.reverse_deps(changed_rels) | changed_rels
    return {rel for rel in (r for _, r in files)
            if rel.replace("\\", "/") not in closure}


def run_lint(paths: Sequence[str],
             baseline_path: Optional[str] = None,
             jobs: Optional[int] = None,
             use_cache: bool = True,
             changed_only: bool = False,
             cache_path: Optional[str] = None) -> LintResult:
    t0 = time.perf_counter()
    files = discover_files(paths)
    # the cache lives beside the baseline in use, so fixture runs
    # against a tmp baseline never touch the committed tree's cache
    cpath = cache_path or os.path.join(
        os.path.dirname(os.path.abspath(
            baseline_path or baseline_mod.DEFAULT_BASELINE)),
        ".jitlint_index.json")
    cache = index_mod.load_cache(cpath) if use_cache else {}
    trusted = _trusted_set(files, cache) if (changed_only and cache) \
        else set()

    tindex, per_file = index_mod.build_index(
        files, PER_FILE_CHECKERS, jobs=jobs, cache=cache,
        trusted=trusted)
    findings = list(tindex.findings)

    if not tindex.errors:
        for checker in GLOBAL_CHECKERS:
            findings.extend(checker(tindex.facts))
        for checker in GRAPH_CHECKERS:
            findings.extend(checker(tindex))

    base = baseline_mod.load_baseline(
        baseline_path or baseline_mod.DEFAULT_BASELINE)
    for msg in drift_mod.check_baseline_justifications(base):
        findings.append(Finding(
            rule="drift", path="libjitsi_tpu/analysis/baseline.json",
            line=1, col=0, message=msg, snippet=msg.split("—")[0].strip(),
            symbol=""))

    _assign_occurrences(findings)
    new, old, stale = baseline_mod.split_by_baseline(findings, base)

    if use_cache and not tindex.errors:
        index_mod.save_cache(tindex, per_file, cpath, prior=cache)

    return LintResult(findings=new, grandfathered=old,
                      stale_baseline=stale,
                      files_checked=len(tindex.facts),
                      errors=tindex.errors,
                      cache_hits=tindex.cache_hits,
                      cache_misses=tindex.cache_misses,
                      wall_s=time.perf_counter() - t0)
