from libjitsi_tpu.control.dtls import (  # noqa: F401
    DtlsSrtpEndpoint,
    generate_certificate,
    is_dtls,
)
from libjitsi_tpu.control.sdes import CryptoAttribute, SdesControl  # noqa: F401
