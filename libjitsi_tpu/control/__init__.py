from libjitsi_tpu.control.sdes import SdesControl, CryptoAttribute  # noqa: F401
