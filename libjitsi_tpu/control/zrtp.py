"""ZRTP — media-path Diffie-Hellman key agreement (RFC 6189).

Rebuilds the reference's `org.jitsi.impl.neomedia.transform.zrtp.
{ZRTPTransformEngine,ZrtpControlImpl}` (which delegate to the zrtp4j
library) from the RFC: the Hello/Commit/DHPart/Confirm state machine,
the H0..H3 hash-image chain with retroactive message-HMAC verification,
ECDH P-256 ("EC25") key agreement, the RFC 6189 §4.4.1.4 s0 / §4.5.1
KDF derivations, Short Authentication String (B32), and SRTP master
key/salt export feeding `SrtpStreamTable` — the same "key provider →
SRTP context" interface SDES and DTLS-SRTP use.

Packet format: ZRTP messages ride RTP-lookalike packets (version 0,
magic cookie 0x5A525450, CRC-32C trailer) multiplexed on the media
port, demuxed by the cookie.  Like the in-memory DTLS endpoint, this is
packet-in/packet-out for the host I/O loop.
"""

from __future__ import annotations

import collections
import hashlib
import hmac as hmac_mod
import os
import struct
from typing import Dict, List, Optional, Tuple

from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives import serialization

from libjitsi_tpu.transform.srtp.policy import SrtpProfile

MAGIC = 0x5A525450  # "ZRTP"
PREAMBLE = 0x505A
VERSION = b"1.10"

HASH_S256 = b"S256"
HASH_S384 = b"S384"
CIPHER_AES1 = b"AES1"
CIPHER_AES3 = b"AES3"
AUTH_HS80 = b"HS80"
AUTH_HS32 = b"HS32"
KA_EC25 = b"EC25"
KA_DH3K = b"DH3k"
KA_MULT = b"Mult"
SAS_B32 = b"B32 "

# ------------------------------------------------ algorithm agility tables --
# RFC 6189 §4.1.2: each Hello advertises ORDERED preference lists per
# slot; the committing endpoint selects, per slot, the first algorithm
# in its own order that the peer also advertised (preference
# intersection).  The old fixed suite (S256/AES1/HS80/EC25/B32) is the
# head of every default list, so default deployments negotiate exactly
# what they always did.

HASH_FNS = {HASH_S256: hashlib.sha256, HASH_S384: hashlib.sha384}
CIPHER_KEY_BITS = {CIPHER_AES1: 128, CIPHER_AES3: 256}
AUTH_TAG_BITS = {AUTH_HS80: 80, AUTH_HS32: 32}

# RFC 3526 §4 3072-bit MODP group ("DH3k", RFC 6189 §5.1.5): p =
# 2^3072 - 2^3008 - 1 + 2^64*(floor(2^2942 pi) + 1690314), generator 2.
# The constant below was re-derived from that formula (and the same
# derivation reproduces the published 2048-bit group-14 value bit for
# bit); p and (p-1)/2 are both prime.
DH3K_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AAAC42DAD33170D04507A33"
    "A85521ABDF1CBA64ECFB850458DBEF0A8AEA71575D060C7DB3970F85A6E1E4C7"
    "ABF5AE8CDB0933D71E8C94E04A25619DCEE3D2261AD2EE6BF12FFA06D98A0864"
    "D87602733EC86A64521F2B18177B200CBBE117577A615D6C770988C0BAD946E2"
    "08E24FA074E5AB3143DB5BFCE0FD108E4B82D120A93AD2CAFFFFFFFFFFFFFFFF",
    16)
DH3K_G = 2
KA_PUB_LEN = {KA_EC25: 64, KA_DH3K: 384}

DEFAULT_PREFS = {
    "hash": (HASH_S256, HASH_S384),
    "cipher": (CIPHER_AES1, CIPHER_AES3),
    "auth": (AUTH_HS80, AUTH_HS32),
    "ka": (KA_EC25, KA_DH3K),
    "sas": (SAS_B32,),
}
_SLOT_CODES = {
    "hash": tuple(HASH_FNS), "cipher": tuple(CIPHER_KEY_BITS),
    "auth": tuple(AUTH_TAG_BITS), "ka": tuple(KA_PUB_LEN),
    "sas": (SAS_B32,),
}

# (cipher, auth) -> the SRTP profile the negotiated keys feed
PROFILE_BY_SUITE = {
    (CIPHER_AES1, AUTH_HS80): SrtpProfile.AES_CM_128_HMAC_SHA1_80,
    (CIPHER_AES1, AUTH_HS32): SrtpProfile.AES_CM_128_HMAC_SHA1_32,
    (CIPHER_AES3, AUTH_HS80): SrtpProfile.AES_256_CM_HMAC_SHA1_80,
    (CIPHER_AES3, AUTH_HS32): SrtpProfile.AES_256_CM_HMAC_SHA1_32,
}

_B32_ALPHABET = "ybndrfg8ejkmcpqxot1uwisza345h769"  # RFC 6189 §5.1.6

# CRC-32C (Castagnoli, reflected poly 0x82F63B78) — RFC 6189 §5 requires
# the RFC 3309 CRC, not zlib's CRC-32/IEEE.
_CRC32C_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for byte in data:
        crc = _CRC32C_TABLE[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac_mod.new(key, msg, hashlib.sha256).digest()


def _kdf(ki: bytes, label: bytes, context: bytes, length_bits: int) -> bytes:
    """RFC 6189 §4.5.1 (NIST SP 800-108 counter-mode, one block)."""
    data = struct.pack("!I", 1) + label + b"\x00" + context + \
        struct.pack("!I", length_bits)
    return _hmac(ki, data)[: length_bits // 8]


def sas_b32(sashash: bytes) -> str:
    """Render the 20-bit short authentication string (RFC 6189 §5.1.6)."""
    bits = int.from_bytes(sashash[:4], "big") >> 12
    return "".join(_B32_ALPHABET[(bits >> s) & 31] for s in (15, 10, 5, 0))


# ---------------------------------------------------------------- packets --

def _wrap(msg: bytes, seq: int, ssrc: int) -> bytes:
    """ZRTP packet: RTP-lookalike header + message + CRC-32 trailer."""
    hdr = struct.pack("!BBH", 0x10, 0, seq & 0xFFFF) + \
        struct.pack("!II", MAGIC, ssrc & 0xFFFFFFFF)
    body = hdr + msg
    return body + struct.pack("!I", crc32c(body))


def is_zrtp(datagram: bytes) -> bool:
    return (len(datagram) >= 12
            and datagram[0] == 0x10
            and datagram[4:8] == struct.pack("!I", MAGIC))


def _unwrap(datagram: bytes) -> Optional[bytes]:
    if not is_zrtp(datagram) or len(datagram) < 16:
        return None
    body, crc = datagram[:-4], struct.unpack("!I", datagram[-4:])[0]
    if crc32c(body) != crc:
        return None
    return body[12:]


def _msg(mtype: bytes, payload: bytes) -> bytes:
    assert len(mtype) == 8
    total_words = (12 + len(payload)) // 4
    return struct.pack("!HH", PREAMBLE, total_words) + mtype + payload


def _parse_msg(msg: bytes) -> Optional[Tuple[bytes, bytes]]:
    if len(msg) < 12 or struct.unpack("!H", msg[:2])[0] != PREAMBLE:
        return None
    return msg[4:12], msg[12:]


# -------------------------------------------------------------- zid cache --

class ZidCache:
    """RFC 6189 §4.9 retained-secret cache: peer ZID → (rs1, rs2).

    After every completed DH-mode session both sides derive the same
    fresh retained secret and shift it in (rs1 → rs2, new → rs1); the
    next session's s0 then mixes the matching secret as s1 — KEY
    CONTINUITY: a MITM who wasn't in the first session cannot produce
    the continuity secret even if the SAS is never compared.  Keeping
    TWO generations tolerates one-sided update loss (a side that
    crashed before updating still matches the peer's rs2).

    In-memory; `snapshot()`/`restore()` give the caller a serializable
    form (the reference's zrtp4j persists its ZidFile likewise).

    BOUNDED: at most `max_entries` peers, least-recently-used evicted
    first (a reconnect storm from rotating ZIDs must not grow host
    memory without bound).  A lookup hit or an update refreshes the
    entry's recency; evictions are counted and the bound rides
    snapshot/restore.  Evicting a peer costs only key continuity on
    its NEXT session (it renegotiates from scratch) — never media.
    """

    def __init__(self, max_entries: int = 4096):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = int(max_entries)
        self._store: "collections.OrderedDict[bytes, Tuple[bytes, Optional[bytes]]]" \
            = collections.OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._store)

    def lookup(self, zid: bytes) -> Tuple[Optional[bytes], Optional[bytes]]:
        key = bytes(zid)
        got = self._store.get(key)
        if got is None:
            return (None, None)
        self._store.move_to_end(key)
        return got

    def update(self, zid: bytes, rs_new: bytes) -> None:
        rs1, _ = self.lookup(zid)
        self._store[bytes(zid)] = (bytes(rs_new), rs1)
        self._store.move_to_end(bytes(zid))
        while len(self._store) > self.max_entries:
            self._store.popitem(last=False)
            self.evictions += 1

    def forget(self, zid: bytes) -> None:
        self._store.pop(bytes(zid), None)

    def snapshot(self) -> dict:
        return {"max_entries": self.max_entries,
                "evictions": self.evictions,
                # list of (zid, rs1, rs2) in LRU->MRU order so restore
                # reproduces the eviction order exactly
                "store": [(z, rs1, rs2)
                          for z, (rs1, rs2) in self._store.items()]}

    @classmethod
    def restore(cls, snap: dict) -> "ZidCache":
        if "store" not in snap:
            # legacy unbounded-format snapshot: {zid: (rs1, rs2)}
            c = cls()
            for z, (rs1, rs2) in snap.items():
                c._store[bytes(z)] = (bytes(rs1), None if rs2 is None
                                      else bytes(rs2))
            return c
        c = cls(max_entries=int(snap["max_entries"]))
        c.evictions = int(snap.get("evictions", 0))
        for z, rs1, rs2 in snap["store"]:
            c._store[bytes(z)] = (bytes(rs1),
                                  None if rs2 is None else bytes(rs2))
        return c


# --------------------------------------------------------------- endpoint --

class ZrtpProtocolError(RuntimeError):
    """An authenticity/protocol check failed on a received message.

    Never escapes `feed()` — the offending packet is dropped and the
    failure recorded in `ZrtpEndpoint.alerts` (an exception here would
    hand any off-path forger a DoS on the host I/O loop)."""


class ZrtpEndpoint:
    """One ZRTP association.  Both sides send Hello; the side told
    `initiate()` sends Commit and becomes the initiator.

    API mirrors the DTLS endpoint: `hello_packets()`, `feed(datagram)`,
    `complete`, `srtp_keys()`, plus `sas` for the user-verification
    string (the MITM defense: both users compare the 4 chars).
    """

    def __init__(self, zid: Optional[bytes] = None, ssrc: int = 0,
                 cache: Optional[ZidCache] = None,
                 multistream_from: Optional["ZrtpEndpoint"] = None,
                 algorithms: Optional[Dict[str, tuple]] = None):
        """`cache`: RFC 6189 §4.9 retained-secret store — sessions with
        a cached peer mix the shared secret into s0 (key continuity)
        and rotate it on completion.  `multistream_from`: a COMPLETED
        DH-mode endpoint of the same peer association; this endpoint
        then keys via Multistream mode (§4.4.3) — no DH, s0 derived
        from the parent's ZRTPSess session key.  `algorithms`: ordered
        preference lists per slot ("hash"/"cipher"/"auth"/"ka"/"sas",
        RFC 6189 §4.1.2) — defaults to DEFAULT_PREFS; the committing
        side selects the first of ITS preferences the peer advertised."""
        if multistream_from is not None:
            if multistream_from.session_key is None:
                raise RuntimeError(
                    "multistream_from endpoint has no session key "
                    "(DH-mode exchange not complete)")
            zid = multistream_from.zid if zid is None else zid
        self.zid = zid if zid is not None else os.urandom(12)
        self.ssrc = ssrc
        self.cache = cache
        self._zrtp_sess = (None if multistream_from is None
                           else multistream_from.session_key)
        # _mult is the NEGOTIATED mode: seeded by capability here, but a
        # peer's DH-mode Commit flips it off (a mult-capable responder
        # must follow the wire, not its constructor)
        self._mult = multistream_from is not None
        self._mult_nonce: Optional[bytes] = None
        self._rotated = False
        # outcomes (read after complete): did a retained secret match
        # (key continuity held), and this session's exportable ZRTPSess
        self.secret_continuity = False
        self.session_key: Optional[bytes] = None
        # hash image chain (RFC 6189 §9)
        self._h0 = os.urandom(32)
        self._h1 = _sha256(self._h0)
        self._h2 = _sha256(self._h1)
        self._h3 = _sha256(self._h2)
        # algorithm agility (RFC 6189 §4.1.2): validated preference
        # lists; the NEGOTIATED suite is pinned at Commit time
        prefs = dict(DEFAULT_PREFS)
        if algorithms:
            for slot, lst in algorithms.items():
                if slot not in _SLOT_CODES:
                    raise ValueError(f"unknown algorithm slot {slot!r}")
                lst = tuple(lst)
                bad = [c for c in lst if c not in _SLOT_CODES[slot]]
                if bad or not lst:
                    raise ValueError(f"unsupported {slot} codes {bad}")
                prefs[slot] = lst
        self._prefs = prefs
        self.suite: Optional[Dict[str, bytes]] = None
        self._hash = hashlib.sha256       # until a suite is negotiated
        self._ka_priv = None              # lazy; depends on suite["ka"]
        self._seq = int.from_bytes(os.urandom(2), "big")
        self.role: Optional[str] = None
        self.complete = False
        self.sas: Optional[str] = None
        self._s0: Optional[bytes] = None
        # dropped-packet security log — bounded: forged packets must not
        # grow host memory (deque evicts oldest)
        self.alerts = collections.deque(maxlen=64)
        self._peer: Dict[bytes, bytes] = {}  # raw peer messages by type
        self._my_hello = self._make_hello()
        self._my_commit: Optional[bytes] = None
        self._my_dhpart: Optional[bytes] = None
        self._peer_pub: Optional[bytes] = None

    # ------------------------------------------------- negotiated suite
    def _nh(self, b: bytes) -> bytes:
        """Negotiated-hash digest (hvi, total_hash, s0 — §4.4.1)."""
        return self._hash(b).digest()

    def _nkdf(self, ki: bytes, label: bytes, context: bytes,
              length_bits: int) -> bytes:
        """§4.5.1 KDF under the NEGOTIATED hash (the message-MAC /
        hash-image-chain domain stays SHA-256: those run before any
        suite exists on the wire)."""
        data = struct.pack("!I", 1) + label + b"\x00" + context + \
            struct.pack("!I", length_bits)
        return hmac_mod.new(ki, data, self._hash).digest()[
            : length_bits // 8]

    def _peer_hello_algs(self) -> Dict[str, tuple]:
        """Parse the peer Hello's per-slot advertised algorithm lists."""
        hello = self._peer[b"Hello   "]
        off = 12 + 4 + 16 + 32 + 12
        cnt = hello[off:off + 8]
        pos = off + 8
        out: Dict[str, tuple] = {}
        for slot, n in (("hash", cnt[1]), ("cipher", cnt[2]),
                        ("auth", cnt[3]), ("ka", cnt[4]),
                        ("sas", cnt[5])):
            out[slot] = tuple(hello[pos + 4 * i: pos + 4 * (i + 1)]
                              for i in range(n))
            pos += 4 * n
        return out

    def _select_suite(self) -> Dict[str, bytes]:
        """RFC 6189 §4.1.2 preference intersection: per slot, the first
        algorithm in OUR ordered list the peer also advertised."""
        peer = self._peer_hello_algs()
        suite: Dict[str, bytes] = {}
        for slot in ("hash", "cipher", "auth", "ka", "sas"):
            theirs = set(peer.get(slot, ()))
            pick = next((c for c in self._prefs[slot] if c in theirs),
                        None)
            if pick is None:
                raise ZrtpProtocolError(
                    f"ZRTP: no common {slot} algorithm "
                    f"(ours {self._prefs[slot]}, theirs "
                    f"{sorted(theirs)})")
            suite[slot] = pick
        return suite

    def _adopt_suite(self, suite: Dict[str, bytes]) -> None:
        self.suite = dict(suite)
        self._hash = HASH_FNS[suite["hash"]]

    def _ka(self) -> bytes:
        return (self.suite or {}).get("ka", KA_EC25)

    # ------------------------------------------------------------ builders
    def _gen_ka(self):
        if self._ka_priv is None:
            if self._ka() == KA_DH3K:
                # 256-bit exponent per RFC 6189 §4.4.1.3 (DH3k)
                self._ka_priv = int.from_bytes(os.urandom(32), "big")
            else:
                self._ka_priv = ec.generate_private_key(ec.SECP256R1())
        return self._ka_priv

    def _pub_bytes(self) -> bytes:
        priv = self._gen_ka()
        if self._ka() == KA_DH3K:
            return pow(DH3K_G, priv, DH3K_P).to_bytes(384, "big")
        return priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.UncompressedPoint)[1:]  # 64B x||y

    def _make_hello(self) -> bytes:
        payload = VERSION + b"libjitsi-tpu    "[:16] + self._h3 + self.zid
        # flags byte + per-slot counts, then the ORDERED lists (§4.1.2)
        p = self._prefs
        payload += bytes([0, len(p["hash"]), len(p["cipher"]),
                          len(p["auth"]), len(p["ka"]), len(p["sas"]),
                          0, 0])
        for slot in ("hash", "cipher", "auth", "ka", "sas"):
            payload += b"".join(p[slot])
        core = _msg(b"Hello   ", payload + b"\x00" * 8)
        mac = _hmac(self._h2, core[:-8])[:8]
        return core[:-8] + mac

    def _make_commit(self) -> bytes:
        suite = self._select_suite()
        if self._mult:
            # Multistream mode (RFC 6189 §4.4.3): no DH — a fresh nonce
            # rides where DH mode carries the hvi commitment
            self._adopt_suite(dict(suite, ka=KA_MULT))
            self._mult_nonce = os.urandom(16)
            payload = self._h2 + self.zid + suite["hash"] + \
                suite["cipher"] + suite["auth"] + KA_MULT + \
                suite["sas"] + self._mult_nonce
            core = _msg(b"Commit  ", payload + b"\x00" * 8)
            return core[:-8] + _hmac(self._h1, core[:-8])[:8]
        self._adopt_suite(suite)
        dh2 = self._make_dhpart(b"DHPart2 ")
        hvi = self._nh(dh2 + self._peer[b"Hello   "])[:32]
        payload = self._h2 + self.zid + suite["hash"] + \
            suite["cipher"] + suite["auth"] + suite["ka"] + \
            suite["sas"] + hvi
        core = _msg(b"Commit  ", payload + b"\x00" * 8)
        mac = _hmac(self._h1, core[:-8])[:8]
        self._my_dhpart = dh2
        return core[:-8] + mac

    def _secret_ids(self, role_label: bytes) -> bytes:
        """RFC 6189 §4.3.1 rs1ID/rs2ID (+ random aux/pbx IDs): each is
        MAC(secret, sender-role label) truncated to 8 bytes; a side with
        no cached secret for this peer sends random IDs, which simply
        never match."""
        rs1 = rs2 = None
        if self.cache is not None and b"Hello   " in self._peer:
            rs1, rs2 = self.cache.lookup(self._peer_zid())
        ids = b""
        for rs in (rs1, rs2):
            ids += (_hmac(rs, role_label)[:8] if rs is not None
                    else os.urandom(8))
        return ids + os.urandom(16)      # auxsecretID, pbxsecretID

    def _make_dhpart(self, mtype: bytes) -> bytes:
        label = b"Initiator" if mtype == b"DHPart2 " else b"Responder"
        payload = self._h1 + self._secret_ids(label) + self._pub_bytes()
        core = _msg(mtype, payload + b"\x00" * 8)
        mac = _hmac(self._h0, core[:-8])[:8]
        return core[:-8] + mac

    def _make_confirm(self, mtype: bytes) -> bytes:
        # simplified confirm: HMAC(mackey, H0||flags) — the encrypted
        # part's semantics (cache expiry, sig) are not modeled
        key = self._mackey_own()
        payload = _hmac(key, self._h0)[:8] + self._h0
        return _msg(mtype, payload)

    # ----------------------------------------------------------- transport
    def _send(self, msg: bytes) -> bytes:
        # 16-bit wire field (RFC 6189 §5 sequence number): wrap at the
        # increment, not at serialization — a random initial seq near
        # 65535 otherwise grows past 2^16 within one handshake retry
        # storm and desyncs any receiver tracking the raw counter
        self._seq = (self._seq + 1) & 0xFFFF
        return _wrap(msg, self._seq, self.ssrc)

    def hello_packets(self) -> List[bytes]:
        return [self._send(self._my_hello)]

    def initiate(self) -> List[bytes]:
        """Become initiator (requires peer Hello already seen).  Idempotent:
        a retry resends the SAME Commit — regenerating it would fork the
        hvi commitment the peer has already pinned.  A side that already
        became responder (peer's Commit won) refuses: flipping roles
        mid-handshake would deadlock both sides."""
        if b"Hello   " not in self._peer:
            raise RuntimeError("peer Hello not yet received")
        if self.role == "responder":
            raise RuntimeError(
                "peer already committed first; this side is responder")
        if self.role == "initiator" and self._my_commit is not None:
            return [self._send(self._my_commit)]
        self.role = "initiator"
        self._my_commit = self._make_commit()
        return [self._send(self._my_commit)]

    @staticmethod
    def _check_mac(msg: bytes, key: bytes, what: str) -> None:
        """Retroactive message-MAC check (RFC 6189 §8.1.1): each message
        carries HMAC(next-revealed-hash-image, message) in its last 8B."""
        if not hmac_mod.compare_digest(_hmac(key, msg[:-8])[:8], msg[-8:]):
            raise ZrtpProtocolError(f"ZRTP: {what} message MAC mismatch "
                                    "(tampered in flight?)")

    def feed(self, datagram: bytes) -> List[bytes]:
        """Process one datagram; returns reply datagrams.  Never raises on
        wire input: malformed, out-of-order, duplicate and wrong-role
        packets are dropped (returns []), and failed authenticity checks
        are dropped with the reason appended to `self.alerts`."""
        msg = _unwrap(datagram)
        if msg is None:
            return []
        parsed = _parse_msg(msg)
        if parsed is None:
            return []
        mtype, payload = parsed
        try:
            return self._process(mtype, payload, msg)
        except ZrtpProtocolError as e:
            self.alerts.append(str(e))
            return []

    def _process(self, mtype: bytes, payload: bytes,
                 msg: bytes) -> List[bytes]:
        out: List[bytes] = []
        if mtype == b"Hello   ":
            # pin the first Hello: its H3/ZID feed the key derivation,
            # so a mid-handshake replacement must not take effect
            if mtype in self._peer:
                if self._peer[mtype] != msg:
                    return []
            else:
                self._peer[mtype] = msg
            out.append(self._send(_msg(b"HelloACK", b"")))
        elif mtype == b"Commit  ":
            if b"Hello   " not in self._peer:
                return []
            if self.role == "initiator":
                # Commit contention (RFC 6189 §4.2): both sides
                # committed.  A DH-mode Commit beats a Multistream one
                # (comparing the 32B hvi against a 16B nonce would be
                # meaningless, and the DH side cannot process Mult);
                # same-mode ties break on the LOWER value backing down
                # to responder and processing the peer's Commit.
                ka_off = 12 + 32 + 12 + 12
                ours_mult = self._my_commit[ka_off:ka_off + 4] == KA_MULT
                theirs_mult = msg[ka_off:ka_off + 4] == KA_MULT
                if ours_mult != theirs_mult:
                    # a DH-mode Commit beats a Multistream one (§4.2;
                    # comparing a 32B hvi against a 16B nonce would be
                    # meaningless, and the DH side cannot process Mult)
                    we_lose = ours_mult
                else:
                    # same MODE (two DH Commits — even with different
                    # KA choices — or two Mults): lower hvi/nonce backs
                    # down, §4.2's symmetric tie-break
                    hvi_off = 12 + 32 + 12 + 20
                    we_lose = self._my_commit[hvi_off:hvi_off + 32] < \
                        msg[hvi_off:hvi_off + 32]
                if not we_lose:
                    return []               # we win; peer backs down
                self.role = None            # back down, re-process below
                self._my_commit = None
                self._my_dhpart = None
                self._mult_nonce = None
                self._ka_priv = None        # peer's suite may differ
            if mtype in self._peer:
                if self._peer[mtype] != msg:
                    return []
                # duplicate Commit: resend the SAME reply (regenerating
                # a DHPart1 would fork total_hash between the sides)
                if self._mult and self._s0 is not None:
                    return [self._send(self._make_confirm(b"Confirm1"))]
                if self._my_dhpart is None:
                    return []
                return [self._send(self._my_dhpart)]
            peer_h2 = payload[:32]
            if _sha256(peer_h2) != self._peer_hello_h3():
                raise ZrtpProtocolError("ZRTP: Commit H2 does not chain to H3")
            # H2 now known -> verify the peer Hello's MAC retroactively
            self._check_mac(self._peer[b"Hello   "], peer_h2, "Hello")
            # the initiator's chosen suite (§4.1.2): every code must be
            # one WE advertised — a Commit naming an alien algorithm is
            # a downgrade/um-mismatch attack or a broken peer
            chosen = {"hash": payload[44:48], "cipher": payload[48:52],
                      "auth": payload[52:56], "sas": payload[60:64]}
            ka_code = payload[56:60]
            if ka_code != KA_MULT:
                chosen["ka"] = ka_code
            for slot, code in chosen.items():
                if code not in self._prefs[slot]:
                    raise ZrtpProtocolError(
                        f"ZRTP: Commit selects {slot} {code!r} we did "
                        "not offer")
            if payload[56:60] == KA_MULT:
                # Multistream commit (§4.4.3): no DH round — derive s0
                # from the shared ZRTPSess and confirm directly
                if self._zrtp_sess is None:
                    raise ZrtpProtocolError(
                        "ZRTP: Multistream Commit but no session key "
                        "(no completed DH-mode association)")
                self._peer[mtype] = msg
                self.role = "responder"
                self._mult = True
                self._adopt_suite(dict(chosen, ka=KA_MULT))
                self._derive()
                out.append(self._send(self._make_confirm(b"Confirm1")))
                return out
            self._peer[mtype] = msg
            self.role = "responder"
            self._mult = False        # peer chose DH mode: follow it
            self._adopt_suite(chosen)
            self._ka_priv = None      # KA is the initiator's choice
            self._my_dhpart = self._make_dhpart(b"DHPart1 ")
            out.append(self._send(self._my_dhpart))
        elif mtype == b"DHPart1 ":
            if self.role != "initiator" or self._my_dhpart is None:
                return []
            if mtype in self._peer:
                if self._peer[mtype] != msg:
                    return []
                return [self._send(self._my_dhpart)]
            # responder never sends Commit; its H1 chains straight to the
            # Hello H3 and reveals H2 = sha256(H1) for the Hello MAC
            peer_h1 = payload[:32]
            peer_h2 = _sha256(peer_h1)
            if _sha256(peer_h2) != self._peer_hello_h3():
                raise ZrtpProtocolError("ZRTP: DHPart1 H1 does not chain to H3")
            self._check_mac(self._peer[b"Hello   "], peer_h2, "Hello")
            pub = payload[64:64 + KA_PUB_LEN[self._ka()]]
            self._parse_point(pub)       # reject junk at receive time
            self._peer[mtype] = msg
            self._peer_pub = pub
            out.append(self._send(self._my_dhpart))
        elif mtype == b"DHPart2 ":
            if self.role != "responder" or b"Commit  " not in self._peer:
                return []
            if mtype in self._peer:
                if self._peer[mtype] != msg or self._s0 is None:
                    return []
                return [self._send(self._make_confirm(b"Confirm1"))]
            # verify commitment: hvi in Commit == hash(DHPart2||our Hello)
            commit = self._peer[b"Commit  "]
            hvi = commit[12 + 32 + 12 + 20:12 + 32 + 12 + 20 + 32]
            if self._nh(msg + self._my_hello)[:32] != hvi:
                raise ZrtpProtocolError("ZRTP: DHPart2 does not match hvi "
                                        "commitment (possible MITM)")
            # H1 revealed -> chains to Commit H2, and keys the Commit MAC
            peer_h1 = payload[:32]
            if _sha256(peer_h1) != commit[12:44]:
                raise ZrtpProtocolError("ZRTP: DHPart2 H1 does not chain to H2")
            self._check_mac(commit, peer_h1, "Commit")
            pub = payload[64:64 + KA_PUB_LEN[self._ka()]]
            self._parse_point(pub)
            self._peer[mtype] = msg
            self._peer_pub = pub
            self._derive()
            out.append(self._send(self._make_confirm(b"Confirm1")))
        elif mtype == b"Confirm1":
            if self.role != "initiator" or \
                    (b"DHPart1 " not in self._peer and not self._mult):
                return []
            self._derive()
            self._verify_confirm(payload)
            out.append(self._send(self._make_confirm(b"Confirm2")))
            self.complete = True
            self._on_complete()
        elif mtype == b"Confirm2":
            if self.role != "responder" or self._s0 is None:
                return []
            self._verify_confirm(payload)
            out.append(self._send(_msg(b"Conf2ACK", b"")))
            self.complete = True
            self._on_complete()
        return out

    def _on_complete(self) -> None:
        """Post-completion continuity bookkeeping (DH mode): rotate the
        retained secret both sides derive identically (§4.5.2) — the
        NEXT session's s0 then proves this one wasn't MITM'd.
        Idempotent: Confirms retransmit on lossy paths, and a double
        rotation would overwrite BOTH cached generations with the same
        value, losing the drift tolerance rs2 exists for."""
        if self._mult or self.cache is None or self._rotated:
            return
        self._rotated = True
        rs_new = self._nkdf(self._s0, b"retained secret", self._ctx, 256)
        self.cache.update(self._peer_zid(), rs_new)

    # ---------------------------------------------------------- key sched
    def _peer_hello_h3(self) -> bytes:
        hello = self._peer[b"Hello   "]
        return hello[12 + 4 + 16:12 + 4 + 16 + 32]

    def _parse_point(self, raw: bytes):
        """Validate a peer's public KA value for the NEGOTIATED group —
        64-byte x||y P-256 point (EC25) or 384-byte MODP element
        (DH3k).  Raises ZrtpProtocolError (dropped+alerted by feed) on
        junk — an invalid value must not escape as ValueError into the
        I/O loop, nor reach the agreement as an invalid-curve /
        small-subgroup input."""
        if self._ka() == KA_DH3K:
            if len(raw) != 384:
                raise ZrtpProtocolError(
                    "ZRTP: DH3k public value truncated")
            y = int.from_bytes(raw, "big")
            if not 1 < y < DH3K_P - 1:
                raise ZrtpProtocolError(
                    "ZRTP: DH3k public value out of range")
            return y
        if len(raw) != 64:
            raise ZrtpProtocolError("ZRTP: DHPart public value truncated")
        try:
            return ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256R1(), b"\x04" + raw)
        except ValueError as e:
            raise ZrtpProtocolError(f"ZRTP: invalid EC point ({e})") from e

    def _dh_result(self) -> bytes:
        peer = self._parse_point(self._peer_pub)
        if self._ka() == KA_DH3K:
            return pow(peer, self._gen_ka(), DH3K_P).to_bytes(384, "big")
        return self._gen_ka().exchange(ec.ECDH(), peer)

    def _match_retained(self) -> Optional[bytes]:
        """s1 selection (RFC 6189 §4.3): compare the PEER's rs1ID/rs2ID
        (from its DHPart, keyed by the peer's role label) against our
        cached generations; first match wins.  Both sides hold the same
        rotated values, so they pick the same secret — and the 2x2 scan
        tolerates one side having missed one rotation."""
        if self.cache is None:
            return None
        peer_dh = self._peer.get(b"DHPart1 " if self.role == "initiator"
                                 else b"DHPart2 ")
        if peer_dh is None:
            return None
        ids = (peer_dh[12 + 32:12 + 40], peer_dh[12 + 40:12 + 48])
        peer_label = b"Responder" if self.role == "initiator" \
            else b"Initiator"
        for mine in self.cache.lookup(self._peer_zid()):
            if mine is not None and _hmac(mine, peer_label)[:8] in ids:
                return mine
        return None

    def _derive(self) -> None:
        if self._s0 is not None:
            return
        if self._mult:
            self._derive_mult()
            return
        zidi, zidr, hello_r, commit = self._session_parties()
        if self.role == "initiator":
            dh1 = self._peer[b"DHPart1 "]
            dh2 = self._my_dhpart
        else:
            dh1 = self._my_dhpart
            dh2 = self._peer[b"DHPart2 "]
        total_hash = self._nh(hello_r + commit + dh1 + dh2)
        dhr = self._dh_result()
        # RFC 6189 §4.4.1.4: s1 = matching retained secret (key
        # continuity) or null; aux/pbx (s2, s3) not modeled -> null
        s1 = self._match_retained()
        self.secret_continuity = s1 is not None
        null = struct.pack("!I", 0)
        s1_part = (struct.pack("!I", len(s1)) + s1) if s1 else null
        self._s0 = self._nh(struct.pack("!I", 1) + dhr + b"ZRTP-HMAC-KDF" +
                            zidi + zidr + total_hash + s1_part + null + null)
        self._ctx = zidi + zidr + total_hash
        self.sas = sas_b32(self._nkdf(self._s0, b"SAS", self._ctx, 256))
        # exportable session key: Multistream children key off this
        # (§4.5.2), so additional media streams skip the DH entirely
        self.session_key = self._nkdf(self._s0, b"ZRTP Session Key",
                                      self._ctx, 256)

    def _session_parties(self):
        """Role-dependent (zidi, zidr, responder-Hello, Commit) shared
        by the DH and Multistream derivations."""
        if self.role == "initiator":
            return (self.zid, self._peer_zid(),
                    self._peer[b"Hello   "], self._my_commit)
        return (self._peer_zid(), self.zid,
                self._my_hello, self._peer[b"Commit  "])

    def _derive_mult(self) -> None:
        """Multistream s0 (RFC 6189 §4.4.3.2): KDF from the parent
        association's ZRTPSess over THIS stream's negotiation hash (the
        Commit carries a fresh nonce, so every stream's keys differ)."""
        zidi, zidr, hello_r, commit = self._session_parties()
        total_hash = self._nh(hello_r + commit)
        self._ctx = zidi + zidr + total_hash
        self._s0 = self._nkdf(self._zrtp_sess, b"ZRTP MSK", self._ctx, 256)
        self.sas = sas_b32(self._nkdf(self._s0, b"SAS", self._ctx, 256))
        # ZRTPSess is per ASSOCIATION (§4.5.2): propagate it so further
        # streams can key off this endpoint even when the caller only
        # kept the newest one
        self.session_key = self._zrtp_sess

    def _peer_zid(self) -> bytes:
        hello = self._peer[b"Hello   "]
        return hello[12 + 4 + 16 + 32:12 + 4 + 16 + 32 + 12]

    def _mackey_own(self) -> bytes:
        label = b"Initiator HMAC key" if self.role == "initiator" else \
            b"Responder HMAC key"
        return self._nkdf(self._s0, label, self._ctx, 256)

    def _mackey_peer(self) -> bytes:
        label = b"Responder HMAC key" if self.role == "initiator" else \
            b"Initiator HMAC key"
        return self._nkdf(self._s0, label, self._ctx, 256)

    def _verify_confirm(self, payload: bytes) -> None:
        mac, peer_h0 = payload[:8], payload[8:40]
        if not hmac_mod.compare_digest(
                _hmac(self._mackey_peer(), peer_h0)[:8], mac):
            raise ZrtpProtocolError("ZRTP: Confirm MAC mismatch")
        # retroactive checks (RFC 6189 §8.1.1): H0 -> H1 seen in peer
        # DHPart, and H0 keys the DHPart message MAC
        dh = self._peer.get(b"DHPart1 " if self.role == "initiator"
                            else b"DHPart2 ")
        if dh is not None:
            if _sha256(peer_h0) != dh[12:44]:
                raise ZrtpProtocolError(
                    "ZRTP: H0 does not chain to DHPart H1")
            self._check_mac(dh, peer_h0, "DHPart")
        if self._mult:
            # no DHPart revealed intermediate images in mult mode: the
            # Confirm's H0 must chain all the way to the peer Hello's
            # H3, and (responder side) it keys the Commit MAC the DH
            # path verifies via DHPart2
            h1 = _sha256(peer_h0)
            h2 = _sha256(h1)
            if _sha256(h2) != self._peer_hello_h3():
                raise ZrtpProtocolError(
                    "ZRTP: Confirm H0 does not chain to Hello H3")
            commit = self._peer.get(b"Commit  ")
            if commit is not None:     # peer was the mult initiator
                if h2 != commit[12:44]:
                    raise ZrtpProtocolError(
                        "ZRTP: Confirm H0 does not chain to Commit H2")
                self._check_mac(commit, h1, "Commit")

    # -------------------------------------------------------------- export
    def srtp_keys(self):
        """(profile, tx_key, tx_salt, rx_key, rx_salt) — initiator sends
        with the initiator key (RFC 6189 §4.5.3); key length and SRTP
        profile follow the NEGOTIATED cipher/auth suite."""
        if self._s0 is None:
            raise RuntimeError("ZRTP not negotiated")
        suite = self.suite or {"cipher": CIPHER_AES1, "auth": AUTH_HS80}
        bits = CIPHER_KEY_BITS[suite["cipher"]]
        ki = self._nkdf(self._s0, b"Initiator SRTP master key",
                        self._ctx, bits)
        si = self._nkdf(self._s0, b"Initiator SRTP master salt",
                        self._ctx, 112)
        kr = self._nkdf(self._s0, b"Responder SRTP master key",
                        self._ctx, bits)
        sr = self._nkdf(self._s0, b"Responder SRTP master salt",
                        self._ctx, 112)
        profile = PROFILE_BY_SUITE[(suite["cipher"], suite["auth"])]
        if self.role == "initiator":
            return profile, ki, si, kr, sr
        return profile, kr, sr, ki, si
