"""ZRTP — media-path Diffie-Hellman key agreement (RFC 6189).

Rebuilds the reference's `org.jitsi.impl.neomedia.transform.zrtp.
{ZRTPTransformEngine,ZrtpControlImpl}` (which delegate to the zrtp4j
library) from the RFC: the Hello/Commit/DHPart/Confirm state machine,
the H0..H3 hash-image chain with retroactive message-HMAC verification,
ECDH P-256 ("EC25") key agreement, the RFC 6189 §4.4.1.4 s0 / §4.5.1
KDF derivations, Short Authentication String (B32), and SRTP master
key/salt export feeding `SrtpStreamTable` — the same "key provider →
SRTP context" interface SDES and DTLS-SRTP use.

Packet format: ZRTP messages ride RTP-lookalike packets (version 0,
magic cookie 0x5A525450, CRC-32C trailer) multiplexed on the media
port, demuxed by the cookie.  Like the in-memory DTLS endpoint, this is
packet-in/packet-out for the host I/O loop.
"""

from __future__ import annotations

import collections
import hashlib
import hmac as hmac_mod
import os
import struct
from typing import Dict, List, Optional, Tuple

from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives import serialization

from libjitsi_tpu.transform.srtp.policy import SrtpProfile

MAGIC = 0x5A525450  # "ZRTP"
PREAMBLE = 0x505A
VERSION = b"1.10"

HASH_S256 = b"S256"
CIPHER_AES1 = b"AES1"
AUTH_HS80 = b"HS80"
KA_EC25 = b"EC25"
SAS_B32 = b"B32 "

_B32_ALPHABET = "ybndrfg8ejkmcpqxot1uwisza345h769"  # RFC 6189 §5.1.6

# CRC-32C (Castagnoli, reflected poly 0x82F63B78) — RFC 6189 §5 requires
# the RFC 3309 CRC, not zlib's CRC-32/IEEE.
_CRC32C_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for byte in data:
        crc = _CRC32C_TABLE[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac_mod.new(key, msg, hashlib.sha256).digest()


def _kdf(ki: bytes, label: bytes, context: bytes, length_bits: int) -> bytes:
    """RFC 6189 §4.5.1 (NIST SP 800-108 counter-mode, one block)."""
    data = struct.pack("!I", 1) + label + b"\x00" + context + \
        struct.pack("!I", length_bits)
    return _hmac(ki, data)[: length_bits // 8]


def sas_b32(sashash: bytes) -> str:
    """Render the 20-bit short authentication string (RFC 6189 §5.1.6)."""
    bits = int.from_bytes(sashash[:4], "big") >> 12
    return "".join(_B32_ALPHABET[(bits >> s) & 31] for s in (15, 10, 5, 0))


# ---------------------------------------------------------------- packets --

def _wrap(msg: bytes, seq: int, ssrc: int) -> bytes:
    """ZRTP packet: RTP-lookalike header + message + CRC-32 trailer."""
    hdr = struct.pack("!BBH", 0x10, 0, seq & 0xFFFF) + \
        struct.pack("!II", MAGIC, ssrc & 0xFFFFFFFF)
    body = hdr + msg
    return body + struct.pack("!I", crc32c(body))


def is_zrtp(datagram: bytes) -> bool:
    return (len(datagram) >= 12
            and datagram[0] == 0x10
            and datagram[4:8] == struct.pack("!I", MAGIC))


def _unwrap(datagram: bytes) -> Optional[bytes]:
    if not is_zrtp(datagram) or len(datagram) < 16:
        return None
    body, crc = datagram[:-4], struct.unpack("!I", datagram[-4:])[0]
    if crc32c(body) != crc:
        return None
    return body[12:]


def _msg(mtype: bytes, payload: bytes) -> bytes:
    assert len(mtype) == 8
    total_words = (12 + len(payload)) // 4
    return struct.pack("!HH", PREAMBLE, total_words) + mtype + payload


def _parse_msg(msg: bytes) -> Optional[Tuple[bytes, bytes]]:
    if len(msg) < 12 or struct.unpack("!H", msg[:2])[0] != PREAMBLE:
        return None
    return msg[4:12], msg[12:]


# --------------------------------------------------------------- endpoint --

class ZrtpProtocolError(RuntimeError):
    """An authenticity/protocol check failed on a received message.

    Never escapes `feed()` — the offending packet is dropped and the
    failure recorded in `ZrtpEndpoint.alerts` (an exception here would
    hand any off-path forger a DoS on the host I/O loop)."""


class ZrtpEndpoint:
    """One ZRTP association.  Both sides send Hello; the side told
    `initiate()` sends Commit and becomes the initiator.

    API mirrors the DTLS endpoint: `hello_packets()`, `feed(datagram)`,
    `complete`, `srtp_keys()`, plus `sas` for the user-verification
    string (the MITM defense: both users compare the 4 chars).
    """

    def __init__(self, zid: Optional[bytes] = None, ssrc: int = 0):
        self.zid = zid if zid is not None else os.urandom(12)
        self.ssrc = ssrc
        # hash image chain (RFC 6189 §9)
        self._h0 = os.urandom(32)
        self._h1 = _sha256(self._h0)
        self._h2 = _sha256(self._h1)
        self._h3 = _sha256(self._h2)
        self._ec_priv = ec.generate_private_key(ec.SECP256R1())
        self._seq = int.from_bytes(os.urandom(2), "big")
        self.role: Optional[str] = None
        self.complete = False
        self.sas: Optional[str] = None
        self._s0: Optional[bytes] = None
        # dropped-packet security log — bounded: forged packets must not
        # grow host memory (deque evicts oldest)
        self.alerts = collections.deque(maxlen=64)
        self._peer: Dict[bytes, bytes] = {}  # raw peer messages by type
        self._my_hello = self._make_hello()
        self._my_commit: Optional[bytes] = None
        self._my_dhpart: Optional[bytes] = None
        self._peer_pub: Optional[bytes] = None

    # ------------------------------------------------------------ builders
    def _pub_bytes(self) -> bytes:
        return self._ec_priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.UncompressedPoint)[1:]  # 64B x||y

    def _make_hello(self) -> bytes:
        payload = VERSION + b"libjitsi-tpu    "[:16] + self._h3 + self.zid
        # flags + one algorithm of each kind (0x10101011-style counts)
        payload += bytes([0, 1, 1, 1]) + HASH_S256 + CIPHER_AES1 + \
            AUTH_HS80 + KA_EC25 + SAS_B32
        core = _msg(b"Hello   ", payload + b"\x00" * 8)
        mac = _hmac(self._h2, core[:-8])[:8]
        return core[:-8] + mac

    def _make_commit(self) -> bytes:
        dh2 = self._make_dhpart(b"DHPart2 ")
        hvi = _sha256(dh2 + self._peer[b"Hello   "])
        payload = self._h2 + self.zid + HASH_S256 + CIPHER_AES1 + \
            AUTH_HS80 + KA_EC25 + SAS_B32 + hvi
        core = _msg(b"Commit  ", payload + b"\x00" * 8)
        mac = _hmac(self._h1, core[:-8])[:8]
        self._my_dhpart = dh2
        return core[:-8] + mac

    def _make_dhpart(self, mtype: bytes) -> bytes:
        rs = os.urandom(32)  # 4 independent secret-IDs (no cached secrets)
        payload = self._h1 + rs + self._pub_bytes()
        core = _msg(mtype, payload + b"\x00" * 8)
        mac = _hmac(self._h0, core[:-8])[:8]
        return core[:-8] + mac

    def _make_confirm(self, mtype: bytes) -> bytes:
        # simplified confirm: HMAC(mackey, H0||flags) — the encrypted
        # part's semantics (cache expiry, sig) are not modeled
        key = self._mackey_own()
        payload = _hmac(key, self._h0)[:8] + self._h0
        return _msg(mtype, payload)

    # ----------------------------------------------------------- transport
    def _send(self, msg: bytes) -> bytes:
        self._seq += 1
        return _wrap(msg, self._seq, self.ssrc)

    def hello_packets(self) -> List[bytes]:
        return [self._send(self._my_hello)]

    def initiate(self) -> List[bytes]:
        """Become initiator (requires peer Hello already seen).  Idempotent:
        a retry resends the SAME Commit — regenerating it would fork the
        hvi commitment the peer has already pinned.  A side that already
        became responder (peer's Commit won) refuses: flipping roles
        mid-handshake would deadlock both sides."""
        if b"Hello   " not in self._peer:
            raise RuntimeError("peer Hello not yet received")
        if self.role == "responder":
            raise RuntimeError(
                "peer already committed first; this side is responder")
        if self.role == "initiator" and self._my_commit is not None:
            return [self._send(self._my_commit)]
        self.role = "initiator"
        self._my_commit = self._make_commit()
        return [self._send(self._my_commit)]

    @staticmethod
    def _check_mac(msg: bytes, key: bytes, what: str) -> None:
        """Retroactive message-MAC check (RFC 6189 §8.1.1): each message
        carries HMAC(next-revealed-hash-image, message) in its last 8B."""
        if not hmac_mod.compare_digest(_hmac(key, msg[:-8])[:8], msg[-8:]):
            raise ZrtpProtocolError(f"ZRTP: {what} message MAC mismatch "
                                    "(tampered in flight?)")

    def feed(self, datagram: bytes) -> List[bytes]:
        """Process one datagram; returns reply datagrams.  Never raises on
        wire input: malformed, out-of-order, duplicate and wrong-role
        packets are dropped (returns []), and failed authenticity checks
        are dropped with the reason appended to `self.alerts`."""
        msg = _unwrap(datagram)
        if msg is None:
            return []
        parsed = _parse_msg(msg)
        if parsed is None:
            return []
        mtype, payload = parsed
        try:
            return self._process(mtype, payload, msg)
        except ZrtpProtocolError as e:
            self.alerts.append(str(e))
            return []

    def _process(self, mtype: bytes, payload: bytes,
                 msg: bytes) -> List[bytes]:
        out: List[bytes] = []
        if mtype == b"Hello   ":
            # pin the first Hello: its H3/ZID feed the key derivation,
            # so a mid-handshake replacement must not take effect
            if mtype in self._peer:
                if self._peer[mtype] != msg:
                    return []
            else:
                self._peer[mtype] = msg
            out.append(self._send(_msg(b"HelloACK", b"")))
        elif mtype == b"Commit  ":
            if b"Hello   " not in self._peer:
                return []
            if self.role == "initiator":
                # Commit contention (RFC 6189 §4.2): both sides committed.
                # The LOWER hvi backs down to responder and processes the
                # peer's Commit; the higher one drops the peer's.
                hvi_off = 12 + 32 + 12 + 20
                ours = self._my_commit[hvi_off:hvi_off + 32]
                theirs = msg[hvi_off:hvi_off + 32]
                if ours >= theirs:
                    return []               # we win; peer backs down
                self.role = None            # back down, re-process below
                self._my_commit = None
                self._my_dhpart = None
            if mtype in self._peer:
                if self._peer[mtype] != msg or self._my_dhpart is None:
                    return []
                # duplicate Commit: resend the SAME DHPart1 (regenerating
                # would fork total_hash between the two sides)
                return [self._send(self._my_dhpart)]
            peer_h2 = payload[:32]
            if _sha256(peer_h2) != self._peer_hello_h3():
                raise ZrtpProtocolError("ZRTP: Commit H2 does not chain to H3")
            # H2 now known -> verify the peer Hello's MAC retroactively
            self._check_mac(self._peer[b"Hello   "], peer_h2, "Hello")
            self._peer[mtype] = msg
            self.role = "responder"
            self._my_dhpart = self._make_dhpart(b"DHPart1 ")
            out.append(self._send(self._my_dhpart))
        elif mtype == b"DHPart1 ":
            if self.role != "initiator" or self._my_dhpart is None:
                return []
            if mtype in self._peer:
                if self._peer[mtype] != msg:
                    return []
                return [self._send(self._my_dhpart)]
            # responder never sends Commit; its H1 chains straight to the
            # Hello H3 and reveals H2 = sha256(H1) for the Hello MAC
            peer_h1 = payload[:32]
            peer_h2 = _sha256(peer_h1)
            if _sha256(peer_h2) != self._peer_hello_h3():
                raise ZrtpProtocolError("ZRTP: DHPart1 H1 does not chain to H3")
            self._check_mac(self._peer[b"Hello   "], peer_h2, "Hello")
            pub = payload[32 + 32:32 + 32 + 64]
            self._parse_point(pub)       # reject junk at receive time
            self._peer[mtype] = msg
            self._peer_pub = pub
            out.append(self._send(self._my_dhpart))
        elif mtype == b"DHPart2 ":
            if self.role != "responder" or b"Commit  " not in self._peer:
                return []
            if mtype in self._peer:
                if self._peer[mtype] != msg or self._s0 is None:
                    return []
                return [self._send(self._make_confirm(b"Confirm1"))]
            # verify commitment: hvi in Commit == hash(DHPart2||our Hello)
            commit = self._peer[b"Commit  "]
            hvi = commit[12 + 32 + 12 + 20:12 + 32 + 12 + 20 + 32]
            if _sha256(msg + self._my_hello) != hvi:
                raise ZrtpProtocolError("ZRTP: DHPart2 does not match hvi "
                                        "commitment (possible MITM)")
            # H1 revealed -> chains to Commit H2, and keys the Commit MAC
            peer_h1 = payload[:32]
            if _sha256(peer_h1) != commit[12:44]:
                raise ZrtpProtocolError("ZRTP: DHPart2 H1 does not chain to H2")
            self._check_mac(commit, peer_h1, "Commit")
            pub = payload[32 + 32:32 + 32 + 64]
            self._parse_point(pub)
            self._peer[mtype] = msg
            self._peer_pub = pub
            self._derive()
            out.append(self._send(self._make_confirm(b"Confirm1")))
        elif mtype == b"Confirm1":
            if self.role != "initiator" or b"DHPart1 " not in self._peer:
                return []
            self._derive()
            self._verify_confirm(payload)
            out.append(self._send(self._make_confirm(b"Confirm2")))
            self.complete = True
        elif mtype == b"Confirm2":
            if self.role != "responder" or self._s0 is None:
                return []
            self._verify_confirm(payload)
            out.append(self._send(_msg(b"Conf2ACK", b"")))
            self.complete = True
        return out

    # ---------------------------------------------------------- key sched
    def _peer_hello_h3(self) -> bytes:
        hello = self._peer[b"Hello   "]
        return hello[12 + 4 + 16:12 + 4 + 16 + 32]

    @staticmethod
    def _parse_point(raw: bytes) -> ec.EllipticCurvePublicKey:
        """Validate a peer's 64-byte x||y P-256 point.  Raises
        ZrtpProtocolError (dropped+alerted by feed) on junk — an invalid
        point must not escape as ValueError into the I/O loop, nor reach
        the ECDH as an invalid-curve input."""
        if len(raw) != 64:
            raise ZrtpProtocolError("ZRTP: DHPart public value truncated")
        try:
            return ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256R1(), b"\x04" + raw)
        except ValueError as e:
            raise ZrtpProtocolError(f"ZRTP: invalid EC point ({e})") from e

    def _dh_result(self) -> bytes:
        return self._ec_priv.exchange(ec.ECDH(),
                                      self._parse_point(self._peer_pub))

    def _derive(self) -> None:
        if self._s0 is not None:
            return
        if self.role == "initiator":
            zidi, zidr = self.zid, self._peer_zid()
            hello_r = self._peer[b"Hello   "]
            commit = self._my_commit
            dh1 = self._peer[b"DHPart1 "]
            dh2 = self._my_dhpart
        else:
            zidi, zidr = self._peer_zid(), self.zid
            hello_r = self._my_hello
            commit = self._peer[b"Commit  "]
            dh1 = self._my_dhpart
            dh2 = self._peer[b"DHPart2 "]
        total_hash = _sha256(hello_r + commit + dh1 + dh2)
        dhr = self._dh_result()
        # RFC 6189 §4.4.1.4 (no cached secrets: s1=s2=s3 null)
        null = struct.pack("!I", 0)
        self._s0 = _sha256(struct.pack("!I", 1) + dhr + b"ZRTP-HMAC-KDF" +
                           zidi + zidr + total_hash + null + null + null)
        self._ctx = zidi + zidr + total_hash
        self.sas = sas_b32(_kdf(self._s0, b"SAS", self._ctx, 256))

    def _peer_zid(self) -> bytes:
        hello = self._peer[b"Hello   "]
        return hello[12 + 4 + 16 + 32:12 + 4 + 16 + 32 + 12]

    def _mackey_own(self) -> bytes:
        label = b"Initiator HMAC key" if self.role == "initiator" else \
            b"Responder HMAC key"
        return _kdf(self._s0, label, self._ctx, 256)

    def _mackey_peer(self) -> bytes:
        label = b"Responder HMAC key" if self.role == "initiator" else \
            b"Initiator HMAC key"
        return _kdf(self._s0, label, self._ctx, 256)

    def _verify_confirm(self, payload: bytes) -> None:
        mac, peer_h0 = payload[:8], payload[8:40]
        if not hmac_mod.compare_digest(
                _hmac(self._mackey_peer(), peer_h0)[:8], mac):
            raise ZrtpProtocolError("ZRTP: Confirm MAC mismatch")
        # retroactive checks: H0 -> H1 seen in peer DHPart, and H0 keys
        # the DHPart message MAC (RFC 6189 §8.1.1)
        dh = self._peer.get(b"DHPart1 " if self.role == "initiator"
                            else b"DHPart2 ")
        if dh is not None:
            if _sha256(peer_h0) != dh[12:44]:
                raise ZrtpProtocolError(
                    "ZRTP: H0 does not chain to DHPart H1")
            self._check_mac(dh, peer_h0, "DHPart")

    # -------------------------------------------------------------- export
    def srtp_keys(self):
        """(profile, tx_key, tx_salt, rx_key, rx_salt) — initiator sends
        with the initiator key (RFC 6189 §4.5.3)."""
        if self._s0 is None:
            raise RuntimeError("ZRTP not negotiated")
        ki = _kdf(self._s0, b"Initiator SRTP master key", self._ctx, 128)
        si = _kdf(self._s0, b"Initiator SRTP master salt", self._ctx, 112)
        kr = _kdf(self._s0, b"Responder SRTP master key", self._ctx, 128)
        sr = _kdf(self._s0, b"Responder SRTP master salt", self._ctx, 112)
        profile = SrtpProfile.AES_CM_128_HMAC_SHA1_80
        if self.role == "initiator":
            return profile, ki, si, kr, sr
        return profile, kr, sr, ki, si
