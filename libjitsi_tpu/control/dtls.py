"""DTLS-SRTP control plane (RFC 5764), host-side.

Rebuilds the reference's `org.jitsi.impl.neomedia.transform.dtls.
{DtlsControlImpl,DtlsPacketTransformer,TlsClientImpl,TlsServerImpl,
DatagramTransportImpl}` (BouncyCastle-based) on OpenSSL's DTLS via the
`cryptography` package's FFI bindings: memory-BIO packet-in/packet-out
(no sockets — the host I/O loop feeds datagrams, exactly like the
reference's DatagramTransportImpl), the `use_srtp` extension for profile
negotiation, X.509 fingerprint verification against signaling, and
RFC 5764 §4.2 "EXTRACTOR-dtls_srtp" keying-material export feeding the
SRTP tables.  Handshake is the cold path and stays off-TPU (SURVEY
§2.2).
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.bindings.openssl.binding import Binding
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID
import datetime

from libjitsi_tpu.transform.srtp.policy import SrtpProfile

_b = Binding()
_lib, _ffi = _b.lib, _b.ffi

# RFC 5764 §4.1.2 / OpenSSL srtp.h profile registry
_PROFILE_BY_ID = {
    0x0001: SrtpProfile.AES_CM_128_HMAC_SHA1_80,
    0x0002: SrtpProfile.AES_CM_128_HMAC_SHA1_32,
    0x0007: SrtpProfile.AEAD_AES_128_GCM,
}
_OPENSSL_NAME = {
    SrtpProfile.AES_CM_128_HMAC_SHA1_80: "SRTP_AES128_CM_SHA1_80",
    SrtpProfile.AES_CM_128_HMAC_SHA1_32: "SRTP_AES128_CM_SHA1_32",
    SrtpProfile.AEAD_AES_128_GCM: "SRTP_AEAD_AES_128_GCM",
}


def is_dtls(datagram: bytes) -> bool:
    """RFC 5764 §5.1.2 demux: first byte in [20..63] = DTLS record."""
    return len(datagram) > 0 and 20 <= datagram[0] <= 63


def generate_certificate(cn: str = "libjitsi-tpu"
                         ) -> Tuple[bytes, bytes, str]:
    """Self-signed ECDSA P-256 cert: (cert_der, key_der, sha256 fp).

    Reference: DtlsControlImpl generates a per-instance self-signed
    certificate whose fingerprint goes into signaling.
    """
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=365))
            .sign(key, hashes.SHA256()))
    cert_der = cert.public_bytes(serialization.Encoding.DER)
    key_der = key.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    return cert_der, key_der, fingerprint(cert_der)


def fingerprint(cert_der: bytes) -> str:
    """SDP-style uppercase colon-separated SHA-256 fingerprint."""
    h = hashlib.sha256(cert_der).hexdigest().upper()
    return ":".join(h[i:i + 2] for i in range(0, len(h), 2))


class DtlsSrtpEndpoint:
    """One DTLS-SRTP association (client or server role).

    Packet-level API:
      out = ep.handshake_packets()      # datagrams to send now
      out = ep.feed(incoming_datagram)  # returns response datagrams
      ep.complete                       # handshake done?
      ep.srtp_keys()                    # (profile, tx_key, tx_salt,
                                        #  rx_key, rx_salt) per role
    """

    EXTRACTOR = b"EXTRACTOR-dtls_srtp"

    def __init__(self, role: str,
                 profiles: Optional[List[SrtpProfile]] = None,
                 cert_der: Optional[bytes] = None,
                 key_der: Optional[bytes] = None,
                 remote_fingerprint: Optional[str] = None,
                 mtu: int = 1200):
        if role not in ("client", "server"):
            raise ValueError("role must be client or server")
        self.role = role
        self.profiles = profiles or [
            SrtpProfile.AES_CM_128_HMAC_SHA1_80,
            SrtpProfile.AEAD_AES_128_GCM,
        ]
        if cert_der is None:
            cert_der, key_der, _ = generate_certificate()
        self.cert_der = cert_der
        self.local_fingerprint = fingerprint(cert_der)
        self.remote_fingerprint = remote_fingerprint
        self.complete = False
        self.peer_cert_der: Optional[bytes] = None

        ctx = _lib.SSL_CTX_new(_lib.DTLS_method())
        if ctx == _ffi.NULL:
            raise RuntimeError("SSL_CTX_new failed")
        self._ctx = _ffi.gc(ctx, _lib.SSL_CTX_free)

        # install cert + key from DER (via memory BIOs — the bindings
        # expose only the *_bio d2i variants)
        cbio = _lib.BIO_new_mem_buf(cert_der, len(cert_der))
        x509p = _lib.d2i_X509_bio(cbio, _ffi.NULL)
        _lib.BIO_free(cbio)
        if x509p == _ffi.NULL:
            raise RuntimeError("d2i_X509_bio failed")
        _lib.SSL_CTX_use_certificate(self._ctx, x509p)
        kbio = _lib.BIO_new_mem_buf(key_der, len(key_der))
        pkey = _lib.d2i_PrivateKey_bio(kbio, _ffi.NULL)
        _lib.BIO_free(kbio)
        if pkey == _ffi.NULL:
            raise RuntimeError("d2i_PrivateKey_bio failed")
        _lib.SSL_CTX_use_PrivateKey(self._ctx, pkey)

        # use_srtp extension (0 == success)
        names = ":".join(_OPENSSL_NAME[p] for p in self.profiles)
        if _lib.SSL_CTX_set_tlsext_use_srtp(self._ctx,
                                            names.encode()) != 0:
            raise RuntimeError("SSL_CTX_set_tlsext_use_srtp failed")

        # request the peer's cert; actual trust = fingerprint vs signaling
        self._verify_cb = _ffi.callback(
            "int(int, X509_STORE_CTX *)", lambda ok, store: 1)
        _lib.SSL_CTX_set_verify(
            self._ctx,
            _lib.SSL_VERIFY_PEER | (
                _lib.SSL_VERIFY_FAIL_IF_NO_PEER_CERT
                if role == "server" else 0),
            self._verify_cb)

        ssl = _lib.SSL_new(self._ctx)
        self._ssl = _ffi.gc(ssl, _lib.SSL_free)
        self._rbio = _lib.BIO_new(_lib.BIO_s_mem())
        self._wbio = _lib.BIO_new(_lib.BIO_s_mem())
        _lib.SSL_set_bio(self._ssl, self._rbio, self._wbio)  # SSL owns BIOs
        if role == "client":
            _lib.SSL_set_connect_state(self._ssl)
        else:
            _lib.SSL_set_accept_state(self._ssl)

    # ------------------------------------------------------------- pumps
    def _drain_out(self) -> List[bytes]:
        out = []
        buf = _ffi.new("char[]", 4096)
        while True:
            n = _lib.BIO_read(self._wbio, buf, len(buf))
            if n <= 0:
                break
            out.append(_ffi.buffer(buf, n)[:])
        return out

    def _pump(self) -> None:
        rc = _lib.SSL_do_handshake(self._ssl)
        if rc == 1 and not self.complete:
            self._on_complete()

    def handshake_packets(self) -> List[bytes]:
        """Kick/continue the handshake; returns datagrams to transmit."""
        if not self.complete:
            self._pump()
        return self._drain_out()

    def feed(self, datagram: bytes) -> List[bytes]:
        """Process one incoming DTLS datagram; returns responses."""
        buf = _ffi.new("char[]", datagram)
        _lib.BIO_write(self._rbio, buf, len(datagram))
        if not self.complete:
            self._pump()
        return self._drain_out()

    # ---------------------------------------------------------- completion
    def _on_complete(self) -> None:
        cert = _lib.SSL_get_peer_certificate(self._ssl)
        if cert != _ffi.NULL:
            bio = _lib.BIO_new(_lib.BIO_s_mem())
            _lib.i2d_X509_bio(bio, cert)
            buf = _ffi.new("char[]", 8192)
            n = _lib.BIO_read(bio, buf, len(buf))
            self.peer_cert_der = _ffi.buffer(buf, n)[:] if n > 0 else b""
            _lib.BIO_free(bio)
            _lib.X509_free(cert)
        if self.remote_fingerprint is not None:
            got = fingerprint(self.peer_cert_der or b"")
            if got != self.remote_fingerprint.upper():
                raise RuntimeError(
                    f"DTLS fingerprint mismatch: {got} != "
                    f"{self.remote_fingerprint} (possible MITM)")
        self.complete = True

    @property
    def selected_profile(self) -> SrtpProfile:
        prof = _lib.SSL_get_selected_srtp_profile(self._ssl)
        if prof == _ffi.NULL:
            raise RuntimeError("no SRTP profile negotiated")
        return _PROFILE_BY_ID[prof.id]

    def srtp_keys(self):
        """RFC 5764 §4.2 key export, role-resolved.

        Returns (profile, tx_key, tx_salt, rx_key, rx_salt): the client
        sends with client_write keys, the server with server_write.
        """
        if not self.complete:
            raise RuntimeError("handshake not complete")
        profile = self.selected_profile
        p = profile.policy
        kl, sl = p.enc_key_len, p.salt_len
        total = 2 * (kl + sl)
        out = _ffi.new("unsigned char[]", total)
        rc = _lib.SSL_export_keying_material(
            self._ssl, out, total, self.EXTRACTOR, len(self.EXTRACTOR),
            _ffi.NULL, 0, 0)
        if rc != 1:
            raise RuntimeError("SSL_export_keying_material failed")
        blob = _ffi.buffer(out, total)[:]
        ck, sk = blob[:kl], blob[kl:2 * kl]
        cs, ss = blob[2 * kl:2 * kl + sl], blob[2 * kl + sl:]
        if self.role == "client":
            return profile, ck, cs, sk, ss
        return profile, sk, ss, ck, cs
