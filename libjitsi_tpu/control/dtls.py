"""DTLS-SRTP control plane (RFC 5764), host-side.

Rebuilds the reference's `org.jitsi.impl.neomedia.transform.dtls.
{DtlsControlImpl,DtlsPacketTransformer,TlsClientImpl,TlsServerImpl,
DatagramTransportImpl}` (BouncyCastle-based) on OpenSSL's DTLS via the
`cryptography` package's FFI bindings: memory-BIO packet-in/packet-out
(no sockets — the host I/O loop feeds datagrams, exactly like the
reference's DatagramTransportImpl), the `use_srtp` extension for profile
negotiation, X.509 fingerprint verification against signaling, and
RFC 5764 §4.2 "EXTRACTOR-dtls_srtp" keying-material export feeding the
SRTP tables.  Handshake is the cold path and stays off-TPU (SURVEY
§2.2).
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

try:
    from cryptography import x509
    from cryptography.hazmat.bindings.openssl.binding import Binding
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:
    # gated dependency: the module must import without `cryptography`
    # (demux + SDES-keyed bridges need none of it); DTLS handshakes
    # raise at use time with a clear message instead
    HAVE_CRYPTOGRAPHY = False
import datetime

from libjitsi_tpu.transform.srtp.policy import SrtpProfile
from libjitsi_tpu.utils.logging import get_logger

_dtls_log = get_logger("control.dtls")

_lib = _ffi = None


def _openssl():
    """Bind the OpenSSL FFI on first DTLS use (lazy so importing this
    module — which every bridge does for `is_dtls` — never requires the
    `cryptography` package to be installed)."""
    global _lib, _ffi
    if _lib is None:
        if not HAVE_CRYPTOGRAPHY:
            raise RuntimeError(
                "DTLS-SRTP requires the 'cryptography' package; "
                "SDES keying (add_participant) works without it")
        b = Binding()
        _lib, _ffi = b.lib, b.ffi
    return _lib, _ffi

# RFC 5764 §4.1.2 / OpenSSL srtp.h profile registry
_PROFILE_BY_ID = {
    0x0001: SrtpProfile.AES_CM_128_HMAC_SHA1_80,
    0x0002: SrtpProfile.AES_CM_128_HMAC_SHA1_32,
    0x0007: SrtpProfile.AEAD_AES_128_GCM,
}
_OPENSSL_NAME = {
    SrtpProfile.AES_CM_128_HMAC_SHA1_80: "SRTP_AES128_CM_SHA1_80",
    SrtpProfile.AES_CM_128_HMAC_SHA1_32: "SRTP_AES128_CM_SHA1_32",
    SrtpProfile.AEAD_AES_128_GCM: "SRTP_AEAD_AES_128_GCM",
}


def is_dtls(datagram: bytes) -> bool:
    """RFC 5764 §5.1.2 demux: first byte in [20..63] = DTLS record."""
    return len(datagram) > 0 and 20 <= datagram[0] <= 63


def generate_certificate(cn: str = "libjitsi-tpu"
                         ) -> Tuple[bytes, bytes, str]:
    """Self-signed ECDSA P-256 cert: (cert_der, key_der, sha256 fp).

    Reference: DtlsControlImpl generates a per-instance self-signed
    certificate whose fingerprint goes into signaling.
    """
    _openssl()
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(days=1))
            .not_valid_after(now + datetime.timedelta(days=365))
            .sign(key, hashes.SHA256()))
    cert_der = cert.public_bytes(serialization.Encoding.DER)
    key_der = key.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    return cert_der, key_der, fingerprint(cert_der)


def fingerprint(cert_der: bytes) -> str:
    """SDP-style uppercase colon-separated SHA-256 fingerprint."""
    h = hashlib.sha256(cert_der).hexdigest().upper()
    return ":".join(h[i:i + 2] for i in range(0, len(h), 2))


class DtlsSrtpEndpoint:
    """One DTLS-SRTP association (client or server role).

    Packet-level API:
      out = ep.handshake_packets()      # datagrams to send now
      out = ep.feed(incoming_datagram)  # returns response datagrams
      ep.complete                       # handshake done?
      ep.srtp_keys()                    # (profile, tx_key, tx_salt,
                                        #  rx_key, rx_salt) per role
    """

    EXTRACTOR = b"EXTRACTOR-dtls_srtp"

    def __init__(self, role: str,
                 profiles: Optional[List[SrtpProfile]] = None,
                 cert_der: Optional[bytes] = None,
                 key_der: Optional[bytes] = None,
                 remote_fingerprint: Optional[str] = None,
                 mtu: int = 1200,
                 cookie_exchange: bool = False):
        if role not in ("client", "server"):
            raise ValueError("role must be client or server")
        _openssl()
        self.role = role
        self.profiles = profiles or [
            SrtpProfile.AES_CM_128_HMAC_SHA1_80,
            SrtpProfile.AEAD_AES_128_GCM,
        ]
        if cert_der is None:
            cert_der, key_der, _ = generate_certificate()
        self.cert_der = cert_der
        self.local_fingerprint = fingerprint(cert_der)
        self.remote_fingerprint = remote_fingerprint
        self.complete = False
        self.peer_cert_der: Optional[bytes] = None

        ctx = _lib.SSL_CTX_new(_lib.DTLS_method())
        if ctx == _ffi.NULL:
            raise RuntimeError("SSL_CTX_new failed")
        self._ctx = _ffi.gc(ctx, _lib.SSL_CTX_free)

        # install cert + key from DER (via memory BIOs — the bindings
        # expose only the *_bio d2i variants)
        cbio = _lib.BIO_new_mem_buf(cert_der, len(cert_der))
        x509p = _lib.d2i_X509_bio(cbio, _ffi.NULL)
        _lib.BIO_free(cbio)
        if x509p == _ffi.NULL:
            raise RuntimeError("d2i_X509_bio failed")
        _lib.SSL_CTX_use_certificate(self._ctx, x509p)
        kbio = _lib.BIO_new_mem_buf(key_der, len(key_der))
        pkey = _lib.d2i_PrivateKey_bio(kbio, _ffi.NULL)
        _lib.BIO_free(kbio)
        if pkey == _ffi.NULL:
            raise RuntimeError("d2i_PrivateKey_bio failed")
        _lib.SSL_CTX_use_PrivateKey(self._ctx, pkey)

        # use_srtp extension (0 == success)
        names = ":".join(_OPENSSL_NAME[p] for p in self.profiles)
        if _lib.SSL_CTX_set_tlsext_use_srtp(self._ctx,
                                            names.encode()) != 0:
            raise RuntimeError("SSL_CTX_set_tlsext_use_srtp failed")

        # request the peer's cert; actual trust = fingerprint vs signaling
        self._verify_cb = _ffi.callback(
            "int(int, X509_STORE_CTX *)", lambda ok, store: 1)
        _lib.SSL_CTX_set_verify(
            self._ctx,
            _lib.SSL_VERIFY_PEER | (
                _lib.SSL_VERIFY_FAIL_IF_NO_PEER_CERT
                if role == "server" else 0),
            self._verify_cb)

        # optional RFC 6347 §4.2.1 cookie exchange (HelloVerifyRequest):
        # a spoofed-source ClientHello costs the server no association
        # state until the cookie round-trips.  Cookie = HMAC-free random
        # per-endpoint secret (no peer address exists on a memory BIO;
        # the bridge's one-socket model ties the exchange to the 5-tuple
        # at the io layer).  Reference behavior: BouncyCastle's
        # DTLSVerifier under DtlsPacketTransformer.
        self._cookie_cbs = None
        if role == "server" and cookie_exchange:
            secret = os.urandom(16)

            @_ffi.callback("int(SSL *, unsigned char *, unsigned int *)")
            def _gen(ssl_p, cookie, clen):
                _ffi.buffer(cookie, 16)[:] = secret
                clen[0] = 16
                return 1

            @_ffi.callback(
                "int(SSL *, const unsigned char *, unsigned int)")
            def _ver(ssl_p, cookie, clen):
                return 1 if _ffi.buffer(cookie, clen)[:] == secret else 0

            self._cookie_cbs = (_gen, _ver)      # keep cffi handles alive
            _lib.SSL_CTX_set_cookie_generate_cb(self._ctx, _gen)
            _lib.SSL_CTX_set_cookie_verify_cb(self._ctx, _ver)

        ssl = _lib.SSL_new(self._ctx)
        self._ssl = _ffi.gc(ssl, _lib.SSL_free)
        self._rbio = _lib.BIO_new(_lib.BIO_s_mem())
        self._wbio = _lib.BIO_new(_lib.BIO_s_mem())
        _lib.SSL_set_bio(self._ssl, self._rbio, self._wbio)  # SSL owns BIOs
        if role == "server" and cookie_exchange:
            _lib.SSL_set_options(self._ssl, 0x00002000)  # OP_COOKIE_EXCHANGE
        if role == "client":
            _lib.SSL_set_connect_state(self._ssl)
        else:
            _lib.SSL_set_accept_state(self._ssl)
        self.retransmits = 0
        # flips once the peer has demonstrably advanced the handshake
        # past the stateless phase (see feed); used by the association
        # table to decide whether an address binding may be superseded
        self.progressed = False
        self._out_bytes = 0

    # ------------------------------------------------------------- pumps
    def _drain_out(self) -> List[bytes]:
        out = []
        buf = _ffi.new("char[]", 4096)
        while True:
            n = _lib.BIO_read(self._wbio, buf, len(buf))
            if n <= 0:
                break
            out.append(_ffi.buffer(buf, n)[:])
        return out

    def _pump(self) -> None:
        rc = _lib.SSL_do_handshake(self._ssl)
        if rc == 1 and not self.complete:
            self._on_complete()

    def handshake_packets(self) -> List[bytes]:
        """Kick/continue the handshake; returns datagrams to transmit."""
        if not self.complete:
            self._pump()
        return self._drain_out()

    def feed(self, datagram: bytes) -> List[bytes]:
        """Process one incoming DTLS datagram; returns responses."""
        buf = _ffi.new("char[]", datagram)
        _lib.BIO_write(self._rbio, buf, len(datagram))
        if not self.complete:
            self._pump()
        out = self._drain_out()
        # a HelloVerifyRequest is one tiny record; the ServerHello
        # flight (certificate etc.) is far larger.  Crossing that line
        # means the peer round-tripped the cookie (or no cookies are in
        # use) and actually holds its source address.
        self._out_bytes += sum(len(d) for d in out)
        if self.complete or self._out_bytes > 300:
            self.progressed = True
        return out

    def tick(self) -> List[bytes]:
        """Drive the RFC 6347 retransmission timer; call periodically
        (e.g. from the media loop tick).  OpenSSL tracks the flight
        timer internally (1 s initial, doubling); when it has expired
        this retransmits the last flight and returns the datagrams —
        without it, one lost handshake datagram deadlocks the
        association.  Reference: BouncyCastle's DTLSReliableHandshake
        under DtlsPacketTransformer.
        """
        if self.complete:
            return []
        rc = _lib.DTLSv1_handle_timeout(self._ssl)
        if rc > 0:
            self.retransmits += 1
        return self._drain_out()

    # ---------------------------------------------------------- completion
    def _on_complete(self) -> None:
        cert = _lib.SSL_get_peer_certificate(self._ssl)
        if cert != _ffi.NULL:
            bio = _lib.BIO_new(_lib.BIO_s_mem())
            _lib.i2d_X509_bio(bio, cert)
            buf = _ffi.new("char[]", 8192)
            n = _lib.BIO_read(bio, buf, len(buf))
            self.peer_cert_der = _ffi.buffer(buf, n)[:] if n > 0 else b""
            _lib.BIO_free(bio)
            _lib.X509_free(cert)
        if self.remote_fingerprint is not None:
            got = fingerprint(self.peer_cert_der or b"")
            if got != self.remote_fingerprint.upper():
                raise RuntimeError(
                    f"DTLS fingerprint mismatch: {got} != "
                    f"{self.remote_fingerprint} (possible MITM)")
        self.complete = True

    @property
    def selected_profile(self) -> SrtpProfile:
        prof = _lib.SSL_get_selected_srtp_profile(self._ssl)
        if prof == _ffi.NULL:
            raise RuntimeError("no SRTP profile negotiated")
        return _PROFILE_BY_ID[prof.id]

    def srtp_keys(self):
        """RFC 5764 §4.2 key export, role-resolved.

        Returns (profile, tx_key, tx_salt, rx_key, rx_salt): the client
        sends with client_write keys, the server with server_write.
        """
        if not self.complete:
            raise RuntimeError("handshake not complete")
        profile = self.selected_profile
        p = profile.policy
        kl, sl = p.enc_key_len, p.salt_len
        total = 2 * (kl + sl)
        out = _ffi.new("unsigned char[]", total)
        rc = _lib.SSL_export_keying_material(
            self._ssl, out, total, self.EXTRACTOR, len(self.EXTRACTOR),
            _ffi.NULL, 0, 0)
        if rc != 1:
            raise RuntimeError("SSL_export_keying_material failed")
        blob = _ffi.buffer(out, total)[:]
        ck, sk = blob[:kl], blob[kl:2 * kl]
        cs, ss = blob[2 * kl:2 * kl + sl], blob[2 * kl + sl:]
        if self.role == "client":
            return profile, ck, cs, sk, ss
        return profile, sk, ss, ck, cs


class StubDtlsEndpoint:
    """Dependency-free stand-in for `DtlsSrtpEndpoint` with the same
    wire surface: `handshake_packets` / `feed` / `tick` / `complete` /
    `progressed` / `srtp_keys` / `selected_profile`.

    NOT DTLS and NOT secure — keys are a public hash of the two hello
    randoms.  It exists so the association table, the off-tick
    handshake plane and the reconnect-storm chaos soak can exercise
    real datagram flows (cookie round-trips, flight retransmission,
    address claiming/supersede, key landing) in environments without
    the `cryptography` package, where `DtlsSrtpEndpoint` raises at
    construction.  Every record's first byte sits in the RFC 5764
    demux range [20, 63] so `is_dtls` routing is identical.

    Handshake shape (mirrors the real flights' roles):
      hello   C->S  small; carries client random + offered profiles
      verify  S->C  small; cookie challenge (cookie_exchange only) —
                    like a HelloVerifyRequest it never flips
                    `progressed`, so spoofed-source hellos still lose
                    the supersede race in `DtlsAssociationTable._claim`
      accept  S->C  LARGE (padded cert: crosses the `progressed` line
                    exactly like a real ServerHello+Certificate flight)
      finish  C->S  carries the client cert for fingerprint pinning
      done    S->C  completes the client side
    """

    _HELLO, _VERIFY, _ACCEPT, _FINISH, _DONE = 58, 59, 60, 61, 62
    FLIGHT_TIMEOUT_S = 0.25        # initial retransmission timer
    #: stable 1-byte wire ids (enum declaration order)
    _PROFILE_ID = {p: i for i, p in enumerate(SrtpProfile)}

    def __init__(self, role: str,
                 profiles: Optional[List[SrtpProfile]] = None,
                 cert_der: Optional[bytes] = None,
                 key_der: Optional[bytes] = None,
                 remote_fingerprint: Optional[str] = None,
                 mtu: int = 1200,
                 cookie_exchange: bool = False):
        if role not in ("client", "server"):
            raise ValueError("role must be client or server")
        self.role = role
        self.profiles = profiles or [
            SrtpProfile.AES_CM_128_HMAC_SHA1_80,
            SrtpProfile.AEAD_AES_128_GCM,
        ]
        self._rand = os.urandom(16)
        self.cert_der = cert_der or (b"stub-cert:" + self._rand)
        self.local_fingerprint = fingerprint(self.cert_der)
        self.remote_fingerprint = remote_fingerprint
        self.peer_cert_der: Optional[bytes] = None
        self.complete = False
        self.progressed = False
        self.retransmits = 0
        self.cookie_exchange = bool(cookie_exchange)
        self._cookie = os.urandom(8) if role == "server" else b"\x00" * 8
        self._peer_rand: Optional[bytes] = None
        self._profile: Optional[SrtpProfile] = None
        self._flight: List[bytes] = []
        self._flight_t = 0.0
        self._timeout = self.FLIGHT_TIMEOUT_S
        self._out_bytes = 0

    # ------------------------------------------------------------ records
    def _hello(self) -> bytes:
        ids = bytes(self._PROFILE_ID[p] for p in self.profiles)
        return (bytes([self._HELLO]) + self._rand + self._cookie
                + bytes([len(ids)]) + ids)

    def _accept(self) -> bytes:
        cert = self.cert_der
        body = (bytes([self._ACCEPT]) + self._rand
                + bytes([self._PROFILE_ID[self._profile]])
                + len(cert).to_bytes(2, "big") + cert)
        return body + b"\x00" * max(0, 400 - len(body))  # cert-flight size

    def _set_flight(self, datagrams: List[bytes]) -> List[bytes]:
        self._flight = list(datagrams)
        self._flight_t = time.monotonic()
        self._timeout = self.FLIGHT_TIMEOUT_S
        return self._note_out(list(datagrams))

    def _note_out(self, out: List[bytes]) -> List[bytes]:
        self._out_bytes += sum(len(d) for d in out)
        if self.complete or self._out_bytes > 300:
            self.progressed = True
        return out

    def _check_fingerprint(self, cert: bytes) -> None:
        self.peer_cert_der = cert
        if self.remote_fingerprint is not None:
            got = fingerprint(cert)
            if got != self.remote_fingerprint.upper():
                raise RuntimeError(
                    f"DTLS fingerprint mismatch: {got} != "
                    f"{self.remote_fingerprint} (possible MITM)")

    # -------------------------------------------------------------- pumps
    def handshake_packets(self) -> List[bytes]:
        if self.complete:
            return []
        if self.role == "client" and not self._flight:
            return self._set_flight([self._hello()])
        return self._note_out(list(self._flight))

    def feed(self, datagram: bytes) -> List[bytes]:
        if not datagram:
            return []
        kind = datagram[0]
        if self.role == "server":
            if kind == self._HELLO:
                rand, cookie = datagram[1:17], datagram[17:25]
                if self.cookie_exchange and cookie != self._cookie:
                    # stateless challenge: tiny, never "progresses"
                    return self._note_out(
                        [bytes([self._VERIFY]) + self._cookie])
                n = datagram[25]
                offered = set(datagram[26:26 + n])
                self._peer_rand = rand
                self._profile = next(
                    (p for p in self.profiles
                     if self._PROFILE_ID[p] in offered),
                    self.profiles[0])
                return self._set_flight([self._accept()])
            if kind == self._FINISH:
                clen = int.from_bytes(datagram[1:3], "big")
                self._check_fingerprint(datagram[3:3 + clen])
                self.complete = True
                self.progressed = True
                self._flight = []
                return self._note_out([bytes([self._DONE])])
            return []
        # client
        if kind == self._VERIFY:
            self._cookie = datagram[1:9]
            return self._set_flight([self._hello()])
        if kind == self._ACCEPT:
            self._peer_rand = datagram[1:17]
            pid = datagram[17]
            self._profile = next(
                (p for p in self.profiles
                 if self._PROFILE_ID[p] == pid), self.profiles[0])
            clen = int.from_bytes(datagram[18:20], "big")
            self._check_fingerprint(datagram[20:20 + clen])
            cert = self.cert_der
            return self._set_flight(
                [bytes([self._FINISH]) + len(cert).to_bytes(2, "big")
                 + cert])
        if kind == self._DONE:
            self.complete = True
            self.progressed = True
            self._flight = []
        return []

    def tick(self) -> List[bytes]:
        if self.complete or not self._flight:
            return []
        now = time.monotonic()
        if now - self._flight_t < self._timeout:
            return []
        self._flight_t = now
        self._timeout *= 2.0           # RFC 6347-style doubling backoff
        self.retransmits += 1
        return self._note_out(list(self._flight))

    # ---------------------------------------------------------- key export
    @property
    def selected_profile(self) -> SrtpProfile:
        if self._profile is None:
            raise RuntimeError("no SRTP profile negotiated")
        return self._profile

    def srtp_keys(self):
        if not self.complete:
            raise RuntimeError("handshake not complete")
        profile = self.selected_profile
        p = profile.policy
        kl, sl = p.enc_key_len, p.salt_len
        cr, sr = ((self._rand, self._peer_rand)
                  if self.role == "client"
                  else (self._peer_rand, self._rand))
        seed = b"stub-dtls-export" + cr + sr
        blob = (hashlib.sha256(seed).digest()
                + hashlib.sha256(seed + b"\x01").digest())
        ck, sk = blob[:kl], blob[kl:2 * kl]
        cs = blob[2 * kl:2 * kl + sl]
        ss = blob[2 * kl + sl:2 * (kl + sl)]
        if self.role == "client":
            return profile, ck, cs, sk, ss
        return profile, sk, ss, ck, cs


class DtlsAssociationTable:
    """Pending DTLS-SRTP associations for a bridge's media loop.

    Owns the sid <-> peer-address binding, datagram routing, flight
    retransmission ticking and the early-media hold window; the owning
    bridge supplies `install(sid, endpoint)` to put exported keys into
    its own tables.  Shared by ConferenceBridge and SfuBridge so the
    association logic exists exactly once.  Reference:
    DtlsPacketTransformer + DtlsControlImpl (SURVEY §3.5).

    Two execution modes:

    * inline (default, `deferred=False`): `on_dtls` runs OpenSSL work
      and key install synchronously on the calling (tick) thread —
      the original behavior, kept for bridges without a lifecycle
      manager.
    * deferred (`deferred=True`, flipped by the lifecycle plane's
      HandshakeQueue): `on_dtls` only ENQUEUES the datagram into a
      bounded inbox and returns nothing; `process(budget)` drains the
      inbox in bounded batches on the between-ticks window, and key
      landing goes through the staged commit barrier (the install
      callback stages; `release_stream` happens at commit).  The tick
      thread never touches OpenSSL.
    """

    def __init__(self, loop, profile: SrtpProfile, install,
                 deferred: bool = False, inbox_limit: int = 8192,
                 endpoint_factory=None):
        self.loop = loop
        self.profile = profile
        self.install = install
        # same-surface endpoint constructor; swap in StubDtlsEndpoint
        # for environments without the `cryptography` package
        self.endpoint_factory = endpoint_factory or DtlsSrtpEndpoint
        self.pending = {}              # sid -> DtlsSrtpEndpoint
        self.addr_of = {}              # (ip, port) -> sid
        self.sid_addr = {}             # sid -> (ip, port)  (companion)
        self.rejected = 0              # fingerprint-mismatch teardowns
        self.deferred = bool(deferred)
        self.inbox_limit = int(inbox_limit)
        self._inbox: "deque" = deque()  # (datagram, addr) awaiting drain
        self.inbox_dropped = 0         # inbox overflow (storm past bound)
        self.retransmits_total = 0     # flight datagrams resent by tick()
        self.feeds_total = 0           # OpenSSL feed() calls (any thread)
        self.handshakes_completed = 0

    def join(self, sid: int, role: str = "server",
             remote_fingerprint: Optional[str] = None,
             cookie_exchange: bool = False,
             remote_addr: Optional[Tuple[int, int]] = None
             ) -> "DtlsSrtpEndpoint":
        ep = self.endpoint_factory(role, profiles=[self.profile],
                                   remote_fingerprint=remote_fingerprint,
                                   cookie_exchange=cookie_exchange)
        self.pending[sid] = ep
        if remote_addr is not None:
            # signaling-known peer address: bind now, no guessing later
            self._bind(sid, tuple(remote_addr))
            ep.progressed = True       # binding is authoritative
        self.loop.hold_stream(sid)
        return ep

    def _bind(self, sid: int, addr) -> None:
        old = self.sid_addr.get(sid)
        if old is not None:
            self.addr_of.pop(old, None)
        self.addr_of[addr] = sid
        self.sid_addr[sid] = addr

    def _claim(self, addr):
        """Pick the sid a first-seen address may drive.  Unclaimed
        pending rows win; otherwise a bound-but-unprogressed row may be
        superseded (with cookie_exchange, a spoofed-source ClientHello
        can bind an address but can never round-trip the cookie, so it
        never progresses and the real peer reclaims the row)."""
        unclaimed = [s for s in self.pending if s not in self.sid_addr]
        if len(unclaimed) == 1:
            return unclaimed[0]
        if not unclaimed:
            stale = [s for s, ep in self.pending.items()
                     if not ep.progressed
                     and self.sid_addr.get(s) is not None]
            if len(stale) == 1:
                return stale[0]
        # ambiguous: guessing could land keys on the wrong row; the
        # peer's flight timer retransmits, signaling-bound joins route
        return None

    def on_dtls(self, datagram: bytes, addr) -> list:
        addr = tuple(addr)
        if self.deferred:
            # tick-thread contract: no OpenSSL here — enqueue only.
            # Replies go out from process() on the between-ticks window.
            if len(self._inbox) >= self.inbox_limit:
                self.inbox_dropped += 1
                return []
            self._inbox.append((bytes(datagram), addr))
            return []
        return self._process_one(datagram, addr)

    # plane=dual: in deferred mode this only ever runs from process()
    # on the between-ticks window; standalone bridges (no lifecycle
    # manager) run it inline from on_dtls, accepting the tick-thread
    # OpenSSL cost.  The runtime twin of this exception is the
    # handshake_tick_thread_feeds counter, which stays 0 whenever a
    # lifecycle manager is attached.
    def _process_one(self, datagram: bytes, addr) -> list:  # jitlint: plane=dual
        sid = self.addr_of.get(addr)
        if sid is None:
            sid = self._claim(addr)
            if sid is None:
                return []
            self._bind(sid, addr)
        ep = self.pending.get(sid)
        if ep is None:
            return []
        try:
            self.feeds_total += 1
            out = ep.feed(datagram)
        except RuntimeError as e:
            # fingerprint mismatch (wrong peer / MITM): drop the
            # association, not the bridge tick
            self.forget(sid)
            self.rejected += 1
            _dtls_log.warn("dtls_association_rejected", sid=sid,
                           error=str(e))
            return []
        if ep.complete:
            # media return address comes from the AUTHENTICATED
            # handshake's bound 5-tuple, never from the first datagram
            self.loop.addr_ip[sid] = addr[0]
            self.loop.addr_port[sid] = addr[1]
            # un-pend BEFORE install: install hooks (e.g. SFU route
            # rebuild) must see this row as keyed
            self.pending.pop(sid, None)
            self.handshakes_completed += 1
            self.install(sid, ep)
            if not self.deferred:
                # deferred mode stages the keys instead; the commit
                # barrier releases held early media atomically
                self.loop.release_stream(sid)
        return out

    def process(self, budget: Optional[int] = None) -> int:
        """Drain up to `budget` queued datagrams (all when None) — the
        off-tick OpenSSL pass for deferred mode.  Replies gather per
        peer address: one PacketBatch/send_batch per address per pass,
        not one per datagram."""
        from libjitsi_tpu.core.packet import PacketBatch

        n = len(self._inbox)
        if budget is not None:
            n = min(n, max(0, int(budget)))
        if n <= 0:
            return 0
        by_addr: Dict[Tuple[int, int], List[bytes]] = {}
        for _ in range(n):
            datagram, addr = self._inbox.popleft()
            out = self._process_one(datagram, addr)
            if out:
                by_addr.setdefault(addr, []).extend(out)
        for addr, datagrams in by_addr.items():
            self.loop.engine.send_batch(
                PacketBatch.from_payloads(datagrams), addr[0], addr[1])
        return n

    def tick(self, stride: int = 1, phase: int = 0) -> int:
        """Flight-retransmission pass: drive RFC 6347 timers and resend
        expired flights, gathered into one PacketBatch per peer address.
        `stride`/`phase` let the off-tick drain service only 1/stride of
        the associations per pass (keyed on sid), spreading a storm's
        flight timers so retransmissions never resend in lockstep — the
        jitter that honors exponential client backoff.  Returns the
        number of datagrams resent."""
        from libjitsi_tpu.core.packet import PacketBatch

        stride = max(1, int(stride))
        by_addr: Dict[Tuple[int, int], List[bytes]] = {}
        for sid, ep in list(self.pending.items()):
            if stride > 1 and (sid % stride) != (phase % stride):
                continue
            out = ep.tick()
            if not out:
                continue
            addr = self.sid_addr.get(sid)
            if addr is None:
                continue
            by_addr.setdefault(addr, []).extend(out)
        sent = 0
        for addr, datagrams in by_addr.items():
            self.loop.engine.send_batch(
                PacketBatch.from_payloads(datagrams), addr[0], addr[1])
            sent += len(datagrams)
        self.retransmits_total += sent
        return sent

    @property
    def backlog(self) -> int:
        """Queued datagrams + pending associations: the admission-facing
        depth of the handshake plane."""
        return len(self._inbox) + len(self.pending)

    def forget(self, sid: int) -> None:
        self.pending.pop(sid, None)
        addr = self.sid_addr.pop(sid, None)
        if addr is not None:
            self.addr_of.pop(addr, None)
            if self._inbox:
                # purge queued datagrams from the forgotten 5-tuple:
                # with recycled addresses (forget -> rejoin same
                # ip:port) a stale ClientHello must never feed the row
                # that later claims the address
                self._inbox = deque(
                    (d, a) for d, a in self._inbox if a != addr)
        self.loop.discard_stream(sid)
