"""SDES key management — RFC 4568 SDP security descriptions.

Rebuilds the reference's `org.jitsi.impl.neomedia.transform.sdes.
{SDesControlImpl,SDesTransformEngine}` (which delegate the attribute
grammar to the sdes4j library): master keys ride in signaling as
``a=crypto`` lines; no handshake on the media path.  This is the easiest
key provider and the one the round-1 end-to-end slice uses — DTLS-SRTP
plugs into the same ``(master_key, master_salt, profile)`` installation
point later (SURVEY §2.2: "same key provider → SRTP context interface
as DTLS/ZRTP").
"""

from __future__ import annotations

import base64
import dataclasses
import os
from typing import List, Optional, Sequence

from libjitsi_tpu.transform.srtp.policy import SrtpProfile

# RFC 4568 §6.2 crypto-suite names happen to match SrtpProfile values.
_SUITES = {p.value: p for p in SrtpProfile}


@dataclasses.dataclass
class CryptoAttribute:
    """One ``a=crypto:<tag> <suite> inline:<key||salt b64>`` line."""

    tag: int
    profile: SrtpProfile
    master_key: bytes
    master_salt: bytes

    def encode(self) -> str:
        blob = base64.b64encode(self.master_key + self.master_salt).decode()
        # unpadded per RFC 4568 §9.2 (b64 pad chars are not in the grammar)
        return f"{self.tag} {self.profile.value} inline:{blob.rstrip('=')}"

    @classmethod
    def parse(cls, line: str) -> "CryptoAttribute":
        line = line.strip()
        if line.startswith("a=crypto:"):
            line = line[len("a=crypto:"):]
        parts = line.split()
        if len(parts) < 3 or not parts[2].startswith("inline:"):
            raise ValueError(f"malformed crypto attribute: {line!r}")
        tag = int(parts[0])
        suite = parts[1]
        if suite not in _SUITES:
            raise ValueError(f"unknown crypto-suite {suite!r}")
        profile = _SUITES[suite]
        inline = parts[2][len("inline:"):]
        # key params may carry |lifetime|MKI — take the key portion
        b64 = inline.split("|")[0]
        blob = base64.b64decode(b64 + "=" * (-len(b64) % 4))
        p = profile.policy
        need = p.enc_key_len + p.salt_len
        if len(blob) != need:
            raise ValueError(
                f"{suite} needs {need}B key||salt, got {len(blob)}B")
        return cls(tag, profile, blob[: p.enc_key_len], blob[p.enc_key_len:])


class SdesControl:
    """Offer/answer state machine over crypto attributes.

    Reference: SDesControlImpl.{getInitiatorCryptoAttributes,
    responderSelectAttribute, initiatorSelectAttribute}.  After a
    successful exchange, `local_key` protects our sender direction and
    `remote_key` our receiver direction.
    """

    def __init__(self, profiles: Optional[Sequence[SrtpProfile]] = None,
                 rng=os.urandom):
        self.profiles = list(profiles) if profiles else [
            SrtpProfile.AES_CM_128_HMAC_SHA1_80,
            SrtpProfile.AES_CM_128_HMAC_SHA1_32,
        ]
        self._rng = rng
        self.local: Optional[CryptoAttribute] = None
        self.remote: Optional[CryptoAttribute] = None

    def _fresh(self, tag: int, profile: SrtpProfile) -> CryptoAttribute:
        p = profile.policy
        return CryptoAttribute(
            tag, profile, self._rng(p.enc_key_len), self._rng(p.salt_len))

    # -------------------------------------------------------------- offer
    def create_offer(self) -> List[str]:
        """Initiator: one attribute per supported suite (fresh keys)."""
        self._offered = [self._fresh(i + 1, pr)
                         for i, pr in enumerate(self.profiles)]
        return [a.encode() for a in self._offered]

    def accept_answer(self, line: str) -> None:
        """Initiator: responder picked one tag; select the matching key."""
        remote = CryptoAttribute.parse(line)
        mine = [a for a in self._offered if a.tag == remote.tag]
        if not mine or mine[0].profile is not remote.profile:
            raise ValueError("answer does not match any offered attribute")
        self.local, self.remote = mine[0], remote

    # ------------------------------------------------------------- answer
    def create_answer(self, offer_lines: Sequence[str]) -> str:
        """Responder: pick the first offered suite we support."""
        for line in offer_lines:
            try:
                remote = CryptoAttribute.parse(line)
            except ValueError:
                continue
            if remote.profile in self.profiles:
                self.remote = remote
                self.local = self._fresh(remote.tag, remote.profile)
                return self.local.encode()
        raise ValueError("no acceptable crypto attribute in offer")

    # ------------------------------------------------------------- result
    @property
    def negotiated(self) -> bool:
        return self.local is not None and self.remote is not None

    @property
    def profile(self) -> SrtpProfile:
        if not self.negotiated:
            raise RuntimeError("SDES not negotiated")
        return self.local.profile
