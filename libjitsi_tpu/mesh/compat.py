"""shard_map across jax versions.

`jax.shard_map` (with its `check_vma=` kwarg) only exists on newer jax;
the pinned container ships 0.4.x where the API lives at
`jax.experimental.shard_map.shard_map` and the kwarg is spelled
`check_rep=`.  Every mesh module routes through this one symbol so the
version probe happens exactly once at import.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
