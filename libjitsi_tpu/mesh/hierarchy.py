"""Hierarchical two-level mixing for broadcast conferences.

A webinar/town-hall conference (a handful of speakers, thousands of
listeners) breaks the conference-affinity contract on purpose: its
LISTENER rows may straddle shards, because listeners contribute no
audio and every listener of a conference receives the *same* mix.
The two-level tick exploits both facts:

1. **Speaker level (home shard).**  A broadcast conference's speaker
   rows never straddle shards — they live on the conference's home
   shard and are mixed there with the same segment-sum mix-minus as
   `mesh/placement.py`'s `shard_local_mix` (full mix-minus: each
   speaker hears everyone but itself).  Non-home shards hold no active
   speaker rows for that conference, so their partial sums are zero.
2. **Bus fan-out (the ONE collective).**  The per-conference mixed bus
   — a tiny ``[n_conf, frame]`` matrix — is summed across shards and
   replicated to every shard with a single ``psum`` per tick
   (registered in ``SANCTIONED_COLLECTIVE_SITES``; the
   ``mesh-collective`` jitlint gate keeps it the only one).  Listeners
   are *fanout-only* rows: no per-row mix-minus, just the shared bus,
   re-protected per listener leg through the existing zero-collective
   `sharded_gcm_fanout` path.

Contrast the participant-sharded escape hatch (`sharded_mix_minus`):
it materializes a mix-minus row for every participant and pays its
psum over participant-sharded contributions — per-listener work the
broadcast shape never needs.  The `bcast_fanout_pps` perf-gate
scenario keeps that comparison honest (hard floor, ≥3x).

`broadcast_step_ref` is the same body under plain `jit` on one device
(the cross-shard psum degenerates to identity because a single device
holds all rows); int32 addition is associative, so psum-of-partial-sums
is bit-exact versus the flat sum — `mesh/parity.py`'s
`assert_hierarchy_parity` asserts it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from libjitsi_tpu.conference.mixer import I16_MAX, I16_MIN, audio_levels
from libjitsi_tpu.mesh.compat import shard_map

AXIS = "streams"


def _broadcast_body(n_conf: int, total_of):
    """Shared two-level tick body: segment-sum speaker partials →
    cross-shard bus total (`total_of`: psum on the mesh, identity on
    one device) → speaker mix-minus + shared listener bus.  One
    definition for both `broadcast_bus_fanout` and
    `broadcast_step_ref` so the mesh tick and its parity/benchmark
    reference cannot drift.

    pcm int16 [B, F] speaker rows, active bool [B], conf int32 [B]
    (GLOBAL broadcast-conference index, 0..n_conf) → (speaker mix-minus
    int16 [B, F], bus int16 [n_conf, F], levels uint8 [B]).
    """

    def _step(pcm, active, conf):
        p = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], p, 0)
        seg = jax.ops.segment_sum(contrib, conf, num_segments=n_conf)
        bus = total_of(seg)
        spk = jnp.clip(bus[conf] - contrib,
                       I16_MIN, I16_MAX).astype(jnp.int16)
        shared = jnp.clip(bus, I16_MIN, I16_MAX).astype(jnp.int16)
        return spk, shared, audio_levels(p, active)

    return _step


def broadcast_bus_fanout(mesh: Mesh, n_conf: int):
    """The hierarchical steady-state tick: speaker rows sharded on the
    batch axis, per-conference buses psum-fanned to EVERY shard in one
    collective (out_spec ``P(None, None)`` = replicated), where the
    fanout-only listener path re-protects them via
    `sharded_gcm_fanout`.  Exactly one cross-chip collective per tick.
    """

    def _total(seg):
        # the ONE sanctioned cross-chip collective of the broadcast
        # tick: [n_conf, F] summed over shards AND replicated back
        return jax.lax.psum(seg, AXIS)

    _step = _broadcast_body(n_conf, _total)
    return jax.jit(shard_map(
        _step, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS, None), P(None, None), P(AXIS)),
        check_vma=False))


def broadcast_step_ref(n_conf: int):
    """Single-device twin of `broadcast_bus_fanout`: the same body
    under plain `jax.jit` over the FULL row array (a single device
    already holds every shard's rows, so the cross-shard total is the
    segment sum itself).  Consumers: `assert_hierarchy_parity` and the
    `bcast_fanout_pps` perf-gate scenario."""
    return jax.jit(_broadcast_body(n_conf, lambda seg: seg))


def listener_fanout_protect(mesh: Mesh, aad_const: int = 12):
    """The listener leg of the broadcast tick: the replicated bus
    payloads are sealed once per listener through the batched
    `sharded_gcm_fanout` path — legs sharded over chips, zero
    collectives (the bus already arrived replicated via the tick's one
    psum)."""
    from libjitsi_tpu.mesh.sharded import sharded_gcm_fanout

    return sharded_gcm_fanout(mesh, aad_const=aad_const)
