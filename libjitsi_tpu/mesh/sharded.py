"""Multi-chip execution: stream sharding + cross-chip mixer collective.

The reference is a single-process library whose "distributed backend" is
UDP sockets (SURVEY §2.7); scaling libjitsi means running more JVMs.  The
TPU rebuild scales inside the framework instead, with the two parallel
axes BASELINE.json asks for:

- **streams axis (data parallel)**: per-stream crypto state and packet
  batches are sharded across chips.  SRTP is row-local (each packet's key
  material travels with its row), so protect/unprotect needs *no*
  collectives — XLA just partitions the batch over ICI-connected chips.
- **participants axis (the mixer collective)**: the conference mix's
  ``total = sum_j pcm_j`` becomes a `psum` over the mesh axis when one
  conference's participants live on different chips (the reference's
  single-threaded `AudioMixer` loop has no analog — this is the part that
  makes 1k-participant rooms possible).

Everything is expressed with `shard_map` over a 1-D `Mesh` whose axis is
named ``"streams"``; multi-host DCN scale-out reuses the same code with a
2-D ``(dcn, streams)`` mesh (partition streams by host first).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libjitsi_tpu.mesh.compat import shard_map

from libjitsi_tpu.conference.mixer import I16_MAX, I16_MIN, audio_levels
from libjitsi_tpu.transform.srtp import kernel

AXIS = "streams"
DCN_AXIS = "dcn"


def make_media_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name "streams"."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def make_multihost_mesh(n_hosts: int, devices=None) -> Mesh:
    """2-D (dcn, streams) mesh: hosts on the outer (DCN) axis, chips on
    the inner (ICI) axis.  Streams partition across hosts first (no
    cross-host media dependency), then across a host's chips; mixer
    collectives over both axes ride ICI within a host and DCN across
    (SURVEY §2.7 DCN row).  On a single host this reshapes the local
    devices to rehearse the layout.
    """
    if devices is None:
        devices = jax.devices()
    if len(devices) % n_hosts:
        raise ValueError(f"{len(devices)} devices not divisible by "
                         f"{n_hosts} hosts")
    arr = np.asarray(devices).reshape(n_hosts, -1)
    return Mesh(arr, (DCN_AXIS, AXIS))


def sharded_mix_minus_2d(mesh: Mesh):
    """Mixer whose participants span BOTH mesh axes: partial sums psum
    over ICI (streams axis) then over DCN — one conference spanning
    hosts.  pcm [N, F] sharded over (dcn*streams) on N."""

    def _mix(pcm, active):
        pcm = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], pcm, 0)
        local = jnp.sum(contrib, axis=0, keepdims=True)
        total = jax.lax.psum(jax.lax.psum(local, AXIS), DCN_AXIS)
        out = jnp.clip(total - contrib, I16_MIN, I16_MAX).astype(jnp.int16)
        return out, audio_levels(pcm, active)

    spec_r = P((DCN_AXIS, AXIS))
    return jax.jit(shard_map(
        _mix, mesh=mesh, in_specs=(P((DCN_AXIS, AXIS), None), spec_r),
        out_specs=(P((DCN_AXIS, AXIS), None), spec_r), check_vma=False,
    ))


# --------------------------------------------------------------------- SRTP

def sharded_srtp_protect(mesh: Mesh, tag_len: int = 10, encrypt: bool = True):
    """Returns a jitted batch-sharded SRTP protect.

    All row arguments are sharded on the batch axis; key material is
    pre-gathered per row (``round_keys [B, R, 16]``, ``midstates
    [B, 2, 5]``) so the computation is embarrassingly parallel across
    chips.  The host control plane keeps each stream's packets on the
    chip that owns the stream's row range, so the gather never crosses
    ICI.
    """
    fn = functools.partial(kernel.srtp_protect, tag_len=tag_len,
                           encrypt=encrypt)
    row = P(AXIS)
    specs = (P(AXIS, None), row, row, P(AXIS, None, None), P(AXIS, None),
             P(AXIS, None, None), row)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=specs, out_specs=(P(AXIS, None), row),
        check_vma=False,
    ))


# -------------------------------------------------------------------- mixer

def sharded_mix_minus(mesh: Mesh):
    """Returns a jitted mixer whose participant axis spans the mesh.

    pcm int16 [N, F] and active bool [N] sharded on N; per-shard partial
    sums are combined with one `psum` over ICI, then subtract-self/clip
    run shard-locally.  Output sharding matches input row sharding.
    """

    def _mix(pcm, active):
        pcm = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], pcm, 0)
        local = jnp.sum(contrib, axis=0, keepdims=True)
        total = jax.lax.psum(local, AXIS)
        out = jnp.clip(total - contrib, I16_MIN, I16_MAX).astype(jnp.int16)
        return out, audio_levels(pcm, active)

    return jax.jit(shard_map(
        _mix, mesh=mesh, in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None), P(AXIS)), check_vma=False,
    ))


def sharded_bridge_mix(mesh: Mesh):
    """Whole-bridge multi-conference mixing sharded over the mesh —
    the DENSE-RECTANGLE special case of the conference-affinity idea.

    pcm int16 [C, N, F] / active bool [C, N] sharded on the CONFERENCE
    axis: conferences are independent, so each chip mixes its shard
    with zero collectives — the bridge scales linearly in chips the
    way stream-data-parallel SRTP does.  It requires every conference
    padded to one fixed size N, which real churn never gives you; the
    production path is `mesh/placement.py`: `ConferencePlacer` pins
    whole conferences to shards over the RAGGED row layout and
    `affinity_tick` mixes them with a shard-local `segment_sum` — same
    zero-collective property, no padding.  Start there.

    `sharded_mix_minus` / `sharded_media_step` remain the explicit
    giant-conference escape hatches: they shard one conference's
    PARTICIPANTS and pay a cross-chip psum every tick (the
    `mesh-collective` lint gate sanctions exactly those sites).  Reach
    for them only when a single conference outgrows a chip's rows.
    """

    from libjitsi_tpu.conference.mixer import mix_minus_many

    return jax.jit(shard_map(
        lambda pcm, active: mix_minus_many(pcm, active),
        mesh=mesh, in_specs=(P(AXIS, None, None), P(AXIS, None)),
        out_specs=(P(AXIS, None, None), P(AXIS, None)), check_vma=False,
    ))


# ---------------------------------------------------------- full media step

def sharded_media_step(mesh: Mesh, tag_len: int = 10):
    """One full conference tick, jitted over the mesh — the framework's
    "training step" equivalent (used by the driver's multi-chip dry run).

    Per chip-local shard: SRTP-unprotect the inbound batch, mix the
    decoded PCM with the cross-chip psum, SRTP-protect the outbound
    batch.  Packet rows and participant rows use the same axis (a
    participant's media stays on its owning chip end to end).
    """

    def _step(data, length, payload_off, round_keys, iv, midstates, roc,
              pcm, active,
              out_data, out_length, out_payload_off, out_rk, out_iv,
              out_mid, out_roc):
        dec, dec_len, auth_ok = kernel.srtp_unprotect(
            data, length, payload_off, round_keys, iv, midstates, roc,
            tag_len, True)
        pcm = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], pcm, 0)
        total = jax.lax.psum(jnp.sum(contrib, axis=0, keepdims=True), AXIS)
        mixed = jnp.clip(total - contrib, I16_MIN, I16_MAX).astype(jnp.int16)
        levels = audio_levels(pcm, active)
        enc, enc_len = kernel.srtp_protect(
            out_data, out_length, out_payload_off, out_rk, out_iv, out_mid,
            out_roc, tag_len, True)
        return dec, dec_len, auth_ok, mixed, levels, enc, enc_len

    row = P(AXIS)
    mat = P(AXIS, None)
    key3 = P(AXIS, None, None)
    in_specs = (mat, row, row, key3, mat, key3, row,
                mat, row,
                mat, row, row, key3, mat, key3, row)
    out_specs = (mat, row, row, mat, row, mat, row)
    return jax.jit(shard_map(
        _step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    ))


def sharded_gcm_fanout(mesh: Mesh, aad_const: int = 12):
    """Full-mesh AEAD SFU fan-out with receiver LEGS sharded over chips.

    The decrypt-once/re-encrypt-N load is embarrassingly parallel over
    the receiver axis: each chip holds a shard of the per-leg key
    schedules + GHASH matrices and seals the SAME P packets for its
    legs — zero collectives, the packets broadcast once over ICI.
    data [P, W]; length [P]; round_keys [G, R, 16]; gmat [G, 128, 128];
    iv12 [G, P, 12] -> (out [G, P, W], out_len [P]).
    Reference: RTPTranslatorImpl's per-receiver send chains (SURVEY
    §3.4), re-designed as a sharded batch.
    """
    from libjitsi_tpu.kernels.gcm import gcm_protect_fanout

    def _fan(data, length, rks, gms, iv):
        out, out_len = gcm_protect_fanout(data, length, rks, gms, iv,
                                          aad_const=aad_const)
        return out, out_len

    return jax.jit(shard_map(
        _fan, mesh=mesh,
        in_specs=(P(None, None), P(None), P(AXIS, None, None),
                  P(AXIS, None, None), P(AXIS, None, None)),
        out_specs=(P(AXIS, None, None), P(None)), check_vma=False))
