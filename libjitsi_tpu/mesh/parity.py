"""Mesh-vs-single-chip parity rehearsals for the PRODUCT objects.

One harness, two consumers: the driver's multi-chip dry run
(`__graft_entry__.dryrun_multichip`) and the pytest suite
(tests/test_mesh_table.py) both assert that the sharded
`ShardedSrtpTable` and the mesh-mode `ConferenceBridge` are
bit-identical to their single-chip twins — keeping the harness here
means the dryrun and CI can never drift apart on what "parity" means.
"""

from __future__ import annotations

import numpy as np

from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpStreamTable


def assert_table_parity(mesh, capacity: int, batch_size: int,
                        rounds: int = 2, profile=None) -> None:
    """Sharded table protect/unprotect must match the plain table byte
    for byte, including the host replay planes (any supported profile:
    CM and GCM both ride this)."""
    from libjitsi_tpu.mesh import ShardedSrtpTable
    from libjitsi_tpu.transform.srtp import SrtpProfile

    if profile is None:
        profile = SrtpProfile.AES_CM_128_HMAC_SHA1_80
    salt_len = profile.policy.salt_len
    rng = np.random.default_rng(23)
    mks = rng.integers(0, 256, (capacity, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (capacity, salt_len), dtype=np.uint8)

    def build_pair():
        sh = ShardedSrtpTable(capacity, mesh, profile)
        sh.add_streams(np.arange(capacity), mks, mss)
        pl = SrtpStreamTable(capacity, profile)
        pl.add_streams(np.arange(capacity), mks, mss)
        return sh, pl

    def batch(seq0):
        # own generator per call: both tables must see IDENTICAL batches
        r = np.random.default_rng(seq0)
        streams = r.integers(0, capacity, batch_size)
        pls = [bytes([seq0 & 0xFF]) * 40 for _ in range(batch_size)]
        return rtp_header.build(
            pls, [(seq0 + i) & 0xFFFF for i in range(batch_size)],
            [0] * batch_size, (0x7000 + streams).tolist(),
            [96] * batch_size, stream=streams.tolist())

    sh_tx, pl_tx = build_pair()
    sh_rx, pl_rx = build_pair()
    for k in range(rounds):
        seq0 = 100 * (k + 1)
        w_sh = sh_tx.protect_rtp(batch(seq0))
        w_pl = pl_tx.protect_rtp(batch(seq0))
        for i in range(w_sh.batch_size):
            if w_sh.to_bytes(i) != w_pl.to_bytes(i):
                raise AssertionError(
                    f"sharded TABLE protect != single-chip at row {i}")
        if not np.array_equal(sh_tx.tx_ext, pl_tx.tx_ext):
            raise AssertionError("sharded TABLE tx state diverged")
        d_sh, ok_sh = sh_rx.unprotect_rtp(w_sh)
        d_pl, ok_pl = pl_rx.unprotect_rtp(w_pl)
        if not (bool(np.all(ok_sh)) and bool(np.all(ok_pl))):
            raise AssertionError("sharded TABLE unprotect auth failed")
        for i in range(d_sh.batch_size):
            if d_sh.to_bytes(i) != d_pl.to_bytes(i):
                raise AssertionError(
                    f"sharded TABLE unprotect != single-chip at row {i}")
        if not (np.array_equal(sh_rx.rx_max, pl_rx.rx_max)
                and np.array_equal(sh_rx.rx_mask, pl_rx.rx_mask)):
            raise AssertionError("sharded TABLE replay state diverged")


def run_bridge_once(cfg, mesh, capacity: int, rounds: int = 2,
                    pipelined: bool = False) -> dict:
    """One tiny G.711 conference through a ConferenceBridge (mesh-mode
    when `mesh` is not None; pipelined dispatch when `pipelined`) over
    real loopback UDP with pinned TX counters; returns
    {(client, seq): wire_bytes} for comparison."""
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.kernels import g711
    from libjitsi_tpu.service.bridge import ConferenceBridge

    bridge = ConferenceBridge(cfg, port=0, capacity=capacity,
                              recv_window_ms=0, mesh=mesh,
                              pipelined=pipelined)
    clis = []
    for ssrc in (10, 20):
        prot = SrtpStreamTable(capacity=1)
        rx_key = (bytes([ssrc]) * 16, bytes([ssrc + 1]) * 14)
        prot.add_stream(0, *rx_key)
        eng = UdpEngine(port=0, max_batch=16)
        bridge.add_participant(
            ssrc, rx_key, (bytes([ssrc + 2]) * 16,
                           bytes([ssrc + 3]) * 14))
        clis.append((ssrc, prot, eng))
    # pin the randomized TX counters so two runs' egress is comparable
    bridge._tx_seq[:] = 300
    bridge._tx_ts[:] = 7000
    got = {}
    now = 50.0
    try:
        for k in range(rounds):
            for ssrc, prot, eng in clis:
                pcm = ((1000 + 500 * ssrc)
                       * np.ones(160)).astype(np.int16)
                pay = np.asarray(g711.ulaw_encode(pcm[None]))[0]
                b = rtp_header.build([pay.tobytes()], [50 + k],
                                     [k * 160], [ssrc], [0],
                                     stream=[0])
                eng.send_batch(prot.protect_rtp(b), "127.0.0.1",
                               bridge.port)
            for _ in range(10):
                if bridge.tick(now=now)["rx"]:
                    break
            bridge.tick(now=now + 0.001)
            for j, (_ssrc, _prot, eng) in enumerate(clis):
                back, _, _ = eng.recv_batch(timeout_ms=2)
                if back.batch_size:
                    hdr = rtp_header.parse(back)
                    for i in range(back.batch_size):
                        got[(j, int(hdr.seq[i]))] = back.to_bytes(i)
            now += 0.020
        # pipelined mode holds the final frame's protect in flight; ship
        # it so sync and pipelined runs are compared on the same frames
        # (flush_sends is a no-op for the sync loop)
        bridge.loop.flush_sends()
        for j, (_ssrc, _prot, eng) in enumerate(clis):
            back, _, _ = eng.recv_batch(timeout_ms=2)
            if back.batch_size:
                hdr = rtp_header.parse(back)
                for i in range(back.batch_size):
                    got[(j, int(hdr.seq[i]))] = back.to_bytes(i)
    finally:
        for _ssrc, _prot, eng in clis:
            eng.close()
        bridge.close()
    return got


def assert_bridge_parity(cfg, mesh, capacity: int,
                        pipelined: bool = False) -> None:
    """Assembled mesh-mode ConferenceBridge egress must be byte-
    identical to the single-chip SYNC bridge for the same conference
    (with `pipelined`, the overlapped-dispatch mesh bridge rides the
    same contract — VERDICT r4 #2)."""
    plain = run_bridge_once(cfg, None, capacity)
    meshed = run_bridge_once(cfg, mesh, capacity, pipelined=pipelined)
    if len(plain) < 2:
        raise AssertionError("bridge parity run produced no egress")
    if plain != meshed:
        raise AssertionError(
            "assembled mesh ConferenceBridge egress != single-chip")


def run_sfu_once(cfg, mesh, capacity: int, rounds: int = 3,
                 pipelined: bool = False) -> dict:
    """One tiny 3-endpoint audio SFU conference over loopback UDP
    (mesh-mode when `mesh` is not None; pipelined fan-out dispatch when
    `pipelined`), deterministic tick clock; returns
    {(endpoint, sender_ssrc, seq): wire_bytes}."""
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.service.sfu_bridge import SfuBridge

    sfu = SfuBridge(cfg, port=0, capacity=capacity, recv_window_ms=0,
                    mesh=mesh, pipelined=pipelined)
    eps = []
    for k in range(3):
        ssrc = 0x600 + 9 * k
        rx_key = (bytes([ssrc & 0xFF]) * 16,
                  bytes([(ssrc + 1) & 0xFF]) * 14)
        tx_key = (bytes([(ssrc + 2) & 0xFF]) * 16,
                  bytes([(ssrc + 3) & 0xFF]) * 14)
        prot = SrtpStreamTable(capacity=1)
        prot.add_stream(0, *rx_key)
        eng = UdpEngine(port=0, max_batch=64)
        sfu.add_endpoint(ssrc, rx_key, tx_key)
        eps.append((ssrc, prot, eng))
    got = {}
    now = 60.0
    try:
        for r in range(rounds):
            for ssrc, prot, eng in eps:
                b = rtp_header.build(
                    [b"sfu-%08x-%d" % (ssrc, r)], [400 + r], [r * 960],
                    [ssrc], [96], stream=[0])
                eng.send_batch(prot.protect_rtp(b), "127.0.0.1",
                               sfu.port)
            for _ in range(12):
                sfu.tick(now=now)
            for j, (_ssrc, _prot, eng) in enumerate(eps):
                back, _, _ = eng.recv_batch(timeout_ms=2)
                if back.batch_size:
                    hdr = rtp_header.parse(back)
                    for i in range(back.batch_size):
                        got[(j, int(hdr.ssrc[i]), int(hdr.seq[i]))] = \
                            back.to_bytes(i)
            now += 0.020
    finally:
        for _ssrc, _prot, eng in eps:
            eng.close()
        sfu.close()
    return got


def assert_sfu_parity(cfg, mesh, capacity: int,
                     pipelined: bool = False) -> None:
    """Assembled mesh-mode SfuBridge fan-out must be byte-identical to
    the single-chip SYNC bridge for the same conference (pipelined
    mesh dispatch included — VERDICT r4 #2)."""
    plain = run_sfu_once(cfg, None, capacity)
    meshed = run_sfu_once(cfg, mesh, capacity, pipelined=pipelined)
    if len(plain) < 6:
        raise AssertionError("sfu parity run produced too little egress")
    if plain != meshed:
        raise AssertionError(
            "assembled mesh SfuBridge egress != single-chip")


# ------------------------------------------------- conference affinity

def build_affinity_workload(batch: int, n_conf: int, rng,
                            part: int = 4, width: int = 128,
                            frame: int = 160, tag_len: int = 10):
    """Argument tuple for `affinity_tick`/`affinity_step_ref`: rx rows
    are authentic ciphertext (protected off-line so unprotect's auth
    passes), `conf` numbers conferences within each shard slice."""
    from libjitsi_tpu.kernels.aes import expand_key
    from libjitsi_tpu.kernels.sha1 import hmac_precompute
    from libjitsi_tpu.transform.srtp import kernel as k

    def dense_args():
        # dense per-row SRTP inputs, keys pre-gathered per row (the
        # same shape family as __graft_entry__'s example args)
        rk = np.stack([
            expand_key(rng.integers(0, 256, 16,
                                    dtype=np.uint8).tobytes())
            for _ in range(batch)])
        mid = np.stack([
            hmac_precompute(rng.integers(0, 256, 20,
                                         dtype=np.uint8).tobytes())
            for _ in range(batch)])
        data = rng.integers(0, 256, (batch, width), dtype=np.uint8)
        data[:, 0] = 0x80
        length = np.full(batch, width - 16, dtype=np.int32)
        payload_off = np.full(batch, 12, dtype=np.int32)
        iv = rng.integers(0, 256, (batch, 16), dtype=np.uint8)
        roc = np.zeros(batch, dtype=np.uint32)
        return data, length, payload_off, rk, iv, mid, roc

    rx = dense_args()
    enc, enc_len = k.srtp_protect(*rx, tag_len=tag_len, encrypt=True)
    rx = (np.asarray(enc), np.asarray(enc_len, np.int32)) + rx[2:]
    tx = dense_args()
    pcm = rng.integers(-2000, 2000, (batch, frame)).astype(np.int16)
    active = np.ones(batch, dtype=bool)
    conf = ((np.arange(batch) // part) % n_conf).astype(np.int32)
    return rx + (pcm, active, conf) + tx


def assert_affinity_parity(mesh, n_devices: int, b_shard: int = 32,
                           part: int = 4, tag_len: int = 10,
                           seed: int = 11) -> None:
    """`affinity_tick` on the mesh must be bit-identical, shard by
    shard, to `affinity_step_ref` (the same body under plain jit) —
    the structural proof that the tick is shard-local: if anything
    leaked across the mesh axis, some shard's slice would differ from
    the single-device run of that slice alone."""
    import jax

    from libjitsi_tpu.mesh.placement import (affinity_step_ref,
                                             affinity_tick)

    rng = np.random.default_rng(seed)
    n_conf = b_shard // part
    args = build_affinity_workload(n_devices * b_shard, n_conf, rng,
                                   part=part, tag_len=tag_len)
    got = affinity_tick(mesh, n_conf, tag_len)(*args)
    jax.block_until_ready(got[3])
    if not bool(np.all(np.asarray(got[2]))):
        raise AssertionError("affinity tick failed SRTP auth")
    ref = affinity_step_ref(n_conf, tag_len)
    for s in range(n_devices):
        sl = slice(s * b_shard, (s + 1) * b_shard)
        want = ref(*[a[sl] for a in args])
        for got_a, want_a in zip(got, want):
            if not np.array_equal(np.asarray(got_a)[sl],
                                  np.asarray(want_a)):
                raise AssertionError(
                    f"affinity tick != per-shard reference on shard {s}")


# ------------------------------------------------- hierarchical broadcast

def build_broadcast_workload(n_devices: int, rows_per_shard: int,
                             n_conf: int, rng, frame: int = 160):
    """Argument tuple for `broadcast_bus_fanout`/`broadcast_step_ref`:
    each broadcast conference's ACTIVE speaker rows live only on its
    home shard (conference c homes on shard c % n_devices); all other
    rows are inactive padding — exactly the layout `ConferencePlacer.
    place_broadcast` produces for the speaker leg."""
    batch = n_devices * rows_per_shard
    pcm = rng.integers(-2000, 2000, (batch, frame)).astype(np.int16)
    active = np.zeros(batch, dtype=bool)
    conf = np.zeros(batch, dtype=np.int32)
    for c in range(n_conf):
        home = c % n_devices
        # a handful of speaker rows in the home shard's row range
        k = int(rng.integers(2, min(8, rows_per_shard // n_conf) + 1))
        base = home * rows_per_shard + (c // n_devices) * 8
        rows = np.arange(base, base + k)
        active[rows] = True
        conf[rows] = c
    return pcm, active, conf


def assert_hierarchy_parity(mesh, n_devices: int,
                            rows_per_shard: int = 32, n_conf: int = 4,
                            frame: int = 160, seed: int = 17) -> None:
    """`broadcast_bus_fanout` on the mesh must be bit-identical to
    `broadcast_step_ref` on one device: the speaker-shard segment-sum
    mix is exact, and the one-psum bus fan-out is exact because int32
    addition is associative — psum-of-per-shard-partial-sums equals the
    flat sum.  Any second collective, any listener-side mix, or any
    float path sneaking in would break bit equality."""
    import jax

    from libjitsi_tpu.mesh.hierarchy import (broadcast_bus_fanout,
                                             broadcast_step_ref)

    rng = np.random.default_rng(seed)
    args = build_broadcast_workload(n_devices, rows_per_shard, n_conf,
                                    rng, frame=frame)
    got = broadcast_bus_fanout(mesh, n_conf)(*args)
    jax.block_until_ready(got[0])
    want = broadcast_step_ref(n_conf)(*args)
    names = ("speaker mix-minus", "bus", "levels")
    for got_a, want_a, name in zip(got, want, names):
        if not np.array_equal(np.asarray(got_a), np.asarray(want_a)):
            raise AssertionError(
                f"hierarchical tick {name} != single-device reference")
    # the bus really is the per-conference speaker sum (numpy oracle)
    pcm, active, conf = args
    for c in range(n_conf):
        rows = active & (conf == c)
        flat = np.clip(pcm[rows].astype(np.int64).sum(axis=0),
                       -32768, 32767).astype(np.int16)
        if not np.array_equal(np.asarray(got[1])[c], flat):
            raise AssertionError(f"bus {c} != numpy speaker sum")
