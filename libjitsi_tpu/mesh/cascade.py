"""Bridge-to-bridge cascade trunk (Octo-style relay, SURVEY §2.7).

Production Jitsi scales one conference across multiple bridges by
cascading them through relay legs; this module is that leg for the
jax_graft bridge.  A `CascadeTrunk` is one end of a point-to-point
trunk between two `SfuBridge` instances, carried over the existing
UDP engine and keyed with its own SRTP context — the relay hop is
encrypted and authenticated independently of the participant legs it
carries, so a trunk peer authenticates frames without holding any
participant key.

Wire format (one datagram per frame; first byte demuxes):

- **media frame** — a trunk-level RTP packet (version bits ``0x80``):
  ssrc ``TRUNK_SSRC``, its own 16-bit trunk seq space, payload =
  ``conf:u32be || inner wire bytes``.  The inner bytes are the
  participant's ORIGINAL SRTP-protected packet, untouched: the far
  bridge unprotects the trunk layer, then feeds the inner packet to
  its own ingest path where the participant's row key (synced via the
  roster plane) authenticates it end-to-end.  The whole trunk packet
  is protected by the trunk `SrtpStreamTable`.
- **control frame** — ``0xC5 || kind:u8 || body``.  HEARTBEAT/ACK
  (liveness + RTT), NACK (trunk-seq loss report), SPEAKERS (top-K
  speaker set per conference), ROSTER (remote conference membership +
  admission parameters for failover adoption) carry JSON bodies; FEC
  carries a packed XOR parity over the last `fec_k` protected media
  frames.

The trunk payload is the PR 11 **top-K speaker bus**, not raw
per-participant fan-out: `wants()` admits only the current speaker
set of a cascaded conference, and SPEAKERS frames propagate ranking
flips so both bridges restrict the same legs.

Loss recovery spans the extra hop under its OWN deadline budget
(`TrunkConfig.deadline_budget_s`): the receive side tracks trunk-seq
gaps (`rtp/loss.LossTracker`), schedules deadline-aware NACKs through
`sfu/recovery.NackScheduler`, the send side serves RTX from a
`PacketCache` behind a `TokenBucket`, and XOR FEC groups recover
single losses without a round trip.  A loss whose deadline passes
falls through to PLC accounting (`plc_fallthrough_total`) and is
never re-NACKed — concealment on the destination bridge, not a
retransmission storm across the trunk.

Liveness reuses the PR 16 admission machinery: heartbeats on a fixed
cadence, `heartbeat_miss_down` misses flip the trunk ``down`` (the
`on_down` hook is the `CascadeSupervisor`'s failover trigger), relay
admission refuses with typed ``trunk_down`` / ``trunk_backlog``
reasons plus a jittered-exponential retry-after hint, and refused
senders back off exactly like PR 16's reconnect clients.

**Journey trace extension**: a media frame may carry an OPTIONAL RFC
5285 header extension (profile `TRACE_EXT_PROFILE`) on the trunk RTP
header: origin bridge id, hop count, the origin loop's journey trace
id, and the origin monotonic stamp — all public observability data
(no key material, no participant payload; secret-flow clean).  The
extension lives in the header region, so `parse().payload_off` skips
it: a legacy peer slicing the payload at `payload_off` recovers
``conf || inner`` bit-exactly and simply never sees the trace, while
a trace-aware peer stitches the journey across the hop
(`packet_journey_seconds{hop=...}` on the far bridge).
"""

from __future__ import annotations

import json
import struct
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp.loss import LossTracker
from libjitsi_tpu.sfu.cache import PacketCache
from libjitsi_tpu.sfu.recovery import (NackScheduler, RecoveryConfig,
                                       TokenBucket)
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.utils.logging import get_logger

_log = get_logger("mesh.cascade")

#: the trunk's own RTP stream identity (one seq/ROC space per direction)
TRUNK_SSRC = 0x7B1D6E00
TRUNK_PT = 0x5D

MAGIC_CONTROL = 0xC5
KIND_HEARTBEAT = 1
KIND_HEARTBEAT_ACK = 2
KIND_NACK = 3
KIND_SPEAKERS = 4
KIND_ROSTER = 5
KIND_FEC = 6

#: RFC 5285 profile id of the trunk journey-trace extension
TRACE_EXT_PROFILE = 0x6A54
#: extension body: bridge_id:u16 hop:u8 ver:u8 trace_id:u32 stamp_us:u64
_TRACE_FMT = ">HBBIQ"
TRACE_EXT_LEN = struct.calcsize(_TRACE_FMT)      # 16 bytes = 4 words
#: full on-wire extension block cost (4B RFC 5285 header + body)
TRACE_WIRE_LEN = 4 + TRACE_EXT_LEN


@dataclass(frozen=True)
class TrunkTrace:
    """Journey context crossing the trunk: which bridge originated the
    packet, how many trunk hops it has taken, the origin loop's journey
    trace id, and the origin's monotonic ingress stamp (seconds).  The
    stamp is only directly comparable on a shared clock; cross-machine
    readers correct it against the trunk RTT ring (see
    `CascadeSupervisor._deliver_remote`)."""

    bridge_id: int
    hop: int
    trace_id: int
    t0: float


def pack_trace(trace: TrunkTrace) -> bytes:
    return struct.pack(_TRACE_FMT,
                       int(trace.bridge_id) & 0xFFFF,
                       int(trace.hop) & 0xFF, 0,
                       int(trace.trace_id) & 0xFFFFFFFF,
                       max(0, int(trace.t0 * 1e6)) & 0xFFFFFFFFFFFFFFFF)


def unpack_trace(body: bytes) -> Optional[TrunkTrace]:
    """Decode a trace extension body; None on anything malformed — an
    unreadable trace degrades to an untraced frame, never a drop."""
    if len(body) < TRACE_EXT_LEN:
        return None
    bridge_id, hop, ver, trace_id, stamp_us = struct.unpack(
        _TRACE_FMT, body[:TRACE_EXT_LEN])
    if ver != 0:
        return None
    return TrunkTrace(bridge_id=bridge_id, hop=hop, trace_id=trace_id,
                      t0=stamp_us / 1e6)


@dataclass
class TrunkConfig:
    """Knobs for one trunk leg (seconds unless suffixed)."""

    heartbeat_interval_s: float = 0.05
    heartbeat_miss_down: int = 5       # consecutive misses -> "down"
    deadline_budget_s: float = 0.12    # trunk-hop NACK/RTX budget
    rtt_init_s: float = 0.02           # assumed until measured
    backlog_bound: int = 256           # queued frames past this: refuse
    retry_base_s: float = 0.05         # trunk_down retry-after base
    roster_interval_s: float = 0.25    # roster-sync cadence
    fec_k: int = 4                     # XOR group size; 0 disables
    nack_budget: int = 16              # trunk seqs NACKed per round
    rtx_budget_bps: float = 2_000_000.0
    rtx_burst_bytes: int = 64 << 10
    rx_window: int = 128               # protected frames kept for FEC


class TrunkRelay:
    """The trunk wire codec + per-direction SRTP/seq/recovery state.

    One instance per trunk end; `CascadeTrunk` owns the socket,
    liveness and control plane and delegates framing here.  tx and rx
    directions are keyed independently (`tx_key` protects what we
    send; `rx_key` opens what the peer sends) so the two ends simply
    swap the same key pair.
    """

    def __init__(self, tx_key: Tuple[bytes, bytes],
                 rx_key: Tuple[bytes, bytes],
                 cfg: Optional[TrunkConfig] = None):
        self.cfg = cfg or TrunkConfig()
        self._tx = SrtpStreamTable(capacity=1)
        self._tx.add_stream(0, *tx_key)
        self._rx = SrtpStreamTable(capacity=1)
        self._rx.add_stream(0, *rx_key)
        self.tx_seq = 1
        self.tx_ts = 0
        self.cache = PacketCache(max_age=2.0)
        self.rtx_bucket = TokenBucket(self.cfg.rtx_budget_bps,
                                      self.cfg.rtx_burst_bytes)
        self.loss = LossTracker()
        self.nacks = NackScheduler(RecoveryConfig(
            nack_budget_per_stream=self.cfg.nack_budget,
            rtt_s=self.cfg.rtt_init_s))
        # recent PROTECTED rx frames by trunk seq, for FEC recovery
        self._rx_window: "OrderedDict[int, bytes]" = OrderedDict()
        # FEC accumulation over PROTECTED tx frames
        self._fec_group: List[bytes] = []
        self._fec_base: Optional[int] = None

    # ------------------------------------------------------------ media
    def frame_media(self, conf: int, inner: bytes, now: float,
                    trace: Optional[TrunkTrace] = None
                    ) -> Optional[Tuple[int, bytes]]:
        """Wrap + trunk-protect one inner wire packet; returns
        (trunk_seq, protected frame bytes), or None when the inner
        packet cannot fit the trunk MTU alongside its framing.  An
        optional `trace` rides as an RTP header extension — inside the
        trunk-authenticated header region, outside the payload a
        legacy peer slices at `payload_off`."""
        payload = struct.pack(">I", int(conf) & 0xFFFFFFFF) + inner
        overhead = 64 + (TRACE_WIRE_LEN if trace is not None else 0)
        if len(payload) + overhead > 1504:     # header + auth headroom
            return None
        ext = None if trace is None else \
            [(TRACE_EXT_PROFILE, pack_trace(trace))]
        seq = self.tx_seq & 0xFFFF
        b = rtp_header.build([payload], [seq], [self.tx_ts],
                             [TRUNK_SSRC], [TRUNK_PT], stream=[0],
                             ext=ext)
        self.tx_seq = (self.tx_seq + 1) & 0xFFFF
        self.tx_ts += 1
        wire = self._tx.protect_rtp(b).to_bytes(0)
        self.cache.insert(TRUNK_SSRC, seq, wire, now=now)
        if self.cfg.fec_k > 0:
            if self._fec_base is None:
                self._fec_base = seq
            self._fec_group.append(wire)
        return seq, wire

    def take_fec(self) -> Optional[bytes]:
        """XOR parity frame over the accumulated group, when full."""
        if self.cfg.fec_k <= 0 or len(self._fec_group) < self.cfg.fec_k:
            return None
        group, self._fec_group = self._fec_group, []
        base, self._fec_base = self._fec_base, None
        maxlen = max(len(g) for g in group)
        xor = np.zeros(maxlen, dtype=np.uint8)
        lens = []
        for g in group:
            a = np.frombuffer(g, dtype=np.uint8)
            xor[: len(a)] ^= a
            lens.append(len(g))
        body = struct.pack(">HBH", base & 0xFFFF, len(group), maxlen)
        body += struct.pack(f">{len(group)}H", *lens)
        return bytes([MAGIC_CONTROL, KIND_FEC]) + body + xor.tobytes()

    def on_fec(self, body: bytes) -> Optional[Tuple[int, bytes]]:
        """Try to recover the single missing frame of an FEC group from
        the rx window; returns (seq, protected frame) on success."""
        base, k, maxlen = struct.unpack(">HBH", body[:5])
        lens = struct.unpack(f">{k}H", body[5:5 + 2 * k])
        xor = np.frombuffer(body[5 + 2 * k:], dtype=np.uint8).copy()
        if len(xor) != maxlen:
            return None
        missing = [i for i in range(k)
                   if ((base + i) & 0xFFFF) not in self._rx_window]
        if len(missing) != 1:
            return None                    # 0 missing or unrecoverable
        for i in range(k):
            seq = (base + i) & 0xFFFF
            if seq in self._rx_window:
                a = np.frombuffer(self._rx_window[seq], dtype=np.uint8)
                xor[: len(a)] ^= a
        mi = missing[0]
        return (base + mi) & 0xFFFF, xor[: lens[mi]].tobytes()

    def open_media(self, wire: bytes, now: float
                   ) -> Optional[Tuple[int, int, bytes,
                                       Optional[TrunkTrace]]]:
        """Unprotect one trunk media frame -> (trunk_seq, conf, inner
        wire bytes, journey trace or None), tracking loss/NACK/FEC
        state.  None on auth failure or replay.  The trace slot is
        None for legacy frames (no extension), foreign extension
        profiles, and malformed trace bodies — graceful degrade, the
        media path is identical either way."""
        hdr_seq = struct.unpack(">H", wire[2:4])[0]
        batch = PacketBatch.from_payloads([wire], stream=[0])
        dec, ok = self._rx.unprotect_rtp(batch)
        if not bool(np.asarray(ok)[0]):
            return None
        self._rx_window[hdr_seq] = wire
        while len(self._rx_window) > self.cfg.rx_window:
            self._rx_window.popitem(last=False)
        self.nacks.on_arrival(TRUNK_SSRC, hdr_seq)
        fresh, _adv = self.loss.observe(hdr_seq)
        if fresh:
            self.nacks.on_losses(TRUNK_SSRC, fresh, now,
                                 deadline=now + self.cfg.deadline_budget_s)
        hdr = rtp_header.parse(dec)
        raw = dec.to_bytes(0)
        trace = None
        if (int(hdr.extension[0]) == 1
                and int(hdr.ext_profile[0]) == TRACE_EXT_PROFILE):
            ext_off = 12 + 4 * int(hdr.cc[0]) + 4
            trace = unpack_trace(
                raw[ext_off: ext_off + 4 * int(hdr.ext_words[0])])
        body = raw[int(hdr.payload_off[0]):]
        conf = struct.unpack(">I", body[:4])[0]
        return hdr_seq, conf, body[4:], trace

    def serve_nack(self, seqs, now: float) -> List[bytes]:
        """Sender side of a trunk NACK: cached frames, RTX-budgeted."""
        out = []
        for s in seqs:
            pkt = self.cache.get(TRUNK_SSRC, int(s))
            if pkt is not None and self.rtx_bucket.allow(len(pkt), now):
                out.append(pkt)
        return out

    def collect(self, now: float) -> Tuple[List[int], List[int]]:
        """Deadline-aware NACK round: (seqs to NACK now, seqs whose
        deadline expired unrecovered — the PLC fall-through; those are
        never re-NACKed)."""
        nacks, expired = self.nacks.collect(now)
        return (nacks.get(TRUNK_SSRC, []), expired.get(TRUNK_SSRC, []))


class _TrunkView:
    """Scrape-time indirection for trunk metrics: forwards every
    attribute read to the owner's CURRENT `.trunk`, so registered
    callables survive the trunk instance being replaced (recovery
    constructs a fresh one — sockets don't outlive a crash)."""

    __slots__ = ("_owner",)

    def __init__(self, owner):
        self._owner = owner

    def __getattr__(self, name):
        return getattr(self._owner.trunk, name)


class CascadeTrunk:
    """One end of a bridge-to-bridge trunk: socket, liveness state
    machine, typed relay admission, and the conference/speaker/roster
    control plane.  Drive it with `pump(now)` once per supervisor tick
    (off-tick plane — after the lifecycle commit barrier)."""

    def __init__(self, tx_key: Tuple[bytes, bytes],
                 rx_key: Tuple[bytes, bytes],
                 config: Optional[TrunkConfig] = None,
                 port: int = 0, seed: int = 0):
        self.cfg = config or TrunkConfig()
        self.relay = TrunkRelay(tx_key, rx_key, self.cfg)
        self.engine = UdpEngine(port=port, max_batch=256)
        self.port = self.engine.port
        self.peer: Optional[Tuple[str, int]] = None
        self.state = "idle"               # idle -> up <-> down
        self.now = 0.0                    # model clock, set by pump()
        self._rng = np.random.default_rng(seed)
        self._attached = False            # riding a MediaLoop ring
        # liveness
        self.hb_seq = 0
        self._hb_sent_at: Dict[int, float] = {}
        self._hb_next = 0.0
        self._hb_miss_streak = 0
        self.attempts = 0                 # reconnect attempts while down
        self.rtt = self.cfg.rtt_init_s
        # journey tracing: who we are on the trace extension, and a
        # zero-arg hook yielding the loop's (trace_id, ingress_t0) —
        # wired by attach()/CascadeSupervisor; None = relay untraced
        self.bridge_id = 0
        self._journey_origin: Optional[
            Callable[[], Tuple[int, Optional[float]]]] = None
        # cascaded conferences: conf -> speaker ssrc set (None = all)
        self._confs: Dict[int, Optional[set]] = {}
        self.local_roster: Dict[int, list] = {}
        self.remote_roster: Dict[int, list] = {}
        self._remote_ssrcs: set = set()    # members homed on the peer
        self._roster_next = 0.0
        # backlog while not "up" (flushes on recovery; bounded)
        self._tx_queue: deque = deque()
        # hooks (wired by CascadeSupervisor / tests)
        self.on_down: Optional[Callable[[float], None]] = None
        self.on_up: Optional[Callable[[float], None]] = None
        self.on_speakers: Optional[Callable[[int, list], None]] = None
        self.on_roster: Optional[Callable[[dict], None]] = None
        # deliver(conf, inner_wire, trace_or_None)
        self.deliver: Optional[
            Callable[[int, bytes, Optional[TrunkTrace]], None]] = None
        # counters (all registered in register_metrics)
        self.heartbeats_total = 0
        self.heartbeat_misses_total = 0
        self.relay_frames_total = 0
        self.relay_bytes_total = 0
        self.nacks_sent_total = 0
        self.rtx_served_total = 0
        self.fec_recovered_total = 0
        self.plc_fallthrough_total = 0
        self.refusals_total = 0
        self.unprotect_drops_total = 0
        self.oversize_drops_total = 0
        self._pps_window: deque = deque()  # (now, relay_frames_total)
        self._rtt_ring = None              # metrics TimingRing when registered

    # ---------------------------------------------------------- liveness
    def connect(self, peer_ip: str, peer_port: int,
                now: float = 0.0) -> None:
        self.peer = (peer_ip, int(peer_port))
        self.state = "up"                  # optimistic; heartbeats judge
        self._hb_miss_streak = 0
        self.attempts = 0
        self._hb_next = now

    def attach(self, loop) -> None:
        """Put the trunk socket on the bridge loop's multi-ring drain:
        trunk datagrams arrive with tick cadence through the same
        ingress span as media, handed to `on_batch` instead of the RTP
        path."""
        loop.add_ring(self.engine, sink=self.on_batch)
        self._attached = True
        # journey stamps cross the trunk: relayed frames carry the
        # loop's current (trace_id, ingress_t0) in the trace extension
        if hasattr(loop, "journey_origin"):
            self._journey_origin = loop.journey_origin

    def admit_reason(self) -> Optional[str]:
        """Typed relay admission (the PR 16 refusal surface): None when
        the trunk accepts relay work right now."""
        if self.state != "up":
            return "trunk_down"
        if len(self._tx_queue) >= self.cfg.backlog_bound:
            return "trunk_backlog"
        return None

    def retry_after(self) -> float:
        """Jittered-exponential retry-after hint for refused senders,
        grown with the reconnect attempt count like PR 16's clients."""
        base = self.cfg.retry_base_s
        return float(base * (2 ** min(self.attempts, 6))
                     * (1.0 + 0.25 * float(self._rng.random())))

    # ------------------------------------------------------- conferences
    def cascade_conference(self, conf: int, speakers=None) -> None:
        """Mark a conference as cascaded over this trunk.  `speakers`
        is the top-K speaker ssrc set forming the trunk payload (None
        relays every member — the degenerate bus of a tiny meeting)."""
        self._confs[int(conf)] = (None if speakers is None
                                  else {int(s) for s in speakers})

    def uncascade_conference(self, conf: int) -> None:
        self._confs.pop(int(conf), None)

    def set_speakers(self, conf: int, ssrcs, now: float = 0.0) -> None:
        """Local top-K ranking flipped: restrict the trunk payload and
        propagate the set to the peer (speaker bus, not fan-out)."""
        conf = int(conf)
        self._confs[conf] = {int(s) for s in ssrcs}
        self._send_control(KIND_SPEAKERS,
                           {"conf": conf,
                            "ssrcs": sorted(self._confs[conf])})

    def wants(self, conf, ssrc: int) -> bool:
        if conf is None or int(conf) not in self._confs:
            return False
        if int(ssrc) in self._remote_ssrcs:
            # homed on the PEER: its media reached this bridge via the
            # trunk in the first place — relaying the locally-accepted
            # copy back would be an echo loop (each packet ping-ponging
            # until the replay window kills it)
            return False
        speakers = self._confs[int(conf)]
        return speakers is None or int(ssrc) in speakers

    def claim_member(self, conf: int, ssrc: int) -> None:
        """Ownership transfer (failover adoption committed): the member
        is homed HERE now — relay its media again, advertise it in the
        local roster."""
        conf, ssrc = int(conf), int(ssrc)
        self._remote_ssrcs.discard(ssrc)
        ms = self.remote_roster.get(conf)
        if ms is not None:
            ms = [m for m in ms if int(m["ssrc"]) != ssrc]
            if ms:
                self.remote_roster[conf] = ms
            else:
                self.remote_roster.pop(conf, None)

    def set_roster(self, roster: Dict[int, list]) -> None:
        """Local conference roster for failover adoption: conf ->
        [{ssrc, rx, tx, name}] with keys hex-encoded.  Synced to the
        peer on `roster_interval_s` cadence."""
        self.local_roster = roster
        self._roster_next = 0.0            # push on next pump

    # ------------------------------------------------------------- relay
    def relay_media(self, conf: int, inner: bytes, now: float) -> bool:
        """Relay one participant wire packet across the trunk; returns
        False on a typed refusal (caller may consult `admit_reason` /
        `retry_after`)."""
        # refresh liveness before admitting: a storm that starves
        # pump() must not keep relaying into a trunk that is dead
        self._refresh_liveness(now)
        reason = self.admit_reason()
        if reason == "trunk_backlog" or (reason == "trunk_down"
                                         and len(self._tx_queue)
                                         >= self.cfg.backlog_bound):
            self.refusals_total += 1
            return False
        framed = self.relay.frame_media(conf, inner, now,
                                        trace=self._mk_trace())
        if framed is None:
            self.oversize_drops_total += 1
            return False
        _seq, wire = framed
        if reason is None:
            self._send(wire)
            self.relay_frames_total += 1
            self.relay_bytes_total += len(wire)
            fec = self.relay.take_fec()
            if fec is not None:
                self._send(fec)
        else:                              # down but under backlog bound
            self._tx_queue.append(wire)
        return True

    def _mk_trace(self) -> Optional[TrunkTrace]:
        """Journey trace for a frame relayed NOW: the loop's current
        trace id + ingress stamp under this bridge's id, hop 0 (the
        origin).  None when no journey source is wired (bare trunks,
        legacy assemblies) — the frame goes out extension-free."""
        if self._journey_origin is None:
            return None
        trace_id, t0 = self._journey_origin()
        if t0 is None:
            return None
        return TrunkTrace(bridge_id=self.bridge_id, hop=0,
                          trace_id=trace_id, t0=t0)

    def relay_pps(self) -> float:
        """Relayed frames/s over a sliding ~2 s window (gauge)."""
        if not self._pps_window:
            return 0.0
        t0, n0 = self._pps_window[0]
        t1, n1 = self._pps_window[-1]
        return float((n1 - n0) / (t1 - t0)) if t1 > t0 else 0.0

    # -------------------------------------------------------------- pump
    def pump(self, now: float) -> None:
        """Per-tick trunk work: drain the socket (when not riding the
        loop's ring), heartbeat/liveness, NACK rounds, PLC expiry,
        roster sync, pps window."""
        self.now = now
        if not self._attached:
            batch, sip, sport = self.engine.recv_batch(timeout_ms=0)
            if batch.batch_size:
                self.on_batch(batch, sip, sport, now=now)
        self._liveness(now)
        nack, expired = self.relay.collect(now)
        if nack and self.state == "up":
            self._send_control(KIND_NACK, {"seqs": [int(s) for s in nack]})
            self.nacks_sent_total += len(nack)
        if expired:
            # deadline passed: the destination conceals; never re-NACK
            self.plc_fallthrough_total += len(expired)
        if self.local_roster and now >= self._roster_next:
            self._send_control(KIND_ROSTER, {
                "confs": {str(c): m for c, m in self.local_roster.items()}})
            self._roster_next = now + self.cfg.roster_interval_s
        self._pps_window.append((now, self.relay_frames_total))
        while (len(self._pps_window) > 2
               and now - self._pps_window[0][0] > 2.0):
            self._pps_window.popleft()

    def _refresh_liveness(self, now: float) -> None:
        """Age unanswered heartbeats into misses and convict the trunk
        down when the streak crosses the bound.  Split out of
        `_liveness` so `relay_media`/`on_datagram`/`_send` refresh the
        control-channel stats too — during a storm that starves
        `pump()`, /metrics must not serve a stale miss streak (and
        relay admission must not trust a dead trunk)."""
        stale = [s for s, t in self._hb_sent_at.items()
                 if now - t > self.cfg.heartbeat_interval_s]
        for s in stale:
            del self._hb_sent_at[s]
        if stale:
            self._hb_miss_streak += len(stale)
            self.heartbeat_misses_total += len(stale)
        if (self.state == "up"
                and self._hb_miss_streak >= self.cfg.heartbeat_miss_down):
            self.state = "down"
            _log.info("trunk_down", misses=self._hb_miss_streak)
            if self.on_down is not None:
                self.on_down(now)

    def _liveness(self, now: float) -> None:
        if self.peer is None:
            return
        if now < self._hb_next:
            return
        if self.state == "up":
            self._hb_next = now + self.cfg.heartbeat_interval_s
        else:
            self.attempts += 1
            self._hb_next = now + self.retry_after()
        self._refresh_liveness(now)
        self.hb_seq = (self.hb_seq + 1) & 0xFFFF
        self._hb_sent_at[self.hb_seq] = now
        self.heartbeats_total += 1
        self._send_control(KIND_HEARTBEAT,
                           {"seq": self.hb_seq, "t": now})

    # ------------------------------------------------------------ ingress
    def on_batch(self, batch: PacketBatch, _sip=None, _sport=None,
                 now: Optional[float] = None) -> None:
        """Ring sink / direct drain: demux every trunk datagram."""
        now = self.now if now is None else now
        for i in range(batch.batch_size):
            self.on_datagram(batch.to_bytes(i), now)

    def on_datagram(self, data: bytes, now: float) -> None:
        if len(data) < 2:
            return
        if data[0] == MAGIC_CONTROL:
            self._on_control(data[1], data[2:], now)
            # refresh AFTER control handling: an ACK settles its own
            # heartbeat entry before the entry could age into a miss
            self._refresh_liveness(now)
            return
        if (len(data) < 12
                or int.from_bytes(data[8:12], "big") != TRUNK_SSRC):
            # not a trunk frame: the local bridge latches a delivered
            # remote speaker's return address to THIS socket, so its
            # fanout echoes land here — expected noise, not corruption
            return
        opened = self.relay.open_media(data, now)
        if opened is None:
            self.unprotect_drops_total += 1
            return
        _seq, conf, inner, trace = opened
        if self.deliver is not None:
            self.deliver(conf, inner, trace)

    def _on_control(self, kind: int, body: bytes, now: float) -> None:
        if kind == KIND_FEC:
            rec = self.relay.on_fec(body)
            if rec is not None:
                seq, wire = rec
                self.fec_recovered_total += 1
                self.relay.nacks.on_arrival(TRUNK_SSRC, seq)
                opened = self.relay.open_media(wire, now)
                if opened is not None and self.deliver is not None:
                    self.deliver(opened[1], opened[2], opened[3])
            return
        msg = json.loads(body.decode("utf-8"))
        if kind == KIND_HEARTBEAT:
            self._send_control(KIND_HEARTBEAT_ACK, msg)
        elif kind == KIND_HEARTBEAT_ACK:
            sent = self._hb_sent_at.pop(int(msg["seq"]), None)
            if sent is not None:
                self.rtt = max(1e-6, now - sent)
                self.relay.nacks.cfg.rtt_s = min(
                    self.rtt, self.cfg.deadline_budget_s / 2)
                if self._rtt_ring is not None:
                    self._rtt_ring.record(self.rtt)
            self._hb_miss_streak = 0
            if self.state != "up":
                self.state = "up"
                self.attempts = 0
                _log.info("trunk_up", queued=len(self._tx_queue))
                while self._tx_queue:
                    self._send(self._tx_queue.popleft())
                    self.relay_frames_total += 1
                if self.on_up is not None:
                    self.on_up(now)
        elif kind == KIND_NACK:
            served = self.relay.serve_nack(msg["seqs"], now)
            for wire in served:
                self._send(wire)
            self.rtx_served_total += len(served)
        elif kind == KIND_SPEAKERS:
            conf = int(msg["conf"])
            self._confs[conf] = {int(s) for s in msg["ssrcs"]}
            if self.on_speakers is not None:
                self.on_speakers(conf, msg["ssrcs"])
        elif kind == KIND_ROSTER:
            self.remote_roster = {int(c): m
                                  for c, m in msg["confs"].items()}
            self._remote_ssrcs = {int(m["ssrc"])
                                  for ms in self.remote_roster.values()
                                  for m in ms}
            if self.on_roster is not None:
                self.on_roster(self.remote_roster)

    # --------------------------------------------------------------- I/O
    def _send(self, data: bytes) -> None:
        if self.peer is None:
            return
        # keep the miss streak / state gauges current on every send,
        # not just on pump() (idempotent for an already-aged clock)
        self._refresh_liveness(self.now)
        self.engine.send_batch(PacketBatch.from_payloads([data]),
                               self.peer[0], self.peer[1])

    def _send_control(self, kind: int, msg: dict) -> None:
        body = json.dumps(msg, sort_keys=True).encode("utf-8")
        self._send(bytes([MAGIC_CONTROL, kind]) + body)

    # ----------------------------------------------------------- metrics
    def register_metrics(self, registry, prefix: str = "trunk",
                         owner=None) -> None:
        """`owner`: an object whose `.trunk` attribute names the
        CURRENT trunk (CascadeSupervisor passes itself).  Every gauge
        and counter then resolves through it AT SCRAPE TIME, so a
        trunk replaced under the supervisor (failover recovery hands
        the restored supervisor a fresh trunk — sockets don't survive
        a crash) keeps the metrics live instead of frozen on the dead
        instance's closures."""
        live = (lambda: owner.trunk) if owner is not None \
            else (lambda: self)
        target = self if owner is None else _TrunkView(owner)
        registry.register_counters(target, [
            ("heartbeats_total", "trunk heartbeats sent"),
            ("heartbeat_misses_total",
             "trunk heartbeats that aged out unanswered"),
            ("relay_frames_total", "media frames relayed across trunk"),
            ("relay_bytes_total", "relayed trunk bytes"),
            ("nacks_sent_total", "trunk-seq NACKs sent"),
            ("rtx_served_total", "trunk RTX frames served from cache"),
            ("fec_recovered_total", "trunk frames recovered via XOR FEC"),
            ("plc_fallthrough_total",
             "deadline-expired trunk losses conceded to PLC"),
            ("refusals_total", "typed trunk relay refusals"),
            ("unprotect_drops_total", "trunk frames failing SRTP auth"),
            ("oversize_drops_total", "inner packets over trunk MTU"),
        ], prefix=prefix)
        registry.register_scalar(f"{prefix}_relay_pps",
                                 lambda: float(live().relay_pps()),
                                 help_="relayed frames/s (sliding 2s)",
                                 kind="gauge")
        registry.register_scalar(
            f"{prefix}_state_up",
            lambda: 1.0 if live().state == "up" else 0.0,
            help_="1 while the trunk liveness state is up")
        registry.register_scalar(
            f"{prefix}_tx_backlog",
            lambda: float(len(live()._tx_queue)),
            help_="frames queued while the trunk is down")
        registry.register_scalar(
            f"{prefix}_heartbeat_miss_streak",
            lambda: float(live()._hb_miss_streak),
            help_="consecutive unanswered heartbeats (refreshed on "
                  "send/ingress, not just pump)")
        self._rtt_ring = registry.timing(f"{prefix}_rtt")

    # --------------------------------------------------------- lifecycle
    def snapshot(self) -> dict:
        """Control-plane state for the checkpoint spine.  Crypto/seq/
        recovery state is transient (re-established by live traffic,
        like the bridge's caches); what must survive a crash is which
        conferences are cascaded and the last synced rosters."""
        return {
            "peer": list(self.peer) if self.peer else None,
            "confs": {str(c): (sorted(s) if s is not None else None)
                      for c, s in self._confs.items()},
            "local_roster": {str(c): m
                             for c, m in self.local_roster.items()},
            "remote_roster": {str(c): m
                              for c, m in self.remote_roster.items()},
        }

    def restore(self, snap: dict, now: float = 0.0) -> None:
        if snap.get("peer"):
            self.connect(snap["peer"][0], int(snap["peer"][1]), now=now)
        self._confs = {int(c): (set(s) if s is not None else None)
                       for c, s in snap.get("confs", {}).items()}
        self.local_roster = {int(c): m for c, m
                             in snap.get("local_roster", {}).items()}
        self.remote_roster = {int(c): m for c, m
                              in snap.get("remote_roster", {}).items()}
        self._remote_ssrcs = {int(m["ssrc"])
                              for ms in self.remote_roster.values()
                              for m in ms}

    def close(self) -> None:
        self.engine.close()
