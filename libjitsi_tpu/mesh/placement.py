"""Conference-affinity placement: a conference never straddles chips.

`mesh/sharded.py`'s original design sharded one conference's
PARTICIPANTS over the mesh axis and paid a cross-chip `psum` inside
every steady-state mixer tick — measured on the 8-way CPU mesh as an
~2x SLOWDOWN versus one plain device (`mesh_cpu8_ratio_vs_plain`
~1.95, BENCH r05).  Conferences, though, are independent: nothing in a
mixer tick couples conference A to conference B.  This module flips
the unit of distribution from participants to conferences:

- **`ConferencePlacer`** assigns each WHOLE conference to one shard at
  join time (greedy least-loaded over a size-class cost model), so a
  conference's SRTP rows, jitter state and recovery state are
  shard-resident and a steady-state tick needs **zero cross-chip
  collectives** — the mix-minus `psum` becomes a shard-local
  `segment_sum` over the shard's own conference rows.
- **`affinity_tick`** is that steady-state tick: one `shard_map` whose
  body runs unprotect → segment-sum mix-minus → protect entirely
  shard-locally.  The only cross-chip traffic left in the system is
  placement/rebalance at join/leave time, which rides the
  `StreamLifecycleManager` staged-install/commit-barrier path (a
  placement move is a lifecycle event, never a mid-tick one).
- **`ShardRowAllocator`** partitions the dense row space into
  contiguous per-shard ranges so "conference C lives on shard S" is a
  row-range invariant the device layout can rely on.

The zero-collective claim is a hard gate, not a convention: the
`mesh-collective` jitlint checker flags any `psum`/`all_gather`/
`ppermute` in `mesh/` outside the escape-hatch kernels sanctioned in
`SANCTIONED_COLLECTIVE_SITES` below (participant-sharding remains
available for the one conference that outgrows a chip — see
`sharded_mix_minus` — but nothing on the steady-state path reaches
it).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from libjitsi_tpu.conference.mixer import I16_MAX, I16_MIN, audio_levels
from libjitsi_tpu.mesh.compat import shard_map
from libjitsi_tpu.transform.srtp import kernel

AXIS = "streams"

#: The ONLY call sites allowed to use cross-chip collectives, each the
#: explicit giant-conference escape hatch (a single conference larger
#: than one chip's row budget participant-shards and pays its psum).
#: The `mesh-collective` jitlint checker reads this list; adding a
#: collective anywhere else in mesh/ fails the lint gate.
SANCTIONED_COLLECTIVE_SITES: Tuple[Tuple[str, str], ...] = (
    ("libjitsi_tpu/mesh/sharded.py", "sharded_mix_minus"),
    ("libjitsi_tpu/mesh/sharded.py", "sharded_mix_minus_2d"),
    ("libjitsi_tpu/mesh/sharded.py", "sharded_media_step"),
    # the broadcast bus: one tiny [n_conf, F] psum per tick fans the
    # speaker-shard mix to every listener shard (mesh/hierarchy.py) —
    # the hierarchical replacement for participant-sharding a
    # broadcast-scale conference
    ("libjitsi_tpu/mesh/hierarchy.py", "broadcast_bus_fanout"),
)

#: participant counts a conference is padded to for cost/warmup
#: purposes (matches the bridge's size-class discipline: shapes the
#: device sees are class shapes, so cost should be class cost)
SIZE_CLASSES: Tuple[int, ...] = (4, 8, 16, 32, 64, 128, 256)


def size_class(n: int) -> int:
    """Round a participant count up to its size class (the shape the
    device actually pays for)."""
    n = int(n)
    for c in SIZE_CLASSES:
        if n <= c:
            return c
    return n  # giant conference: costed at its true size


@dataclass(frozen=True)
class PlacementMove:
    """One rebalance decision: move `conf_id` from `src` to `dst`.
    Executed by the lifecycle plane through the commit barrier."""

    conf_id: int
    src: int
    dst: int
    n_participants: int


@dataclass
class _ShardLoad:
    cost: float = 0.0
    rows: int = 0
    confs: int = 0


class ConferencePlacer:
    """Greedy least-loaded whole-conference placement.

    Cost model: a conference of n participants costs
    ``alpha * class(n) + beta * class(n)**2`` — the linear term is the
    per-row crypto/mix work, the quadratic term the fan-out legs
    (every participant receives every other's media), both rounded up
    to the size class because class shapes are what the device
    executes.  Placement is deterministic: identical join order yields
    identical placement (ties break to the lowest shard index).

    Rebalance happens ONLY through `plan_rebalance()` — called by the
    lifecycle plane on join/leave, never mid-tick — and only when the
    most-loaded shard exceeds `hysteresis` x the mean (so steady churn
    does not thrash conferences between shards).
    """

    def __init__(self, n_shards: int, rows_per_shard: int = 128,
                 alpha: float = 1.0, beta: float = 1.0 / 64.0,
                 hysteresis: float = 1.3, max_moves: int = 4):
        if n_shards < 1:
            raise ValueError("need at least one shard")
        self.n_shards = int(n_shards)
        self.rows_per_shard = int(rows_per_shard)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.hysteresis = float(hysteresis)
        self.max_moves = int(max_moves)
        self._loads: List[_ShardLoad] = [_ShardLoad()
                                         for _ in range(self.n_shards)]
        self._shard_of: Dict[int, int] = {}
        self._size_of: Dict[int, int] = {}
        # broadcast conferences: conf_id -> {shard: n_listener_rows}.
        # Speaker rows stay in _shard_of/_size_of (home shard, never
        # straddle); listener rows MAY straddle and are costed linearly
        # (fanout-only rows have no mix-minus, so no quadratic term).
        self._bcast_listeners: Dict[int, Dict[int, int]] = {}
        self.placements = 0
        self.rejects = 0
        self.moves_planned = 0
        # bridge-level placement axis (PR 17 cascade): `place` chooses
        # BRIDGES, not just shards, when `enable_bridges` turns the
        # axis on.  Same greedy least-loaded cost model one level up:
        # a conference is homed on one bridge of the cascade, its
        # shard placement is local to that bridge, and failover
        # (`evacuate_bridge`) re-homes a dead bridge's conferences
        # onto the survivors.
        self.n_bridges = 0
        self._bridge_of: Dict[int, int] = {}
        self._bridge_cost: List[float] = []
        self.bridge_placements = 0
        self.bridge_evacuations = 0

    # ------------------------------------------------------------- cost

    #: per-row cost of a fanout-only listener relative to `alpha` — no
    #: mix-minus row, no fan-out legs back into the mix, just one
    #: shared-bus re-protect; linear, never quadratic
    LISTENER_COST: float = 1.0 / 8.0

    def cost(self, n_participants: int) -> float:
        c = size_class(n_participants)
        return self.alpha * c + self.beta * c * c

    def listener_cost(self, n_rows: int) -> float:
        return self.alpha * self.LISTENER_COST * int(n_rows)

    # -------------------------------------------------------- placement

    def shard_of(self, conf_id: int) -> Optional[int]:
        return self._shard_of.get(int(conf_id))

    def size_of(self, conf_id: int) -> int:
        """Placed participant rows (for a broadcast conference: its
        SPEAKER rows; listeners are tracked in `listener_count`)."""
        return self._size_of.get(int(conf_id), 0)

    def conferences_on(self, shard: int) -> List[int]:
        return sorted(c for c, s in self._shard_of.items()
                      if s == int(shard))

    def place(self, conf_id: int, n_participants: int,
              avoid=()) -> Optional[int]:
        """Assign a NEW conference to the least-loaded shard with row
        headroom; returns the shard, or None when no shard can hold it
        (the caller refuses the join with a typed `capacity` reason).
        Shards in `avoid` (e.g. currently burning their error budget)
        are skipped unless they are the only ones with room.
        Re-placing a known conference resizes it in place instead."""
        conf_id = int(conf_id)
        if conf_id in self._shard_of:
            self.resize(conf_id, n_participants)
            return self._shard_of[conf_id]
        n = int(n_participants)
        avoid = {int(a) for a in avoid}
        best = None
        for only_clean in (True, False) if avoid else (False,):
            for s in range(self.n_shards):
                if only_clean and s in avoid:
                    continue
                if self._loads[s].rows + n > self.rows_per_shard:
                    continue
                if (best is None
                        or self._loads[s].cost < self._loads[best].cost):
                    best = s  # strict <: ties stay on the lowest index
            if best is not None:
                break
        if best is None:
            self.rejects += 1
            return None
        self._assign(conf_id, best, n)
        self.placements += 1
        return best

    # -------------------------------------------------- bridge axis
    def enable_bridges(self, n_bridges: int) -> None:
        """Turn on the bridge-level placement axis: conferences are
        homed on one of `n_bridges` cascaded bridges before (and
        independently of) their shard placement on that bridge."""
        if n_bridges < 1:
            raise ValueError("need at least one bridge")
        self.n_bridges = int(n_bridges)
        self._bridge_cost = [0.0] * self.n_bridges

    def bridge_of(self, conf_id: int) -> Optional[int]:
        return self._bridge_of.get(int(conf_id))

    def place_bridge(self, conf_id: int, n_participants: int,
                     avoid=()) -> Optional[int]:
        """Home a NEW conference on the least-loaded bridge of the
        cascade (same cost model as shard placement, one level up).
        Bridges in `avoid` — dead peers, burning error budgets — are
        skipped unless no other bridge exists.  Re-placing a known
        conference returns its current home."""
        if self.n_bridges < 1:
            raise RuntimeError("bridge axis not enabled")
        conf_id = int(conf_id)
        if conf_id in self._bridge_of:
            return self._bridge_of[conf_id]
        c = self.cost(n_participants)
        avoid = {int(a) for a in avoid}
        best = None
        for only_clean in (True, False) if avoid else (False,):
            for b in range(self.n_bridges):
                if only_clean and b in avoid:
                    continue
                if (best is None
                        or self._bridge_cost[b] < self._bridge_cost[best]):
                    best = b
            if best is not None:
                break
        self._bridge_of[conf_id] = best
        self._bridge_cost[best] += c
        self.bridge_placements += 1
        return best

    def adopt_bridge(self, conf_id: int, bridge: int,
                     n_participants: int) -> None:
        """Forced re-homing (failover adoption): the survivor takes a
        dead peer's conference regardless of load."""
        conf_id = int(conf_id)
        prev = self._bridge_of.get(conf_id)
        c = self.cost(n_participants)
        if prev is not None:
            self._bridge_cost[prev] = max(
                0.0, self._bridge_cost[prev] - c)
        self._bridge_of[conf_id] = int(bridge)
        self._bridge_cost[int(bridge)] += c

    def evacuate_bridge(self, bridge: int) -> List[int]:
        """A bridge died: un-home its conferences and return them (the
        failover plane re-places each via `adopt_bridge` as adoption
        commits — never implicitly, so a refused adoption leaves the
        conference un-homed and retryable, not torn)."""
        bridge = int(bridge)
        out = sorted(c for c, b in self._bridge_of.items()
                     if b == bridge)
        for c in out:
            del self._bridge_of[c]
        if bridge < len(self._bridge_cost):
            self._bridge_cost[bridge] = 0.0
        self.bridge_evacuations += 1
        return out

    def release_bridge(self, conf_id: int,
                       n_participants: int = 0) -> None:
        conf_id = int(conf_id)
        b = self._bridge_of.pop(conf_id, None)
        if b is not None and n_participants:
            self._bridge_cost[b] = max(
                0.0, self._bridge_cost[b] - self.cost(n_participants))

    def bridge_loads(self) -> List[float]:
        return list(self._bridge_cost)

    def rebuild(self, assignments, broadcast=()) -> None:
        """Reset accounting to match reality (checkpoint recovery: the
        restored bridge's rows are authoritative, not whatever the
        placer believed before the kill).  `assignments` iterates
        (conf_id, shard, n_participants); `broadcast` iterates
        (conf_id, {shard: n_listener_rows}) for the listener legs of
        broadcast conferences (their speaker rows ride
        `assignments`)."""
        self._loads = [_ShardLoad() for _ in range(self.n_shards)]
        self._shard_of.clear()
        self._size_of.clear()
        self._bcast_listeners.clear()
        for conf_id, shard, n in assignments:
            self._assign(int(conf_id), int(shard), int(n))
        for conf_id, per in broadcast:
            self._bcast_listeners[int(conf_id)] = {}
            for shard, n in per.items():
                p = self._bcast_listeners[int(conf_id)]
                p[int(shard)] = int(n)
                ld = self._loads[int(shard)]
                ld.cost += self.listener_cost(int(n))
                ld.rows += int(n)

    def _assign(self, conf_id: int, shard: int, n: int) -> None:
        self._shard_of[conf_id] = shard
        self._size_of[conf_id] = n
        ld = self._loads[shard]
        ld.cost += self.cost(n)
        ld.rows += n
        ld.confs += 1

    def resize(self, conf_id: int, n_participants: int) -> None:
        """A participant joined/left an existing conference: update the
        shard's accounting (the conference does not move here; a move
        is only ever a `plan_rebalance` decision)."""
        conf_id = int(conf_id)
        shard = self._shard_of[conf_id]
        old = self._size_of[conf_id]
        new = int(n_participants)
        ld = self._loads[shard]
        ld.cost += self.cost(new) - self.cost(old)
        ld.rows += new - old
        self._size_of[conf_id] = new

    def try_grow(self, conf_id: int, delta: int = 1) -> bool:
        """Admit `delta` more participants into a placed conference if
        its shard has row headroom; False = the join must be refused
        (the conference cannot straddle onto another shard)."""
        conf_id = int(conf_id)
        shard = self._shard_of[conf_id]
        if self._loads[shard].rows + delta > self.rows_per_shard:
            return False
        self.resize(conf_id, self._size_of[conf_id] + delta)
        return True

    def shrink(self, conf_id: int, delta: int = 1) -> None:
        """A participant left; releases the conference when empty."""
        conf_id = int(conf_id)
        n = self._size_of[conf_id] - delta
        if n <= 0:
            self.release(conf_id)
        else:
            self.resize(conf_id, n)

    def release(self, conf_id: int) -> None:
        conf_id = int(conf_id)
        for shard, n in self._bcast_listeners.pop(conf_id, {}).items():
            ld = self._loads[shard]
            ld.cost -= self.listener_cost(n)
            ld.rows -= n
        shard = self._shard_of.pop(conf_id, None)
        if shard is None:
            return
        n = self._size_of.pop(conf_id)
        ld = self._loads[shard]
        ld.cost -= self.cost(n)
        ld.rows -= n
        ld.confs -= 1

    # -------------------------------------------------------- broadcast

    def place_broadcast(self, conf_id: int, n_speakers: int,
                        n_listeners: int = 0,
                        avoid=()) -> Optional[int]:
        """Place a BROADCAST conference: the speaker rows get a home
        shard exactly like a normal conference (never straddle); the
        `n_listeners` fanout-only rows then spread over ALL shards by
        row headroom.  Returns the home shard, or None when either leg
        cannot be satisfied (nothing is partially placed)."""
        conf_id = int(conf_id)
        if conf_id in self._shard_of:
            raise ValueError(f"conference {conf_id} already placed")
        home = self.place(conf_id, n_speakers, avoid=avoid)
        if home is None:
            return None
        self._bcast_listeners[conf_id] = {}
        for _ in range(int(n_listeners)):
            if self.grow_listeners(conf_id) is None:
                self.release(conf_id)
                self.rejects += 1
                return None
        return home

    def is_broadcast(self, conf_id: int) -> bool:
        return int(conf_id) in self._bcast_listeners

    def listener_shards(self, conf_id: int) -> Dict[int, int]:
        """{shard: resident listener rows} for a broadcast conference."""
        return dict(self._bcast_listeners.get(int(conf_id), {}))

    def listener_count(self, conf_id: int) -> int:
        return sum(self._bcast_listeners.get(int(conf_id), {}).values())

    def grow_listeners(self, conf_id: int, delta: int = 1,
                       avoid=(), shard: Optional[int] = None
                       ) -> Optional[int]:
        """Admit `delta` more fanout-only listener rows onto whichever
        shard has row headroom (least-loaded first, lowest index ties;
        straddling is the point).  `shard` pins a specific shard (a
        demoted speaker's row stays physically where it is).  Returns
        the chosen shard or None when no shard can hold them."""
        conf_id = int(conf_id)
        if conf_id not in self._bcast_listeners:
            raise ValueError(f"conference {conf_id} is not broadcast")
        delta = int(delta)
        avoid = {int(a) for a in avoid}
        best = None
        if shard is not None:
            best = int(shard)
        else:
            for only_clean in (True, False) if avoid else (False,):
                for s in range(self.n_shards):
                    if only_clean and s in avoid:
                        continue
                    if self._loads[s].rows + delta > self.rows_per_shard:
                        continue
                    if (best is None or self._loads[s].cost
                            < self._loads[best].cost):
                        best = s
                if best is not None:
                    break
        if best is None:
            return None
        per = self._bcast_listeners[conf_id]
        per[best] = per.get(best, 0) + delta
        ld = self._loads[best]
        ld.cost += self.listener_cost(delta)
        ld.rows += delta
        return best

    def shrink_listeners(self, conf_id: int, shard: int,
                         delta: int = 1) -> None:
        conf_id, shard = int(conf_id), int(shard)
        per = self._bcast_listeners[conf_id]
        n = per[shard] - int(delta)
        ld = self._loads[shard]
        ld.cost -= self.listener_cost(int(delta))
        ld.rows -= int(delta)
        if n <= 0:
            del per[shard]
        else:
            per[shard] = n

    # -------------------------------------------------------- rebalance

    def loads(self) -> List[Tuple[float, int, int]]:
        """Per-shard (cost, rows, conferences) — /debug + metrics."""
        return [(ld.cost, ld.rows, ld.confs) for ld in self._loads]

    def shard_utilization(self) -> List[float]:
        """Per-shard row-range fullness in [0, 1] — the capacity
        plane's forecast-exhaustion signal (utils/capacity.py steers
        placement away from shards past its exhaustion fraction the
        way `shard_burn` steering avoids burning ones)."""
        if not self.rows_per_shard:
            return [0.0] * self.n_shards
        return [ld.rows / self.rows_per_shard for ld in self._loads]

    def plan_rebalance(self) -> List[PlacementMove]:
        """Propose up to `max_moves` conference moves that shrink the
        max-shard cost.  Pure planning: accounting updates when the
        caller confirms each move landed (`apply_move`), because a move
        is a staged lifecycle event that can still roll back."""
        moves: List[PlacementMove] = []
        # plan against a scratch copy so multi-move plans compose
        cost = [ld.cost for ld in self._loads]
        rows = [ld.rows for ld in self._loads]
        placed = dict(self._shard_of)
        mean = sum(cost) / self.n_shards
        for _ in range(self.max_moves):
            hot = max(range(self.n_shards), key=lambda s: (cost[s], -s))
            cold = min(range(self.n_shards), key=lambda s: (cost[s], s))
            if cost[hot] <= self.hysteresis * max(mean, 1e-9):
                break
            # smallest conference on the hot shard that fits the cold
            # one and actually improves the imbalance
            # broadcast conferences never move: their speaker rows are
            # pinned home and their listener rows already straddle
            cands = sorted((self._size_of[c], c)
                           for c, s in placed.items()
                           if s == hot and c not in self._bcast_listeners)
            moved = False
            for n, c in cands:
                if rows[cold] + n > self.rows_per_shard:
                    continue
                delta = self.cost(n)
                if cost[cold] + delta >= cost[hot]:
                    continue  # would just swap who is hot
                moves.append(PlacementMove(c, hot, cold, n))
                cost[hot] -= delta
                rows[hot] -= n
                cost[cold] += delta
                rows[cold] += n
                placed[c] = cold
                moved = True
                break
            if not moved:
                break
        self.moves_planned += len(moves)
        return moves

    def apply_move(self, move: PlacementMove) -> None:
        """Commit one planned move into the accounting (called after
        the lifecycle barrier actually landed the row migration)."""
        conf_id = int(move.conf_id)
        if self._shard_of.get(conf_id) != move.src:
            raise ValueError(f"conference {conf_id} not on shard "
                             f"{move.src}")
        n = self._size_of[conf_id]
        self.release(conf_id)
        self._assign(conf_id, move.dst, n)

    # ---------------------------------------------------- observability

    def register_metrics(self, registry, prefix: str = "placement") -> None:
        registry.register_counters(self, (
            ("placements", "conferences placed onto shards"),
            ("rejects", "placements refused for shard capacity"),
            ("moves_planned", "rebalance moves proposed"),
        ), prefix=prefix)
        registry.register_multi(
            f"{prefix}_shard_cost",
            lambda: [({"shard": str(s)}, ld.cost)
                     for s, ld in enumerate(self._loads)],
            help_="size-class cost model load per shard")
        registry.register_multi(
            f"{prefix}_shard_rows",
            lambda: [({"shard": str(s)}, float(ld.rows))
                     for s, ld in enumerate(self._loads)],
            help_="participant rows resident per shard")
        if self.n_bridges:
            registry.register_counters(self, (
                ("bridge_placements",
                 "conferences homed onto cascade bridges"),
                ("bridge_evacuations",
                 "dead-bridge evacuations (failover)"),
            ), prefix=prefix)
            registry.register_multi(
                f"{prefix}_bridge_cost",
                lambda: [({"bridge": str(b)}, c)
                         for b, c in enumerate(self._bridge_cost)],
                help_="cost-model load per cascade bridge")

    def status(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "rows_per_shard": self.rows_per_shard,
            "shards": [{"shard": s, "cost": ld.cost, "rows": ld.rows,
                        "confs": ld.confs}
                       for s, ld in enumerate(self._loads)],
            "conferences": {str(c): s
                            for c, s in sorted(self._shard_of.items())},
            "bridges": {str(c): b
                        for c, b in sorted(self._bridge_of.items())},
            "broadcast": {str(c): {"home": self._shard_of.get(c),
                                   "listeners": dict(sorted(per.items()))}
                          for c, per in
                          sorted(self._bcast_listeners.items())},
        }


class ShardRowAllocator:
    """Contiguous per-shard row ranges over the dense stream table.

    Shard s owns rows [s*rows_per, (s+1)*rows_per): a conference placed
    on shard s draws all its rows from that range, which is what makes
    the table's device layout shard-resident (row partition boundaries
    coincide with shard boundaries, so `P(AXIS)` sharding of any
    row-indexed array puts a conference's state wholly on its chip).
    """

    def __init__(self, capacity: int, n_shards: int):
        if capacity % n_shards:
            raise ValueError(f"capacity {capacity} not divisible by "
                             f"{n_shards} shards")
        self.capacity = int(capacity)
        self.n_shards = int(n_shards)
        self.rows_per = self.capacity // self.n_shards
        # descending free stacks: pop() hands out lowest row first
        self._free: List[List[int]] = [
            list(range((s + 1) * self.rows_per - 1,
                       s * self.rows_per - 1, -1))
            for s in range(self.n_shards)]

    def shard_of_row(self, sid: int) -> int:
        return int(sid) // self.rows_per

    def free_rows(self, shard: int) -> int:
        return len(self._free[int(shard)])

    def alloc_many(self, shard: int, k: int) -> List[int]:
        free = self._free[int(shard)]
        if len(free) < k:
            raise RuntimeError(
                f"shard {shard} row range exhausted ({len(free)} free, "
                f"{k} wanted)")
        return [free.pop() for _ in range(int(k))]

    def free_many(self, sids: Sequence[int]) -> None:
        for sid in sids:
            sid = int(sid)
            self._free[self.shard_of_row(sid)].append(sid)
            self._free[self.shard_of_row(sid)].sort(reverse=True)

    def reserve(self, sids: Sequence[int]) -> None:
        """Claim specific rows (checkpoint restore)."""
        want = {int(s) for s in sids}
        for s in range(self.n_shards):
            self._free[s] = [r for r in self._free[s] if r not in want]


# ------------------------------------------------------ steady-state tick

def shard_local_mix(mesh: Mesh, n_conf_per_shard: int):
    """Mix-minus for conference-affinity layouts: ZERO collectives.

    pcm int16 [B, F], active bool [B], conf int32 [B] — all sharded on
    the batch axis, `conf` numbering conferences WITHIN each shard
    (0..n_conf_per_shard).  Because a conference never straddles
    shards, the cross-participant sum is a shard-local `segment_sum`
    over the shard's own conference rows; contrast `sharded_mix_minus`
    which pays a cross-chip psum to mix one participant-sharded
    conference.
    """

    def _mix(pcm, active, conf):
        p = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], p, 0)
        seg = jax.ops.segment_sum(contrib, conf,
                                  num_segments=n_conf_per_shard)
        mixed = jnp.clip(seg[conf] - contrib,
                         I16_MIN, I16_MAX).astype(jnp.int16)
        return mixed, audio_levels(p, active)

    row = P(AXIS)
    mat = P(AXIS, None)
    return jax.jit(shard_map(
        _mix, mesh=mesh, in_specs=(mat, row, row),
        out_specs=(mat, row), check_vma=False))


def _affinity_step_body(n_conf_per_shard: int, tag_len: int):
    """The shard-local tick body shared by `affinity_tick` (wrapped in
    `shard_map`) and `affinity_step_ref` (plain jit): unprotect →
    segment-sum mix-minus → protect.  One definition so the mesh tick
    and its single-device parity/benchmark reference cannot drift."""

    def _step(data, length, off, rk, iv, mid, roc, pcm, active, conf,
              odata, olength, ooff, ork, oiv, omid, oroc):
        dec, dec_len, auth_ok = kernel.srtp_unprotect(
            data, length, off, rk, iv, mid, roc, tag_len, True)
        p = pcm.astype(jnp.int32)
        contrib = jnp.where(active[:, None], p, 0)
        seg = jax.ops.segment_sum(contrib, conf,
                                  num_segments=n_conf_per_shard)
        mixed = jnp.clip(seg[conf] - contrib,
                         I16_MIN, I16_MAX).astype(jnp.int16)
        levels = audio_levels(p, active)
        enc, enc_len = kernel.srtp_protect(
            odata, olength, ooff, ork, oiv, omid, oroc, tag_len, True)
        return dec, dec_len, auth_ok, mixed, levels, enc, enc_len

    return _step


def affinity_tick(mesh: Mesh, n_conf_per_shard: int, tag_len: int = 10):
    """The whole steady-state tick under conference affinity: one
    `shard_map` running SRTP-unprotect → shard-local segment-sum
    mix-minus → SRTP-protect, with zero cross-chip collectives (the
    `mesh-collective` jitlint gate proves this stays true).

    Every array is sharded on the batch/row axis; `conf` [B] numbers
    conferences within each shard.  Because each shard's rows are a
    contiguous range owned by `ShardRowAllocator`, the host never
    reshuffles rows to launch this — batches arrive shard-major.

    Successor of `sharded_media_step` (kept as the participant-sharded
    escape hatch): same signature family, minus the psum.
    """
    _step = _affinity_step_body(n_conf_per_shard, tag_len)
    row = P(AXIS)
    mat = P(AXIS, None)
    k3 = P(AXIS, None, None)
    in_specs = (mat, row, row, k3, mat, k3, row,
                mat, row, row,
                mat, row, row, k3, mat, k3, row)
    out_specs = (mat, row, row, mat, row, mat, row)
    return jax.jit(shard_map(
        _step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False))


def affinity_step_ref(n_conf_per_shard: int, tag_len: int = 10):
    """Single-device twin of `affinity_tick`: the SAME shard-local body
    under plain `jax.jit`, no mesh.  Two consumers: parity assertions
    (the mesh tick must be bit-identical to this, shard by shard) and
    the `mesh_agg_pps_ratio` perf-gate scenario, which times one
    shard's workload on one device — legitimate as a per-shard proxy
    precisely because the body has zero collectives, so shards share
    no data and no synchronization."""
    return jax.jit(_affinity_step_body(n_conf_per_shard, tag_len))
