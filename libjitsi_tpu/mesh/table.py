"""ShardedSrtpTable — the production SRTP table running on a device mesh.

VERDICT r3 #2: round 3 sharded raw *kernels* (mesh/sharded.py) but every
product object stayed single-chip.  This table is the product object
sharded: the same `SrtpStreamTable` host control plane (header parse,
RFC 3711 App A index estimation, replay windows, kdr epochs, size-class
bucketing — all of context.py, unchanged) with the DEVICE side row-
partitioned over a `jax.sharding.Mesh`:

- key tables `[S, R, 16]` / `[S, 2, 5]` live sharded on the row axis —
  device d owns rows [d*S/n, (d+1)*S/n); nothing is replicated;
- each batch is grouped by owning device on the host (the control plane
  already knows every packet's row), padded per device to a power-of-two
  lane count, and the crypto runs under `shard_map` with ZERO
  collectives: a packet's key material is chip-local by construction —
  stream-data-parallelism exactly as SURVEY §2.7 prescribes;
- results scatter back to wire order on the host.

Reference: `SRTPTransformer`'s per-SSRC context map scaled by running
more JVMs; here the ONE table spans the mesh and `RTPTranslatorImpl`-
scale fan-outs (SURVEY §3.4) ride the same row partition.

Profile scope: AES-CM / NULL / AES-GCM profiles.  GCM shards via its
PER-ROW form (key schedule + GHASH matrix gathers are chip-local; the
grouped-GHASH grid would span shards and per-row is the measured winner
below ~32k rows anyway).  F8's second schedule stays single-chip for
now — the table raises rather than silently falling back.  SRTCP
(low-rate control traffic) intentionally uses the inherited single-chip
path.

Async caveat: the sharded seams materialize results on the host (the
scatter back to wire order needs the bytes), so `protect_rtp_async`'s
deferred-materialization contract does not overlap launches in mesh
mode — callers that rely on the double-buffering seam must say so and
be refused (ConferenceBridge rejects mesh+pipelined) rather than get a
silent no-op.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libjitsi_tpu.transform.srtp import kernel
from libjitsi_tpu.transform.srtp.context import SrtpStreamTable, _uniform_off
from libjitsi_tpu.transform.srtp.policy import Cipher, SrtpProfile


class ShardedRowsMixin:
    """Shared sharding scaffolding for row-partitioned product objects
    (the SRTP table and the fan-out translator must keep identical
    geometry or same-mesh deployments desync): partition sizes, the
    `_dev`-invalidation mirror, and the sharded device cache."""

    def _init_sharding(self, mesh: Mesh, capacity: int) -> None:
        n_dev = int(mesh.devices.size)
        if capacity % n_dev:
            raise ValueError(f"capacity {capacity} not divisible by "
                             f"{n_dev} mesh devices")
        self.mesh = mesh
        # rows map over EVERY mesh axis: a 1-D "streams" mesh and the
        # 2-D (dcn, streams) multi-host mesh both flatten onto the row
        # partition (device order = row-major over the axes)
        self._axes = tuple(mesh.axis_names)
        self.n_dev = n_dev
        self.rows_per = capacity // n_dev
        self._sh_dev = None
        self._sh_fns: Dict[Tuple, "jax.stages.Wrapped"] = {}

    # the parent classes use `self._dev = None` as their invalidation
    # signal (every key mutator sets it); mirror that onto the sharded
    # copies so they re-place on the next launch after any re-keying
    @property
    def _dev(self):
        return self.__dev

    @_dev.setter
    def _dev(self, value):
        self.__dev = value
        if value is None:
            self._sh_dev = None

    def _sharded_tables(self):
        """Subclass hook: the (round-keys, aux) numpy masters to place."""
        raise NotImplementedError

    def _sharded_device(self):
        if self._sh_dev is None:
            spec = NamedSharding(self.mesh, P(self._axes, None, None))
            rk, aux = self._sharded_tables()
            self._sh_dev = (jax.device_put(rk, spec),
                            jax.device_put(aux, spec))
            if hasattr(self, "_aliased"):
                # the table's COW discipline repoints masters before
                # in-place mutation when this is set
                self._aliased = True
        return self._sh_dev

    def _sharded_launch(self, fn, ids, data, length, off, tail_args):
        """Plan/gather/dispatch/scatter shared by EVERY sharded seam
        (table CM/GCM, translator CM/GCM fan-outs): route rows to their
        owning chips, run `fn` under shard_map, scatter results back to
        wire order.  `tail_args` are the op's trailing per-row arrays
        (iv/roc for CM, iv12 for GCM)."""
        tab_rk, tab_aux = self._sharded_device()
        ids = np.asarray(ids, dtype=np.int64)
        plan = _OwnerPlan(ids, self.capacity, self.rows_per, self.n_dev)
        local = local_rows(plan, ids, self.capacity, self.rows_per,
                           self.n_dev)
        outs = fn(
            tab_rk, tab_aux, jnp.asarray(local),
            jnp.asarray(np.asarray(data)[plan.slot]),
            jnp.asarray(np.asarray(length, dtype=np.int32)[plan.slot]),
            jnp.asarray(np.asarray(off)[plan.slot]),
            *(jnp.asarray(np.asarray(a)[plan.slot]) for a in tail_args))
        d = np.asarray(outs[0])
        d = d.reshape(-1, d.shape[-1])[plan.inv]
        rest = [np.asarray(o).reshape(-1)[plan.inv] for o in outs[1:]]
        return (d, *rest)


def local_rows(plan: "_OwnerPlan", ids: np.ndarray, capacity: int,
               rows_per: int, n_dev: int) -> np.ndarray:
    """Per-lane chip-local row indices for a planned batch: global row
    id minus the owning chip's base offset (lanes holding another
    chip's pad row clamp into range and produce garbage the scatter
    drops).  ONE implementation for every sharded consumer — the table
    and the fan-out translator must agree with _OwnerPlan's layout."""
    s = np.clip(np.asarray(ids, dtype=np.int64), 0, capacity - 1)[
        plan.slot]
    base = (np.arange(n_dev, dtype=np.int64) * rows_per)[:, None]
    return np.clip(s - base, 0, rows_per - 1).astype(np.int32)


class _OwnerPlan:
    """Host-side routing of one batch onto the row partition: `slot`
    [n_dev, per] gathers batch rows into per-device lanes (pads repeat a
    real row — crypto on device is stateless, pads are dropped at
    scatter); `inv` [B] maps each original row to its flat lane."""

    __slots__ = ("slot", "inv", "per")

    def __init__(self, stream: np.ndarray, capacity: int, rows_per: int,
                 n_dev: int):
        s = np.clip(stream, 0, capacity - 1)
        owner = s // rows_per
        order = np.argsort(owner, kind="stable")
        counts = np.bincount(owner, minlength=n_dev)
        top = int(counts.max()) if len(stream) else 1
        self.per = 1 << max(int(top - 1).bit_length(), 2)  # pow2, >= 4
        self.slot = np.zeros((n_dev, self.per), dtype=np.int64)
        self.inv = np.empty(len(stream), dtype=np.int64)
        fallback = order[0] if len(order) else 0
        pos = 0
        for d in range(n_dev):
            rows = order[pos:pos + counts[d]]
            pos += counts[d]
            if len(rows):
                self.slot[d, :len(rows)] = rows
                self.slot[d, len(rows):] = rows[0]
                self.inv[rows] = d * self.per + np.arange(len(rows))
            else:
                self.slot[d, :] = fallback


class ShardedSrtpTable(ShardedRowsMixin, SrtpStreamTable):
    """`SrtpStreamTable` whose RTP crypto runs sharded over a mesh."""

    def __init__(self, capacity: int, mesh: Mesh,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80):
        if profile.policy.cipher not in (Cipher.AES_CM, Cipher.NULL,
                                         Cipher.AES_GCM):
            raise ValueError(
                f"ShardedSrtpTable supports AES-CM/NULL/AES-GCM "
                f"profiles; {profile.value} stays single-chip for now")
        self._init_sharding(mesh, capacity)
        super().__init__(capacity, profile)

    def _sharded_tables(self):
        return (self._rk_rtp,
                self._gm_rtp if self._gcm else self._mid_rtp)

    @classmethod
    def restore(cls, snap: dict, mesh: Mesh) -> "ShardedSrtpTable":
        """Resume a snapshot as a MESH table (a checkpointed mesh
        deployment must come back sharded, not silently single-chip)."""
        from libjitsi_tpu.transform.srtp.policy import SrtpProfile

        t = cls(len(snap["active"]), mesh,
                SrtpProfile(snap["profile"]))
        t._load_state(snap)
        return t

    def warmup(self, max_batch: int, off_const=12) -> None:
        """Pre-compile the shard_map protect/unprotect ladder so live
        ticks never absorb an XLA compile (the same discipline as
        AudioMixer's setup-time warmup): lane counts are power-of-two
        padded and bounded by the BATCH size (worst-case skew parks a
        whole batch on one chip), so the pow2 ladder up to `max_batch`
        covers every lane shape a batch that size can produce for the
        given payload offset.  Other offsets (rare: header extensions
        vary per batch) still compile lazily, like the size-class
        bucketing elsewhere.  Called by ConferenceBridge.warmup();
        standalone deployments call it before going live."""
        tab_rk, tab_aux = self._sharded_device()
        gcm = self._gcm
        ops = ("gcm_protect", "gcm_unprotect") if gcm \
            else ("protect", "unprotect")
        lanes = 4
        top = max(4, max_batch)
        while True:
            for op in ops:
                fn = self._shard_fn(op, self.policy.auth_tag_len,
                                    self.policy.cipher != Cipher.NULL,
                                    off_const)
                shape = (self.n_dev, lanes)
                args = [tab_rk, tab_aux,
                        jnp.zeros(shape, jnp.int32),
                        jnp.zeros(shape + (256,), jnp.uint8),
                        jnp.full(shape, 64, jnp.int32),
                        jnp.full(shape, off_const, jnp.int32)]
                if gcm:
                    args.append(jnp.zeros(shape + (12,), jnp.uint8))
                else:
                    args += [jnp.zeros(shape + (16,), jnp.uint8),
                             jnp.zeros(shape, jnp.uint32)]
                jax.block_until_ready(fn(*args))
            if lanes >= top:
                break
            lanes *= 2

    # ------------------------------------------------------- sharded seams
    def _run_sharded(self, op: str, stream, batch, hdr, length,
                     tail_args):
        off_const = _uniform_off(hdr.payload_off, batch.capacity)
        fn = self._shard_fn(op, self.policy.auth_tag_len,
                            self.policy.cipher != Cipher.NULL, off_const)
        return self._sharded_launch(fn, stream, batch.data, length,
                                    hdr.payload_off, tail_args)

    @staticmethod
    def _roc32(v) -> np.ndarray:
        return (np.asarray(v, dtype=np.uint64)
                & 0xFFFFFFFF).astype(np.uint32)

    def _cm_rtp_protect_call(self, stream, batch, hdr, iv, v):
        data, olen = self._run_sharded("protect", stream, batch, hdr,
                                       batch.length, [iv, self._roc32(v)])
        return data, olen.astype(np.int32)

    def _cm_rtp_unprotect_call(self, stream, batch, hdr, iv, v, length):
        data, mlen, auth_ok = self._run_sharded(
            "unprotect", stream, batch, hdr, length,
            [iv, self._roc32(v)])
        return data, mlen.astype(np.int32), auth_ok

    # ----------------------------------------------------- GCM (per row)
    def _gcm_rtp_protect_call(self, stream, batch, hdr, iv12):
        """Sharded AEAD: the PER-ROW form is row-local (key schedule +
        GHASH matrix gather with chip-local indices), so it shards like
        CM with zero collectives.  The grouped-GHASH form needs its
        grid built per shard — future work; per-row is the measured
        winner below ~32k rows anyway (BASELINE round-4 crossover)."""
        data, olen = self._run_sharded("gcm_protect", stream, batch,
                                       hdr, batch.length, [iv12])
        return data, olen.astype(np.int32)

    def _gcm_rtp_unprotect_call(self, stream, batch, hdr, iv12, length):
        data, mlen, auth_ok = self._run_sharded(
            "gcm_unprotect", stream, batch, hdr, length, [iv12])
        return data, mlen.astype(np.int32), auth_ok

    def _shard_fn(self, op: str, tag_len: int, encrypt: bool, off_const):
        if op.startswith("gcm_"):
            # GCM's tag/encrypt are fixed by the kernel: normalize them
            # OUT of the cache key so warmup and the live seams can
            # never build the same program under different keys
            tag_len, encrypt = 0, True
        key = (op, tag_len, encrypt, off_const)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        if op.startswith("gcm_"):
            from libjitsi_tpu.kernels import gcm as gcm_kernel

            gfn = gcm_kernel.gcm_protect if op == "gcm_protect" \
                else gcm_kernel.gcm_unprotect

            def _run(tab_rk, tab_gm, local, data, length, off, iv12):
                out = gfn(data[0], length[0], off[0], tab_rk[local[0]],
                          tab_gm[local[0]], iv12[0],
                          aad_const=off_const)
                return tuple(o[None] for o in out)

            n_out = 2 if op == "gcm_protect" else 3
            fn = jax.jit(jax.shard_map(
                _run, mesh=self.mesh,
                in_specs=(row3, row3, lanes, row3, lanes, lanes, row3),
                out_specs=(row3, lanes) if n_out == 2
                else (row3, lanes, lanes),
                check_vma=False))
            self._sh_fns[key] = fn
            return fn
        kfn = kernel.srtp_protect if op == "protect" \
            else kernel.srtp_unprotect

        def _run(tab_rk, tab_mid, local, data, length, off, iv, roc):
            # per-shard leading axis is 1 (this chip's lane block)
            out = kfn(data[0], length[0], off[0], tab_rk[local[0]],
                      iv[0], tab_mid[local[0]], roc[0], tag_len,
                      encrypt, payload_off_const=off_const)
            return tuple(o[None] for o in out)

        n_out = 2 if op == "protect" else 3
        fn = jax.jit(jax.shard_map(
            _run, mesh=self.mesh,
            in_specs=(row3, row3, lanes, row3, lanes, lanes, row3, lanes),
            out_specs=(row3, lanes) if n_out == 2 else (row3, lanes, lanes),
            check_vma=False))
        self._sh_fns[key] = fn
        return fn
