"""ShardedSrtpTable — the production SRTP table running on a device mesh.

VERDICT r3 #2: round 3 sharded raw *kernels* (mesh/sharded.py) but every
product object stayed single-chip.  This table is the product object
sharded: the same `SrtpStreamTable` host control plane (header parse,
RFC 3711 App A index estimation, replay windows, kdr epochs, size-class
bucketing — all of context.py, unchanged) with the DEVICE side row-
partitioned over a `jax.sharding.Mesh`:

- key tables `[S, R, 16]` / `[S, 2, 5]` / `[S, 128, 128]` live sharded
  on the row axis — device d owns rows [d*S/n, (d+1)*S/n); nothing is
  replicated;
- each batch is grouped by owning device on the host (the control plane
  already knows every packet's row), padded per device to a power-of-two
  lane count, and the crypto runs under `shard_map` with ZERO
  collectives: a packet's key material is chip-local by construction —
  stream-data-parallelism exactly as SURVEY §2.7 prescribes;
- results stay DEVICE-RESIDENT in lane layout until materialized: the
  scatter back to wire order is deferred (`_LazyArray`), so
  `protect_rtp_async` keeps its launch-overlap contract in mesh mode
  and the bridges compose `mesh=...` with `pipelined=True`
  (VERDICT r4 #2 — the 8-chip deployment is exactly the one that needs
  launch overlap).

Reference: `SRTPTransformer`'s per-SSRC context map scaled by running
more JVMs; here the ONE table spans the mesh and `RTPTranslatorImpl`-
scale fan-outs (SURVEY §3.4) ride the same row partition.

Profile scope: ALL four cipher modes shard (VERDICT r4 #6).  AES-CM /
NULL ride the two-table seam; AES-F8's second key schedule is one more
`[S, R, 16]` tensor on the same row partition; AES-GCM shards both its
per-row form AND the grouped-GHASH form (per-device group grids —
picked per shape by `kernels.registry` measurement, same doctrine as
the single-chip table).  SRTCP runs sharded on the RTCP key tables —
control traffic must not silently hop to a single-chip path.
"""

from __future__ import annotations

import weakref
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from libjitsi_tpu.mesh.compat import shard_map

from libjitsi_tpu.kernels import registry as _registry
from libjitsi_tpu.transform.srtp import kernel
from libjitsi_tpu.transform.srtp.context import SrtpStreamTable, _uniform_off
from libjitsi_tpu.transform.srtp.policy import Cipher, SrtpProfile


class _LazyArray:
    """Deferred scatter-to-wire-order of one sharded-launch output.

    Holds the device array in `[n_dev, per(, W)]` lane layout plus the
    plan's inverse map; the D2H transfer and host scatter happen on
    first materialization (`np.asarray`, `block_until_ready`, or
    `astype` of an already-materialized value).  This deferral is what
    lets `protect_rtp_async`/`translate_async` overlap launches in mesh
    mode: `PendingProtect`/`PendingTranslate` hold these until
    `.result()` while the next batch's plan/dispatch proceeds.
    """

    __slots__ = ("_dev", "_inv", "_dtype", "_np")

    def __init__(self, dev, inv, dtype=None):
        self._dev, self._inv, self._dtype = dev, inv, dtype
        self._np = None

    def _materialize(self) -> np.ndarray:
        if self._np is None:
            a = np.asarray(self._dev)
            a = a.reshape(-1, *a.shape[2:]) if a.ndim > 1 else a
            if self._inv is not None:   # None: affine plan, wire order
                a = a[self._inv]
            if self._dtype is not None:
                a = a.astype(self._dtype)
            self._np = a
            self._dev = None
        return self._np

    def astype(self, dtype):
        if self._np is not None:
            return self._np.astype(dtype)
        return _LazyArray(self._dev, self._inv, dtype)

    def block_until_ready(self):
        self._materialize()
        return self

    def __array__(self, dtype=None, copy=None):
        a = self._materialize()
        if dtype is not None and a.dtype != np.dtype(dtype):
            a = a.astype(dtype)
        return a


class ShardedRowsMixin:
    """Shared sharding scaffolding for row-partitioned product objects
    (the SRTP table and the fan-out translator must keep identical
    geometry or same-mesh deployments desync): partition sizes, the
    `_dev`-invalidation mirror, and the sharded device cache (one entry
    per named table group — "rtp", "rtcp")."""

    def _init_sharding(self, mesh: Mesh, capacity: int) -> None:
        n_dev = int(mesh.devices.size)
        if capacity % n_dev:
            raise ValueError(f"capacity {capacity} not divisible by "
                             f"{n_dev} mesh devices")
        self.mesh = mesh
        # rows map over EVERY mesh axis: a 1-D "streams" mesh and the
        # 2-D (dcn, streams) multi-host mesh both flatten onto the row
        # partition (device order = row-major over the axes)
        self._axes = tuple(mesh.axis_names)
        self.n_dev = n_dev
        self.rows_per = capacity // n_dev
        self._sh_dev: Dict[str, Tuple] = {}
        self._sh_fns: Dict[Tuple, "jax.stages.Wrapped"] = {}

    # the parent classes use `self._dev = None` as their invalidation
    # signal (every key mutator sets it); mirror that onto the sharded
    # copies so they re-place on the next launch after any re-keying
    @property
    def _dev(self):
        return self.__dev

    @_dev.setter
    def _dev(self, value):
        self.__dev = value
        if value is None:
            self._sh_dev = {}

    def _sharded_tables(self, group: str):
        """Subclass hook: the numpy master tensors to place for a named
        group ("rtp"/"rtcp"), all `[S, ...]` row-major."""
        raise NotImplementedError

    def _sharded_device(self, group: str = "rtp") -> Tuple:
        got = self._sh_dev.get(group)
        if got is None:
            spec = NamedSharding(self.mesh, P(self._axes, None, None))
            got = tuple(jax.device_put(t, spec)
                        for t in self._sharded_tables(group))
            self._sh_dev[group] = got
            if hasattr(self, "_aliased"):
                # the table's COW discipline repoints masters before
                # in-place mutation when this is set
                self._aliased = True
        return got

    def _sharded_launch(self, fn, tabs, ids, lane_args, extra_args=(),
                        plan=None):
        """Plan/gather/dispatch shared by EVERY sharded seam (table
        CM/F8/GCM/SRTCP, translator fan-outs): route rows to their
        owning chips, run `fn` under shard_map, and return one
        `_LazyArray` per output — the scatter back to wire order is
        DEFERRED until materialization, keeping the async contract.
        `lane_args` are per-row arrays (1-D like length/off/roc or
        N-D like data/iv) routed through the plan; `extra_args` are
        already device-wide arrays passed through as-is (grouped-GCM
        grids, fan-out packet blocks).  Callers that pre-built the
        plan (to derive grids from it) pass it via `plan`.
        """
        ids = np.asarray(ids, dtype=np.int64)
        if plan is None:
            plan = _OwnerPlan(ids, self.capacity, self.rows_per,
                              self.n_dev)
        local = local_rows(plan, ids, self.capacity, self.rows_per,
                           self.n_dev)
        if plan.affine:
            # identity routing: lane gather is a reshape, and the
            # output scatter is skipped entirely (inv=None)
            outs = fn(*tabs, jnp.asarray(local),
                      *(jnp.asarray(np.asarray(a).reshape(
                            plan.slot.shape[0], plan.per,
                            *np.asarray(a).shape[1:]))
                        for a in lane_args),
                      *(jnp.asarray(e) for e in extra_args))
            return tuple(_LazyArray(o, None) for o in outs)
        outs = fn(*tabs, jnp.asarray(local),
                  *(jnp.asarray(np.asarray(a)[plan.slot])
                    for a in lane_args),
                  *(jnp.asarray(e) for e in extra_args))
        return tuple(_LazyArray(o, plan.inv) for o in outs)


def local_rows(plan: "_OwnerPlan", ids: np.ndarray, capacity: int,
               rows_per: int, n_dev: int) -> np.ndarray:
    """Per-lane chip-local row indices for a planned batch: global row
    id minus the owning chip's base offset (lanes holding another
    chip's pad row clamp into range and produce garbage the scatter
    drops).  ONE implementation for every sharded consumer — the table
    and the fan-out translator must agree with _OwnerPlan's layout."""
    s = np.clip(np.asarray(ids, dtype=np.int64), 0, capacity - 1)[
        plan.slot]
    base = (np.arange(n_dev, dtype=np.int64) * rows_per)[:, None]
    return np.clip(s - base, 0, rows_per - 1).astype(np.int32)


class _OwnerPlan:
    """Host-side routing of one batch onto the row partition: `slot`
    [n_dev, per] gathers batch rows into per-device lanes (pads repeat a
    real row — crypto on device is stateless, pads are dropped at
    scatter); `inv` [B] maps each original row to its flat lane.
    Fully vectorized — no Python loop over devices (VERDICT r4 weak #6:
    the loop showed at 64k-batch x 8-device shapes)."""

    __slots__ = ("slot", "inv", "per", "affine")

    def __init__(self, stream: np.ndarray, capacity: int, rows_per: int,
                 n_dev: int):
        s = np.clip(stream, 0, capacity - 1)
        n = len(s)
        owner = s // rows_per
        # Affine fast path (conference-affinity placement's steady
        # state, mesh/placement.py): the batch already arrives
        # shard-major with equal per-shard counts — rows are drawn from
        # contiguous per-shard ranges, so no argsort, no scattered
        # writes, and crucially NO pad-lane skew (random routing pads
        # every device to the hottest device's pow2 lane count, which
        # is where the mesh's 2x-slowdown came from).  Identity
        # routing: slot is a reshape, inv is arange.
        cnt = n // n_dev if n_dev else 0
        self.affine = bool(
            n and cnt >= 4 and n == cnt * n_dev
            and (cnt & (cnt - 1)) == 0
            and np.array_equal(owner,
                               np.repeat(np.arange(n_dev), cnt)))
        if self.affine:
            self.per = cnt
            self.slot = np.arange(n, dtype=np.int64).reshape(n_dev, cnt)
            self.inv = np.arange(n, dtype=np.int64)
            return
        order = np.argsort(owner, kind="stable")
        counts = np.bincount(owner, minlength=n_dev)
        top = int(counts.max()) if n else 1
        self.per = per = 1 << max(int(top - 1).bit_length(), 2)
        starts = np.concatenate(([0], np.cumsum(counts)))
        dev_sorted = owner[order]
        lane = np.arange(n, dtype=np.int64) - starts[dev_sorted]
        # pads repeat each device's FIRST routed row; devices with no
        # rows fall back to the batch's first row overall
        first = np.full(n_dev, order[0] if n else 0, dtype=np.int64)
        has = counts > 0
        first[has] = order[starts[:-1][has]]
        self.slot = np.broadcast_to(first[:, None], (n_dev, per)).copy()
        self.slot[dev_sorted, lane] = order
        self.inv = np.empty(n, dtype=np.int64)
        self.inv[order] = dev_sorted * per + lane


def mesh_gcm_grid(local: np.ndarray):
    """Per-device grouped-GHASH grids over an `_OwnerPlan`'s lane
    layout — the mesh form of `context._gcm_grid` (VERDICT r4 #4: the
    sharded table must not be pinned to the per-row form the round-4
    data showed losing 2.3x at 64k rows).

    `local` [n_dev, per] are chip-local key rows per lane.  Returns
    (grid [n_dev, Gp, Pp] int32 lane-index-or-minus-one, us [n_dev, Gp]
    int32 local stream rows, inv [n_dev, per] int32) with Gp/Pp shared
    pow2 shapes across devices, or None when structurally unusable
    (tiny lanes, all-distinct streams, or skew so heavy the padded grid
    would more than double the GHASH work — same guards as the
    single-chip grid).
    """
    n_dev, per = local.shape
    if per < 8:
        return None
    order2 = np.argsort(local, axis=1, kind="stable")
    ss = np.take_along_axis(local, order2, 1)
    firsts = np.ones_like(ss, dtype=bool)
    firsts[:, 1:] = ss[:, 1:] != ss[:, :-1]
    grp = np.cumsum(firsts, axis=1) - 1
    g = int(grp[:, -1].max()) + 1
    if g == per:      # every lane its own stream: grouped ≡ per-row
        return None
    pos = np.arange(per, dtype=np.int64)[None, :]
    fpos = np.maximum.accumulate(np.where(firsts, pos, 0), axis=1)
    rank = pos - fpos
    p = int(rank.max()) + 1
    gp = 1 << max(g - 1, 0).bit_length()
    pp = 1 << max(p - 1, 0).bit_length()
    if gp * pp > 2 * per:
        return None
    d_idx = np.repeat(np.arange(n_dev), per)
    grid = np.full((n_dev, gp, pp), -1, dtype=np.int32)
    grid[d_idx, grp.ravel(), rank.ravel()] = \
        order2.ravel().astype(np.int32)
    us = np.zeros((n_dev, gp), dtype=np.int32)
    us[d_idx, grp.ravel()] = ss.ravel().astype(np.int32)
    inv = np.empty((n_dev, per), dtype=np.int32)
    np.put_along_axis(inv, order2, (grp * pp + rank).astype(np.int32), 1)
    return grid, us, inv


class _MeshSeamToken:
    """Registry handle for a mesh table's GCM seam.

    The module-global `kernels.registry` keys its measured choices by
    argument signature; passing the TABLE itself would retain every
    table (and its ~16 MiB GHASH masters) in the registry's choice
    dict forever and force a re-benchmark per instance.  This token
    hashes by GEOMETRY (capacity, mesh size, profile) — tables with
    identical geometry share one measured choice (their shard programs
    are identical), and the weakref lets dead tables be collected.
    """

    __slots__ = ("geom", "ref")

    def __init__(self, table: "ShardedSrtpTable"):
        self.geom = (table.capacity, table.n_dev, table.profile.value)
        self.ref = weakref.ref(table)

    def __hash__(self):
        return hash(self.geom)

    def __eq__(self, other):
        return (isinstance(other, _MeshSeamToken)
                and self.geom == other.geom)


# Measured grouped-vs-per-row choice for the MESH table, mirroring the
# single-chip registry pattern (context.py): both providers take the
# full argument list; per_row ignores the grid machinery.  The seam
# token rides in the signature, so choices are per (geometry, batch
# shape) — measured once per deployment geometry, shared by same-shape
# tables (warmup's scratch table pins the live table's choice).

def _mesh_gcm_protect_grouped(token, stream, data, length, off, iv12,
                              off_const):
    return token.ref()._gcm_mesh_launch("gcm_protect_grouped", stream,
                                        data, length, off, iv12,
                                        off_const)


def _mesh_gcm_protect_per_row(token, stream, data, length, off, iv12,
                              off_const):
    return token.ref()._gcm_mesh_launch("gcm_protect", stream, data,
                                        length, off, iv12, off_const)


def _mesh_gcm_unprotect_grouped(token, stream, data, length, off, iv12,
                                off_const):
    return token.ref()._gcm_mesh_launch("gcm_unprotect_grouped", stream,
                                        data, length, off, iv12,
                                        off_const)


def _mesh_gcm_unprotect_per_row(token, stream, data, length, off, iv12,
                                off_const):
    return token.ref()._gcm_mesh_launch("gcm_unprotect", stream, data,
                                        length, off, iv12, off_const)


_registry.register("mesh_gcm_rtp_protect", "grouped",
                   _mesh_gcm_protect_grouped)
_registry.register("mesh_gcm_rtp_protect", "per_row",
                   _mesh_gcm_protect_per_row)
_registry.register("mesh_gcm_rtp_unprotect", "grouped",
                   _mesh_gcm_unprotect_grouped)
_registry.register("mesh_gcm_rtp_unprotect", "per_row",
                   _mesh_gcm_unprotect_per_row)


class ShardedSrtpTable(ShardedRowsMixin, SrtpStreamTable):
    """`SrtpStreamTable` whose RTP *and* RTCP crypto runs sharded."""

    def __init__(self, capacity: int, mesh: Mesh,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80):
        self._init_sharding(mesh, capacity)
        super().__init__(capacity, profile)

    def _sharded_tables(self, group: str):
        if group == "rtp":
            t = [self._rk_rtp,
                 self._gm_rtp if self._gcm else self._mid_rtp]
            if self._f8:
                t.append(self._rk_f8_rtp)
        else:
            t = [self._rk_rtcp,
                 self._gm_rtcp if self._gcm else self._mid_rtcp]
            if self._f8:
                t.append(self._rk_f8_rtcp)
        return tuple(t)

    @classmethod
    def restore(cls, snap: dict, mesh: Mesh) -> "ShardedSrtpTable":
        """Resume a snapshot as a MESH table (a checkpointed mesh
        deployment must come back sharded, not silently single-chip)."""
        from libjitsi_tpu.transform.srtp.policy import SrtpProfile

        t = cls(len(snap["active"]), mesh,
                SrtpProfile(snap["profile"]))
        t._load_state(snap)
        return t

    def warmup(self, max_batch: int, off_const=12,
               capacities=(224, 544)) -> None:
        """Pre-compile the shard_map ladders so live ticks never absorb
        an XLA compile (the same discipline as AudioMixer's setup-time
        warmup): lane counts are power-of-two padded and bounded by the
        BATCH size (worst-case skew parks a whole batch on one chip),
        so the pow2 ladder up to `max_batch` covers every lane shape a
        batch that size can produce — per payload offset AND per
        bucketing capacity class (the defaults are `bucket_by_size`'s
        LENGTH_CLASSES + CLASS_HEADROOM; batches in the terminal
        full-width class, like rare offsets, still compile lazily).
        Covers the RTP ops, the SRTCP programs (sharded since round
        5 — RTCP batches are not size-bucketed, so only the listed
        capacities pre-compile), and for GCM the registry's
        grouped/per-row measurement (advisor r5: the measurement
        compiles both providers and times 12 launches — that must
        happen here, ON THIS table, not on the first live batch).
        Called by ConferenceBridge.warmup(); standalone deployments
        call it before going live."""
        tabs = self._sharded_device("rtp")
        rtcp_tabs = self._sharded_device("rtcp")
        gcm = self._gcm
        encrypt = self.policy.cipher != Cipher.NULL
        tag = self.policy.auth_tag_len
        if gcm:
            ops = ("gcm_protect", "gcm_unprotect")
        elif self._f8:
            ops = ("f8_protect", "f8_unprotect")
        else:
            ops = ("protect", "unprotect")
        for cap in capacities:
            lanes = 4
            top = max(4, max_batch)
            while True:
                for op in ops:
                    fn = self._shard_fn(op, tag, encrypt, off_const)
                    shape = (self.n_dev, lanes)
                    args = list(tabs)
                    args += [jnp.zeros(shape, jnp.int32),
                             jnp.zeros(shape + (cap,), jnp.uint8),
                             jnp.full(shape, 64, jnp.int32),
                             jnp.full(shape, off_const, jnp.int32)]
                    if gcm:
                        args.append(jnp.zeros(shape + (12,), jnp.uint8))
                    else:
                        args += [jnp.zeros(shape + (16,), jnp.uint8),
                                 jnp.zeros(shape, jnp.uint32)]
                    jax.block_until_ready(fn(*args))
                if not gcm and lanes <= 256:
                    # SRTCP ladder (the GCM SRTCP seam reuses the RTP
                    # gcm programs above — same _shard_fn cache key).
                    # Capped at 256 lanes: control traffic is low-rate,
                    # and every ladder rung is a tunnel compile.
                    self._warmup_rtcp(rtcp_tabs, cap, lanes, tag,
                                      encrypt)
                if lanes >= top:
                    break
                lanes *= 2
        if gcm:
            self._warmup_gcm_registry(max_batch, capacities)

    def _warmup_rtcp(self, rtcp_tabs, cap: int, lanes: int, tag: int,
                     encrypt: bool) -> None:
        shape = (self.n_dev, lanes)
        p_fn = self._shard_fn(
            "rtcp_f8_protect" if self._f8 else "rtcp_protect", tag,
            encrypt, None)
        jax.block_until_ready(p_fn(
            *rtcp_tabs, jnp.zeros(shape, jnp.int32),
            jnp.zeros(shape + (cap,), jnp.uint8),
            jnp.full(shape, 64, jnp.int32),
            jnp.zeros(shape + (16,), jnp.uint8),
            jnp.zeros(shape, jnp.int32)))
        u_fn = self._shard_fn(
            "rtcp_f8_unprotect" if self._f8 else "rtcp_unprotect", tag,
            encrypt, None)
        jax.block_until_ready(u_fn(
            *rtcp_tabs, jnp.zeros(shape, jnp.int32),
            jnp.zeros(shape + (cap,), jnp.uint8),
            jnp.full(shape, 64, jnp.int32),
            jnp.zeros(shape + (16,), jnp.uint8)))

    def _warmup_gcm_registry(self, max_batch: int, capacities) -> None:
        """Drive THIS table's GCM registry seams with synthetic args so
        the grouped/per-row compiles and the 12-launch measurement
        happen off the media path and land in THIS table's program
        cache (a scratch table would pin the registry choice via the
        geometry token but leave the live table's jit closures cold —
        advisor r5).  Pure dispatch: these seams touch no host crypto
        state (replay/tx planes live in the callers above them)."""
        from libjitsi_tpu.core.packet import ROW_CLASSES

        rng = np.random.default_rng(0)
        n = max(1, min(self.capacity, 64))
        for cap in capacities:
            for bsz in ROW_CLASSES:
                if bsz > max(ROW_CLASSES[0], max_batch):
                    break
                # heavy stream reuse: the grouped grid must be
                # structurally usable or the measurement would only
                # ever exercise the per-row provider
                stream = np.sort(
                    np.resize(np.arange(n, dtype=np.int64), bsz))
                data = rng.integers(0, 256, (bsz, cap), dtype=np.uint8)
                length = np.full(bsz, 172, np.int32)
                off = np.full(bsz, 12, np.int32)
                iv12 = rng.integers(0, 256, (bsz, 12), dtype=np.uint8)
                for op in ("mesh_gcm_rtp_protect",
                           "mesh_gcm_rtp_unprotect"):
                    outs = _registry.call(op, self._token(), stream,
                                          data, length, off, iv12, 12)
                    jax.block_until_ready(outs)

    # ------------------------------------------------------- sharded seams
    def _run_sharded(self, op: str, stream, batch, hdr, length,
                     tail_args):
        off_const = _uniform_off(hdr.payload_off, batch.capacity)
        fn = self._shard_fn(op, self.policy.auth_tag_len,
                            self.policy.cipher != Cipher.NULL, off_const)
        return self._sharded_launch(
            fn, self._sharded_device("rtp"), stream,
            [batch.data, np.asarray(length, dtype=np.int32),
             hdr.payload_off, *tail_args])

    @staticmethod
    def _roc32(v) -> np.ndarray:
        return (np.asarray(v, dtype=np.uint64)
                & 0xFFFFFFFF).astype(np.uint32)

    def _cm_rtp_protect_call(self, stream, batch, hdr, iv, v):
        data, olen = self._run_sharded("protect", stream, batch, hdr,
                                       batch.length, [iv, self._roc32(v)])
        return data, olen.astype(np.int32)

    def _cm_rtp_unprotect_call(self, stream, batch, hdr, iv, v, length):
        data, mlen, auth_ok = self._run_sharded(
            "unprotect", stream, batch, hdr, length,
            [iv, self._roc32(v)])
        return data, mlen.astype(np.int32), auth_ok

    # ------------------------------------------------------------------ F8
    def _f8_rtp_protect_call(self, stream, batch, hdr, iv, v):
        """Sharded AES-F8: the second key schedule `[S, R, 16]` rides
        the same row partition as the first (VERDICT r4 #6)."""
        data, olen = self._run_sharded("f8_protect", stream, batch, hdr,
                                       batch.length, [iv, self._roc32(v)])
        return data, olen.astype(np.int32)

    def _f8_rtp_unprotect_call(self, stream, batch, hdr, iv, v, length):
        data, mlen, auth_ok = self._run_sharded(
            "f8_unprotect", stream, batch, hdr, length,
            [iv, self._roc32(v)])
        return data, mlen.astype(np.int32), auth_ok

    # ----------------------------------------------------------------- GCM
    def _gcm_rtp_protect_call(self, stream, batch, hdr, iv12):
        """Sharded AEAD: BOTH forms shard — per-row (key schedule +
        GHASH matrix gathers chip-local) and grouped-GHASH (per-device
        group grids, `mesh_gcm_grid`); the winner is picked per shape
        by registry measurement, exactly like the single-chip table
        (VERDICT r4 #4 closed the hardcoded per-row regression)."""
        off_const = _uniform_off(hdr.payload_off, batch.capacity)
        data, olen = _registry.call(
            "mesh_gcm_rtp_protect", self._token(),
            np.asarray(stream, dtype=np.int64), batch.data,
            np.asarray(batch.length, dtype=np.int32), hdr.payload_off,
            np.asarray(iv12), off_const)
        return data, olen.astype(np.int32)

    def _gcm_rtp_unprotect_call(self, stream, batch, hdr, iv12, length):
        off_const = _uniform_off(hdr.payload_off, batch.capacity)
        data, mlen, auth_ok = _registry.call(
            "mesh_gcm_rtp_unprotect", self._token(),
            np.asarray(stream, dtype=np.int64), batch.data,
            np.asarray(length, dtype=np.int32), hdr.payload_off,
            np.asarray(iv12), off_const)
        return data, mlen.astype(np.int32), auth_ok

    def _token(self) -> _MeshSeamToken:
        tok = getattr(self, "_seam_token", None)
        if tok is None:
            tok = self._seam_token = _MeshSeamToken(self)
        return tok

    def _gcm_mesh_launch(self, op: str, stream, data, length, off, iv12,
                         off_const):
        """One sharded GCM launch, per-row or grouped.  The grouped
        form builds per-device group grids from the owner plan; when no
        usable grid exists (skew/all-distinct) it degrades to the
        per-row program — the registry then just measures a tie."""
        fn = self._shard_fn(op, 0, True, off_const)
        tabs = self._sharded_device("rtp")
        if not op.endswith("_grouped"):
            return self._sharded_launch(
                fn, tabs, stream, [data, length, off, iv12])
        ids = np.asarray(stream, dtype=np.int64)
        plan = _OwnerPlan(ids, self.capacity, self.rows_per, self.n_dev)
        local = local_rows(plan, ids, self.capacity, self.rows_per,
                           self.n_dev)
        gg = mesh_gcm_grid(local)
        if gg is None:
            return self._sharded_launch(
                self._shard_fn(op[: -len("_grouped")], 0, True,
                               off_const),
                tabs, stream, [data, length, off, iv12], plan=plan)
        return self._sharded_launch(fn, tabs, stream,
                                    [data, length, off, iv12],
                                    extra_args=gg, plan=plan)

    # ----------------------------------------------------------- SRTCP
    def _rtcp_protect_call(self, stream, batch, iv, index_word,
                           encrypting: bool, f8: bool = False):
        """Sharded SRTCP protect on the row-partitioned RTCP tables
        (VERDICT r4 #6: a mesh deployment must not silently hop to a
        single-chip path for control traffic)."""
        fn = self._shard_fn("rtcp_f8_protect" if f8 else "rtcp_protect",
                            self.policy.auth_tag_len, encrypting, None)
        return self._sharded_launch(
            fn, self._sharded_device("rtcp"), stream,
            [batch.data, np.asarray(batch.length, dtype=np.int32), iv,
             np.asarray(index_word)])

    def _rtcp_unprotect_call(self, stream, batch, iv, length,
                             encrypting: bool, f8: bool = False):
        fn = self._shard_fn(
            "rtcp_f8_unprotect" if f8 else "rtcp_unprotect",
            self.policy.auth_tag_len, encrypting, None)
        return self._sharded_launch(
            fn, self._sharded_device("rtcp"), stream,
            [batch.data, np.asarray(length, dtype=np.int32), iv])

    def _gcm_rtcp_seal_call(self, stream, kin, klen, iv12):
        """Sharded AEAD SRTCP: the RTP gcm shard program re-runs on the
        RTCP table group (same shapes, aad pinned at 12 by the host
        layout shuffle in context.py)."""
        n = len(np.asarray(klen))
        return self._sharded_launch(
            self._shard_fn("gcm_protect", 0, True, 12),
            self._sharded_device("rtcp"), stream,
            [kin, np.asarray(klen, dtype=np.int32),
             np.full(n, 12, np.int32), iv12])

    def _gcm_rtcp_open_call(self, stream, kin, klen, iv12):
        n = len(np.asarray(klen))
        return self._sharded_launch(
            self._shard_fn("gcm_unprotect", 0, True, 12),
            self._sharded_device("rtcp"), stream,
            [kin, np.asarray(klen, dtype=np.int32),
             np.full(n, 12, np.int32), iv12])

    # ------------------------------------------------------- shard programs
    def _shard_fn(self, op: str, tag_len: int, encrypt: bool, off_const):
        if op.startswith("gcm_"):
            # GCM's tag/encrypt are fixed by the kernel: normalize them
            # OUT of the cache key so warmup and the live seams can
            # never build the same program under different keys
            tag_len, encrypt = 0, True
        key = (op, tag_len, encrypt, off_const)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        f8 = op.startswith("f8_") or op.startswith("rtcp_f8_")
        if op.startswith("gcm_"):
            fn = self._build_gcm_fn(op, off_const, row3, lanes)
        elif op.startswith("rtcp_"):
            fn = self._build_rtcp_fn(op, tag_len, encrypt, f8, row3,
                                     lanes)
        else:
            fn = self._build_rtp_fn(op, tag_len, encrypt, f8, off_const,
                                    row3, lanes)
        self._sh_fns[key] = fn
        return fn

    def _build_rtp_fn(self, op, tag_len, encrypt, f8, off_const, row3,
                      lanes):
        kfn = kernel.srtp_protect if op.endswith("protect") and not \
            op.endswith("unprotect") else kernel.srtp_unprotect
        if f8:
            def _run(tab_rk, tab_mid, tab_f8, local, data, length, off,
                     iv, roc):
                out = kfn(data[0], length[0], off[0], tab_rk[local[0]],
                          iv[0], tab_mid[local[0]], roc[0], tag_len,
                          encrypt, payload_off_const=off_const,
                          f8_round_keys=tab_f8[local[0]])
                return tuple(o[None] for o in out)
            in_specs = (row3, row3, row3, lanes, row3, lanes, lanes,
                        row3, lanes)
        else:
            def _run(tab_rk, tab_mid, local, data, length, off, iv, roc):
                # per-shard leading axis is 1 (this chip's lane block)
                out = kfn(data[0], length[0], off[0], tab_rk[local[0]],
                          iv[0], tab_mid[local[0]], roc[0], tag_len,
                          encrypt, payload_off_const=off_const)
                return tuple(o[None] for o in out)
            in_specs = (row3, row3, lanes, row3, lanes, lanes, row3,
                        lanes)
        n_out = 2 if "unprotect" not in op else 3
        return jax.jit(shard_map(
            _run, mesh=self.mesh, in_specs=in_specs,
            out_specs=(row3, lanes) if n_out == 2
            else (row3, lanes, lanes), check_vma=False))

    def _build_gcm_fn(self, op, off_const, row3, lanes):
        from libjitsi_tpu.kernels import gcm as gcm_kernel

        grouped = op.endswith("_grouped")
        base = op[: -len("_grouped")] if grouped else op
        unprot = base == "gcm_unprotect"
        if grouped:
            gfn = gcm_kernel.gcm_protect_grouped if not unprot \
                else gcm_kernel.gcm_unprotect_grouped

            def _run(tab_rk, tab_gm, local, data, length, off, iv12,
                     grid, us, inv):
                out = gfn(data[0], length[0], off[0], tab_rk[local[0]],
                          tab_gm[us[0]], iv12[0], grid[0], inv[0],
                          aad_const=off_const)
                return tuple(o[None] for o in out)

            in_specs = (row3, row3, lanes, row3, lanes, lanes, row3,
                        row3, lanes, lanes)
        else:
            gfn = gcm_kernel.gcm_protect if not unprot \
                else gcm_kernel.gcm_unprotect

            def _run(tab_rk, tab_gm, local, data, length, off, iv12):
                out = gfn(data[0], length[0], off[0], tab_rk[local[0]],
                          tab_gm[local[0]], iv12[0],
                          aad_const=off_const)
                return tuple(o[None] for o in out)

            in_specs = (row3, row3, lanes, row3, lanes, lanes, row3)
        return jax.jit(shard_map(
            _run, mesh=self.mesh, in_specs=in_specs,
            out_specs=(row3, lanes, lanes) if unprot else (row3, lanes),
            check_vma=False))

    def _build_rtcp_fn(self, op, tag_len, encrypt, f8, row3, lanes):
        unprot = op.endswith("unprotect")
        if unprot:
            if f8:
                def _run(tab_rk, tab_mid, tab_f8, local, data, length,
                         iv):
                    out = kernel.srtcp_unprotect(
                        data[0], length[0], tab_rk[local[0]], iv[0],
                        tab_mid[local[0]], tag_len, encrypt,
                        f8_round_keys=tab_f8[local[0]])
                    return tuple(o[None] for o in out)
                in_specs = (row3, row3, row3, lanes, row3, lanes, row3)
            else:
                def _run(tab_rk, tab_mid, local, data, length, iv):
                    out = kernel.srtcp_unprotect(
                        data[0], length[0], tab_rk[local[0]], iv[0],
                        tab_mid[local[0]], tag_len, encrypt)
                    return tuple(o[None] for o in out)
                in_specs = (row3, row3, lanes, row3, lanes, row3)
            out_specs = (row3, lanes, lanes, lanes, lanes)
        else:
            if f8:
                def _run(tab_rk, tab_mid, tab_f8, local, data, length,
                         iv, word):
                    out = kernel.srtcp_protect(
                        data[0], length[0], tab_rk[local[0]], iv[0],
                        tab_mid[local[0]], word[0], tag_len, encrypt,
                        f8_round_keys=tab_f8[local[0]])
                    return tuple(o[None] for o in out)
                in_specs = (row3, row3, row3, lanes, row3, lanes, row3,
                            lanes)
            else:
                def _run(tab_rk, tab_mid, local, data, length, iv,
                         word):
                    out = kernel.srtcp_protect(
                        data[0], length[0], tab_rk[local[0]], iv[0],
                        tab_mid[local[0]], word[0], tag_len, encrypt)
                    return tuple(o[None] for o in out)
                in_specs = (row3, row3, lanes, row3, lanes, row3, lanes)
            out_specs = (row3, lanes)
        return jax.jit(shard_map(
            _run, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False))
