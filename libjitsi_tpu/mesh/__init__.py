from libjitsi_tpu.mesh.sharded import (  # noqa: F401
    make_media_mesh,
    make_multihost_mesh,
    sharded_bridge_mix,
    sharded_mix_minus,
    sharded_mix_minus_2d,
    sharded_gcm_fanout,
    sharded_srtp_protect,
    sharded_media_step,
)
from libjitsi_tpu.mesh.table import ShardedSrtpTable  # noqa: F401
from libjitsi_tpu.mesh.translator import ShardedRtpTranslator  # noqa: F401
