"""Multi-chip plane.  Default path: conference-affinity sharding
(`placement`) — whole conferences pinned to shards, zero-collective
`affinity_tick` steady state.  The participant-sharded kernels
(`sharded_mix_minus`, `sharded_media_step`) are the explicit
giant-conference escape hatches and pay a cross-chip psum per tick;
the `mesh-collective` lint gate keeps collectives confined to them."""

from libjitsi_tpu.mesh.cascade import (  # noqa: F401
    CascadeTrunk,
    TrunkConfig,
    TrunkRelay,
)
from libjitsi_tpu.mesh.placement import (  # noqa: F401
    SANCTIONED_COLLECTIVE_SITES,
    ConferencePlacer,
    PlacementMove,
    ShardRowAllocator,
    affinity_step_ref,
    affinity_tick,
    shard_local_mix,
    size_class,
)
from libjitsi_tpu.mesh.hierarchy import (  # noqa: F401
    broadcast_bus_fanout,
    broadcast_step_ref,
    listener_fanout_protect,
)
from libjitsi_tpu.mesh.sharded import (  # noqa: F401
    make_media_mesh,
    make_multihost_mesh,
    sharded_bridge_mix,
    sharded_mix_minus,
    sharded_mix_minus_2d,
    sharded_gcm_fanout,
    sharded_srtp_protect,
    sharded_media_step,
)
from libjitsi_tpu.mesh.table import ShardedSrtpTable  # noqa: F401
from libjitsi_tpu.mesh.translator import ShardedRtpTranslator  # noqa: F401
