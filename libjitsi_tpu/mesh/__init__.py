from libjitsi_tpu.mesh.sharded import (  # noqa: F401
    make_media_mesh,
    sharded_mix_minus,
    sharded_srtp_protect,
    sharded_media_step,
)
