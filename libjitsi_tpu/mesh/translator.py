"""ShardedRtpTranslator — the SFU fan-out primitive on a device mesh.

The decrypt-once / re-encrypt-N fan-out (BASELINE config #5, reference
`RTPTranslatorImpl`, SURVEY §3.4) is embarrassingly parallel over the
RECEIVER axis: each output row's key material belongs to exactly one
receiver leg, so partitioning legs across chips makes every key gather
chip-local — zero collectives, the same stream-data-parallel doctrine
as `ShardedSrtpTable` (the packets each chip needs are routed to it by
the host plan, which already expands the (packet × receiver) matrix).

The routing/expansion/IV host plane is `RtpTranslator`'s, unchanged;
only the protect launch seams are overridden.  GCM fan-outs shard BOTH
ways: the general per-row form (each output row's key schedule + GHASH
matrix gather is chip-local), and the full-mesh per-LEG-matrix fast
path, which shards over the LEG axis (`_gcm_uniform_fanout_call` — the
product form of mesh/sharded.py's `sharded_gcm_fanout`); parity tests
pin both against the single-chip translator.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from libjitsi_tpu.mesh.compat import shard_map

from libjitsi_tpu.mesh.table import ShardedRowsMixin
from libjitsi_tpu.sfu.translator import RtpTranslator
from libjitsi_tpu.transform.srtp import kernel
from libjitsi_tpu.transform.srtp.policy import Cipher, SrtpProfile


class ShardedRtpTranslator(ShardedRowsMixin, RtpTranslator):
    """`RtpTranslator` whose re-encrypt fan-out runs sharded by leg.

    `translate_async` keeps its overlap contract in mesh mode: the
    sharded seams return deferred-scatter results (`_LazyArray`), so
    `PendingTranslate` holds device-resident lane buffers until
    `.result()` — SfuBridge composes mesh with pipelined ticks.
    """

    def __init__(self, capacity: int, mesh: Mesh,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80):
        if profile.policy.cipher not in (Cipher.AES_CM, Cipher.NULL,
                                         Cipher.AES_GCM):
            raise ValueError(
                f"ShardedRtpTranslator supports AES-CM/NULL/AES-GCM "
                f"profiles; {profile.value} stays single-chip for now")
        self._init_sharding(mesh, capacity)
        super().__init__(capacity, profile)

    def _sharded_tables(self, group: str = "rtp"):
        return self._rk, (self._gm if self._gcm else self._mid)

    def _cm_fanout_call(self, recv, data, length, payload_off, iv, idx):
        from libjitsi_tpu.transform.srtp.context import _uniform_off

        roc = ((np.asarray(idx) >> 16) & 0xFFFFFFFF).astype(np.uint32)
        out, out_len = self._sharded_launch(
            self._fanout_fn(_uniform_off(payload_off,
                                         np.asarray(data).shape[-1])),
            self._sharded_device(), recv,
            [data, np.asarray(length, dtype=np.int32), payload_off, iv,
             roc])
        return out, out_len.astype(np.int32)

    def _gcm_fanout_call(self, recv, data, length, payload_off, iv12,
                         capacity):
        from libjitsi_tpu.transform.srtp.context import _uniform_off

        fn = self._gcm_fanout_fn(_uniform_off(payload_off, capacity))
        out, out_len = self._sharded_launch(
            fn, self._sharded_device(), recv,
            [data, np.asarray(length, dtype=np.int32), payload_off,
             iv12])
        return out, out_len.astype(np.int32)

    def _gcm_uniform_fanout_call(self, rr, pdata, plen, iv, aad_const):
        """Leg-partitioned full-mesh AEAD fan-out from the DEVICE-
        RESIDENT row-partitioned tables: legs route to their owning
        chips via the same owner plan as every sharded seam — no host
        re-gather / re-upload of the per-leg 16 KiB GHASH matrices
        (advisor r5: the old form shipped ~16 KiB x legs across the
        link every call) — the P packets broadcast, and each chip
        seals the same packets for ITS legs with zero collectives
        (the product form of mesh/sharded.py's sharded_gcm_fanout)."""
        plen32 = np.asarray(plen, dtype=np.int32)
        fn = self._gcm_uniform_fn(aad_const)
        (out,) = self._sharded_launch(
            fn, self._sharded_device(), rr, [np.asarray(iv)],
            extra_args=(np.asarray(pdata), plen32))
        # leg-major [G, P, W]; the output length is structural (AEAD
        # appends a 16B tag), so no second device output to scatter
        return out, plen32 + 16

    def _gcm_uniform_fn(self, off_const):
        key = ("gcm_uniform_fanout", off_const)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        from libjitsi_tpu.kernels import gcm as gcm_kernel

        def _run(tab_rk, tab_gm, local, iv, data, length):
            out, _ = gcm_kernel.gcm_protect_fanout(
                data, length, tab_rk[local[0]], tab_gm[local[0]],
                iv[0], aad_const=off_const)
            return (out[None],)

        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        fn = jax.jit(shard_map(
            _run, mesh=self.mesh,
            in_specs=(row3, row3, lanes,
                      P(self._axes, None, None, None),
                      P(None, None), P(None)),
            out_specs=(P(self._axes, None, None, None),),
            check_vma=False))
        self._sh_fns[key] = fn
        return fn

    def _gcm_fanout_fn(self, off_const):
        key = ("gcm_fanout", off_const)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        from libjitsi_tpu.kernels import gcm as gcm_kernel

        def _run(tab_rk, tab_gm, local, data, length, off, iv12):
            out = gcm_kernel.gcm_protect(
                data[0], length[0], off[0], tab_rk[local[0]],
                tab_gm[local[0]], iv12[0], aad_const=off_const)
            return tuple(o[None] for o in out)

        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        fn = jax.jit(shard_map(
            _run, mesh=self.mesh,
            in_specs=(row3, row3, lanes, row3, lanes, lanes, row3),
            out_specs=(row3, lanes), check_vma=False))
        self._sh_fns[key] = fn
        return fn

    def _fanout_fn(self, off_const=None):
        key = ("fanout", self.policy.auth_tag_len,
               self.policy.cipher != Cipher.NULL, off_const)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        tag_len = self.policy.auth_tag_len
        encrypt = self.policy.cipher != Cipher.NULL

        def _run(tab_rk, tab_mid, local, data, length, off, iv, roc):
            out = kernel.srtp_protect(
                data[0], length[0], off[0], tab_rk[local[0]], iv[0],
                tab_mid[local[0]], roc[0], tag_len, encrypt,
                payload_off_const=off_const)
            return tuple(o[None] for o in out)

        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        fn = jax.jit(shard_map(
            _run, mesh=self.mesh,
            in_specs=(row3, row3, lanes, row3, lanes, lanes, row3,
                      lanes),
            out_specs=(row3, lanes), check_vma=False))
        self._sh_fns[key] = fn
        return fn
