"""ShardedRtpTranslator — the SFU fan-out primitive on a device mesh.

The decrypt-once / re-encrypt-N fan-out (BASELINE config #5, reference
`RTPTranslatorImpl`, SURVEY §3.4) is embarrassingly parallel over the
RECEIVER axis: each output row's key material belongs to exactly one
receiver leg, so partitioning legs across chips makes every key gather
chip-local — zero collectives, the same stream-data-parallel doctrine
as `ShardedSrtpTable` (the packets each chip needs are routed to it by
the host plan, which already expands the (packet × receiver) matrix).

The routing/expansion/IV host plane is `RtpTranslator`'s, unchanged;
only the CM protect launch seam is overridden.  GCM fan-outs stay
single-chip at product level for now (`mesh/sharded.py`'s
`sharded_gcm_fanout` covers the kernel; the grouped per-leg matrix form
needs a per-shard grid) — the constructor refuses rather than silently
falling back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from libjitsi_tpu.mesh.table import (_OwnerPlan, ShardedRowsMixin,
                                     local_rows)
from libjitsi_tpu.sfu.translator import RtpTranslator
from libjitsi_tpu.transform.srtp import kernel
from libjitsi_tpu.transform.srtp.policy import Cipher, SrtpProfile


class ShardedRtpTranslator(ShardedRowsMixin, RtpTranslator):
    """`RtpTranslator` whose re-encrypt fan-out runs sharded by leg.

    Async caveat: `translate_async` still works, but the sharded seam
    scatters results on the HOST, so the pending object holds already-
    materialized arrays — there is no launch/recv overlap in mesh mode.
    Callers that depend on the overlap must not use the mesh translator
    (SfuBridge refuses mesh+pipelined for exactly this reason).
    """

    def __init__(self, capacity: int, mesh: Mesh,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80):
        if profile.policy.cipher not in (Cipher.AES_CM, Cipher.NULL):
            raise ValueError(
                f"ShardedRtpTranslator supports AES-CM/NULL profiles; "
                f"{profile.value} stays single-chip for now")
        self._init_sharding(mesh, capacity)
        super().__init__(capacity, profile)

    def _sharded_tables(self):
        return self._rk, self._mid

    def _cm_fanout_call(self, recv, data, length, payload_off, iv, idx):
        tab_rk, tab_mid = self._sharded_device()
        plan = _OwnerPlan(np.asarray(recv, dtype=np.int64),
                          self.capacity, self.rows_per, self.n_dev)
        local = local_rows(plan, recv, self.capacity, self.rows_per,
                           self.n_dev)
        fn = self._fanout_fn()
        out, out_len = fn(
            tab_rk, tab_mid, jnp.asarray(local),
            jnp.asarray(np.asarray(data)[plan.slot]),
            jnp.asarray(np.asarray(length,
                                   dtype=np.int32)[plan.slot]),
            jnp.asarray(np.asarray(payload_off)[plan.slot]),
            jnp.asarray(np.asarray(iv)[plan.slot]),
            jnp.asarray(((np.asarray(idx) >> 16) & 0xFFFFFFFF)
                        .astype(np.uint32)[plan.slot]))
        o = np.asarray(out)
        return (o.reshape(-1, o.shape[-1])[plan.inv],
                np.asarray(out_len).reshape(-1)[plan.inv]
                .astype(np.int32))

    def _fanout_fn(self):
        key = ("fanout", self.policy.auth_tag_len,
               self.policy.cipher != Cipher.NULL)
        fn = self._sh_fns.get(key)
        if fn is not None:
            return fn
        tag_len = self.policy.auth_tag_len
        encrypt = self.policy.cipher != Cipher.NULL

        def _run(tab_rk, tab_mid, local, data, length, off, iv, roc):
            out = kernel.srtp_protect(
                data[0], length[0], off[0], tab_rk[local[0]], iv[0],
                tab_mid[local[0]], roc[0], tag_len, encrypt)
            return tuple(o[None] for o in out)

        row3 = P(self._axes, None, None)
        lanes = P(self._axes, None)
        fn = jax.jit(jax.shard_map(
            _run, mesh=self.mesh,
            in_specs=(row3, row3, lanes, row3, lanes, lanes, row3,
                      lanes),
            out_specs=(row3, lanes), check_vma=False))
        self._sh_fns[key] = fn
        return fn
