"""EncodingConfiguration — the codec/encoding registry.

Rebuilds `org.jitsi.impl.neomedia.codec.EncodingConfigurationImpl` (API
`org.jitsi.service.neomedia.codec.EncodingConfiguration`) and the role of
`FMJPlugInConfiguration`: one place that knows every supported encoding,
its RTP clock rate, static/dynamic payload typing, a preference order the
application can adjust, and which host codec implementation (if any)
backs it — so offer/answer layers and `MediaStream.
add_dynamic_rtp_payload_type` draw from a single table, as the reference
does at `MediaServiceImpl` init.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Encoding:
    name: str
    media_type: str          # "audio" | "video"
    clock_rate: int
    channels: int = 1
    static_pt: Optional[int] = None   # RFC 3551 static assignment
    available: Callable[[], bool] = lambda: True


def _opus_ok():
    from libjitsi_tpu.codecs import opus_available
    return opus_available()


def _speex_ok():
    from libjitsi_tpu.codecs import speex_available
    return speex_available()


def _gsm_ok():
    from libjitsi_tpu.codecs import gsm_available
    return gsm_available()


# the reference's registerCustomCodecs() set, minus hardware-only entries
_KNOWN: List[Encoding] = [
    Encoding("opus", "audio", 48000, 2, None, _opus_ok),
    Encoding("PCMU", "audio", 8000, 1, 0),             # G.711 µ-law kernel
    Encoding("PCMA", "audio", 8000, 1, 8),             # G.711 A-law kernel
    Encoding("speex", "audio", 8000, 1, None, _speex_ok),
    Encoding("speex/16000", "audio", 16000, 1, None, _speex_ok),
    Encoding("GSM", "audio", 8000, 1, 3, _gsm_ok),
    # G.722's RTP clock rate is 8000 by RFC 3551 §4.5.2 historical
    # accident even though it samples at 16 kHz
    Encoding("G722", "audio", 8000, 1, 9),
    Encoding("telephone-event", "audio", 8000, 1, None),   # RFC 4733
    Encoding("VP8", "video", 90000, 1, None),
    Encoding("VP9", "video", 90000, 1, None),
    Encoding("H264", "video", 90000, 1, None),
]

_DYNAMIC_PT_FIRST = 96
_DYNAMIC_PT_LAST = 127


class EncodingConfiguration:
    """Preference-ordered registry of supported encodings.

    Priorities follow the reference's semantics: 0 disables an encoding,
    higher values sort earlier in `supported()`.
    """

    def __init__(self):
        self._encodings: Dict[str, Encoding] = {}
        self._priority: Dict[str, int] = {}
        base = 1000
        for i, e in enumerate(_KNOWN):
            self._encodings[e.name] = e
            self._priority[e.name] = base - i

    def register(self, enc: Encoding, priority: int = 1) -> None:
        self._encodings[enc.name] = enc
        self._priority[enc.name] = priority

    def set_priority(self, name: str, priority: int) -> None:
        if name not in self._encodings:
            raise KeyError(name)
        self._priority[name] = priority

    def priority(self, name: str) -> int:
        return self._priority.get(name, 0)

    def supported(self, media_type: Optional[str] = None) -> List[Encoding]:
        """Enabled encodings whose backing codec is present, sorted by
        descending priority (reference: getEnabledEncodings)."""
        out = [e for e in self._encodings.values()
               if self._priority[e.name] > 0 and e.available()
               and (media_type is None or e.media_type == media_type)]
        return sorted(out, key=lambda e: -self._priority[e.name])

    def assign_payload_types(self, media_type: Optional[str] = None
                             ) -> Dict[int, Encoding]:
        """PT -> encoding table: static PTs keep their RFC 3551 numbers,
        dynamic ones are assigned 96.. in priority order (what an SDP
        offer advertises)."""
        table: Dict[int, Encoding] = {}
        supported = self.supported(media_type)
        for e in supported:
            # supported() is descending priority: first claimant of a
            # shared static PT (the higher-priority encoding) keeps it
            if e.static_pt is not None and e.static_pt not in table:
                table[e.static_pt] = e
        next_dyn = _DYNAMIC_PT_FIRST
        for e in supported:
            if e.static_pt is not None:
                continue
            while next_dyn in table:        # a static PT may sit in 96..127
                next_dyn += 1
            if next_dyn > _DYNAMIC_PT_LAST:
                continue                    # dynamic space full; statics stay
            table[next_dyn] = e
            next_dyn += 1
        return table

    def apply_to_stream(self, stream, media_type: str) -> Dict[int, Encoding]:
        """Install the PT table on a MediaStream
        (MediaStream.addDynamicRTPPayloadType in the reference).

        Installed lowest-priority first: add_dynamic_rtp_payload_type also
        sets the stream's single jitter clock rate, and the PRIMARY
        (highest-priority) encoding's rate must be the one that sticks.
        """
        table = self.assign_payload_types(media_type)
        by_prio = sorted(table.items(),
                         key=lambda kv: self._priority[kv[1].name])
        for pt, e in by_prio:
            stream.add_dynamic_rtp_payload_type(pt, e.name, e.clock_rate)
        return table
