"""SfuBridge — the videobridge-style forwarding conference as one object.

Reference: Jitsi Videobridge builds on the reference's
`RTPTranslatorImpl` + `CachingTransformer` + RTCP termination
(SURVEY §3.4, §2.2, §2.3) with one StreamRTPManager per endpoint and a
per-receiver send chain.  Here the whole SFU tick composes the dense
pieces: one batched MediaLoop (unprotect every sender's packets in one
launch), the `RtpTranslator` (decrypt-once / re-encrypt-per-leg in one
fan-out launch — grouped GCM kernel on AEAD conferences), a
`PacketCache` serving NACK retransmissions per leg, and
`RtcpTermination` (feedback dedupe/aggregation, min-REMB).

Endpoints both send and receive: `add_endpoint(ssrc, rx_key, tx_key)`
installs the sender-side SRTP row (what they send us) and the receiver
leg (what we send them); routing defaults to full mesh (everyone
forwards to everyone else).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from libjitsi_tpu.bwe.batched import BatchedRemoteBitrateEstimator
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io.loop import MediaLoop
from libjitsi_tpu.io.udp import UdpEngine
from libjitsi_tpu.rtp import ext as rtp_ext
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.media_stream import StreamRegistry
from libjitsi_tpu.sfu import PacketCache, RtpTranslator
from libjitsi_tpu.sfu import rtx as rtx_mod
from libjitsi_tpu.sfu.recovery import RecoveryConfig, RecoveryController
from libjitsi_tpu.sfu.rtcp_termination import RtcpTermination
from libjitsi_tpu.sfu.simulcast import SimulcastForwarder
from libjitsi_tpu.transform.header_ext import AbsSendTimeEngine
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable
from libjitsi_tpu.utils.logging import get_logger

_log = get_logger("service.sfu")


def _layer_for_bw(layer_bps, bw: float) -> int:
    """Highest layer whose nominal rate fits the advertised bandwidth
    (ascending rates; layer 0 always fits)."""
    want = 0
    for layer, bps in enumerate(layer_bps):
        if bps <= bw:
            want = layer
    return want


class _SvcTrack:
    """One sender's VP9 SVC track: every layer in ONE SSRC, each
    receiver gets a `Vp9SvcForwarder` projection (spatial/temporal
    subsetting) instead of a simulcast stream pick.  Shares the
    fan-out/RTX plumbing with `_VideoTrack` via the same duck surface
    (fwd.forward, tx_sid/rtx_sid/rtx_seq, precache, out_ssrc)."""

    def __init__(self, sender_sid: int, ssrc: int, svc_sid: int,
                 layer_bps, rtx_pt: int):
        from libjitsi_tpu.sfu.svc import Vp9SvcForwarder

        self._fwd_cls = Vp9SvcForwarder
        self.sender_sid = sender_sid
        self.out_ssrc = ssrc & 0xFFFFFFFF     # projection keeps the ssrc
        self.rtx_ssrc = (ssrc ^ _VideoTrack.RTX_SSRC_XOR) & 0xFFFFFFFF
        self.layer_sids = [svc_sid]
        self.layer_ssrcs = [self.out_ssrc]    # teardown/feedback key
        self.layer_bps = [float(b) for b in layer_bps]
        self.rtx_pt = rtx_pt
        self.fwd: Dict[int, object] = {}
        self.rtx_seq: Dict[int, int] = {}
        self.tx_sid: Dict[int, int] = {}
        self.rtx_sid: Dict[int, int] = {}
        self.precache = PacketCache()

    def make_forwarder(self):
        return self._fwd_cls(initial_sid=0)

    def select_layer(self, fwd, bw: float):
        """Spatial-layer pick for `bw`; returns the SSRC to PLI when a
        raise awaits a keyframe, else None."""
        want = _layer_for_bw(self.layer_bps, bw)
        if want != fwd.target_sid:
            if fwd.request_layers(sid=want):
                return self.out_ssrc
        elif fwd.awaiting_keyframe:
            return self.out_ssrc
        return None


class _VideoTrack:
    """One sender's simulcast video track inside an SfuBridge.

    Reference: `MediaStreamTrackDesc` + `RTPEncodingDesc` consumed by
    `RTPTranslatorImpl` (SURVEY §2.3): L spatial layers arrive as
    separate SSRCs; each receiver gets exactly one, projected through a
    `SimulcastForwarder` into a single coherent stream.  Retransmissions
    toward receivers ride RFC 4588 RTX streams (own SSRC = out_ssrc ^
    "RTX", own SRTP row), served from a pre-SRTP cache of the rewritten
    per-receiver packets.
    """

    RTX_SSRC_XOR = 0x00525458          # "RTX"

    def __init__(self, sender_sid: int, out_ssrc: int, layer_ssrcs,
                 layer_sids, layer_bps, rtx_pt: int):
        self.sender_sid = sender_sid
        self.out_ssrc = out_ssrc & 0xFFFFFFFF
        self.rtx_ssrc = (out_ssrc ^ self.RTX_SSRC_XOR) & 0xFFFFFFFF
        self.layer_ssrcs = [int(s) & 0xFFFFFFFF for s in layer_ssrcs]
        self.layer_sids = list(layer_sids)
        self.layer_bps = [float(b) for b in layer_bps]
        self.rtx_pt = rtx_pt
        self.fwd: Dict[int, SimulcastForwarder] = {}   # recv sid ->
        self.rtx_seq: Dict[int, int] = {}              # recv sid ->
        # dedicated SRTP tx rows per receiver: the projection and its
        # RTX stream are each their own RTP stream (own SSRC, own seq
        # space), so each gets its own row context — sharing the
        # receiver's audio row would interleave independent seq spaces
        # in one RFC 3711 index estimator
        self.tx_sid: Dict[int, int] = {}               # recv sid ->
        self.rtx_sid: Dict[int, int] = {}              # recv sid ->
        self.precache = PacketCache()                  # pre-SRTP copies

    def make_forwarder(self):
        return SimulcastForwarder(self.layer_ssrcs,
                                  out_ssrc=self.out_ssrc)

    def select_layer(self, fwd, bw: float):
        """Simulcast-layer pick for `bw`; returns the layer SSRC to PLI
        while a switch awaits its keyframe, else None."""
        want = _layer_for_bw(self.layer_bps, bw)
        if want != fwd.target_layer:
            if fwd.request_layer(want):
                return self.layer_ssrcs[want]
        elif fwd.awaiting_keyframe:
            return self.layer_ssrcs[fwd.target_layer]
        return None


class SfuBridge:
    """Secure selective-forwarding bridge on one UDP port."""

    def __init__(self, config, port: int = 0, capacity: int = 256,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                 recv_window_ms: int = 1,
                 kernel_timestamps: bool = False,
                 abs_send_time_ext_id: int = 3,
                 pipelined: bool = False,
                 pipeline_depth: int = 1,
                 mesh=None,
                 recovery_config: Optional[RecoveryConfig] = None,
                 engine_mode: str = "auto",
                 ingest_rings: int = 1):
        self.capacity = capacity
        self.profile = profile
        self.ast_ext_id = abs_send_time_ext_id
        self.engine_mode = engine_mode
        self.ingest_rings = max(1, int(ingest_rings))
        self.pipelined = pipelined or pipeline_depth > 1
        self._pending_fanout: list = []
        self._media_ran = False
        self.registry = StreamRegistry(config, capacity=capacity)
        # rx_table: what endpoints SEND us (media + their SRTCP);
        # tx_table: what we send THEM (our SRTCP feedback; media forward
        # crypto is the translator's per-leg fan-out).  Mesh mode
        # (SURVEY §2.7, VERDICT r3 #2): tables row-partition and the
        # fan-out shards by receiver leg — the assembled SFU tick runs
        # sharded, not just its kernels.
        self._mesh = mesh
        if mesh is not None:
            # composes with pipelined=True: the sharded seams defer
            # their wire-order scatter (mesh/table._LazyArray), so the
            # fan-out launch overlaps the next recv window in mesh mode
            from libjitsi_tpu.mesh import (ShardedRtpTranslator,
                                           ShardedSrtpTable)
            self.rx_table = ShardedSrtpTable(capacity, mesh, profile)
            self.tx_table = ShardedSrtpTable(capacity, mesh, profile)
            self.translator = ShardedRtpTranslator(capacity, mesh,
                                                   profile)
        else:
            self.rx_table = SrtpStreamTable(capacity, profile)
            self.tx_table = SrtpStreamTable(capacity, profile)
            self.translator = RtpTranslator(capacity=capacity,
                                            profile=profile)
        self.cache = PacketCache()
        self.rtcp_term = RtcpTermination(bridge_ssrc=0x5F0BFF)
        # end-to-end loss recovery (sfu/recovery.py): uplink gap
        # detection -> upstream NACKs, budgeted NACK service, adaptive
        # FEC on egress legs, and the supervisor's shed-FEC-first /
        # shrink-RTX-second escalation rungs.  Transient (like the
        # caches): a restored bridge re-learns loss state from traffic.
        self.recovery = RecoveryController(recovery_config)
        # resolve uplink SSRCs back to leg sids so nack_queued events
        # land in the stream's flight ring (and mark it priority for
        # tail-biased header sampling)
        self.recovery.sid_of = self._sid_of_ssrc
        # flight recorder slot (attached by BridgeSupervisor; shared
        # with self.loop and self.recovery)
        self.flight = None
        self.loop = MediaLoop(
            UdpEngine(port=port, max_batch=4 * capacity,
                      kernel_timestamps=kernel_timestamps,
                      engine_mode=engine_mode,
                      reuseport=self.ingest_rings > 1),
            self.registry, on_media=self._on_media,
            on_rtcp=self._on_rtcp,
            on_dtls=lambda d, a: self._dtls.on_dtls(d, a), chain=None,
            recv_window_ms=recv_window_ms,
            # the SFU unprotects inside _on_media (chain=None), so deep
            # reverse pipelining doesn't engage here — depth > 1 still
            # turns on pipelined replies/fan-out (loop.pipelined)
            pipeline_depth=pipeline_depth)
        self.port = self.loop.engine.port
        # SO_REUSEPORT multi-queue: sibling drain rings on the SAME
        # port, kernel-sharded by flow hash; each tick drains every
        # ring (io/loop.py) and the AdaptiveBatcher governs their caps
        for _ in range(self.ingest_rings - 1):
            self.loop.add_ring(UdpEngine(
                port=self.port, reuseport=True,
                max_batch=4 * capacity,
                kernel_timestamps=kernel_timestamps,
                engine_mode=engine_mode))
        self._ssrc_of: Dict[int, int] = {}     # sid -> sender ssrc
        # rows keyed by stage_endpoints but not yet committed: demuxed
        # media queues on the hold mask, and the route mesh excludes
        # them until commit_endpoints flips them live between ticks
        self._staged: set = set()
        self.forwarded = 0
        self.retransmitted = 0
        # overload degradation (set by BridgeSupervisor): suppress the
        # RTCP feedback fan-out while media forwarding keeps flowing
        self.degraded = False
        # receive-side GCC over each sender->bridge leg: fed per tick
        # from the abs-send-time ext + (kernel, when enabled) arrival
        # stamps; one transport row per sender sid.  Reference:
        # RemoteBitrateEstimatorAbsSendTime driven from the translator's
        # receive path (SURVEY §2.3).
        self.bwe = BatchedRemoteBitrateEstimator(capacity=capacity)
        self._bwe_fed = np.zeros(capacity, dtype=bool)
        # egress abs-send-time stamping so every receiver can run its
        # own receive-side estimate on the bridge->receiver leg
        # (reference: AbsSendTimeEngine on the SFU's send chain)
        self._ast = AbsSendTimeEngine(abs_send_time_ext_id,
                                      clock=lambda: self._now)
        self._now = time.time()
        # pending DTLS-SRTP associations (shared table: routing,
        # retransmit timers, early-media hold)
        from libjitsi_tpu.control.dtls import DtlsAssociationTable
        self._dtls = DtlsAssociationTable(self.loop, profile,
                                          self._install_dtls)
        # video: layer-row sid -> its track; plus per-endpoint leg keys
        # (kept to derive per-track projection/RTX rows) and receiver
        # downlink REMBs
        self._video: Dict[int, _VideoTrack] = {}
        self._rx_keys: Dict[int, Tuple[bytes, bytes]] = {}
        self._tx_keys: Dict[int, Tuple[bytes, bytes]] = {}
        self._recv_bw: Dict[int, float] = {}   # recv sid -> REMB bps
        # BWE transport row per stream row: GCC estimates per TRANSPORT
        # (5-tuple), so a sender's video layer rows feed its primary row
        self._transport_of = np.arange(capacity, dtype=np.int64)
        # conference scoping (mesh/placement.py): sid -> conference id.
        # Endpoints with a conference id forward only within it; rows
        # without one (direct add_endpoint) form one shared mesh, which
        # keeps the single-conference bridge behavior unchanged.
        self._conf_of: Dict[int, int] = {}
        # broadcast conferences (mesh/hierarchy.py): conference id ->
        # current speaker sids.  Speakers fan out to every member;
        # every other member is a fanout-only listener row (routes to
        # nobody, uplink RTP masked off in the loop).
        self._bcast_speakers: Dict[int, set] = {}
        # cascade trunks (mesh/cascade.py): conference id -> trunk.
        # Accepted uplink media from a cascaded conference's current
        # speaker set is relayed across the trunk (top-K speaker bus,
        # never raw per-participant fan-out)
        self._trunks: Dict[int, object] = {}

    # ---------------------------------------------------------- endpoints
    def add_endpoint(self, ssrc: int, rx_key: Tuple[bytes, bytes],
                     tx_key: Tuple[bytes, bytes],
                     name: Optional[str] = None) -> int:
        if ssrc in self._ssrc_of.values():
            raise ValueError(f"ssrc {ssrc:#x} already joined")
        self._quiesce_fanout()
        sid = self.registry.alloc(self)
        if name is not None:
            # SDES-style display name: hostile input, escaped at
            # metric render time (never trusted raw)
            self.loop.metrics.set_stream_name(sid, name)
        self.rx_table.add_stream(sid, *rx_key)
        self.tx_table.add_stream(sid, *tx_key)
        self.translator.add_receiver(sid, *tx_key)
        self.registry.map_ssrc(ssrc, sid)
        self._ssrc_of[sid] = ssrc & 0xFFFFFFFF
        self._rx_keys[sid] = tuple(rx_key)
        self._tx_keys[sid] = tuple(tx_key)
        self._rebuild_routes()
        for track in set(self._video.values()):
            self._attach_video_receiver(track, sid)
        _log.info("endpoint_join", sid=sid, ssrc=ssrc)
        return sid

    def add_endpoint_dtls(self, ssrc: int, role: str = "server",
                          remote_fingerprint: Optional[str] = None,
                          cookie_exchange: bool = False,
                          remote_addr=None):
        """Join keyed by DTLS-SRTP instead of direct keys: allocates the
        row and starts an association; media arriving before the
        handshake finishes is queued (MediaLoop.hold_stream) and
        replayed once keys install.  Returns (sid, endpoint) — publish
        `endpoint.local_fingerprint` via signaling, and pass
        `remote_addr` when signaling knows the peer's 5-tuple (with
        several concurrent unbound joins, unknown-address handshakes
        are dropped rather than guessed onto the wrong row).
        Reference: DtlsControlImpl started by MediaStream.start
        (SURVEY §3.5)."""
        if ssrc in self._ssrc_of.values():
            raise ValueError(f"ssrc {ssrc:#x} already joined")
        sid = self.registry.alloc(self)
        self.registry.map_ssrc(ssrc, sid)
        self._ssrc_of[sid] = ssrc & 0xFFFFFFFF
        ep = self._dtls.join(sid, role, remote_fingerprint,
                             cookie_exchange, remote_addr)
        _log.info("endpoint_join_dtls", sid=sid, ssrc=ssrc, role=role)
        return sid, ep

    def _install_dtls(self, sid: int, ep) -> None:
        self._quiesce_fanout()
        profile, tk, tsalt, rk, rsalt = ep.srtp_keys()
        self.rx_table.add_stream(sid, rk, rsalt)
        self.tx_table.add_stream(sid, tk, tsalt)
        self.translator.add_receiver(sid, tk, tsalt)
        self._rx_keys[sid] = (rk, rsalt)
        self._tx_keys[sid] = (tk, tsalt)
        self._rebuild_routes()
        # video tracks created while this endpoint was mid-handshake
        # attach now that its leg keys exist
        for track in set(self._video.values()):
            self._attach_video_receiver(track, sid)
        _log.info("dtls_keys_installed", sid=sid, profile=profile.name)

    def stage_dtls_keys(self, sid: int, ep) -> None:
        """Staged landing for a completed DTLS handshake (the lifecycle
        plane's HandshakeQueue): install the exported keys into both
        SRTP tables + the translator leg for the already-allocated row
        and leave it STAGED — `commit_endpoints` flips it live between
        ticks (one route rebuild for the whole batch, held early media
        replayed atomically).  `_install_dtls` stays as the inline twin
        for bridges running without a lifecycle manager."""
        profile, tk, tsalt, rk, rsalt = ep.srtp_keys()
        self.rx_table.add_stream(sid, rk, rsalt)
        self.tx_table.add_stream(sid, tk, tsalt)
        self.translator.add_receiver(sid, tk, tsalt)
        self._rx_keys[sid] = (rk, rsalt)
        self._tx_keys[sid] = (tk, tsalt)
        self._staged.add(sid)
        _log.info("dtls_keys_staged", sid=sid, profile=profile.name)

    def remove_endpoint(self, sid: int) -> None:
        self.remove_endpoints([sid])

    def remove_endpoints(self, sids) -> None:
        """Batched evict: `remove_endpoint` for many legs at once — one
        fan-out quiesce, ONE `remove_streams` pass per SRTP table (one
        copy-on-write episode however many streams leave), one route
        rebuild.  The lifecycle plane's leave path; O(evicted), not
        O(evicted * per-call table copies)."""
        sids = [int(s) for s in sids]
        if not sids:
            return
        self._quiesce_fanout()
        rx_rows: list = []
        tx_rows: list = []
        gone_ssrcs: list = []
        for sid in sids:
            ssrc = self._ssrc_of.pop(sid, None)
            if ssrc is not None:
                self.registry.unmap_ssrc(ssrc)
                gone_ssrcs.append(ssrc)
            if self.rx_table.active[sid]:
                rx_rows.append(sid)
            if self.tx_table.active[sid]:
                tx_rows.append(sid)
            self.translator.disconnect(sid)
            self.translator.remove_receiver(sid)
            self.rtcp_term.forget_receiver(sid)
            self._bwe_fed[sid] = False
            self._dtls.forget(sid)
            self._rx_keys.pop(sid, None)
            self._tx_keys.pop(sid, None)
            self._recv_bw.pop(sid, None)
            conf = self._conf_of.pop(sid, None)
            if conf is not None and conf in self._bcast_speakers:
                self._bcast_speakers[conf].discard(sid)
                self.loop.set_fanout_only(sid, False)
            # a staged-but-never-committed row: throw its held media
            # away (the endpoint left before its admit flipped live)
            if sid in self._staged:
                self._staged.discard(sid)
                self.loop.discard_stream(sid)
            # as a video sender: tear the track + its layer rows down
            # (the SSRC unmap matters: a recycled row must not demux the
            # old layer SSRCs and latch the departed sender's address)
            for lsid in [k for k, t in self._video.items()
                         if t.sender_sid == sid]:
                track = self._video.pop(lsid)
                li = track.layer_sids.index(lsid)
                self.registry.unmap_ssrc(track.layer_ssrcs[li])
                gone_ssrcs.append(track.layer_ssrcs[li])
                rx_rows.append(lsid)
                self._transport_of[lsid] = lsid
                self.registry.release(lsid)
                for d in (track.tx_sid, track.rtx_sid):
                    for row in d.values():
                        tx_rows.append(row)
                        self.registry.release(row)
            # as a video receiver: drop forwarders + projection/RTX rows
            for track in set(self._video.values()):
                track.fwd.pop(sid, None)
                track.rtx_seq.pop(sid, None)
                for d in (track.tx_sid, track.rtx_sid):
                    row = d.pop(sid, None)
                    if row is not None:
                        tx_rows.append(row)
                        self.registry.release(row)
            self.loop.addr_ip[sid] = 0
            self.loop.addr_port[sid] = 0
            self.loop.metrics.set_stream_name(sid, None)
            self.registry.release(sid)
        self.rx_table.remove_streams(rx_rows)
        self.tx_table.remove_streams(tx_rows)
        self.bwe.reset_rows(sids)
        # recovery state is per departed sender SSRC / receiver leg:
        # recycle it so churn can't grow trackers without bound
        self.recovery.forget_ssrcs(gone_ssrcs)
        self.recovery.forget_legs(sids)
        self._rebuild_routes()
        for sid in sids:
            _log.info("endpoint_leave", sid=sid)

    # ---------------------------------------------------- lifecycle plane
    def stage_endpoints(self, specs, sids=None,
                        conferences=None) -> List[int]:
        """Off-tick half of a batched admit: allocate rows, install BOTH
        SRTP tables and the translator legs in ONE vectorized
        `add_streams` pass each, map the SSRCs (media racing the admit
        queues on the hold mask instead of being dropped), and leave the
        rows STAGED — no route includes them and no held packet replays
        until `commit_endpoints` flips them live between ticks.

        specs: iterable of (ssrc, (rx_mk, rx_ms), (tx_mk, tx_ms), name).
        `sids` pins specific rows (the lifecycle plane's
        conference-affinity path: rows drawn from the conference's
        shard range by `ShardRowAllocator`); `conferences` scopes each
        endpoint's forwarding to its conference id.
        Returns the allocated sids in spec order.
        """
        specs = list(specs)
        if not specs:
            return []
        for ssrc, _rx, _tx, _name in specs:
            if ssrc in self._ssrc_of.values():
                raise ValueError(f"ssrc {ssrc:#x} already joined")
        self._quiesce_fanout()
        if sids is None:
            sids = [self.registry.alloc(self) for _ in specs]
        else:
            sids = [int(s) for s in sids]
            if len(sids) != len(specs):
                raise ValueError("sids/specs length mismatch")
            self.registry.reserve_many(sids, self)
        if conferences is not None:
            for sid, conf in zip(sids, conferences):
                if conf is not None:
                    self._conf_of[sid] = int(conf)
        arr = np.asarray(sids, dtype=np.int64)
        rx_mks = np.stack([np.frombuffer(rx[0], np.uint8)
                           for _, rx, _, _ in specs])
        rx_mss = np.stack([np.frombuffer(rx[1], np.uint8)
                           for _, rx, _, _ in specs])
        tx_mks = np.stack([np.frombuffer(tx[0], np.uint8)
                           for _, _, tx, _ in specs])
        tx_mss = np.stack([np.frombuffer(tx[1], np.uint8)
                           for _, _, tx, _ in specs])
        self.rx_table.add_streams(arr, rx_mks, rx_mss)
        self.tx_table.add_streams(arr, tx_mks, tx_mss)
        self.translator.add_receivers(
            sids, [tx[0] for _, _, tx, _ in specs],
            [tx[1] for _, _, tx, _ in specs])
        for sid, (ssrc, rx, tx, name) in zip(sids, specs):
            self.registry.map_ssrc(ssrc, sid)
            self._ssrc_of[sid] = ssrc & 0xFFFFFFFF
            self._rx_keys[sid] = tuple(rx)
            self._tx_keys[sid] = tuple(tx)
            if name is not None:
                self.loop.metrics.set_stream_name(sid, name)
            self.loop.hold_stream(sid)
            self._staged.add(sid)
            _log.info("endpoint_staged", sid=sid, ssrc=ssrc)
        return sids

    def commit_endpoints(self, sids) -> None:
        """Between-ticks commit barrier: flip staged rows live — one
        route rebuild for the whole batch, held media replayed through
        the normal receive path, video receivers attached."""
        sids = [int(s) for s in sids if int(s) in self._staged]
        if not sids:
            return
        self._quiesce_fanout()
        for sid in sids:
            self._staged.discard(sid)
            conf = self._conf_of.get(sid)
            if conf is not None and conf in self._bcast_speakers:
                # joining a broadcast conference: fanout-only unless in
                # the current speaker set (role flips ride the same
                # barrier later)
                self.loop.set_fanout_only(
                    sid, sid not in self._bcast_speakers[conf])
        self._rebuild_routes()
        for sid in sids:
            for track in set(self._video.values()):
                self._attach_video_receiver(track, sid)
            self.loop.release_stream(sid)
            _log.info("endpoint_join", sid=sid,
                      ssrc=self._ssrc_of.get(sid))

    def set_broadcast_speakers(self, conference: int, sids) -> None:
        """Declare/update a broadcast conference's speaker set and
        rebuild its routes: speakers fan out to every member, all other
        members become fanout-only listener rows.  Called by the
        lifecycle plane BETWEEN ticks (a promotion/demotion is a
        commit-barrier event, never a mid-tick one); the fan-out
        quiesce makes the standalone call safe too."""
        conference = int(conference)
        speakers = {int(s) for s in sids}
        if self._bcast_speakers.get(conference) == speakers:
            return
        self._quiesce_fanout()
        self._bcast_speakers[conference] = speakers
        for sid, conf in self._conf_of.items():
            if conf == conference:
                self.loop.set_fanout_only(sid, sid not in speakers)
        self._rebuild_routes()
        tr = self._trunks.get(conference)
        if tr is not None:
            # propagate the top-K flip across the trunk: the peer
            # restricts the same legs (speaker bus, not fan-out)
            tr.set_speakers(conference,
                            [self._ssrc_of[s] for s in speakers
                             if s in self._ssrc_of], now=self._now)

    # ------------------------------------------------------------ cascade
    def attach_trunk(self, trunk, conference, speakers=None) -> None:
        """Cascade `conference` over `trunk` (mesh/cascade.py): every
        accepted uplink packet from the conference's speaker set is
        relayed across the trunk, and speaker-set flips propagate to
        the peer bridge.  `speakers` is the initial top-K ssrc set
        (None relays every member — the degenerate bus)."""
        self._trunks[int(conference)] = trunk
        trunk.cascade_conference(int(conference), speakers)

    def detach_trunk(self, conference) -> None:
        tr = self._trunks.pop(int(conference), None)
        if tr is not None:
            tr.uncascade_conference(int(conference))

    def _relay_trunk(self, batch: PacketBatch, rows: np.ndarray,
                     streams, ssrcs) -> None:
        """Relay the ORIGINAL protected wire bytes of accepted rows
        whose (conference, ssrc) rides a trunk's speaker bus.  The
        inner packet stays untouched — the peer bridge authenticates
        it with the participant's own row key."""
        for i, r in enumerate(rows):
            conf = self._conf_of.get(int(streams[i]))
            if conf is None:
                continue
            tr = self._trunks.get(conf)
            if tr is not None and tr.wants(conf, int(ssrcs[i])):
                tr.relay_media(conf, batch.to_bytes(int(r)),
                               now=self._now)

    def clear_broadcast(self, conference: int) -> None:
        """Drop a conference's broadcast routing (back to full mesh)."""
        if self._bcast_speakers.pop(int(conference), None) is not None:
            for sid, conf in self._conf_of.items():
                if conf == int(conference):
                    self.loop.set_fanout_only(sid, False)
            self._quiesce_fanout()
            self._rebuild_routes()

    def migrate_endpoints(self, mapping: Dict[int, int]) -> None:
        """Move live endpoints to new rows BIT-EXACT — the execution
        half of a placement rebalance (mesh/placement.py): both SRTP
        tables' per-row crypto state (keys, rollover counters, replay
        windows, kdr epochs), the translator leg material, SSRC demux,
        addresses and conference scoping all relocate unchanged, so a
        conference migrating to another shard cannot tear (a packet
        keyed before the move authenticates identically after it).
        Transient learning state (BWE, RTCP reception, recovery
        trackers) resets and re-learns from traffic, same as it does
        across a checkpoint restore.

        Callers run this BETWEEN ticks (the lifecycle plane sequences
        it behind the commit barrier); the pipeline drain + fan-out
        quiesce here make that safe even standalone.  Rows serving
        video tracks or still staged/DTLS-pending refuse to move.
        """
        mapping = {int(s): int(d) for s, d in mapping.items()}
        mapping = {s: d for s, d in mapping.items() if s != d}
        if not mapping:
            return
        src = sorted(mapping)
        dst = [mapping[s] for s in src]
        if len(set(dst)) != len(dst) or set(src) & set(dst):
            raise ValueError("overlapping migration mapping")
        for s in src:
            if s not in self._ssrc_of:
                raise ValueError(f"sid {s} not live")
            if s in self._staged or s in self._dtls.pending:
                raise ValueError(f"sid {s} is mid-install")
            if s in self._video or any(
                    t.sender_sid == s or s in t.fwd
                    for t in set(self._video.values())):
                raise ValueError(f"sid {s} serves a video track")
        drain = getattr(self.loop, "drain", None)
        if drain is not None:
            drain()
        self._quiesce_fanout()
        self.registry.reserve_many(dst, self)
        self.rx_table.move_rows(src, dst)
        self.tx_table.move_rows(src, dst)
        self.translator.move_receivers(src, dst)
        for s, d in zip(src, dst):
            ssrc = self._ssrc_of.pop(s)
            self.registry.unmap_ssrc(ssrc)
            self.registry.map_ssrc(ssrc, d)
            self._ssrc_of[d] = ssrc
            self._rx_keys[d] = self._rx_keys.pop(s)
            self._tx_keys[d] = self._tx_keys.pop(s)
            if s in self._recv_bw:
                self._recv_bw[d] = self._recv_bw.pop(s)
            if s in self._conf_of:
                self._conf_of[d] = self._conf_of.pop(s)
                conf = self._conf_of[d]
                if conf in self._bcast_speakers:
                    spk = self._bcast_speakers[conf]
                    if s in spk:
                        spk.discard(s)
                        spk.add(d)
                    self.loop.set_fanout_only(s, False)
                    self.loop.set_fanout_only(d, d not in spk)
            self.loop.addr_ip[d] = self.loop.addr_ip[s]
            self.loop.addr_port[d] = self.loop.addr_port[s]
            self.loop.addr_ip[s] = 0
            self.loop.addr_port[s] = 0
            name = self.loop.metrics.stream_names.get(s)
            self.loop.metrics.set_stream_name(d, name)
            self.loop.metrics.set_stream_name(s, None)
            self._bwe_fed[s] = False
            self._bwe_fed[d] = False
            self.registry.release(s)
        self.bwe.reset_rows(src)
        self.recovery.forget_legs(src)
        for s in src:
            self.rtcp_term.forget_receiver(s)
        self._rebuild_routes()
        for s, d in zip(src, dst):
            _log.info("endpoint_migrated", src=s, dst=d)

    def _sid_of_ssrc(self, ssrc: int) -> Optional[int]:
        """Reverse of `_ssrc_of` (recovery's sid resolver): uplink
        media SSRC -> sender leg sid, video layers included."""
        ssrc = int(ssrc) & 0xFFFFFFFF
        for sid, s in self._ssrc_of.items():
            if s == ssrc:
                return sid
        for lsid, track in self._video.items():
            li = track.layer_sids.index(lsid)
            if track.layer_ssrcs[li] == ssrc:
                return track.sender_sid
        return None

    # --------------------------------------------------------------- video
    def add_video_track(self, sender_sid: int, layer_ssrcs,
                        layer_bps, rtx_pt: int = 97) -> "_VideoTrack":
        """Declare a joined endpoint's simulcast video track.

        layer_ssrcs: the L spatial layers' SSRCs, low to high;
        layer_bps: nominal bitrate of each layer (ascending) — layer
        selection picks the highest layer whose rate fits the
        receiver's advertised REMB.  Each layer gets its own SRTP row
        (one row per SSRC: RFC 3711 contexts, replay windows and index
        estimation are per-stream).  Reference: RTPEncodingDesc layers
        under MediaStreamTrackDesc (SURVEY §2.3).
        """
        if sender_sid not in self._ssrc_of:
            raise ValueError(f"sid {sender_sid} not joined")
        if len(layer_ssrcs) != len(layer_bps):
            raise ValueError("one nominal bitrate per layer")
        self._quiesce_fanout()
        rx_key = self._rx_keys[sender_sid]
        layer_sids = []
        for ssrc in layer_ssrcs:
            lsid = self.registry.alloc(self)
            self.rx_table.add_stream(lsid, *rx_key)
            self.registry.map_ssrc(ssrc, lsid)
            # GCC is per transport: layer rows feed the sender's row
            self._transport_of[lsid] = sender_sid
            layer_sids.append(lsid)
        track = _VideoTrack(sender_sid, self._ssrc_of[sender_sid],
                            layer_ssrcs, layer_sids, layer_bps, rtx_pt)
        for lsid in layer_sids:
            self._video[lsid] = track
        for r in self._ssrc_of:
            if r != sender_sid:
                self._attach_video_receiver(track, r)
        _log.info("video_track_added", sid=sender_sid,
                  layers=len(layer_sids))
        return track

    def add_svc_track(self, sender_sid: int, ssrc: int, layer_bps,
                      rtx_pt: int = 97) -> "_SvcTrack":
        """Declare a joined endpoint's VP9 SVC track: one SSRC carrying
        every spatial layer; each receiver gets a per-receiver
        `Vp9SvcForwarder` projection (layer subsetting) driven by its
        REMB, with the same RTX/PLI plumbing as simulcast.  layer_bps:
        nominal cumulative rate per spatial layer, ascending."""
        if sender_sid not in self._ssrc_of:
            raise ValueError(f"sid {sender_sid} not joined")
        self._quiesce_fanout()
        svc_sid = self.registry.alloc(self)
        self.rx_table.add_stream(svc_sid, *self._rx_keys[sender_sid])
        self.registry.map_ssrc(ssrc, svc_sid)
        self._transport_of[svc_sid] = sender_sid
        track = _SvcTrack(sender_sid, ssrc, svc_sid, layer_bps, rtx_pt)
        self._video[svc_sid] = track
        for r in self._ssrc_of:
            if r != sender_sid:
                self._attach_video_receiver(track, r)
        _log.info("svc_track_added", sid=sender_sid, ssrc=ssrc,
                  layers=len(track.layer_bps))
        return track

    def _attach_video_receiver(self, track, recv_sid: int) -> None:
        if recv_sid == track.sender_sid or recv_sid in track.fwd:
            return
        if recv_sid not in self._tx_keys:
            # no leg keys yet (mid-DTLS): attach happens at install
            return
        track.fwd[recv_sid] = track.make_forwarder()
        track.rtx_seq[recv_sid] = 0
        # the projection and its RTX stream each get a dedicated row
        # under this receiver's leg keys (RFC 4588: RTX is its own
        # stream; RFC 3711: one index estimator per stream)
        for d in (track.tx_sid, track.rtx_sid):
            row = self.registry.alloc(self)
            self.tx_table.add_stream(row, *self._tx_keys[recv_sid])
            d[recv_sid] = row

    def _forward_video(self, sub: PacketBatch, vrows: np.ndarray
                       ) -> None:
        """Project video rows through each receiver's forwarder, cache
        the pre-SRTP rewrites for RTX, protect all legs in one launch."""
        lens = np.asarray(sub.length)
        rows_of: Dict[int, list] = {}      # id(track) -> batch rows
        tracks: Dict[int, _VideoTrack] = {}
        for i in vrows:
            t = self._video[int(sub.stream[i])]
            rows_of.setdefault(id(t), []).append(int(i))
            tracks[id(t)] = t
        out_payloads: list = []
        out_rows: list = []                # SRTP row per packet
        out_addr: list = []                # receiver sid per packet
        for key_, trows in rows_of.items():
            track = tracks[key_]
            tb = PacketBatch(sub.data[trows], lens[trows],
                             sub.stream[trows])
            for r, fwd in track.fwd.items():
                if self.loop.addr_port[r] == 0:
                    continue
                pkts = fwd.forward(tb)
                key = (r << 32) | track.out_ssrc
                for p in pkts:
                    seq = int.from_bytes(p[2:4], "big")
                    track.precache.insert(key, seq, p, now=self._now)
                out_payloads.extend(pkts)
                out_rows.extend([track.tx_sid[r]] * len(pkts))
                out_addr.extend([r] * len(pkts))
        if not out_payloads:
            return
        wb = PacketBatch.from_payloads(out_payloads, stream=out_rows)
        wire = self.tx_table.protect_rtp(wb)
        addr = np.asarray(out_addr, dtype=np.int64)
        with self.loop.tracer.span("egress"):
            sent = self.loop.engine.send_batch(
                wire, self.loop.addr_ip[addr], self.loop.addr_port[addr])
            self.loop.note_journey(sent, sids=addr)
        self.forwarded += sent

    def _select_video_layers(self) -> None:
        """Keyframe-gated layer selection from receiver REMBs: pick the
        highest layer whose nominal rate fits each receiver's advertised
        bandwidth; a pending switch keeps a PLI request live upstream
        until the target layer's keyframe arrives."""
        for track in set(self._video.values()):
            for r, fwd in track.fwd.items():
                bw = self._recv_bw.get(r)
                if bw is None:
                    continue
                kf_ssrc = track.select_layer(fwd, bw)
                if kf_ssrc is not None:
                    self.rtcp_term.request_keyframe(kf_ssrc)

    def _serve_video_nack(self, sid: int, nack: "rtcp.Nack") -> bool:
        """NACKed video returns as proper RTX encapsulation (not a raw
        replay): pre-SRTP copies from the track's cache, OSN spliced in,
        RTX SSRC/PT/seq space, protected under the receiver's RTX row."""
        for track in set(self._video.values()):
            if sid not in track.fwd or \
                    nack.media_ssrc != track.out_ssrc:
                continue
            rtx_row = track.rtx_sid.get(sid)
            if rtx_row is None:
                return False
            key = (sid << 32) | track.out_ssrc
            copies, missing = track.precache.lookup_nack(
                key, nack.lost_seqs, return_missing=True)
            self.recovery.rtx_cache_miss += len(missing)
            if not copies:
                return True          # ours, but aged out of the cache
            if not self.recovery.allow_rtx(
                    sum(len(c) for c in copies), self._now):
                return True          # over the retransmission budget
            self.recovery.rtx_requests_served += len(copies)
            b = PacketBatch.from_payloads(copies,
                                          stream=[rtx_row] * len(copies))
            out = rtx_mod.encapsulate_batch(b, track.rtx_ssrc,
                                            track.rtx_pt,
                                            track.rtx_seq[sid])
            track.rtx_seq[sid] = (track.rtx_seq[sid]
                                  + out.batch_size) & 0xFFFF
            wire = self.tx_table.protect_rtp(out)
            with self.loop.tracer.span("egress"):
                sent = self.loop.engine.send_batch(
                    wire, self.loop.addr_ip[sid],
                    self.loop.addr_port[sid])
                # NACK-arrival -> RTX-egress is this tick's journey
                self.loop.note_journey(sent, sids=[sid])
            self.retransmitted += sent
            if self.flight is not None:
                self.flight.record("rtx_served", sid=sid,
                                   ssrc=int(track.out_ssrc),
                                   n=len(copies), rtx=True)
            _log.debug("video_nack_rtx", sid=sid, sent=sent)
            return True
        return False

    def _rebuild_routes(self) -> None:
        """Full mesh: every sender forwards to every OTHER endpoint.
        DTLS-pending rows have no leg keys yet and stay out of the mesh
        until their install completes; staged rows (lifecycle admit in
        flight) stay out until their commit barrier."""
        sids = [s for s in sorted(self._ssrc_of)
                if s not in self._dtls.pending and s not in self._staged]
        if self._conf_of:
            # conference-scoped mesh: a sender fans out only within its
            # conference (rows without an id share the -1 group)
            groups: Dict[int, list] = {}
            for s in sids:
                groups.setdefault(self._conf_of.get(s, -1), []).append(s)
            for conf, grp in groups.items():
                speakers = self._bcast_speakers.get(conf)
                if speakers is None:
                    for s in grp:
                        self.translator.connect(
                            s, [r for r in grp if r != s])
                else:
                    # broadcast conference: only speakers have legs —
                    # a speaker fans out to every other member; the
                    # listeners are fanout-only rows with no route of
                    # their own (their uplink is masked in the loop)
                    for s in grp:
                        self.translator.connect(
                            s, [r for r in grp if r != s]
                            if s in speakers else [])
        else:
            for s in sids:
                self.translator.connect(s, [r for r in sids if r != s])

    # --------------------------------------------------------------- tick
    def _on_media(self, batch: PacketBatch, _ok) -> None:
        """Decrypt once, fan out, cache per-leg copies, send.

        Pipelined mode: the fan-out re-encrypt is DISPATCHED here and
        its bytes ship at the start of the next tick's media handling
        (after the recv window — the launch overlaps the socket wait),
        same seam as MediaLoop's pipelined replies."""
        self._media_ran = True
        perf = self.loop.perf
        if self._pending_fanout:
            self._flush_fanout()
        perf.note_h2d(batch.data.nbytes +
                      np.asarray(batch.length).nbytes)
        # sync unprotect blends dispatch+compute+d2h — attributed
        # wholesale to device_compute, same as the loop's reverse chain
        with perf.phase("device_compute"):
            dec, ok, idx = self.rx_table.unprotect_rtp(
                batch, return_index=True)
        perf.note_d2h(dec.data.nbytes)
        rows = np.nonzero(ok)[0]
        if len(rows) == 0:
            return None
        sub = PacketBatch(dec.data[rows],
                          np.asarray(dec.length)[rows],
                          dec.stream[rows])
        hdr = rtp_header.parse(sub)
        # uplink loss detection: gaps in each sender's seq space queue
        # upstream NACKs (drained toward the sender by emit_feedback)
        with self.loop.tracer.span("recovery"):
            self.recovery.observe_rx(hdr.ssrc, hdr.seq, self._now)
        self._feed_bwe(sub, rows, hdr=hdr)
        if self._trunks:
            # cascade relay taps the PROTECTED ingress rows (the trunk
            # re-wraps them; participant SRTP crosses intact)
            self._relay_trunk(batch, rows, sub.stream, hdr.ssrc)
        # stamp the bridge's own abs-send-time before the fan-out so
        # every receiver leg can run receive-side GCC on its downlink
        sub, _ = self._ast.rtp_transformer.transform(sub)
        idx_sel = idx[rows]
        if self._video:
            vmask = np.isin(sub.stream, list(self._video.keys()))
            if vmask.any():
                self._forward_video(sub, np.nonzero(vmask)[0])
                keep = np.nonzero(~vmask)[0]
                if len(keep) == 0:
                    return None
                sub = PacketBatch(sub.data[keep],
                                  np.asarray(sub.length)[keep],
                                  sub.stream[keep])
                idx_sel = idx_sel[keep]
        if self.pipelined:
            with self.loop.tracer.span("forward_chain"):
                # dispatch carries its ingress origin: the flush lands
                # on a LATER tick, and the journey must charge the
                # pipelining delay to the tick the packets arrived on
                with perf.phase("dispatch"):
                    pend = self.translator.translate_async(sub, idx_sel)
                self._pending_fanout.append(
                    (pend, self.loop.journey_origin()))
            return None
        with self.loop.tracer.span("forward_chain"):
            with perf.phase("device_compute"):
                wire, recv = self.translator.translate(sub, idx_sel)
        self._emit_fanout(wire, recv)
        return None

    def _quiesce_fanout(self) -> None:
        """Ship any in-flight pipelined fan-out BEFORE mutating state it
        may still read: SRTP/translator key tensors are rewritten in
        place (a dispatched launch can alias them zero-copy on CPU),
        and a recycled row must not receive a departed endpoint's
        old-key packets.  Every mutating entry point (add/remove
        endpoint, DTLS install, video track/receiver attach) calls this
        first."""
        if self._pending_fanout:
            self._flush_fanout()

    def _flush_fanout(self) -> None:
        perf = self.loop.perf
        pending, self._pending_fanout = self._pending_fanout, []
        for pend, origin in pending:
            perf.fence(pend)
            with perf.phase("d2h_transfer"):
                out = pend.result()
            self._emit_fanout(*out, origin=origin)

    def _emit_fanout(self, wire: PacketBatch, recv: np.ndarray,
                     origin=None) -> None:
        if wire.batch_size == 0:
            return
        # a just-joined leg has no latched address yet: sending to
        # 0.0.0.0:0 would EINVAL out of sendmmsg and crash the tick
        ready = self.loop.addr_port[recv] != 0
        if not ready.any():
            return
        rr = np.nonzero(ready)[0]
        wire = PacketBatch(wire.data[rr],
                           np.asarray(wire.length)[rr],
                           wire.stream[rr])
        recv = recv[rr]
        # cache each leg's protected copy for NACK service, keyed by
        # (leg sid, SENDER ssrc) + original seq — seq survives the
        # fan-out, and two senders' seq ranges must never collide in
        # one leg's cache
        hdr = rtp_header.parse(wire)
        copies = [wire.to_bytes(i) for i in range(wire.batch_size)]
        self.cache.insert_batch(
            (recv.astype(np.int64) << 32) | hdr.ssrc.astype(np.int64),
            hdr.seq, copies, now=self._now)
        with self.loop.tracer.span("egress"):
            sent = self.loop.engine.send_batch(
                wire, self.loop.addr_ip[recv], self.loop.addr_port[recv])
            self.loop.note_journey_at(
                origin if origin is not None
                else self.loop.journey_origin(), sent, sids=recv)
        self.forwarded += sent
        # adaptive FEC over the PROTECTED per-leg copies: XOR of SRTP
        # ciphertexts is opaque, and a recovered packet still passes the
        # receiver's normal SRTP auth — FEC adds redundancy, never an
        # injection surface.  One FEC stream per (leg, sender ssrc).
        if self.recovery.fec_active():
            fec_out, fec_addr = [], []
            for j, pkt in enumerate(copies):
                fec = self.recovery.fec_protect(int(recv[j]),
                                                int(hdr.ssrc[j]), pkt)
                if fec is not None:
                    fec_out.append(fec)
                    fec_addr.append(int(recv[j]))
            if fec_out:
                fa = np.asarray(fec_addr, dtype=np.int64)
                with self.loop.tracer.span("egress"):
                    self.loop.engine.send_batch(
                        PacketBatch.from_payloads(fec_out),
                        self.loop.addr_ip[fa], self.loop.addr_port[fa])
                if self.flight is not None:
                    for fsid in set(fec_addr):
                        self.flight.record(
                            "fec_sent", sid=fsid,
                            n=fec_addr.count(fsid))

    def _feed_bwe(self, sub: PacketBatch, rows: np.ndarray,
                  hdr=None) -> None:
        """Drive the bridge's receive-side GCC from the senders'
        abs-send-time stamps.  Arrival times prefer the engine's kernel
        rx stamps (row-aligned via MediaLoop.last_rtp_arrival_ns);
        without them, the tick's host clock."""
        if hdr is None:
            hdr = rtp_header.parse(sub)
        off, dlen, found = rtp_ext.find_one_byte_ext(sub, hdr,
                                                     self.ast_ext_id)
        f = np.nonzero(found & (dlen == 3))[0]
        if len(f) == 0:
            return
        d = sub.data
        o = off[f]
        ast24 = ((d[f, o].astype(np.int64) << 16)
                 | (d[f, o + 1].astype(np.int64) << 8)
                 | d[f, o + 2].astype(np.int64))
        ats = self.loop.last_rtp_arrival_ns
        if ats is not None:
            arrival_ms = ats[rows][f].astype(np.float64) / 1e6
        else:
            arrival_ms = np.full(len(f), self._now * 1000.0)
        tids = self._transport_of[sub.stream[f].astype(np.int64)]
        self.bwe.incoming_batch(tids, arrival_ms, ast24,
                                np.asarray(sub.length)[f])
        self._bwe_fed[tids] = True

    def own_estimate_bps(self, sid: int) -> Optional[float]:
        """The bridge's current receive-side estimate for a sender leg
        (None until that sender's abs-send-time stamps have fed it)."""
        if not self._bwe_fed[sid]:
            return None
        return float(self.bwe.bitrate[sid])

    def _on_rtcp(self, batch: PacketBatch, _ok) -> None:
        """SRTCP-authenticate, then: NACK -> retransmit from the
        per-leg cache; everything else feeds RTCP termination (REMB
        aggregation, PLI dedupe).  Unauthenticated control packets are
        dropped — a spoofed NACK is a retransmission amplifier and a
        spoofed REMB caps the conference bitrate."""
        dec, ok = self.rx_table.unprotect_rtcp(batch)
        for i in np.nonzero(np.asarray(ok))[0]:
            sid = int(batch.stream[i])
            try:
                pkts = rtcp.parse_compound(dec.to_bytes(int(i)))
            except ValueError:
                continue
            self.rtcp_term.on_receiver_rtcp(sid, pkts)
            for p in pkts:
                if isinstance(p, rtcp.Nack):
                    if not self._serve_video_nack(sid, p):
                        self._serve_nack(sid, p)
                elif isinstance(p, rtcp.Remb):
                    # receiver's downlink estimate drives its simulcast
                    # layer selection
                    self._recv_bw[sid] = float(p.bitrate_bps)
                elif isinstance(p, (rtcp.ReceiverReport,
                                    rtcp.SenderReport)):
                    # reported downlink loss drives the FEC ratio
                    for rb in p.reports:
                        self.recovery.on_receiver_report(
                            rb.fraction_lost)

    def _serve_nack(self, sid: int, nack: "rtcp.Nack") -> None:
        key = (sid << 32) | (nack.media_ssrc & 0xFFFFFFFF)
        copies, missing = self.cache.lookup_nack(key, nack.lost_seqs,
                                                 return_missing=True)
        self.recovery.rtx_cache_miss += len(missing)
        if missing and self.flight is not None:
            self.flight.record("rtx_cache_miss", sid=sid,
                               ssrc=int(nack.media_ssrc),
                               n=len(missing))
        if not copies:
            return
        if not self.recovery.allow_rtx(sum(len(c) for c in copies),
                                       self._now):
            return      # over the retransmission-bandwidth budget
        out = PacketBatch.from_payloads(copies)
        with self.loop.tracer.span("egress"):
            sent = self.loop.engine.send_batch(
                out, self.loop.addr_ip[sid], self.loop.addr_port[sid])
            self.loop.note_journey(sent, sids=[sid])
        self.retransmitted += sent
        self.recovery.rtx_requests_served += len(copies)
        if self.flight is not None:
            self.flight.record("rtx_served", sid=sid,
                               ssrc=int(nack.media_ssrc), n=len(copies))
        _log.debug("nack_served", sid=sid, lost=len(nack.lost_seqs),
                   sent=sent)

    def emit_feedback(self, now: Optional[float] = None) -> int:
        """Drain RTCP termination toward each media sender: aggregated
        RR + min-REMB + merged NACKs + rate-limited PLI, SRTCP-protected
        with the sender leg's keys.  Call periodically (the reference's
        RecurringRunnable cadence); also drains the accumulation so a
        long-lived conference does not grow state unboundedly."""
        if self.degraded:
            # overload: RTCP reports are the first work shed (senders
            # coast on their last estimates; media is untouched)
            return 0
        now = time.time() if now is None else now
        sent = 0
        # periodic GCC tick: every fed sender leg's estimate advances
        # (AIMD increase in normal state, beta-cut on overuse)
        if self._bwe_fed.any():
            self.bwe.update_estimate(now * 1000.0)
        # bridge-detected uplink losses (budgeted, held off, deduped by
        # the NackScheduler) merge into the same termination window as
        # receiver-relayed NACKs
        with self.loop.tracer.span("recovery"):
            upstream = self.recovery.collect_upstream_nacks(now)
        for ssrc, seqs in upstream.items():
            self.rtcp_term.queue_nack(ssrc, seqs)
        if self._video:
            self._select_video_layers()
        for sid, ssrc in list(self._ssrc_of.items()):
            own = self.own_estimate_bps(sid)
            blobs = self.rtcp_term.make_sender_feedback(ssrc, now=now,
                                                        own_bps=own)
            # video senders also get per-layer feedback (the PLIs that
            # gate a pending layer switch are keyed by layer SSRC for
            # simulcast, by the stream SSRC for SVC)
            for track in set(self._video.values()):
                if track.sender_sid == sid:
                    for lssrc in track.layer_ssrcs:
                        blobs += self.rtcp_term.make_sender_feedback(
                            lssrc, now=now)
            # a video-only sender latches addresses on its LAYER rows,
            # not the primary sid — fall back so PLIs still reach it
            arow = sid
            if self.loop.addr_port[arow] == 0:
                for track in set(self._video.values()):
                    if track.sender_sid != sid:
                        continue
                    arow = next((l for l in track.layer_sids
                                 if self.loop.addr_port[l] != 0), sid)
            if self.loop.addr_port[arow] == 0 or not blobs:
                continue
            b = PacketBatch.from_payloads(
                [rtcp.build_compound(blobs)], stream=[sid])
            wire = self.tx_table.protect_rtcp(b)
            sent += self.loop.engine.send_batch(
                wire, self.loop.addr_ip[arow],
                self.loop.addr_port[arow])
        return sent

    def tick(self, now: Optional[float] = None) -> dict:
        self._now = time.time() if now is None else now
        self._media_ran = False
        rx = self.loop.tick()
        if self._pending_fanout and not self._media_ran:
            # no media drove _on_media this tick: flush here instead
            # (flushing a batch dispatched THIS tick would kill its
            # overlap window, hence the flag, not an rx check)
            self._flush_fanout()
        if self._dtls.pending and not self._dtls.deferred:
            # inline mode only: with a lifecycle manager attached the
            # flight pass runs off-tick (HandshakeQueue.drain)
            self._dtls.tick()
        return {"rx": rx, "forwarded": self.forwarded,
                "retransmitted": self.retransmitted}

    # ----------------------------------------------------------- resume
    def snapshot(self) -> dict:
        """Checkpoint the conference's durable state (SURVEY §5): SRTP
        indices + replay windows (both tables), the per-sender BWE bank,
        endpoint rows/keys/SSRCs, receiver REMBs and latched addresses —
        a restarted bridge resumes mid-conference without re-keying, so
        senders' SRTP counters keep authenticating and nothing glitches.

        Transient state is deliberately excluded and re-established by
        the protocol itself: mid-handshake DTLS endpoints (keyless —
        they rejoin via signaling and fresh flights), video tracks
        (re-attach via add_video_track/add_svc_track; their forwarders
        re-anchor on the next keyframe), and the NACK caches (age out
        in ~1 s anyway).
        """
        self._quiesce_fanout()
        keyed = {sid: ssrc for sid, ssrc in self._ssrc_of.items()
                 if sid in self._tx_keys}
        return {
            "capacity": self.capacity,
            "profile": self.profile.name,
            "sharded": self._mesh is not None,
            "ast_ext_id": self.ast_ext_id,
            # recover must not silently flip I/O engines: a restart in
            # the middle of an A/B perf run would contaminate the run
            "engine_mode": self.engine_mode,
            "ingest_rings": self.ingest_rings,
            "rx_table": self.rx_table.snapshot(),
            "tx_table": self.tx_table.snapshot(),
            "bwe": self.bwe.snapshot(),
            "bwe_fed": self._bwe_fed.copy(),
            "ssrc_of": keyed,
            "rx_keys": dict(self._rx_keys),
            "tx_keys": dict(self._tx_keys),
            "recv_bw": {s: bw for s, bw in self._recv_bw.items()
                        if s in keyed},
            "conf_of": {s: c for s, c in self._conf_of.items()
                        if s in keyed},
            "bcast_speakers": {c: sorted(s) for c, s in
                               self._bcast_speakers.items()},
            "addr_ip": self.loop.addr_ip.copy(),
            "addr_port": self.loop.addr_port.copy(),
        }

    @classmethod
    def restore(cls, config, snap: dict, port: int = 0,
                **kwargs) -> "SfuBridge":
        """Resume a snapshotted conference (fresh socket on `port`).

        Endpoint rows reoccupy their exact old sids (registry.reserve)
        so the restored SRTP tables and SSRC demux line up; the
        translator re-derives its per-leg session keys from the stored
        leg master keys (derivation is deterministic, RFC 3711 KDF).
        """
        from libjitsi_tpu.transform.srtp import SrtpStreamTable as _T

        kwargs.setdefault("engine_mode", snap.get("engine_mode", "auto"))
        kwargs.setdefault("ingest_rings", snap.get("ingest_rings", 1))
        bridge = cls(config, port=port, capacity=snap["capacity"],
                     profile=SrtpProfile[snap["profile"]],
                     abs_send_time_ext_id=snap["ast_ext_id"], **kwargs)
        if snap.get("sharded") and bridge._mesh is None:
            raise ValueError(
                "snapshot came from a MESH bridge; pass mesh=... to "
                "restore (resuming single-chip would silently un-shard "
                "the deployment)")
        if bridge._mesh is not None:
            # a mesh deployment must resume SHARDED, not silently
            # single-chip (same rule as ConferenceBridge.restore)
            from libjitsi_tpu.mesh import ShardedSrtpTable
            bridge.rx_table = ShardedSrtpTable.restore(
                snap["rx_table"], bridge._mesh)
            bridge.tx_table = ShardedSrtpTable.restore(
                snap["tx_table"], bridge._mesh)
        else:
            bridge.rx_table = _T.restore(snap["rx_table"])
            bridge.tx_table = _T.restore(snap["tx_table"])
        bridge.bwe = BatchedRemoteBitrateEstimator.restore(snap["bwe"])
        bridge._bwe_fed = np.asarray(snap["bwe_fed"]).copy()
        bridge._rx_keys = dict(snap["rx_keys"])
        bridge._tx_keys = dict(snap["tx_keys"])
        bridge._recv_bw = dict(snap["recv_bw"])
        bridge._conf_of = {int(s): int(c) for s, c in
                           snap.get("conf_of", {}).items()}
        bridge._bcast_speakers = {
            int(c): {int(s) for s in spk}
            for c, spk in snap.get("bcast_speakers", {}).items()}
        for sid, conf in bridge._conf_of.items():
            if conf in bridge._bcast_speakers:
                bridge.loop.set_fanout_only(
                    sid, sid not in bridge._bcast_speakers[conf])
        sids = sorted(snap["ssrc_of"])
        bridge.registry.reserve_many(sids, bridge)
        for sid in sids:
            ssrc = snap["ssrc_of"][sid]
            bridge.registry.map_ssrc(ssrc, sid)
            bridge._ssrc_of[sid] = ssrc
        bridge.translator.add_receivers(
            sids, [bridge._tx_keys[s][0] for s in sids],
            [bridge._tx_keys[s][1] for s in sids])
        bridge._rebuild_routes()
        # per-row state copies only onto RESERVED rows; anything else
        # (old video layer rows, departed endpoints) must come back
        # zeroed or a later alloc of that row would inherit a stale
        # latched address / BWE estimate
        keep = np.zeros(snap["capacity"], dtype=bool)
        keep[sids] = True
        bridge.loop.addr_ip[:] = np.where(keep, snap["addr_ip"], 0)
        bridge.loop.addr_port[:] = np.where(keep, snap["addr_port"], 0)
        bridge._bwe_fed &= keep
        stale = np.nonzero(~keep)[0]
        if len(stale):
            bridge.bwe.reset_rows(stale)
        return bridge

    def close(self) -> None:
        if self._pending_fanout:
            self._flush_fanout()     # the last tick's media still ships
        for eng in self.loop.rings:
            eng.close()
