"""SfuBridge — the videobridge-style forwarding conference as one object.

Reference: Jitsi Videobridge builds on the reference's
`RTPTranslatorImpl` + `CachingTransformer` + RTCP termination
(SURVEY §3.4, §2.2, §2.3) with one StreamRTPManager per endpoint and a
per-receiver send chain.  Here the whole SFU tick composes the dense
pieces: one batched MediaLoop (unprotect every sender's packets in one
launch), the `RtpTranslator` (decrypt-once / re-encrypt-per-leg in one
fan-out launch — grouped GCM kernel on AEAD conferences), a
`PacketCache` serving NACK retransmissions per leg, and
`RtcpTermination` (feedback dedupe/aggregation, min-REMB).

Endpoints both send and receive: `add_endpoint(ssrc, rx_key, tx_key)`
installs the sender-side SRTP row (what they send us) and the receiver
leg (what we send them); routing defaults to full mesh (everyone
forwards to everyone else).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io.loop import MediaLoop
from libjitsi_tpu.io.udp import UdpEngine
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.media_stream import StreamRegistry
from libjitsi_tpu.sfu import PacketCache, RtpTranslator
from libjitsi_tpu.sfu.rtcp_termination import RtcpTermination
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable
from libjitsi_tpu.utils.logging import get_logger

_log = get_logger("service.sfu")


class SfuBridge:
    """Secure selective-forwarding bridge on one UDP port."""

    def __init__(self, config, port: int = 0, capacity: int = 256,
                 profile: SrtpProfile =
                 SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                 recv_window_ms: int = 1,
                 kernel_timestamps: bool = False):
        self.capacity = capacity
        self.profile = profile
        self.registry = StreamRegistry(config, capacity=capacity)
        # rx_table: what endpoints SEND us (media + their SRTCP);
        # tx_table: what we send THEM (our SRTCP feedback; media forward
        # crypto is the translator's per-leg fan-out)
        self.rx_table = SrtpStreamTable(capacity, profile)
        self.tx_table = SrtpStreamTable(capacity, profile)
        self.translator = RtpTranslator(capacity=capacity,
                                        profile=profile)
        self.cache = PacketCache()
        self.rtcp_term = RtcpTermination(bridge_ssrc=0x5F0BFF)
        self.loop = MediaLoop(
            UdpEngine(port=port, max_batch=4 * capacity,
                      kernel_timestamps=kernel_timestamps),
            self.registry, on_media=self._on_media,
            on_rtcp=self._on_rtcp, chain=None,
            recv_window_ms=recv_window_ms)
        self.port = self.loop.engine.port
        self._ssrc_of: Dict[int, int] = {}     # sid -> sender ssrc
        self.forwarded = 0
        self.retransmitted = 0

    # ---------------------------------------------------------- endpoints
    def add_endpoint(self, ssrc: int, rx_key: Tuple[bytes, bytes],
                     tx_key: Tuple[bytes, bytes]) -> int:
        if ssrc in self._ssrc_of.values():
            raise ValueError(f"ssrc {ssrc:#x} already joined")
        sid = self.registry.alloc(self)
        self.rx_table.add_stream(sid, *rx_key)
        self.tx_table.add_stream(sid, *tx_key)
        self.translator.add_receiver(sid, *tx_key)
        self.registry.map_ssrc(ssrc, sid)
        self._ssrc_of[sid] = ssrc & 0xFFFFFFFF
        self._rebuild_routes()
        _log.info("endpoint_join", sid=sid, ssrc=ssrc)
        return sid

    def remove_endpoint(self, sid: int) -> None:
        ssrc = self._ssrc_of.pop(sid, None)
        if ssrc is not None:
            self.registry.unmap_ssrc(ssrc)
        self.rx_table.remove_stream(sid)
        self.tx_table.remove_stream(sid)
        self.translator.disconnect(sid)
        self.translator.remove_receiver(sid)
        self.rtcp_term.forget_receiver(sid)
        self.loop.addr_ip[sid] = 0
        self.loop.addr_port[sid] = 0
        self.registry.release(sid)
        self._rebuild_routes()
        _log.info("endpoint_leave", sid=sid)

    def _rebuild_routes(self) -> None:
        """Full mesh: every sender forwards to every OTHER endpoint."""
        sids = sorted(self._ssrc_of)
        for s in sids:
            self.translator.connect(s, [r for r in sids if r != s])

    # --------------------------------------------------------------- tick
    def _on_media(self, batch: PacketBatch, _ok) -> None:
        """Decrypt once, fan out, cache per-leg copies, send."""
        dec, ok, idx = self.rx_table.unprotect_rtp(batch,
                                                   return_index=True)
        rows = np.nonzero(ok)[0]
        if len(rows) == 0:
            return None
        sub = PacketBatch(dec.data[rows],
                          np.asarray(dec.length)[rows],
                          dec.stream[rows])
        wire, recv = self.translator.translate(sub, idx[rows])
        if wire.batch_size == 0:
            return None
        # a just-joined leg has no latched address yet: sending to
        # 0.0.0.0:0 would EINVAL out of sendmmsg and crash the tick
        ready = self.loop.addr_port[recv] != 0
        if not ready.any():
            return None
        rr = np.nonzero(ready)[0]
        wire = PacketBatch(wire.data[rr],
                           np.asarray(wire.length)[rr],
                           wire.stream[rr])
        recv = recv[rr]
        # cache each leg's protected copy for NACK service, keyed by
        # (leg sid, SENDER ssrc) + original seq — seq survives the
        # fan-out, and two senders' seq ranges must never collide in
        # one leg's cache
        from libjitsi_tpu.rtp import header as rtp_header

        hdr = rtp_header.parse(wire)
        self.cache.insert_batch(
            (recv.astype(np.int64) << 32) | hdr.ssrc.astype(np.int64),
            hdr.seq,
            [wire.to_bytes(i) for i in range(wire.batch_size)],
            now=self._now)
        sent = self.loop.engine.send_batch(
            wire, self.loop.addr_ip[recv], self.loop.addr_port[recv])
        self.forwarded += sent
        return None

    def _on_rtcp(self, batch: PacketBatch, _ok) -> None:
        """SRTCP-authenticate, then: NACK -> retransmit from the
        per-leg cache; everything else feeds RTCP termination (REMB
        aggregation, PLI dedupe).  Unauthenticated control packets are
        dropped — a spoofed NACK is a retransmission amplifier and a
        spoofed REMB caps the conference bitrate."""
        dec, ok = self.rx_table.unprotect_rtcp(batch)
        for i in np.nonzero(np.asarray(ok))[0]:
            sid = int(batch.stream[i])
            try:
                pkts = rtcp.parse_compound(dec.to_bytes(int(i)))
            except ValueError:
                continue
            self.rtcp_term.on_receiver_rtcp(sid, pkts)
            for p in pkts:
                if isinstance(p, rtcp.Nack):
                    self._serve_nack(sid, p)

    def _serve_nack(self, sid: int, nack: "rtcp.Nack") -> None:
        key = (sid << 32) | (nack.media_ssrc & 0xFFFFFFFF)
        copies = self.cache.lookup_nack(key, nack.lost_seqs)
        if not copies:
            return
        out = PacketBatch.from_payloads(copies)
        sent = self.loop.engine.send_batch(
            out, self.loop.addr_ip[sid], self.loop.addr_port[sid])
        self.retransmitted += sent
        _log.debug("nack_served", sid=sid, lost=len(nack.lost_seqs),
                   sent=sent)

    def emit_feedback(self, now: Optional[float] = None) -> int:
        """Drain RTCP termination toward each media sender: aggregated
        RR + min-REMB + merged NACKs + rate-limited PLI, SRTCP-protected
        with the sender leg's keys.  Call periodically (the reference's
        RecurringRunnable cadence); also drains the accumulation so a
        long-lived conference does not grow state unboundedly."""
        now = time.time() if now is None else now
        sent = 0
        for sid, ssrc in list(self._ssrc_of.items()):
            if self.loop.addr_port[sid] == 0:
                # no address: still drain to bound memory
                self.rtcp_term.make_sender_feedback(ssrc, now=now)
                continue
            blobs = self.rtcp_term.make_sender_feedback(ssrc, now=now)
            if not blobs:
                continue
            b = PacketBatch.from_payloads(
                [rtcp.build_compound(blobs)], stream=[sid])
            wire = self.tx_table.protect_rtcp(b)
            sent += self.loop.engine.send_batch(
                wire, self.loop.addr_ip[sid], self.loop.addr_port[sid])
        return sent

    def tick(self, now: Optional[float] = None) -> dict:
        self._now = time.time() if now is None else now
        rx = self.loop.tick()
        return {"rx": rx, "forwarded": self.forwarded,
                "retransmitted": self.retransmitted}

    def close(self) -> None:
        self.loop.engine.close()
