from libjitsi_tpu.service.bridge import ConferenceBridge  # noqa: F401
