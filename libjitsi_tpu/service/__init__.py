from libjitsi_tpu.service.bridge import ConferenceBridge  # noqa: F401
from libjitsi_tpu.service.sfu_bridge import SfuBridge  # noqa: F401
from libjitsi_tpu.service.obs_server import ObservabilityServer  # noqa: F401
from libjitsi_tpu.service.supervisor import (  # noqa: F401
    BridgeSupervisor, SupervisorConfig)
