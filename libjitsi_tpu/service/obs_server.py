"""ObservabilityServer: stdlib-http surface for the metrics plane.

A threaded `http.server` (no framework, no new deps) serving:

  /metrics              Prometheus text exposition (registry render);
                        negotiates OpenMetrics via the Accept header —
                        an OpenMetrics scrape gets exemplars on
                        histogram buckets (trace ids linking tail
                        latency to flight-recorder `hdr` events) and
                        the `# EOF` terminator
  /healthz              supervisor health JSON; 503 when stalled
  /debug/slo            SloEngine status: per-SLO burn rates over the
                        four windows, states, thresholds; plus the
                        supervisor's host/device phase attribution
  /debug/capacity       CapacityModel status: per-resource utilization
                        fits, users-per-chip headroom, bottleneck,
                        forecast-refusal state (utils/capacity.py)
  /debug/device         live device-memory stats per device
                        (utils/profiling.device_memory)
  /debug/streams/<sid>  flight-recorder dump for one stream
  /debug/postmortems    supervisor's bounded post-mortem list
  /debug/fleet          cross-bridge journey view: scrapes every
                        registered peer's /metrics (OpenMetrics) and
                        stitches hop-labeled packet_journey_seconds
                        exemplars by trace id — one packet's path
                        across the cascade, bridged by the trunk's
                        trace extension (mesh/cascade.py)

The server binds an ephemeral port by default (`port=0`; read `.port`
after `start()`), runs on a daemon thread, and never touches the data
path — `/metrics` renders from the same dense arrays the tick already
maintains, so a scrape costs one string build, not a lock on the loop.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from libjitsi_tpu.utils.logging import get_logger
from libjitsi_tpu.utils.metrics import (CONTENT_TYPE_OPENMETRICS,
                                        CONTENT_TYPE_PROM,
                                        _parse_labels, _split_exemplar,
                                        parse_exposition,
                                        process_families_text)

_log = get_logger("service.obs")

CONTENT_TYPE_METRICS = CONTENT_TYPE_PROM


def _jsonable(obj):
    """json.dumps default= hook: numpy scalars/arrays -> python."""
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


JOURNEY_FAMILY = "packet_journey_seconds"


def _journey_exemplars(text: str) -> List[dict]:
    """Hop-labeled journey exemplars out of one OpenMetrics scrape:
    `{trace_id, hop, seconds, origin}` per `_bucket` exemplar.  The
    trace id is the stitch key — the origin bridge stamps it on the
    trunk trace extension, so the SAME id shows up under `hop="local"`
    on the origin and `hop="bX-bY"` on the destination."""
    out: List[dict] = []
    seen = set()
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        sample, ex = _split_exemplar(line)
        if ex is None or not ex.startswith("{"):
            continue
        brace, close = sample.find("{"), sample.rfind("}")
        name = sample[:brace] if brace >= 0 else sample.split()[0]
        # family names carry the registry namespace prefix
        if not name.endswith(f"{JOURNEY_FAMILY}_bucket"):
            continue
        labels = (_parse_labels(sample[brace + 1: close])
                  if 0 <= brace < close else None) or {}
        eclose = ex.rfind("}")
        elabels = (_parse_labels(ex[1:eclose])
                   if eclose > 0 else None) or {}
        tail = ex[eclose + 1:].split()
        tid = elabels.get("trace_id")
        if tid is None or not tail:
            continue
        try:
            seconds = float(tail[0])
        except ValueError:
            continue
        hop = labels.get("hop", "")
        key = (tid, hop, seconds)
        if key in seen:                 # same exemplar, +Inf slot
            continue
        seen.add(key)
        out.append({"trace_id": tid, "hop": hop, "seconds": seconds,
                    "origin": elabels.get("origin")})
    return out


def stitch_journeys(scrapes: Dict[str, str]) -> dict:
    """Merge several bridges' OpenMetrics scrapes into one fleet
    journey view.  `scrapes` maps bridge name -> exposition text; the
    result groups hop-labeled journey exemplars by trace id and marks
    the ids observed on more than one bridge as STITCHED — the packet
    demonstrably crossed the trunk and kept its trace.  Shared by
    `/debug/fleet` (live) and `scripts/trace_report.py
    --merge-bridges` (offline twin)."""
    bridges: Dict[str, dict] = {}
    journeys: Dict[str, dict] = {}
    for name, text in sorted(scrapes.items()):
        _types, samples, _errs = parse_exposition(text)
        hops = {
            labels["hop"]: value
            for sname, labels, value in samples
            if sname.endswith(f"{JOURNEY_FAMILY}_count")
            and "hop" in labels}
        exs = _journey_exemplars(text)
        bridges[name] = {"hops": hops, "exemplars": len(exs)}
        for e in exs:
            j = journeys.setdefault(e["trace_id"], {
                "trace_id": e["trace_id"], "spans": []})
            j["spans"].append({"bridge": name, "hop": e["hop"],
                               "seconds": e["seconds"],
                               "origin": e["origin"]})
    for j in journeys.values():
        j["bridges"] = sorted({s["bridge"] for s in j["spans"]})
        j["stitched"] = len(j["bridges"]) > 1
    stitched = sorted(t for t, j in journeys.items() if j["stitched"])
    return {
        "bridges": bridges,
        "journeys": sorted(journeys.values(),
                           key=lambda j: (-len(j["bridges"]),
                                          j["trace_id"])),
        "stitched_trace_ids": stitched,
    }


def fetch_metrics(base_url: str, timeout: float = 1.0) -> str:
    """One peer scrape, OpenMetrics negotiated (exemplars ride only on
    the OM content type)."""
    req = urllib.request.Request(
        base_url.rstrip("/") + "/metrics",
        headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


class ObservabilityServer:
    """Serve /metrics, /healthz and flight-recorder debug dumps."""

    def __init__(self, metrics=None, supervisor=None, flight=None,
                 slo=None, capacity=None, host: str = "127.0.0.1",
                 port: int = 0, name: str = "local",
                 peers: Optional[Dict[str, str]] = None):
        self.metrics = metrics
        self.supervisor = supervisor
        # explicit flight wins; else follow the supervisor's recorder
        self._flight = flight
        # explicit slo engine wins; else follow the supervisor's
        self._slo = slo
        # explicit capacity model wins; else follow the supervisor's
        self._capacity = capacity
        self.host = host
        self.port = int(port)
        # fleet axis: this bridge's name plus peer name -> base URL,
        # scraped (OpenMetrics) by /debug/fleet for journey stitching
        self.name = str(name)
        self.peers: Dict[str, str] = dict(peers or {})
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def add_peer(self, name: str, base_url: str) -> None:
        self.peers[str(name)] = str(base_url)

    @property
    def flight(self):
        if self._flight is not None:
            return self._flight
        return getattr(self.supervisor, "flight", None)

    @property
    def slo(self):
        if self._slo is not None:
            return self._slo
        return getattr(self.supervisor, "slo", None)

    @property
    def capacity(self):
        if self._capacity is not None:
            return self._capacity
        return getattr(self.supervisor, "capacity", None)

    # ---------------------------------------------------------- handlers
    def _metrics_text(self, openmetrics: bool = False) -> str:
        if self.metrics is None:
            return "# EOF\n" if openmetrics else "\n"
        # standard process families ride every scrape, un-namespaced
        # (stock Prometheus `up`/restart detection); scrape_duration is
        # THIS response's registry render wall time.  The OpenMetrics
        # `# EOF` terminator must stay last, so splice before it.
        t0 = time.perf_counter()
        text = self.metrics.render(openmetrics=openmetrics)
        extra = process_families_text(time.perf_counter() - t0)
        if openmetrics and text.endswith("# EOF\n"):
            return text[:-len("# EOF\n")] + extra + "# EOF\n"
        return text + extra

    def _health(self) -> dict:
        if self.supervisor is None:
            return {"ok": True, "state": "unknown"}
        h = dict(self.supervisor.health())
        h["ok"] = h.get("state") != "stalled"
        return h

    def _route(self, path: str, accept: str = ""):
        """-> (status, content_type, body_bytes)"""
        if path == "/metrics":
            # content negotiation the way Prometheus does it: the
            # scraper opts into OpenMetrics explicitly; default stays
            # the 0.0.4 text format (exemplar-free)
            om = "application/openmetrics-text" in (accept or "")
            ctype = CONTENT_TYPE_OPENMETRICS if om \
                else CONTENT_TYPE_METRICS
            return (200, ctype,
                    self._metrics_text(openmetrics=om).encode("utf-8"))
        if path == "/debug/slo":
            slo = self.slo
            if slo is None:
                return (404, "application/json",
                        b'{"error": "no slo engine attached"}')
            doc = slo.status()
            # host/device attribution rides along: a burning SLO plus
            # `bound: host` names the fix (ingress path), not just the
            # symptom
            sup = self.supervisor
            if sup is not None and hasattr(sup, "phase_attribution"):
                doc["attribution"] = sup.phase_attribution()
            return (200, "application/json",
                    json.dumps(doc,
                               default=_jsonable).encode("utf-8"))
        if path == "/debug/capacity":
            cap = self.capacity
            if cap is None:
                return (404, "application/json",
                        b'{"error": "no capacity model attached"}')
            return (200, "application/json",
                    json.dumps(cap.status(),
                               default=_jsonable).encode("utf-8"))
        if path == "/debug/device":
            # live device-memory stats (utils/profiling.device_memory):
            # leak-shaped growth is visible without attaching a profiler
            try:
                import jax

                from libjitsi_tpu.utils.profiling import device_memory

                devices = [device_memory(d) for d in jax.devices()]
                return (200, "application/json",
                        json.dumps({"devices": devices},
                                   default=_jsonable).encode("utf-8"))
            except Exception as exc:
                return (500, "application/json",
                        json.dumps({"error": repr(exc)})
                        .encode("utf-8"))
        if path == "/healthz":
            h = self._health()
            code = 200 if h.get("ok") else 503
            return (code, "application/json",
                    json.dumps(h, default=_jsonable).encode("utf-8"))
        if path.startswith("/debug/streams/"):
            flight = self.flight
            sid_s = path[len("/debug/streams/"):]
            if flight is None or not sid_s.lstrip("-").isdigit():
                return (404, "application/json", b'{"error": "no such '
                        b'stream or no flight recorder"}')
            body = json.dumps(flight.dump(int(sid_s)),
                              default=_jsonable)
            return (200, "application/json", body.encode("utf-8"))
        if path == "/debug/streams":
            flight = self.flight
            streams = flight.streams() if flight is not None else []
            return (200, "application/json",
                    json.dumps({"streams": streams}).encode("utf-8"))
        if path == "/debug/postmortems":
            pms = list(getattr(self.supervisor, "postmortems", ()))
            return (200, "application/json",
                    json.dumps(pms, default=_jsonable).encode("utf-8"))
        if path == "/debug/fleet":
            # own registry renders in-process (no self-scrape over
            # HTTP); peers are scraped best-effort — a dead peer shows
            # up under `errors`, it doesn't 500 the fleet view
            scrapes = {self.name: self._metrics_text(openmetrics=True)}
            errors: Dict[str, str] = {}
            for pname, base in sorted(self.peers.items()):
                try:
                    scrapes[pname] = fetch_metrics(base)
                except Exception as exc:
                    errors[pname] = repr(exc)
            doc = stitch_journeys(scrapes)
            doc["self"] = self.name
            doc["peers"] = sorted(self.peers)
            doc["errors"] = errors
            sup = self.supervisor
            if sup is not None and hasattr(sup, "trunk_owd_s"):
                doc["trunk_owd_s"] = float(sup.trunk_owd_s)
            return (200, "application/json",
                    json.dumps(doc, default=_jsonable).encode("utf-8"))
        return (404, "application/json", b'{"error": "not found"}')

    # ----------------------------------------------------------- control
    def start(self) -> "ObservabilityServer":
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                accept = self.headers.get("Accept", "")
                try:
                    status, ctype, body = outer._route(path, accept)
                except Exception as exc:   # render must never kill scrape
                    status, ctype = 500, "application/json"
                    body = json.dumps(
                        {"error": repr(exc)}).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                _log.debug("http", line=(fmt % args))

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="obs-server", daemon=True)
        self._thread.start()
        _log.info("obs_server_started", host=self.host, port=self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "ObservabilityServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
