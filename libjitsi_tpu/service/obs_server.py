"""ObservabilityServer: stdlib-http surface for the metrics plane.

A threaded `http.server` (no framework, no new deps) serving:

  /metrics              Prometheus text exposition (registry render);
                        negotiates OpenMetrics via the Accept header —
                        an OpenMetrics scrape gets exemplars on
                        histogram buckets (trace ids linking tail
                        latency to flight-recorder `hdr` events) and
                        the `# EOF` terminator
  /healthz              supervisor health JSON; 503 when stalled
  /debug/slo            SloEngine status: per-SLO burn rates over the
                        four windows, states, thresholds; plus the
                        supervisor's host/device phase attribution
  /debug/device         live device-memory stats per device
                        (utils/profiling.device_memory)
  /debug/streams/<sid>  flight-recorder dump for one stream
  /debug/postmortems    supervisor's bounded post-mortem list

The server binds an ephemeral port by default (`port=0`; read `.port`
after `start()`), runs on a daemon thread, and never touches the data
path — `/metrics` renders from the same dense arrays the tick already
maintains, so a scrape costs one string build, not a lock on the loop.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from libjitsi_tpu.utils.logging import get_logger
from libjitsi_tpu.utils.metrics import (CONTENT_TYPE_OPENMETRICS,
                                        CONTENT_TYPE_PROM)

_log = get_logger("service.obs")

CONTENT_TYPE_METRICS = CONTENT_TYPE_PROM


def _jsonable(obj):
    """json.dumps default= hook: numpy scalars/arrays -> python."""
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


class ObservabilityServer:
    """Serve /metrics, /healthz and flight-recorder debug dumps."""

    def __init__(self, metrics=None, supervisor=None, flight=None,
                 slo=None, host: str = "127.0.0.1", port: int = 0):
        self.metrics = metrics
        self.supervisor = supervisor
        # explicit flight wins; else follow the supervisor's recorder
        self._flight = flight
        # explicit slo engine wins; else follow the supervisor's
        self._slo = slo
        self.host = host
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def flight(self):
        if self._flight is not None:
            return self._flight
        return getattr(self.supervisor, "flight", None)

    @property
    def slo(self):
        if self._slo is not None:
            return self._slo
        return getattr(self.supervisor, "slo", None)

    # ---------------------------------------------------------- handlers
    def _metrics_text(self, openmetrics: bool = False) -> str:
        if self.metrics is None:
            return "# EOF\n" if openmetrics else "\n"
        return self.metrics.render(openmetrics=openmetrics)

    def _health(self) -> dict:
        if self.supervisor is None:
            return {"ok": True, "state": "unknown"}
        h = dict(self.supervisor.health())
        h["ok"] = h.get("state") != "stalled"
        return h

    def _route(self, path: str, accept: str = ""):
        """-> (status, content_type, body_bytes)"""
        if path == "/metrics":
            # content negotiation the way Prometheus does it: the
            # scraper opts into OpenMetrics explicitly; default stays
            # the 0.0.4 text format (exemplar-free)
            om = "application/openmetrics-text" in (accept or "")
            ctype = CONTENT_TYPE_OPENMETRICS if om \
                else CONTENT_TYPE_METRICS
            return (200, ctype,
                    self._metrics_text(openmetrics=om).encode("utf-8"))
        if path == "/debug/slo":
            slo = self.slo
            if slo is None:
                return (404, "application/json",
                        b'{"error": "no slo engine attached"}')
            doc = slo.status()
            # host/device attribution rides along: a burning SLO plus
            # `bound: host` names the fix (ingress path), not just the
            # symptom
            sup = self.supervisor
            if sup is not None and hasattr(sup, "phase_attribution"):
                doc["attribution"] = sup.phase_attribution()
            return (200, "application/json",
                    json.dumps(doc,
                               default=_jsonable).encode("utf-8"))
        if path == "/debug/device":
            # live device-memory stats (utils/profiling.device_memory):
            # leak-shaped growth is visible without attaching a profiler
            try:
                import jax

                from libjitsi_tpu.utils.profiling import device_memory

                devices = [device_memory(d) for d in jax.devices()]
                return (200, "application/json",
                        json.dumps({"devices": devices},
                                   default=_jsonable).encode("utf-8"))
            except Exception as exc:
                return (500, "application/json",
                        json.dumps({"error": repr(exc)})
                        .encode("utf-8"))
        if path == "/healthz":
            h = self._health()
            code = 200 if h.get("ok") else 503
            return (code, "application/json",
                    json.dumps(h, default=_jsonable).encode("utf-8"))
        if path.startswith("/debug/streams/"):
            flight = self.flight
            sid_s = path[len("/debug/streams/"):]
            if flight is None or not sid_s.lstrip("-").isdigit():
                return (404, "application/json", b'{"error": "no such '
                        b'stream or no flight recorder"}')
            body = json.dumps(flight.dump(int(sid_s)),
                              default=_jsonable)
            return (200, "application/json", body.encode("utf-8"))
        if path == "/debug/streams":
            flight = self.flight
            streams = flight.streams() if flight is not None else []
            return (200, "application/json",
                    json.dumps({"streams": streams}).encode("utf-8"))
        if path == "/debug/postmortems":
            pms = list(getattr(self.supervisor, "postmortems", ()))
            return (200, "application/json",
                    json.dumps(pms, default=_jsonable).encode("utf-8"))
        return (404, "application/json", b'{"error": "not found"}')

    # ----------------------------------------------------------- control
    def start(self) -> "ObservabilityServer":
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                accept = self.headers.get("Accept", "")
                try:
                    status, ctype, body = outer._route(path, accept)
                except Exception as exc:   # render must never kill scrape
                    status, ctype = 500, "application/json"
                    body = json.dumps(
                        {"error": repr(exc)}).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                _log.debug("http", line=(fmt % args))

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="obs-server", daemon=True)
        self._thread.start()
        _log.info("obs_server_started", host=self.host, port=self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "ObservabilityServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
