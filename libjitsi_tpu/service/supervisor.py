"""Supervised runtime: watchdog, overload shedding, stream quarantine,
and crash-restart recovery from crypto checkpoints.

The reference runs inside a JVM container that supplies process
supervision; this framework is its own server process, so liveness and
recovery are in scope (SURVEY §5 robustness gap).  One
`BridgeSupervisor` wraps a bridge's tick and layers four mechanisms:

1. **Watchdog** — every tick is timed against a deadline (default: the
   ptime budget).  Consecutive overruns drive a health state machine
   (healthy → overloaded → stalled) exported via MetricsRegistry, so an
   external orchestrator can probe liveness without touching media.

2. **Graceful degradation** — sustained overload walks an escalation
   ladder instead of letting the tick fall behind unboundedly:
   level 1 shrinks the recv batching window to 0 (poll, don't wait),
   level 2 sets `bridge.degraded` (skips speaker scoring / egress level
   stamping / RTCP report generation — work whose absence degrades UX,
   not correctness).  On a bridge with a loss-recovery controller
   (`bridge.recovery`, sfu/recovery.py) two more rungs precede stream
   loss: level 3 sheds FEC redundancy, level 4 shrinks the
   retransmission budget; only then (level 5+, or 3+ without a
   controller) are the lowest-priority streams shed deterministically.
   Recovery walks the same ladder back down once ticks meet the
   deadline again, restoring shed streams LIFO.

3. **Stream quarantine** — per-stream sliding windows over the SRTP
   auth-failure and replay-rejection counters.  A stream exceeding the
   threshold (key mismatch, replay attack, or a corrupting middlebox)
   is dropped at ingress — BEFORE the source-address latch, so a
   spoofing sender can't redirect return media — and re-admitted after
   an exponentially-backed-off ban.

4. **Crash-restart recovery** — periodic whole-bridge snapshots into a
   single versioned checkpoint file (atomic rename), and a `recover()`
   path that reopens sockets with bounded retry + backoff and restores
   the bridge with SRTP ROC/replay state intact, proven bit-exact by
   tests/test_chaos_recovery.py.
"""

from __future__ import annotations

import os
import pickle
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.utils.flight import FlightRecorder
from libjitsi_tpu.utils.health import (ExponentialBackoff, SlidingWindowCounter,
                                       Watchdog, retrying, state_code)
from libjitsi_tpu.utils.tracing import PipelineTracer

CKPT_MAGIC = "ljt-ckpt"
CKPT_VERSION = 1


@dataclass
class SupervisorConfig:
    """Knobs, all per-tick counts unless suffixed otherwise.

    Quarantine thresholds are windowed totals: an SSRC is banned when
    its last `quarantine_window` ticks accumulate that many SRTP auth
    failures / replay rejections.  Replay's threshold is much higher —
    reordering and duplication produce benign replay hits, only a storm
    (attack or broken sender) should convict.
    """

    deadline_ms: float = 20.0
    overload_after: int = 3          # consecutive overruns -> escalate
    stall_after: int = 25            # consecutive overruns -> STALLED
    overload_exit: int = 5           # consecutive good ticks -> de-escalate
    shed_step: int = 4               # streams shed per level-3+ escalation
    # stage attribution: when one stage owns at least this share of the
    # tick's budget ledger, escalation jumps to the rung that targets
    # that stage (forward_chain -> shed FEC, ingress -> shrink the recv
    # window) instead of walking the wall-time ladder in order
    stage_share_threshold: float = 0.6
    quarantine_window: int = 50      # ticks of history per stream
    quarantine_auth_threshold: int = 20
    quarantine_replay_threshold: int = 200
    quarantine_backoff_ticks: int = 50    # first ban length
    quarantine_backoff_cap: int = 1600    # ban length ceiling
    checkpoint_every: int = 0        # ticks between checkpoints; 0 = off
    checkpoint_path: Optional[str] = None


class BridgeSupervisor:
    """Wraps ConferenceBridge / SfuBridge ticks with the four mechanisms
    above.  Call `sup.tick()` wherever you called `bridge.tick()`; the
    bridge result passes through unchanged.
    """

    def __init__(self, bridge, config: Optional[SupervisorConfig] = None,
                 metrics=None, priorities: Optional[Dict[int, int]] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 flight: Optional[FlightRecorder] = None,
                 slo=None):
        self.bridge = bridge
        self.cfg = config or SupervisorConfig()
        self.loop = getattr(bridge, "loop", bridge)
        self.clock = clock
        self.priorities = priorities or {}
        # flight recorder: every destructive action below (quarantine,
        # shed, recover) dumps a post-mortem naming its trigger
        self.flight = flight if flight is not None else FlightRecorder()
        self.postmortems: deque = deque(maxlen=32)
        # optional SloEngine (utils/slo.py): ticked here so its windows
        # advance on the same cadence as the watchdog, and its worst
        # state rides on every ladder_escalate event
        self.slo = slo
        if slo is not None and getattr(slo, "flight", None) is None:
            slo.flight = self.flight
        self._attach_flight()
        # stage-budget ledger drained from the loop's PipelineTracer
        # each tick: overload events name the dominant stage instead of
        # just "the tick was slow"
        self.tracer: Optional[PipelineTracer] = getattr(
            self.loop, "tracer", None)
        self.last_ledger: Dict[str, float] = {}
        # host/device phase ledger (utils/perf.PhaseProfiler via the
        # tracer): escalations say host-bound vs device-bound, not just
        # which stage.  getattr-guarded — test stubs carry only
        # take_ledger
        self.last_phases: Dict[str, float] = {}
        cap = self.loop.registry.capacity
        self.watchdog = Watchdog(self.cfg.deadline_ms / 1000.0,
                                 overload_after=self.cfg.overload_after,
                                 stall_after=self.cfg.stall_after)
        self._auth_win = SlidingWindowCounter(cap, self.cfg.quarantine_window)
        self._replay_win = SlidingWindowCounter(cap,
                                                self.cfg.quarantine_window)
        # baseline the failure counters at ATTACH time: a supervisor
        # adopting a long-running (or just-restored) bridge must judge
        # fresh failures only, not replay history as a sudden burst
        table = getattr(bridge, "rx_table", None)
        if table is not None and hasattr(table, "auth_fail"):
            self._last_auth = np.asarray(table.auth_fail[:cap]).copy()
            self._last_replay = np.asarray(
                table.replay_reject[:cap]).copy()
        else:
            self._last_auth = np.zeros(cap, dtype=np.int64)
            self._last_replay = np.zeros(cap, dtype=np.int64)
        self._ban = ExponentialBackoff(self.cfg.quarantine_backoff_ticks,
                                       cap=self.cfg.quarantine_backoff_cap)
        self.level = 0               # current escalation-ladder rung
        self._rungs: List[str] = []  # actions taken, LIFO unwind order
        self._good = 0               # consecutive on-deadline ticks
        self._shed: List[int] = []   # shed sids, LIFO restore order
        self._shed_set: set = set()
        # sids evicted by the lifecycle plane (stream LEFT, the slot is
        # dead or recycled): distinct from overload sheds, so the LIFO
        # unwind never "restores" a departed stream
        self._evicted: set = set()
        # StreamLifecycleManager attaches itself here; when present its
        # commit barrier + off-tick install stage run between ticks
        self.lifecycle = None
        # optional AdaptiveBatcher (io/batching.py): ticked on this
        # cadence; the recv_window rung clamps its window writes so the
        # ladder and the tuner never fight over the same knob
        self.batcher = None
        # optional CapacityModel (utils/capacity.py): fed each tick,
        # consulted by admission_decision (capacity_forecast) and the
        # lifecycle plane's placement steering / retry-after hints
        self.capacity = None
        self.last_tick_s = 0.0
        self._quarantined: Dict[int, int] = {}  # sid -> release tick
        self._q_strikes: Dict[int, int] = {}    # sid -> conviction count
        self.quarantine_total = 0
        self._saved_window: Optional[float] = None
        self.ticks = 0
        self.checkpoints_written = 0
        if metrics is not None:
            self.register_metrics(metrics)

    def _attach_flight(self) -> None:
        """Hand the recorder to every pipeline piece that can feed it
        (loop header samples, recovery-ladder actions, bridge events).
        Only objects that declare a `flight` slot participate."""
        for obj in (self.loop, self.bridge,
                    getattr(self.bridge, "recovery", None)):
            if obj is not None and hasattr(obj, "flight"):
                obj.flight = self.flight

    # ------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None):
        lc = self.lifecycle
        if lc is not None:
            # bracket the data path with the compile-cache guard: any
            # compile event landing inside this window is a lifecycle
            # bug (shapes must be warmed off-tick)
            lc.tick_begin()
        t0 = self.clock()
        result = (self.bridge.tick(now=now) if now is not None
                  else self.bridge.tick())
        self.last_tick_s = self.clock() - t0
        over = self.watchdog.observe(self.last_tick_s)
        if lc is not None:
            lc.tick_end()
        if self.tracer is not None:
            self.last_ledger = self.tracer.take_ledger()
            take_phases = getattr(self.tracer, "take_phase_ledger",
                                  None)
            if take_phases is not None:
                phases = take_phases()
                if phases:       # sampled ticks only; keep last split
                    self.last_phases = phases
        self.ticks += 1
        if self.slo is not None:
            self.slo.on_tick()
        if self.batcher is not None:
            self.batcher.on_tick()
        if self.capacity is not None:
            self.capacity.on_tick(self)
        self._update_quarantine()
        if over:
            self._good = 0
            # one rung per `overload_after` consecutive overruns: graded
            # pressure, not a free-fall to full shedding
            if (self.watchdog.consecutive % self.cfg.overload_after) == 0:
                self._escalate()
        else:
            self._good += 1
            if self.level > 0 and self._good >= self.cfg.overload_exit:
                self._deescalate()
                self._good = 0
        if (self.cfg.checkpoint_every
                and self.ticks % self.cfg.checkpoint_every == 0):
            self.save_checkpoint()
        if lc is not None:
            # between-ticks window: flip staged streams live (commit
            # barrier), then stage the next admit/evict wave off-tick
            lc.run_between_ticks(now=now)
        return result

    # ------------------------------------------- overload escalation

    #: wall-time rung order (the PR-2 ladder); recovery-only rungs are
    #: skipped on bridges without a controller, and `shed_streams`
    #: repeats once every named rung is held
    LADDER = ("recv_window", "degrade", "shed_fec", "throttle_rtx")

    def _slo_state(self) -> str:
        return self.slo.state() if self.slo is not None else "none"

    def _pick_rung(self, stage: Optional[str], share: float,
                   rec) -> str:
        """Stage-attributed rung choice: when one stage owns the tick
        budget, act on THAT stage — shed FEC only when forward_chain
        dominates, shrink the recv window only when ingress does.  No
        dominant stage (or its rung already held) falls back to the
        wall-time ladder order."""
        taken = set(self._rungs)
        if share >= self.cfg.stage_share_threshold:
            if (stage == "forward_chain" and rec is not None
                    and "shed_fec" not in taken):
                return "shed_fec"
            if stage == "ingress" and "recv_window" not in taken:
                return "recv_window"
        for rung in self.LADDER:
            if rung in ("shed_fec", "throttle_rtx") and rec is None:
                continue
            if rung not in taken:
                return rung
        return "shed_streams"

    def _apply_rung(self, rung: str) -> None:
        rec = getattr(self.bridge, "recovery", None)
        if rung == "recv_window":
            # stop waiting for packets: the batching window is latency
            # the tick can't afford while behind
            self._saved_window = getattr(self.loop, "recv_window_ms",
                                         None)
            if self._saved_window is not None:
                self.loop.recv_window_ms = 0
            if self.batcher is not None:
                self.batcher.clamp_window(True)
        elif rung == "degrade":
            self.bridge.degraded = True
        elif rung == "shed_fec":
            # loss-recovery coupling: FEC overhead is the first
            # bandwidth/CPU to go — redundancy sheds before media
            rec.shed_fec(True)
        elif rung == "throttle_rtx":
            # then the retransmission budget shrinks...
            rec.throttle_rtx(True)
        else:
            # ...and only then are whole streams dropped
            self._shed_streams(self.cfg.shed_step)

    def _escalate(self) -> None:
        self.level += 1
        rec = getattr(self.bridge, "recovery", None)
        # budget attribution: the ladder acts on WHERE the tick budget
        # went, not just that it overran — the dominant stage, its
        # ledger share, the chosen rung, and the SLO state ride on
        # every escalation event for the post-mortem
        stage, stage_s = PipelineTracer.dominant(self.last_ledger)
        total = sum(self.last_ledger.values())
        share = (stage_s / total) if total > 0 else 0.0
        rung = self._pick_rung(stage, share, rec)
        phase, _phase_s, phase_share, bound = self._phase_attr()
        self.flight.record(
            "ladder_escalate", tick=self.ticks, level=self.level,
            worst_s=self.watchdog.worst_s,
            stage=stage or "unknown", stage_s=stage_s,
            stage_share=round(share, 4), rung=rung,
            phase=phase, phase_share=round(phase_share, 4),
            bound=bound, slo_state=self._slo_state())
        self._apply_rung(rung)
        self._rungs.append(rung)

    def _deescalate(self) -> None:
        """Pop the most recent rung and reverse it — LIFO, so whatever
        order stage attribution escalated in, recovery unwinds it."""
        rec = getattr(self.bridge, "recovery", None)
        rung = self._rungs.pop() if self._rungs else "shed_streams"
        self.flight.record("ladder_deescalate", tick=self.ticks,
                           level=self.level - 1, rung=rung)
        if rung == "shed_streams":
            if self._shed:
                restored = 0
                while self._shed and restored < self.cfg.shed_step:
                    sid = self._shed.pop()
                    self._shed_set.discard(sid)
                    if sid in self._evicted:
                        # the stream LEFT while shed: its slot is dead
                        # (or already recycled) — restoring it would
                        # resurrect a departed stream into someone
                        # else's row.  Skip without consuming budget.
                        continue
                    self.flight.record("shed_restore", sid=sid,
                                       tick=self.ticks)
                    restored += 1
                self._sync_drop_mask()
        elif rung == "throttle_rtx" and rec is not None:
            rec.throttle_rtx(False)
        elif rung == "shed_fec" and rec is not None:
            rec.shed_fec(False)
        elif rung == "degrade":
            self.bridge.degraded = False
        elif rung == "recv_window" and self._saved_window is not None:
            self.loop.recv_window_ms = self._saved_window
            self._saved_window = None
            if self.batcher is not None:
                self.batcher.clamp_window(False)
        self.level -= 1

    def _active_sids(self) -> List[int]:
        by_ssrc = getattr(self.bridge, "_ssrc_of", None)
        if by_ssrc:
            return sorted(by_ssrc.keys())
        ports = getattr(self.loop, "addr_port", None)
        if ports is None:
            return []
        return [int(s) for s in np.nonzero(np.asarray(ports) > 0)[0]]

    def _shed_streams(self, k: int) -> None:
        """Shed the k lowest-priority active streams, deterministically:
        priority ascending (default 0), then highest sid first — newest
        joins go before long-standing participants.  The dominant
        speaker is never shed."""
        speaker = getattr(self.bridge, "speaker", None)
        dominant = getattr(speaker, "dominant", -1) if speaker else -1
        staged = getattr(self.bridge, "_staged", ())
        cands = [s for s in self._active_sids()
                 if s not in self._shed_set and s not in self._quarantined
                 and s not in staged and s != dominant]
        cands.sort(key=lambda s: (self.priorities.get(s, 0), -s))
        stage, stage_s = PipelineTracer.dominant(self.last_ledger)
        for sid in cands[:k]:
            self._shed.append(sid)
            self._shed_set.add(sid)
            ev = self.flight.record(
                "shed", sid=sid, tick=self.ticks, level=self.level,
                priority=self.priorities.get(sid, 0),
                stage=stage or "unknown", stage_s=stage_s)
            self.postmortems.append({
                "trigger": "overload_shed", "sid": sid,
                "tick": self.ticks, "event": ev,
                "dump": self.flight.dump(sid)})
        if cands[:k]:
            self._sync_drop_mask()

    # ------------------------------------------------- lifecycle plane

    def note_evicted(self, sids) -> None:
        """Lifecycle evict bookkeeping: the stream LEFT — this is not an
        overload shed.  Clear every per-sid mechanism (shed membership,
        quarantine, strike history, failure windows) so the departed
        stream can never be restored, and its row's next occupant starts
        with a clean record.  Flight-records `evicted`, distinct from
        `shed`."""
        changed = False
        for sid in sids:
            sid = int(sid)
            self._evicted.add(sid)
            self._shed_set.discard(sid)
            if self._quarantined.pop(sid, None) is not None:
                changed = True
            self._q_strikes.pop(sid, None)
            if sid < len(self._last_auth):
                self._auth_win.reset_rows([sid])
                self._replay_win.reset_rows([sid])
            self.flight.record("evicted", sid=sid, tick=self.ticks)
        if changed or sids:
            self._sync_drop_mask()

    def note_admitted(self, sids) -> None:
        """Lifecycle admit bookkeeping: a row given to a NEW stream is
        no longer 'evicted' — overload shedding may target it again."""
        for sid in sids:
            self._evicted.discard(int(sid))

    def admission_decision(self, shard=None, handshake_backlog=None,
                           handshake_bound=0, trunk=None):
        """Burn-aware admission control for the lifecycle plane:
        `(ok, reason)` where reason is a typed string.  Joins are
        refused while the error budget is burning fast, while the phase
        ledger says the tick is host-bound under overload (installing
        more streams feeds the bottleneck), or while streams are
        actively being shed (admitting during shedding thrashes).

        With conference-affinity sharding, pass the TARGET `shard`: a
        join is also refused (`shard_burn`) when a per-shard sliced SLO
        says that specific shard is burning fast — the other shards
        keep admitting, which is the point of slicing (a fleet-wide
        gate would brown out all 8 chips for one hot one).

        DTLS/ZRTP joins pass the handshake plane's current
        `handshake_backlog` (queued datagrams + pending associations)
        and its `handshake_bound`: past the bound the join is refused
        `handshake_backlog` — the shard_burn-style typed backpressure
        for reconnect storms (the caller attaches a retry-after hint)."""
        if self._slo_state() == "fast_burn":
            return False, "fast_burn"
        if shard is not None and self.slo is not None:
            for spec in getattr(self.slo, "sliced", ()):
                if (spec.label == "shard"
                        and self.slo.slice_state(spec.name, shard)
                        == "fast_burn"):
                    return False, "shard_burn"
        if self.slo is not None:
            for spec in getattr(self.slo, "sliced", ()):
                # per-hop journey burn (cascade tracing): a trunk hop
                # whose journey tail is burning fast means more members
                # would land on a degraded cross-bridge path — refuse
                # typed, like shard_burn, rather than brown out
                if (spec.label == "hop"
                        and self.slo.burning_slices(spec.name)):
                    return False, "hop_burn"
        if (handshake_bound and handshake_backlog is not None
                and handshake_backlog >= handshake_bound):
            return False, "handshake_backlog"
        if trunk is not None:
            # cascade relay admission (mesh/cascade.py): typed
            # trunk_down / trunk_backlog, same surface as the
            # handshake plane's backpressure
            r = trunk.admit_reason()
            if r is not None:
                return False, r
        if self.watchdog.state == "stalled":
            return False, "stalled"
        if self._shed_set:
            return False, "shedding"
        if self.level > 0:
            _phase, _s, share, bound = self._phase_attr()
            if bound == "host" and share >= self.cfg.stage_share_threshold:
                return False, "host_bound"
        if self.capacity is not None and \
                self.capacity.should_refuse(shard=shard):
            # forecast refusal (utils/capacity.py): every hard signal
            # above is still green, but a confident headroom fit says
            # this join won't fit before one of them fires — refuse
            # NOW, typed and with a retry-after hint, instead of
            # admitting into a forecast brown-out
            return False, "capacity_forecast"
        return True, "ok"

    # ------------------------------------------------------ quarantine

    def _update_quarantine(self) -> None:
        table = getattr(self.bridge, "rx_table", None)
        if table is None or not hasattr(table, "auth_fail"):
            return
        cap = len(self._last_auth)
        auth = np.asarray(table.auth_fail[:cap])
        replay = np.asarray(table.replay_reject[:cap])
        d_auth = auth - self._last_auth
        d_replay = replay - self._last_replay
        self._auth_win.push(d_auth)
        self._replay_win.push(d_replay)
        self._last_auth[:] = auth
        self._last_replay[:] = replay
        # per-stream failure deltas feed the flight ring: when a
        # conviction lands, the dump shows the storm that caused it
        for sid in np.nonzero(d_auth > 0)[0]:
            self.flight.record("srtp_auth_fail", sid=int(sid),
                               tick=self.ticks, n=int(d_auth[sid]))
        for sid in np.nonzero(d_replay > 0)[0]:
            self.flight.record("srtp_replay_reject", sid=int(sid),
                               tick=self.ticks, n=int(d_replay[sid]))

        changed = False
        for sid in [s for s, until in self._quarantined.items()
                    if self.ticks >= until]:
            del self._quarantined[sid]
            self._auth_win.reset_rows([sid])
            self._replay_win.reset_rows([sid])
            self.flight.record("quarantine_release", sid=sid,
                               tick=self.ticks)
            changed = True

        auth_sum = self._auth_win.sums()
        replay_sum = self._replay_win.sums()
        bad = np.nonzero(
            (auth_sum >= self.cfg.quarantine_auth_threshold)
            | (replay_sum >= self.cfg.quarantine_replay_threshold))[0]
        for sid in (int(s) for s in bad):
            if sid in self._quarantined or sid in self._shed_set:
                continue
            strikes = self._q_strikes.get(sid, 0)
            self._quarantined[sid] = self.ticks + int(
                self._ban.delay(strikes))
            self._q_strikes[sid] = strikes + 1
            self.quarantine_total += 1
            reason = ("auth_storm"
                      if auth_sum[sid] >= self.cfg.quarantine_auth_threshold
                      else "replay_storm")
            ev = self.flight.record(
                "quarantine", sid=sid, tick=self.ticks, reason=reason,
                auth_window=int(auth_sum[sid]),
                replay_window=int(replay_sum[sid]),
                until=self._quarantined[sid], strikes=strikes + 1)
            self.postmortems.append({
                "trigger": "quarantine", "sid": sid,
                "tick": self.ticks, "event": ev,
                "dump": self.flight.dump(sid)})
            self._auth_win.reset_rows([sid])
            self._replay_win.reset_rows([sid])
            changed = True
        if changed:
            self._sync_drop_mask()

    def _sync_drop_mask(self) -> None:
        self.loop.inbound_drop[:] = False
        banned = self._shed_set | set(self._quarantined)
        if banned:
            self.loop.inbound_drop[list(banned)] = True

    # ------------------------------------------------------ checkpoint

    def save_checkpoint(self, path: Optional[str] = None) -> str:
        """Serialize the whole bridge into one versioned checkpoint
        file.  Write-to-temp + rename: a crash mid-write leaves the
        previous checkpoint intact, never a torn one."""
        path = path or self.cfg.checkpoint_path
        if path is None:
            raise ValueError("no checkpoint path configured")
        # pipeline drain barrier: a deep-pipelined loop may hold
        # dispatched-but-uncommitted ticks (replay state, egress bytes,
        # pinned arenas) — the snapshot must never capture a half tick
        drain = getattr(self.loop, "drain", None)
        if drain is not None:
            drain()
        blob = {"magic": CKPT_MAGIC, "version": CKPT_VERSION,
                "bridge": type(self.bridge).__name__,
                "ticks": self.ticks,
                "snap": self.bridge.snapshot()}
        if self.lifecycle is not None:
            # in-flight admits (queued joins + staged-but-uncommitted
            # installs) ride the checkpoint so recover() can complete
            # or roll them back instead of leaving half-installed rows
            blob["lifecycle"] = self.lifecycle.snapshot()
        # cascade control plane (CascadeSupervisor): trunk peer/rosters
        # and the in-flight adoption queue ride the same atomic file —
        # a crash mid-failover resumes adoption, never a torn trunk
        snap_cascade = getattr(self, "cascade_snapshot", None)
        if snap_cascade is not None:
            blob["cascade"] = snap_cascade()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
        self.checkpoints_written += 1
        self.flight.record("checkpoint_saved", tick=self.ticks,
                           path=path)
        return path

    @staticmethod
    def load_checkpoint(path: str) -> dict:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if (not isinstance(blob, dict)
                or blob.get("magic") != CKPT_MAGIC):
            raise ValueError(f"{path}: not a libjitsi_tpu checkpoint")
        if blob.get("version") != CKPT_VERSION:
            raise ValueError(
                f"{path}: checkpoint version {blob.get('version')} "
                f"(supported: {CKPT_VERSION})")
        return blob

    @classmethod
    def recover(cls, config, path: str, bridge_cls, port: int = 0,
                retries: int = 5, backoff_s: float = 0.05,
                sleep: Callable[[float], None] = time.sleep,
                supervisor_config: Optional[SupervisorConfig] = None,
                metrics=None, **bridge_kwargs) -> "BridgeSupervisor":
        """Crash-restart: load the checkpoint, re-bind the socket with
        bounded retry (a just-killed worker's port can linger), restore
        the bridge (SRTP ROC/replay included), resume supervising."""
        blob = cls.load_checkpoint(path)
        bridge = retrying(
            lambda: bridge_cls.restore(config, blob["snap"], port=port,
                                       **bridge_kwargs),
            retries=retries, backoff_s=backoff_s, sleep=sleep)
        sup = cls(bridge, config=supervisor_config, metrics=metrics)
        sup.ticks = blob["ticks"]
        # lifecycle in-flight state (if any) is held for the next
        # StreamLifecycleManager attached to this supervisor: its
        # constructor reconciles every half-installed stream (complete
        # or roll back — never a half state)
        sup.pending_lifecycle = blob.get("lifecycle")
        # crash-restart is a destructive action like any other: it
        # leaves a post-mortem naming the checkpoint it rose from
        ev = sup.flight.record("recovered", tick=sup.ticks, path=path,
                               bridge=blob["bridge"])
        sup.postmortems.append({
            "trigger": "checkpoint_recover", "tick": sup.ticks,
            "event": ev, "dump": sup.flight.dump_all()})
        return sup

    # --------------------------------------------------- observability

    def register_metrics(self, registry, prefix: str = "supervisor") -> None:
        wd, cfg = self.watchdog, self.cfg
        registry.register_scalar(
            f"{prefix}_ticks_overrun", lambda: wd.overruns,
            help_="ticks that exceeded the deadline", kind="counter")
        registry.register_scalar(
            f"{prefix}_watchdog_state", lambda: state_code(wd.state),
            help_="0 healthy, 1 overloaded, 2 stalled")
        registry.register_scalar(
            f"{prefix}_overload_level", lambda: self.level,
            help_="current escalation-ladder rung")
        registry.register_scalar(
            f"{prefix}_streams_shed", lambda: len(self._shed),
            help_="streams currently shed for overload")
        registry.register_scalar(
            f"{prefix}_streams_quarantined", lambda: len(self._quarantined),
            help_="streams currently quarantined")
        registry.register_scalar(
            f"{prefix}_quarantine_total", lambda: self.quarantine_total,
            help_="quarantine convictions since start", kind="counter")
        registry.register_scalar(
            f"{prefix}_checkpoints_written",
            lambda: self.checkpoints_written, kind="counter")
        registry.register_scalar(
            f"{prefix}_inbound_dropped",
            lambda: self.loop.inbound_dropped_total,
            help_="packets dropped by shed/quarantine masks",
            kind="counter")
        # per-stream arrays are registered as CALLABLES resolving
        # through self.bridge/self.loop at render time: a checkpoint
        # restore that rebinds rx_table (or the whole bridge) must not
        # leave the exporter reading the pre-restore arrays
        registry.register_array(
            "inbound_dropped", lambda: self.loop.inbound_dropped,
            help_="per-stream packets dropped at ingress", kind="counter")
        table = getattr(self.bridge, "rx_table", None)
        if table is not None and hasattr(table, "auth_fail"):
            registry.register_array(
                "srtp_auth_fail", lambda: self.bridge.rx_table.auth_fail,
                help_="SRTP authentication failures", kind="counter")
            registry.register_array(
                "srtp_replay_reject",
                lambda: self.bridge.rx_table.replay_reject,
                help_="SRTP replay-window rejections", kind="counter")
        if hasattr(self.bridge, "forwarded"):
            # denominator of the residual-loss SLO: packets the bridge
            # actually forwarded downstream
            registry.register_scalar(
                "bridge_forwarded", lambda: self.bridge.forwarded,
                help_="packets forwarded to receivers", kind="counter")
        if hasattr(self.bridge, "_video"):
            # simulcast/SVC forwarders are per-receiver objects; export
            # the fleet-wide sums (drift rule: every bumped counter is
            # scraped somewhere)
            def _fwds():
                return [f for t in set(self.bridge._video.values())
                        for f in t.fwd.values()]
            registry.register_scalar(
                "video_layer_switches",
                lambda: sum(f.switches for f in _fwds()),
                help_="simulcast/SVC layer switches across receivers",
                kind="counter")
            registry.register_scalar(
                "video_svc_dropped",
                lambda: sum(f.dropped for f in _fwds()
                            if hasattr(f, "dropped")),
                help_="SVC packets dropped by layer projection",
                kind="counter")
            registry.register_scalar(
                "video_svc_late_dropped",
                lambda: sum(f.late_dropped for f in _fwds()
                            if hasattr(f, "late_dropped")),
                help_="late SVC packets with no renumber hole left",
                kind="counter")
        rec = getattr(self.bridge, "recovery", None)
        if rec is not None:
            rec.register_metrics(registry)
        if self.slo is not None:
            self.slo.register_metrics(registry)
        bank = getattr(self.bridge, "bank", None)
        if bank is not None and hasattr(bank, "plc_frames"):
            registry.register_array(
                "plc_frames", lambda: self.bridge.bank.plc_frames,
                help_="frames concealed by packet-loss concealment",
                kind="counter")
            if hasattr(bank, "register_metrics"):
                bank.register_metrics(registry)

    def _phase_attr(self):
        """(phase, seconds, share, bound) of the last sampled phase
        split — "which phase owns the tick, and is that host-side or
        device-side?"."""
        from libjitsi_tpu.utils.perf import classify_bound

        phase, phase_s = PipelineTracer.dominant(self.last_phases)
        total = sum(self.last_phases.values())
        share = (phase_s / total) if total > 0 else 0.0
        return (phase or "unknown", phase_s, share,
                classify_bound(self.last_phases))

    def phase_attribution(self) -> dict:
        """Host/device attribution summary for /debug/slo: the phase
        split the escalation ladder is currently judging by, labeled
        with the ingest engine mode and its syscall telemetry — a phase
        share is only comparable against runs of the SAME engine."""
        phase, phase_s, share, bound = self._phase_attr()
        out = {"bound": bound, "phase": phase,
               "phase_share": round(share, 4),
               "phases": dict(self.last_phases)}
        loop = getattr(self.bridge, "loop", None)
        if loop is not None:
            out["engine_mode"] = getattr(loop, "engine_mode", "recvmmsg")
            out["ingest_syscalls"] = int(
                getattr(loop, "ingest_syscalls", 0))
            out["ingest_ring_reaps"] = int(
                getattr(loop, "ingest_ring_reaps", 0))
        caches = [c for name in ("rx_table", "tx_table")
                  for c in (getattr(getattr(self.bridge, name, None),
                                    "_ks_cache", None),)
                  if c is not None]
        if caches:
            # off-tick phases don't appear in the tick's phase split —
            # keystream pregeneration runs at the lifecycle barrier, so
            # its cost is attributed here as a separate ledger line
            served = sum(c.hits for c in caches)
            missed = sum(c.misses for c in caches)
            out["off_tick"] = {
                "keystream_fill_seconds": round(
                    sum(c.fill_seconds for c in caches), 6),
                "keystream_filled_slots": int(
                    sum(c.filled_slots for c in caches)),
                "keystream_hit_rate": round(
                    served / (served + missed), 4)
                if served + missed else None,
            }
        hq = getattr(self.lifecycle, "handshakes", None) \
            if self.lifecycle is not None else None
        if hq is not None:
            # same rule as the keystream ledger: handshake OpenSSL work
            # runs on the between-ticks window, so the PhaseProfiler's
            # tick split never contains it — its wall time is attributed
            # here, and `tick_thread_feeds` must stay 0 (the reconnect
            # soak gates on it)
            out.setdefault("off_tick", {}).update({
                "handshake_drain_seconds": round(hq.off_tick_seconds, 6),
                "handshake_queue_depth": int(hq.depth),
                "handshake_tick_thread_feeds": int(
                    getattr(self.lifecycle,
                            "tick_thread_handshake_feeds", 0)),
            })
        return out

    def health(self) -> dict:
        """Liveness summary for probes / logs."""
        return {"state": self.watchdog.state, "level": self.level,
                "rungs": list(self._rungs),
                "shed": sorted(self._shed_set),
                "evicted": len(self._evicted),
                "quarantined": sorted(self._quarantined),
                "ticks": self.ticks, "overruns": self.watchdog.overruns,
                "last_ledger": dict(self.last_ledger),
                "last_phases": dict(self.last_phases),
                "bound": self._phase_attr()[3],
                "slo_state": self._slo_state(),
                "postmortems": len(self.postmortems)}


class CascadeSupervisor(BridgeSupervisor):
    """Supervisor for one end of a bridge-to-bridge cascade
    (mesh/cascade.py): everything BridgeSupervisor does, plus the trunk
    control plane and the failover headline — a conference that
    survives the death of its home bridge.

    Division of labour with CascadeTrunk: the trunk owns the wire
    (SRTP-keyed relay, heartbeats, NACK/RTX/FEC under the hop's
    deadline budget, typed `trunk_down`/`trunk_backlog` refusals); this
    class owns POLICY — which conferences ride the trunk, roster sync
    from the bridge's committed keyed rows, and orphan adoption when
    the peer dies:

    * heartbeat loss trips `trunk.on_down` -> `_on_trunk_down`: the
      peer's conferences are promoted (their typed trunk refusals
      lift), the placer's bridge axis is evacuated, and every remote
      roster member is queued for adoption;
    * adoption rides the NORMAL lifecycle commit barrier —
      `request_join` -> staged -> committed between ticks; an orphan
      counts as adopted only once its row resolves committed, and a
      join refused under pressure re-queues on the PR 16 retry-after
      hint with exponential escalation (adopt-or-retry, never torn);
    * the whole adoption queue plus trunk control plane rides the
      checkpoint spine (`cascade_snapshot`), so a crash mid-failover
      resumes adoption on recovery instead of stranding half a
      conference.

    Per-bridge burn: when an SloEngine is attached, a
    `SlicedSloSpec(label="bridge")` tracks this bridge's trunk media
    continuity exactly as PR 10's `label="shard"` slices shard burn.
    """

    #: a queued-but-uncommitted adoption older than this is treated as
    #: rolled back and re-queued (covers recovery from a checkpoint
    #: that captured the join before its commit)
    adopt_commit_timeout_s = 1.0
    #: roster re-derivation cadence (ticks); pushes only on change
    roster_sync_ticks = 5

    def __init__(self, bridge, trunk, config=None, metrics=None,
                 bridge_id: int = 0, peer_bridge_id: int = 1, **kw):
        super().__init__(bridge, config, metrics=None, **kw)
        self.trunk = trunk
        self.bridge_id = int(bridge_id)
        self.peer_bridge_id = int(peer_bridge_id)
        trunk.on_down = self._on_trunk_down
        trunk.on_up = self._on_trunk_up
        trunk.on_roster = self._on_roster
        trunk.on_speakers = self._apply_remote_speakers
        trunk.deliver = self._deliver_remote
        trunk.bridge_id = int(bridge_id)   # stamped on trace extensions
        if hasattr(trunk, "flight"):
            trunk.flight = self.flight
        self.trunk_failovers_total = 0
        self.orphans_adopted = 0
        self.orphans_requeued = 0
        self.remote_delivered = 0
        # cross-bridge journey tracing: hop-labeled children of
        # packet_journey_seconds (register_metrics binds the vec; falls
        # back to the bridge loop's own vec when none is registered),
        # plus the rtt-ring-corrected trunk one-way-delay estimate
        self._journey_vec = None
        self.trunk_owd_s = 0.0
        self.adopting = False            # failover in progress
        self._now = 0.0                  # model clock from tick()
        self._adopt_q: deque = deque()   # entries awaiting request_join
        self._pending_commit: List[dict] = []   # joined, pre-barrier
        self._conf_outstanding: Dict[int, int] = {}
        self._remote_marks: set = set()  # confs homed on the peer
        self._marks_pending = False      # marks awaiting lifecycle
        if self.slo is not None:
            self._register_bridge_slo()
        if metrics is not None:
            self.register_metrics(metrics)

    # ------------------------------------------------------ wiring

    def cascade_conference(self, conference, speakers=None,
                           remote: bool = False) -> None:
        """Put one conference on the trunk.  `remote=False`: homed
        HERE — local speaker-bus media relays to the peer.
        `remote=True`: homed on the PEER — local joins consult the
        trunk's typed admission (the PR 16 refusal surface) and the
        conference is a failover-adoption candidate."""
        conf = int(conference)
        self.bridge.attach_trunk(self.trunk, conf, speakers)
        if remote:
            self._remote_marks.add(conf)
            if self.lifecycle is not None:
                self.lifecycle.mark_remote_conference(conf, self.trunk)
            else:
                self._marks_pending = True

    # -------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None):
        result = super().tick(now=now)
        tnow = float(now) if now is not None else self.clock()
        self._now = tnow
        lc = self.lifecycle
        if lc is not None and self._marks_pending:
            for conf in sorted(self._remote_marks):
                lc.mark_remote_conference(conf, self.trunk)
            self._marks_pending = False
        if self.ticks % self.roster_sync_ticks == 0:
            self._sync_roster()
        self.trunk.pump(tnow)
        if self._adopt_q and lc is not None:
            self._drain_adoptions(tnow)
        if self._pending_commit:
            self._scan_commits(tnow)
        if (self.adopting and not self._adopt_q
                and not self._pending_commit):
            self.adopting = False
        return result

    def _sync_roster(self) -> None:
        """Re-derive the local roster from the bridge's COMMITTED keyed
        rows (staged rows are not yet adoptable) and push on change.
        This is what makes failover possible at all: the survivor can
        only re-key orphans it has a roster for."""
        b = self.bridge
        roster: Dict[int, list] = {}
        for sid, conf in sorted(b._conf_of.items()):
            conf = int(conf)
            if conf not in getattr(b, "_trunks", {}):
                continue
            if sid in b._staged:
                continue
            ssrc = b._ssrc_of.get(sid)
            rx = b._rx_keys.get(sid)
            tx = b._tx_keys.get(sid)
            if ssrc is None or rx is None or tx is None:
                continue
            if int(ssrc) in self.trunk._remote_ssrcs:
                # peer-homed member installed here by roster sync: not
                # ours to advertise (claimed only on failover adoption)
                continue
            roster.setdefault(conf, []).append({
                "ssrc": int(ssrc),
                "rx": [rx[0].hex(), rx[1].hex()],
                "tx": [tx[0].hex(), tx[1].hex()],
            })
        if roster != self.trunk.local_roster:
            self.trunk.set_roster(roster)

    # -------------------------------------------------- trunk hooks

    def _deliver_remote(self, conf: int, inner: bytes,
                        trace=None) -> None:
        """Re-inject a trunk-delivered participant packet into the
        local bridge's primary socket: the remote speaker is a regular
        keyed row here (roster sync installed it), so the inner SRTP
        authenticates and routes through the stock data path — zero
        cascade-specific shapes, zero recompiles.

        When the frame carried a journey trace extension, the hop is
        recorded here (host side, off the jit path): a hop-labeled
        `packet_journey_seconds` observation whose exemplar carries the
        ORIGIN bridge's trace id — the stitch point /debug/fleet and
        `trace_report.py --merge-bridges` join on."""
        if trace is not None:
            self._note_hop(trace)
        self.trunk.engine.send_batch(
            PacketBatch.from_payloads([inner]),
            "127.0.0.1", self.bridge.port)
        self.remote_delivered += 1

    def _note_hop(self, trace) -> None:
        """Observe one cross-bridge journey segment: origin ingress
        stamp -> local trunk ingest, under a `b<origin>-b<me>` hop
        label.  The origin stamp is a FOREIGN monotonic clock; the
        trunk RTT ring corrects it — the wire can't be faster than
        half the measured round trip, so the raw delta is floored at
        owd (and a cross-machine, incomparable-clock delta degrades to
        the rtt-derived estimate instead of garbage)."""
        ring = getattr(self.trunk, "_rtt_ring", None)
        rtt = (ring.percentile(50) if ring is not None and ring.count
               else float(self.trunk.rtt))
        owd = max(rtt / 2.0, 0.0)
        self.trunk_owd_s = owd
        raw = time.perf_counter() - float(trace.t0)
        # plausibility window: floor at the wire delay, and treat a
        # multi-second delta (incomparable clocks) as wire-delay-only
        dt = raw if owd <= raw <= 10.0 else owd
        vec = self._journey_vec
        if vec is None:
            vec = getattr(getattr(self.bridge, "loop", None),
                          "journey_vec", None)
            if vec is None:
                return
        hop = f"b{int(trace.bridge_id)}-b{self.bridge_id}"
        tail = vec.labels(hop).observe(
            dt, exemplar={"trace_id": str(int(trace.trace_id)),
                          "origin": str(int(trace.bridge_id))})
        if tail:
            self.flight.record("hop_tail", tick=self.ticks,
                               hop=hop, trace=int(trace.trace_id),
                               seconds=dt)

    def _apply_remote_speakers(self, conf: int, ssrcs) -> None:
        """Speaker bus crossing the trunk: map the peer's active-speaker
        SSRCs onto local rows and update the broadcast route.  The
        bridge's no-change early-return breaks the echo loop."""
        b = self.bridge
        if conf not in b._bcast_speakers:
            return
        sids = [s for s in (b._sid_of_ssrc(int(x)) for x in ssrcs)
                if s is not None]
        if sids:
            b.set_broadcast_speakers(conf, sids)

    def _on_roster(self, roster: dict) -> None:
        """Peer roster sync: install any not-yet-local member of a
        cascaded conference as a regular keyed row (that is what lets
        its trunk-delivered media authenticate), via the same admission
        queue failover adoption uses — just without the promotion."""
        b = self.bridge
        queued = {(e["conf"], int(e["m"]["ssrc"]))
                  for e in list(self._adopt_q) + self._pending_commit}
        for conf, members in sorted(roster.items()):
            conf = int(conf)
            if (conf not in self.trunk._confs
                    and conf not in self._remote_marks):
                continue
            for m in members:
                ssrc = int(m["ssrc"])
                if b._sid_of_ssrc(ssrc) is not None:
                    continue
                if (conf, ssrc) in queued:
                    continue
                self._adopt_q.append({
                    "conf": conf, "m": dict(m), "n": len(members),
                    "attempts": 0, "retry_at": self._now,
                    "promote": False})

    def _on_trunk_up(self, now: float) -> None:
        self.flight.record("trunk_up", tick=self.ticks,
                           peer=self.peer_bridge_id)

    def _on_trunk_down(self, now: float) -> None:
        """Failover: the peer stopped answering heartbeats.  Promote
        its conferences (typed trunk refusals lift — joins admit HERE
        now), evacuate its placement axis, and queue every remote
        roster member for adoption through the commit barrier."""
        self.trunk_failovers_total += 1
        self.adopting = True
        ev = self.flight.record("trunk_failover", tick=self.ticks,
                                peer=self.peer_bridge_id,
                                inflight=self._journey_inflight())
        # post-mortem at conviction, mirroring quarantine/shed/recover:
        # the in-flight journey set names exactly which trace ids were
        # mid-hop when the trunk died — the per-hop attribution for
        # time-to-media-restored in churn_soak --cascade
        self.postmortems.append({
            "trigger": "trunk_failover", "tick": self.ticks,
            "event": ev, "dump": self.flight.dump_all()})
        lc = self.lifecycle
        placer = getattr(lc, "placer", None) if lc is not None else None
        if placer is not None and getattr(placer, "n_bridges", 0):
            placer.evacuate_bridge(self.peer_bridge_id)
        b = self.bridge
        queued = {(e["conf"], int(e["m"]["ssrc"]))
                  for e in list(self._adopt_q) + self._pending_commit}
        for conf, members in sorted(self.trunk.remote_roster.items()):
            conf = int(conf)
            if lc is not None:
                lc.promote_remote_conference(conf)
            self._remote_marks.discard(conf)
            fresh = [m for m in members
                     if b._sid_of_ssrc(int(m["ssrc"])) is None
                     and (conf, int(m["ssrc"])) not in queued]
            if not fresh:
                continue
            self._conf_outstanding[conf] = (
                self._conf_outstanding.get(conf, 0) + len(fresh))
            for m in fresh:
                self._adopt_q.append({
                    "conf": conf, "m": dict(m), "n": len(members),
                    "attempts": 0, "retry_at": float(now),
                    "promote": True})

    # ----------------------------------------------------- adoption

    def _drain_adoptions(self, now: float) -> None:
        lc = self.lifecycle
        n = len(self._adopt_q)
        for _ in range(n):
            ent = self._adopt_q.popleft()
            if float(ent["retry_at"]) > now:
                self._adopt_q.append(ent)
                continue
            m = ent["m"]
            ssrc = int(m["ssrc"])
            sid = self.bridge._sid_of_ssrc(ssrc)
            if sid is not None:
                self._adopt_done(ent, sid=sid)       # already local
                continue
            rx = tuple(bytes.fromhex(h) for h in m["rx"])
            tx = tuple(bytes.fromhex(h) for h in m["tx"])
            ok, reason = lc.request_join(ssrc, rx, tx,
                                         name=m.get("name"),
                                         conference=ent["conf"])
            if ok or reason == "duplicate":
                ent["commit_deadline"] = now + self.adopt_commit_timeout_s
                self._pending_commit.append(ent)
                continue
            # typed refusal: re-queue on the retry-after hint, with the
            # same exponential escalation a storming client would apply
            ent["attempts"] = int(ent["attempts"]) + 1
            ent["retry_at"] = now + (
                lc.retry_after_hint(reason, conference=ent["conf"])
                * (2 ** min(ent["attempts"], 6)))
            self.orphans_requeued += 1
            self._adopt_q.append(ent)

    def _scan_commits(self, now: float) -> None:
        """An orphan is adopted when its row resolves COMMITTED (past
        the barrier), not when the join queues.  A join that never
        commits (rolled back, or checkpointed pre-commit) re-queues —
        adopt-or-retry, never a torn row."""
        b = self.bridge
        still: List[dict] = []
        for ent in self._pending_commit:
            ssrc = int(ent["m"]["ssrc"])
            sid = b._sid_of_ssrc(ssrc)
            if sid is not None and sid not in b._staged:
                self._adopt_done(ent, sid=sid)
            elif now >= float(ent.get("commit_deadline", 0.0)):
                ent["attempts"] = int(ent["attempts"]) + 1
                ent["retry_at"] = now
                ent.pop("commit_deadline", None)
                self.orphans_requeued += 1
                self._adopt_q.append(ent)
            else:
                still.append(ent)
        self._pending_commit = still

    def _adopt_done(self, ent: dict, sid: Optional[int] = None) -> None:
        conf = int(ent["conf"])
        if ent.get("promote"):
            self.orphans_adopted += 1
            ssrc = int(ent["m"]["ssrc"])
            self.trunk.claim_member(conf, ssrc)
            ev = self.flight.record("orphan_adopted", sid=sid,
                                    tick=self.ticks, conf=conf,
                                    ssrc=ssrc)
            # adoption-commit post-mortem: second half of the failover
            # story (conviction is the first), per adopted stream
            self.postmortems.append({
                "trigger": "trunk_failover", "sid": sid,
                "tick": self.ticks, "event": ev,
                "dump": self.flight.dump(sid) if sid is not None
                else self.flight.dump_all()})
            # an orphan that was on the conference's top-K speaker bus
            # resumes speaking HERE: its fresh row landed as a listener
            # (the broadcast speaker set holds the dead row's sid)
            spk = self.trunk._confs.get(conf)
            cur = self.bridge._bcast_speakers.get(conf)
            if (sid is not None and spk is not None and ssrc in spk
                    and cur is not None and sid not in cur):
                self.bridge.set_broadcast_speakers(
                    conf, sorted(cur | {sid}))
        left = self._conf_outstanding.get(conf, 0) - 1
        if left > 0:
            self._conf_outstanding[conf] = left
        elif conf in self._conf_outstanding:
            del self._conf_outstanding[conf]
            if ent.get("promote"):
                # the whole conference is committed here: re-home it on
                # the placer's bridge axis
                lc = self.lifecycle
                placer = getattr(lc, "placer", None) \
                    if lc is not None else None
                if placer is not None and getattr(placer, "n_bridges", 0):
                    placer.adopt_bridge(conf, self.bridge_id,
                                        int(ent.get("n", 1)))

    # ------------------------------------------------- observability

    def _journey_inflight(self) -> List[int]:
        """Trace ids currently mid-journey on this bridge's loop: the
        live tick's trace plus every pipelined dispatch still holding
        an origin stamp.  Captured into the trunk-down post-mortem —
        these are the packets whose journey the failover cut."""
        lp = getattr(self.bridge, "loop", None)
        if lp is None:
            return []
        ids = {int(getattr(lp, "trace_id", 0))}
        for ent in getattr(lp, "_inflight", ()):
            ids.add(int(ent[2][0]))          # (pend, mask, origin, tick)
        for e in getattr(lp, "_rx_inflight", ()):
            ids.add(int(e["origin"][0]))
        return sorted(ids)

    def _register_bridge_slo(self) -> None:
        from libjitsi_tpu.utils.slo import SlicedSloSpec
        tr = self.trunk
        me = str(self.bridge_id)

        def _read():
            good = tr.relay_frames_total + self.remote_delivered
            bad = (tr.plc_fallthrough_total + tr.unprotect_drops_total
                   + tr.refusals_total)
            yield (me, float(good), float(bad))

        self.slo.add_sliced(SlicedSloSpec(
            name="bridge_media", objective=0.999, label="bridge",
            reader=_read,
            description="per-bridge trunk media continuity: frames "
                        "relayed/delivered vs concealed, dropped or "
                        "refused"))
        self._register_hop_slo()

    def _register_hop_slo(self) -> None:
        """Per-hop journey burn (`label="hop"`): each hop-labeled
        child of packet_journey_seconds is one slice; an observation
        within the trunk's deadline budget is good, past it is bad.
        `admission_decision` refuses `hop_burn` while any hop slice is
        fast-burning — the cross-bridge twin of shard_burn."""
        from libjitsi_tpu.utils.slo import SlicedSloSpec
        budget = float(self.trunk.cfg.deadline_budget_s)

        def _read():
            vec = self._journey_vec
            if vec is None:
                vec = getattr(getattr(self.bridge, "loop", None),
                              "journey_vec", None)
            if vec is None:
                return
            for lv, h in vec.children():
                j = int(np.searchsorted(h.uppers, budget,
                                        side="right")) - 1
                good = float(h.cumulative()[j]) if j >= 0 else 0.0
                yield (lv, good, float(h.count) - good)

        self.slo.add_sliced(SlicedSloSpec(
            name="hop_journey", objective=0.99, label="hop",
            reader=_read,
            description="per-hop packet journey tail vs the trunk "
                        "deadline budget"))

    def register_metrics(self, registry,
                         prefix: str = "supervisor") -> None:
        super().register_metrics(registry, prefix)
        # owner indirection: gauges follow THIS supervisor's current
        # trunk, so a recovery-supplied replacement stays observable
        self.trunk.register_metrics(registry, owner=self)
        registry.register_scalar(
            "trunk_failovers_total",
            lambda: self.trunk_failovers_total,
            help_="trunk down transitions that triggered failover",
            kind="counter")
        registry.register_scalar(
            "cascade_orphans_adopted", lambda: self.orphans_adopted,
            help_="orphaned remote streams committed on this bridge "
                  "after peer death", kind="counter")
        registry.register_scalar(
            "cascade_orphans_requeued", lambda: self.orphans_requeued,
            help_="adoption attempts re-queued on a typed refusal or "
                  "rollback", kind="counter")
        registry.register_scalar(
            "cascade_remote_delivered", lambda: self.remote_delivered,
            help_="trunk-delivered remote packets re-injected locally",
            kind="counter")
        registry.register_scalar(
            "trunk_one_way_delay_seconds", lambda: self.trunk_owd_s,
            help_="rtt-ring-corrected trunk one-way-delay estimate")
        from libjitsi_tpu.io.loop import JOURNEY_BUCKETS
        self._journey_vec = registry.histogram_vec(
            "packet_journey_seconds", JOURNEY_BUCKETS, "hop",
            help_="ingress-arrival to egress-send packet latency",
            exemplars=True)

    # ------------------------------------------------- checkpointing

    def cascade_snapshot(self) -> dict:
        """Picked up by BridgeSupervisor.save_checkpoint: the trunk
        control plane plus every in-flight adoption."""
        return {
            "trunk": self.trunk.snapshot(),
            "adopting": bool(self.adopting),
            "remote_marks": sorted(self._remote_marks),
            "adopt_q": [dict(e) for e in self._adopt_q],
            "pending_commit": [dict(e) for e in self._pending_commit],
            "conf_outstanding": {int(c): int(n) for c, n
                                 in self._conf_outstanding.items()},
            "counters": {
                "trunk_failovers_total": self.trunk_failovers_total,
                "orphans_adopted": self.orphans_adopted,
                "orphans_requeued": self.orphans_requeued,
            },
        }

    def restore_cascade(self, cas: dict, now: float = 0.0) -> None:
        self.trunk.restore(cas.get("trunk", {}), now=now)
        self.adopting = bool(cas.get("adopting", False))
        self._remote_marks = {int(c) for c
                              in cas.get("remote_marks", ())}
        self._marks_pending = bool(self._remote_marks)
        self._adopt_q = deque(dict(e) for e in cas.get("adopt_q", ()))
        # joins checkpointed pre-commit cannot be assumed committed:
        # give them a fresh deadline; _scan_commits either sees the
        # reconciled row (adopted) or times out and re-queues
        self._pending_commit = []
        for e in cas.get("pending_commit", ()):
            ent = dict(e)
            ent["commit_deadline"] = now + self.adopt_commit_timeout_s
            self._pending_commit.append(ent)
        self._conf_outstanding = {
            int(c): int(n)
            for c, n in cas.get("conf_outstanding", {}).items()}
        ctr = cas.get("counters", {})
        self.trunk_failovers_total = int(
            ctr.get("trunk_failovers_total", 0))
        self.orphans_adopted = int(ctr.get("orphans_adopted", 0))
        self.orphans_requeued = int(ctr.get("orphans_requeued", 0))
        # re-attach cascaded conferences to the restored bridge
        for conf, speakers in sorted(self.trunk._confs.items()):
            self.bridge.attach_trunk(
                self.trunk, conf,
                sorted(speakers) if speakers is not None else None)

    @classmethod
    def recover(cls, config, path: str, bridge_cls, trunk=None,
                port: int = 0, retries: int = 5,
                backoff_s: float = 0.05,
                sleep: Callable[[float], None] = time.sleep,
                supervisor_config: Optional[SupervisorConfig] = None,
                metrics=None, bridge_id: int = 0,
                peer_bridge_id: int = 1,
                **bridge_kwargs) -> "CascadeSupervisor":
        """Crash-restart with the cascade control plane restored: the
        caller supplies a fresh CascadeTrunk (sockets don't survive a
        crash any more than the bridge's do); peer, cascaded
        conferences, rosters and the adoption queue come back from the
        checkpoint, so a failover interrupted by the crash RESUMES."""
        if trunk is None:
            raise ValueError("CascadeSupervisor.recover needs a trunk")
        blob = cls.load_checkpoint(path)
        bridge = retrying(
            lambda: bridge_cls.restore(config, blob["snap"], port=port,
                                       **bridge_kwargs),
            retries=retries, backoff_s=backoff_s, sleep=sleep)
        sup = cls(bridge, trunk, config=supervisor_config,
                  metrics=metrics, bridge_id=bridge_id,
                  peer_bridge_id=peer_bridge_id)
        sup.ticks = blob["ticks"]
        sup.pending_lifecycle = blob.get("lifecycle")
        cas = blob.get("cascade")
        if cas is not None:
            sup.restore_cascade(cas)
        ev = sup.flight.record("recovered", tick=sup.ticks, path=path,
                               bridge=blob["bridge"])
        sup.postmortems.append({
            "trigger": "checkpoint_recover", "tick": sup.ticks,
            "event": ev, "dump": sup.flight.dump_all()})
        return sup
