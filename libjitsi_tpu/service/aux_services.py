"""Auxiliary substrate services: file access, resources, audio notifier.

Reference (SURVEY §2.1 "File access / resources / audio notifier"):
`org.jitsi.service.fileaccess.FileAccessService`,
`org.jitsi.service.resources.ResourceManagementService`,
`org.jitsi.service.audionotifier.AudioNotifierService`.  These exist for
a desktop client (per-user config dirs, i18n bundles, notification
sounds); on a server they shrink to the pieces the rest of the framework
actually uses: a scoped data directory, key/value resource lookup, and a
tone renderer wired to the synthetic device layer.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional

import numpy as np


class FileAccessService:
    """Scoped file access under one data directory.

    Reference: FileAccessServiceImpl resolves persistent files under the
    user's ~/.sip-communicator home; here the home is configurable
    (``libjitsi_tpu.data_dir``, default a temp dir) so recorders and
    packet logs have a sanctioned place to write.
    """

    def __init__(self, config=None):
        base = None
        if config is not None:
            base = config.get_string("libjitsi_tpu.data_dir")
        if base:
            self._base = os.path.abspath(base)
            os.makedirs(self._base, exist_ok=True)
        else:
            # fresh private dir (0700) — a fixed /tmp name would be
            # pre-creatable by another local user (CWE-379)
            self._base = tempfile.mkdtemp(prefix="libjitsi_tpu-")

    @property
    def data_dir(self) -> str:
        return self._base

    def get_private_file(self, name: str) -> str:
        """Path for a persistent file; parents created, traversal refused."""
        path = os.path.normpath(os.path.join(self._base, name))
        if not path.startswith(os.path.abspath(self._base) + os.sep) \
                and path != os.path.abspath(self._base):
            raise ValueError(f"path {name!r} escapes the data dir")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return path

    def create_temp_file(self, suffix: str = "") -> str:
        fd, path = tempfile.mkstemp(suffix=suffix, dir=self._base)
        os.close(fd)
        return path


class ResourceManagementService:
    """Key/value resource lookup (settings + strings).

    Reference: ResourceManagementService serves i18n strings, images and
    sound paths from bundle resources; server-side it is a dict with
    defaults — enough for components that look up tunables/messages by
    resource key.
    """

    def __init__(self, entries: Optional[Dict[str, Any]] = None):
        self._entries: Dict[str, Any] = dict(entries or {})

    def register(self, key: str, value: Any) -> None:
        self._entries[key] = value

    def get_setting(self, key: str, default: Any = None) -> Any:
        return self._entries.get(key, default)

    def get_string(self, key: str, default: Optional[str] = None):
        v = self._entries.get(key, default)
        return None if v is None else str(v)


class AudioNotifierService:
    """Render notification tones through the synthetic device layer.

    Reference: AudioNotifierService/SCAudioClip plays .wav notification
    sounds on the NOTIFY device; here `play` synthesizes the tone and
    writes it to the selected NOTIFY device's sink (NullSink by default),
    returning the PCM so tests and callers can assert on it.
    """

    def __init__(self, audio_system=None):
        self._audio_system = audio_system
        self.is_mute = False

    def set_mute(self, mute: bool) -> None:
        self.is_mute = bool(mute)

    def play(self, freq_hz: float = 440.0, duration_s: float = 0.2,
             sample_rate: int = 48000) -> np.ndarray:
        from libjitsi_tpu.device.sources import ToneSource

        n = int(duration_s * sample_rate)
        if self.is_mute:
            return np.zeros(0, dtype=np.int16)
        pcm = ToneSource(freq_hz, sample_rate=sample_rate).read(n)
        if self._audio_system is not None:
            from libjitsi_tpu.device.system import DataFlow

            dev = self._audio_system.selected_device(DataFlow.NOTIFY)
            if dev is not None:
                sink = dev.create_sink()
                try:
                    sink.write(pcm)
                finally:
                    sink.close()
        return pcm
