"""MediaStream — one RTP session leg as a row in shared batched state.

The reference's `org.jitsi.impl.neomedia.MediaStreamImpl` (~4k lines) owns
sockets, an FMJ Processor, a TransformEngineChain and per-stream stats
objects; 10k streams = 10k heavyweight object graphs.  Here a stream is a
*row id* into dense tables owned by a shared `StreamRegistry` (crypto
contexts, stats, levels) plus a small host control block (ssrc, seq/ts
counters, direction, format map).  The transform chain is shared and
batched; any number of streams' packets ride one device launch.

API shape mirrors `org.jitsi.service.neomedia.MediaStream`:
`set_direction`, `add_dynamic_rtp_payload_type`, `set_remote_ssrc`,
`start`/`close`, plus batched `send`/`receive` (the connector read/write
surface that the io/ layer drives).
"""

from __future__ import annotations

import enum
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from libjitsi_tpu.core.config import ConfigurationService
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.rtp.stats import StreamStatsTable
from libjitsi_tpu.rtp.stats2 import StatsPoller
from libjitsi_tpu.control.sdes import SdesControl
from libjitsi_tpu.transform.engine import TransformEngineChain, TransformEngine
from libjitsi_tpu.transform.header_ext import (
    AbsSendTimeEngine,
    CsrcAudioLevelEngine,
    TransportCCEngine,
)
from libjitsi_tpu.transform.srtp.context import SrtpStreamTable
from libjitsi_tpu.transform.srtp.engine import SrtpTransformEngine
from libjitsi_tpu.transform.srtp.policy import SrtpProfile


class Direction(enum.Enum):
    """Reference: org.jitsi.service.neomedia.MediaDirection."""

    SENDRECV = "sendrecv"
    SENDONLY = "sendonly"
    RECVONLY = "recvonly"
    INACTIVE = "inactive"

    @property
    def allows_sending(self) -> bool:
        return self in (Direction.SENDRECV, Direction.SENDONLY)

    @property
    def allows_receiving(self) -> bool:
        return self in (Direction.SENDRECV, Direction.RECVONLY)


class StreamRegistry:
    """Shared batch domain: dense per-stream tables + ssrc demux.

    One registry per media service; all its streams' packets can share
    device launches.  Reference analog: the MediaServiceImpl-owned
    machinery each MediaStreamImpl hooks into.
    """

    def __init__(self, config: ConfigurationService, capacity: int = 1024):
        self.config = config
        self.capacity = capacity
        self.stats = StreamStatsTable(capacity)
        # MediaStreamStats2-shaped pull API (rates for all rows close in
        # one vectorized poll; streams read per-track views from it)
        self.stats2 = StatsPoller(self.stats)
        # per-profile crypto tables, created on first use (tx, rx)
        self._srtp: Dict[SrtpProfile, Tuple[SrtpStreamTable, SrtpStreamTable]] = {}
        self._free = list(range(capacity - 1, -1, -1))
        self._ssrc_to_sid: Dict[int, int] = {}
        self.streams: Dict[int, "MediaStream"] = {}

    @property
    def free_slots(self) -> int:
        """Rows available to alloc (admission control's capacity gate)."""
        return len(self._free)

    def alloc(self, stream: "MediaStream") -> int:
        if not self._free:
            raise RuntimeError("stream capacity exhausted")
        sid = self._free.pop()
        self.streams[sid] = stream
        return sid

    def reserve(self, sid: int, stream) -> None:
        """Claim a SPECIFIC row (checkpoint restore: a resumed bridge
        must reoccupy its old sids so SRTP rows and demux keep lining
        up).  Raises if the row is already taken."""
        self.reserve_many([sid], stream)

    def reserve_many(self, sids, stream) -> None:
        """Bulk `reserve`: one pass over the free list regardless of
        how many rows a restore reclaims (a 10k-endpoint resume must
        not pay len(free) per row)."""
        want = {int(s) for s in sids}
        taken = want - set(self._free)
        if taken:
            raise ValueError(f"sids not free: {sorted(taken)}")
        self._free = [s for s in self._free if s not in want]
        for s in want:
            self.streams[s] = stream

    def release(self, sid: int) -> None:
        self.streams.pop(sid, None)
        for tx, rx in self._srtp.values():
            if tx.active[sid]:
                tx.remove_stream(sid)
            if rx.active[sid]:
                rx.remove_stream(sid)
        self.stats.reset(sid)  # a recycled row must not inherit counters
        self.stats2.reset(sid)
        self._free.append(sid)

    def srtp_tables(self, profile: SrtpProfile
                    ) -> Tuple[SrtpStreamTable, SrtpStreamTable]:
        if profile not in self._srtp:
            self._srtp[profile] = (
                SrtpStreamTable(self.capacity, profile),
                SrtpStreamTable(self.capacity, profile),
            )
        return self._srtp[profile]

    # ------------------------------------------------------------- demux
    def map_ssrc(self, ssrc: int, sid: int) -> None:
        self._ssrc_to_sid[ssrc & 0xFFFFFFFF] = sid

    def unmap_ssrc(self, ssrc: int) -> None:
        self._ssrc_to_sid.pop(ssrc & 0xFFFFFFFF, None)

    def demux(self, batch: PacketBatch) -> np.ndarray:
        """Fill batch.stream from each packet's RTP SSRC; returns the ids
        (-1 where unknown — the reference drops packets of unknown SSRC
        unless discovery is enabled)."""
        hdr = rtp_header.parse(batch)
        m = self._ssrc_to_sid
        sids = np.fromiter((m.get(int(s), -1) for s in hdr.ssrc),
                           dtype=np.int64, count=batch.batch_size)
        batch.stream[:] = sids
        return sids

    def demux_rtcp(self, batch: PacketBatch) -> np.ndarray:
        """Same, for RTCP rows (sender SSRC sits at byte offset 4)."""
        ssrc = rtp_header.read_u32(batch.data, 4)
        m = self._ssrc_to_sid
        sids = np.fromiter((m.get(int(s), -1) for s in ssrc),
                           dtype=np.int64, count=batch.batch_size)
        batch.stream[:] = sids
        return sids


class MediaStream:
    """One RTP session leg (reference: MediaStreamImpl).

    Use via `MediaService.create_media_stream`.  Typical life cycle::

        s = media_service().create_media_stream(profile=..., registry=...)
        s.add_dynamic_rtp_payload_type(96, "opus", 48000)
        s.set_remote_ssrc(0x1234)
        offer = s.sdes.create_offer()        # -> signaling
        s.sdes.accept_answer(answer_line)
        s.start()
        wire = s.send([payload0, payload1])  # protected RTP bytes out
        pkts, ok = s.receive(incoming)       # decrypted payloads in
    """

    def __init__(self, registry: StreamRegistry,
                 profile: SrtpProfile = SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                 direction: Direction = Direction.SENDRECV,
                 local_ssrc: Optional[int] = None,
                 extra_engines: Sequence[TransformEngine] = ()):
        self.registry = registry
        self.profile = profile
        self.direction = direction
        self.sid = registry.alloc(self)
        self.local_ssrc = (int.from_bytes(os.urandom(4), "big")
                           if local_ssrc is None else local_ssrc) & 0xFFFFFFFF
        self.remote_ssrc: Optional[int] = None
        self.sdes = SdesControl(profiles=[profile])
        self._formats: Dict[int, Tuple[str, int]] = {}  # pt -> (name, rate)
        self._tx_seq = int.from_bytes(os.urandom(2), "big")
        self._tx_ts = int.from_bytes(os.urandom(4), "big")
        self._extra = list(extra_engines)
        self._chain: Optional[TransformEngineChain] = None
        self._started = False
        self._rtcp_listeners: list = []
        # send-side BWE (reference: BandwidthEstimatorImpl on the
        # stream): fed by handle_rtcp from RR loss, REMB caps and TCC
        # feedback (the latter via a TransportCCEngine when one is in
        # the chain's extra engines)
        from libjitsi_tpu.bwe.send_side import SendSideBandwidthEstimation
        self.bwe = SendSideBandwidthEstimation()
        self._tcc_engine: Optional[TransportCCEngine] = next(
            (e for e in self._extra
             if isinstance(e, TransportCCEngine)), None)

    # ------------------------------------------------------------ control
    def add_dynamic_rtp_payload_type(self, pt: int, encoding: str,
                                     clock_rate: int) -> None:
        """Reference: MediaStream.addDynamicRTPPayloadType."""
        self._formats[pt] = (encoding, clock_rate)
        self.registry.stats.clock_rate[self.sid] = clock_rate

    def set_direction(self, d: Direction) -> None:
        self.direction = d

    def set_remote_ssrc(self, ssrc: int) -> None:
        if self.remote_ssrc is not None:
            self.registry.unmap_ssrc(self.remote_ssrc)
        self.remote_ssrc = ssrc & 0xFFFFFFFF
        self.registry.map_ssrc(self.remote_ssrc, self.sid)

    def start(self, srtp_control=None) -> None:
        """Install negotiated keys and build the transform chain.

        `srtp_control`: any COMPLETED keying control exposing
        ``srtp_keys() -> (profile, tx_key, tx_salt, rx_key, rx_salt)``
        — a `DtlsSrtpEndpoint` or `ZrtpEndpoint`; default is the
        stream's own SDES negotiation.  Reference:
        MediaStreamImpl.start() wiring the TransformEngineChain with
        whichever SrtpControl (SDES/DTLS/ZRTP) signaling chose.
        """
        if self._started:
            return
        tx_tab, rx_tab = self.registry.srtp_tables(self.profile)
        if srtp_control is not None:
            profile, tk, tsalt, rk, rsalt = srtp_control.srtp_keys()
            if profile != self.profile:
                raise ValueError(
                    f"control negotiated {profile.name}, stream built "
                    f"for {self.profile.name}")
            tx_tab.add_stream(self.sid, tk, tsalt)
            rx_tab.add_stream(self.sid, rk, rsalt)
        elif self.sdes.negotiated:
            lo, re = self.sdes.local, self.sdes.remote
            tx_tab.add_stream(self.sid, lo.master_key, lo.master_salt)
            rx_tab.add_stream(self.sid, re.master_key, re.master_salt)
        else:
            raise RuntimeError(
                "no keys negotiated; complete SDES, or pass a completed "
                "DTLS/ZRTP control to start()")
        engines = list(self._extra) + [SrtpTransformEngine(tx_tab, rx_tab)]
        self._chain = TransformEngineChain(engines)
        self._started = True

    def close(self) -> bytes:
        """Tear down; returns an RTCP BYE to send (reference emits BYE)."""
        bye = rtcp.build_bye(rtcp.Bye([self.local_ssrc]))
        if self.remote_ssrc is not None:
            self.registry.unmap_ssrc(self.remote_ssrc)
        self.registry.release(self.sid)
        self._started = False
        return bye

    # --------------------------------------------------------------- send
    def send(self, payloads: Sequence[bytes], pt: int = 96,
             ts_step: int = 960, marker=None) -> List[bytes]:
        """Packetize + run the send chain; returns wire-ready datagrams.

        ts_step defaults to 20 ms at 48 kHz.  Reference path: FMJ
        packetizer -> RTPConnectorOutputStream.write -> chain loop
        (SURVEY §3.2).
        """
        if not self.direction.allows_sending:
            raise RuntimeError(f"direction {self.direction.value} cannot send")
        if not self._started:
            raise RuntimeError("start() first")
        n = len(payloads)
        seqs = [(self._tx_seq + i) & 0xFFFF for i in range(n)]
        tss = [(self._tx_ts + i * ts_step) & 0xFFFFFFFF for i in range(n)]
        self._tx_seq = (self._tx_seq + n) & 0xFFFF
        self._tx_ts = (self._tx_ts + n * ts_step) & 0xFFFFFFFF
        batch = rtp_header.build(payloads, seqs, tss, self.local_ssrc, pt,
                                 marker=marker, stream=[self.sid] * n)
        out, mask = self._chain.rtp_transformer.transform(batch)
        self.registry.stats.on_sent(out.stream[mask],
                                    np.asarray(out.length)[mask])
        return [out.to_bytes(i) for i in np.nonzero(mask)[0]]

    # ------------------------------------------------------------ receive
    def receive(self, datagrams: Sequence[bytes],
                arrival: Optional[float] = None
                ) -> Tuple[PacketBatch, np.ndarray]:
        """Run the receive chain on raw datagrams for this stream.

        Returns (batch, ok): decrypted packets and per-row verdicts.
        Multi-stream ingest goes through `StreamRegistry.demux` + the
        shared chain instead (io layer / SFU path).
        """
        if not self.direction.allows_receiving:
            raise RuntimeError(f"direction {self.direction.value} cannot receive")
        if not self._started:
            raise RuntimeError("start() first")
        batch = PacketBatch.from_payloads(datagrams,
                                          stream=[self.sid] * len(datagrams))
        out, ok = self._chain.rtp_transformer.reverse_transform(batch)
        hdr = rtp_header.parse(out)
        if np.any(ok):
            now = time.time() if arrival is None else arrival
            self.registry.stats.on_received(
                out.stream[ok], hdr.seq[ok], hdr.ts[ok],
                np.asarray(out.length)[ok],
                np.full(int(ok.sum()), now))
        return out, ok

    # --------------------------------------------------------------- rtcp
    def make_rtcp_report(self, now: Optional[float] = None) -> bytes:
        """Compound SR/RR + SDES CNAME (reference: RTCP report generation
        the stream's RTPManager schedules)."""
        st = self.registry.stats
        sending = self.direction.allows_sending and st.tx_packets[self.sid] > 0
        blocks = []
        if self.remote_ssrc is not None and st.rx_packets[self.sid] > 0:
            blocks = [st.make_report_block(self.sid, self.remote_ssrc, now)]
        if sending:
            sr = st.make_sr(self.sid, self.local_ssrc, self._tx_ts,
                            reports=blocks, now=now)
            main = rtcp.build_sr(sr)
        else:
            main = rtcp.build_rr(rtcp.ReceiverReport(self.local_ssrc, blocks))
        cname = f"libjitsi-tpu-{self.local_ssrc:08x}".encode()
        sdes = rtcp.build_sdes([rtcp.SdesChunk(self.local_ssrc, [(1, cname)])])
        return rtcp.build_compound([main, sdes])

    def handle_rtcp(self, blob: bytes, now: Optional[float] = None) -> list:
        """Feed an incoming (already-unprotected) compound RTCP packet to
        stats; returns the parsed packets for upper layers (BWE etc.).
        Registered RTCP listeners (reference: RTCPPacketListener on
        MediaStreamStats2) see every parsed packet."""
        pkts = rtcp.parse_compound(blob)
        st = self.registry.stats
        now_ms = (time.time() if now is None else now) * 1000.0
        for p in pkts:
            if isinstance(p, rtcp.SenderReport):
                st.on_sr_received(self.sid, p, arrival=now)
                for rb in p.reports:
                    if rb.ssrc == self.local_ssrc:
                        st.on_rr_received(self.sid, rb, now=now)
                        self.bwe.on_receiver_report(rb.fraction_lost,
                                                    now_ms)
            elif isinstance(p, rtcp.ReceiverReport):
                for rb in p.reports:
                    if rb.ssrc == self.local_ssrc:
                        st.on_rr_received(self.sid, rb, now=now)
                        self.bwe.on_receiver_report(rb.fraction_lost,
                                                    now_ms)
            elif isinstance(p, rtcp.Remb):
                self.bwe.on_remb(p.bitrate_bps)
            elif isinstance(p, rtcp.TccFeedback) and \
                    self._tcc_engine is not None:
                sts = [self._tcc_engine.lookup_send_time(
                           (p.base_seq + i) & 0xFFFF)
                       for i in range(len(p.received))]
                self.bwe.on_tcc_feedback(
                    p, [None if t is None else t * 1000.0 for t in sts],
                    now_ms)
        for fn in list(self._rtcp_listeners):   # listeners may remove
            for p in pkts:                      # themselves mid-callback
                fn(self, p)
        return pkts

    def add_rtcp_listener(self, fn) -> None:
        """fn(stream, parsed_rtcp_packet) per incoming RTCP packet."""
        self._rtcp_listeners.append(fn)

    def remove_rtcp_listener(self, fn) -> None:
        self._rtcp_listeners.remove(fn)

    # -------------------------------------------------------------- stats
    @property
    def stats(self) -> dict:
        """Flat snapshot for this stream (see `send_stats` /
        `receive_stats` for the typed MediaStreamStats2 views)."""
        st = self.registry.stats
        i = self.sid
        return {
            "tx_packets": int(st.tx_packets[i]),
            "tx_bytes": int(st.tx_bytes[i]),
            "rx_packets": int(st.rx_packets[i]),
            "rx_bytes": int(st.rx_bytes[i]),
            "cumulative_lost": st.cumulative_lost(i),
            "jitter_rtp_units": float(st.jitter[i]),
            "rtt_seconds": float(st.rtt[i]),
        }

    def send_stats(self):
        """Typed per-track send stats (reference: `stats.SendTrackStats`
        via MediaStreamStats2.getSendStats).  Rates reflect the
        registry poller's last closed interval — call
        `registry.stats2.poll()` periodically."""
        return self.registry.stats2.send_stats(self.sid)

    def receive_stats(self):
        """Typed per-track receive stats (reference:
        `stats.ReceiveTrackStats` via getReceiveStats)."""
        return self.registry.stats2.receive_stats(self.sid)


def create_media_stream(config: ConfigurationService,
                        registry: Optional[StreamRegistry] = None,
                        **kwargs) -> MediaStream:
    if registry is None:
        raise ValueError("a StreamRegistry is required "
                         "(MediaService owns the default)")
    return MediaStream(registry, **kwargs)
