"""Typed stream API: audio and video specializations.

Mirrors the reference's `org.jitsi.service.neomedia.AudioMediaStream`
(DTMF sending, per-stream audio-level listeners — backed by
`AudioMediaStreamImpl`) and `VideoMediaStream` (keyframe requests,
simulcast accessors — `VideoMediaStreamImpl`), as thin facades over the
shared batched machinery: the DTMF engine and level extraction are
chain engines; keyframe requests are RTCP PLI/FIR builders.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.media_stream import MediaStream
from libjitsi_tpu.transform.dtmf import DtmfTransformEngine
from libjitsi_tpu.transform.header_ext import CsrcAudioLevelEngine


class AudioMediaStream(MediaStream):
    """Reference: AudioMediaStream.startSendingDTMF / addDTMFListener /
    setLocalUserAudioLevelListener."""

    def __init__(self, *args, dtmf_pt: int = 101, level_ext_id: int = 1,
                 **kwargs):
        self._dtmf = DtmfTransformEngine(dtmf_pt=dtmf_pt,
                                         on_event=self._dispatch_dtmf)
        self._levels = CsrcAudioLevelEngine(ext_id=level_ext_id)
        self._dtmf_listeners = []
        self._level_listeners = []
        self._levels.on_levels = self._dispatch_levels
        extra = list(kwargs.pop("extra_engines", ()))
        # audio-level stamping runs before DTMF morphing, both before SRTP
        kwargs["extra_engines"] = [self._levels, self._dtmf] + extra
        super().__init__(*args, **kwargs)

    # ------------------------------------------------------------- DTMF
    def start_sending_dtmf(self, tone: str) -> None:
        self._dtmf.start_tone(self.sid, tone)

    def stop_sending_dtmf(self) -> None:
        self._dtmf.stop_tone(self.sid)

    def add_dtmf_listener(self, fn: Callable) -> None:
        self._dtmf_listeners.append(fn)

    def _dispatch_dtmf(self, sid: int, event) -> None:
        for fn in self._dtmf_listeners:
            fn(sid, event)

    # ------------------------------------------------------------ levels
    def set_level_source(self, level_of: Callable[[np.ndarray], np.ndarray]
                         ) -> None:
        """Install the per-row level source stamped into RFC 6464 exts
        (typically `lambda sids: mixer_levels[sids]`)."""
        self._levels.level_of = level_of

    def add_audio_level_listener(self, fn: Callable) -> None:
        self._level_listeners.append(fn)

    def _dispatch_levels(self, sids, levels) -> None:
        for fn in self._level_listeners:
            fn(sids, levels)

    @property
    def last_received_level(self) -> int:
        return int(self._levels.last_levels[self.sid])


class VideoMediaStream(MediaStream):
    """Reference: VideoMediaStream (keyframe request via RTCP feedback,
    simulcast bookkeeping via the track model)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.simulcast = None  # SimulcastReceiver, set via set_layers
        self._fir_seq_n = 0

    def set_simulcast_layers(self, layer_ssrcs: Sequence[int]) -> None:
        from libjitsi_tpu.codecs.vp8 import SimulcastReceiver

        self.simulcast = SimulcastReceiver(layer_ssrcs)

    def request_keyframe(self, use_fir: bool = False) -> bytes:
        """Build the PLI (or FIR) to send toward the remote sender
        (reference: RTCPFeedbackMessageSender.sendPLI/FIR)."""
        if self.remote_ssrc is None:
            raise RuntimeError("no remote ssrc to request a keyframe from")
        if use_fir:
            return rtcp.build_fir(rtcp.Fir(
                self.local_ssrc, self.remote_ssrc,
                [(self.remote_ssrc, self._next_fir_seq())]))
        return rtcp.build_pli(rtcp.Pli(self.local_ssrc, self.remote_ssrc))

    def _next_fir_seq(self) -> int:
        self._fir_seq_n = (self._fir_seq_n + 1) & 0xFF
        return self._fir_seq_n
