"""Stream lifecycle plane: churn-proof admit/evict for the whole bridge.

The translator/SFU primitive benchmarks beautifully on a STATIC stream
population, but the north-star traffic is continuous join/leave: every
naive install risks landing a recompile or a multi-hundred-ms table
copy on the data path, departed streams leak recovery/PLC/BWE state,
and overload shedding can "restore" a stream that already left.  One
`StreamLifecycleManager` owns the whole problem:

1. **O(1) slot admit/evict into pre-compiled bucketed shapes** — the
   device only ever sees the size-class shapes of core/packet.py
   (`LENGTH_CLASSES` x `ROW_CLASSES`); the manager warms each row class
   OFF-TICK the first time the population bucket (power of two) could
   reach it, so growing from 63 to 64 streams compiles nothing on the
   media path.  `utils/compile_cache.CompileCacheStats` brackets every
   tick (`tick_begin`/`tick_end`, wired by BridgeSupervisor): any
   compile event inside the window increments `datapath_recompiles`,
   and `assert_datapath_clean()` turns the "zero recompiles ever land
   on the data path" claim into a checkable invariant.

2. **Pipelined off-tick key install** — `request_join` only queues; the
   KDF/key-schedule/GHASH work runs between ticks in batches
   (`SfuBridge.stage_endpoints` -> one vectorized `add_streams` per
   table), media racing the install queues on the MediaLoop hold mask,
   and `commit_endpoints` flips the whole batch live atomically between
   ticks (one route rebuild, held media replayed).  In-flight admits
   ride the supervisor checkpoint and are completed or rolled back by
   `_reconcile` after `recover()` — never left half-installed.

3. **Burn-aware admission control** — joins are refused with a TYPED
   reason (`fast_burn`, `host_bound`, `shedding`, `stalled`,
   `capacity`, `backlog`, `duplicate`, `shard_burn`,
   `handshake_backlog`) exported as
   `lifecycle_admit_rejected{reason=...}` and flight-recorded, via
   `BridgeSupervisor.admission_decision()`.  Evictions are bookkept as
   `evicted` (distinct from overload `shed`), so the supervisor's LIFO
   unwind never resurrects a departed stream.

4. **Off-tick handshake pipeline** (`HandshakeQueue`) — DTLS joins
   admit through `request_handshake` (same typed-refusal contract,
   plus a retry-after hint when the handshake plane is saturated),
   their OpenSSL work drains in bounded batches on the between-ticks
   window, and completed keys land via `stage_dtls_keys` -> the same
   commit barrier as direct-keyed joins: a keyed row becomes live
   atomically, never mid-tick, and the media tick thread never
   executes a single OpenSSL call.

Reference: no analog — the reference allocates a MediaStream object
per join and lets the JVM GC departures; a dense-table runtime must
manage stream mortality explicitly.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from libjitsi_tpu.core.packet import ROW_CLASSES
from libjitsi_tpu.utils.compile_cache import compile_stats
from libjitsi_tpu.utils.flight import FlightRecorder
from libjitsi_tpu.utils.logging import get_logger

_log = get_logger("lifecycle")

#: every reason `request_join`/`request_handshake` can refuse with
#: (typed: metrics, flight events and callers all share these strings)
ADMIT_REASONS = ("capacity", "backlog", "duplicate", "fast_burn",
                 "stalled", "shedding", "host_bound", "shard_burn",
                 "hop_burn", "handshake_backlog", "trunk_down",
                 "trunk_backlog", "capacity_forecast")


@dataclass
class LifecycleConfig:
    """Knobs for the admit/evict pipeline."""

    min_bucket: int = 16         # smallest population bucket warmed
    install_batch: int = 64      # joins staged per between-ticks window
    max_pending: int = 512       # queued + staged backlog cap
    warm_payload_len: int = 160  # representative payload for warmups
    # est. packets per stream per tick: sizes the row classes a
    # population bucket can drive (warmup_rtp uses the same figure)
    pkts_per_stream: int = 4
    # ------------------------------------------ handshake plane knobs
    # datagrams the HandshakeQueue drains per between-ticks window
    # (the OpenSSL budget — install_batch's twin for handshakes)
    handshake_batch: int = 64
    # backlog bound (queued datagrams + pending associations) past
    # which request_handshake refuses `handshake_backlog`
    max_handshakes: int = 256
    # flight retransmission jitter: each off-tick pass services only
    # 1/stride of the pending associations' RFC 6347 timers, spreading
    # a storm's flights so retransmissions never fire in lockstep
    handshake_retx_stride: int = 4
    # nominal between-ticks cadence used to turn a backlog depth into
    # the retry-after hint attached to handshake_backlog refusals
    handshake_retry_tick_s: float = 0.02


class HandshakeQueue:
    """Off-tick DTLS handshake pipeline for one bridge.

    Construction flips the bridge's `DtlsAssociationTable` to deferred
    ingest — `on_dtls` (tick thread) only enqueues datagrams — and
    re-points its install callback at the STAGED landing: completed
    keys go through `stage_dtls_keys` and flip live at the next commit
    barrier, never mid-tick.  `drain()` runs on the between-ticks
    window (wired into `run_between_ticks`): one bounded `process`
    batch of OpenSSL work plus a jittered flight-retransmission pass
    with gather egress (one PacketBatch per peer per pass).

    ZRTP associations share the same endpoint surface (`feed` /
    `complete` / `srtp_keys`), so a ZRTP-keyed bridge plugs into this
    queue unchanged; today's bridges key via DTLS-SRTP.
    """

    def __init__(self, lc: "StreamLifecycleManager"):
        self.lc = lc
        self.bridge = lc.bridge
        self.cfg = lc.cfg
        self.table = lc.bridge._dtls
        self.table.deferred = True
        # generous inbox: refusal happens at ADMISSION (typed, with a
        # retry hint), not by silently dropping datagrams of already
        # admitted associations.  ~2 flights of 6 datagrams per row.
        self.table.inbox_limit = max(self.table.inbox_limit,
                                     12 * self.cfg.max_handshakes)
        self._inline_install = self.table.install
        self.table.install = self._on_complete
        # sid -> admission metadata (ssrc/role/fingerprint/cookie/addr
        # + admit tick): what a checkpoint needs to REQUEUE the
        # association after recover (OpenSSL state cannot serialize)
        self.active: Dict[int, dict] = {}
        self._pass = 0
        self.off_tick_seconds = 0.0
        self.completed = 0
        self.requeued = 0

    @property
    def depth(self) -> int:
        """Admission-facing depth: queued datagrams + pending rows."""
        return self.table.backlog

    def retry_after(self) -> float:
        """Hint for a refused client: model-time until the drain could
        plausibly reach it, from the backlog depth and the per-window
        budget.  Clients honor it with their own exponential backoff
        on repeated refusals."""
        passes = 1 + self.depth // max(1, self.cfg.handshake_batch)
        return round(passes * self.cfg.handshake_retry_tick_s, 4)

    def drain(self) -> int:
        """The between-ticks pass: bounded OpenSSL work + jittered
        flight retransmissions.  Wall time accrues to
        `off_tick_seconds` (the supervisor's phase-attribution ledger
        line — handshake cost is attributed HERE, never to a tick
        phase)."""
        t0 = time.perf_counter()
        n = self.table.process(self.cfg.handshake_batch)
        self._pass += 1
        self.table.tick(stride=max(1, self.cfg.handshake_retx_stride),
                        phase=self._pass)
        if self.active:
            # drop metadata for rows that left the plane sideways
            # (evicted mid-handshake, fingerprint-rejected)
            live = self.bridge._ssrc_of
            self.active = {s: m for s, m in self.active.items()
                           if s in self.table.pending or s in live}
        self.off_tick_seconds += time.perf_counter() - t0
        return n

    def _on_complete(self, sid: int, ep) -> None:
        """Install callback for the deferred table: land the exported
        keys STAGED so the commit barrier flips the row live."""
        meta = self.active.pop(sid, None)
        if hasattr(self.bridge, "stage_dtls_keys"):
            # the committed population grows at the next barrier: warm
            # its bucket NOW (off-tick) so the flip compiles nothing
            self.lc._ensure_warm(len(self.bridge._ssrc_of)
                                 - len(self.lc._listener_sids))
            self.bridge.stage_dtls_keys(sid, ep)
            self.lc._staged.append(sid)
            self.lc.key_installs += 1
        else:
            # bridge without a staged pipeline: inline install (still
            # off-tick — we are on the between-ticks window)
            self._inline_install(sid, ep)
            self.bridge.loop.release_stream(sid)
        self.completed += 1
        self.lc.flight.record(
            "handshake_complete", tick=self.lc.ticks(), sid=sid,
            ssrc=(meta or {}).get("ssrc"),
            profile=ep.selected_profile.name)

    def snapshot(self) -> List[dict]:
        """Mid-handshake associations for the supervisor checkpoint:
        OpenSSL state cannot serialize, so each rides as its admission
        parameters (plus its bound 5-tuple) and REQUEUES as a fresh
        association after recover — the peer's flight timers drive the
        new handshake."""
        out = []
        for sid, ep in self.table.pending.items():
            meta = self.active.get(sid, {})
            out.append({
                "ssrc": meta.get("ssrc", self.bridge._ssrc_of.get(sid)),
                "role": meta.get("role", getattr(ep, "role", "server")),
                "fingerprint": meta.get("fingerprint"),
                "cookie": bool(meta.get("cookie", False)),
                "addr": self.table.sid_addr.get(sid),
            })
        return out


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class StreamLifecycleManager:
    """Owns admit/evict for one bridge.  Construct after the
    BridgeSupervisor; the manager attaches itself
    (`supervisor.lifecycle = self`) so the supervisor's tick brackets
    the data path with the compile guard and runs the commit barrier +
    install stage between ticks.  Without a supervisor, call
    `run_between_ticks()` manually after each `bridge.tick()`."""

    def __init__(self, bridge, supervisor=None,
                 config: Optional[LifecycleConfig] = None,
                 metrics=None, flight: Optional[FlightRecorder] = None):
        self.bridge = bridge
        self.supervisor = supervisor
        self.cfg = config or LifecycleConfig()
        if flight is None:
            flight = (supervisor.flight if supervisor is not None
                      else getattr(bridge, "flight", None))
        self.flight = flight if flight is not None else FlightRecorder()
        # join queue: (ssrc, rx_key, tx_key, name, conference, role,
        # shard) — host-side only until poll() stages a batch.  `role`/
        # `shard` are None except for broadcast-conference joins
        # ("speaker"/"listener"; listeners carry their assigned shard,
        # which may differ from the conference's home shard)
        self._join_q: deque = deque()
        self._queued_ssrcs: set = set()
        # conference-affinity placement (mesh/placement.py): None until
        # enable_placement — the single-conference bridge needs none
        self.placer = None
        self._rows_per_shard = 0
        self._move_inflight: Optional[dict] = None
        self.moves_applied = 0
        self._staged: List[int] = []     # staged sids awaiting commit
        self._evict_q: List[int] = []
        # counters (all registered in register_metrics)
        self.admits = 0
        self.evicts = 0
        self.key_installs = 0
        self.datapath_recompiles = 0
        self.admit_rejected: Dict[str, int] = {}
        # broadcast conferences (mesh/hierarchy.py): conf ->
        # {"speakers": set of sids, "join_good"/"join_bad": cumulative
        # listener-join outcomes feeding the label="conference" burn
        # slice}; listener sids tracked separately for the fanout-only
        # warmup ladder and the bcast_listeners gauge
        self._bcast: Dict[int, dict] = {}
        self._listener_sids: set = set()
        # cascaded conferences homed on a REMOTE bridge (mesh/cascade):
        # conf key -> trunk.  While the trunk is down/backlogged, joins
        # into these refuse with the trunk's typed reason + retry-after
        # hint; failover adoption promotes them local and clears this
        self._remote_conf: Dict[int, object] = {}
        self._role_flips: List[Tuple[int, int, str]] = []
        self.speaker_promotions = 0
        self.speaker_demotions = 0
        # population bucket whose shapes are warm; row classes warmed
        self._warm_bucket = 0
        self._warm_rows: set = set()
        # fanout-only listener rows warm a ladder of their own: no
        # uplink RTP classes, just fan-out legs + RTCP
        self._warm_lbucket = 0
        self._warm_lrows: set = set()
        self._tick_compiles0: Optional[int] = None
        # off-tick handshake pipeline: attaches only when the bridge
        # keys rows via a DTLS association table (SfuBridge /
        # ConferenceBridge); direct-keyed bridges and test fakes get
        # None and the plane behaves exactly as before
        self.handshakes: Optional[HandshakeQueue] = None
        if getattr(bridge, "_dtls", None) is not None:
            self.handshakes = HandshakeQueue(self)
        # OpenSSL feed() calls observed INSIDE tick windows (invariant:
        # 0 once deferred — the reconnect soak gates on it)
        self.tick_thread_handshake_feeds = 0
        self._tick_feeds0: Optional[int] = None
        if supervisor is not None:
            supervisor.lifecycle = self
            pend = getattr(supervisor, "pending_lifecycle", None)
            if pend:
                self._reconcile(pend)
                supervisor.pending_lifecycle = None
        if metrics is not None:
            self.register_metrics(metrics)

    # ------------------------------------------------------- placement

    def enable_placement(self, n_shards: int, placer=None) -> None:
        """Turn on conference-affinity sharding (mesh/placement.py):
        joins carry a `conference` id, whole conferences are assigned
        to shards at join time, rows are drawn from the conference's
        shard range, and rebalance moves run through the commit
        barrier.  `n_shards` must divide the registry capacity (shard
        ranges are contiguous row blocks)."""
        from libjitsi_tpu.mesh.placement import ConferencePlacer
        capacity = self.bridge.registry.capacity
        if capacity % n_shards:
            raise ValueError(f"capacity {capacity} not divisible by "
                             f"{n_shards} shards")
        self._rows_per_shard = capacity // n_shards
        if placer is None:
            placer = ConferencePlacer(
                n_shards, rows_per_shard=self._rows_per_shard)
        elif placer.rows_per_shard > self._rows_per_shard:
            raise ValueError("placer rows_per_shard exceeds the "
                             "registry's shard range")
        self.placer = placer
        # shard-major dispatch: contiguous shard sid ranges mean a
        # stable per-batch sort groups each device's rows (io/loop.py)
        loop = getattr(self.bridge, "loop", None)
        if loop is not None and hasattr(loop, "enable_shard_major"):
            loop.enable_shard_major(self._rows_per_shard)

    # ------------------------------------------------------- broadcast

    def declare_broadcast(self, conference, objective: float = 0.999
                          ) -> int:
        """Declare `conference` a BROADCAST conference (webinar shape:
        a handful of speakers, fanout-only listeners).  Requires
        placement: the speaker rows get a home shard (never straddle),
        listener rows spread over every shard (`mesh/hierarchy.py`'s
        two-level tick mixes speakers on the home shard and fans the
        bus out in one sanctioned collective).  Joins then default to
        role="listener"; speakers join with role="speaker" or are
        promoted later (`promote_speaker`, a commit-barrier event).
        Registers the label="conference" listener-join burn slice on
        the supervisor's SLO engine the first time.  Returns the home
        shard."""
        if self.placer is None:
            raise RuntimeError("broadcast conferences need placement "
                               "(enable_placement first)")
        conf = int(conference)
        if conf in self._bcast:
            return self.placer.shard_of(conf)
        home = self.placer.place_broadcast(
            conf, 0, avoid=self._burning_shards())
        if home is None:
            raise RuntimeError("no shard can home the broadcast "
                               "conference")
        self._bcast[conf] = {"speakers": set(),
                             "join_good": 0, "join_bad": 0}
        if hasattr(self.bridge, "set_broadcast_speakers"):
            self.bridge.set_broadcast_speakers(conf, ())
        self._register_conference_slo(objective)
        self.flight.record("broadcast_declared", tick=self.ticks(),
                           conf=conf, home=home)
        _log.info("broadcast_declared", conf=conf, home=home)
        return home

    def _register_conference_slo(self, objective: float) -> None:
        slo = getattr(self.supervisor, "slo", None) \
            if self.supervisor is not None else None
        if slo is None:
            return
        if any(s.name == "bcast_listener_join"
               for s in getattr(slo, "sliced", ())):
            return
        from libjitsi_tpu.utils.slo import SlicedSloSpec

        def _reader():
            for conf, st in self._bcast.items():
                yield (str(conf), float(st["join_good"]),
                       float(st["join_bad"]))

        slo.add_sliced(SlicedSloSpec(
            "bcast_listener_join", objective=objective,
            label="conference", reader=_reader,
            description="broadcast listener joins admitted vs refused, "
                        "per conference"))

    def _place_bcast_join(self, conf: int, role: str
                          ) -> Tuple[Optional[int], Optional[str]]:
        """(shard, reason) for a join into a broadcast conference.
        Speakers grow the home shard (never straddle); listeners land
        on any shard with row headroom, steering around burning ones."""
        home = self.placer.shard_of(conf)
        if role == "speaker":
            if self.supervisor is not None:
                ok, r = self.supervisor.admission_decision(shard=home)
                if not ok and r in ("shard_burn", "capacity_forecast"):
                    return None, r
            if not self.placer.try_grow(conf):
                return None, "capacity"
            return home, None
        shard = self.placer.grow_listeners(
            conf, avoid=self._burning_shards())
        if shard is None:
            return None, "capacity"
        return shard, None

    def promote_speaker(self, conference, sid: int) -> None:
        """Queue a listener→speaker role flip; applied at the next
        commit barrier (routes rebuild, fanout-only mask clears, the
        row migrates to the home shard if it lives elsewhere) — never
        mid-tick."""
        self._role_flips.append((int(conference), int(sid), "speaker"))

    # ------------------------------------------------------- cascade
    def mark_remote_conference(self, conference, trunk) -> None:
        """A cascaded conference homed on the trunk's PEER bridge:
        local joins are admitted while the trunk is up (they become
        local legs of the cascade) but refuse with the trunk's typed
        reason (`trunk_down` / `trunk_backlog`) while it is not."""
        self._remote_conf[self._conf_key(0, conference)] = trunk

    def promote_remote_conference(self, conference) -> None:
        """Failover: the conference is now homed HERE (orphan adoption
        committed) — joins stop consulting the trunk."""
        key = self._conf_key(0, conference)
        if self._remote_conf.pop(key, None) is not None:
            self.flight.record("conf_promoted", tick=self.ticks(),
                               conf=key)

    def retry_after_hint(self, reason: str, conference=None) -> float:
        """Seconds a refused caller should wait before retrying (the
        PR 16 hint surface, extended to trunk refusals): handshake
        refusals ride the queue's drain estimate, trunk refusals the
        trunk's jittered-exponential backoff."""
        if reason == "handshake_backlog" and self.handshakes is not None:
            return self.handshakes.retry_after
        if reason == "capacity_forecast":
            cap = getattr(self.supervisor, "capacity", None) \
                if self.supervisor is not None else None
            if cap is not None:
                return float(cap.retry_after())
        if reason in ("trunk_down", "trunk_backlog"):
            trunk = None
            if conference is not None:
                trunk = self._remote_conf.get(
                    self._conf_key(0, conference))
            if trunk is None and self._remote_conf:
                trunk = next(iter(self._remote_conf.values()))
            if trunk is not None:
                return float(trunk.retry_after())
        return self.cfg.handshake_retry_tick_s

    def demote_speaker(self, conference, sid: int) -> None:
        """Queue a speaker→listener role flip (commit-barrier event)."""
        self._role_flips.append((int(conference), int(sid), "listener"))

    def _conf_key(self, ssrc: int, conference) -> int:
        # a placement-enabled join without a conference id is a
        # singleton conference (keyed off the ssrc, negative so user
        # conference ids can never collide with it)
        return int(conference) if conference is not None \
            else -(int(ssrc) + 2)

    def _free_rows_on(self, shard: int, k: int) -> List[int]:
        """Up to `k` free registry rows inside `shard`'s range.  The
        registry stays the single source of truth for row freedom
        (video tracks and direct add_endpoint also draw from it);
        placement only constrains WHERE a conference's rows may live."""
        lo = shard * self._rows_per_shard
        hi = lo + self._rows_per_shard
        avail = sorted(s for s in self.bridge.registry._free
                       if lo <= s < hi)
        return avail[:k]

    # ------------------------------------------------------- admission

    def ticks(self) -> int:
        return self.supervisor.ticks if self.supervisor is not None else 0

    def _admission_reason(self, ssrc: int) -> Optional[str]:
        if (ssrc in self.bridge._ssrc_of.values()
                or ssrc in self._queued_ssrcs):
            return "duplicate"
        if len(self._join_q) + len(self._staged) >= self.cfg.max_pending:
            return "backlog"
        # queued joins have slots spoken for; evictions still queued do
        # NOT count as free (they only free up at the barrier)
        if self.bridge.registry.free_slots <= len(self._join_q):
            return "capacity"
        if self.supervisor is not None:
            ok, reason = self.supervisor.admission_decision()
            if not ok:
                return reason
        return None

    def _burning_shards(self) -> set:
        """Shards placement must steer around: fast-burning per-shard
        SLO slices, plus shards the capacity forecast already calls
        exhausted (utils/capacity.py) — same avoidance surface, one
        reactive signal and one predictive."""
        sup = self.supervisor
        out: set = set()
        slo = getattr(sup, "slo", None) if sup is not None else None
        if slo is not None:
            for spec in getattr(slo, "sliced", ()):
                if spec.label == "shard":
                    out |= {int(k)
                            for k in slo.burning_slices(spec.name)}
        cap = getattr(sup, "capacity", None) if sup is not None else None
        if cap is not None:
            out |= {int(s) for s in cap.exhausted_shards()}
        return out

    def _place_join(self, ssrc: int, conference) -> Tuple[Optional[int],
                                                          Optional[str]]:
        """Placement half of admission: returns (conf_key, reason).
        A join into an EXISTING conference targets its shard — refused
        `shard_burn` when that specific shard is burning fast (the
        conference cannot straddle to a healthy one), `capacity` when
        the shard's row range is full.  A NEW conference places
        least-loaded, steering around burning shards."""
        conf = self._conf_key(ssrc, conference)
        shard = self.placer.shard_of(conf)
        if shard is not None:
            if self.supervisor is not None:
                ok, r = self.supervisor.admission_decision(shard=shard)
                if not ok and r in ("shard_burn", "capacity_forecast"):
                    return conf, r
            if not self.placer.try_grow(conf):
                return conf, "capacity"
            return conf, None
        if self.placer.place(conf, 1,
                             avoid=self._burning_shards()) is None:
            return conf, "capacity"
        return conf, None

    def request_join(self, ssrc: int, rx_key: Tuple[bytes, bytes],
                     tx_key: Tuple[bytes, bytes],
                     name: Optional[str] = None,
                     conference=None,
                     role: Optional[str] = None) -> Tuple[bool, str]:
        """Admission decision + queue.  Returns (accepted, reason):
        (True, "queued") or (False, <typed reason>).  Nothing touches
        the device here — keys install off-tick in poll().

        With placement enabled (`enable_placement`), `conference`
        groups endpoints: the whole conference lives on one shard, its
        rows are drawn from that shard's range, and forwarding is
        scoped to it.  A join without a conference id is a singleton
        conference.  Joins into a declared BROADCAST conference default
        to role="listener" (fanout-only row on any shard); pass
        role="speaker" to join the mixed speaker set on the home
        shard."""
        ssrc = int(ssrc) & 0xFFFFFFFF
        reason = self._admission_reason(ssrc)
        if (reason is None and conference is not None
                and self._remote_conf):
            # cascaded conference homed on the trunk's peer: typed
            # trunk refusal while the trunk is down or backlogged
            # (None while up — the join becomes a local cascade leg)
            trunk = self._remote_conf.get(
                self._conf_key(ssrc, conference))
            if trunk is not None:
                reason = trunk.admit_reason()
        conf = shard = None
        bcast = False
        if reason is None and self.placer is not None:
            conf = self._conf_key(ssrc, conference)
            bcast = conf in self._bcast
            if bcast:
                role = role or "listener"
                shard, reason = self._place_bcast_join(conf, role)
            else:
                role = None
                conf, reason = self._place_join(ssrc, conference)
        if reason is not None:
            if bcast and role == "listener":
                self._bcast[conf]["join_bad"] += 1
            self.admit_rejected[reason] = \
                self.admit_rejected.get(reason, 0) + 1
            self.flight.record("admit_reject", tick=self.ticks(),
                               ssrc=ssrc, reason=reason)
            _log.info("admit_reject", ssrc=ssrc, reason=reason)
            return False, reason
        self._join_q.append((ssrc, tuple(rx_key), tuple(tx_key), name,
                             conf, role if bcast else None, shard))
        self._queued_ssrcs.add(ssrc)
        self.flight.record("admit_queued", tick=self.ticks(), ssrc=ssrc)
        return True, "queued"

    def request_handshake(self, ssrc: int, role: str = "server",
                          remote_fingerprint: Optional[str] = None,
                          cookie_exchange: bool = False,
                          remote_addr=None,
                          name: Optional[str] = None
                          ) -> Tuple[bool, str, float]:
        """Admission decision + association start for a DTLS-keyed
        join: the handshake plane's twin of `request_join`.  Returns
        `(accepted, reason, retry_after_s)` — `(True, "queued", 0.0)`
        on admit, or a typed refusal; `handshake_backlog` refusals
        (the plane saturated past `max_handshakes`) carry a non-zero
        retry-after hint that clients honor with exponential backoff.

        On admit the row allocates and the association starts
        immediately (`add_endpoint_dtls`): datagrams route to it from
        the next packet on, but ALL OpenSSL work runs on the
        between-ticks drain and the keys land via the staged commit
        barrier — the tick thread never handshakes.  Pass
        `remote_addr` when signaling knows the peer's 5-tuple; under a
        storm (many concurrent unbound rows) unknown-address datagrams
        are dropped rather than guessed onto the wrong row."""
        hq = self.handshakes
        if hq is None:
            raise RuntimeError(
                "bridge has no DTLS association table; use request_join")
        ssrc = int(ssrc) & 0xFFFFFFFF
        reason: Optional[str] = None
        if (ssrc in self.bridge._ssrc_of.values()
                or ssrc in self._queued_ssrcs):
            reason = "duplicate"
        elif self.bridge.registry.free_slots <= len(self._join_q):
            reason = "capacity"
        elif self.supervisor is not None:
            ok, r = self.supervisor.admission_decision(
                handshake_backlog=hq.depth,
                handshake_bound=self.cfg.max_handshakes)
            if not ok:
                reason = r
        elif hq.depth >= self.cfg.max_handshakes:
            reason = "handshake_backlog"
        if reason is not None:
            retry = hq.retry_after() \
                if reason == "handshake_backlog" else 0.0
            self.admit_rejected[reason] = \
                self.admit_rejected.get(reason, 0) + 1
            self.flight.record("handshake_reject", tick=self.ticks(),
                               ssrc=ssrc, reason=reason,
                               retry_after_s=retry)
            _log.info("handshake_reject", ssrc=ssrc, reason=reason,
                      retry_after_s=retry)
            return False, reason, retry
        sid, _ep = self.bridge.add_endpoint_dtls(
            ssrc, role=role, remote_fingerprint=remote_fingerprint,
            cookie_exchange=cookie_exchange, remote_addr=remote_addr)
        if name is not None:
            self.bridge.loop.metrics.set_stream_name(sid, name)
        hq.active[sid] = {
            "ssrc": ssrc, "role": role,
            "fingerprint": remote_fingerprint,
            "cookie": bool(cookie_exchange), "tick": self.ticks(),
        }
        self.flight.record("handshake_queued", tick=self.ticks(),
                           sid=sid, ssrc=ssrc)
        return True, "queued", 0.0

    def request_leave(self, sid: Optional[int] = None,
                      ssrc: Optional[int] = None) -> bool:
        """Queue an evict (by sid or ssrc).  A join still queued
        host-side is simply cancelled; anything staged or live is torn
        down at the next between-ticks barrier."""
        if sid is None:
            if ssrc is None:
                raise ValueError("need sid or ssrc")
            ssrc = int(ssrc) & 0xFFFFFFFF
            if ssrc in self._queued_ssrcs:          # never installed
                self._queued_ssrcs.discard(ssrc)
                if self.placer is not None:
                    for j in self._join_q:
                        if j[0] != ssrc or j[4] is None:
                            continue
                        if j[5] == "listener":
                            self.placer.shrink_listeners(j[4], j[6])
                        elif j[5] == "speaker":
                            self.placer.resize(
                                j[4], max(self.placer.size_of(j[4]) - 1,
                                          0))
                        else:
                            self.placer.shrink(j[4])
                self._join_q = deque(j for j in self._join_q
                                     if j[0] != ssrc)
                self.flight.record("admit_cancelled",
                                   tick=self.ticks(), ssrc=ssrc)
                return True
            sid = next((s for s, v in self.bridge._ssrc_of.items()
                        if v == ssrc), None)
            if sid is None:
                return False
        self._evict_q.append(int(sid))
        return True

    # ------------------------------------------- between-ticks pipeline

    def run_between_ticks(self, now=None) -> None:
        """The off-tick half of the plane: handshake drain first (its
        completions stage rows that the SAME window's commit flips
        live), then the commit barrier (staged rows flip live, queued
        evicts tear down — both between ticks, never inside one), then
        the next install wave, then any placement rebalance moves
        (also lifecycle events: a conference only ever changes shards
        here, never mid-tick)."""
        if self.handshakes is not None:
            self.handshakes.drain()
        self.commit()
        self.poll()
        self.rebalance()
        self.fill_keystream()

    def _keystream_caches(self):
        for name in ("rx_table", "tx_table"):
            cache = getattr(getattr(self.bridge, name, None),
                            "_ks_cache", None)
            if cache is not None:
                yield cache

    def fill_keystream(self) -> None:
        """Off-tick keystream pregeneration: top up the GCM caches'
        sliding windows AFTER the commit barrier (so a rekey's
        invalidation has already landed and the refill keys are the
        live ones).  All compile shapes here are fixed-chunk, so this
        phase never recompiles the data path."""
        for cache in self._keystream_caches():
            cache.fill()

    def commit(self) -> None:
        """Atomic (w.r.t. the tick) population flip: committed admits
        and processed evicts both land here, between ticks."""
        if self._staged or self._evict_q or self._role_flips:
            # pipeline drain barrier: a deep-pipelined loop may still
            # hold in-flight reverse work referencing rows about to be
            # evicted/recycled — collapse it before the population flips
            loop = getattr(self.bridge, "loop", None)
            drain = getattr(loop, "drain", None)
            if drain is not None:
                drain()
        if self._staged:
            sids, self._staged = self._staged, []
            self.bridge.commit_endpoints(sids)
            self.admits += len(sids)
            if self.supervisor is not None:
                self.supervisor.note_admitted(sids)
            touched: set = set()
            for sid in sids:
                conf = getattr(self.bridge, "_conf_of", {}).get(sid)
                st = self._bcast.get(conf)
                if st is not None:
                    if sid in self._listener_sids:
                        st["join_good"] += 1
                    elif sid in st["speakers"]:
                        touched.add(conf)
                self.flight.record("admit_commit", tick=self.ticks(),
                                   sid=sid)
            # newly committed speakers reshape routing: one
            # set_broadcast_speakers per touched conference rebuilds
            # routes and fanout-only masks at the barrier
            for conf in sorted(touched):
                self._push_speakers(conf)
        if self._evict_q:
            live = dict.fromkeys(self._evict_q)  # de-dup, keep order
            self._evict_q = []
            sids = [s for s in live if s in self.bridge._ssrc_of]
            if sids:
                conf_of = getattr(self.bridge, "_conf_of", {})
                gone_confs = [conf_of.get(s) for s in sids]
                self.bridge.remove_endpoints(sids)
                self.evicts += len(sids)
                if self.supervisor is not None:
                    self.supervisor.note_evicted(sids)
                if self.placer is not None:
                    touched = set()
                    bcast_gone = set()
                    for sid, conf in zip(sids, gone_confs):
                        if conf is None:
                            continue
                        st = self._bcast.get(conf)
                        if st is None:
                            self.placer.shrink(conf)
                            if self.placer.shard_of(conf) is None:
                                self._drop_conference_slices(conf)
                            continue
                        bcast_gone.add(conf)
                        if sid in self._listener_sids:
                            self._listener_sids.discard(sid)
                            self.placer.shrink_listeners(
                                conf, sid // self._rows_per_shard)
                        elif sid in st["speakers"]:
                            st["speakers"].discard(sid)
                            self.placer.resize(
                                conf,
                                max(self.placer.size_of(conf) - 1, 0))
                            touched.add(conf)
                    # a broadcast conference only releases when its last
                    # member leaves (0 speakers with listeners still
                    # attached is a legitimate state)
                    for conf in sorted(bcast_gone):
                        if any(c == conf for s, c in conf_of.items()
                               if s in self.bridge._ssrc_of):
                            continue
                        self.placer.release(conf)
                        self._drop_conference_slices(conf)
                        self._bcast.pop(conf, None)
                        touched.discard(conf)
                        if hasattr(self.bridge, "clear_broadcast"):
                            self.bridge.clear_broadcast(conf)
                    for conf in sorted(touched):
                        self._push_speakers(conf)
        self._apply_role_flips()

    def _push_speakers(self, conf: int) -> None:
        if hasattr(self.bridge, "set_broadcast_speakers"):
            self.bridge.set_broadcast_speakers(
                conf, tuple(sorted(self._bcast[conf]["speakers"])))

    def _apply_role_flips(self) -> None:
        """Commit-barrier application of queued promote/demote events:
        routes rebuild, fanout-only masks flip and (for a promotion off
        the home shard) the row migrates home — all between ticks, all
        on pre-warmed shapes, so a role flip compiles nothing."""
        if not self._role_flips:
            return
        flips, self._role_flips = self._role_flips, []
        touched: set = set()
        for conf, sid, role in flips:
            st = self._bcast.get(conf)
            if st is None or sid not in self.bridge._ssrc_of:
                continue
            if role == "speaker":
                if sid in st["speakers"]:
                    continue
                home = self.placer.shard_of(conf)
                cur = sid // self._rows_per_shard
                if cur != home:
                    rows = self._free_rows_on(home, 1)
                    if not rows or not self.placer.try_grow(conf):
                        self.flight.record(
                            "speaker_flip_refused", tick=self.ticks(),
                            conf=conf, sid=sid, reason="capacity")
                        continue
                    self.bridge.migrate_endpoints({sid: rows[0]})
                    self.placer.shrink_listeners(conf, cur)
                    self._listener_sids.discard(sid)
                    sid = rows[0]
                else:
                    if not self.placer.try_grow(conf):
                        self.flight.record(
                            "speaker_flip_refused", tick=self.ticks(),
                            conf=conf, sid=sid, reason="capacity")
                        continue
                    self.placer.shrink_listeners(conf, cur)
                    self._listener_sids.discard(sid)
                st["speakers"].add(sid)
                self.speaker_promotions += 1
            else:
                if sid not in st["speakers"]:
                    continue
                st["speakers"].discard(sid)
                self.placer.resize(
                    conf, max(self.placer.size_of(conf) - 1, 0))
                # the demoted row stays physically put: it re-books as
                # a listener row on its current shard
                self.placer.grow_listeners(
                    conf, shard=sid // self._rows_per_shard)
                self._listener_sids.add(sid)
                self.speaker_demotions += 1
            touched.add(conf)
            self.flight.record("speaker_flip", tick=self.ticks(),
                               conf=conf, sid=sid, role=role)
            _log.info("speaker_flip", conf=conf, sid=sid, role=role)
        for conf in sorted(touched):
            self._push_speakers(conf)

    def poll(self) -> None:
        """Stage the next install wave: batch-limited, slot-limited,
        with the target bucket's shapes warmed BEFORE any new stream
        can contribute traffic.  Under placement, each join's row is
        drawn from its conference's shard range (a spec whose shard has
        no physical row free — out-of-band allocs can fragment a range
        — re-queues for a later wave rather than straddling)."""
        n = min(len(self._join_q), self.cfg.install_batch,
                self.bridge.registry.free_slots)
        if n <= 0:
            return
        popped = [self._join_q.popleft() for _ in range(n)]
        if self.placer is None:
            specs, sids, confs = popped, None, None
        else:
            by_shard: Dict[int, list] = {}
            for spec in popped:
                # broadcast listeners carry their own assigned shard
                # (may straddle off the conference's home shard)
                shard = spec[6] if spec[5] == "listener" \
                    else self.placer.shard_of(spec[4])
                by_shard.setdefault(shard, []).append(spec)
            specs, sids, confs = [], [], []
            requeue: list = []
            for shard in sorted(by_shard):
                group = by_shard[shard]
                rows = self._free_rows_on(shard, len(group))
                for spec, row in zip(group, rows):
                    specs.append(spec)
                    sids.append(row)
                    confs.append(spec[4])
                requeue.extend(group[len(rows):])
            for spec in reversed(requeue):
                self._join_q.appendleft(spec)
            if not specs:
                return
        for spec in specs:
            self._queued_ssrcs.discard(spec[0])
        n_listen = sum(1 for spec in specs if spec[5] == "listener")
        # listeners warm their OWN fanout-only ladder; they never
        # contribute uplink RTP, so they stay out of the RTP-class
        # population estimate entirely
        self._ensure_warm(len(self.bridge._ssrc_of)
                          - len(self._listener_sids)
                          + len(specs) - n_listen)
        if n_listen or self._listener_sids:
            self._ensure_warm_listeners(
                len(self._listener_sids) + n_listen)
        specs4 = [tuple(spec[:4]) for spec in specs]
        if self.placer is None:
            # kwarg-free call: bridge fakes/older bridges keep working
            out_sids = self.bridge.stage_endpoints(specs4)
        else:
            out_sids = self.bridge.stage_endpoints(
                specs4, sids=sids, conferences=confs)
        self.key_installs += len(specs)
        self._staged.extend(out_sids)
        for sid, spec in zip(out_sids, specs):
            if spec[5] == "listener":
                self._listener_sids.add(int(sid))
            elif spec[5] == "speaker":
                self._bcast[spec[4]]["speakers"].add(int(sid))
            self.flight.record("key_install", tick=self.ticks(),
                               sid=sid, ssrc=spec[0])

    @property
    def key_installs_pending(self) -> int:
        return len(self._join_q) + len(self._staged)

    # ------------------------------------------------ placement moves

    def rebalance(self) -> int:
        """Execute the placer's rebalance plan as lifecycle events:
        each move relocates one whole conference's rows to the
        destination shard's range via `migrate_endpoints` (bit-exact
        SRTP/translator state, between ticks, behind the same drain
        barrier commits use).  A conference with members still queued
        or staged skips its move — moving half a conference would
        straddle it, the one invariant this module exists to hold."""
        if self.placer is None:
            return 0
        done = 0
        conf_of = getattr(self.bridge, "_conf_of", {})
        for mv in self.placer.plan_rebalance():
            members = [s for s, c in conf_of.items()
                       if c == mv.conf_id]
            sids = sorted(s for s in members
                          if s in self.bridge._ssrc_of
                          and s not in self.bridge._staged)
            if not sids or len(sids) != len(members):
                continue  # mid-install conference: move next window
            if any(j[4] == mv.conf_id for j in self._join_q):
                continue
            rows = self._free_rows_on(mv.dst, len(sids))
            if len(rows) < len(sids):
                continue  # destination range fragmented; replan later
            mapping = dict(zip(sids, rows))
            self._move_inflight = {"conf": int(mv.conf_id),
                                   "src": mv.src, "dst": mv.dst,
                                   "mapping": dict(mapping)}
            self.flight.record("placement_move_begin",
                               tick=self.ticks(), conf=mv.conf_id,
                               src=mv.src, dst=mv.dst, rows=len(sids))
            self.bridge.migrate_endpoints(mapping)
            self.placer.apply_move(mv)
            self._move_inflight = None
            self.moves_applied += 1
            done += 1
            self.flight.record("placement_move", tick=self.ticks(),
                               conf=mv.conf_id, src=mv.src, dst=mv.dst,
                               rows=len(sids))
            _log.info("placement_move", conf=mv.conf_id, src=mv.src,
                      dst=mv.dst, rows=len(sids))
        return done

    def _drop_conference_slices(self, conf) -> None:
        slo = getattr(self.supervisor, "slo", None) \
            if self.supervisor is not None else None
        if slo is None:
            return
        for spec in getattr(slo, "sliced", ()):
            if spec.label == "conference":
                slo.drop_slice(spec.name, str(conf))

    # ----------------------------------------------- bucketed warmup

    def _ensure_warm(self, population: int) -> None:
        """Grow the warm bucket to the next power of two covering
        `population` and pre-compile (off-tick, throwaway tables) every
        RTP row class that bucket's aggregate traffic can drive.  Shapes
        depend only on the size classes, so within a bucket admits and
        evicts compile NOTHING; crossing a boundary pays compile cost
        here, never inside a tick."""
        bucket = _next_pow2(max(self.cfg.min_bucket, population))
        if bucket <= self._warm_bucket:
            return
        max_rows = min(bucket * self.cfg.pkts_per_stream,
                       ROW_CLASSES[-1])
        # one class of headroom: fan-out rows are packets x receivers,
        # which can cross the class ABOVE the aggregate-traffic estimate
        # while the population is still inside this bucket — that first
        # crossing must not compile inside a tick
        above = [rc for rc in ROW_CLASSES if rc > max_rows]
        cover = above[0] if above else ROW_CLASSES[-1]
        want = [rc for rc in ROW_CLASSES
                if rc <= cover and rc not in self._warm_rows]
        if not want and ROW_CLASSES[0] not in self._warm_rows:
            want = [ROW_CLASSES[0]]
        tr = getattr(self.bridge, "translator", None)
        for rc in want:
            self.bridge.rx_table.warmup_rtp(
                rc, payload_len=self.cfg.warm_payload_len)
            self.bridge.tx_table.warmup_rtp(
                rc, payload_len=self.cfg.warm_payload_len)
            if tr is not None and hasattr(tr, "warmup_fanout"):
                # the fan-out expansion (packets x receivers) has its own
                # class-padded shape space — compile it here, off-tick
                tr.warmup_fanout(rc, payload_len=self.cfg.warm_payload_len)
            if hasattr(self.bridge.rx_table, "warmup_rtcp"):
                # control traffic (NACK/RR/SR) rides the same
                # zero-recompile discipline as media
                self.bridge.rx_table.warmup_rtcp(rc)
                self.bridge.tx_table.warmup_rtcp(rc)
            self._warm_rows.add(rc)
        self.flight.record("bucket_warm", tick=self.ticks(),
                           bucket=bucket, rows=sorted(self._warm_rows))
        _log.info("bucket_warm", bucket=bucket,
                  row_classes=sorted(self._warm_rows))
        self._warm_bucket = bucket

    def _ensure_warm_listeners(self, population: int) -> None:
        """The fanout-only twin of `_ensure_warm`: listener rows never
        contribute uplink RTP, so their ladder skips the RTP row
        classes entirely and warms only the fan-out expansion (the
        shared bus re-protected once per listener leg) and RTCP shapes.
        A 4096-listener broadcast therefore warms a handful of fanout
        classes instead of dragging the RTP ladder to its ceiling —
        and listener churn inside a bucket still compiles nothing."""
        bucket = _next_pow2(max(self.cfg.min_bucket, population))
        if bucket <= self._warm_lbucket:
            return
        max_rows = min(bucket, ROW_CLASSES[-1])
        above = [rc for rc in ROW_CLASSES if rc > max_rows]
        cover = above[0] if above else ROW_CLASSES[-1]
        want = [rc for rc in ROW_CLASSES
                if rc <= cover and rc not in self._warm_lrows]
        if not want and ROW_CLASSES[0] not in self._warm_lrows:
            want = [ROW_CLASSES[0]]
        tr = getattr(self.bridge, "translator", None)
        for rc in want:
            if tr is not None and hasattr(tr, "warmup_fanout"):
                tr.warmup_fanout(rc,
                                 payload_len=self.cfg.warm_payload_len)
            if hasattr(self.bridge.rx_table, "warmup_rtcp"):
                self.bridge.rx_table.warmup_rtcp(rc)
                self.bridge.tx_table.warmup_rtcp(rc)
            self._warm_lrows.add(rc)
        self.flight.record("listener_bucket_warm", tick=self.ticks(),
                           bucket=bucket,
                           rows=sorted(self._warm_lrows))
        _log.info("listener_bucket_warm", bucket=bucket,
                  row_classes=sorted(self._warm_lrows))
        self._warm_lbucket = bucket

    # --------------------------------------------- data-path compile proof

    def tick_begin(self) -> None:
        self._tick_compiles0 = compile_stats().compile_events
        self._tick_feeds0 = (self.handshakes.table.feeds_total
                             if self.handshakes is not None else None)

    def tick_end(self) -> None:
        if self._tick_feeds0 is not None:
            # the other zero-on-the-tick-thread invariant: with the
            # deferred table no OpenSSL feed may run inside the tick
            d = self.handshakes.table.feeds_total - self._tick_feeds0
            self._tick_feeds0 = None
            if d > 0:
                self.tick_thread_handshake_feeds += d
                self.flight.record("tick_thread_handshake",
                                   tick=self.ticks(), n=d)
                _log.warn("tick_thread_handshake", n=d)
        if self._tick_compiles0 is None:
            return
        delta = compile_stats().compile_events - self._tick_compiles0
        self._tick_compiles0 = None
        if delta > 0:
            self.datapath_recompiles += delta
            self.flight.record("datapath_recompile",
                               tick=self.ticks(), n=delta)
            _log.warn("datapath_recompile", n=delta)

    def assert_datapath_clean(self) -> None:
        """The zero-recompile invariant, as an assertion: call after a
        soak window (once all shapes are warm) — raises if any compile
        event landed inside a tick."""
        if self.datapath_recompiles:
            raise AssertionError(
                f"{self.datapath_recompiles} compile event(s) landed on "
                f"the data path (inside tick windows)")

    # --------------------------------------------------- checkpointing

    def snapshot(self) -> dict:
        """In-flight admit state for the supervisor checkpoint: queued
        joins carry their keys (host-side only so far); staged sids'
        keys already ride the bridge snapshot.  With placement enabled
        the in-flight move (if any) rides too, so recovery can tell a
        completed move from a rolled-back one."""
        snap = {
            "queued": [tuple(j) for j in self._join_q],
            "staged": [(sid, self.bridge._ssrc_of.get(sid))
                       for sid in self._staged],
        }
        if self.handshakes is not None:
            # mid-handshake associations: keyless, so they ride as
            # their admission parameters and requeue after recover
            # (staged handshake rows already carry keys and ride the
            # "staged" list + bridge snapshot like any other admit)
            snap["handshakes"] = self.handshakes.snapshot()
        if self.placer is not None:
            snap["placement"] = {
                "n_shards": self.placer.n_shards,
                "move_inflight": self._move_inflight,
            }
        if self._bcast:
            snap["broadcast"] = {
                str(conf): {"home": self.placer.shard_of(conf),
                            "speakers": sorted(st["speakers"]),
                            "join_good": st["join_good"],
                            "join_bad": st["join_bad"]}
                for conf, st in self._bcast.items()}
            snap["listener_sids"] = sorted(self._listener_sids)
        return snap

    def _reconcile(self, pend: dict) -> None:
        """Post-`recover()` reconciliation: every in-flight admit either
        COMPLETES or ROLLS BACK — never a half state.

        * staged installs: the bridge snapshot captured their keys, SSRC
          mapping and table rows, and `restore()` routed them — the
          admit completes here (counted, flight-recorded).  A staged sid
          whose keys did NOT survive is rolled back: its remnants are
          removed and the slot freed.
        * queued joins: never touched the device; they re-enter the
          queue and install through the normal off-tick pipeline.
        """
        pl = pend.get("placement")
        if pl is not None and self.placer is None:
            self.enable_placement(int(pl["n_shards"]))
        for conf_s, st in pend.get("broadcast", {}).items():
            self._bcast[int(conf_s)] = {
                "speakers": {int(s) for s in st["speakers"]},
                "join_good": int(st["join_good"]),
                "join_bad": int(st["join_bad"]),
            }
        self._bcast_homes = {int(c): int(st["home"])
                             for c, st in
                             pend.get("broadcast", {}).items()
                             if st.get("home") is not None}
        self._listener_sids = {int(s)
                               for s in pend.get("listener_sids", [])}
        if self._bcast:
            self._register_conference_slo(0.999)
        for sid, ssrc in pend.get("staged", []):
            sid = int(sid)
            if (sid in self.bridge._ssrc_of
                    and sid in self.bridge._tx_keys):
                self.admits += 1
                self.flight.record("admit_commit", tick=self.ticks(),
                                   sid=sid, recovered=True)
            else:
                if sid in self.bridge._ssrc_of:
                    self.bridge.remove_endpoints([sid])
                self._listener_sids.discard(sid)
                for st in self._bcast.values():
                    st["speakers"].discard(sid)
                self.flight.record("admit_rollback", tick=self.ticks(),
                                   sid=sid, ssrc=ssrc)
                _log.info("admit_rollback", sid=sid)
        if self.placer is not None:
            self._reconcile_placement(pl or {})
        for spec in pend.get("queued", []):
            ssrc, rx, tx, name = spec[:4]
            conf = spec[4] if len(spec) > 4 else None
            role = spec[5] if len(spec) > 5 else None
            # solo (negative) conference keys re-derive from the ssrc
            self.request_join(ssrc, rx, tx, name=name,
                              conference=conf if (conf is None
                                                  or conf >= 0) else None,
                              role=role)
        for rec in pend.get("handshakes", []):
            # mid-handshake at the kill: the OpenSSL state died with
            # the process, so the association REQUEUES as a fresh row
            # (same ssrc, same bound 5-tuple when known) and the
            # peer's flight timers / signaling re-join drive the new
            # handshake — completed or requeued, never torn
            ssrc = rec.get("ssrc")
            if ssrc is None or self.handshakes is None:
                continue
            addr = rec.get("addr")
            ok, reason, retry = self.request_handshake(
                ssrc, role=rec.get("role", "server"),
                remote_fingerprint=rec.get("fingerprint"),
                cookie_exchange=bool(rec.get("cookie", False)),
                remote_addr=tuple(addr) if addr is not None else None)
            if ok:
                self.handshakes.requeued += 1
            self.flight.record("handshake_requeue", tick=self.ticks(),
                               ssrc=ssrc, accepted=ok,
                               reason=reason, retry_after_s=retry)
            _log.info("handshake_requeue", ssrc=ssrc, accepted=ok,
                      reason=reason)

    def _reconcile_placement(self, pl: dict) -> None:
        """Rebuild placement accounting from the RESTORED rows — the
        bridge's row layout is authoritative, never the placer's
        pre-kill beliefs.  `migrate_endpoints` is host-atomic between
        ticks, so a kill during a placement move restores either the
        fully-pre-move or fully-post-move layout; this proves which one
        landed (completed vs rolled back) and asserts the invariant
        placement exists for: no conference straddles a shard range."""
        members: Dict[int, list] = {}
        for sid, conf in self.bridge._conf_of.items():
            if sid in self.bridge._ssrc_of:
                members.setdefault(int(conf), []).append(int(sid))
        live = set(self.bridge._ssrc_of)
        self._listener_sids &= live
        for st in self._bcast.values():
            st["speakers"] &= live
        homes = getattr(self, "_bcast_homes", {})
        assignments = []
        broadcast = []
        for conf, sids in sorted(members.items()):
            if conf in self._bcast:
                # broadcast conferences legitimately straddle on their
                # LISTENER rows; only the speaker rows are pinned home
                speakers = self._bcast[conf]["speakers"]
                spk = [s for s in sids if s in speakers]
                spk_shards = {s // self._rows_per_shard for s in spk}
                home = homes.get(conf)
                if home is None:
                    home = (spk_shards.pop() if len(spk_shards) == 1
                            else 0)
                elif spk_shards - {home}:
                    raise AssertionError(
                        f"broadcast conference {conf} speaker rows "
                        f"off home shard {home} after recovery — "
                        f"torn placement")
                assignments.append((conf, home, len(spk)))
                per: Dict[int, int] = {}
                for s in sids:
                    if s not in speakers:
                        sh = s // self._rows_per_shard
                        per[sh] = per.get(sh, 0) + 1
                broadcast.append((conf, per))
                continue
            shards = {s // self._rows_per_shard for s in sids}
            if len(shards) != 1:
                raise AssertionError(
                    f"conference {conf} straddles shards {sorted(shards)} "
                    f"after recovery — torn placement")
            assignments.append((conf, shards.pop(), len(sids)))
        # a declared broadcast conference with no live members yet must
        # still hold its home-shard reservation across recovery
        for conf, home in sorted(homes.items()):
            if conf not in members:
                assignments.append((conf, home, 0))
                broadcast.append((conf, {}))
        self.placer.rebuild(assignments, broadcast=broadcast)
        self._bcast_homes = {}
        mv = pl.get("move_inflight")
        if mv:
            conf = int(mv["conf"])
            landed = self.placer.shard_of(conf)
            outcome = ("completed" if landed == int(mv["dst"])
                       else "rolled_back")
            if outcome == "completed":
                self.moves_applied += 1
            self.flight.record("placement_move_recovered",
                               tick=self.ticks(), conf=conf,
                               outcome=outcome, src=mv["src"],
                               dst=mv["dst"])
            _log.info("placement_move_recovered", conf=conf,
                      outcome=outcome)

    # --------------------------------------------------- observability

    def register_metrics(self, registry, prefix: str = "lifecycle") -> None:
        registry.register_counters(self, (
            ("admits", "streams admitted (committed live)"),
            ("evicts", "streams evicted by the lifecycle plane"),
            ("key_installs", "streams whose keys installed off-tick"),
            ("datapath_recompiles",
             "compile events inside tick windows (invariant: 0)"),
            ("moves_applied",
             "placement rebalance moves executed at the barrier"),
        ), prefix=prefix)
        registry.register_scalar(
            f"{prefix}_key_installs_pending",
            lambda: self.key_installs_pending,
            help_="joins queued or staged, not yet committed")
        registry.register_scalar(
            f"{prefix}_warm_bucket", lambda: self._warm_bucket,
            help_="population bucket whose shapes are pre-compiled")
        registry.register_multi(
            f"{prefix}_admit_rejected", self._rejected_samples,
            help_="admissions refused, by typed reason", kind="counter")
        registry.register_scalar(
            "bcast_listeners", lambda: float(len(self._listener_sids)),
            help_="fanout-only listener rows live across all "
                  "broadcast conferences")
        registry.register_scalar(
            "speaker_promotions_total",
            lambda: float(self.speaker_promotions),
            help_="listener-to-speaker role flips applied at the "
                  "commit barrier", kind="counter")
        # keystream pregeneration cache (transform/srtp/keystream.py):
        # summed across the rx/tx tables' caches; all zero until
        # enable_keystream_cache is called on a GCM bridge
        registry.register_scalar(
            "srtp_keystream_hits",
            lambda: float(sum(c.hits for c in self._keystream_caches())),
            help_="packets served from the pregenerated keystream "
                  "window (fast-path protect/unprotect)",
            kind="counter")
        registry.register_scalar(
            "srtp_keystream_misses",
            lambda: float(sum(c.misses
                              for c in self._keystream_caches())),
            help_="packets that fell back to the stock GCM path "
                  "(window miss, reorder, rekey, non-uniform batch)",
            kind="counter")
        registry.register_scalar(
            "srtp_keystream_evictions",
            lambda: float(sum(c.evictions
                              for c in self._keystream_caches())),
            help_="pregenerated keystream slots discarded unused "
                  "(window slide, rekey invalidation, SSRC change)",
            kind="counter")
        registry.register_scalar(
            "srtp_keystream_fill_seconds",
            lambda: float(sum(c.fill_seconds
                              for c in self._keystream_caches())),
            help_="cumulative off-tick wall time spent generating "
                  "keystream (the cache-fill phase)", kind="counter")
        # handshake plane (HandshakeQueue + deferred association
        # table): all read through self.handshakes so direct-keyed
        # bridges export zeros instead of raising
        registry.register_scalar(
            "handshake_queue_depth",
            lambda: float(self.handshakes.depth)
            if self.handshakes is not None else 0.0,
            help_="queued handshake datagrams + pending associations "
                  "awaiting the off-tick drain")
        registry.register_scalar(
            "dtls_handshakes_active",
            lambda: float(len(self.handshakes.table.pending))
            if self.handshakes is not None else 0.0,
            help_="DTLS associations mid-handshake (allocated, "
                  "keyless rows)")
        registry.register_scalar(
            "dtls_retransmits_total",
            lambda: float(self.handshakes.table.retransmits_total)
            if self.handshakes is not None else 0.0,
            help_="expired-flight datagrams resent by the batched "
                  "retransmission pass", kind="counter")
        registry.register_scalar(
            "dtls_feeds_total",
            lambda: float(self.handshakes.table.feeds_total)
            if self.handshakes is not None else 0.0,
            help_="handshake datagrams fed to endpoints (all on the "
                  "off-tick drain in deferred mode)", kind="counter")
        registry.register_scalar(
            "dtls_inbox_dropped",
            lambda: float(self.handshakes.table.inbox_dropped)
            if self.handshakes is not None else 0.0,
            help_="handshake datagrams dropped at the deferred "
                  "table's inbox bound (admission refuses first; this "
                  "staying near 0 proves the bound is generous)",
            kind="counter")
        registry.register_scalar(
            "dtls_handshakes_completed",
            lambda: float(self.handshakes.completed)
            if self.handshakes is not None else 0.0,
            help_="handshakes whose keys landed via the staged "
                  "commit barrier", kind="counter")
        registry.register_scalar(
            "handshake_off_tick_seconds",
            lambda: float(self.handshakes.off_tick_seconds)
            if self.handshakes is not None else 0.0,
            help_="cumulative between-ticks wall time in the "
                  "handshake drain (OpenSSL + flight resends)",
            kind="counter")
        registry.register_scalar(
            "handshake_tick_thread_feeds",
            lambda: float(self.tick_thread_handshake_feeds),
            help_="OpenSSL feed() calls observed inside tick windows "
                  "(invariant: 0)", kind="counter")

    def _rejected_samples(self):
        return [({"reason": r}, float(c))
                for r, c in sorted(self.admit_rejected.items())]
